// Native runtime components for distributed_tensorflow_tpu.
//
// The reference's native layer lives inside the tensorflow==1.4.0 wheel
// (C++ graph executor, gRPC runtime, Saver/record IO — SURVEY.md §2b); the
// TPU compute path here is XLA, but the host-side runtime around it is
// native C++ as well:
//
//   * crc32c (Castagnoli, slice-by-8) + the TFRecord mask — the framing
//     checksum for TensorBoard event files / TFRecord IO, byte-identical to
//     the pure-Python summary/crc32c.py implementation;
//   * a vectorized XOR-task sample generator (the reference's get_data,
//     example.py:24-48, built sample-by-sample in Python lists);
//   * a threaded, double-buffered batch loader: per-epoch Fisher–Yates
//     shuffle + row gather executed by worker threads into a bounded ring of
//     pre-allocated pinned-ish buffers, so the Python training loop's
//     next() is a memcpy away from an already-gathered batch (the
//     feed_dict-era host stall moves off the hot path entirely).
//
// C ABI only — consumed from Python via ctypes (no pybind11 in the image).
// Build: `make -C native` -> libdttpu.so.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// crc32c (Castagnoli), slice-by-8.
// ---------------------------------------------------------------------------

namespace {

uint32_t g_tables[8][256];
std::atomic<bool> g_tables_ready{false};
std::mutex g_tables_mu;

void init_tables() {
  if (g_tables_ready.load(std::memory_order_acquire)) return;
  std::lock_guard<std::mutex> lock(g_tables_mu);
  if (g_tables_ready.load(std::memory_order_relaxed)) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    g_tables[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = g_tables[0][i];
    for (int t = 1; t < 8; ++t) {
      c = g_tables[0][c & 0xFF] ^ (c >> 8);
      g_tables[t][i] = c;
    }
  }
  g_tables_ready.store(true, std::memory_order_release);
}

}  // namespace

extern "C" uint32_t dt_crc32c(const uint8_t* data, uint64_t len,
                              uint32_t crc) {
  init_tables();
  crc ^= 0xFFFFFFFFu;
  while (len >= 8) {
    uint64_t word;
    std::memcpy(&word, data, 8);
    crc ^= static_cast<uint32_t>(word);
    uint32_t hi = static_cast<uint32_t>(word >> 32);
    crc = g_tables[7][crc & 0xFF] ^ g_tables[6][(crc >> 8) & 0xFF] ^
          g_tables[5][(crc >> 16) & 0xFF] ^ g_tables[4][crc >> 24] ^
          g_tables[3][hi & 0xFF] ^ g_tables[2][(hi >> 8) & 0xFF] ^
          g_tables[1][(hi >> 16) & 0xFF] ^ g_tables[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  while (len--) crc = g_tables[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

extern "C" uint32_t dt_masked_crc32c(const uint8_t* data, uint64_t len) {
  uint32_t crc = dt_crc32c(data, len, 0);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// PRNG: splitmix64 (seeding) + xoshiro256** (stream).
// ---------------------------------------------------------------------------

namespace {

uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

struct Xoshiro {
  uint64_t s[4];
  explicit Xoshiro(uint64_t seed) {
    for (auto& w : s) w = splitmix64(seed);
  }
  static uint64_t rotl(uint64_t x, int k) { return (x << k) | (x >> (64 - k)); }
  uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0]; s[3] ^= s[1]; s[1] ^= s[2]; s[0] ^= s[3]; s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
  // Unbiased bounded draw (Lemire).
  uint64_t bounded(uint64_t n) {
    unsigned __int128 m = static_cast<unsigned __int128>(next()) * n;
    uint64_t lo = static_cast<uint64_t>(m);
    if (lo < n) {
      uint64_t thresh = (0 - n) % n;
      while (lo < thresh) {
        m = static_cast<unsigned __int128>(next()) * n;
        lo = static_cast<uint64_t>(m);
      }
    }
    return static_cast<uint64_t>(m >> 64);
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// XOR-task generator: x [n, 2*bits] in {0,1}, y = x_lo ^ x_hi [n, bits].
// ---------------------------------------------------------------------------

// The RNG stream is derived from the fixed-size block index, NOT the thread
// id, so the output is identical regardless of the machine's core count —
// multi-host jobs that generate "the same" dataset per process and slice it
// by process_index must see byte-identical rows everywhere.
static const int64_t kXorBlock = 4096;

extern "C" void dt_xor_generate(uint64_t seed, int64_t n, int32_t bits,
                                float* x, float* y) {
  int64_t nblocks = (n + kXorBlock - 1) / kXorBlock;
  int64_t nthreads = std::max<int64_t>(
      1, std::min<int64_t>(std::thread::hardware_concurrency(), nblocks));
  std::vector<std::thread> pool;
  std::atomic<int64_t> next_block{0};
  for (int64_t t = 0; t < nthreads; ++t) {
    pool.emplace_back([&, n, bits, seed]() {
      for (;;) {
        int64_t blk = next_block.fetch_add(1);
        if (blk >= nblocks) return;
        int64_t lo = blk * kXorBlock, hi = std::min(n, lo + kXorBlock);
        uint64_t s = seed ^ (0x9E3779B97F4A7C15ull *
                             static_cast<uint64_t>(blk + 1));
        Xoshiro rng(s);
        for (int64_t i = lo; i < hi; ++i) {
          float* xr = x + i * 2 * bits;
          float* yr = y + i * bits;
          for (int32_t b = 0; b < 2 * bits; b += 64) {
            uint64_t w = rng.next();
            int32_t take = std::min(64, 2 * bits - b);
            for (int32_t j = 0; j < take; ++j)
              xr[b + j] = static_cast<float>((w >> j) & 1);
          }
          for (int32_t j = 0; j < bits; ++j)
            yr[j] = static_cast<float>(
                (static_cast<int>(xr[j]) ^ static_cast<int>(xr[bits + j])));
        }
      }
    });
  }
  for (auto& th : pool) th.join();
}

// ---------------------------------------------------------------------------
// Threaded batch loader.
// ---------------------------------------------------------------------------

namespace {

struct Slot {
  std::vector<uint8_t> x, y;
  int64_t batch_id = -1;   // which global batch occupies this slot
  bool ready = false;
};

struct Loader {
  const uint8_t* x;
  const uint8_t* y;
  int64_t xrow, yrow, n, batch, per_epoch;
  uint64_t seed;
  bool shuffle;

  std::vector<Slot> slots;
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::atomic<int64_t> next_job{0};
  int64_t next_consume = 0;
  bool stop = false;

  // Epoch permutations are built lazily, guarded by mu.
  int64_t perm_epoch = -1;
  std::vector<int64_t> perm;

  void build_perm(int64_t epoch) {
    perm.resize(n);
    for (int64_t i = 0; i < n; ++i) perm[i] = i;
    if (!shuffle) return;
    uint64_t s = seed * 0x9E3779B97F4A7C15ull + static_cast<uint64_t>(epoch);
    Xoshiro rng(s);
    for (int64_t i = n - 1; i > 0; --i) {
      int64_t j = static_cast<int64_t>(rng.bounded(i + 1));
      std::swap(perm[i], perm[j]);
    }
  }

  // Copy the permutation rows for global batch `job` while holding mu (the
  // perm vector mutates across epochs), then gather outside the lock.
  void run_worker() {
    for (;;) {
      int64_t job = next_job.fetch_add(1);
      std::vector<int64_t> idx(batch);
      {
        std::unique_lock<std::mutex> lock(mu);
        if (stop) return;
        int64_t epoch = job / per_epoch;
        int64_t off = (job % per_epoch) * batch;
        // Serialize epoch transitions: a worker may only build/read perm for
        // `epoch` once all earlier batches have been *assigned* (they have —
        // job ids are monotonic) and the perm is current.
        while (!stop && perm_epoch != epoch) {
          if (perm_epoch < epoch &&
              next_consume >= std::min(job, epoch * per_epoch)) {
            build_perm(epoch);
            perm_epoch = epoch;
            break;
          }
          cv_free.wait_for(lock, std::chrono::milliseconds(1));
        }
        if (stop) return;
        for (int64_t i = 0; i < batch; ++i) idx[i] = perm[off + i];
        // Wait for this job's ring slot to be free AND for the job to fit
        // the in-flight window.  The window check prevents claim-jumping:
        // without it a fast worker could claim slot (j % depth) for job
        // j+depth while the slower worker holding job j is still at the
        // epoch barrier — the consumer needs j on that slot first, and all
        // three would wait on each other forever.
        Slot& s = slots[job % slots.size()];
        while (!stop &&
               (s.batch_id >= 0 ||
                job - next_consume >= static_cast<int64_t>(slots.size())))
          cv_free.wait(lock);
        if (stop) return;
        s.batch_id = job;  // claim
      }
      Slot& s = slots[job % slots.size()];
      for (int64_t i = 0; i < batch; ++i) {
        std::memcpy(s.x.data() + i * xrow, x + idx[i] * xrow, xrow);
        if (y != nullptr)
          std::memcpy(s.y.data() + i * yrow, y + idx[i] * yrow, yrow);
      }
      {
        std::lock_guard<std::mutex> lock(mu);
        s.ready = true;
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

// Row sizes are in BYTES — the gather is dtype-agnostic memcpy, so any
// fixed-width row layout (f32 features, i32 labels, ...) loads natively.
extern "C" void* dt_loader_create(const uint8_t* x, int64_t xrow,
                                  const uint8_t* y, int64_t yrow, int64_t n,
                                  int64_t batch, uint64_t seed,
                                  int32_t shuffle, int32_t num_threads,
                                  int32_t queue_depth) {
  if (batch <= 0 || n < batch) return nullptr;
  auto* L = new Loader();
  L->x = x; L->y = y; L->xrow = xrow; L->yrow = yrow;
  L->n = n; L->batch = batch; L->per_epoch = n / batch;
  L->seed = seed; L->shuffle = shuffle != 0;
  if (num_threads <= 0) num_threads = 2;
  if (queue_depth < num_threads + 1) queue_depth = num_threads + 1;
  L->slots.resize(queue_depth);
  for (auto& s : L->slots) {
    s.x.resize(batch * xrow);
    if (y != nullptr) s.y.resize(batch * yrow);
  }
  for (int32_t i = 0; i < num_threads; ++i)
    L->workers.emplace_back([L] { L->run_worker(); });
  return L;
}

extern "C" int64_t dt_loader_batches_per_epoch(void* h) {
  return static_cast<Loader*>(h)->per_epoch;
}

// Blocks until the next in-order batch is gathered; copies it out.
extern "C" void dt_loader_next(void* h, uint8_t* xout, uint8_t* yout) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lock(L->mu);
  int64_t want = L->next_consume;
  Slot& s = L->slots[want % L->slots.size()];
  L->cv_ready.wait(lock, [&] { return s.batch_id == want && s.ready; });
  std::memcpy(xout, s.x.data(), L->batch * L->xrow);
  if (yout != nullptr && L->y != nullptr)
    std::memcpy(yout, s.y.data(), L->batch * L->yrow);
  s.batch_id = -1;
  s.ready = false;
  L->next_consume = want + 1;
  lock.unlock();
  L->cv_free.notify_all();
}

extern "C" void dt_loader_destroy(void* h) {
  auto* L = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lock(L->mu);
    L->stop = true;
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers) t.join();
  delete L;
}

// ---------------------------------------------------------------------------
// Byte-pair encoding: the tokenizer encode hot loop (data/text.py
// BPETokenizer.encode) in native code.  Semantics are EXACTLY the Python
// reference: repeatedly find the lowest-rank adjacent pair present in the
// sequence and replace every non-overlapping occurrence left-to-right,
// until no learned pair remains.  merge_pairs is [a0, b0, a1, b1, ...] in
// rank order; merged token r gets id base_id + r.
// Returns the output length, or -1 if out_cap is too small.
#include <unordered_map>

extern "C" int64_t dt_bpe_encode(const uint8_t* text, int64_t n,
                                 const int32_t* merge_pairs,
                                 int64_t n_merges, int32_t base_id,
                                 int32_t* out, int64_t out_cap) {
  if (n > out_cap) return -1;
  std::vector<int32_t> seq(n);
  for (int64_t i = 0; i < n; ++i) seq[i] = text[i];

  std::unordered_map<uint64_t, int32_t> rank;
  rank.reserve(static_cast<size_t>(n_merges) * 2);
  auto key = [](int32_t a, int32_t b) {
    return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
           static_cast<uint32_t>(b);
  };
  for (int64_t r = 0; r < n_merges; ++r)
    rank.emplace(key(merge_pairs[2 * r], merge_pairs[2 * r + 1]),
                 static_cast<int32_t>(r));

  std::vector<int32_t> next(seq.size());
  while (seq.size() > 1) {
    int32_t best_rank = -1;
    for (size_t i = 0; i + 1 < seq.size(); ++i) {
      auto it = rank.find(key(seq[i], seq[i + 1]));
      if (it != rank.end() &&
          (best_rank < 0 || it->second < best_rank))
        best_rank = it->second;
    }
    if (best_rank < 0) break;
    const int32_t a = merge_pairs[2 * best_rank];
    const int32_t b = merge_pairs[2 * best_rank + 1];
    const int32_t merged = base_id + best_rank;
    next.clear();
    for (size_t i = 0; i < seq.size();) {
      if (i + 1 < seq.size() && seq[i] == a && seq[i + 1] == b) {
        next.push_back(merged);
        i += 2;
      } else {
        next.push_back(seq[i]);
        i += 1;
      }
    }
    seq.swap(next);
  }
  for (size_t i = 0; i < seq.size(); ++i) out[i] = seq[i];
  return static_cast<int64_t>(seq.size());
}
