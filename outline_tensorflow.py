"""MNIST MLP entrypoint (low-level tier) — BASELINE configs #1/#2.

The reference ships this file EMPTY (0 bytes, see SURVEY.md §2a #16); the
driver's north star repurposes the outlines as real ``--device=tpu``
entrypoints.  This one is the low-level-API MNIST run: the 2-layer MLP
data-parallel over all chips (pmap+psum capability expressed as pjit over a
``data`` mesh), with the same monitored-session machinery as example.py.

Run: python outline_tensorflow.py [--device=tpu] [--epochs=N] [--data_dir=...]
Real MNIST IDX/npz files in --data_dir are used when present; otherwise a
learnable synthetic stand-in with identical shapes (zero-egress default).
"""
import os
import sys

from distributed_tensorflow_tpu.utils import flags as flags_lib
from distributed_tensorflow_tpu.utils.flags import FLAGS

flags_lib.DEFINE_string("device", "", "Force a JAX platform; empty = default")
flags_lib.DEFINE_string("data_dir", os.environ.get("DATA_DIR", ""),
                        "Directory with MNIST files (IDX or mnist.npz)")
flags_lib.DEFINE_string("log_dir",
                        os.environ.get("LOG_DIR", os.path.join("logs", "mnist")),
                        "Checkpoint/summary directory")
flags_lib.DEFINE_integer("epochs", 5, "Training epochs")
flags_lib.DEFINE_integer("batch_size", 1024, "Global batch size")
flags_lib.DEFINE_float("learning_rate", 1e-3, "Adam learning rate")
flags_lib.DEFINE_integer("seed", 0, "PRNG seed")


def main() -> int:
    FLAGS.parse()
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)

    from distributed_tensorflow_tpu.parallel import cluster
    cluster.initialize()

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import data, models, optim, parallel, train
    from distributed_tensorflow_tpu.summary import SummaryWriter

    mesh = parallel.data_parallel_mesh()
    is_chief = cluster.is_chief()
    print(f"devices: {len(jax.devices())} ({jax.devices()[0].platform}), "
          f"mesh={dict(mesh.shape)}", file=sys.stderr)

    (x_train, y_train), (x_val, y_val) = data.mnist(
        FLAGS.data_dir or None, flatten=True, seed=FLAGS.seed)

    model = models.mnist_mlp()
    optimizer = optim.adam(FLAGS.learning_rate)
    metric_fns = {"accuracy": "accuracy"}
    train_step = train.make_train_step(
        model, "sparse_categorical_crossentropy", optimizer,
        metric_fns=metric_fns, mesh=mesh, seed=FLAGS.seed)
    eval_step = train.make_eval_step(
        model, "sparse_categorical_crossentropy", metric_fns=metric_fns)

    batch_size = parallel.round_batch_to_mesh(FLAGS.batch_size, mesh)
    local_batch = batch_size // jax.process_count()
    dataset = data.Dataset([x_train, y_train], local_batch, seed=FLAGS.seed,
                           process_index=jax.process_index(),
                           process_count=jax.process_count())
    state = train.init_train_state(model, optimizer,
                                   jax.random.PRNGKey(FLAGS.seed), (784,))
    state = jax.device_put(state, NamedSharding(mesh, P()))
    batch_sharding = NamedSharding(mesh, P("data"))

    writer = SummaryWriter(FLAGS.log_dir) if is_chief else None
    hooks = [train.StopAtStepHook(last_step=FLAGS.epochs * len(dataset)),
             train.CheckpointHook(every_secs=120.0),
             train.LoggingHook(every_steps=max(10, len(dataset) // 2)),
             train.PreemptionHook()]
    if writer is not None:
        hooks.append(train.SummaryHook(writer, every_steps=10))

    with train.TrainSession(state, train_step, checkpoint_dir=FLAGS.log_dir,
                            hooks=hooks, is_chief=is_chief) as sess:
        while not sess.should_stop():
            for batch in data.prefetch_to_device(iter(dataset),
                                                 sharding=batch_sharding):
                if sess.should_stop():
                    break
                sess.run_step(batch)
        val = eval_step(sess.state, (x_val[:4096], y_val[:4096]))
        print(f"Final step {sess.step}: val loss {float(val['loss']):.4f}  "
              f"val accuracy {float(val['accuracy']):.4f}", flush=True)
    if writer is not None:
        writer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
