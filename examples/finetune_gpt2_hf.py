"""Fine-tune a Hugging Face GPT-2 checkpoint under this framework.

The interop walkthrough: take a ``transformers`` GPT-2 (here random-init
tiny for a no-download demo; point ``--hf_dir`` at a real downloaded
checkpoint directory to use trained weights + its tokenizer), convert the
weights (``models.convert.gpt2_from_hf``), fine-tune with the framework's
compiled train step on a data-parallel mesh, and generate through the
KV cache — ids stay exactly the checkpoint's
(``data.GPT2BPETokenizer``).

Run (CPU mesh): ``XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
python examples/finetune_gpt2_hf.py --device=cpu --steps=30``
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_tensorflow_tpu.utils import flags as flags_lib

flags_lib.DEFINE_string("device", "", "cpu|tpu override (config-level)")
flags_lib.DEFINE_string("hf_dir", "", "local HF checkpoint dir (config + "
                        "weights + vocab.json/merges.txt); empty = "
                        "random-init tiny demo model")
flags_lib.DEFINE_integer("steps", 50, "fine-tune steps")
flags_lib.DEFINE_integer("batch_size", 16, "global batch size")
flags_lib.DEFINE_integer("seq_len", 32, "training sequence length")
FLAGS = flags_lib.FLAGS


def main() -> int:
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)
    import jax
    import numpy as np
    import torch
    import transformers
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import optim, parallel, train
    from distributed_tensorflow_tpu.models.convert import gpt2_from_hf

    if FLAGS.hf_dir:
        hf = transformers.GPT2LMHeadModel.from_pretrained(FLAGS.hf_dir)
        from distributed_tensorflow_tpu.data import GPT2BPETokenizer
        tok = GPT2BPETokenizer.load(
            os.path.join(FLAGS.hf_dir, "vocab.json"),
            os.path.join(FLAGS.hf_dir, "merges.txt"))
        encode = tok.encode
        decode = tok.decode
    else:
        torch.manual_seed(0)
        hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
            vocab_size=256, n_positions=64, n_embd=64, n_layer=2, n_head=2,
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0))
        # demo tokenizer: the framework's byte-level base (ids < 256
        # land inside the tiny vocab)
        from distributed_tensorflow_tpu.data import ByteTokenizer
        tok = ByteTokenizer()
        encode, decode = tok.encode, tok.decode

    mesh = parallel.data_parallel_mesh()
    model, params = gpt2_from_hf(hf.eval(), mesh=mesh)
    print(f"converted GPT-2: {model.config.num_layers} layers, "
          f"hidden {model.config.hidden_size}, "
          f"vocab {model.config.vocab_size}", file=sys.stderr)

    corpus = ("the quick brown fox jumps over the lazy dog. " * 64)
    ids = np.asarray(encode(corpus))
    seq = FLAGS.seq_len
    n = (len(ids) - 1) // seq
    if n == 0:
        raise SystemExit(
            f"--seq_len={seq} exceeds the tokenized corpus "
            f"({len(ids)} ids) — no training rows")
    rows = np.stack([ids[i * seq:i * seq + seq + 1] for i in range(n)])

    optimizer = optim.adamw(3e-4)
    step = train.make_custom_train_step(model.lm_loss_fn(), optimizer,
                                        grad_clip_norm=1.0)
    state = train.TrainState.create(params, optimizer.init(params))
    batch = parallel.round_batch_to_mesh(FLAGS.batch_size, mesh)
    bsh = NamedSharding(mesh, P("data"))
    rng = np.random.default_rng(0)
    for it in range(FLAGS.steps):
        pick = rng.integers(0, len(rows), batch)
        state, m = step(state, {"input_ids": jax.device_put(
            rows[pick].astype(np.int32), bsh)})
        if it % 10 == 0 or it == FLAGS.steps - 1:
            print(f"step {it}: loss={float(m['loss']):.4f}",
                  file=sys.stderr)

    prompt = encode("the quick brown")[None].astype(np.int32)
    out = model.generate(state.params, prompt, max_new_tokens=12,
                         temperature=0.0)
    print("generated:", repr(decode(np.asarray(out)[0].tolist())))
    return 0


if __name__ == "__main__":
    sys.exit(main())
