"""BERT classification fine-tune — the pooled-head workflow end to end.

The BASELINE "BERT-base MLM fine-tune" config's little sibling, runnable
anywhere: a tiny BERT encoder + [CLS] pooler + classification head trained
on a deterministic synthetic task (does the token sequence contain the
"trigger" token?), exercising

  * the ``Bert.apply`` + ``pooled`` fine-tune head composition,
  * ``make_custom_train_step`` with a dict batch and grad clipping,
  * megatron TP partition rules on a data+tensor mesh,
  * eval accuracy reporting.

Run (CPU mesh): ``XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
python examples/finetune_bert.py --device=cpu --steps=60``
Run (TPU): ``python examples/finetune_bert.py``
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_tensorflow_tpu.utils import flags as flags_lib

flags_lib.DEFINE_string("device", "", "cpu|tpu override (config-level)")
flags_lib.DEFINE_integer("steps", 150, "training steps")
flags_lib.DEFINE_integer("batch_size", 32, "global batch size")
flags_lib.DEFINE_integer("seq_len", 32, "sequence length")
flags_lib.DEFINE_integer("seed", 0, "data/init seed")
flags_lib.DEFINE_integer("mlm_steps", 0,
                         "MLM pretrain steps before the classifier "
                         "fine-tune (the standard BERT recipe order)")
flags_lib.DEFINE_integer("mlm_predictions_per_seq", 0,
                         "gather at most N masked positions before the "
                         "MLM head (BertConfig.mlm_predictions_per_seq; "
                         "0 = project every position)")
flags_lib.DEFINE_bool("fused_layernorm", False,
                         "LayerNorm via the fused Pallas kernel")
flags_lib.DEFINE_bool("remat", False, "checkpoint each encoder layer")
flags_lib.DEFINE_string("remat_policy", "full",
                        "remat policy: full | dots | dots_no_batch")
FLAGS = flags_lib.FLAGS

TRIGGER = 7          # class 1 iff this token id appears in the sequence
NUM_CLASSES = 2


def make_batch(rng, vocab, batch, seq):
    ids = rng.integers(8, vocab, (batch, seq)).astype("int32")
    labels = rng.integers(0, NUM_CLASSES, batch).astype("int32")
    pos = rng.integers(0, seq, batch)
    rows = labels == 1
    ids[rows, pos[rows]] = TRIGGER
    return ids, labels


def main() -> int:
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import optim, parallel, train
    from distributed_tensorflow_tpu.models.bert import Bert, BertConfig
    from distributed_tensorflow_tpu.ops import losses

    n = len(jax.devices())
    tp = 2 if n % 2 == 0 and n > 1 else 1
    mesh = parallel.make_mesh({"data": n // tp, "tensor": tp})
    print(f"devices: {n} ({jax.devices()[0].platform}), "
          f"mesh={dict(mesh.shape)}", file=sys.stderr)

    config = BertConfig(vocab_size=64, hidden_size=128, num_layers=2,
                        num_heads=4, intermediate_size=256,
                        max_position=FLAGS.seq_len, dropout_rate=0.1,
                        dtype=jnp.bfloat16,
                        mlm_predictions_per_seq=FLAGS.mlm_predictions_per_seq,
                        fused_layernorm=FLAGS.fused_layernorm,
                        remat=FLAGS.remat, remat_policy=FLAGS.remat_policy)
    model = Bert(config)
    params = model.init(jax.random.PRNGKey(FLAGS.seed))
    # fine-tune head: fresh [hidden, classes] on top of the pooler
    params["classifier"] = {
        "kernel": jnp.zeros((config.hidden_size, NUM_CLASSES), jnp.float32),
        "bias": jnp.zeros((NUM_CLASSES,), jnp.float32)}

    def loss_fn(p, model_state, batch, rng, train_flag):
        seq_out = model.apply(p, batch["input_ids"], train=train_flag,
                              rng=rng)
        pooled = model.pooled(p, seq_out)
        logits = (pooled @ p["classifier"]["kernel"].astype(pooled.dtype)
                  + p["classifier"]["bias"].astype(pooled.dtype)
                  ).astype(jnp.float32)
        loss = losses.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"])
        acc = jnp.mean((jnp.argmax(logits, -1) == batch["labels"]
                        ).astype(jnp.float32))
        return loss, ({"accuracy": acc}, model_state)

    optimizer = optim.adamw(5e-4)
    state = train.TrainState.create(params, optimizer.init(params))
    if tp > 1:
        rules = model.partition_rules()
        state = train.shard_train_state(state, mesh, rules)
    else:
        state = jax.device_put(state, NamedSharding(mesh, P()))
    step = train.make_custom_train_step(loss_fn, optimizer,
                                        grad_clip_norm=1.0)

    rng = np.random.default_rng(FLAGS.seed)
    bsh = NamedSharding(mesh, P("data"))
    batch = parallel.round_batch_to_mesh(FLAGS.batch_size, mesh)

    # Optional MLM warm-up (the standard BERT recipe order: pretrain the
    # encoder with the MLM head, then fine-tune the classifier).  This is
    # the phase where ``mlm_predictions_per_seq`` actually executes: the
    # masked-position gather before the vocab projection.
    if FLAGS.mlm_steps:
        MASK_ID = 1   # reserved: data tokens are drawn from [8, vocab)
        mlm_step = train.make_custom_train_step(model.mlm_loss_fn(),
                                                optimizer,
                                                grad_clip_norm=1.0)
        for i in range(FLAGS.mlm_steps):
            ids = rng.integers(8, config.vocab_size,
                               (batch, FLAGS.seq_len)).astype(np.int32)
            mask = (rng.random((batch, FLAGS.seq_len)) < 0.15
                    ).astype(np.float32)
            # BERT's corruption rule at the masked positions — 80%
            # [MASK], 10% random token, 10% keep — applied HOST-side:
            # mlm_loss_fn forwards input_ids as-is, so without this the
            # "MLM" phase would be a readable-identity task.
            inp = ids.copy()
            r = rng.random((batch, FLAGS.seq_len))
            m = mask == 1.0
            inp[m & (r < 0.8)] = MASK_ID
            rand_rows = m & (r >= 0.8) & (r < 0.9)
            inp[rand_rows] = rng.integers(
                8, config.vocab_size, int(rand_rows.sum())).astype(np.int32)
            mb = jax.device_put(
                {"input_ids": inp, "labels": ids,
                 "mlm_mask": mask,
                 "attention_mask": np.ones_like(ids)}, bsh)
            state, mlm_m = mlm_step(state, mb)
            if (i + 1) % 25 == 0 or i + 1 == FLAGS.mlm_steps:
                print(f"mlm step {i + 1}: "
                      f"loss={float(mlm_m['loss']):.4f} "
                      f"acc={float(mlm_m['mlm_accuracy']):.3f}",
                      flush=True)

    metrics = {}
    for i in range(FLAGS.steps):
        ids, labels = make_batch(rng, config.vocab_size, batch,
                                 FLAGS.seq_len)
        b = jax.device_put({"input_ids": ids, "labels": labels}, bsh)
        state, metrics = step(state, b)
        if (i + 1) % 25 == 0:
            print(f"step {i + 1}: loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}", flush=True)

    # held-out eval
    eval_step = jax.jit(lambda p, b: loss_fn(p, {}, b,
                                             jax.random.PRNGKey(0), False))
    ids, labels = make_batch(np.random.default_rng(FLAGS.seed + 1),
                             config.vocab_size, 256, FLAGS.seq_len)
    _, (m, _) = eval_step(state.params,
                          {"input_ids": jnp.asarray(ids),
                           "labels": jnp.asarray(labels)})
    print(f"eval accuracy: {float(m['accuracy']):.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
