"""End-to-end distributed GPT training — the full subsystem stack in one
script.

The transformer-family analogue of ``example.py``: causal-LM training on a
deterministic synthetic corpus (no downloads), exercising

  * mesh construction with data+fsdp axes and ZeRO state placement,
  * mixed bf16 compute over an f32 master copy (``policy``),
  * EMA parameter averaging riding in opt_state,
  * TrainSession with stop/checkpoint/summary/logging hooks and sharded
    per-process checkpoints,
  * KV-cache generation from the trained weights at the end.

Run (CPU mesh): ``XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
python examples/train_gpt.py --device=cpu --steps=60``
Run (TPU): ``python examples/train_gpt.py``
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_tensorflow_tpu.utils import flags as flags_lib

flags_lib.DEFINE_string("device", "", "cpu|tpu override (config-level)")
flags_lib.DEFINE_integer("steps", 200, "training steps")
flags_lib.DEFINE_integer("batch_size", 32, "global batch size")
flags_lib.DEFINE_integer("seq_len", 64, "sequence length")
flags_lib.DEFINE_string("log_dir", "/tmp/dttpu_gpt", "checkpoints + events")
flags_lib.DEFINE_integer("seed", 0, "data/init seed")
flags_lib.DEFINE_integer("num_layers", 2, "decoder blocks")
flags_lib.DEFINE_integer("pipeline_stages", 0,
                         "split the decoder over a 'pipe' mesh axis "
                         "(0 = off; must divide --num_layers AND the "
                         "device count; replaces the fsdp axis)")
flags_lib.DEFINE_string("pp_schedule", "gpipe",
                        "pipeline schedule: gpipe (autodiff backward) | "
                        "1f1b (hand-scheduled, O(stages) activation memory)")
flags_lib.DEFINE_string("family", "gpt2",
                        "decoder recipe: gpt2 (layernorm/gelu/learned "
                        "positions) | llama (rmsnorm/swiglu/rope/GQA, "
                        "models/llama.py)")
flags_lib.DEFINE_integer("loss_seq_chunk", 0,
                         "chunked LM loss: compute the head projection + "
                         "log-softmax N tokens at a time (the full "
                         "[tokens, vocab] logits never materialise; "
                         "0 = off)")
flags_lib.DEFINE_string("remat_policy", "full",
                        "with remat: full (save nothing) | dots (save "
                        "matmul outputs) | dots_no_batch")
flags_lib.DEFINE_bool("remat", False, "checkpoint each decoder layer "
                      "(recompute in backward; unlocks bigger batches)")
FLAGS = flags_lib.FLAGS


def main() -> int:
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import data, optim, parallel, summary, train
    from distributed_tensorflow_tpu.data.datasets import (lm_sequences,
                                                          synthetic_lm_corpus)
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig

    n = len(jax.devices())
    pp = FLAGS.pipeline_stages
    if pp > 1:
        if n % pp:
            raise SystemExit(f"--pipeline_stages={pp} does not divide the "
                             f"device count {n}")
        if FLAGS.num_layers % pp:
            raise SystemExit(f"--pipeline_stages={pp} does not divide "
                             f"--num_layers={FLAGS.num_layers}")
        fsdp = 1
        mesh = parallel.make_mesh({"pipe": pp, "data": n // pp})
    else:
        fsdp = 2 if n % 2 == 0 and n > 1 else 1
        mesh = parallel.make_mesh({"data": n // fsdp, "fsdp": fsdp})
    print(f"devices: {n} ({jax.devices()[0].platform}), "
          f"mesh={dict(mesh.shape)}", file=sys.stderr)

    # XLA:CPU miscompiles scan+ppermute pipeline programs with bf16
    # activations ("Invalid binary instruction opcode copy" check failure
    # in both the GPipe transpose and the jitted pipelined forward) — on
    # the CPU backend the pp path trains in f32.  TPU keeps bf16.
    pp_cpu = pp > 1 and jax.devices()[0].platform == "cpu"
    if pp_cpu:
        print("pp on XLA:CPU: falling back to f32 activations (bf16 "
              "pipeline programs trip an XLA:CPU compiler bug)",
              file=sys.stderr)
    dims = dict(vocab_size=256, num_layers=FLAGS.num_layers, num_heads=4,
                hidden_size=128, max_position=FLAGS.seq_len,
                dtype=jnp.float32 if pp_cpu else jnp.bfloat16,
                pipeline_stages=pp if pp > 1 else 0,
                remat=FLAGS.remat, remat_policy=FLAGS.remat_policy,
                loss_seq_chunk=FLAGS.loss_seq_chunk)
    if FLAGS.family == "llama":
        from distributed_tensorflow_tpu.models.llama import llama_config
        config = llama_config(num_kv_heads=2, **dims)
    elif FLAGS.family == "gpt2":
        config = GPTConfig(**dims)
    else:
        raise SystemExit(f"--family={FLAGS.family!r}: gpt2|llama")
    model = GPT(config, mesh=mesh if pp > 1 else None)
    optimizer = optim.with_ema(optim.adamw(3e-3), decay=0.99)

    params = model.init(jax.random.PRNGKey(FLAGS.seed))
    state = train.TrainState.create(params, optimizer.init(params))
    state = train.shard_train_state(state, mesh,
                                    model.partition_rules(fsdp=fsdp > 1))

    if pp > 1 and FLAGS.pp_schedule == "1f1b":
        # hand-scheduled 1F1B: full-model grads at O(stages) memory
        step = train.make_1f1b_train_step(model, optimizer,
                                          grad_clip_norm=1.0)
    else:
        # non-pp, or GPipe: apply() routes the decoder through the
        # pipeline and autodiff transposes it into the backward schedule.
        # The bf16 policy is skipped under pp: config.dtype already casts
        # the compute path, and the param-cast composed with the pipeline
        # shard_map trips an XLA:CPU check failure.
        step = train.make_custom_train_step(
            model.lm_loss_fn(), optimizer, grad_clip_norm=1.0,
            policy=None if pp > 1 else "mixed_bfloat16")

    # order-1 (bigram) chain: strongly learnable, so short runs show a real
    # drop below the uniform baseline
    rows = lm_sequences(synthetic_lm_corpus(config.vocab_size, 200_000,
                                            seed=FLAGS.seed, order=1),
                        FLAGS.seq_len)
    batch = parallel.round_batch_to_mesh(FLAGS.batch_size, mesh)
    if pp > 1 and batch % pp:
        # the pipeline also needs batch % microbatches == 0 (= stages
        # here); round up to the lcm of the data-shard and stage counts
        import math
        quantum = math.lcm(parallel.data_shards(mesh), pp)
        batch = -(-FLAGS.batch_size // quantum) * quantum
        print(f"batch_size -> {batch} (divisible by {quantum}: data shards"
              f" x pipeline stages)", file=sys.stderr)
    ds = data.Dataset([rows], batch, seed=FLAGS.seed)
    bsh = NamedSharding(mesh, P(("data", "fsdp")) if fsdp > 1 else P("data"))

    writer = summary.SummaryWriter(FLAGS.log_dir) if parallel.is_chief() \
        else None
    hooks = [train.StopAtStepHook(FLAGS.steps),
             train.LoggingHook(every_steps=20),
             train.NaNHook(every_steps=20)]
    if writer is not None:
        hooks.append(train.SummaryHook(writer, every_steps=10))

    sync_every = 1 if jax.devices()[0].platform == "cpu" else 20
    with train.TrainSession(state, step, checkpoint_dir=FLAGS.log_dir,
                            hooks=hooks, sharded_checkpoint=True) as sess:
        it = 0
        while not sess.should_stop():
            for (b,) in ds:
                if sess.should_stop():
                    break
                m = sess.run_step({"input_ids": jax.device_put(b, bsh)})
                it += 1
                if it % sync_every == 0:
                    float(m["loss"])   # CPU collectives need a shallow queue
        final = sess.state
    if writer is not None:
        writer.close()

    # Evaluate both live and EMA weights on held-out rows; generate a sample.
    eval_rows = rows[-64:]
    loss_fn = model.lm_loss_fn()

    # jit the eval: the pipelined apply (shard_map manual over 'pipe' only)
    # requires a jit context on a multi-axis mesh
    @jax.jit
    def _eval(params, rows_):
        return loss_fn(params, (), {"input_ids": rows_}, None, False)

    def eval_loss(params):
        loss, (metrics, _) = _eval(params, jnp.asarray(eval_rows))
        return float(loss), float(metrics["token_accuracy"])
    live = eval_loss(final.params)
    ema = eval_loss(optim.ema_params(final.opt_state))
    uniform = float(np.log(config.vocab_size))
    print(f"eval loss: live={live[0]:.3f} ema={ema[0]:.3f} "
          f"(uniform={uniform:.3f}); token acc live={live[1]:.3f}")

    prompt = jnp.asarray(eval_rows[:2, :8])
    out = model.generate(final.params, prompt, max_new_tokens=16)
    print(f"generated: {np.asarray(out)[0].tolist()}")
    if live[0] >= uniform:
        print("WARNING: did not beat the uniform baseline", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
