"""ViT image classification — the vision-transformer training workflow.

Trains a tiny ViT on a deterministic synthetic image task (which quadrant
holds the bright patch), exercising

  * patchify-by-conv + pre-LN scanned encoder (``models.vit``),
  * data-parallel mesh training via ``make_custom_train_step``,
  * warmup-cosine LR schedule + grad clipping,
  * eval accuracy as the convergence oracle.

Run (CPU mesh): ``XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
python examples/train_vit.py --device=cpu --steps=300``
Run (TPU): ``python examples/train_vit.py``
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_tensorflow_tpu.utils import flags as flags_lib

flags_lib.DEFINE_string("device", "", "cpu|tpu override (config-level)")
flags_lib.DEFINE_integer("steps", 300, "training steps")
flags_lib.DEFINE_integer("batch_size", 64, "global batch size")
flags_lib.DEFINE_integer("seed", 0, "data/init seed")
FLAGS = flags_lib.FLAGS

SIZE = 32
CLASSES = 4


def make_batch(rng, batch):
    """Class = quadrant of a bright 8x8 patch on a noisy background."""
    x = rng.normal(0.0, 0.2, (batch, SIZE, SIZE, 3)).astype("float32")
    y = rng.integers(0, CLASSES, batch).astype("int32")
    half = SIZE // 2
    for i in range(batch):
        r = (y[i] // 2) * half + rng.integers(0, half - 8)
        c = (y[i] % 2) * half + rng.integers(0, half - 8)
        x[i, r:r + 8, c:c + 8] += 1.0
    return x, y


def main() -> int:
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import optim, parallel, train
    from distributed_tensorflow_tpu.models.vit import ViT, ViTConfig

    n = len(jax.devices())
    mesh = parallel.make_mesh({"data": n})
    print(f"devices: {n} ({jax.devices()[0].platform}), "
          f"mesh={dict(mesh.shape)}", file=sys.stderr)

    model = ViT(ViTConfig(image_size=SIZE, patch_size=8, channels=3,
                          num_classes=CLASSES, hidden_size=64, num_layers=4,
                          num_heads=4, intermediate_size=128,
                          dropout_rate=0.1))
    params = model.init(jax.random.PRNGKey(FLAGS.seed))
    optimizer = optim.adamw(optim.schedules.warmup_cosine_decay(
        3e-3, 20, FLAGS.steps))
    state = train.TrainState.create(params, optimizer.init(params))
    state = jax.device_put(state, NamedSharding(mesh, P()))
    step = train.make_custom_train_step(model.loss_fn(), optimizer,
                                        grad_clip_norm=1.0)

    rng = np.random.default_rng(FLAGS.seed)
    bsh = NamedSharding(mesh, P("data"))
    batch = parallel.round_batch_to_mesh(FLAGS.batch_size, mesh)
    for i in range(FLAGS.steps):
        x, y = make_batch(rng, batch)
        b = jax.device_put((x, y), bsh)
        state, metrics = step(state, b)
        if (i + 1) % 20 == 0:
            print(f"step {i + 1}: loss={float(metrics['loss']):.4f} "
                  f"acc={float(metrics['accuracy']):.3f}", flush=True)

    x, y = make_batch(np.random.default_rng(FLAGS.seed + 1), 256)
    import jax.numpy as jnp
    logits = jax.jit(lambda p, xb: model.apply(p, xb))(state.params,
                                                       jnp.asarray(x))
    acc = float(np.mean(np.argmax(np.asarray(logits), -1) == y))
    print(f"eval accuracy: {acc:.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
