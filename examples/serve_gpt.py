"""GPT serving demo: every decode path of the framework in one script.

Runs a small randomly-initialized GPT (structure, not quality — no
weights ship with the repo) through the serving tier:

  * greedy KV-cache ``generate`` (batched prompt prefill),
  * sampled generate (temperature / top_k / top_p),
  * ragged-prompt batch (LEFT-padded ``prompt_valid``),
  * beam search,
  * weight-only int8 decode (``ops.quant``, dequantize-inside-jit),
  * speculative decoding (layer-truncated draft; greedy exactness),

printing tokens/s for each.  On CPU the absolute numbers are
meaningless; the point is the surfaces and their composition.  Real
checkpoints drop in via ``models/convert.py`` (HF GPT-2) — see
examples/finetune_gpt2_hf.py.

While decoding, the demo serves live telemetry (obs/): ``/metrics``
exposes per-path token counters, decode-duration histograms, and
tokens/s gauges in Prometheus text format, ``/healthz`` a JSON liveness
doc — the same endpoint a production serving replica would register
with a scraper (docs/OBSERVABILITY.md).  ``--metrics_port=-1`` turns it
off; the default picks an ephemeral port and prints the URL.

Run: ``python examples/serve_gpt.py --device=cpu --new_tokens=32``
"""
from __future__ import annotations

import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_tensorflow_tpu.utils import flags as flags_lib

flags_lib.DEFINE_string("device", "", "cpu|tpu override (config-level)")
flags_lib.DEFINE_integer("new_tokens", 32, "tokens to generate per path")
flags_lib.DEFINE_integer("batch", 4, "batch size for the batched paths")
flags_lib.DEFINE_integer("seed", 0, "init/prompt seed")
flags_lib.DEFINE_integer("metrics_port", 0,
                         "serve /metrics + /healthz during the demo "
                         "(0 = ephemeral port, -1 = off)")
flags_lib.DEFINE_bool("engine", False,
                      "also run the greedy/sampled/ragged demos through "
                      "the continuous-batching engine (serve/) — same "
                      "tokens/s lines, lock-step paths stay as the "
                      "baseline; serve metrics land on /metrics")
flags_lib.DEFINE_integer("replicas", 1,
                         ">= 2: also run a FLEET demo — that many "
                         "engine replicas behind the fleet Router "
                         "(least-loaded placement, per-tenant "
                         "fair-share, a hot-swapped LoRA adapter), "
                         "with the dttpu_router_*/dttpu_tenant_* "
                         "gauges live on /metrics")
flags_lib.DEFINE_bool("shared_prefix", False,
                      "also run the paged-KV radix-cache demo: "
                      "requests sharing a system prompt map the same "
                      "read-only pages, skip those prefill windows, "
                      "and print the measured TTFT delta + prefix-hit "
                      "line (serve/pages.py)")
FLAGS = flags_lib.FLAGS


def main() -> int:
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_tensorflow_tpu import obs
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    from distributed_tensorflow_tpu.models.speculative import \
        generate_speculative
    from distributed_tensorflow_tpu.ops import quant

    telemetry = None
    if FLAGS.metrics_port >= 0:
        telemetry = obs.Telemetry(metrics_port=FLAGS.metrics_port,
                                  service="serve").start()
        print(f"telemetry: {telemetry.metrics_url()} (+ /healthz)",
              flush=True)

    new = FLAGS.new_tokens
    b = FLAGS.batch
    plen = 8
    max_len = plen + new + 8
    config = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                       num_heads=4, intermediate_size=512,
                       max_position=max_len + 8, dropout_rate=0.0)
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(FLAGS.seed))
    rng = np.random.default_rng(FLAGS.seed)
    prompt = rng.integers(0, config.vocab_size, (b, plen)).astype(np.int32)

    def timed(name, fn, tokens_out):
        out = fn()
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = fn()
        out = jax.tree.map(np.asarray, out)     # value fetch
        dt = time.perf_counter() - t0
        print(f"{name:<28} {tokens_out / dt:10,.0f} tok/s", flush=True)
        if telemetry is not None:
            # one label value per decode path; static cardinality
            path = re.sub(r"[^a-z0-9]+", "_", name.lower()).strip("_")
            reg = telemetry.registry
            reg.counter("dttpu_decode_tokens_total",
                        "Tokens generated, by decode path.",
                        labels={"path": path}).inc(tokens_out)
            reg.histogram("dttpu_decode_seconds",
                          "Wall time per timed decode call.",
                          labels={"path": path}).observe(dt)
            reg.gauge("dttpu_decode_tokens_per_second",
                      "Decode throughput, by path.",
                      labels={"path": path}).set(tokens_out / dt)
        return out

    greedy = timed("greedy generate", jax.jit(
        lambda: model.generate(params, prompt, max_new_tokens=new,
                               temperature=0.0, max_len=max_len)),
        b * new)

    timed("sampled (T=0.8, top_p=0.9)", jax.jit(
        lambda: model.generate(params, prompt, max_new_tokens=new,
                               temperature=0.8, top_p=0.9,
                               rng=jax.random.PRNGKey(1),
                               max_len=max_len)), b * new)

    valid = np.ones((b, plen), np.int32)
    valid[0, : plen // 2] = 0                    # one shorter prompt,
    ragged_prompt = prompt.copy()                # LEFT-padded
    ragged_prompt[0, : plen // 2] = 0
    timed("ragged batch (prompt_valid)", jax.jit(
        lambda: model.generate(params, jnp.asarray(ragged_prompt),
                               max_new_tokens=new,
                               prompt_valid=jnp.asarray(valid),
                               max_len=max_len)), b * new)

    timed("beam search (beam=4)", jax.jit(
        lambda: model.beam_search(params, prompt, max_new_tokens=new,
                                  beam_size=4, max_len=max_len)), b * new)

    timed("chunked prefill (W=4)", jax.jit(
        lambda: model.generate(params, prompt, max_new_tokens=new,
                               temperature=0.0, max_len=max_len,
                               prefill_chunk=4)), b * new)

    qparams = quant.quantize_tree(params)
    q_out = timed("int8 weights", jax.jit(
        lambda: model.generate(quant.dequantize_tree(qparams), prompt,
                               max_new_tokens=new, temperature=0.0,
                               max_len=max_len)), b * new)
    agree = float(np.mean(np.asarray(greedy)[:, plen:]
                          == np.asarray(q_out)[:, plen:]))
    print(f"{'':<28} int8 greedy agreement {agree:.3f}", flush=True)

    kv8_model = GPT(dataclasses.replace(config, kv_cache_dtype="int8"))
    kv8_out = timed("int8 weights + int8 KV cache", jax.jit(
        lambda: kv8_model.generate(quant.dequantize_tree(qparams), prompt,
                                   max_new_tokens=new, temperature=0.0,
                                   max_len=max_len)), b * new)
    agree8 = float(np.mean(np.asarray(greedy)[:, plen:]
                           == np.asarray(kv8_out)[:, plen:]))
    print(f"{'':<28} full-int8 greedy agreement {agree8:.3f}", flush=True)

    if FLAGS.engine:
        # Continuous-batching engine (serve/): per-request slots, chunked
        # prefill, retrace-free admission.  Greedy must match the
        # lock-step greedy output token-for-token (the engine exactness
        # contract, docs/SERVING.md); the ragged path needs no padding at
        # all — unequal prompts are simply unequal requests.
        from distributed_tensorflow_tpu import serve

        reg = telemetry.registry if telemetry is not None else None

        def timed_engine(name, eng, plist, tokens_out):
            def run():
                handles = [eng.submit(p, new) for p in plist]
                eng.drain()          # drain fetches tokens: wall closes
                return handles
            run()                    # warmup: compiles the engine's jits
            t0 = time.perf_counter()
            handles = run()
            dt = time.perf_counter() - t0
            print(f"{name:<28} {tokens_out / dt:10,.0f} tok/s",
                  flush=True)
            if telemetry is not None:
                path = re.sub(r"[^a-z0-9]+", "_", name.lower()).strip("_")
                reg.counter("dttpu_decode_tokens_total",
                            "Tokens generated, by decode path.",
                            labels={"path": path}).inc(tokens_out)
                reg.gauge("dttpu_decode_tokens_per_second",
                          "Decode throughput, by path.",
                          labels={"path": path}).set(tokens_out / dt)
            return handles

        eng = serve.Engine(model, params, num_slots=b, max_len=max_len,
                           prefill_chunk=4, tick_steps=4, registry=reg)
        hs = timed_engine("engine greedy", eng, list(prompt), b * new)
        agree_eng = float(np.mean([
            h.tokens == np.asarray(greedy)[i, plen:].tolist()
            for i, h in enumerate(hs)]))
        print(f"{'':<28} engine==lock-step greedy {agree_eng:.3f}",
              flush=True)

        eng_s = serve.Engine(model, params, num_slots=b, max_len=max_len,
                             prefill_chunk=4, tick_steps=4, registry=reg,
                             temperature=0.8, top_p=0.9,
                             rng=jax.random.PRNGKey(1))
        timed_engine("engine sampled (T=0.8)", eng_s, list(prompt),
                     b * new)

        # ragged: the short prompt is just a shorter REQUEST — submit the
        # unpadded rows the lock-step path had to left-pad
        ragged_rows = [ragged_prompt[0, plen // 2:]] + list(prompt[1:])
        timed_engine("engine ragged", eng, ragged_rows, b * new)

    if FLAGS.shared_prefix:
        # Paged-KV radix cache (serve/pages.py): one SYSTEM PROMPT
        # shared by every request.  The first request prefills it cold
        # and publishes its full pages; every follower maps them
        # read-only and skips those prefill windows — the TTFT delta
        # printed below is that skipped work, and the hit tokens are
        # bit-identical to a cold cache (tests/test_pages.py pins it).
        from distributed_tensorflow_tpu import serve

        reg = telemetry.registry if telemetry is not None else None
        # page_size pinned small so a 2-page system prompt + tail +
        # budget fits the demo's max_len whatever --new_tokens is
        eng_sp = serve.Engine(model, params, num_slots=b,
                              max_len=max_len, prefill_chunk=4,
                              tick_steps=4,
                              page_size=serve.auto_page_size(max_len, 4),
                              registry=reg)
        # warmup compiles the paged executables (cold-compile must not
        # masquerade as the uncached TTFT)
        eng_sp.submit(rng.integers(0, config.vocab_size, 6).astype(
            np.int32), 2)
        eng_sp.drain()
        sys_prompt = rng.integers(0, config.vocab_size,
                                  2 * eng_sp.scheduler.page_size
                                  ).astype(np.int32)
        ttfts = []
        for i in range(b):
            tail = rng.integers(0, config.vocab_size,
                                2 + i).astype(np.int32)
            h = eng_sp.submit(np.concatenate([sys_prompt, tail]), new)
            eng_sp.drain()
            ttfts.append(h.ttft_s)
        st = eng_sp.stats()
        cold_ms = ttfts[0] * 1e3
        hit_ms = sum(ttfts[1:]) / max(len(ttfts) - 1, 1) * 1e3
        print(f"{'shared-prefix (paged KV)':<28} ttft cold "
              f"{cold_ms:7.1f} ms -> hit {hit_ms:7.1f} ms "
              f"({cold_ms / max(hit_ms, 1e-9):.1f}x faster)",
              flush=True)
        print(f"{'':<28} prefix hits {st.prefix_hits_total}/"
              f"{st.prefix_lookups_total}, "
              f"{st.prefill_windows_skipped_total} prefill windows "
              f"skipped, {st.prefix_tokens_reused_total} tokens "
              f"reused, {st.pages_free}/{st.pages_total} pages free",
              flush=True)

    if FLAGS.replicas >= 2:
        # Fleet demo (fleet/): N engine replicas behind one Router —
        # least-loaded placement off Engine.stats(), two tenants under
        # a deficit-weighted fair-share policy, and tenant "pro"
        # decoding under a hot-swapped LoRA adapter.  Greedy traffic
        # with adapter_id=None must still match the lock-step greedy
        # output (the fleet inherits the engine exactness contract).
        from distributed_tensorflow_tpu import fleet, serve

        reg = telemetry.registry if telemetry is not None else None
        policy = fleet.TenantPolicy(quantum=8)
        router = fleet.Router(
            [serve.Engine(model, params, num_slots=b, max_len=max_len,
                          prefill_chunk=4, tick_steps=4, registry=reg,
                          tenancy=policy, adapter_capacity=2,
                          adapter_rank=4)
             for _ in range(FLAGS.replicas)],
            registry=reg)
        router.load_adapter(
            "pro-tuned", model.init_lora(jax.random.PRNGKey(11), rank=4))

        def fleet_round():
            handles = []
            for i, p in enumerate(prompt):
                tenant = "pro" if i % 2 else "free"
                handles.append(router.submit(
                    p, new, tenant=tenant,
                    adapter_id="pro-tuned" if tenant == "pro" else None))
            router.drain()
            return handles

        fleet_round()                          # warmup: compiles all
        t0 = time.perf_counter()
        hs = fleet_round()
        dt = time.perf_counter() - t0
        print(f"{'fleet (%d replicas)' % FLAGS.replicas:<28} "
              f"{b * new / dt:10,.0f} tok/s", flush=True)
        base_rows = [i for i in range(b) if i % 2 == 0]
        agree_fleet = float(np.mean([
            hs[i].tokens == np.asarray(greedy)[i, plen:].tolist()
            for i in base_rows]))
        spread = {r: sum(1 for _, rid in router.placements if rid == r)
                  for r in router.replica_ids}
        print(f"{'':<28} fleet==lock-step greedy {agree_fleet:.3f} "
              f"(base-model rows), placements {spread}", flush=True)

    draft = GPT(dataclasses.replace(config, num_layers=2))
    d_params = dict(params)
    d_params["decoder"] = jax.tree.map(lambda a: a[:2], params["decoder"])
    spec_out, acc = timed("speculative (gamma=4)", jax.jit(
        lambda: generate_speculative(model, params, draft, d_params,
                                     prompt[:1], max_new_tokens=new,
                                     gamma=4)), new)
    match = float(np.mean(np.asarray(greedy)[:1, plen:]
                          == np.asarray(spec_out)[:, plen:]))
    print(f"{'':<28} spec acceptance {float(acc):.3f}, greedy match "
          f"{match:.3f}", flush=True)
    if telemetry is not None:
        # self-scrape: prove the endpoint a scraper would hit is live and
        # carrying the decode series recorded above
        import urllib.request
        with urllib.request.urlopen(telemetry.metrics_url(),
                                    timeout=5) as resp:
            text = resp.read().decode("utf-8")
        samples = [l for l in text.splitlines()
                   if l and not l.startswith("#")]
        print(f"{'':<28} /metrics scrape: {len(samples)} samples",
              flush=True)
        telemetry.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
