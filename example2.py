"""High-level (Sequential/compile/fit) distributed training entrypoint.

Capability-parity rebuild of reference example2.py (cited lines refer to
/root/reference/example2.py): the same XOR task and MLP expressed as a
``Sequential`` container (ref :151-156), ``compile(loss='mean_squared_error',
optimizer='adam', metrics=['accuracy'])`` (ref :165), and
``model.fit(..., validation_data=..., callbacks=[TensorBoard])``
(ref :197-200) — with the same cluster bootstrap as example.py.

Divergences from the reference, on purpose (SURVEY.md §7):
  * No ``K.set_session`` bridge (ref :194-195): fit drives the framework's
    own jitted step directly; distribution is a ``mesh=`` argument to
    ``compile``.
  * Checkpointing is NOT silently disabled (the reference comments it out,
    ref :187,191-192) — pass --log_dir and the TensorBoard callback writes
    there; epochs defaults to the module constant instead of the reference's
    hard-coded ``epochs=20`` drift (ref :20,200).
  * The broken ``xor_metric`` (ref :158-163, no return statement) maps to
    the working ``bitwise_accuracy`` metric.
"""
import os
import sys
from time import time

from distributed_tensorflow_tpu.utils import flags as flags_lib
from distributed_tensorflow_tpu.utils.flags import FLAGS

# Hyperparameters (parity with ref :14-21)
bits = 32
train_batch_size = 50
train_set_size = 30000
val_set_size = 1000
epochs = 50

flags_lib.DEFINE_string("job_name", flags_lib.env_default("JOB_NAME", None),
                        "Legacy role name ('ps' is refused)")
flags_lib.DEFINE_integer("task_index",
                         flags_lib.env_default("TASK_INDEX", 0, int),
                         "Process index; 0 is chief")
flags_lib.DEFINE_string("log_dir",
                        os.environ.get("LOG_DIR",
                                       os.path.join("logs", "xor2_{}")),
                        "TensorBoard/checkpoint dir; '{}' gets a timestamp "
                        "(parity with ref :197)")
flags_lib.DEFINE_string("device", "",
                        "Force a JAX platform ('tpu', 'cpu'); empty = default")
flags_lib.DEFINE_integer("epochs", epochs, "Training epochs")
flags_lib.DEFINE_integer("batch_size", train_batch_size, "Global batch size")
flags_lib.DEFINE_integer("seed", 0, "PRNG seed")


def main() -> int:
    FLAGS.parse()
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)

    from distributed_tensorflow_tpu.parallel import cluster
    config = cluster.cluster_from_env()
    if FLAGS.job_name == "ps" or config.is_legacy_ps:
        print("JOB_NAME=ps: no parameter-server role on TPU. Exiting.")
        if os.environ.get("DTTPU_LAUNCHER"):
            # under a supervisor, exit 0 would read as "completed" —
            # refuse loudly instead (fleet/launcher.py names the reason)
            return cluster.LEGACY_PS_EXIT_CODE
        return 0
    if not config.distributed:
        print("Running single-machine training")
    cluster.initialize(config)

    import jax

    from distributed_tensorflow_tpu import data, models, ops, parallel

    mesh = parallel.data_parallel_mesh()
    print(f"devices: {len(jax.devices())} ({jax.devices()[0].platform}), "
          f"mesh={dict(mesh.shape)}")

    # Sequential model (parity with ref :151-156).
    model = models.Sequential(name="xor_mlp")
    model.add(ops.Dense(128, activation="relu"))
    model.add(ops.Dropout(0.3))
    model.add(ops.Dense(128, activation="relu"))
    model.add(ops.Dropout(0.3))
    model.add(ops.Dense(bits, activation="sigmoid"))

    # compile (parity with ref :165; 'accuracy' on sigmoid bits = the
    # reference's rounded elementwise accuracy graph).
    model.compile(loss="mean_squared_error", optimizer="adam",
                  metrics=["bitwise_accuracy"], mesh=mesh, seed=FLAGS.seed)

    (x_train, y_train), (x_val, y_val) = data.xor_data(
        train_set_size, val_set_size, seed=FLAGS.seed)

    log_dir = FLAGS.log_dir.format(time())
    tensorboard = models.TensorBoard(log_dir=log_dir)   # ref :197

    # fit (parity with ref :200).
    model.fit(x_train, y_train, epochs=FLAGS.epochs,
              batch_size=FLAGS.batch_size,
              validation_data=(x_val, y_val),
              callbacks=[tensorboard], seed=FLAGS.seed)

    final = model.evaluate(x_val, y_val, batch_size=FLAGS.batch_size,
                           verbose=0)
    print(f"Final validation accuracy: {final['bitwise_accuracy']:.4f}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
