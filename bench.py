"""Benchmark harness — prints ONE JSON line to stdout.

Metric (per BASELINE.md): MNIST-MLP training examples/sec/chip, measured on
the framework's compiled data-parallel train step on whatever devices are
available (the real TPU chip under the driver; the virtual CPU mesh in
tests), plus a convergence gate (final eval accuracy must clear 0.9 on the
synthetic set or the result is reported as failed).

``vs_baseline``: the reference publishes no numbers (BASELINE.md:
"published: {}"), so the baseline is a measured stand-in for its
CPU/GPU-era stack: the SAME model/batch/optimizer stepped with torch on CPU
(the reference's TF-1.4 path is unrunnable here).  When torch is
unavailable the documented fallback constant is used.  Everything except
the JSON line goes to stderr.
"""
import json
import sys
import time

# Estimated examples/sec for the reference-era stack on a single CPU host —
# used only if the live torch baseline cannot run.
FALLBACK_BASELINE = 1.0e5

BATCH = 8192
STEPS_PER_CALL = 32   # lax.scan'd updates per dispatch (train.make_multi_train_step)
WARMUP_CALLS = 2
CALLS = 8


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_framework():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import data, models, optim, parallel, train

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    log(f"framework: {n_chips} x {jax.devices()[0].platform}, "
        f"mesh={dict(mesh.shape)}")

    (xt, yt), (xv, yv) = data.mnist(flatten=True)
    model = models.mnist_mlp()
    optimizer = optim.adam()
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, mesh=mesh)
    eval_step = train.make_eval_step(model, "sparse_categorical_crossentropy",
                                     metric_fns={"accuracy": "accuracy"})
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = jax.device_put(state, NamedSharding(mesh, P()))
    bsh = NamedSharding(mesh, P("data"))

    batch = parallel.round_batch_to_mesh(BATCH, mesh)
    # backend="auto": the native C++ threaded gather loader when built.
    ds = data.Dataset([xt, yt], batch, seed=0, backend="auto")

    # Convergence gate: a couple of epochs must clear 0.9 eval accuracy.
    for b in ds.epochs(2):
        state, _ = step(state, jax.device_put(b, bsh))
    acc = float(eval_step(state, (xv[:8192], yv[:8192]))["accuracy"])
    log(f"eval accuracy after 2 epochs: {acc:.4f}")

    # Throughput: the framework's multi-step path — STEPS_PER_CALL updates
    # scanned inside ONE compiled dispatch (train.make_multi_train_step), a
    # device-resident stacked batch, block at the end.
    multi = train.make_multi_train_step(
        model, "sparse_categorical_crossentropy", optimizer,
        steps_per_call=STEPS_PER_CALL, mesh=mesh)
    k = STEPS_PER_CALL
    xs = np.resize(xt, (k * batch, xt.shape[1])).reshape(k, batch, -1)
    ys = np.resize(yt, (k * batch,)).reshape(k, batch)
    msh = NamedSharding(mesh, P(None, "data"))
    bench_batch = (jax.device_put(xs, msh), jax.device_put(ys, msh))
    for _ in range(WARMUP_CALLS):
        state, m = multi(state, bench_batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(CALLS):
        state, m = multi(state, bench_batch)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    steps = CALLS * k
    eps = steps * batch / dt
    log(f"framework (multi-step): {eps:,.0f} examples/s total, "
        f"{eps / n_chips:,.0f} /chip ({dt / steps * 1e3:.2f} ms/step, "
        f"{k} steps/dispatch)")

    # Single-step dispatch path (what TrainSession drives per batch) — kept
    # visible so a regression there can't hide behind the scanned number.
    single_batch = (bench_batch[0][0], bench_batch[1][0])
    for _ in range(5):
        state, m = step(state, single_batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(40):
        state, m = step(state, single_batch)
    jax.block_until_ready(m["loss"])
    dts = time.perf_counter() - t0
    eps_single = 40 * batch / dts
    log(f"framework (single-step): {eps_single:,.0f} examples/s total "
        f"({dts / 40 * 1e3:.2f} ms/step)")
    return eps / n_chips, acc, eps_single / n_chips


def bench_torch_baseline():
    """Same MLP/batch/optimizer stepped with torch on CPU (reference-era
    proxy: host-resident training, no XLA)."""
    try:
        import torch
        import torch.nn as nn
    except Exception as e:  # pragma: no cover
        log(f"torch baseline unavailable ({e}); using fallback constant")
        return None
    torch.manual_seed(0)
    torch.set_num_threads(max(1, (torch.get_num_threads())))
    model = nn.Sequential(nn.Linear(784, 128), nn.ReLU(), nn.Dropout(0.2),
                          nn.Linear(128, 10))
    opt = torch.optim.Adam(model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    x = torch.rand(BATCH, 784)
    y = torch.randint(0, 10, (BATCH,))
    for _ in range(3):  # warmup
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
    steps = 15
    t0 = time.perf_counter()
    for _ in range(steps):
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
    dt = time.perf_counter() - t0
    eps = steps * BATCH / dt
    log(f"torch CPU baseline: {eps:,.0f} examples/s")
    return eps


def main():
    value, acc, value_single = bench_framework()
    baseline = bench_torch_baseline()
    if baseline is None:
        baseline = FALLBACK_BASELINE
    converged = acc > 0.9
    result = {
        "metric": "mnist_mlp_train_examples_per_sec_per_chip"
                  + ("" if converged else "_NOT_CONVERGED"),
        "value": round(value, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(value / baseline, 3),
        "steps_per_call": STEPS_PER_CALL,
        "single_step_value": round(value_single, 1),
        "eval_accuracy": round(acc, 4),
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
