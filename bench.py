"""Benchmark harness — prints ONE JSON line to stdout.

Default metric (per BASELINE.md): MNIST-MLP training examples/sec/chip,
measured on the framework's compiled data-parallel train step on whatever
devices are available (the real TPU chip under the driver; the virtual CPU
mesh in tests), plus a convergence gate (final eval accuracy must clear the
per-provenance threshold or the result is reported as failed).

Other configs: ``python bench.py --config=cifar_cnn|resnet50|bert|gpt|llama|gpt_decode``
measure those rows (same JSON shape; resnet50/bert are throughput+finite-loss
benches, no convergence gate).  ``DTTPU_BENCH_SMOKE=1`` shrinks model/batch
sizes so every config path smoke-runs on the CPU mesh.

Supervisor layer (the default entry): the axon TPU tunnel can hang
indefinitely during backend init, so the bench re-runs itself as a child
subprocess — a hung attempt is killed and retried in a FRESH process (the
hang is in first-touch backend init; a second attempt often wins tunnel
flakes), and if the tunnel is down hard the final attempt measures on
single-device XLA:CPU and labels the metric ``*_CPU_FALLBACK``.  The
probe/retry loop itself runs through ``resilience.Supervisor`` (the same
bounded-restart machinery the training tier uses: probe failures are
transient ``ConnectionError``s with exponential backoff inside the
bring-up budget; failed attempts checkpoint their partial JSON).  The
driver therefore always receives a nonzero, honestly-labeled number.
Env knobs: ``DTTPU_BENCH_TPU_ATTEMPTS`` (default 2),
``DTTPU_BENCH_INIT_TIMEOUT`` (total backend-init budget, split across
attempts; default 240 s), ``DTTPU_BENCH_RUN_TIMEOUT`` (per-attempt wall
clock; default 900 s), ``DTTPU_BENCH_NO_SUPERVISOR=1`` (run inline).

Every JSON line also carries an ``mfu`` field when the chip's peak FLOP/s is
known (model FLOPs utilisation = achieved FLOP/s ÷ peak): the per-step FLOP
count comes from XLA's own cost analysis of the exact compiled executable
(``lower().compile().cost_analysis()``), falling back to an analytic model.
Image benches carry ``data: real|synthetic`` provenance (real files under
``DTTPU_DATA_DIR`` vs the procedural stand-ins in data/datasets.py) and gate
convergence on the provenance-appropriate threshold.

Telemetry (obs/): unless ``DTTPU_BENCH_TELEMETRY=0``, train-config JSON
lines carry ``step_time_p50_ms``/``step_time_p95_ms`` (per-update host
latency, every sample closed with a completion barrier) and
``trace_file`` — a Chrome-trace/Perfetto host timeline of the measured
dispatches plus every jit compile/retrace the sanitizer observed
(``DTTPU_BENCH_TRACE_FILE`` overrides the path,
``DTTPU_BENCH_LATENCY_STEPS`` sizes the async latency pass).

``vs_baseline``: the reference publishes no numbers (BASELINE.md:
"published: {}"), so the baseline is a measured stand-in for its
CPU/GPU-era stack: the SAME model/batch/optimizer stepped with torch on CPU
(the reference's TF-1.4 path is unrunnable here).  When torch is
unavailable the documented fallback constant is used.  Everything except
the JSON line goes to stderr.
"""
import contextlib
import json
import os
import sys
import time

SMOKE = bool(os.environ.get("DTTPU_BENCH_SMOKE"))

# Telemetry (obs/): DTTPU_BENCH_TELEMETRY=0 disables.  When on, the run
# records a host timeline (dispatch spans + RetraceGuard compile/retrace
# instants) whose file path lands in the JSON line as `trace_file`, and
# per-update host latencies (each closed with a completion barrier, never
# the async-dispatch lie dtlint DT107 flags) feed `step_time_p50_ms` /
# `step_time_p95_ms`.  Measured overhead on the CPU smoke bench is under
# 1% (docs/OBSERVABILITY.md).
TELEMETRY = os.environ.get("DTTPU_BENCH_TELEMETRY", "1") != "0"
_STEP_TIMES = []   # per-update seconds, barrier-closed (see _time_steps)
LATENCY_STEPS = int(os.environ.get("DTTPU_BENCH_LATENCY_STEPS", "10"))

_PROMOTED = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "docs", "PROMOTED.json")


def _load_promoted_defaults():
    """docs/PROMOTED.json (written by scripts/promote_levers.py from
    measured MFU-ablation winners) supplies DEFAULTS for the lever env
    knobs — setdefault, so an explicitly exported env var still wins, and
    rows that record their lever state (loss_seq_chunk / remat_policy /
    mlm_predictions_per_seq in the result JSON) disclose what ran.

    Called from main() only — importing bench as a library must not
    mutate os.environ — and skipped under SMOKE: wiring checks measure
    nothing, so promoted real-hardware defaults would only make their
    behavior depend on repo state."""
    if SMOKE or not os.path.exists(_PROMOTED):
        return
    try:
        with open(_PROMOTED) as f:
            for k, v in (json.load(f).get("env") or {}).items():
                os.environ.setdefault(k, str(v))
    except (OSError, ValueError) as e:
        print(f"bench: ignoring unreadable {_PROMOTED}: {e}",
              file=sys.stderr)

# Estimated examples/sec for the reference-era stack on a single CPU host —
# used only if the live torch baseline cannot run.  Per config: these are
# measured torch-CPU rates from this machine (mnist/cifar) or the
# torchvision-resnet50-on-CPU ballpark (no torchvision in this image).
FALLBACK_BASELINE = {"mnist_mlp": 1.9e5, "cifar_cnn": 9.0e2,
                     "resnet50": 3.0}

BATCH = int(os.environ.get("DTTPU_BENCH_BATCH", 512 if SMOKE else 8192))
# Scanned updates per dispatch.  Each dispatch pays one host->device
# round trip (tens of ms over the tunnel); more steps/call amortize it.
STEPS_PER_CALL = int(os.environ.get("DTTPU_BENCH_STEPS",
                                    4 if SMOKE else 64))
WARMUP_CALLS = 1 if SMOKE else 2
CALLS = 2 if SMOKE else 8
# Timed windows per measurement; the headline takes the BEST window.
# Applied symmetrically to the framework paths AND the torch baseline:
# the two sides run minutes apart, and on a shared host a background
# spike landing in one side's single window flips a ~1.0x ratio (the
# r04 rehearsal measured 0.97 and 1.01 for identical configs).
WINDOWS = 1 if SMOKE else max(1, int(os.environ.get("DTTPU_BENCH_WINDOWS",
                                                    "3")))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


_SYNC = None


def _sync_every_step() -> bool:
    """XLA:CPU collective rendezvous can't take deep async dispatch queues
    (a 40 s thread rendezvous deadlocks under many queued steps), so on the
    CPU mesh every step is blocked individually; on TPU the queue stays
    async and only the window-closing value fetch blocks."""
    global _SYNC
    if _SYNC is None:
        import jax
        _SYNC = SMOKE or jax.default_backend() == "cpu"
    return _SYNC


def _fetch(metrics) -> float:
    """Device->host fetch of the loss — the only reliable completion
    barrier.  Over the axon TPU tunnel ``jax.block_until_ready`` returns
    before the remote execution finishes, so any window "closed" with it
    times dispatch, not compute; a value fetch cannot lie.  The steps in a
    window form a donated-state chain, so fetching the last loss proves
    every step ran."""
    import numpy as np
    return float(np.asarray(metrics["loss"]).ravel()[-1])


# ---------------------------------------------------------------------------
# MFU accounting

# bf16 peak FLOP/s per chip by device_kind substring (public TPU specs).
_PEAK_BF16 = [("v6e", 918e12), ("v6 lite", 918e12), ("v5p", 459e12),
              ("v5e", 197e12), ("v5 lite", 197e12), ("v4", 275e12),
              ("v3", 123e12), ("v2", 46e12)]


def _peak_flops_per_chip():
    """Per-chip peak bf16 FLOP/s, or None when unknown (CPU mesh).
    ``DTTPU_PEAK_FLOPS`` overrides for parts not in the table."""
    env = os.environ.get("DTTPU_PEAK_FLOPS")
    if env:
        return float(env)
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if dev.platform == "cpu":
        return None
    for key, val in _PEAK_BF16:
        if key in kind:
            return val
    return None


def _flops_of(fn, *args):
    """Total FLOPs of one call of a jitted ``fn`` on ``args``, from XLA's
    cost analysis of the exact compiled executable.  Returns None when the
    backend doesn't report flops.  Lowering is shape-only (nothing runs,
    donated buffers are untouched)."""
    try:
        target = fn if hasattr(fn, "lower") else None
        if target is None:
            return None
        cost = target.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", 0.0) or 0.0)
        return f if f > 0 else None
    except Exception as e:  # pragma: no cover - backend-specific
        log(f"cost_analysis unavailable ({e})")
        return None


def _per_example_flops(f_total, global_examples, mesh):
    """XLA's ``cost_analysis`` reports the per-device SPMD program's FLOPs;
    divide by the per-device (local) example count — global/ data shards —
    not the global batch, or mfu understates by the shard count on
    multi-device meshes (advisor round 2)."""
    if not f_total:
        return None
    from distributed_tensorflow_tpu import parallel
    return f_total * parallel.data_shards(mesh) / global_examples


def _attach_mfu(result: dict, rate_per_chip: float, flops_per_example,
                analytic=None, scanned=False) -> dict:
    """Add flops/example + mfu fields to a bench result.  ``rate_per_chip``
    is examples/s/chip (or tokens/s/chip with flops per token).

    XLA's ``cost_analysis`` counts a ``lax.scan`` body ONCE regardless of
    trip count (measured: an 8-iteration scan of a matmul body reports the
    same flops as a 1-iteration scan), so for scanned programs — the LM
    layer stacks, the K-step multi-dispatch — the compiled-step figure
    undercounts by ~the trip count and the mfu field understated by the
    same factor in rounds 2-4 (gpt read 0.17 while the analytic 6N+12Lhs
    accounting of the identical run gives 0.45).  Callers whose timed
    program contains a scan pass ``scanned=True``; for those rows, when
    the XLA figure is less than 60% of the analytic estimate, trust the
    analytic model and keep the raw XLA number in
    ``flops_xla_scan_undercount`` for the record.  Unscanned rows always
    keep the XLA source (resnet: XLA ~= 3x the forward-only analytic
    constant, and silently replacing an honest compiled-step figure with
    a rough hard-coded constant would corrupt the provenance trail)."""
    f = flops_per_example or analytic
    if not f:
        return result
    source = "xla" if flops_per_example else "analytic"
    if (scanned and flops_per_example and analytic
            and flops_per_example < 0.6 * analytic):
        result["flops_xla_scan_undercount"] = round(float(flops_per_example), 1)
        f, source = analytic, "analytic"
    result["flops_per_example"] = round(float(f), 1)
    result["flops_source"] = source
    peak = _peak_flops_per_chip()
    if peak:
        result["mfu"] = round(rate_per_chip * f / peak, 4)
    return result


# HBM bandwidth per chip by device_kind substring (public TPU specs),
# bytes/s — the roofline's second axis next to _PEAK_BF16.
_PEAK_HBM_BW = [("v6e", 1640e9), ("v6 lite", 1640e9), ("v5p", 2765e9),
                ("v5e", 819e9), ("v5 lite", 819e9), ("v4", 1228e9),
                ("v3", 900e9), ("v2", 700e9)]


def _peak_hbm_bw():
    """Per-chip HBM bandwidth in bytes/s, or None when unknown.
    ``DTTPU_PEAK_BW`` overrides for parts not in the table (and for the
    CPU smoke, where tests pin a fake roofline)."""
    env = os.environ.get("DTTPU_PEAK_BW")
    if env:
        return float(env)
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if dev.platform == "cpu":
        return None
    for key, val in _PEAK_HBM_BW:
        if key in kind:
            return val
    return None


def _attach_analytical(result: dict, step_fn, abstract_args,
                       tokens_per_step=None, in_specs=None,
                       mesh=None) -> dict:
    """Add the dtlint graph-tier cost model's static numbers next to the
    measured ones, making every perf claim cross-checkable against a
    roofline that was computed from the SAME traced program the lint
    gate checks (docs/ANALYSIS.md §graph tier):

    * ``analytical_flops`` / ``analytical_bytes``: FLOPs and bytes-moved
      of ONE compiled step per the cost model — scan bodies count times
      their trip count, so unlike XLA's ``cost_analysis`` this figure
      does not undercount the layer stack or the K-step dispatch;
    * ``analytical_flops_per_token`` when ``tokens_per_step`` is given;
    * ``analytical_mfu``: the roofline CEILING as an MFU fraction —
      ``min(1, peak_bw * intensity / peak_flops)`` — i.e. the best MFU
      this program shape can reach on this part.  A measured ``mfu``
      above it means the accounting (not the hardware) is wrong; far
      below it means the implementation leaves roofline on the table.
      Needs a known peak (``DTTPU_PEAK_FLOPS``/``DTTPU_PEAK_BW`` pin a
      fake roofline on the CPU smoke; bw unknown -> compute-bound
      ceiling 1.0);
    * ``analytical_comm_bytes`` / ``analytical_comm_time_s`` (when the
      caller passes ``in_specs``+``mesh``): the SPMD tier's static
      communication ledger for the same traced step — per-device wire
      bytes and modeled time of every collective the propagation finds
      (docs/ANALYSIS.md §spmd tier).  The sentinel holds these to a
      tight tolerance: static comm volume only moves when the program
      changes, so unexpected growth reds ``scripts/perf_gate.py``.

    Tracing is abstract (``jax.eval_shape``-style args) and never
    compiles; any failure logs and leaves the measured row intact.
    """
    try:
        from distributed_tensorflow_tpu.analysis import graph as graph_lib
        cost = graph_lib.entry_cost(step_fn, *abstract_args)
    except Exception as e:  # pragma: no cover - shape-spec drift
        log(f"analytical cost model unavailable ({e})")
        return result
    result["analytical_flops"] = round(float(cost.flops), 1)
    result["analytical_bytes"] = round(float(cost.bytes), 1)
    if tokens_per_step:
        result["analytical_flops_per_token"] = round(
            float(cost.flops) / tokens_per_step, 1)
    peak = _peak_flops_per_chip()
    if peak:
        bw = _peak_hbm_bw()
        ceiling = (min(1.0, bw * cost.intensity / peak) if bw else 1.0)
        result["analytical_mfu"] = round(ceiling, 4)
    if in_specs is not None and mesh is not None:
        try:
            from distributed_tensorflow_tpu.analysis import spmd as spmd_lib
            ledger = spmd_lib.entry_comm(step_fn, *abstract_args,
                                         in_specs=in_specs, mesh=mesh)
            result["analytical_comm_bytes"] = round(
                float(ledger.total_bytes), 1)
            result["analytical_comm_time_s"] = float(
                f"{ledger.total_time_s:.3e}")
        except Exception as e:  # pragma: no cover - propagation drift
            log(f"analytical comm ledger unavailable ({e})")
    return result


def _transformer_flops_per_token(params, num_layers: int, hidden: int,
                                 seq: int) -> float:
    """Analytic training FLOPs/token for a dense transformer: 6N for the
    matmul path (fwd 2N + bwd 4N) + 12*L*h*s for attention logits/context
    (fwd 4*L*h*s halves for QK^T and PV, x3 for training)."""
    import jax
    n = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    return 6.0 * n + 12.0 * num_layers * hidden * seq


# ---------------------------------------------------------------------------
# Measurement core


def bench_framework():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import data, models, optim, parallel, train

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    log(f"framework: {n_chips} x {jax.devices()[0].platform}, "
        f"mesh={dict(mesh.shape)}")

    data_dir = os.environ.get("DTTPU_DATA_DIR")
    prov = data.provenance("mnist", data_dir)
    (xt, yt), (xv, yv) = data.mnist(data_dir, flatten=True)
    model = models.mnist_mlp()
    optimizer = optim.adam()
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, mesh=mesh)
    eval_step = train.make_eval_step(model, "sparse_categorical_crossentropy",
                                     metric_fns={"accuracy": "accuracy"})
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = jax.device_put(state, NamedSharding(mesh, P()))
    bsh = NamedSharding(mesh, P("data"))

    batch = parallel.round_batch_to_mesh(BATCH, mesh)
    # backend="auto": the native C++ threaded gather loader when built.
    ds = data.Dataset([xt, yt], batch, seed=0, backend="auto")

    # Convergence gate: a couple of epochs must clear the eval threshold.
    for b in ds.epochs(1 if SMOKE else 2):
        state, m_ = step(state, jax.device_put(b, bsh))
        if _sync_every_step():
            jax.block_until_ready(m_["loss"])
    acc = float(eval_step(state, (xv[:8192], yv[:8192]))["accuracy"])
    log(f"eval accuracy after 2 epochs ({prov} data): {acc:.4f}")

    # Throughput: the framework's multi-step path — STEPS_PER_CALL updates
    # scanned inside ONE compiled dispatch (train.make_multi_train_step), a
    # device-resident stacked batch, block at the end.
    multi = train.make_multi_train_step(
        model, "sparse_categorical_crossentropy", optimizer,
        steps_per_call=STEPS_PER_CALL, mesh=mesh)
    k = STEPS_PER_CALL
    xs = np.resize(xt, (k * batch, xt.shape[1])).reshape(k, batch, -1)
    ys = np.resize(yt, (k * batch,)).reshape(k, batch)
    msh = NamedSharding(mesh, P(None, "data"))
    bench_batch = (jax.device_put(xs, msh), jax.device_put(ys, msh))
    f_total = _flops_of(multi, state, bench_batch)
    flops_per_example = _per_example_flops(f_total, k * batch, mesh)
    rate, _, sec, state = _time_steps(multi, state, bench_batch,
                                      warmup=WARMUP_CALLS, steps=CALLS,
                                      updates_per_call=k)
    eps = rate * k * batch
    log(f"framework (multi-step): {eps:,.0f} examples/s total, "
        f"{eps / n_chips:,.0f} /chip ({sec / k * 1e3:.2f} ms/step, "
        f"best of {WINDOWS} windows, {k} steps/dispatch)")

    # Single-step dispatch path (what TrainSession drives per batch) — kept
    # visible so a regression there can't hide behind the scanned number.
    single_batch = (bench_batch[0][0], bench_batch[1][0])
    rate, _, sec, state = _time_steps(step, state, single_batch,
                                      warmup=5, steps=40)
    eps_single = rate * batch
    log(f"framework (single-step): {eps_single:,.0f} examples/s total "
        f"({sec * 1e3:.2f} ms/step, best of {WINDOWS} windows)")
    return (eps / n_chips, acc, eps_single / n_chips, prov,
            flops_per_example)


def bench_torch_baseline():
    """Same MLP/batch/optimizer stepped with torch on CPU (reference-era
    proxy: host-resident training, no XLA)."""

    def build():
        import torch
        import torch.nn as nn
        model = nn.Sequential(nn.Linear(784, 128), nn.ReLU(),
                              nn.Dropout(0.2), nn.Linear(128, 10))
        x = torch.rand(BATCH, 784)
        y = torch.randint(0, 10, (BATCH,))
        ce = nn.CrossEntropyLoss()
        return model, lambda out: ce(out, y), \
            torch.optim.Adam(model.parameters()), (x,), BATCH

    # steps matches the framework's single-step window (40; _time_steps
    # clamps to 4 under SMOKE): comparable window DURATION means equal
    # exposure to background-noise spikes, so the two sides' best-of-N
    # statistics are comparable
    return _torch_step_rate(build, warmup=3, steps=4 if SMOKE else 40)


def _time_steps(step, state, batch, warmup=3, steps=12, updates_per_call=1):
    """Generic throughput timing for a compiled train step.  Returns
    (steps/sec, last loss, sec/step, final state) from the BEST of
    ``WINDOWS`` timed windows (same treatment as the torch baseline —
    see WINDOWS); per-chip normalization is the caller's job.  The input
    ``state`` is DONATED into the step chain — callers continuing to
    step must use the returned state.  On the CPU mesh every step is
    synced (see ``_sync_every_step``).

    Telemetry side channel (``TELEMETRY``): each timed dispatch is
    wrapped in an obs "dispatch" span, and per-UPDATE host latencies are
    collected into ``_STEP_TIMES`` for the JSON line's p50/p95 — only
    where a completion barrier closes the step: inline on the synced CPU
    mesh, and via a short dedicated pass (``LATENCY_STEPS``, each step
    closed with a value fetch) on async backends, where a per-step host
    clock inside the pipelined window would time dispatch (the DT107
    lie).  ``updates_per_call``: scanned multi-step dispatches report
    per-update latency, not per-dispatch."""
    import jax
    from distributed_tensorflow_tpu.obs import trace as obs_trace
    if SMOKE:
        warmup, steps = min(warmup, 2), min(steps, 4)
    for _ in range(warmup):
        state, m = step(state, batch)
        if _sync_every_step():
            jax.block_until_ready(m["loss"])
    _fetch(m)
    sync = _sync_every_step()
    # every window's (dt, loss) is captured together so the returned rate,
    # sec/step and loss all come from the SAME (best) window
    best_dt, best_loss = None, None
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(steps):
            t_step = time.perf_counter()
            with obs_trace.span("dispatch", updates=updates_per_call):
                state, m = step(state, batch)
            if sync:
                jax.block_until_ready(m["loss"])
                if TELEMETRY:
                    _STEP_TIMES.append(
                        (time.perf_counter() - t_step) / updates_per_call)
        loss = _fetch(m)
        dt = time.perf_counter() - t0
        if best_dt is None or dt < best_dt:
            best_dt, best_loss = dt, loss
    if TELEMETRY and not sync:
        for _ in range(min(steps, LATENCY_STEPS)):
            t_step = time.perf_counter()
            with obs_trace.span("dispatch", updates=updates_per_call):
                state, m = step(state, batch)
            _fetch(m)   # value fetch: the only honest barrier (docstring)
            _STEP_TIMES.append(
                (time.perf_counter() - t_step) / updates_per_call)
    return steps / best_dt, best_loss, best_dt / steps, state


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Ran out of memory", "out of memory",
                "hbm capacity", "Allocation failure")


def _is_oom(e: Exception) -> bool:
    """OOM classification for the batch ladder.  Primary signal: a jaxlib
    ``XlaRuntimeError`` whose status line is RESOURCE_EXHAUSTED (the
    canonical ``{code}: {message}`` rendering); the marker substrings cover
    runtimes that phrase allocation failure differently."""
    try:
        from jax.errors import JaxRuntimeError
        if (isinstance(e, JaxRuntimeError)
                and str(e).lstrip().startswith("RESOURCE_EXHAUSTED")):
            return True
    except ImportError:
        pass
    return any(k in str(e) for k in _OOM_MARKERS)


def _run_batch_ladder(name, ladder, mesh, build, step, warmup, steps):
    """Time ``step`` at the largest per-chip batch that fits.

    ``build(global_batch) -> (state, bench_batch)`` allocates fresh device
    buffers per rung (the step donates state, so a failed rung's state is
    unusable).  Only OOM errors descend the ladder — anything else is a
    real bug and raises immediately with its original traceback.  Failed
    rungs' buffers are dropped before the next allocation so the retry
    doesn't OOM on the dead rung's memory.

    Returns (steps/sec, loss, sec/step, global_batch, step_flops|None).
    """
    from distributed_tensorflow_tpu import parallel
    err = None
    for per_chip in ladder:
        batch = parallel.round_batch_to_mesh(
            per_chip * parallel.data_shards(mesh), mesh)
        state, bench_batch = build(batch)
        try:
            flops = _flops_of(step, state, bench_batch)
            rate, loss, ms, _ = _time_steps(step, state, bench_batch,
                                            warmup=warmup, steps=steps)
            return rate, loss, ms, batch, flops
        except Exception as e:
            if not _is_oom(e):
                raise
            err = e
            log(f"{name}: batch {per_chip}/chip OOM; retrying smaller")
            state = bench_batch = None   # free before the next rung
    raise err


def _torch_step_rate(build, warmup=2, steps=3):
    """examples/sec for the same workload stepped with torch on CPU;
    ``build() -> (module, loss_fn, optimizer, example_inputs, batch)``.
    Returns None (logged) on ANY failure — a missing torch/torchvision
    feature must not lose the framework measurement."""
    try:
        import torch
        torch.manual_seed(0)
        model, loss_fn, opt, inputs, batch = build()
        for _ in range(warmup):
            opt.zero_grad(); loss_fn(model(*inputs)).backward(); opt.step()
        eps = 0.0
        for _ in range(WINDOWS):    # best-of, same as the framework side
            t0 = time.perf_counter()
            for _ in range(steps):
                opt.zero_grad()
                loss_fn(model(*inputs)).backward()
                opt.step()
            eps = max(eps, steps * batch / (time.perf_counter() - t0))
    except Exception as e:  # pragma: no cover
        log(f"torch baseline unavailable ({e})")
        return None
    log(f"torch CPU baseline: {eps:,.1f} examples/s (best of {WINDOWS})")
    return eps


def bench_cifar_cnn():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_tensorflow_tpu import data, models, optim, parallel, train

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    batch = parallel.round_batch_to_mesh(64 if SMOKE else 1024, mesh)
    data_dir = os.environ.get("DTTPU_DATA_DIR")
    prov = data.provenance("cifar10", data_dir)
    (xt, yt), (xv, yv) = data.cifar10(data_dir)
    model = models.cifar_cnn()
    optimizer = optim.adam()
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, mesh=mesh)
    eval_step = train.make_eval_step(model, "sparse_categorical_crossentropy",
                                     metric_fns={"accuracy": "accuracy"})
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (32, 32, 3))
    state = jax.device_put(state, NamedSharding(mesh, P()))
    bsh = NamedSharding(mesh, P("data"))
    ds = data.Dataset([xt, yt], batch, seed=0, backend="auto")
    epochs = 1 if SMOKE else 2
    for i, b in enumerate(ds.epochs(epochs)):
        state, m = step(state, jax.device_put(b, bsh))
        # smoke: enough steps to actually clear the 0.15 smoke gate
        # (one step left accuracy at chance and the gate un-passable)
        if SMOKE and i >= 30:
            break
        if _sync_every_step():
            jax.block_until_ready(m["loss"])
    acc = float(eval_step(state, (xv[:2048], yv[:2048]))["accuracy"])
    log(f"cifar_cnn eval accuracy ({prov} data): {acc:.4f}")
    bench_batch = jax.device_put(next(iter(ds)), bsh)
    f_total = _flops_of(step, state, bench_batch)
    rate, loss, ms, _ = _time_steps(step, state, bench_batch)
    eps = rate * batch / n_chips
    log(f"cifar_cnn: {eps:,.0f} examples/s/chip ({ms*1e3:.2f} ms/step)")

    def torch_build():
        import torch
        import torch.nn as nn
        m = nn.Sequential(
            nn.Conv2d(3, 32, 3), nn.ReLU(), nn.Conv2d(32, 32, 3), nn.ReLU(),
            nn.MaxPool2d(2), nn.Conv2d(32, 64, 3), nn.ReLU(),
            nn.Conv2d(64, 64, 3), nn.ReLU(), nn.MaxPool2d(2), nn.Flatten(),
            nn.LazyLinear(256), nn.ReLU(), nn.Dropout(0.5), nn.Linear(256, 10))
        tb = 64
        x = torch.rand(tb, 3, 32, 32)
        y = torch.randint(0, 10, (tb,))
        ce = nn.CrossEntropyLoss()
        m(x)  # materialize lazy
        return m, lambda out: ce(out, y), torch.optim.Adam(m.parameters()), (x,), tb

    # steps=8 keeps the torch windows in the same duration ballpark as the
    # framework's 12-step windows (best-of-N comparability, see WINDOWS)
    baseline = (_torch_step_rate(torch_build, steps=2 if SMOKE else 8)
                or FALLBACK_BASELINE["cifar_cnn"])
    gate = 0.15 if SMOKE else (0.40 if prov == "real" else 0.35)
    result = dict(metric="cifar_cnn_train_examples_per_sec_per_chip"
                         + ("" if acc > gate else "_NOT_CONVERGED"),
                  value=round(eps, 1), unit="examples/sec/chip",
                  vs_baseline=round(eps / baseline, 3),
                  eval_accuracy=round(acc, 4), data=prov)
    return _attach_mfu(result, eps, _per_example_flops(f_total, batch, mesh),
                       analytic=1.53e8)


def bench_resnet50():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_tensorflow_tpu import models, optim, parallel, train

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    size = 64 if SMOKE else 224
    model = models.resnet50(num_classes=1000)
    optimizer = optim.momentum(0.1, beta=0.9)
    # mixed_bfloat16: without the policy the f32 conv kernels promote the
    # bf16 batch back to f32 and every conv runs off the bf16 MXU path —
    # the master params stay f32 (grads/update in f32)
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, mesh=mesh,
                                 policy="mixed_bfloat16")
    rng = np.random.default_rng(0)
    bsh = NamedSharding(mesh, P("data"))

    def build(batch):
        state = train.init_train_state(model, optimizer,
                                       jax.random.PRNGKey(0),
                                       (size, size, 3))
        state = jax.device_put(state, NamedSharding(mesh, P()))
        x = rng.random((batch, size, size, 3), np.float32)
        y = rng.integers(0, 1000, batch).astype(np.int32)
        return state, (jax.device_put(jnp.asarray(x, jnp.bfloat16), bsh),
                       jax.device_put(y, bsh))

    # 256/chip measured +22% over 64/chip on v5e (probe 2026-07-30); the
    # bf16 policy halves activation memory so 512 leads the ladder, which
    # descends on OOM for smaller-HBM parts.
    rate, loss, ms, batch, f_total = _run_batch_ladder(
        "resnet50", [8] if SMOKE else [512, 256, 128, 64], mesh, build, step,
        warmup=2, steps=4 if SMOKE else 10)
    eps = rate * batch / n_chips
    log(f"resnet50: {eps:,.1f} examples/s/chip ({ms*1e3:.1f} ms/step, "
        f"loss={loss:.3f})")

    def torch_build():
        import torch
        import torch.nn as nn
        try:
            from torchvision.models import resnet50 as tv_resnet50
            m = tv_resnet50()
        except Exception:
            raise RuntimeError("torchvision unavailable")
        tb = 4
        x = torch.rand(tb, 3, size, size)
        y = torch.randint(0, 1000, (tb,))
        ce = nn.CrossEntropyLoss()
        return m, lambda out: ce(out, y), \
            torch.optim.SGD(m.parameters(), 0.1, momentum=0.9), (x,), tb

    baseline = _torch_step_rate(torch_build) or FALLBACK_BASELINE["resnet50"]
    finite = np.isfinite(loss)
    result = dict(metric="resnet50_train_examples_per_sec_per_chip"
                         + ("" if finite else "_NONFINITE_LOSS"),
                  value=round(eps, 2), unit="examples/sec/chip",
                  vs_baseline=round(eps / baseline, 3),
                  image_size=size, batch=batch)
    return _attach_mfu(result, eps, _per_example_flops(f_total, batch, mesh),
                       analytic=12.3e9 * (size / 224) ** 2)


def bench_bert():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_tensorflow_tpu import optim, train, parallel
    from distributed_tensorflow_tpu.models.bert import Bert, BertConfig

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    seq = int(os.environ.get("DTTPU_BENCH_BERT_SEQ", "128"))
    # DTTPU_BENCH_MLM_GATHER=1: head on masked positions only (cap 20% of
    # seq) — A/B hook until the hardware ablation decides the default
    gather = (seq // 5
              if os.environ.get("DTTPU_BENCH_MLM_GATHER") == "1" else 0)
    # DTTPU_BENCH_BERT_REMAT: "" (off) / "full" / "dots".  Evidence
    # (docs/evidence_r5/ablation_bert.jsonl — note every ablation arm
    # including "base" ran remat=True, unlike this row's no-remat
    # default): dots-vs-full is +12.4% (147,351 vs 131,123 tok/s/chip),
    # and the full lever set (dots + gather + b128, arm
    # remat_dots_gather 168,819) beats the same window's measured bench
    # row (gather, no remat, b96: 134,995) by +25% — that composite win
    # is what promote_levers' mapping buys.
    remat_policy = os.environ.get("DTTPU_BENCH_BERT_REMAT", "").strip().lower()
    if remat_policy in ("0", "off", "false", "no", "none"):
        remat_policy = ""  # natural disable spellings, not a policy name
    elif remat_policy and remat_policy not in ("full", "dots",
                                               "dots_no_batch"):
        raise SystemExit("DTTPU_BENCH_BERT_REMAT must be ''/off/full/dots/"
                         f"dots_no_batch; got {remat_policy!r}")
    remat = dict(remat=True, remat_policy=remat_policy) if remat_policy \
        else {}
    # DTTPU_BENCH_BERT_FUSED_LN=1: the fused Pallas LayerNorm.  The pure
    # arm measured +6.4% (08-01 ablation) but its composition with the
    # promoted remat_dots+gather defaults is unmeasured — promote_levers
    # deliberately has NO mapping for it until the composite arm
    # (remat_dots_gather_ln, queued) is, so this knob is for measured
    # flips only.
    fused_ln = os.environ.get("DTTPU_BENCH_BERT_FUSED_LN") == "1"
    # dropout_rate=0.0: aligns this row with the gpt/llama rows (and with
    # every mfu_ablation arm) — BertConfig's 0.1 default was the ONLY LM
    # row still paying per-layer dropout mask generation, which measured
    # 47% on 2026-08-01 (bench row 119,627 vs the same-lever ablation arm
    # 176,237 tok/s/chip, logs/followups_r5b.log).
    config = (BertConfig(vocab_size=512, hidden_size=128, num_layers=2,
                         num_heads=2, intermediate_size=512,
                         max_position=seq, dtype=jnp.bfloat16,
                         dropout_rate=0.0,
                         mlm_predictions_per_seq=gather,
                         fused_layernorm=fused_ln, **remat) if SMOKE
              else BertConfig(max_position=seq, dtype=jnp.bfloat16,
                              dropout_rate=0.0,
                              mlm_predictions_per_seq=gather,
                              fused_layernorm=fused_ln, **remat))
    model = Bert(config)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optim.adamw(1e-4)
    step = train.make_custom_train_step(model.mlm_loss_fn(), optimizer,
                                        grad_clip_norm=1.0)
    rng = np.random.default_rng(0)
    bsh = NamedSharding(mesh, P("data"))

    def build(batch):
        state = train.TrainState.create(params, optimizer.init(params))
        state = jax.device_put(state, NamedSharding(mesh, P()))
        bench_batch = jax.device_put({
            "input_ids": rng.integers(0, config.vocab_size,
                                      (batch, seq)).astype(np.int32),
            "labels": rng.integers(0, config.vocab_size,
                                   (batch, seq)).astype(np.int32),
            "mlm_mask": (rng.random((batch, seq)) < 0.15).astype(np.float32),
            "attention_mask": np.ones((batch, seq), np.int32),
        }, bsh)
        return state, bench_batch

    # 96/chip measured best on v5e without levers (probe 2026-07-30:
    # 109k tok/s/chip vs 85k at 32/chip; 128/chip OOMs without remat at
    # seq 128).  With REMAT on, the 08-01 ablation measured batch 128
    # fitting AND faster (remat_dots_gather b128 168,819 — the best
    # arm), so the ladder tries 128 first; an OOM rung falls through.
    # Gather alone does NOT unlock 128 — no arm measured b128 without
    # remat, and the 07-30 probe says it OOMs — so that case keeps the
    # 96-first ladder.
    ladder = [128, 96, 48, 24] if remat_policy else [96, 48, 24]
    rate, loss, ms, batch, f_total = _run_batch_ladder(
        "bert", [4] if SMOKE else ladder, mesh, build, step,
        warmup=2, steps=4 if SMOKE else 10)
    tokens = rate * batch * seq / n_chips
    log(f"bert: {tokens:,.0f} tokens/s/chip ({ms*1e3:.1f} ms/step, "
        f"loss={loss:.3f})")
    finite = np.isfinite(loss)
    result = dict(metric="bert_mlm_train_tokens_per_sec_per_chip"
                         + ("" if finite else "_NONFINITE_LOSS"),
                  value=round(tokens, 1), unit="tokens/sec/chip",
                  vs_baseline=1.0,  # no runnable reference-era BERT
                  # baseline exists; 1.0 = "unity ratio by definition"
                  seq_len=seq, batch=batch)
    # the gathered head skips work on non-gathered tokens; the XLA-counted
    # f_total already reflects this, the analytic fallback must too
    from distributed_tensorflow_tpu.models.bert import \
        mlm_gather_flops_correction
    analytic = (_transformer_flops_per_token(params, config.num_layers,
                                             config.hidden_size, seq)
                - mlm_gather_flops_correction(config, seq))
    if gather:
        result["mlm_predictions_per_seq"] = gather
    if remat_policy:
        result["remat_policy"] = remat_policy
    if fused_ln:
        result["fused_layernorm"] = True
    return _attach_mfu(
        result, tokens, _per_example_flops(f_total, batch * seq, mesh),
        analytic=analytic, scanned=True)


def bench_mnist_mlp():
    value_multi, acc, value_single, prov, flops = bench_framework()
    baseline = bench_torch_baseline()
    if baseline is None:
        baseline = FALLBACK_BASELINE["mnist_mlp"]
    gate = 0.95 if prov == "real" else 0.9
    converged = acc > gate
    # Headline = best dispatch mode.  Both are legitimate framework paths
    # (TrainSession drives single-step; fit(steps_per_execution=K) the
    # scanned one); on a single CPU device the scan's state-donation chain
    # is slower than plain dispatch, and reporting the multi-step number
    # unconditionally handed r03's fallback 0.92 while the same run's
    # single-step was 1.03.  This is a CONFIG selection (which dispatch
    # discipline to run), not extra noise samples — each mode's own rate
    # is already its best-of-WINDOWS, same as the torch side's.
    value = max(value_multi, value_single)
    result = {
        "metric": "mnist_mlp_train_examples_per_sec_per_chip"
                  + ("" if converged else "_NOT_CONVERGED"),
        "value": round(value, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(value / baseline, 3),
        "steps_per_call": STEPS_PER_CALL,
        "dispatch_mode": "multi" if value_multi >= value_single else "single",
        "multi_step_value": round(value_multi, 1),
        "single_step_value": round(value_single, 1),
        # r01-r03 records reported the multi-step ratio unconditionally;
        # keep emitting it so cross-round trend lines stay comparable
        "multi_step_vs_baseline": round(value_multi / baseline, 3),
        "eval_accuracy": round(acc, 4),
        "data": prov,
    }
    # flops comes from the K-step multi-dispatch scan (bench_framework)
    return _attach_mfu(result, value, flops, analytic=6.1e5, scanned=True)


def _gpt_bench_config(seq, experts=0):
    """The GPT bench model: GPT-2-small (or the SMOKE shrink), bf16.
    ONE constructor shared by the train and decode rows so their numbers
    stay measurements of the same model."""
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.models.gpt import GPTConfig

    # remat=True: the layer-scan otherwise saves every activation for
    # backward and OOMs a 16G chip at batch 48/seq 256; rematerialising
    # measured FASTER at equal batch too (scripts/tune_gpt_batch.py,
    # 2026-07-31: 120k tok/s at remat batch 48 vs 101-108k no-remat 24)
    moe = dict(moe_experts=experts, moe_top_k=2) if experts else {}
    # DTTPU_BENCH_LOSS_CHUNK > 0: chunked LM loss (the [tokens, vocab]
    # logits never materialise); DTTPU_BENCH_REMAT_POLICY: what the
    # per-layer checkpoint saves — A/B hooks until the hardware ablation
    # (scripts/mfu_ablation.py) decides the defaults
    chunk = int(os.environ.get("DTTPU_BENCH_LOSS_CHUNK", "0"))
    rpol = os.environ.get("DTTPU_BENCH_REMAT_POLICY", "full")
    return (GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                      num_heads=2, intermediate_size=512,
                      max_position=seq, dtype=jnp.bfloat16,
                      dropout_rate=0.0, remat=True, remat_policy=rpol,
                      loss_seq_chunk=chunk, **moe) if SMOKE
            else GPTConfig(vocab_size=50257, hidden_size=768,
                           num_layers=12, num_heads=12,
                           intermediate_size=3072, max_position=seq,
                           dtype=jnp.bfloat16, dropout_rate=0.0,
                           remat=True, remat_policy=rpol,
                           loss_seq_chunk=chunk, **moe))


def bench_gpt(seq=None, experts=None):
    """Causal-LM training throughput (tokens/s/chip) on a GPT-2-small-
    shaped decoder, bf16, adamw — the LM-family row next to BERT's MLM.
    Explicit ``seq``/``experts`` arguments WIN over the env vars (the
    moe/long rows pass them to define their row; an exported
    DTTPU_BENCH_SEQ must not silently retarget a named row) — the env
    vars only fill in when the caller passes None."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_tensorflow_tpu import optim, train, parallel
    from distributed_tensorflow_tpu.models.gpt import GPT

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    seq = (int(seq) if seq is not None
           else int(os.environ.get("DTTPU_BENCH_SEQ", 256)))
    experts = (int(experts) if experts is not None
               else int(os.environ.get("DTTPU_BENCH_GPT_MOE", 0)))
    config = _gpt_bench_config(seq, experts)
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optim.adamw(1e-4)
    step = train.make_custom_train_step(model.lm_loss_fn(), optimizer,
                                        grad_clip_norm=1.0)
    rng = np.random.default_rng(0)
    bsh = NamedSharding(mesh, P("data"))

    def build(batch):
        state = train.TrainState.create(params, optimizer.init(params))
        state = jax.device_put(state, NamedSharding(mesh, P()))
        tokens = rng.integers(0, config.vocab_size,
                              (batch, seq + 1)).astype(np.int32)
        # lm_loss_fn shifts internally: inputs ids[:, :-1], targets [:, 1:]
        bench_batch = jax.device_put({"input_ids": tokens}, bsh)
        return state, bench_batch

    ladder = ([4] if SMOKE else
              [max(1, 48 * 256 // seq), max(1, 24 * 256 // seq),
               max(1, 12 * 256 // seq)])
    if config.loss_seq_chunk and not SMOKE:
        # chunked LM loss removes the [tokens, vocab] logits wall (~2.5GB f32
        # at seq 2048 batch 6) — the explicit A/B lever earns a 2x rung
        # the plain ladder can't attempt
        ladder = [max(1, 96 * 256 // seq)] + ladder
    rate, loss, ms, batch, f_total = _run_batch_ladder(
        "gpt", ladder, mesh, build, step,
        warmup=2, steps=4 if SMOKE else 10)
    tokens_s = rate * batch * seq / n_chips
    log(f"gpt: {tokens_s:,.0f} tokens/s/chip ({ms*1e3:.1f} ms/step, "
        f"loss={loss:.3f})")
    finite = np.isfinite(loss)
    result = dict(metric="gpt_lm_train_tokens_per_sec_per_chip"
                         + ("" if finite else "_NONFINITE_LOSS"),
                  value=round(tokens_s, 1), unit="tokens/sec/chip",
                  vs_baseline=1.0,  # no reference-era GPT baseline exists
                  seq_len=seq, batch=batch)
    if config.loss_seq_chunk:
        result["loss_seq_chunk"] = config.loss_seq_chunk
    if config.remat_policy != "full":
        result["remat_policy"] = config.remat_policy
    analytic = _transformer_flops_per_token(params, config.num_layers,
                                            config.hidden_size, seq)
    if experts:
        # 6N counts every expert's FFN weights, but each token routes
        # through only top_k of them — discount the inactive experts'
        # matmul flops or the MoE row's mfu overstates by ~experts/top_k
        # on the FFN share
        from jax.tree_util import tree_flatten_with_path
        n_exp = sum(int(v.size) for p, v in tree_flatten_with_path(params)[0]
                    if any("expert" in str(k).lower() for k in p))
        analytic -= 6.0 * n_exp * max(0.0, 1.0 - config.moe_top_k / experts)
    result = _attach_mfu(
        result, tokens_s, _per_example_flops(f_total, batch * seq, mesh),
        analytic=analytic, scanned=True)
    # graph-tier static cross-check: trace the SAME step abstractly and
    # attach the cost model's flops/bytes + the roofline MFU ceiling
    state_a = jax.eval_shape(
        lambda p: train.TrainState.create(p, optimizer.init(p)), params)
    batch_a = {"input_ids": jax.ShapeDtypeStruct((batch, seq + 1),
                                                 jnp.int32)}
    return _attach_analytical(
        result, step, (state_a, batch_a), tokens_per_step=batch * seq,
        in_specs=(P(), {"input_ids": P("data")}), mesh=mesh)



def bench_llama():
    """Llama-recipe causal-LM training throughput (tokens/s/chip): the
    same harness as bench_gpt on the rmsnorm/swiglu/rope/GQA decoder
    (models/llama.py) — the modern-LM row of the matrix."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_tensorflow_tpu import optim, train, parallel
    from distributed_tensorflow_tpu.models.gpt import GPT
    from distributed_tensorflow_tpu.models.llama import llama_config

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    seq = int(os.environ.get("DTTPU_BENCH_SEQ", "256"))
    # ~160M-param body (GPT-2-small-ish dims + GQA 12q/4kv) so the row is
    # comparable to the gpt row while fitting the v5e ladder comfortably
    # remat=True for the same reason as _gpt_bench_config: bigger ladder
    # rungs fit and the rematerialised step measured faster at equal batch
    chunk = int(os.environ.get("DTTPU_BENCH_LOSS_CHUNK", "0"))
    rpol = os.environ.get("DTTPU_BENCH_REMAT_POLICY", "full")
    # DTTPU_BENCH_LLAMA_FUSED_LN=1: the fused rmsnorm kernel — measured
    # flips only (no promote mapping until the llama fused_ln arm lands)
    fused_ln = os.environ.get("DTTPU_BENCH_LLAMA_FUSED_LN") == "1"
    config = (llama_config(vocab_size=512, hidden_size=128, num_layers=2,
                           num_heads=4, num_kv_heads=2,
                           intermediate_size=384, max_position=seq,
                           dtype=jnp.bfloat16, remat=True,
                           remat_policy=rpol, fused_layernorm=fused_ln,
                           loss_seq_chunk=chunk) if SMOKE
              else llama_config(vocab_size=32000, hidden_size=768,
                                num_layers=12, num_heads=12,
                                num_kv_heads=4, intermediate_size=2048,
                                max_position=seq, dtype=jnp.bfloat16,
                                remat=True, remat_policy=rpol,
                                fused_layernorm=fused_ln,
                                loss_seq_chunk=chunk))
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optim.adamw(1e-4)
    step = train.make_custom_train_step(model.lm_loss_fn(), optimizer,
                                        grad_clip_norm=1.0)
    rng = np.random.default_rng(0)
    bsh = NamedSharding(mesh, P("data"))

    def build(batch):
        state = train.TrainState.create(params, optimizer.init(params))
        state = jax.device_put(state, NamedSharding(mesh, P()))
        tokens = rng.integers(0, config.vocab_size,
                              (batch, seq + 1)).astype(np.int32)
        bench_batch = jax.device_put({"input_ids": tokens}, bsh)
        return state, bench_batch

    ladder = ([4] if SMOKE else
              [max(1, 48 * 256 // seq), max(1, 24 * 256 // seq),
               max(1, 12 * 256 // seq)])
    rate, loss, ms, batch, f_total = _run_batch_ladder(
        "llama", ladder, mesh, build, step,
        warmup=2, steps=4 if SMOKE else 10)
    tokens_s = rate * batch * seq / n_chips
    log(f"llama: {tokens_s:,.0f} tokens/s/chip ({ms*1e3:.1f} ms/step, "
        f"loss={loss:.3f})")
    finite = np.isfinite(loss)
    result = dict(metric="llama_lm_train_tokens_per_sec_per_chip"
                         + ("" if finite else "_NONFINITE_LOSS"),
                  value=round(tokens_s, 1), unit="tokens/sec/chip",
                  vs_baseline=1.0,  # no reference-era Llama baseline exists
                  seq_len=seq, batch=batch)
    if config.loss_seq_chunk:
        result["loss_seq_chunk"] = config.loss_seq_chunk
    if config.remat_policy != "full":
        result["remat_policy"] = config.remat_policy
    if fused_ln:
        result["fused_layernorm"] = True
    return _attach_mfu(
        result, tokens_s, _per_example_flops(f_total, batch * seq, mesh),
        analytic=_transformer_flops_per_token(params, config.num_layers,
                                              config.hidden_size, seq),
        scanned=True)



def _decode_eval_weights(model, config, train_steps=150):
    """Trained-or-random weights for the decode rows' HONESTY metrics.

    Random-init logits are near-uniform, so greedy argmax sits on
    rounding-order ties: ANY two numerically-equivalent decode paths
    (bf16 vs f32, fp vs int8, spec vs plain) diverge at the first tie
    and the per-token agreement compounds toward chance — measured
    2026-08-01 on the v5e: int8-vs-fp greedy match 0.58 at random init,
    pure tie noise, says nothing about quantization fidelity.  Training
    ~150 steps on a learnable order-1 Markov corpus (next = (tok * 31
    + 7) % active with p=0.9, uniform otherwise — a 512-entry lookup a
    decoder learns in seconds) gives the logits real margins so the
    agreement metrics measure the decode paths, not the init.
    Disabled (random init, steps=0) via DTTPU_BENCH_DECODE_TRAIN=0.

    Returns (params, train_steps_run, corpus_sampler) where
    corpus_sampler(rng, batch, length) draws in-distribution prompts."""
    import jax
    import numpy as np

    active = min(512, config.vocab_size)

    def sample(rng, batch, length):
        toks = np.empty((batch, length), np.int64)
        toks[:, 0] = rng.integers(0, active, batch)
        for t in range(1, length):
            follow = rng.random(batch) < 0.9
            toks[:, t] = np.where(follow, (toks[:, t - 1] * 31 + 7) % active,
                                  rng.integers(0, active, batch))
        return toks.astype(np.int32)

    params = model.init(jax.random.PRNGKey(0))
    if os.environ.get("DTTPU_BENCH_DECODE_TRAIN", "1") == "0":
        return params, 0, sample
    # 30 smoke steps: enough for the toy model to learn the chain so the
    # match metrics have margins (2 steps measured match 0.77 at seq 64
    # — still in the tie-noise regime the training exists to leave)
    steps = 30 if SMOKE else train_steps
    params = _train_lm(model, params, steps, sample,
                       min(128, config.max_position), seed=7)
    return params, steps, sample


def _train_lm(model, init_params, steps, sample, seq_train, seed):
    """ONE bench-training harness for the decode rows' pre-train AND the
    spec row's draft distillation (same recipe by construction).

    CAUTION: the train step DONATES its input state — ``init_params``
    buffers are consumed; callers whose tree shares buffers with a tree
    they still need must deep-copy first.  Returns the DEVICE-resident
    trained params: a device_get would make every later generate()
    re-ship ~250MB of weights through the tunnel per call (measured
    2026-08-01: fp decode 991 tok/s from a host tree vs 23.6k
    device-resident)."""
    import jax
    import numpy as np
    from distributed_tensorflow_tpu import optim, train

    optimizer = optim.adamw(3e-4)
    step = train.make_custom_train_step(model.lm_loss_fn(), optimizer,
                                        grad_clip_norm=1.0)
    state = train.TrainState.create(init_params,
                                    optimizer.init(init_params))
    rng = np.random.default_rng(seed)
    if steps <= 0:
        return init_params
    for _ in range(steps):
        batch = {"input_ids": jax.device_put(sample(rng, 32, seq_train + 1))}
        state, metrics = step(state, batch)
    _fetch(metrics)
    return state.params


def bench_gpt_decode():
    """Serving-side decode throughput (tokens/s/chip): greedy KV-cache
    generation on the GPT-2-small decoder, bf16.  The timed window is one
    full ``generate`` dispatch — its ``lax.scan`` teacher-forces the
    ``prompt_len - 1`` prompt positions in the same loop as the new-token
    steps, so the short 8-token prompt biases ms/token by under 3% — and
    closes with a value fetch of the emitted tokens (docs/PERF.md
    methodology).  Generation is placed on ONE device (no mesh), so the
    per-chip figure is the measured throughput undivided."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu.models.gpt import GPT

    seq = int(os.environ.get("DTTPU_BENCH_SEQ", "256"))
    config = _gpt_bench_config(seq)
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(0))
    batch = 4 if SMOKE else 64
    prompt_len = 8
    new_tokens = 16 if SMOKE else seq - prompt_len
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, config.vocab_size,
                          (batch, prompt_len)).astype(np.int32)

    gen = jax.jit(lambda p, ids: model.generate(
        p, ids, max_new_tokens=new_tokens, temperature=0.0, max_len=seq))
    np.asarray(gen(params, prompt))              # compile + warmup
    dt = None
    for _ in range(WINDOWS):                     # best-of, like every row
        t0 = time.perf_counter()
        out = gen(params, prompt)
        np.asarray(out)                          # value fetch closes window
        w = time.perf_counter() - t0
        dt = w if dt is None else min(dt, w)
    tokens_s = batch * new_tokens / dt          # single-device: per chip
    log(f"gpt_decode: {tokens_s:,.0f} tokens/s/chip "
        f"({dt * 1e3 / new_tokens:.2f} ms/token at batch {batch})")
    return dict(metric="gpt_decode_tokens_per_sec_per_chip",
                value=round(tokens_s, 1), unit="tokens/sec/chip",
                vs_baseline=1.0,  # no reference-era decode baseline exists
                batch=batch, new_tokens=new_tokens, seq_len=seq)


def bench_gpt_decode_int8():
    """Weight-only int8 decode (ops.quant): the int8 tree is the jitted
    ``generate``'s argument and ``dequantize_tree`` runs INSIDE the jit,
    so weights stay int8 in HBM (4x smaller reads — decode is
    bandwidth-bound) and the scale multiply fuses into the matmul
    prologue.  Also measures the FULL-int8 serving point (int8 weights
    + ``kv_cache_dtype="int8"`` — halved cache traffic on top of the
    weight reads).  Reports all three rates from the same run and the
    greedy-token agreement of each quantized path vs fp — the honesty
    signal that rounding didn't change the decoded text."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu.models.gpt import GPT
    from distributed_tensorflow_tpu.ops import quant

    seq = int(os.environ.get("DTTPU_BENCH_SEQ", "256"))
    config = _gpt_bench_config(seq)
    model = GPT(config)
    model_kv8 = GPT(dataclasses.replace(config, kv_cache_dtype="int8"))
    # trained weights + in-distribution prompts: the agreement metrics
    # measure quantization fidelity, not random-init argmax-tie noise
    # (see _decode_eval_weights) — rates are weight-value-independent
    params, trained_steps, sample = _decode_eval_weights(model, config)
    qparams = quant.quantize_tree(params)
    batch = 4 if SMOKE else 64
    prompt_len = 8
    new_tokens = 16 if SMOKE else seq - prompt_len
    rng = np.random.default_rng(0)
    prompt = sample(rng, batch, prompt_len)

    gen_fp = jax.jit(lambda p, ids: model.generate(
        p, ids, max_new_tokens=new_tokens, temperature=0.0, max_len=seq))
    gen_q = jax.jit(lambda qp, ids: model.generate(
        quant.dequantize_tree(qp), ids, max_new_tokens=new_tokens,
        temperature=0.0, max_len=seq))
    gen_q_kv8 = jax.jit(lambda qp, ids: model_kv8.generate(
        quant.dequantize_tree(qp), ids, max_new_tokens=new_tokens,
        temperature=0.0, max_len=seq))

    def timed(fn, args):
        np.asarray(fn(*args))                    # compile + warmup
        t0 = time.perf_counter()
        out = fn(*args)
        toks = np.asarray(out)                   # value fetch closes window
        return batch * new_tokens / (time.perf_counter() - t0), toks

    fp_rate, fp_toks = timed(gen_fp, (params, prompt))
    q_rate, q_toks = timed(gen_q, (qparams, prompt))
    kv8_rate, kv8_toks = timed(gen_q_kv8, (qparams, prompt))
    match = float(np.mean(fp_toks[:, prompt_len:] == q_toks[:, prompt_len:]))
    kv8_match = float(np.mean(fp_toks[:, prompt_len:]
                              == kv8_toks[:, prompt_len:]))
    # tie-noise floor: the same fp weights decoded in float32 — the
    # bf16-vs-f32 disagreement is pure rounding-order tie noise, so an
    # int8 match at/above this floor means quantization changed nothing
    # the dtype itself doesn't (one un-timed decode; compile-only cost)
    model_f32 = GPT(dataclasses.replace(config, dtype=jnp.float32))
    params_f32 = jax.tree.map(
        lambda a: a.astype(jnp.float32)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
    f32_toks = np.asarray(jax.jit(lambda p, ids: model_f32.generate(
        p, ids, max_new_tokens=new_tokens, temperature=0.0,
        max_len=seq))(params_f32, prompt))
    floor = float(np.mean(fp_toks[:, prompt_len:]
                          == f32_toks[:, prompt_len:]))
    log(f"gpt_decode_int8: {q_rate:,.0f} tokens/s/chip vs fp "
        f"{fp_rate:,.0f} ({q_rate / fp_rate:.2f}x), greedy match "
        f"{match:.3f} (bf16-vs-f32 floor {floor:.3f}); +kv8 "
        f"{kv8_rate:,.0f} ({kv8_rate / fp_rate:.2f}x, match {kv8_match:.3f})")
    return dict(metric="gpt_decode_int8_tokens_per_sec_per_chip",
                value=round(q_rate, 1), unit="tokens/sec/chip",
                vs_baseline=round(q_rate / fp_rate, 3),  # fp path, same run
                fp_value=round(fp_rate, 1), greedy_token_match=round(match, 4),
                tie_noise_floor_match=round(floor, 4),
                full_int8_value=round(kv8_rate, 1),
                full_int8_greedy_match=round(kv8_match, 4),
                trained_steps=trained_steps,
                batch=batch, new_tokens=new_tokens, seq_len=seq)


def bench_gpt_decode_spec():
    """Speculative greedy decode (models/speculative.py): the GPT-2-small
    target verifies proposals from a 2-layer draft built by TRUNCATING
    the target's own stacked decoder params (shared embeddings/head —
    the cheapest self-distilled draft) and briefly fine-tuned on the
    target's training corpus (see _decode_eval_weights).  Reports spec
    and plain rates from the same run, the acceptance fraction, and the
    greedy-match honesty signal: the two paths agree by construction
    except where two vocab entries argmax-tie closer than the ~1e-4
    window-vs-step reduction difference (the same tie-noise class the
    int8 row's floor calibrates) — on TRAINED weights the margins are
    real, so a match well below 1.0 means a decode-stack bug.  Batch 1:
    speculative decoding is the latency play."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu.models.gpt import GPT
    from distributed_tensorflow_tpu.models.speculative import \
        generate_speculative

    seq = int(os.environ.get("DTTPU_BENCH_SEQ", "256"))
    config = _gpt_bench_config(seq)
    model = GPT(config)
    # speculative speedup = f(draft/target agreement), and two RANDOM-init
    # models cannot agree (measured 2026-08-01: acceptance 0.022, spec
    # 0.80x — the machinery pays its overhead and wins nothing).  Train
    # the target on the learnable Markov corpus, then distill the
    # truncated draft on the same corpus, so the row measures the
    # hardware speedup at a REALISTIC acceptance (the deployment regime:
    # drafts are distilled from their targets precisely so they agree).
    params, trained_steps, sample = _decode_eval_weights(model, config)
    draft_layers = min(2, config.num_layers)
    draft_model = GPT(dataclasses.replace(config,
                                          num_layers=draft_layers))
    # the stacked decoder tree slices by layer; everything else is shared
    draft_params = dict(params)
    draft_params["decoder"] = jax.tree.map(lambda a: a[:draft_layers],
                                           params["decoder"])
    if trained_steps:
        # deep-copy: _train_lm's step DONATES its input state, and the
        # truncated draft tree shares the target's embedding/head
        # buffers — donating those would delete the target's params
        draft_init = jax.tree.map(lambda a: jnp.array(a, copy=True),
                                  draft_params)
        draft_params = _train_lm(draft_model, draft_init,
                                 2 if SMOKE else 100, sample,
                                 min(128, seq), seed=11)
    prompt_len = 8
    # DTTPU_BENCH_SPEC_GAMMA: proposals per verify step — the speedup
    # curve's x-axis (more proposals amortise the target pass further
    # but waste more draft work per rejection); 4 is the bench default
    gamma = int(os.environ.get("DTTPU_BENCH_SPEC_GAMMA", "4"))
    # the learned position table has seq rows; speculative windows embed
    # positions up to total + gamma - 2, so leave gamma - 1 headroom
    new_tokens = 16 if SMOKE else seq - prompt_len - gamma + 1
    rng = np.random.default_rng(0)
    prompt = sample(rng, 1, prompt_len)

    gen_plain = jax.jit(lambda p, ids: model.generate(
        p, ids, max_new_tokens=new_tokens, temperature=0.0,
        max_len=seq))
    gen_spec = jax.jit(lambda tp, dp, ids: generate_speculative(
        model, tp, draft_model, dp, ids, max_new_tokens=new_tokens,
        gamma=gamma))

    def timed(fn, args):
        out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0])      # compile + warmup
        dt = None
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(jax.tree.leaves(out)[0])  # value fetch
            w = time.perf_counter() - t0
            dt = w if dt is None else min(dt, w)
        return new_tokens / dt, out

    plain_rate, plain_out = timed(gen_plain, (params, prompt))
    spec_rate, (spec_out, acc) = timed(gen_spec,
                                       (params, draft_params, prompt))
    match = float(np.mean(np.asarray(plain_out)[:, prompt_len:]
                          == np.asarray(spec_out)[:, prompt_len:]))
    log(f"gpt_decode_spec: {spec_rate:,.0f} tok/s vs plain "
        f"{plain_rate:,.0f} ({spec_rate / plain_rate:.2f}x), acceptance "
        f"{float(acc):.3f}, greedy match {match:.3f}")
    return dict(metric="gpt_decode_spec_tokens_per_sec",
                value=round(spec_rate, 1), unit="tokens/sec",
                vs_baseline=round(spec_rate / plain_rate, 3),  # plain, same run
                plain_value=round(plain_rate, 1),
                acceptance=round(float(acc), 4),
                greedy_token_match=round(match, 4),
                gamma=gamma, draft_layers=draft_layers, batch=1,
                trained_steps=trained_steps,
                new_tokens=new_tokens, seq_len=seq)


def bench_gpt_serve():
    """Continuous-batching serving engine (serve/) vs lock-step batching,
    measured in the SAME process on the same model and the same seeded
    mixed-length arrival trace.  The engine path replays the trace
    through ``serve.Engine`` — slot-scheduled KV cache, chunked prefill,
    retrace-free admission — and reports aggregate tokens/s plus TTFT
    p50/p95 under load; the lock-step comparator groups the same
    requests into ``generate()`` batches in arrival order (LEFT-padded
    ragged prompts, each batch decoding until its longest member's
    budget), which is the fixed-batch serving discipline the engine
    replaces.  ``vs_lockstep`` > 1.0 is the acceptance bar: short
    requests no longer pay for long batchmates.  Single device (no
    mesh), like the other decode rows; wall clocks close with host
    value fetches on both sides.

    BOTH storage layouts replay the mixed trace: ``vs_lockstep`` stays
    the CONTIGUOUS stripe engine's ratio (the PR 4 comparator, so the
    metric is comparable across rounds) and ``vs_lockstep_paged`` is
    the default paged engine's — on this CPU smoke the XLA-emulated
    page gather costs fusion in the tiny-model tick, which is exactly
    what the side-by-side number makes visible (docs/SERVING.md).

    Two more measured phases (serve/pages.py):

    * ``shared_prefix``: a seeded arrival trace where requests share
      one of a few SYSTEM PROMPTS (plus the mixed trace's per-group
      long-tail stragglers).  Replayed on the paged engine with the
      radix prefix cache ON and OFF (``prefix_cache=False`` — same
      engine, same paging, reuse ablated): ``vs_no_reuse`` is the
      cache's own win, ``prefix_hit_rate``/``prefill_windows_skipped``
      the mechanism, and the TTFT p50 delta the latency effect.
    * ``slots_at_fixed_mem``: with the page pool capped at the
      contiguous layout's HBM budget (``slots`` full stripes), a burst
      of short requests shows how many slots the paged engine actually
      runs CONCURRENTLY — strictly more than the stripe layout's
      ``slots``, because pages are allocated per actual footprint.
    """
    import jax
    import numpy as np
    from distributed_tensorflow_tpu import serve
    from distributed_tensorflow_tpu.models.gpt import GPT
    from distributed_tensorflow_tpu.obs import reqtrace

    seq = int(os.environ.get("DTTPU_BENCH_SEQ", "256"))
    config = _gpt_bench_config(seq)
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(0))
    slots = int(os.environ.get('DTTPU_BENCH_SERVE_SLOTS',
                               6 if SMOKE else 16))
    chunk = 16 if SMOKE else 32
    tick_steps = int(os.environ.get("DTTPU_BENCH_SERVE_TICK",
                                    "6" if SMOKE else "8"))
    n_req = 30 if SMOKE else 96   # a multiple of slots: full groups/batches
    rng = np.random.default_rng(0)

    # Mixed-length trace: mostly short answers with a heavy tail of long
    # ones — the regime where a lock-step batch stalls on its longest
    # member.  Arrival order is uncorrelated with length, so the longs
    # land spread out (one seeded position per group of ``slots``
    # consecutive arrivals — the expected interleaving, which is also
    # the lock-step WORST case only in the sense that nearly every
    # fixed batch inherits one straggler).  Budgets clamp so both
    # servers fit max_len = seq.
    plens = rng.integers(3, 2 * chunk + 1, n_req)
    p_max = int(plens.max())
    long_req = np.zeros(n_req, bool)
    for lo in range(0, n_req, slots):
        long_req[lo + int(rng.integers(0, min(slots, n_req - lo)))] = True
    # long budgets come from THREE discrete tiers (not a continuum) so
    # the lock-step comparator compiles at most three per-batch budget
    # values — its per-budget traces are legitimate, but they must stay
    # inside the bench retrace budget so the JSON's retrace_warnings
    # remains a clean signal for the ENGINE's no-recompile contract
    long_tiers = np.array([seq // 3, (5 * seq) // 12, seq // 2])
    budgets = np.where(long_req,
                       rng.choice(long_tiers, n_req),
                       rng.integers(2, 9, n_req))
    cap = seq - max(p_max, 2 * chunk) - 1
    budgets = np.clip(budgets, 1, cap).astype(int)
    prompts = [rng.integers(0, config.vocab_size, p).astype(np.int32)
               for p in plens]
    # short arrival stagger (in ticks): the queue builds while the
    # first admissions are still prefilling, as live traffic would
    arrivals = np.sort(rng.integers(0, slots + 1, n_req))
    # seeded tenant ids ride the trace (drawn AFTER the arrays above so
    # the prompts/budgets/arrivals stay byte-identical to earlier
    # rounds); this engine enforces no tenancy policy — the ids feed
    # the per-tenant serve metrics and keep the trace shared with
    # --config=fleet, which does enforce fair-share
    tenants = rng.choice(["free", "pro", "batch"], n_req)

    def make_engine(**kw):
        """Engine + warmup covering the mid+last prefill windows, the
        admit splice/arm, and the tick (a cold engine would otherwise
        compile inside the measured window)."""
        eng = serve.Engine(model, params, num_slots=kw.pop("num_slots",
                                                           slots),
                           max_len=seq, prefill_chunk=chunk,
                           tick_steps=tick_steps, **kw)
        eng.submit(rng.integers(0, config.vocab_size,
                                chunk + 2).astype(np.int32), 4)
        eng.submit(prompts[0], 2)
        eng.drain()
        return eng

    def replay_engine(eng, trace_prompts, trace_budgets, trace_arrivals,
                      trace_tenants=None):
        handles = []
        i = tick = 0
        n = len(trace_prompts)
        t0 = time.perf_counter()
        while i < n or eng.busy:
            while i < n and trace_arrivals[i] <= tick:
                handles.append(eng.submit(
                    trace_prompts[i], int(trace_budgets[i]),
                    tenant=("default" if trace_tenants is None
                            else str(trace_tenants[i]))))
                i += 1
            eng.step()
            tick += 1
        # the final tick fetched its tokens: the wall is barrier-closed
        wall = time.perf_counter() - t0
        return wall, handles

    def ttft_pcts(handles):
        ttfts = sorted(h.ttft_s for h in handles)
        return (ttfts[int(0.50 * (len(ttfts) - 1))],
                ttfts[int(0.95 * (len(ttfts) - 1))])

    # best of 2 windows on BOTH sides (the WINDOWS rationale: a
    # background spike landing in one side's single window flips the
    # ratio); TTFTs are reported from the best engine window
    eng = make_engine()                          # paged (the default)
    wall_engine, handles = min(
        (replay_engine(eng, prompts, budgets, arrivals, tenants)
         for _ in range(2)), key=lambda r: r[0])
    total_tokens = sum(len(h.tokens) for h in handles)
    engine_tps = total_tokens / wall_engine
    ttft_p50, ttft_p95 = ttft_pcts(handles)
    page_size = eng.scheduler.page_size

    eng_c = make_engine(paged=False)             # the PR 4 comparator
    wall_contig, handles_c = min(
        (replay_engine(eng_c, prompts, budgets, arrivals, tenants)
         for _ in range(2)), key=lambda r: r[0])
    contig_tps = sum(len(h.tokens) for h in handles_c) / wall_contig

    # Kernel read path: the SAME paged layout read through the fused
    # Pallas page-walk kernel instead of the XLA gather.  Off-TPU the
    # kernel runs in interpret mode, so the CPU smoke exercises the
    # real kernel body but the ratio only certifies a win on TPU
    # (scripts/validate_paged_tpu.py owns the Mosaic-compiled numbers).
    eng_k = make_engine(use_paged_kernel=True)
    wall_kernel, handles_k = min(
        (replay_engine(eng_k, prompts, budgets, arrivals, tenants)
         for _ in range(2)), key=lambda r: r[0])
    kernel_tps = sum(len(h.tokens) for h in handles_k) / wall_kernel

    # Lock-step comparator: same requests, batches of `slots` in arrival
    # order, LEFT-padded to the global max prompt, each batch running its
    # longest member's budget.  Useful tokens = each request's own
    # budget (the surplus a short request decodes past its budget is
    # lock-step waste, not throughput).  One jitted generate with the
    # budget static: <= one trace per batch, under the retrace budget.
    gen_j = jax.jit(
        lambda p, ids, valid, mn: model.generate(
            p, ids, max_new_tokens=mn, temperature=0.0, max_len=seq,
            prompt_valid=valid),
        static_argnums=(3,))
    batch_args = []
    for lo in range(0, n_req, slots):
        idx = range(lo, min(lo + slots, n_req))
        ids = np.zeros((slots, p_max), np.int32)
        valid = np.zeros((slots, p_max), np.int32)
        for r, j in enumerate(idx):
            ids[r, p_max - plens[j]:] = prompts[j]
            valid[r, p_max - plens[j]:] = 1
        batch_args.append((ids, valid,
                           int(budgets[list(idx)].max())))
    for ids, valid, mn in batch_args:        # compile warmup per budget
        np.asarray(gen_j(params, ids, valid, mn))
    wall_lock = None
    for _ in range(2):                       # best of 2, same as engine
        t0 = time.perf_counter()
        for ids, valid, mn in batch_args:
            np.asarray(gen_j(params, ids, valid, mn))  # fetch closes
        w = time.perf_counter() - t0
        wall_lock = w if wall_lock is None else min(wall_lock, w)
    lock_tps = float(budgets.sum()) / wall_lock

    ratio_contig = contig_tps / lock_tps
    ratio_paged = engine_tps / lock_tps
    ratio_kernel = kernel_tps / lock_tps
    kernel_vs_gather = kernel_tps / engine_tps
    log(f"gpt_serve: paged {engine_tps:,.0f} tok/s, contiguous "
        f"{contig_tps:,.0f}, kernel {kernel_tps:,.0f}, lockstep "
        f"{lock_tps:,.0f} "
        f"(contiguous {ratio_contig:.2f}x / paged {ratio_paged:.2f}x / "
        f"kernel {ratio_kernel:.2f}x, kernel vs gather "
        f"{kernel_vs_gather:.2f}x), "
        f"ttft p50 {ttft_p50*1e3:.1f} ms / p95 {ttft_p95*1e3:.1f} ms "
        f"over {n_req} requests")

    # ---- shared-prefix trace: the radix cache's own measured win ----
    # Same long-tail discipline as the mixed trace, but every prompt is
    # one of a few SYSTEM PROMPTS (3 pages each) plus a short unique
    # tail — the multi-user serving shape prefix reuse exists for.
    rng2 = np.random.default_rng(7)
    n_sp = 24 if SMOKE else 48
    sys_len = 3 * page_size
    sys_prompts = [rng2.integers(0, config.vocab_size,
                                 sys_len).astype(np.int32)
                   for _ in range(3)]
    which = rng2.integers(0, 3, n_sp)
    sp_prompts = [np.concatenate([
        sys_prompts[w],
        rng2.integers(0, config.vocab_size,
                      int(rng2.integers(4, 13))).astype(np.int32)])
        for w in which]
    sp_long = np.zeros(n_sp, bool)
    for lo in range(0, n_sp, slots):
        sp_long[lo + int(rng2.integers(0, min(slots, n_sp - lo)))] = True
    sp_budgets = np.where(sp_long, rng2.choice(long_tiers, n_sp),
                          rng2.integers(2, 9, n_sp))
    sp_max = max(p.size for p in sp_prompts)
    sp_budgets = np.clip(sp_budgets, 1, seq - sp_max - 1).astype(int)
    sp_arrivals = np.sort(rng2.integers(0, slots + 1, n_sp))

    sp_results = {}
    for label, reuse in (("reuse", True), ("no_reuse", False)):
        eng_sp = make_engine(prefix_cache=reuse)
        wall, hs = min(
            (replay_engine(eng_sp, sp_prompts, sp_budgets, sp_arrivals)
             for _ in range(2)), key=lambda r: r[0])
        p50, p95 = ttft_pcts(hs)
        sp_results[label] = dict(
            tps=sum(len(h.tokens) for h in hs) / wall,
            p50=p50, p95=p95, stats=eng_sp.stats())

    # the kernel read path over the SAME shared-prefix trace (radix
    # reuse on): prefix hits land pages the kernel then walks
    eng_spk = make_engine(prefix_cache=True, use_paged_kernel=True)
    wall_spk, hs_spk = min(
        (replay_engine(eng_spk, sp_prompts, sp_budgets, sp_arrivals)
         for _ in range(2)), key=lambda r: r[0])
    sp_kernel_tps = sum(len(h.tokens) for h in hs_spk) / wall_spk

    sp_args = []
    for lo in range(0, n_sp, slots):
        idx = range(lo, min(lo + slots, n_sp))
        ids = np.zeros((slots, sp_max), np.int32)
        valid = np.zeros((slots, sp_max), np.int32)
        for r, j in enumerate(idx):
            ids[r, sp_max - sp_prompts[j].size:] = sp_prompts[j]
            valid[r, sp_max - sp_prompts[j].size:] = 1
        sp_args.append((ids, valid, int(sp_budgets[list(idx)].max())))
    for ids, valid, mn in sp_args:
        np.asarray(gen_j(params, ids, valid, mn))
    sp_lock = None
    for _ in range(2):
        t0 = time.perf_counter()
        for ids, valid, mn in sp_args:
            np.asarray(gen_j(params, ids, valid, mn))
        w = time.perf_counter() - t0
        sp_lock = w if sp_lock is None else min(sp_lock, w)
    sp_lock_tps = float(sp_budgets.sum()) / sp_lock

    st = sp_results["reuse"]["stats"]
    shared_prefix = dict(
        requests=n_sp,
        tokens_per_sec=round(sp_results["reuse"]["tps"], 1),
        no_reuse_tokens_per_sec=round(sp_results["no_reuse"]["tps"], 1),
        vs_no_reuse=round(sp_results["reuse"]["tps"]
                          / sp_results["no_reuse"]["tps"], 3),
        lockstep_tokens_per_sec=round(sp_lock_tps, 1),
        vs_lockstep=round(sp_results["reuse"]["tps"] / sp_lock_tps, 3),
        kernel_tokens_per_sec=round(sp_kernel_tps, 1),
        kernel_vs_gather=round(
            sp_kernel_tps / sp_results["reuse"]["tps"], 3),
        prefix_hit_rate=round(st.prefix_hit_rate, 3),
        prefill_windows_skipped=st.prefill_windows_skipped_total,
        prefix_tokens_reused=st.prefix_tokens_reused_total,
        ttft_p50_ms=round(sp_results["reuse"]["p50"] * 1e3, 3),
        ttft_p95_ms=round(sp_results["reuse"]["p95"] * 1e3, 3),
        no_reuse_ttft_p50_ms=round(sp_results["no_reuse"]["p50"] * 1e3,
                                   3))
    log(f"gpt_serve shared-prefix: reuse "
        f"{shared_prefix['tokens_per_sec']:,.0f} tok/s vs no-reuse "
        f"{shared_prefix['no_reuse_tokens_per_sec']:,.0f} "
        f"({shared_prefix['vs_no_reuse']:.2f}x), hit rate "
        f"{shared_prefix['prefix_hit_rate']:.2f}, "
        f"{shared_prefix['prefill_windows_skipped']} windows skipped, "
        f"ttft p50 {shared_prefix['ttft_p50_ms']:.1f} ms vs "
        f"{shared_prefix['no_reuse_ttft_p50_ms']:.1f} ms uncached")

    # ---- slots_at_fixed_mem: concurrency at the contiguous budget ----
    # Page pool capped at the stripe layout's HBM (slots full stripes);
    # 2x the slots; a same-tick burst of short requests.  Peak
    # concurrent ACTIVE slots is the measured claim: pages allocated
    # per actual footprint, not per worst-case stripe.
    eng_m = make_engine(num_slots=2 * slots,
                        num_pages=slots * (seq // page_size) + 1)
    burst_n = 2 * slots
    b_prompts = [rng2.integers(0, config.vocab_size,
                               int(rng2.integers(4, 2 * chunk))
                               ).astype(np.int32)
                 for _ in range(burst_n)]
    b_handles = [eng_m.submit(p, 8) for p in b_prompts]
    peak_active = 0
    while eng_m.busy:
        eng_m.step()
        peak_active = max(peak_active, eng_m.stats().active)
    assert all(h.done for h in b_handles)
    log(f"gpt_serve slots_at_fixed_mem: {peak_active} concurrent slots "
        f"on a {slots}-stripe budget (contiguous layout: {slots})")

    # ---- tracing overhead: the span-emission budget, measured ----
    # The mixed trace replayed with request tracing ON (ids minted at
    # Engine.submit, lifecycle spans emitted by the scheduler) vs OFF
    # (``reqtrace.configure(enabled=False)``: mint returns None and
    # every carrier skips the calls — one attribute check per
    # request).  Two fresh engines, arms INTERLEAVED best-of-2, so a
    # background spike or cache-warmth drift can't land on one side.
    # With no active tracer (TELEMETRY=0) both arms mint nothing and
    # the ratio degenerates to noise around 1.0 — still reported, but
    # the ON arm's traced lane count says which regime ran.
    eng_on = make_engine()
    eng_off = make_engine()
    wall_on = wall_off = None
    toks_on = 0
    try:
        for _ in range(2):
            reqtrace.configure(enabled=True)
            w, hs_t = replay_engine(eng_on, prompts, budgets,
                                    arrivals, tenants)
            if wall_on is None or w < wall_on:
                wall_on, toks_on = w, sum(len(h.tokens) for h in hs_t)
            reqtrace.configure(enabled=False)
            w, hs_t = replay_engine(eng_off, prompts, budgets,
                                    arrivals, tenants)
            wall_off = w if wall_off is None else min(wall_off, w)
    finally:
        reqtrace.configure(enabled=True)
    on_tps = toks_on / wall_on
    off_tps = toks_on / wall_off     # same trace: same token total
    tracing = dict(
        on_tokens_per_sec=round(on_tps, 1),
        off_tokens_per_sec=round(off_tps, 1),
        ratio=round(on_tps / off_tps, 4),
        overhead_pct=round(max(0.0, 1.0 - on_tps / off_tps) * 100, 2),
        traced_requests=len(reqtrace.completed()))
    log(f"gpt_serve tracing: on {on_tps:,.0f} tok/s vs off "
        f"{off_tps:,.0f} (ratio {tracing['ratio']:.3f}, "
        f"{tracing['traced_requests']} lanes in the ring)")

    # ---- critical path: head-of-line interference, measured ----
    # An ADVERSARIAL long-prompt trace under an active obs.critpath
    # ledger: a wave of short requests with real decode budgets fills
    # the slots first, then multi-window long prompts land mid-decode —
    # every decode tick sharing the pump with those prefill windows is
    # stretched, and the ledger attributes exactly that stretch to the
    # victims' prefill_interference phase.  The interference_share_*
    # fields are top-level (the perf ledger only lifts top-level
    # numerics into ``measured``) so the sentinel gates their drift
    # (up is bad — docs/OBSERVABILITY.md Critical path).
    from distributed_tensorflow_tpu.obs import critpath as critpath_lib

    rng3 = np.random.default_rng(11)
    # leave free slots for the longs: they must ADMIT (and prefill)
    # while the shorts are still decoding, not queue behind them
    n_long = max(2, slots // 3)
    n_short = max(1, slots - n_long)
    cp_prompts = [rng3.integers(0, config.vocab_size,
                                int(rng3.integers(4, 9))
                                ).astype(np.int32)
                  for _ in range(n_short)]
    cp_prompts += [rng3.integers(0, config.vocab_size,
                                 3 * chunk + 4).astype(np.int32)
                   for _ in range(n_long)]
    cp_budgets = np.array([6 * tick_steps] * n_short + [4] * n_long)
    cp_budgets = np.clip(cp_budgets, 1, seq - (3 * chunk + 4) - 1)
    # shorts at tick 0, longs two ticks later: the longs' windows hit
    # slots that are already decoding
    cp_arrivals = np.array([0] * n_short + [2] * n_long)
    cp_tenants = ["interactive"] * n_short + ["batch"] * n_long
    cp_ledger = critpath_lib.CritpathLedger()
    eng_cp = make_engine()
    with critpath_lib.activated(cp_ledger):
        wall_cp, hs_cp = replay_engine(eng_cp, cp_prompts, cp_budgets,
                                       cp_arrivals, cp_tenants)
    assert all(h.done for h in hs_cp)
    cp_rep = cp_ledger.report()

    # the same vocabulary fleet-wide on virtual time: a seeded
    # workload through the real Router over SimEngines — the sim must
    # reproduce a NONZERO interference distribution for the
    # decomposition to be believed at fleet scale (the >=1e6-request
    # run lives in the slow test tier / --config=fleet_sim)
    from distributed_tensorflow_tpu.fleet import sim as sim_lib
    from distributed_tensorflow_tpu.fleet import workload as workload_lib
    sim_n = 2000 if SMOKE else 20000
    sim_cm = sim_lib.CostModel.analytic(
        n_params=1e8, prefill_chunk=64, num_slots=8, tick_steps=16)
    sim_tr = workload_lib.synthesize(sim_n, seed=11,
                                     horizon_s=sim_n / 80.0)
    sim_rep = sim_lib.FleetSim(
        sim_tr, sim_cm, replicas=2,
        engine={"num_slots": 8, "prefill_chunk": 64,
                "tick_steps": 16}).run()
    critpath = dict(
        requests=cp_rep["requests"],
        interference_ratio=cp_rep["interference_ratio"],
        phase_seconds=cp_rep["phase_seconds"],
        worst_e2e_s=round(cp_rep["worst"][0]["e2e_s"], 6)
        if cp_rep["worst"] else 0.0,
        sim_requests=sim_rep["simulated_requests"],
        sim_interference_share_p50=sim_rep["interference_share_p50"],
        sim_interference_share_p95=sim_rep["interference_share_p95"])
    log(f"gpt_serve critpath: interference share p50 "
        f"{cp_rep['interference_share_p50']:.3f} / p95 "
        f"{cp_rep['interference_share_p95']:.3f} over "
        f"{cp_rep['requests']} adversarial requests (ratio "
        f"{cp_rep['interference_ratio']:.3f}); sim p95 "
        f"{sim_rep['interference_share_p95']:.3f} over "
        f"{sim_rep['simulated_requests']} virtual requests")
    report_path = os.environ.get("DTTPU_CRITPATH_REPORT")
    if report_path:
        # the CI artifact: the full ledger document plus the sim leg
        with open(report_path, "w") as f:
            json.dump({"serve": cp_rep, "sim": sim_rep}, f, indent=2)

    return dict(metric="gpt_serve_tokens_per_sec_per_chip",
                value=round(engine_tps, 1), unit="tokens/sec/chip",
                tracing=tracing,
                vs_baseline=round(ratio_contig, 3),  # lock-step, same run
                tokens_per_sec=round(engine_tps, 1),
                contiguous_tokens_per_sec=round(contig_tps, 1),
                lockstep_tokens_per_sec=round(lock_tps, 1),
                vs_lockstep=round(ratio_contig, 3),
                vs_lockstep_paged=round(ratio_paged, 3),
                kernel_tokens_per_sec=round(kernel_tps, 1),
                vs_lockstep_paged_kernel=round(ratio_kernel, 3),
                paged_kernel_vs_gather=round(kernel_vs_gather, 3),
                ttft_p50_ms=round(ttft_p50 * 1e3, 3),
                ttft_p95_ms=round(ttft_p95 * 1e3, 3),
                interference_share_p50=cp_rep["interference_share_p50"],
                interference_share_p95=cp_rep["interference_share_p95"],
                sim_interference_share_p50=sim_rep[
                    "interference_share_p50"],
                sim_interference_share_p95=sim_rep[
                    "interference_share_p95"],
                requests=n_req, num_slots=slots, prefill_chunk=chunk,
                tick_steps=tick_steps, total_new_tokens=total_tokens,
                seq_len=seq, page_size=page_size,
                shared_prefix=shared_prefix,
                slots_at_fixed_mem=peak_active,
                slots_at_fixed_mem_contiguous=slots,
                critpath=critpath)


def bench_fleet():
    """Multi-replica fleet serving (fleet/): an ADVERSARIAL three-tenant
    burst routed over N Engine replicas by the least-loaded Router, with
    a deficit-weighted fair-share tenancy policy and a LoRA adapter
    hot-swapped per request on one tenant's traffic.  The tenants carry
    EQUAL total token demand in skewed request shapes — ``free`` many
    short requests, ``pro`` medium (under a LoRA adapter), ``batch``
    few long — submitted as whole per-tenant blocks in that order, the
    worst case for FIFO admission (the last tenant would wait for both
    blocks ahead of it).  The JSON reports fleet tokens/s, per-tenant
    TTFT p50/p95, and ``fairness_ratio``: over the contended window
    (up to the admission that exhausts the first tenant's backlog), the
    min/max ratio of weight-normalized cumulative ADMITTED token
    budgets per tenant — the deficit scheduler's own decision variable,
    so 1.0 is perfect token-weighted fair-share and plain FIFO on this
    trace measures 0.0 (the last block admits nothing inside the
    window).  CPU mesh, single process, zero retrace_warnings
    (admission, retirement, failover, and adapter swaps never
    recompile).  A page-wire leg then drains a replica of long-prompt
    requests with their KV pages shipped (fleet/pagewire.py) vs
    re-prefilled, reporting the destination's skipped prefill windows
    and a chunk_pages × overlap sweep (``wire`` in the JSON)."""
    import jax
    import numpy as np
    from distributed_tensorflow_tpu import fleet, serve
    from distributed_tensorflow_tpu.models.gpt import GPT
    from distributed_tensorflow_tpu.obs import metrics as metrics_lib

    seq = int(os.environ.get("DTTPU_BENCH_SEQ", "256"))
    config = _gpt_bench_config(seq)
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(0))
    n_replicas = int(os.environ.get("DTTPU_BENCH_FLEET_REPLICAS", "2"))
    slots = int(os.environ.get("DTTPU_BENCH_SERVE_SLOTS",
                               4 if SMOKE else 8))
    chunk = 16 if SMOKE else 32
    tick_steps = int(os.environ.get("DTTPU_BENCH_SERVE_TICK",
                                    "4" if SMOKE else "8"))
    # equal per-tenant token demand, skewed request shapes
    demand = 60 if SMOKE else 240
    profiles = {"free": (2, 5), "pro": (5, 9), "batch": (10, 16)}
    tenants = tuple(profiles)
    rng = np.random.default_rng(0)

    reqs = []                  # (tenant, prompt, budget, adapter_id)
    for tenant, (lo, hi) in profiles.items():
        left = demand
        while left > 0:
            budget = min(int(rng.integers(lo, hi)), left)
            plen = int(rng.integers(3, 2 * chunk + 1))
            prompt = rng.integers(0, config.vocab_size,
                                  plen).astype(np.int32)
            # tenant "pro" serves a fine-tuned LoRA variant: the
            # adapter swap rides the measured path
            adapter = "pro-tuned" if tenant == "pro" else None
            reqs.append((tenant, prompt, budget, adapter))
            left -= budget
    # per-tenant blocks in profile order — the FIFO worst case the
    # fair-share queue has to undo (arrival order is part of the trace)
    n_req = len(reqs)

    policy = fleet.TenantPolicy(quantum=8)
    reg = metrics_lib.Registry()
    engines = [serve.Engine(model, params, num_slots=slots, max_len=seq,
                            prefill_chunk=chunk, tick_steps=tick_steps,
                            registry=reg, tenancy=policy,
                            adapter_capacity=2, adapter_rank=4)
               for _ in range(n_replicas)]
    router = fleet.Router(engines, registry=reg)
    adapter = model.init_lora(jax.random.PRNGKey(7), rank=4)
    router.load_adapter("pro-tuned", adapter)

    # Warmup covers every executable on EVERY replica (round-robin by
    # load): two requests per replica — one multi-window prefill, one
    # short — plus one adapter-carrying request per replica.
    for _ in range(n_replicas):
        router.submit(rng.integers(0, config.vocab_size,
                                   chunk + 2).astype(np.int32), 4)
        router.submit(reqs[0][1], 2)
        router.submit(reqs[0][1], 2, adapter_id="pro-tuned")
    router.drain()

    def replay():
        # the whole adversarially-ordered trace arrives as one burst,
        # then the fleet drains it
        handles = [(tenant, budget,
                    router.submit(prompt, budget, tenant=tenant,
                                  adapter_id=ad))
                   for tenant, prompt, budget, ad in reqs]
        t0 = time.perf_counter()
        while router.busy:
            router.step()
        wall = time.perf_counter() - t0
        return wall, handles

    (wall, handles) = min((replay() for _ in range(2)),
                          key=lambda r: r[0])
    assert all(h.status == "ok" for _, _, h in handles)
    total_tokens = sum(len(h.tokens) for _, _, h in handles)
    tps = total_tokens / wall

    # fairness over the contended window: walk admissions in TTFT order
    # (burst submit => admission order), accumulating each tenant's
    # admitted token budget, and stop at the admission that exhausts the
    # first tenant's backlog — beyond it the comparison is meaningless.
    remaining = {t: sum(1 for tt, _, _ in handles if tt == t)
                 for t in tenants}
    admitted = {t: 0 for t in tenants}
    for tenant, budget, _ in sorted(handles,
                                    key=lambda r: r[2].ttft_s):
        admitted[tenant] += budget
        remaining[tenant] -= 1
        if remaining[tenant] == 0:
            break
    norm = [admitted[t] / policy.quota(t).weight for t in tenants]
    fairness = (min(norm) / max(norm)) if max(norm) > 0 else 0.0

    def pct(vals, q):
        vals = sorted(vals)
        return vals[int(q * (len(vals) - 1))]

    ttft_all = [h.ttft_s for _, _, h in handles]
    tenant_p50, tenant_p95 = {}, {}
    for tenant in tenants:
        ts = [h.ttft_s for t, _, h in handles if t == tenant]
        tenant_p50[tenant] = round(pct(ts, 0.50) * 1e3, 3)
        tenant_p95[tenant] = round(pct(ts, 0.95) * 1e3, 3)

    # -- migration leg (docs/RESILIENCE.md §migration): rolling-restart
    # cost with and without live migration, plus decode work preserved
    # across a kill.  Same engines/executables as the fairness run, so
    # nothing below compiles anything new.
    from distributed_tensorflow_tpu.resilience import faults

    mig_budget = 16 if SMOKE else 24

    def mig_batch(n=6):
        hs = []
        for _ in range(n):
            plen = int(rng.integers(3, 2 * chunk + 1))
            pr = rng.integers(0, config.vocab_size, plen).astype(np.int32)
            hs.append(router.submit(pr, mig_budget))
        for _ in range(3):
            router.step()           # decode in flight on both replicas
        return hs

    # drain-with-migration: export + import on the survivor, then the
    # drained replica is immediately free for its restart
    hs_m = mig_batch()
    t0 = time.perf_counter()
    router.drain_replica(0, migrate=True, timeout_s=600)
    drain_migrate_ms = (time.perf_counter() - t0) * 1e3
    router.drain()
    assert all(h.status == "ok" for h in hs_m)
    router.resume_replica(0)

    # wait-drain (the legacy path): the restart waits out every decode
    hs_w = mig_batch()
    t0 = time.perf_counter()
    router.drain_replica(0, migrate=False, timeout_s=600)
    drain_wait_ms = (time.perf_counter() - t0) * 1e3
    router.drain()
    assert all(h.status == "ok" for h in hs_w)
    router.resume_replica(0)

    # kill: replica 0 dies mid-decode; its requests migrate with their
    # progress.  tokens_preserved_ratio = fraction of the migrated
    # requests' final decode work that was salvaged from the snapshot
    # instead of regenerated on the survivor.
    kill_plan = faults.FaultPlan(
        [{"kind": "kill_replica", "at": 4, "replica": 0}], registry=reg)
    with faults.activated(kill_plan):
        hs_k = mig_batch()
        router.drain()
    assert all(h.status == "ok" for h in hs_k)
    migrated = [h for h in hs_k if h.migrations]
    preserved = sum(h.tokens_preserved for h in migrated)
    mig_total = sum(len(h.tokens) for h in migrated)
    preserved_ratio = preserved / mig_total if mig_total else 0.0

    # -- page-wire leg (docs/RESILIENCE.md §page wire): migrate a
    # replica's long-prompt requests with their KV pages SHIPPED over
    # the wire vs re-prefilled from scratch.  Long UNIQUE prompts (no
    # radix reuse between requests or arms) make the comparison clean:
    # the no-wire arm's destination skips zero prefill windows, the
    # wire arm's destination skips every window the shipped pages
    # cover.  Placement is forced onto the victim by draining the
    # survivors around the submit, so every arm migrates the same
    # number of requests.
    router.add_replica(engines[0])        # the kill leg's victim rejoins
    live_rids = sorted(router.stats())
    victim_rid, surv_rids = live_rids[0], live_rids[1:]
    surv_engines = [router.replica(r) for r in surv_rids]

    def wire_arm(wire_obj, n=4):
        router.page_wire = wire_obj
        for rid in surv_rids:
            router.drain_replica(rid, migrate=False, timeout_s=600)
        hs = []
        for _ in range(n):
            plen = 4 * chunk + 3          # multi-window, multi-page
            pr = rng.integers(0, config.vocab_size,
                              plen).astype(np.int32)
            hs.append(router.submit(pr, mig_budget))
        for rid in surv_rids:
            router.resume_replica(rid)
        for _ in range(256):              # prefill fully on the victim
            router.step()
            if all(len(h.tokens) >= 1 for h in hs):
                break
        skip0 = sum(e.stats().prefill_windows_skipped_total
                    for e in surv_engines)
        c0 = reg.get("dttpu_wire_chunks_total")
        b0 = reg.get("dttpu_wire_bytes_total")
        r0 = reg.get("dttpu_wire_chunk_retries_total")
        c0, b0, r0 = [m.value if m is not None else 0
                      for m in (c0, b0, r0)]
        t0 = time.perf_counter()
        router.drain_replica(victim_rid, migrate=True, timeout_s=600)
        drain_ms = (time.perf_counter() - t0) * 1e3
        router.drain()
        # total = drain + completing the migrated requests: the
        # re-prefill arm pays its recompute here, not in the drain
        total_ms = (time.perf_counter() - t0) * 1e3
        assert all(h.status == "ok" for h in hs)
        skipped = sum(e.stats().prefill_windows_skipped_total
                      for e in surv_engines) - skip0
        router.resume_replica(victim_rid)
        get = lambda name: (reg.get(name).value
                            if reg.get(name) is not None else 0)
        return dict(drain_migrate_ms=round(drain_ms, 3),
                    total_ms=round(total_ms, 3),
                    dest_windows_skipped=int(skipped),
                    chunks=int(get("dttpu_wire_chunks_total") - c0),
                    bytes=int(get("dttpu_wire_bytes_total") - b0),
                    retries=int(
                        get("dttpu_wire_chunk_retries_total") - r0))

    wire = fleet.PageWire(registry=reg, chunk_pages=2, overlap=2)
    wire_arm(wire, n=1)       # trace _wire_gather/_wire_splice once
    nowire = wire_arm(None)
    wired = wire_arm(wire)
    # chunk/overlap sweep: how framing granularity and frames-in-flight
    # trade wall clock for retry blast radius on this link
    sweep = []
    combos = ([(1, 1), (2, 2)] if SMOKE
              else [(1, 1), (2, 1), (2, 2), (4, 2), (4, 4)])
    for cp, ov in combos:
        w = fleet.PageWire(registry=reg, chunk_pages=cp, overlap=ov)
        arm = wire_arm(w)
        sweep.append(dict(chunk_pages=cp, overlap=ov, **arm))
    router.page_wire = None
    wire_pages = int(reg.get("dttpu_wire_pages_shipped_total").value)
    wire_transfers = int(reg.get("dttpu_wire_transfers_total").value)

    log(f"fleet wire: migrate+complete {wired['total_ms']:.0f} ms "
        f"shipping pages ({wired['dest_windows_skipped']} dest windows "
        f"skipped) vs {nowire['total_ms']:.0f} ms re-prefill "
        f"({nowire['dest_windows_skipped']} skipped); "
        f"{wire_transfers} transfers, {wire_pages} pages shipped")

    log(f"fleet: {n_replicas} replicas {tps:,.0f} tok/s, admission "
        f"fairness {fairness:.3f} (FIFO on this trace: 0.0), per-tenant "
        "ttft p95 "
        + ", ".join(f"{t} {tenant_p95[t]:.1f} ms" for t in tenants))
    log(f"fleet migration: drain {drain_migrate_ms:.0f} ms migrate vs "
        f"{drain_wait_ms:.0f} ms wait; kill preserved "
        f"{preserved}/{mig_total} tokens "
        f"({preserved_ratio:.2f}) across {len(migrated)} migrations")
    return dict(metric="fleet_tokens_per_sec",
                value=round(tps, 1), unit="tokens/sec",
                tokens_per_sec=round(tps, 1),
                fairness_ratio=round(fairness, 4),
                ttft_p50_ms=round(pct(ttft_all, 0.50) * 1e3, 3),
                ttft_p95_ms=round(pct(ttft_all, 0.95) * 1e3, 3),
                tenant_ttft_p50_ms=tenant_p50,
                tenant_ttft_p95_ms=tenant_p95,
                drain_migrate_ms=round(drain_migrate_ms, 3),
                drain_wait_ms=round(drain_wait_ms, 3),
                tokens_preserved_ratio=round(preserved_ratio, 4),
                wire=dict(shipped=wired, re_prefill=nowire,
                          sweep=sweep, transfers=wire_transfers,
                          pages_shipped=wire_pages),
                migrations=int(
                    reg.get("dttpu_migrations_total").value),
                replicas=n_replicas, requests=n_req,
                num_slots=slots, prefill_chunk=chunk,
                tick_steps=tick_steps, total_new_tokens=total_tokens,
                seq_len=seq)


def bench_fleet_sim():
    """Million-request fleet simulation (fleet/sim.py): the REAL
    Router/Watchdog/tenancy/faults stack driven at virtual-time speed
    by ``SimEngine`` replicas priced with the graph-tier cost model.
    Four legs, one JSON line:

    1. **autoscaler** — the seeded diurnal+burst trace (two scheduled
       ``correlated_kill`` events included) under the SLO-driven
       ``Autoscaler`` (scale-out on missed attainment/backlog,
       migrate-based scale-in, heal after kills).
    2. **static** — the SAME trace on a fixed peak-sized fleet.
       ``autoscaler_vs_static`` = attainment per replica-second,
       autoscaler over static — >= 1.0 means the policy buys the same
       SLO for less provisioned capacity.
    3. **curve** — SLO attainment vs static replica count on a clean
       subset trace (``slo_vs_replicas``), the capacity-planning curve.
    4. **affinity ablation** — the saturated Zipf-prefix trace through
       a 4-replica static fleet twice: prefix-affinity placement on
       (``affinity_weight=1``) vs blind least-loaded
       (``affinity_weight=0``), SAME seeded trace (fingerprint
       equality asserted).  ``affinity_vs_blind`` is the tokens/s
       ratio on virtual time and ``fleet_prefix_hit_rate`` the
       affinity arm's fleet-wide radix hit rate — both PerfLedger
       fields the perf gate watches.  10⁶ requests full-scale, 2k
       under DTTPU_BENCH_SMOKE (DTTPU_BENCH_FLEET_SIM_ABLATION
       overrides); the Zipf population scales with the request count
       (512 per 2k requests) so the cold-landing rate — the thing
       placement policy controls — is scale-invariant instead of
       washing out once every replica has seen every prefix (sim
       fingerprints never evict).
    5. **real affinity** — the same on/off comparison on a REAL
       2-replica CPU ``serve.Engine`` fleet (tiny GPT, shared system
       prompts): placement quality is judged by the replicas' actual
       radix caches, pinning that the sim conclusion transfers
       (``DTTPU_BENCH_FLEET_AFFINITY_REAL=0`` skips).
    6. **validation** — a small burst replayed against BOTH a real
       2-replica ``serve.Engine`` fleet and the simulator with a
       ``CostModel.calibrate``\\ d from two measured points on that
       engine; asserts sim-predicted tokens/s and TTFT p50 land within
       25% of the real replay (``DTTPU_BENCH_FLEET_SIM_VALIDATE=0``
       skips, e.g. where no jax backend is wanted).

    ``sim_wall_s`` counts legs 1-3 only (the virtual-time claim:
    >= 1e6 simulated requests under 60 s of CPU wall-clock);
    ``simulated_requests`` is their request total.  The ablation legs
    keep their own clock (``ablation.wall_s``) so the headline claim
    stays comparable across PRs."""
    import gc
    import numpy as np
    from distributed_tensorflow_tpu import fleet
    from distributed_tensorflow_tpu.fleet import sim as sim_lib
    from distributed_tensorflow_tpu.fleet import workload
    from distributed_tensorflow_tpu.obs import federate, reqtrace

    n_main = int(os.environ.get("DTTPU_BENCH_FLEET_SIM_REQUESTS",
                                "8000" if SMOKE else "400000"))
    n_curve = int(os.environ.get("DTTPU_BENCH_FLEET_SIM_CURVE",
                                 "2000" if SMOKE else "65000"))
    horizon_s = 1800.0
    curve_replicas = (2, 3, 4, 6)
    slo = fleet.SLO(ttft_s=2.0, itl_s=0.02)
    # a ~200M-param weight-streaming decode point: mean demand sits
    # right at the 2-replica floor, so the diurnal peak and the burst
    # spikes genuinely need the autoscaler, while a peak-sized static
    # fleet idles through the trough
    engine_kw = dict(num_slots=8, prefill_chunk=64, tick_steps=16)
    cm = sim_lib.CostModel.analytic(
        n_params=2.0e8, prefill_chunk=64, num_slots=8, tick_steps=16,
        hw=sim_lib.HardwarePoint())
    trace = workload.synthesize(
        n_main, seed=0, horizon_s=horizon_s, bursts=3,
        burst_magnitude=5.0, failures=2, failure_k=2)

    sim_wall = [0.0]
    simulated = [0]

    # One federation over every leg's registries: the per-tenant SLO
    # gauges (dttpu_slo_*) stream in from the sims' TTFT/TPOT samples,
    # and the request lanes the SimEngines sample (1-in-trace_sample,
    # VIRTUAL timestamps) land in the bench tracer next to the host
    # spans — DTTPU_BENCH_TRACE_FILE carries both out for the CI merge.
    fed = federate.FederatedMetrics()

    def run_leg(tr, cost=None, engine=None, account=True, **kw):
        fs = sim_lib.FleetSim(tr, cost if cost is not None else cm,
                              slo=slo,
                              engine=dict(engine if engine is not None
                                          else engine_kw),
                              **kw)
        fs.metrics.federation = fed
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        try:
            rep = fs.run()
        finally:
            gc.enable()
        rep["wall_s"] = time.perf_counter() - t0
        if account:
            sim_wall[0] += rep["wall_s"]
            simulated[0] += rep["simulated_requests"]
        return rep

    auto_rep = run_leg(
        trace, replicas=2,
        autoscaler=dict(min_replicas=2, max_replicas=8,
                        eval_interval_s=15.0, cooldown_s=60.0),
        watchdog=dict(tick_deadline_s=5.0), seed=1)
    log(f"fleet_sim autoscaler: {auto_rep['completed']:,} ok, "
        f"attainment {auto_rep['slo_attainment']:.4f}, "
        f"{auto_rep['scale_outs']} out / {auto_rep['scale_ins']} in, "
        f"{auto_rep['migrations']} migrations, "
        f"{auto_rep['replica_seconds']:,.0f} replica-s")

    static_rep = run_leg(trace, replicas=6, seed=1)
    log(f"fleet_sim static x6: attainment "
        f"{static_rep['slo_attainment']:.4f}, "
        f"{static_rep['replica_seconds']:,.0f} replica-s")
    vs_static = ((auto_rep["slo_attainment"]
                  / max(auto_rep["replica_seconds"], 1e-9))
                 / (static_rep["slo_attainment"]
                    / max(static_rep["replica_seconds"], 1e-9)))

    curve_trace = workload.synthesize(
        n_curve, seed=1, horizon_s=horizon_s / 4, bursts=2,
        burst_magnitude=4.0, failures=0)
    curve = {}
    for r in curve_replicas:
        rep = run_leg(curve_trace, replicas=r, seed=2)
        curve[str(r)] = dict(
            slo_attainment=rep["slo_attainment"],
            attainment_ttft=rep["attainment_ttft"],
            attainment_itl=rep["attainment_itl"],
            ttft_p99_ms=rep["ttft_p99_ms"],
            itl_p99_ms=rep["itl_p99_ms"])
    log("fleet_sim curve: " + ", ".join(
        f"{r}r {c['slo_attainment']:.3f}" for r, c in curve.items()))

    # -- affinity ablation: prefix-affinity placement on vs off --------
    # Saturated arrivals (1000 req/s against a 4-replica fleet) so
    # virtual time is compute-bound, prefix-dominated requests (short
    # own-suffix, small decode budget, 512 Zipf populations) so the
    # prefill a hot landing skips is a material share of the work —
    # the regime ROADMAP item 6 is about, where blind placement
    # forfeits the radix win on every cold landing.
    n_abl = int(os.environ.get("DTTPU_BENCH_FLEET_SIM_ABLATION",
                               "2000" if SMOKE else "1000000"))
    # Zipf population scales with the trace (512 per 2k requests =
    # smoke-identical at smoke scale): sim fingerprints never evict,
    # so a FIXED population saturates every replica after a few
    # thousand requests and both arms converge to hit rate ~1 — the
    # cold-landing rate the placement policy controls must stay
    # scale-invariant for the 10⁶ leg to measure anything.
    abl_pops = max(512, (n_abl * 512) // 2000)
    abl_engine = dict(num_slots=8, prefill_chunk=16, tick_steps=8)
    abl_cm = sim_lib.CostModel.analytic(
        n_params=2.0e8, prefill_chunk=16, num_slots=8, tick_steps=8,
        hw=sim_lib.HardwarePoint())

    def abl_trace():
        return workload.synthesize(
            n_abl, seed=3, horizon_s=n_abl / 1000.0,
            prefix_populations=abl_pops, prefix_fraction=0.9,
            plen_mean=12.0, new_tokens_mean=4.0, bursts=0, failures=0)

    abl_fp = abl_trace().fingerprint()

    def abl_arm(weight):
        # re-synthesize per arm and assert fingerprint equality: both
        # arms provably replay the IDENTICAL workload, so the ratio
        # below measures placement policy and nothing else
        tr = abl_trace()
        assert tr.fingerprint() == abl_fp, "ablation arms diverged"
        return run_leg(tr, cost=abl_cm, engine=abl_engine, replicas=4,
                       seed=4, affinity_weight=weight,
                       account=False)

    abl_on = abl_arm(1.0)
    abl_off = abl_arm(0.0)
    assert abl_on["tokens_generated"] == abl_off["tokens_generated"], (
        "ablation arms generated different token counts")
    tps_on = abl_on["tokens_generated"] / abl_on["virtual_time_s"]
    tps_off = abl_off["tokens_generated"] / abl_off["virtual_time_s"]
    affinity_vs_blind = tps_on / tps_off
    ablation = dict(
        requests=n_abl, replicas=4, populations=abl_pops,
        wall_s=round(abl_on["wall_s"] + abl_off["wall_s"], 3),
        trace_fingerprint=abl_fp,
        affinity=dict(
            fleet_prefix_hit_rate=abl_on["fleet_prefix_hit_rate"],
            tokens_per_vsec=round(tps_on, 2),
            virtual_time_s=abl_on["virtual_time_s"],
            ttft_p50_ms=abl_on["ttft_p50_ms"],
            ttft_p95_ms=abl_on["ttft_p95_ms"]),
        blind=dict(
            fleet_prefix_hit_rate=abl_off["fleet_prefix_hit_rate"],
            tokens_per_vsec=round(tps_off, 2),
            virtual_time_s=abl_off["virtual_time_s"],
            ttft_p50_ms=abl_off["ttft_p50_ms"],
            ttft_p95_ms=abl_off["ttft_p95_ms"]))
    log(f"fleet_sim affinity ablation ({n_abl:,} req): hit rate "
        f"{abl_on['fleet_prefix_hit_rate']:.4f} (affinity) vs "
        f"{abl_off['fleet_prefix_hit_rate']:.4f} (blind), tokens/s "
        f"ratio {affinity_vs_blind:.4f}")

    real_affinity = None
    if os.environ.get("DTTPU_BENCH_FLEET_AFFINITY_REAL", "1") != "0":
        real_affinity = _fleet_affinity_real()
        log(f"fleet affinity (real 2-replica): hit rate "
            f"{real_affinity['affinity']['fleet_prefix_hit_rate']:.4f}"
            f" (affinity) vs "
            f"{real_affinity['blind']['fleet_prefix_hit_rate']:.4f} "
            f"(blind), {real_affinity['affinity']['affinity_hits']} "
            f"affinity placements")

    validation = None
    if os.environ.get("DTTPU_BENCH_FLEET_SIM_VALIDATE", "1") != "0":
        validation = _fleet_sim_validate(cm_seed=0)
        log(f"fleet_sim validation: sim/real tokens/s "
            f"{validation['tokens_per_sec_ratio']:.3f}, ttft p50 "
            f"{validation['ttft_p50_ratio']:.3f} (|err| <= 0.25)")

    total_tokens = (auto_rep["tokens_generated"]
                    + static_rep["tokens_generated"])
    result = dict(
        metric="fleet_sim_requests_per_sec",
        value=round(simulated[0] / max(sim_wall[0], 1e-9), 1),
        unit="requests/sec",
        simulated_requests=simulated[0],
        sim_wall_s=round(sim_wall[0], 3),
        virtual_time_s=round(auto_rep["virtual_time_s"], 3),
        autoscaler=auto_rep, static=static_rep,
        autoscaler_vs_static=round(vs_static, 4),
        slo_vs_replicas=curve,
        # top-level (measured) perf-gate fields: deterministic virtual-
        # time numbers, gated by scripts/perf_gate.py via the committed
        # ledger/baseline.jsonl fleet_sim row
        affinity_vs_blind=round(affinity_vs_blind, 4),
        fleet_prefix_hit_rate=abl_on["fleet_prefix_hit_rate"],
        ablation=ablation,
        slo=dict(ttft_s=slo.ttft_s, itl_s=slo.itl_s),
        cost_model=dict(prefill_window_s=cm.prefill_window_s,
                        decode_tick_s=cm.decode_tick_s,
                        overhead_s=cm.overhead_s,
                        provenance=cm.provenance),
        total_tokens=total_tokens,
        requests_main=n_main, requests_curve=n_curve)
    fed_text = fed.expose()
    result["federation"] = dict(
        slo_series=sum(1 for ln in fed_text.splitlines()
                       if ln.startswith("dttpu_slo_")),
        sources=fed.source_count())
    result["tracing"] = dict(
        # ring-bounded (256): "did sampling run", not a request count
        sampled_lanes=len(reqtrace.completed()),
        trace_sample=int(engine_kw.get("trace_sample", 64)))
    log(f"fleet_sim federation: {result['federation']['slo_series']} "
        f"SLO series over {result['federation']['sources']} source(s), "
        f"{result['tracing']['sampled_lanes']} sampled lanes in the "
        f"trace ring")
    if real_affinity is not None:
        result["real_affinity"] = real_affinity
    if validation is not None:
        result["validation"] = validation
    return result


def _fleet_affinity_real():
    """The affinity ablation's REAL leg: a tiny 2-replica CPU
    ``serve.Engine`` fleet behind the Router with prefix-affinity
    placement on vs off.  Requests share a handful of system prompts
    (distinct unique suffixes); a seeding wave registers each prompt's
    pages on whichever replica first serves it, then the measured wave
    is placed by each policy and the replicas' ACTUAL radix caches
    judge the outcome — ``fleet_prefix_hit_rate`` summed over both
    engines' pool counters, exactly the sim leg's metric.  Wall time
    is deliberately not compared (2 real engines timeshare one CPU);
    this leg pins that the placement-quality conclusion transfers from
    cost-model to hardware."""
    import jax
    import numpy as np
    from distributed_tensorflow_tpu import fleet, serve
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    from distributed_tensorflow_tpu.obs import metrics as metrics_lib
    import jax.numpy as jnp

    config = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=2, intermediate_size=256,
                       max_position=128, dtype=jnp.float32,
                       dropout_rate=0.0)
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(0))
    slots, chunk, ticks = 4, 16, 4
    pops, followers, budget = 4, 24, 4
    rng = np.random.default_rng(7)
    system = [rng.integers(0, config.vocab_size, 2 * chunk)
              .astype(np.int32) for _ in range(pops)]

    def prompt(pop):
        suffix = rng.integers(0, config.vocab_size, 5).astype(np.int32)
        return np.concatenate([system[pop], suffix])

    # one prompt set, replayed by BOTH arms — the comparison measures
    # placement policy, not workload luck.  The follower population
    # order is SHUFFLED: a round-robin order would parity-align with
    # blind placement's strict alternation and hand the blind arm the
    # holder by coincidence.
    seed_prompts = [prompt(pop) for pop in range(pops)]
    follower_prompts = [prompt(int(pop))
                        for pop in rng.integers(0, pops, followers)]

    def arm(weight):
        reg = metrics_lib.Registry()
        engines = [serve.Engine(model, params, num_slots=slots,
                                max_len=128, prefill_chunk=chunk,
                                tick_steps=ticks, registry=reg,
                                paged=True)
                   for _ in range(2)]
        router = fleet.Router(engines, registry=reg,
                              affinity_weight=weight)
        # seeding wave: one request per system prompt — its admission
        # registers the prompt's pages on the serving replica
        for p in seed_prompts:
            router.submit(p, budget)
        router.drain()
        seeded = {rid: (s.prefix_lookups_total, s.prefix_hits_total)
                  for rid, s in router.stats().items()}
        hs = [router.submit(p, budget) for p in follower_prompts]
        router.drain()
        assert all(h.status == "ok" for h in hs)
        stats = router.stats()
        lookups = sum(s.prefix_lookups_total - seeded[rid][0]
                      for rid, s in stats.items())
        hits = sum(s.prefix_hits_total - seeded[rid][1]
                   for rid, s in stats.items())
        return dict(
            fleet_prefix_hit_rate=round(hits / lookups
                                        if lookups else 0.0, 4),
            prefix_tokens_reused=int(sum(
                s.prefix_tokens_reused_total for s in stats.values())),
            affinity_hits=int(reg.get(
                "dttpu_router_affinity_hits_total").value),
            placements=list(router.placements))

    on, off = arm(1.0), arm(0.0)
    return dict(requests=followers, populations=pops,
                affinity=dict((k, v) for k, v in on.items()
                              if k != "placements"),
                blind=dict((k, v) for k, v in off.items()
                           if k != "placements"))


def _fleet_sim_validate(cm_seed=0):
    """The fleet_sim stub-validation leg: one small burst through a
    real single-replica CPU ``serve.Engine`` fleet (still behind the
    Router) and through the simulator with a cost model CALIBRATED
    from two measured points (a decode tick at full batch, a
    prefill-window tick) on that same engine.  One replica because the
    comparison is wall-vs-virtual time: N real engines timeshare one
    CPU (wall = sum of their work) while N sim replicas run in
    parallel virtual time — single-replica makes the two clocks
    commensurable.  Returns the sim/real ratios and asserts both
    within 25%."""
    import jax
    import numpy as np
    from distributed_tensorflow_tpu import fleet, serve
    from distributed_tensorflow_tpu.analysis import graph as graph_lib
    from distributed_tensorflow_tpu.fleet import sim as sim_lib
    from distributed_tensorflow_tpu.fleet import workload
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    from distributed_tensorflow_tpu.obs import metrics as metrics_lib
    import jax.numpy as jnp

    # deliberately tiny: the contract under test is sim-vs-real on the
    # SAME engine, not model scale
    config = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                       num_heads=2, intermediate_size=256,
                       max_position=128, dtype=jnp.float32,
                       dropout_rate=0.0)
    model = GPT(config)
    params = model.init(jax.random.PRNGKey(0))
    slots, chunk, ticks = 4, 16, 4
    n_req, budget = 48, 10
    rng = np.random.default_rng(cm_seed)
    prompts = [rng.integers(0, config.vocab_size,
                            int(rng.integers(3, 2 * chunk + 1)))
               .astype(np.int32) for _ in range(n_req)]

    def make_engine(reg):
        return serve.Engine(model, params, num_slots=slots,
                            max_len=128, prefill_chunk=chunk,
                            tick_steps=ticks, registry=reg)

    reg = metrics_lib.Registry()
    engines = [make_engine(reg)]
    router = fleet.Router(engines, registry=reg)
    # warmup: compile every executable on both replicas
    for _ in range(2):
        router.submit(prompts[0], 2)
        router.submit(rng.integers(0, config.vocab_size,
                                   chunk + 3).astype(np.int32), 2)
    router.drain()

    # -- calibration: two measured points on engine 0 ------------------
    eng = engines[0]
    for _ in range(slots):                   # full decode batch
        eng.submit(prompts[0][:4], 64)
    while eng.stats().active < slots:        # admit + prefill everyone
        eng.step()
    # per-step MIN, not mean: each step is the same deterministic
    # compute, so scheduler preemption on a shared core only ever adds
    # time — the minimum is the clean sample
    tick_samples = []
    while eng.stats().active == slots and len(tick_samples) < 12:
        t0 = time.perf_counter()
        eng.step()
        tick_samples.append(time.perf_counter() - t0)
    measured_tick_s = min(tick_samples)
    eng.drain()
    # prefill point: one long prompt alone; the first step (admit +
    # first window) is untimed, the remaining pure-window steps are
    eng.submit(rng.integers(0, config.vocab_size,
                            6 * chunk).astype(np.int32), 1)
    eng.step()
    window_samples = []
    while eng.stats().prefilling and len(window_samples) < 12:
        t0 = time.perf_counter()
        eng.step()
        window_samples.append(time.perf_counter() - t0)
    measured_window_s = (min(window_samples) if window_samples
                         else measured_tick_s)
    eng.drain()
    targets = {t.name: t for t in eng.scheduler.graph_targets()}
    window_cost = graph_lib.target_cost(targets["prefill_window"])
    tick_cost = graph_lib.target_cost(targets["decode_tick"])
    cm = sim_lib.CostModel.calibrate(window_cost, tick_cost,
                                     measured_window_s, measured_tick_s)

    # -- real replay: the whole burst, wall-clock (min of 3 on both
    # wall and ttft p50 to shed scheduler noise on a shared CI core) --
    def real_replay():
        hs = [router.submit(p, budget) for p in prompts]
        t0 = time.perf_counter()
        while router.busy:
            router.step()
        wall = time.perf_counter() - t0
        assert all(h.status == "ok" for h in hs)
        ttfts = sorted(h.ttft_s for h in hs)
        return wall, ttfts[len(ttfts) // 2], hs
    replays = [real_replay() for _ in range(3)]
    real_wall = min(r[0] for r in replays)
    real_ttft_p50 = min(r[1] for r in replays)
    real_tokens = sum(len(h.tokens) for h in replays[0][2])
    real_tps = real_tokens / real_wall

    # -- sim replay: same burst shape, same engine geometry ------------
    tr = workload.Trace(
        arrival_s=np.zeros(n_req, dtype=np.float64),
        plen=np.array([len(p) for p in prompts], dtype=np.int32),
        new_tokens=np.full(n_req, budget, dtype=np.int32),
        tenant=np.zeros(n_req, dtype=np.int16),
        prefix_id=np.zeros(n_req, dtype=np.int32),
        prefix_len=np.zeros(n_req, dtype=np.int32),
        adapter=np.full(n_req, -1, dtype=np.int16),
        tenants=(("default", 1.0),), events=(), horizon_s=0.0,
        seed=cm_seed)
    fs = sim_lib.FleetSim(
        tr, cm, replicas=1,
        engine=dict(num_slots=slots, prefill_chunk=chunk,
                    tick_steps=ticks),
        quantum_s=measured_tick_s, inflight_cap_per_replica=n_req,
        seed=0)
    sim_rep = fs.run()
    sim_tps = sim_rep["tokens_generated"] / sim_rep["virtual_time_s"]
    sim_ttft_p50 = sim_rep["ttft_p50_ms"] / 1e3

    tps_ratio = sim_tps / real_tps
    ttft_ratio = sim_ttft_p50 / real_ttft_p50
    assert abs(tps_ratio - 1.0) <= 0.25, (
        f"sim tokens/s off by {tps_ratio:.3f}x "
        f"(sim {sim_tps:.1f} vs real {real_tps:.1f})")
    assert abs(ttft_ratio - 1.0) <= 0.25, (
        f"sim ttft p50 off by {ttft_ratio:.3f}x "
        f"(sim {sim_ttft_p50*1e3:.1f} ms vs real "
        f"{real_ttft_p50*1e3:.1f} ms)")
    return dict(
        requests=n_req,
        measured_tick_s=round(measured_tick_s, 6),
        measured_window_s=round(measured_window_s, 6),
        calibrated=dict(prefill_window_s=round(cm.prefill_window_s, 6),
                        decode_tick_s=round(cm.decode_tick_s, 6),
                        overhead_s=round(cm.overhead_s, 6)),
        real_tokens_per_sec=round(real_tps, 2),
        sim_tokens_per_sec=round(sim_tps, 2),
        tokens_per_sec_ratio=round(tps_ratio, 4),
        real_ttft_p50_ms=round(real_ttft_p50 * 1e3, 3),
        sim_ttft_p50_ms=round(sim_ttft_p50 * 1e3, 3),
        ttft_p50_ratio=round(ttft_ratio, 4))


def bench_gpt_moe():
    """The gpt row with a mixture-of-experts FFN (ops.moe top-2/8 capacity
    routing + aux load-balance loss) — the measured row for the MoE
    subsystem.  Single-chip the experts are co-located (no all_to_all);
    the routing/capacity compute is what this row prices."""
    experts = int(os.environ.get("DTTPU_BENCH_GPT_MOE", "8"))
    result = bench_gpt(experts=experts)
    result["metric"] = "gpt_moe" + result.pop("metric")[len("gpt"):]
    result["moe_experts"] = experts
    return result


def bench_gpt_long():
    """The gpt row at seq 2048 — the long-context operating point where
    ``use_flash="auto"`` actually dispatches the fused Pallas kernel on
    TPU (crossover at DTTPU_FLASH_MIN_SEQ=2048, docs/PERF.md); seq 256
    keeps the default gpt row on the XLA path, so this row is the one
    that exercises flash attention end-to-end in a train step."""
    result = bench_gpt(seq=2048)
    result["metric"] = "gpt_long" + result.pop("metric")[len("gpt"):]
    return result


def bench_recovery():
    """Recovery smoke (docs/RESILIENCE.md): a small training run with an
    injected prefetch-producer kill mid-flight; the resilience
    ``Supervisor`` restarts it from the last good checkpoint.  The JSON
    line reports ``restore_ms`` (wall clock of the verified
    ``restore_latest_good`` walk on the retry) and
    ``recovery_steps_lost`` (steps between the restored checkpoint and
    the failure point — the save-interval tax), so the restart path has
    a measured number instead of a vibe.  Always tiny (XOR MLP): this
    row measures the recovery machinery, not the model."""
    import shutil
    import tempfile
    import jax
    from distributed_tensorflow_tpu import data, ops, optim, train
    from distributed_tensorflow_tpu.obs import metrics as metrics_lib
    from distributed_tensorflow_tpu.resilience import (NonfiniteGuardHook,
                                                       Supervisor, faults)

    target_step, save_every, kill_at_batch = 24, 5, 13
    reg = metrics_lib.Registry()
    ckpt_dir = tempfile.mkdtemp(prefix="dttpu-recovery-")
    restore_ms: list = []
    resumed_steps: list = []
    fail_steps: list = []

    def make_bits():
        model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
        opt = optim.adam()
        state = train.init_train_state(model, opt, jax.random.PRNGKey(0),
                                       (64,))
        step = train.make_train_step(model, "mse", opt, device_health=True,
                                     skip_nonfinite=True)
        (xt, yt), _ = data.xor_data(500, val_size=10, seed=0)
        return state, step, data.Dataset([xt, yt], 50, seed=0)

    def build_session():
        state, step, ds = make_bits()
        t0 = time.perf_counter()
        restored, _ = train.checkpoint.restore_latest_good(state, ckpt_dir)
        dt_ms = (time.perf_counter() - t0) * 1e3
        if restored is not None:
            state = restored
            restore_ms.append(dt_ms)
            resumed_steps.append(int(state.step))
        sess = train.TrainSession(
            state, step, checkpoint_dir=ckpt_dir, restore=False,
            hooks=[train.CheckpointHook(every_steps=save_every,
                                        every_secs=None),
                   NonfiniteGuardHook(max_consecutive=3),
                   train.StopAtStepHook(last_step=target_step)])
        sess._recovery_ds = ds
        return sess

    def train_fn(sess):
        it = data.prefetch_to_device(iter(sess._recovery_ds.epochs(1000)),
                                     size=2)
        try:
            for batch in it:
                if sess.should_stop():
                    break
                sess.run_step(batch)
        except BaseException:
            fail_steps.append(sess.step)
            raise
        return sess.step

    plan = faults.FaultPlan(
        [{"kind": "kill_prefetch", "at": kill_at_batch}], registry=reg)
    sup = Supervisor(max_restarts=2, backoff_base=0.01, registry=reg)
    # goodput accounting (obs/goodput.py): the supervised run's wall
    # clock attributed into step / checkpoint / backoff / stall buckets
    # — the recovery row carries the split so "how much did that fault
    # cost" is a number, not a rerun
    from distributed_tensorflow_tpu.obs import goodput as goodput_lib
    acct = goodput_lib.GoodputAccountant(registry=reg)
    try:
        with faults.activated(plan), goodput_lib.activated(acct):
            final_step = sup.run(build_session, train_fn)
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    goodput_report = acct.report()

    lost = (fail_steps[0] - resumed_steps[0]
            if fail_steps and resumed_steps else -1)

    # -- serve-tier watchdog smoke (docs/RESILIENCE.md §watchdog): a
    # 2-replica fleet takes an injected stall_tick on replica 0; the
    # Watchdog's tick-deadline policy must detect it at the first check
    # after the stalled tick, quarantine the replica, and migrate its
    # requests to the survivor.  detect_ms measures stall start ->
    # quarantine (separate registry: the training-recovery fault count
    # above stays the row's faults_injected).
    import numpy as np
    from distributed_tensorflow_tpu import fleet as fleet_lib
    from distributed_tensorflow_tpu import serve as serve_lib
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    wreg = metrics_lib.Registry()
    gmodel = gpt_tiny(dropout_rate=0.0)
    gparams = gmodel.init(jax.random.PRNGKey(0))
    engines = [serve_lib.Engine(gmodel, gparams, num_slots=2, max_len=64,
                                prefill_chunk=4, tick_steps=2,
                                registry=wreg) for _ in range(2)]
    wrouter = fleet_lib.Router(engines, registry=wreg)
    # warm-compile every executable before arming a tick deadline (a
    # first-compile tick is legitimately slower than any sane deadline)
    warm = [e.submit(np.arange(1, 7, dtype=np.int32), 3)
            for e in engines]
    for _ in range(8):
        for e in engines:
            e.step()
    tick_deadline_s, stall_s = 0.25, 1.0
    wd = fleet_lib.Watchdog(wrouter, tick_deadline_s=tick_deadline_s,
                            registry=wreg)
    wplan = faults.FaultPlan(
        [{"kind": "stall_tick", "at": 3, "replica": 0,
          "seconds": stall_s}], registry=wreg)
    wrng = np.random.default_rng(3)
    detect_ms = None
    t_stall = None
    with faults.activated(wplan):
        whs = [wrouter.submit(
                   wrng.integers(0, 50, 5).astype(np.int32), 8)
               for _ in range(4)]
        while wrouter.busy:
            t0 = time.perf_counter()
            wrouter.step()
            if t_stall is None and wplan.log:
                t_stall = t0        # the stall landed inside this step
            if wd.check() and detect_ms is None:
                detect_ms = (time.perf_counter() - t_stall) * 1e3
    watchdog_ok = (detect_ms is not None
                   and 0 in wrouter.quarantined
                   and all(h.status == "ok" for h in whs)
                   and all(h.done for h in warm))

    ok = (final_step >= target_step and restore_ms
          and reg.get("dttpu_restarts_total").value >= 1
          and watchdog_ok)
    return {
        "metric": "recovery_restore_ms" + ("" if ok else "_FAILED"),
        "value": round(restore_ms[0], 3) if restore_ms else 0.0,
        "unit": "ms",
        "restore_ms": round(restore_ms[0], 3) if restore_ms else None,
        "recovery_steps_lost": lost,
        "restarts": reg.get("dttpu_restarts_total").value,
        "faults_injected": reg.get("dttpu_faults_injected_total").value,
        "final_step": final_step,
        # watchdog smoke: detection latency from stall start (the stall
        # itself is stall_s, so "within deadline" means detect_ms stays
        # a small overhead above it), quarantine + migration counts
        "watchdog_detect_ms": (round(detect_ms, 3)
                               if detect_ms is not None else None),
        "watchdog_stall_s": stall_s,
        "watchdog_tick_deadline_s": tick_deadline_s,
        "watchdog_quarantined": len(wrouter.quarantined),
        "watchdog_migrations": int(
            wreg.get("dttpu_migrations_total").value),
        # where the supervised run's wall clock went (buckets sum to
        # wall_s by construction; goodput_pct = step/wall)
        "goodput": goodput_report,
        "goodput_pct": goodput_report["goodput_pct"],
    }


CONFIGS = {
    "mnist_mlp": bench_mnist_mlp,
    "cifar_cnn": bench_cifar_cnn,
    "resnet50": bench_resnet50,
    "bert": bench_bert,
    "gpt": bench_gpt,
    "gpt_long": bench_gpt_long,
    "gpt_moe": bench_gpt_moe,
    "llama": bench_llama,
    "gpt_decode": bench_gpt_decode,
    "gpt_decode_int8": bench_gpt_decode_int8,
    "gpt_decode_spec": bench_gpt_decode_spec,
    "gpt_serve": bench_gpt_serve,
    "fleet": bench_fleet,
    "fleet_sim": bench_fleet_sim,
    "recovery": bench_recovery,
}


# ---------------------------------------------------------------------------
# Supervisor: retry backend bring-up in fresh subprocesses, CPU fallback.


def _parse_last_json(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _result_ok(r) -> bool:
    return (isinstance(r, dict) and float(r.get("value", 0) or 0) > 0
            and "TIMEOUT" not in str(r.get("metric", "")))


def _run_child(extra_argv, env, timeout):
    """One bench attempt in a fresh interpreter.  Returns (parsed JSON or
    None, reason string).  stderr passes through; stdout is captured so
    exactly one JSON line ever reaches the real stdout."""
    import subprocess
    cmd = [sys.executable, os.path.abspath(__file__)] + sys.argv[1:] + extra_argv
    try:
        proc = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                              timeout=timeout)
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode("utf-8", "replace") if e.stdout else ""
        return _parse_last_json(out), f"RUN_TIMEOUT after {timeout:.0f}s"
    out = proc.stdout.decode("utf-8", "replace")
    return _parse_last_json(out), f"rc={proc.returncode}"


def _probe_backend(timeout: float) -> bool:
    """Cheaply check that the backend comes up in a fresh interpreter
    before committing a full bench attempt to it.  ``jax.devices()`` is
    exactly the call that hangs when the axon tunnel is dead, so a tiny
    subprocess that only does that is a reliable, inexpensive liveness
    test — r03 burned its whole 240s init budget on two attempts against
    a tunnel that a 45s probe would have shown was down."""
    import subprocess
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0


class _BringupExhausted(RuntimeError):
    """Fatal-to-the-Supervisor: the bring-up budget or the attempt
    quota is gone — stop retrying and fall back to CPU."""


def supervise(config: str, device: str | None = None) -> int:
    """Backend bring-up routed through ``resilience.Supervisor``
    (ROADMAP Open item 3, honesty-gap half): the probe/backoff/retry
    loop that used to be hand-rolled here is now the SAME bounded-
    restart machinery the training tier survives preemption with —
    a dead tunnel probe raises ``ConnectionError`` (transient: backoff
    and retry), a failed child attempt likewise, and the partial result
    of every failed attempt is checkpointed so the final CPU fallback
    reports the best information available instead of nothing.  A flaky
    tunnel therefore yields a LATE REAL number (the Supervisor keeps
    probing inside the bring-up budget) instead of the five-rounds-
    running ``_CPU_FALLBACK`` label."""
    # Importing the package here is safe for the watchdog story: the
    # hang lives in first-touch backend init (jax.devices()), which this
    # parent process never calls — module import only registers the
    # backend lazily.
    from distributed_tensorflow_tpu.resilience import Supervisor

    attempts = int(os.environ.get("DTTPU_BENCH_TPU_ATTEMPTS", "4"))
    init_total = float(os.environ.get("DTTPU_BENCH_INIT_TIMEOUT", "240"))
    run_timeout = float(os.environ.get("DTTPU_BENCH_RUN_TIMEOUT", "900"))
    probe_timeout = float(os.environ.get("DTTPU_BENCH_PROBE_TIMEOUT", "45"))
    # Total wall-clock the supervisor may spend waiting for a dead tunnel
    # to come back (probe + sleep cycles) before giving up on the backend.
    # Default keeps worst case (budget + CPU-fallback run) inside the
    # ~25 min the driver demonstrably tolerated in r03's outage round.
    bringup_budget = float(os.environ.get("DTTPU_BENCH_BRINGUP_BUDGET",
                                          "600"))
    # Probing is pointless when the user pinned the device (no tunnel in
    # play) and must not run under the simulated-failure test hook (the
    # probe subprocess bypasses bench.py, so it would always pass).
    probing = (os.environ.get("DTTPU_BENCH_PROBE", "1") != "0"
               and not device
               and not os.environ.get("DTTPU_BENCH_TEST_FAIL_BELOW"))
    env = dict(os.environ, DTTPU_BENCH_CHILD="1")
    # Split the init budget across attempts: the hang is in first-touch
    # backend init, and a fresh process's second try often wins tunnel
    # flakes that a single long wait never recovers from.
    env["DTTPU_BENCH_INIT_TIMEOUT"] = str(max(60.0,
                                              init_total / max(1, attempts)))
    # mutable checkpoint across Supervisor restarts: the last parsed
    # (partial/failed) child JSON and the attempt counter
    state = {"deadline": time.monotonic() + bringup_budget,
             "last": None, "attempt": 0}

    def probe_session():
        """Supervisor's build_session: gate a full attempt behind the
        cheap liveness probe.  Probe failure -> transient
        ConnectionError (Supervisor backs off and rebuilds); budget or
        attempt exhaustion -> fatal _BringupExhausted (fall back)."""
        if state["attempt"] >= attempts:
            raise _BringupExhausted("backend attempts exhausted")
        if probing:
            remaining = state["deadline"] - time.monotonic()
            if remaining <= 0:
                raise _BringupExhausted(
                    f"bring-up budget ({bringup_budget:.0f}s) exhausted "
                    "while probing")
            t = min(probe_timeout, max(10.0, remaining))
            log(f"supervisor: probing backend ({t:.0f}s timeout)")
            if not _probe_backend(t):
                log("supervisor: probe failed (tunnel down?); backing "
                    "off for retry")
                raise ConnectionError("backend probe failed")
            log("supervisor: probe ok, committing a full attempt")
        return contextlib.nullcontext()

    def run_attempt(_session):
        i = state["attempt"]
        state["attempt"] = i + 1
        env["DTTPU_BENCH_ATTEMPT"] = str(i)
        log(f"supervisor: attempt {i + 1}/{attempts} "
            f"(init timeout {float(env['DTTPU_BENCH_INIT_TIMEOUT']):.0f}s)")
        t_child = time.monotonic()
        r, why = _run_child([], env, run_timeout)
        # The budget bounds probe+sleep waiting only — a full attempt's
        # runtime must not starve the remaining attempts.
        state["deadline"] += time.monotonic() - t_child
        if _result_ok(r):
            return r
        if r is not None:
            state["last"] = r       # checkpointed partial result
        log(f"supervisor: attempt {i + 1} failed ({why})")
        raise ConnectionError(f"bench attempt {i + 1} failed ({why})")

    def budgeted_sleep(seconds):
        """Backoff clamped to the remaining bring-up budget (looked up
        through the module so test monkeypatching applies)."""
        time.sleep(min(seconds,
                       max(0.0, state["deadline"] - time.monotonic())))

    sup = Supervisor(
        # the restart quota is enforced by probe_session (budget +
        # attempts), not by the Supervisor's own counter — give it
        # enough headroom that it never preempts those policies
        max_restarts=max(64, attempts * 16),
        backoff_base=15.0, backoff_factor=1.7, backoff_max=120.0,
        jitter=0.25, sleep=budgeted_sleep,
        classify=lambda e: ("transient" if isinstance(e, ConnectionError)
                            else "fatal"))
    try:
        r = sup.run(probe_session, run_attempt)
        print(json.dumps(r), flush=True)
        return 0
    except _BringupExhausted as e:
        log(f"supervisor: {e}")
    except ConnectionError:
        pass                        # restart budget truly gone
    last = state["last"]
    log("supervisor: backend attempts exhausted; "
        "measuring on single-device XLA:CPU (labeled _CPU_FALLBACK)")
    # ONE device, not the virtual 8-mesh: sharding a bench-sized batch over
    # 8 virtual CPU devices measures collective overhead, not the machine
    # (BENCH_r02's fallback lost to its own single-process torch baseline
    # exactly this way).  The multichip-shaped path is proven separately by
    # the dryrun and the mesh test suite; the fallback's one job is an
    # honest per-device liveness number.
    cenv = dict(env, DTTPU_BENCH_ATTEMPT="-1")
    # XLA:CPU and torch-MKL are a statistical tie on this workload
    # (measured 0.96-1.10 across identical runs); more best-of windows on
    # both sides tighten the ratio toward the true ~1.0.  Forced, not
    # setdefault: a process-wide export must not silently thin the
    # official outage-round record's sampling.
    cenv["DTTPU_BENCH_WINDOWS"] = "5"
    # The flag may also arrive FROM the environment (the test suite and CI
    # export it process-wide) — force it to 1 rather than merely not adding
    # it, or the child silently runs the 8-way mesh anyway.
    flags = [f for f in cenv.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count=1")
    cenv["XLA_FLAGS"] = " ".join(flags)
    if config != "mnist_mlp":
        # Full-size conv/transformer configs are too slow for a bounded CPU
        # run; the smoke-sized number is still nonzero and labeled.
        cenv["DTTPU_BENCH_SMOKE"] = "1"
    r, why = _run_child(["--device=cpu"], cenv, run_timeout)
    if _result_ok(r):
        r["metric"] = str(r["metric"]) + "_CPU_FALLBACK"
        r["fallback"] = "cpu"
        if cenv.get("DTTPU_BENCH_SMOKE"):
            # the number was measured on the shrunken smoke config — mark
            # it so it can't be misread as the full-size model on CPU
            r["config_size"] = "smoke"
        print(json.dumps(r), flush=True)
        return 0
    log(f"supervisor: CPU fallback failed too ({why})")
    print(json.dumps(last or dict(metric=config + "_BENCH_FAILED", value=0.0,
                                  unit="examples/sec/chip", vs_baseline=0.0)),
          flush=True)
    return 3


def _git_sha() -> str:
    """Code identity for the perf ledger: ``DTTPU_GIT_SHA`` when the
    driver exports it (detached workdirs), else ``git rev-parse`` of the
    bench's own checkout, else "unknown" — never an exception."""
    sha = os.environ.get("DTTPU_GIT_SHA")
    if sha:
        return sha
    try:
        import subprocess
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _backend_fingerprint() -> dict:
    """Backend/mesh identity for the perf ledger: rows from an 8-way
    virtual CPU mesh, a single CPU device, and a v4-8 must never be
    compared as if they were the same machine."""
    import jax
    devices = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_count": len(devices),
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "process_count": jax.process_count(),
    }


def _stamp_identity(result: dict, config: str) -> dict:
    """Stamp the JSON line with run identity (obs/ledger.py schema):
    anonymous rows can only be compared by filename convention."""
    import uuid
    from distributed_tensorflow_tpu.obs import ledger as ledger_lib
    result["schema_version"] = ledger_lib.SCHEMA_VERSION
    result["run_id"] = uuid.uuid4().hex[:16]
    result["git_sha"] = _git_sha()
    result["config"] = config
    result["timestamp"] = round(time.time(), 3)
    result["fingerprint"] = _backend_fingerprint()
    return result


def main():
    _load_promoted_defaults()
    config = "mnist_mlp"
    device = os.environ.get("DTTPU_BENCH_DEVICE")
    for arg in sys.argv[1:]:
        if arg.startswith("--device="):
            device = arg.split("=", 1)[1]
            continue
        config = arg.split("=", 1)[1] if arg.startswith("--config=") else arg
    if config not in CONFIGS:
        log(f"unknown config {config!r}; choices: {sorted(CONFIGS)}")
        sys.exit(2)

    if (not os.environ.get("DTTPU_BENCH_CHILD")
            and not os.environ.get("DTTPU_BENCH_NO_SUPERVISOR")):
        sys.exit(supervise(config, device))

    # Test hook: simulate a dead tunnel for supervisor tests.  Fails TPU
    # attempts (attempt >= 0) below the threshold; the CPU fallback child
    # runs with attempt=-1 and is never failed.
    fail_below = int(os.environ.get("DTTPU_BENCH_TEST_FAIL_BELOW", "0"))
    attempt = int(os.environ.get("DTTPU_BENCH_ATTEMPT", "-1"))
    if fail_below and 0 <= attempt < fail_below:
        log("test hook: simulated backend failure")
        sys.exit(7)

    if device:
        # The axon sitecustomize force-selects the TPU platform at the
        # config level, so an env var alone cannot redirect to CPU.
        import jax
        jax.config.update("jax_platforms", device)

    # The axon TPU tunnel can hang indefinitely (even jax.devices() blocks).
    # A hung bench leaves the driver with nothing; emit a failure JSON line
    # instead if the backend doesn't come up within the timeout.  (The
    # supervisor treats that line as a failed attempt and retries.)
    import threading
    ready = threading.Event()
    timeout_s = float(os.environ.get("DTTPU_BENCH_INIT_TIMEOUT", "240"))
    # Exactly ONE JSON line may reach stdout: the watchdog and the main
    # thread race for this flag; the loser stays silent.
    report_lock = threading.Lock()
    claimed = [False]

    def claim_report() -> bool:
        with report_lock:
            if claimed[0]:
                return False
            claimed[0] = True
            return True

    def watchdog():
        if not ready.wait(timeout_s) and claim_report():
            log(f"backend init exceeded {timeout_s:.0f}s (tunnel hung?)")
            print(json.dumps(dict(
                metric=config + "_BACKEND_INIT_TIMEOUT", value=0.0,
                unit="examples/sec/chip", vs_baseline=0.0)), flush=True)
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    import jax
    n = len(jax.devices())   # blocks here when the tunnel is hung
    ready.set()
    log(f"backend up: {n} device(s)")
    # Warn-only retrace sanitizer (analysis/sanitizer.py): every jit built
    # during the bench gets a trace budget, so a rate that was silently
    # dominated by recompiles arrives annotated instead of trusted.  The
    # budget default leaves room for the batch ladder's legitimate
    # shape-driven retraces (one lower() + one call per rung); warnings
    # go to stderr with an arg-diff, and the JSON line carries the count.
    # Telemetry tracer: active for the whole measurement so _time_steps'
    # dispatch spans AND the sanitizer's jit_compile/retrace instants land
    # on one host timeline, written next to the JSON line as `trace_file`.
    tracer = None
    if TELEMETRY:
        from distributed_tensorflow_tpu.obs import trace as obs_trace
        tracer = obs_trace.activate(obs_trace.Tracer(enabled=True))
    if os.environ.get("DTTPU_BENCH_SANITIZE", "1") != "0":
        from distributed_tensorflow_tpu.analysis.sanitizer import RetraceGuard
        budget = int(os.environ.get("DTTPU_BENCH_RETRACE_BUDGET", "6"))
        with RetraceGuard(budget=budget, mode="warn",
                          enforce_donation=False) as guard:
            result = CONFIGS[config]()
        if guard.violations:
            result["retrace_warnings"] = len(guard.violations)
    else:
        result = CONFIGS[config]()
    if _STEP_TIMES:
        # barrier-closed per-update host latencies (see _time_steps);
        # decode configs time whole generate() calls instead and carry
        # no step-time fields
        ts = sorted(_STEP_TIMES)
        result["step_time_p50_ms"] = round(ts[int(0.50 * (len(ts) - 1))]
                                           * 1e3, 3)
        result["step_time_p95_ms"] = round(ts[int(0.95 * (len(ts) - 1))]
                                           * 1e3, 3)
    if tracer is not None:
        import tempfile
        path = os.environ.get("DTTPU_BENCH_TRACE_FILE") or os.path.join(
            tempfile.gettempdir(), f"dttpu-bench-{config}-trace.json")
        try:
            result["trace_file"] = tracer.save(path)
        except OSError as e:
            log(f"could not write trace file {path}: {e}")
    _stamp_identity(result, config)
    ledger_path = os.environ.get("DTTPU_BENCH_LEDGER")
    if ledger_path:
        # opt-in (CI sets it): a default repo path would dirty every
        # test run's working tree with measurement rows
        try:
            from distributed_tensorflow_tpu.obs import ledger as ledger_lib
            ledger_lib.PerfLedger(ledger_path).append(
                ledger_lib.row_from_bench(result))
            log(f"ledger: appended {config} row to {ledger_path}")
        except Exception as e:
            log(f"ledger append failed ({e}); JSON line still printed")
    if claim_report():
        print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
