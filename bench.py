"""Benchmark harness — prints ONE JSON line to stdout.

Metric (per BASELINE.md): MNIST-MLP training examples/sec/chip, measured on
the framework's compiled data-parallel train step on whatever devices are
available (the real TPU chip under the driver; the virtual CPU mesh in
tests), plus a convergence gate (final eval accuracy must clear 0.9 on the
synthetic set or the result is reported as failed).

``vs_baseline``: the reference publishes no numbers (BASELINE.md:
"published: {}"), so the baseline is a measured stand-in for its
CPU/GPU-era stack: the SAME model/batch/optimizer stepped with torch on CPU
(the reference's TF-1.4 path is unrunnable here).  When torch is
unavailable the documented fallback constant is used.  Everything except
the JSON line goes to stderr.
"""
import json
import sys
import time

# Estimated examples/sec for the reference-era stack on a single CPU host —
# used only if the live torch baseline cannot run.
FALLBACK_BASELINE = 1.0e5

BATCH = 8192
WARMUP = 5
STEPS = 60


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_framework():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu import data, models, optim, parallel, train

    n_chips = len(jax.devices())
    mesh = parallel.data_parallel_mesh()
    log(f"framework: {n_chips} x {jax.devices()[0].platform}, "
        f"mesh={dict(mesh.shape)}")

    (xt, yt), (xv, yv) = data.mnist(flatten=True)
    model = models.mnist_mlp()
    optimizer = optim.adam()
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, mesh=mesh)
    eval_step = train.make_eval_step(model, "sparse_categorical_crossentropy",
                                     metric_fns={"accuracy": "accuracy"})
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = jax.device_put(state, NamedSharding(mesh, P()))
    bsh = NamedSharding(mesh, P("data"))

    batch = parallel.round_batch_to_mesh(BATCH, mesh)
    # backend="auto": the native C++ threaded gather loader when built.
    ds = data.Dataset([xt, yt], batch, seed=0, backend="auto")

    # Convergence gate: a couple of epochs must clear 0.9 eval accuracy.
    for b in ds.epochs(2):
        state, _ = step(state, jax.device_put(b, bsh))
    acc = float(eval_step(state, (xv[:8192], yv[:8192]))["accuracy"])
    log(f"eval accuracy after 2 epochs: {acc:.4f}")

    # Throughput: fixed resident batch, async dispatch, block at the end.
    bench_batch = jax.device_put(next(iter(ds)), bsh)
    for _ in range(WARMUP):
        state, m = step(state, bench_batch)
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, m = step(state, bench_batch)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    eps = STEPS * batch / dt
    log(f"framework: {eps:,.0f} examples/s total, "
        f"{eps / n_chips:,.0f} /chip ({dt / STEPS * 1e3:.2f} ms/step)")
    return eps / n_chips, acc


def bench_torch_baseline():
    """Same MLP/batch/optimizer stepped with torch on CPU (reference-era
    proxy: host-resident training, no XLA)."""
    try:
        import torch
        import torch.nn as nn
    except Exception as e:  # pragma: no cover
        log(f"torch baseline unavailable ({e}); using fallback constant")
        return None
    torch.manual_seed(0)
    torch.set_num_threads(max(1, (torch.get_num_threads())))
    model = nn.Sequential(nn.Linear(784, 128), nn.ReLU(), nn.Dropout(0.2),
                          nn.Linear(128, 10))
    opt = torch.optim.Adam(model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    x = torch.rand(BATCH, 784)
    y = torch.randint(0, 10, (BATCH,))
    for _ in range(3):  # warmup
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
    steps = 15
    t0 = time.perf_counter()
    for _ in range(steps):
        opt.zero_grad(); loss_fn(model(x), y).backward(); opt.step()
    dt = time.perf_counter() - t0
    eps = steps * BATCH / dt
    log(f"torch CPU baseline: {eps:,.0f} examples/s")
    return eps


def main():
    value, acc = bench_framework()
    baseline = bench_torch_baseline()
    if baseline is None:
        baseline = FALLBACK_BASELINE
    converged = acc > 0.9
    result = {
        "metric": "mnist_mlp_train_examples_per_sec_per_chip"
                  + ("" if converged else "_NOT_CONVERGED"),
        "value": round(value, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(value / baseline, 3),
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
