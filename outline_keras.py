"""CIFAR-10 CNN entrypoint (high-level tier) — BASELINE config #3.

The reference ships this file EMPTY (0 bytes, SURVEY.md §2a #16); per the
driver's north star it becomes the Keras-style CNN entrypoint:
``Sequential``/``compile``/``fit`` over the small conv net, data-parallel
across all chips via the mesh argument to ``compile`` — the high-level user
never touches a collective.

Run: python outline_keras.py [--device=tpu] [--epochs=N] [--data_dir=...]
Real CIFAR-10 files in --data_dir are used when present; otherwise the
learnable synthetic stand-in (zero-egress default).
"""
import os
import sys
from time import time

from distributed_tensorflow_tpu.utils import flags as flags_lib
from distributed_tensorflow_tpu.utils.flags import FLAGS

flags_lib.DEFINE_string("device", "", "Force a JAX platform; empty = default")
flags_lib.DEFINE_string("data_dir", os.environ.get("DATA_DIR", ""),
                        "Directory with CIFAR-10 files")
flags_lib.DEFINE_string("log_dir",
                        os.environ.get("LOG_DIR",
                                       os.path.join("logs", "cifar_{}")),
                        "TensorBoard directory; '{}' gets a timestamp")
flags_lib.DEFINE_integer("epochs", 10, "Training epochs")
flags_lib.DEFINE_integer("batch_size", 256, "Global batch size")
flags_lib.DEFINE_integer("steps_per_execution", 1,
                         "Optimizer updates per compiled dispatch (K>1 "
                         "amortizes host->device latency for small models)")
flags_lib.DEFINE_integer("seed", 0, "PRNG seed")


def main() -> int:
    FLAGS.parse()
    if FLAGS.device:
        import jax
        jax.config.update("jax_platforms", FLAGS.device)

    from distributed_tensorflow_tpu.parallel import cluster
    cluster.initialize()

    import jax

    from distributed_tensorflow_tpu import data, models, parallel

    mesh = parallel.data_parallel_mesh()
    print(f"devices: {len(jax.devices())} ({jax.devices()[0].platform}), "
          f"mesh={dict(mesh.shape)}", file=sys.stderr)

    (x_train, y_train), (x_val, y_val) = data.cifar10(FLAGS.data_dir or None,
                                                      seed=FLAGS.seed)

    model = models.Sequential(models.cifar_cnn().layers, name="cifar_cnn")
    model.compile(loss="sparse_categorical_crossentropy", optimizer="adam",
                  metrics=["accuracy"], mesh=mesh, seed=FLAGS.seed,
                  steps_per_execution=FLAGS.steps_per_execution)

    tensorboard = models.TensorBoard(log_dir=FLAGS.log_dir.format(time()))
    # Standard CIFAR recipe: pad-reflect crop + horizontal flip, host-side,
    # overlapped with device compute by the prefetch queue.
    train_augment = data.augment.compose(data.augment.random_crop(4),
                                         data.augment.random_flip_lr())
    model.fit(x_train, y_train, epochs=FLAGS.epochs,
              batch_size=FLAGS.batch_size,
              validation_data=(x_val[:4096], y_val[:4096]),
              callbacks=[tensorboard], seed=FLAGS.seed,
              augment=train_augment)

    final = model.evaluate(x_val, y_val, batch_size=FLAGS.batch_size,
                           verbose=0)
    print(f"Final validation accuracy: {final['accuracy']:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
