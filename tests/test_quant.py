"""Weight-only int8 quantization tests."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import data, models, ops
from distributed_tensorflow_tpu.ops import quant


def test_quantize_roundtrip_error_bound():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 3.0
    t = quant.quantize_tensor(w)
    assert t.q.dtype == jnp.int8
    assert t.scale.shape == (1, 32)     # per-output-channel
    back = quant.dequantize_tensor(t)
    # symmetric rounding error <= scale/2 per element
    err = np.abs(np.asarray(back) - np.asarray(w))
    bound = np.asarray(t.scale) / 2 + 1e-6
    assert (err <= bound).all()
    # per-tensor mode
    t2 = quant.quantize_tensor(w, reduce_axes=None)
    assert t2.scale.shape == ()


def test_quantize_tree_selectivity():
    params = {"dense": {"kernel": jnp.ones((64, 64)),
                        "bias": jnp.ones((64,))},
              "small": jnp.ones((4, 4))}
    qt = quant.quantize_tree(params, min_size=1024)
    assert isinstance(qt["dense"]["kernel"], quant.QTensor)
    assert not isinstance(qt["dense"]["bias"], quant.QTensor)   # 1-D
    assert not isinstance(qt["small"], quant.QTensor)           # tiny
    back = quant.dequantize_tree(qt)
    assert back["dense"]["kernel"].shape == (64, 64)
    # ~4x smaller for the quantized leaf (int8 + small scale vs f32)
    q_bytes = quant.quantized_bytes(qt["dense"]["kernel"])
    f_bytes = quant.quantized_bytes(params["dense"]["kernel"])
    assert q_bytes < f_bytes / 3.5


def test_quantized_model_accuracy_preserved():
    """A trained XOR model predicts (nearly) identically from int8
    weights — weight-only quantization is a serving drop-in."""
    (xt, yt), (xv, yv) = data.xor_data(600, val_size=128, seed=0)
    model = models.Sequential([ops.Dense(64, "relu"),
                               ops.Dense(32, "sigmoid")])
    model.compile(loss="mse", optimizer="adam",
                  metrics=["bitwise_accuracy"])
    model.fit(xt, yt, epochs=10, batch_size=50, verbose=0)
    full = model.evaluate(xv, yv, verbose=0)["bitwise_accuracy"]

    qparams = quant.quantize_tree(model.state.params, min_size=512)
    deq = quant.dequantize_tree(qparams)
    preds_q = jax.jit(lambda p, x: model.stack.apply(p, {}, x)[0])(
        deq, jnp.asarray(xv))
    acc_q = float(jnp.mean((jnp.round(preds_q) ==
                            jnp.round(jnp.asarray(yv))).astype(jnp.float32)))
    assert acc_q >= full - 0.02          # <= 2 points of bitwise accuracy


def test_quantized_tree_checkpoints(tmp_path):
    """QTensor trees ride the existing checkpoint machinery (4x smaller
    on disk for the quantized leaves)."""
    from distributed_tensorflow_tpu.train import checkpoint as ck
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (256, 256))}
    qt = quant.quantize_tree(params, min_size=64)
    path = ck.save(str(tmp_path / "q"), 0, {"params": qt})
    restored = ck.restore({"params": qt}, path)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"].q),
                                  np.asarray(qt["w"].q))
    np.testing.assert_allclose(np.asarray(restored["params"]["w"].scale),
                               np.asarray(qt["w"].scale))


def test_quantize_tree_idempotent():
    """Re-quantizing an already-quantized tree (e.g. a serving-prep script
    re-run on a restored quantized checkpoint) is a no-op, not corruption."""
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (2048, 2048))}
    once = quant.quantize_tree(params, min_size=64)
    twice = quant.quantize_tree(once, min_size=64)
    assert isinstance(twice["w"], quant.QTensor)
    assert not isinstance(twice["w"].scale, quant.QTensor)
    np.testing.assert_array_equal(np.asarray(once["w"].q),
                                  np.asarray(twice["w"].q))
    quant.dequantize_tree(twice)   # still dequantizes cleanly


def test_stacked_kernels_get_per_layer_scales():
    """Scanned model families stack kernels on a leading L axis; each
    layer slice (and head) must quantize against ITS OWN max, not the
    stack-wide one."""
    k1 = jax.random.normal(jax.random.PRNGKey(0), (16, 32)) * 0.1
    k2 = jax.random.normal(jax.random.PRNGKey(1), (16, 32)) * 10.0
    stacked = jnp.stack([k1, k2])                      # [L=2, in, out]
    t = quant.quantize_tensor(stacked)       # auto: keep first+last axes
    assert t.scale.shape == (2, 1, 32)
    # layer 0's scale reflects its own small range, ~100x below layer 1's
    s0 = float(np.asarray(t.scale)[0].max())
    s1 = float(np.asarray(t.scale)[1].max())
    assert s1 / s0 > 20
    # per-slice rounding error bound holds for the SMALL layer too
    back = quant.dequantize_tensor(t)
    err0 = np.abs(np.asarray(back)[0] - np.asarray(stacked)[0])
    assert (err0 <= np.asarray(t.scale)[0] / 2 + 1e-6).all()


def test_quantized_gpt_generates():
    """4-D attention kernels ([L, d, h, hd]) quantize per layer/head and
    the quantized model still generates identically-shaped output."""
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny
    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    qk = quant.quantize_tree(params, min_size=512)
    qkv = qk["decoder"]["attention"]["query"]["kernel"]
    # [L, d, h, hd] kernel: per-layer + per-hd-channel scales, d/h reduced
    assert isinstance(qkv, quant.QTensor)
    L, d, h, hd = qkv.q.shape
    assert qkv.scale.shape == (L, 1, 1, hd)
    assert L == g.config.num_layers
    deq = quant.dequantize_tree(qk)
    out = g.generate(deq, jnp.ones((1, 3), jnp.int32), max_new_tokens=4)
    assert out.shape == (1, 7)


def test_vector_quantization_gets_whole_tensor_scale():
    """1-D inputs through the public API must not get degenerate
    per-element scales (which would be bigger than the f32 input)."""
    v = jax.random.normal(jax.random.PRNGKey(0), (128,))
    t = quant.quantize_tensor(v)
    assert t.scale.shape == ()
    back = quant.dequantize_tensor(t)
    err = np.abs(np.asarray(back) - np.asarray(v))
    assert (err <= float(t.scale) / 2 + 1e-6).all()


def test_quantized_beam_search_with_ragged_prompts():
    """Three subsystems composed: int8 weight-only quantization feeding
    KV-cache beam search over LEFT-padded ragged prompts.  The quantized
    beams must be valid token ids with the ragged contract intact, and
    (same weights in, deterministic search) reproducible."""
    import numpy as np
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    deq = quant.dequantize_tree(quant.quantize_tree(params, min_size=512))
    prompts = jnp.asarray([[0, 0, 5, 7], [1, 2, 3, 4]], jnp.int32)
    valid = jnp.asarray([[0, 0, 1, 1], [1, 1, 1, 1]], jnp.int32)
    out1 = g.beam_search(deq, prompts, max_new_tokens=5, beam_size=2,
                         prompt_valid=valid)
    out2 = g.beam_search(deq, prompts, max_new_tokens=5, beam_size=2,
                         prompt_valid=valid)
    assert out1.shape == (2, 9)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert int(out1.max()) < g.config.vocab_size and int(out1.min()) >= 0
    # the fp search on the SAME rounding-free path stays close: beams may
    # diverge token-wise under rounding, but both must be valid searches
    fp = g.beam_search(params, prompts, max_new_tokens=5, beam_size=2,
                       prompt_valid=valid)
    assert fp.shape == out1.shape
