"""bench.py harness tests: supervisor retry/fallback, JSON contract,
dataset provenance labeling, OOM classification, FLOP accounting.

The reference has no benchmark harness at all (BASELINE.md: "published:
{}"); bench.py is the driver-facing measurement artifact, so its failure
handling is tested as first-class behavior — round 1 shipped a 0.0 because
a tunnel hang had no retry path.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import bench
from distributed_tensorflow_tpu import data

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _env(**extra):
    env = dict(os.environ)
    env.update({"DTTPU_BENCH_SMOKE": "1",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
               **{k: str(v) for k, v in extra.items()})
    return env


def _run(args, env, timeout=600):
    proc = subprocess.run([sys.executable, BENCH] + args, env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          timeout=timeout, cwd=REPO)
    return proc


class TestSupervisor:
    def test_smoke_run_single_json_line(self):
        """A working backend (user-requested CPU) succeeds on attempt 1;
        stdout carries exactly one JSON line with the full field contract."""
        proc = _run(["--device=cpu"], _env())
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1, lines
        r = json.loads(lines[0])
        assert r["value"] > 0
        assert r["metric"].startswith("mnist_mlp_train_examples_per_sec")
        assert "_CPU_FALLBACK" not in r["metric"]  # user asked for cpu
        assert r["data"] == "synthetic"
        assert r["unit"] == "examples/sec/chip"
        assert r["vs_baseline"] > 0
        # XLA:CPU reports flops, so the FLOP accounting fields must appear.
        assert r.get("flops_per_example", 0) > 0
        # telemetry fields (default-on): barrier-closed per-update
        # latency percentiles + the host-timeline trace file, whose
        # dispatch spans and jit_compile instants must parse as Chrome
        # trace JSON (docs/OBSERVABILITY.md)
        assert r["step_time_p50_ms"] > 0
        assert r["step_time_p95_ms"] >= r["step_time_p50_ms"]
        assert os.path.exists(r["trace_file"])
        trace = json.load(open(r["trace_file"]))
        names = {e["name"] for e in trace["traceEvents"]}
        assert "dispatch" in names and "jit_compile" in names

    def test_telemetry_off_drops_fields(self):
        """DTTPU_BENCH_TELEMETRY=0: no trace file, no latency fields —
        the schema change is strictly opt-out."""
        proc = _run(["--device=cpu"], _env(DTTPU_BENCH_TELEMETRY=0))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        r = json.loads(proc.stdout.decode().strip().splitlines()[-1])
        assert r["value"] > 0
        assert "step_time_p50_ms" not in r
        assert "step_time_p95_ms" not in r
        assert "trace_file" not in r

    def test_dead_backend_falls_back_to_cpu_with_label(self):
        """Both simulated-TPU attempts die -> supervisor measures on the
        CPU mesh and labels the metric honestly."""
        proc = _run([], _env(DTTPU_BENCH_TEST_FAIL_BELOW=5,
                             DTTPU_BENCH_TPU_ATTEMPTS=2))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1
        r = json.loads(lines[0])
        assert r["metric"].endswith("_CPU_FALLBACK")
        assert r["fallback"] == "cpu"
        assert r["value"] > 0
        err = proc.stderr.decode()
        assert "attempt 1" in err and "attempt 2" in err

    def test_retry_wins_on_second_attempt(self):
        """Attempt 0 dies, attempt 1 succeeds -> no fallback label: the
        fresh-subprocess retry is what recovers tunnel flakes."""
        proc = _run(["--device=cpu"], _env(DTTPU_BENCH_TEST_FAIL_BELOW=1,
                                           DTTPU_BENCH_TPU_ATTEMPTS=2))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        r = json.loads(proc.stdout.decode().strip().splitlines()[-1])
        assert "_CPU_FALLBACK" not in r["metric"]
        assert r["value"] > 0


class TestSupervisorProbe:
    """In-process tests of the probe-gated bring-up loop (the subprocess
    tier covers the no-probe paths; these cover the budget bookkeeping)."""

    def _supervise(self, monkeypatch, capsys, probe_results, child_results,
                   budget="30", attempts="4"):
        calls = {"probe": 0, "child": 0}

        def fake_probe(timeout):
            i = min(calls["probe"], len(probe_results) - 1)
            calls["probe"] += 1
            return probe_results[i]

        def fake_child(extra_argv, env, timeout):
            i = min(calls["child"], len(child_results) - 1)
            calls["child"] += 1
            return child_results[i]

        monkeypatch.setattr(bench, "_probe_backend", fake_probe)
        monkeypatch.setattr(bench, "_run_child", fake_child)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.setenv("DTTPU_BENCH_BRINGUP_BUDGET", budget)
        monkeypatch.setenv("DTTPU_BENCH_TPU_ATTEMPTS", attempts)
        monkeypatch.delenv("DTTPU_BENCH_TEST_FAIL_BELOW", raising=False)
        monkeypatch.delenv("DTTPU_BENCH_PROBE", raising=False)
        rc = bench.supervise("mnist_mlp")
        out = capsys.readouterr().out.strip().splitlines()
        return rc, json.loads(out[-1]), calls

    def test_probe_pass_commits_attempt(self, monkeypatch, capsys):
        ok = {"metric": "m", "value": 5.0, "vs_baseline": 1.2}
        rc, r, calls = self._supervise(monkeypatch, capsys,
                                       [True], [(ok, "rc=0")])
        assert rc == 0 and r["value"] == 5.0
        assert calls == {"probe": 1, "child": 1}

    def test_probe_failures_retry_then_recover(self, monkeypatch, capsys):
        ok = {"metric": "m", "value": 5.0, "vs_baseline": 1.2}
        rc, r, calls = self._supervise(monkeypatch, capsys,
                                       [False, False, True],
                                       [(ok, "rc=0")])
        assert rc == 0 and r["value"] == 5.0
        assert calls["probe"] == 3 and calls["child"] == 1

    def test_budget_exhausted_falls_back(self, monkeypatch, capsys):
        """Probe never passes -> no full attempt is ever spent; the CPU
        fallback child (which runs without probing) is the one report."""
        fb = {"metric": "m", "value": 3.0, "vs_baseline": 1.0}
        rc, r, calls = self._supervise(
            monkeypatch, capsys, [False], [(fb, "rc=0")],
            # time.sleep is stubbed, so only probe-time consumes budget;
            # zero budget exhausts immediately
            budget="0")
        assert rc == 0
        assert r["metric"].endswith("_CPU_FALLBACK")
        assert calls["child"] == 1  # the fallback child only

    def test_child_runtime_excluded_from_budget(self, monkeypatch, capsys):
        """A slow failing attempt must not eat the probe budget: with a
        tiny budget and a child that 'takes' long, the supervisor still
        probes again for attempt 2."""
        ok = {"metric": "m", "value": 5.0, "vs_baseline": 1.2}

        t = [0.0]
        monkeypatch.setattr(bench.time, "monotonic", lambda: t[0])

        def slow_fail_child(extra_argv, env, timeout):
            t[0] += 100.0   # simulated 100 s child vs 30 s budget
            return None, "rc=7"

        calls = {"probe": 0}

        def fake_probe(timeout):
            calls["probe"] += 1
            return True

        seq = [slow_fail_child,
               lambda *a: ({"metric": "m", "value": 5.0,
                            "vs_baseline": 1.2}, "rc=0")]

        def child(extra_argv, env, timeout):
            return seq.pop(0)(extra_argv, env, timeout)

        monkeypatch.setattr(bench, "_probe_backend", fake_probe)
        monkeypatch.setattr(bench, "_run_child", child)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        monkeypatch.setenv("DTTPU_BENCH_BRINGUP_BUDGET", "30")
        monkeypatch.setenv("DTTPU_BENCH_TPU_ATTEMPTS", "4")
        monkeypatch.delenv("DTTPU_BENCH_TEST_FAIL_BELOW", raising=False)
        rc = bench.supervise("mnist_mlp")
        out = capsys.readouterr().out.strip().splitlines()
        r = json.loads(out[-1])
        assert rc == 0 and r["value"] == 5.0
        assert calls["probe"] == 2  # probed again after the 100s child


class TestPromoteLevers:
    """scripts/promote_levers.py selection rule: a PURE lever arm must
    beat base by >= 2% measured tokens/sec to become a bench default."""

    def _promote(self, rows):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import promote_levers
        finally:
            sys.path.pop(0)
        return promote_levers.promote(rows)

    def test_winning_levers_promote(self):
        rows = [
            {"model": "gpt", "arm": "base", "tokens_per_sec": 100.0},
            {"model": "gpt", "arm": "loss_chunk", "tokens_per_sec": 110.0},
            {"model": "gpt", "arm": "remat_dots", "tokens_per_sec": 101.0},
            {"model": "bert", "arm": "base", "tokens_per_sec": 200.0},
            {"model": "bert", "arm": "mlm_gather", "tokens_per_sec": 230.0},
        ]
        env, evidence = self._promote(rows)
        # loss_chunk (+10%) and mlm_gather (+15%) promote; remat_dots
        # (+1%, under the 2% bar) does not
        assert env == {"DTTPU_BENCH_LOSS_CHUNK": "512",
                       "DTTPU_BENCH_MLM_GATHER": "1"}
        assert {e["model"] for e in evidence} == {"gpt", "bert"}

    def test_bert_remat_dots_promotes(self):
        # the 08-01 hardware table's shape: bert remat_dots is a pure
        # +12% lever and must map onto DTTPU_BENCH_BERT_REMAT
        rows = [
            {"model": "bert", "arm": "base", "tokens_per_sec": 131123.0},
            {"model": "bert", "arm": "remat_dots",
             "tokens_per_sec": 147351.0},
        ]
        env, _ = self._promote(rows)
        assert env == {"DTTPU_BENCH_BERT_REMAT": "dots"}

    def test_composite_arms_never_promote(self):
        # a composite arm can WIN the table without promoting env levers:
        # its batch move has no env knob
        rows = [
            {"model": "gpt", "arm": "base", "tokens_per_sec": 100.0},
            {"model": "gpt", "arm": "loss_chunk_b192",
             "tokens_per_sec": 150.0},
        ]
        env, evidence = self._promote(rows)
        assert env == {}
        assert evidence[0]["best"]["arm"] == "loss_chunk_b192"

    def test_no_base_row_promotes_nothing(self):
        rows = [{"model": "gpt", "arm": "loss_chunk",
                 "tokens_per_sec": 1e9}]
        env, _ = self._promote(rows)
        assert env == {}

    def test_parse_rejects_smoke_and_cpu_rows(self):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import promote_levers
        finally:
            sys.path.pop(0)
        mk = lambda **kw: json.dumps(dict(
            model="gpt", arm="base", tokens_per_sec=1.0, **kw))
        lines = [mk(backend="cpu", smoke=True),
                 mk(backend="cpu", smoke=False),
                 mk(backend="tpu", smoke=True),
                 mk(backend="tpu", smoke=False)]
        assert len(promote_levers.parse(lines)) == 1
        assert len(promote_levers.parse(lines, allow_any=True)) == 4


class TestPromotedDefaults:
    """bench._load_promoted_defaults: PROMOTED.json is a DEFAULT layer —
    explicit env wins, SMOKE runs ignore it, absence is silent."""

    def test_setdefault_env_wins_and_smoke_skips(self, monkeypatch,
                                                 tmp_path):
        f = tmp_path / "PROMOTED.json"
        f.write_text(json.dumps(
            {"env": {"DTTPU_TEST_PROMOTED_KNOB": "5"}}))
        monkeypatch.setattr(bench, "_PROMOTED", str(f))
        monkeypatch.setattr(bench, "SMOKE", False)
        # seed-then-delete so monkeypatch records an undo for the key —
        # _load_promoted_defaults writes os.environ directly, and an
        # unrecorded setdefault would leak past teardown
        monkeypatch.setenv("DTTPU_TEST_PROMOTED_KNOB", "seed")
        monkeypatch.delenv("DTTPU_TEST_PROMOTED_KNOB")
        bench._load_promoted_defaults()
        assert os.environ["DTTPU_TEST_PROMOTED_KNOB"] == "5"
        monkeypatch.setenv("DTTPU_TEST_PROMOTED_KNOB", "9")
        bench._load_promoted_defaults()
        assert os.environ["DTTPU_TEST_PROMOTED_KNOB"] == "9"
        monkeypatch.delenv("DTTPU_TEST_PROMOTED_KNOB")
        monkeypatch.setattr(bench, "SMOKE", True)
        bench._load_promoted_defaults()
        assert "DTTPU_TEST_PROMOTED_KNOB" not in os.environ

    def test_absent_and_corrupt_files_are_tolerated(self, monkeypatch,
                                                    tmp_path):
        monkeypatch.setattr(bench, "SMOKE", False)
        monkeypatch.setattr(bench, "_PROMOTED",
                            str(tmp_path / "missing.json"))
        bench._load_promoted_defaults()          # no raise
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        monkeypatch.setattr(bench, "_PROMOTED", str(bad))
        bench._load_promoted_defaults()          # warns, no raise


class TestRecovery:
    """bench.py --config=recovery: the resilience smoke's JSON contract
    (docs/RESILIENCE.md).  Run in-process — the row is tiny by design
    (XOR MLP) and a subprocess would mostly measure jax import time."""

    def test_recovery_schema_and_one_injected_kill(self):
        result = bench.bench_recovery()
        assert result["metric"] == "recovery_restore_ms"
        assert result["unit"] == "ms"
        assert result["value"] > 0
        assert result["restore_ms"] == result["value"]
        # the kill lands between two save intervals: 0 < lost <= interval
        assert 0 <= result["recovery_steps_lost"] <= 5
        assert result["restarts"] >= 1
        assert result["faults_injected"] == 1
        assert result["final_step"] == 24
        # watchdog smoke: the injected stall (1.0 s) was detected at the
        # first post-stall check — detection latency sits just above the
        # stall itself, never an unbounded wait — and the quarantined
        # replica's requests migrated to the survivor
        assert result["watchdog_quarantined"] == 1
        assert result["watchdog_detect_ms"] is not None
        stall_ms = result["watchdog_stall_s"] * 1e3
        assert stall_ms < result["watchdog_detect_ms"] < stall_ms + 5e3
        assert result["watchdog_migrations"] >= 1
        # goodput ledger satellite: the recovery row carries the full
        # wall-clock split, and the buckets sum to wall within 1%
        gp = result["goodput"]
        assert result["goodput_pct"] == gp["goodput_pct"]
        assert 0.0 < gp["goodput_pct"] <= 100.0
        assert sum(gp["buckets_s"].values()) == pytest.approx(
            gp["wall_s"], rel=0.01)
        for bucket in ("step", "checkpoint_save", "checkpoint_restore",
                       "restart_backoff", "fault_recovery"):
            assert gp["buckets_s"][bucket] > 0.0, (bucket, gp)
        json.dumps(result)                      # one-line-JSON safe


class TestIdentityStamp:
    """Every bench line carries run identity (obs/ledger.py schema):
    run_id, git_sha, backend/mesh fingerprint — anonymous rows can only
    be compared by filename convention."""

    def test_stamp_identity_fields(self):
        from distributed_tensorflow_tpu.obs import ledger as ledger_lib
        r = bench._stamp_identity({"value": 1.0}, "mnist_mlp")
        assert r["schema_version"] == ledger_lib.SCHEMA_VERSION
        assert len(r["run_id"]) == 16
        assert r["config"] == "mnist_mlp"
        assert r["timestamp"] > 0
        fp = r["fingerprint"]
        assert fp["backend"] == "cpu"
        assert fp["device_count"] >= 1
        assert fp["process_count"] >= 1
        assert "device_kind" in fp
        # two runs never share a run_id
        r2 = bench._stamp_identity({"value": 1.0}, "mnist_mlp")
        assert r2["run_id"] != r["run_id"]
        # a stamped line is directly convertible to a ledger row
        ledger_lib.validate_row(ledger_lib.row_from_bench(r))

    def test_git_sha_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("DTTPU_GIT_SHA", "cafe1234babe")
        assert bench._git_sha() == "cafe1234babe"
        monkeypatch.delenv("DTTPU_GIT_SHA")
        sha = bench._git_sha()       # this repo IS a git checkout
        assert sha and sha != "unknown" and "\n" not in sha

    @pytest.mark.slow
    def test_smoke_line_is_stamped_and_ledgered(self, tmp_path):
        """Subprocess contract: the stamps survive the supervise()
        parent re-dump, and DTTPU_BENCH_LEDGER appends one valid row.
        A full bench subprocess, so slow-tier like the other smokes."""
        from distributed_tensorflow_tpu.obs import ledger as ledger_lib
        ledger_path = str(tmp_path / "ledger.jsonl")
        proc = _run(["--device=cpu"],
                    _env(DTTPU_BENCH_LEDGER=ledger_path,
                         DTTPU_GIT_SHA="feedbeef0123"))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        r = json.loads(proc.stdout.decode().strip().splitlines()[-1])
        assert r["git_sha"] == "feedbeef0123"
        assert r["config"] == "mnist_mlp"
        assert len(r["run_id"]) == 16
        assert r["fingerprint"]["backend"] == "cpu"
        rows = ledger_lib.PerfLedger(ledger_path).rows()
        assert len(rows) == 1
        row = rows[0]
        assert row["run_id"] == r["run_id"]
        assert row["git_sha"] == "feedbeef0123"
        assert row["measured"]["value"] == r["value"]
        assert row["knobs"].get("DTTPU_BENCH_SMOKE") == "1"


class TestHelpers:
    def test_parse_last_json(self):
        text = "noise\n{\"a\": 1}\nnot json {broken\n"
        assert bench._parse_last_json(text) == {"a": 1}
        assert bench._parse_last_json("nothing here") is None

    def test_result_ok(self):
        assert bench._result_ok({"metric": "m", "value": 5.0})
        assert not bench._result_ok({"metric": "m_BACKEND_INIT_TIMEOUT",
                                     "value": 0.0})
        assert not bench._result_ok({"metric": "m_RUN_TIMEOUT", "value": 1.0})
        assert not bench._result_ok(None)
        assert not bench._result_ok({"metric": "m", "value": 0})

    def test_is_oom(self):
        assert bench._is_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1 bytes"))
        assert bench._is_oom(RuntimeError("Ran out of memory on device"))
        assert not bench._is_oom(ValueError("shape mismatch"))

    def test_transformer_flops_per_token(self):
        params = {"w": np.zeros((1000,), np.float32)}
        f = bench._transformer_flops_per_token(params, num_layers=2,
                                               hidden=8, seq=16)
        assert f == 6 * 1000 + 12 * 2 * 8 * 16

    def test_decode_eval_weights_device_resident(self, monkeypatch):
        """The trained decode-row params must stay DEVICE-resident: a
        host (numpy) tree makes every later generate() re-ship the full
        weight set through the tunnel per call (measured 2026-08-01: fp
        decode 991 tok/s from a host tree vs 23.6k device-resident)."""
        import jax
        from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig

        monkeypatch.setattr(bench, "SMOKE", True)
        monkeypatch.delenv("DTTPU_BENCH_DECODE_TRAIN", raising=False)
        config = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                           num_heads=2, intermediate_size=32,
                           max_position=16, dropout_rate=0.0)
        params, steps, sample = bench._decode_eval_weights(GPT(config),
                                                           config)
        assert steps > 0
        for leaf in jax.tree.leaves(params):
            assert isinstance(leaf, jax.Array), type(leaf)
        toks = sample(np.random.default_rng(0), 2, 8)
        assert toks.shape == (2, 8) and toks.max() < 64

    def test_decode_eval_weights_disable_knob(self, monkeypatch):
        monkeypatch.setenv("DTTPU_BENCH_DECODE_TRAIN", "0")
        from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
        config = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                           num_heads=2, intermediate_size=32,
                           max_position=16, dropout_rate=0.0)
        _, steps, _ = bench._decode_eval_weights(GPT(config), config)
        assert steps == 0

    def test_attach_mfu_with_peak_override(self, monkeypatch):
        monkeypatch.setenv("DTTPU_PEAK_FLOPS", "1e12")
        r = bench._attach_mfu({"metric": "m"}, rate_per_chip=1e6,
                              flops_per_example=1e5)
        assert r["mfu"] == pytest.approx(0.1)
        assert r["flops_source"] == "xla"

    def test_attach_mfu_analytic_fallback(self, monkeypatch):
        monkeypatch.setenv("DTTPU_PEAK_FLOPS", "1e12")
        r = bench._attach_mfu({"metric": "m"}, 1e6, None, analytic=2e5)
        assert r["mfu"] == pytest.approx(0.2)
        assert r["flops_source"] == "analytic"

    def test_attach_mfu_scan_undercount_flips_to_analytic(self, monkeypatch):
        # XLA counts a lax.scan body once, so a scanned 12-layer LM's
        # compiled-step flops land at ~1/3 of the 6N analytic figure;
        # the analytic model must win and the raw XLA number be recorded
        monkeypatch.setenv("DTTPU_PEAK_FLOPS", "1e12")
        r = bench._attach_mfu({"metric": "m"}, 1e3,
                              flops_per_example=2.9e8, analytic=7.7e8,
                              scanned=True)
        assert r["flops_source"] == "analytic"
        assert r["flops_per_example"] == pytest.approx(7.7e8)
        assert r["flops_xla_scan_undercount"] == pytest.approx(2.9e8)
        assert r["mfu"] == pytest.approx(0.77)

    def test_attach_mfu_honest_xla_kept(self, monkeypatch):
        # resnet-shaped case: XLA ~= 3x the forward-only analytic constant
        # — the compiled-step figure is honest and must keep priority
        monkeypatch.setenv("DTTPU_PEAK_FLOPS", "1e12")
        r = bench._attach_mfu({"metric": "m"}, 1e3,
                              flops_per_example=3.6e10, analytic=1.23e10,
                              scanned=True)
        assert r["flops_source"] == "xla"
        assert "flops_xla_scan_undercount" not in r

    def test_attach_mfu_unscanned_never_flips(self, monkeypatch):
        # an unscanned row whose honest XLA figure is below a rough
        # hard-coded analytic constant must NOT be replaced — the flip is
        # scoped to programs where the scan-body undercount can occur
        monkeypatch.setenv("DTTPU_PEAK_FLOPS", "1e12")
        r = bench._attach_mfu({"metric": "m"}, 1e3,
                              flops_per_example=7e7, analytic=1.53e8)
        assert r["flops_source"] == "xla"
        assert r["flops_per_example"] == pytest.approx(7e7)
        assert "flops_xla_scan_undercount" not in r


class TestProvenance:
    def test_no_dir_is_synthetic(self):
        assert data.provenance("mnist", None) == "synthetic"
        assert data.provenance("cifar10", "") == "synthetic"

    def test_unknown_dataset_raises(self):
        with pytest.raises(ValueError):
            data.provenance("imagenet", "/tmp")

    def test_real_mnist_npz(self, tmp_path):
        x = np.zeros((8, 28, 28), np.uint8)
        y = np.zeros((8,), np.uint8)
        np.savez(tmp_path / "mnist.npz", x_train=x, y_train=y,
                 x_test=x, y_test=y)
        assert data.provenance("mnist", str(tmp_path)) == "real"
        (xt, yt), (xe, ye) = data.mnist(str(tmp_path), flatten=True)
        assert xt.shape == (8, 784) and yt.dtype == np.int32

    def test_partial_idx_files_stay_synthetic(self, tmp_path):
        (tmp_path / "train-images-idx3-ubyte").write_bytes(b"x")
        assert data.provenance("mnist", str(tmp_path)) == "synthetic"

    def test_real_cifar_npz(self, tmp_path):
        x = np.zeros((4, 32, 32, 3), np.uint8)
        y = np.zeros((4,), np.uint8)
        np.savez(tmp_path / "cifar10.npz", x_train=x, y_train=y,
                 x_test=x, y_test=y)
        assert data.provenance("cifar10", str(tmp_path)) == "real"


class TestGptLong:
    def test_gpt_long_metric_and_seq_pinned_against_env(self):
        """gpt_long is the gpt row pinned at seq 2048 (the flash-dispatch
        operating point).  Round-5 advisor fix: the row's EXPLICIT seq
        now beats DTTPU_BENCH_SEQ — an exported env var must not
        silently retarget a named row's defining parameter (the SMOKE
        config keeps the run cheap on CPU despite the 2048 label)."""
        proc = _run(["--config=gpt_long", "--device=cpu"],
                    _env(DTTPU_BENCH_SEQ=128))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1
        r = json.loads(lines[0])
        assert r["metric"].startswith("gpt_long_lm_train_tokens_per_sec")
        assert r["seq_len"] == 2048
        assert r["value"] > 0

    def test_gpt_decode_int8_smoke(self):
        """int8 decode measures both paths in one run and reports their
        greedy-token agreement; on the smoke model the two paths must
        agree on nearly every token or the quant path is broken."""
        proc = _run(["--config=gpt_decode_int8", "--device=cpu"],
                    _env(DTTPU_BENCH_SEQ=64))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1
        r = json.loads(lines[0])
        assert r["metric"].startswith("gpt_decode_int8_tokens_per_sec")
        assert r["value"] > 0 and r["fp_value"] > 0
        assert r["greedy_token_match"] > 0.9

    def test_gpt_decode_spec_smoke(self):
        """Speculative decode: trains the target, distills the truncated
        draft (the donation-sensitive deep-copy path — a dropped copy
        deletes the target's shared embedding/head buffers and crashes
        here), and must keep the exactness guarantee: spec output ==
        plain greedy output."""
        proc = _run(["--config=gpt_decode_spec", "--device=cpu"],
                    _env(DTTPU_BENCH_SEQ=64))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1
        r = json.loads(lines[0])
        assert r["metric"].startswith("gpt_decode_spec_tokens_per_sec")
        assert r["value"] > 0 and r["plain_value"] > 0
        assert r["greedy_token_match"] > 0.9
        assert 0.0 <= r["acceptance"] <= 1.0
        assert r["trained_steps"] > 0

    def test_gpt_moe_smoke(self):
        proc = _run(["--config=gpt_moe", "--device=cpu"],
                    _env(DTTPU_BENCH_SEQ=64))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        r = json.loads(lines[0])
        assert r["metric"].startswith("gpt_moe_lm_train_tokens_per_sec")
        assert r["moe_experts"] == 8
        assert r["value"] > 0

    def test_gpt_serve_smoke_schema(self):
        """Continuous-batching row: the seeded mixed-length arrival
        trace runs on the CPU mesh and the JSON carries the serving
        schema — engine tokens/s (paged default AND contiguous
        comparator), TTFT percentiles, a vs_lockstep ratio against the
        in-process lock-step baseline, plus the paged-KV phases: the
        shared-prefix trace (radix-cache reuse vs the prefix_cache=False
        ablation) and the fixed-HBM concurrency measurement.
        Admission/retirement must never recompile the hot executables:
        after warmup the sanitizer sees zero violations, so
        retrace_warnings must be absent."""
        proc = _run(["--config=gpt_serve", "--device=cpu"],
                    _env(DTTPU_BENCH_SEQ=128))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1
        r = json.loads(lines[0])
        assert r["metric"].startswith("gpt_serve_tokens_per_sec")
        assert r["tokens_per_sec"] > 0
        assert r["contiguous_tokens_per_sec"] > 0
        assert r["lockstep_tokens_per_sec"] > 0
        assert r["vs_lockstep"] == r["vs_baseline"]
        assert r["vs_lockstep_paged"] > 0
        # the fused page-walk kernel leg: same paged layout read
        # through the Pallas kernel (interpret mode on CPU, so the
        # ratio vs the gather path is informational off-TPU — the
        # fields just have to exist and be sane)
        assert r["kernel_tokens_per_sec"] > 0
        assert r["vs_lockstep_paged_kernel"] > 0
        assert r["paged_kernel_vs_gather"] > 0
        assert 0 < r["ttft_p50_ms"] <= r["ttft_p95_ms"]
        assert r["requests"] > 0 and r["num_slots"] > 0
        assert r["page_size"] > 0
        assert r.get("retrace_warnings", 0) == 0
        # the acceptance bar: strictly better than lock-step batching
        # on the mixed-length trace (CPU smoke margin is ~1.2-1.4x)
        assert r["vs_lockstep"] > 1.0
        # paged-KV phase 1: the shared-prefix trace.  The radix cache
        # must actually fire (hits, skipped windows) and pay for
        # itself: tokens/s AND TTFT p50 strictly better than the same
        # engine with reuse ablated.
        sp = r["shared_prefix"]
        assert sp["requests"] > 0
        assert sp["prefix_hit_rate"] > 0
        assert sp["prefill_windows_skipped"] > 0
        assert sp["prefix_tokens_reused"] > 0
        assert sp["vs_no_reuse"] > 1.0
        assert 0 < sp["ttft_p50_ms"] < sp["no_reuse_ttft_p50_ms"]
        assert sp["lockstep_tokens_per_sec"] > 0
        assert sp["kernel_tokens_per_sec"] > 0
        assert sp["kernel_vs_gather"] > 0
        # paged-KV phase 2: at the contiguous layout's HBM budget the
        # paged engine runs strictly more concurrent slots
        assert r["slots_at_fixed_mem"] > r["slots_at_fixed_mem_contiguous"]

    def test_fleet_smoke_schema(self):
        """Fleet row: the adversarial three-tenant block burst routed
        over 2 CPU replicas under the deficit fair-share policy with a
        LoRA adapter on one tenant's traffic.  The JSON carries fleet
        tokens/s, per-tenant TTFT p50/p95, and fairness_ratio — the
        weight-normalized admitted-token min/max over the contended
        window, where plain FIFO on this trace measures 0.0.  Placement,
        failover, and adapter swaps must never recompile: zero
        retrace_warnings."""
        proc = _run(["--config=fleet", "--device=cpu"],
                    _env(DTTPU_BENCH_SEQ=128))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1
        r = json.loads(lines[0])
        assert r["metric"] == "fleet_tokens_per_sec"
        assert r["tokens_per_sec"] > 0
        assert r["replicas"] == 2
        for tenant in ("free", "pro", "batch"):
            p50 = r["tenant_ttft_p50_ms"][tenant]
            p95 = r["tenant_ttft_p95_ms"][tenant]
            assert 0 < p50 <= p95
        assert 0 < r["ttft_p50_ms"] <= r["ttft_p95_ms"]
        assert r.get("retrace_warnings", 0) == 0
        # the fair-share bar: the deficit queue must interleave the
        # per-tenant blocks FIFO would serialize (FIFO scores 0.0; the
        # CPU smoke converges well above half)
        assert r["fairness_ratio"] > 0.5
        # migration leg: drain-by-migration frees the replica without
        # waiting out its decodes, and the kill leg salvages decode
        # work through snapshots (ratio in (0, 1]: the migrated
        # requests were mid-decode, not finished)
        assert 0 < r["drain_migrate_ms"] < r["drain_wait_ms"]
        assert 0 < r["tokens_preserved_ratio"] <= 1.0
        assert r["migrations"] >= 1

    def test_fleet_sim_smoke_schema(self):
        """Fleet-simulator row (docs/FLEET_SIM.md): the seeded
        diurnal+burst trace with two scheduled correlated kills through
        the REAL router on virtual time, autoscaler-vs-static scored as
        attainment per replica-second, the SLO-vs-replicas capacity
        curve, and the stub-validation leg (sim within 25% of a real
        serve.Engine replay, asserted in-process)."""
        proc = _run(["--config=fleet_sim", "--device=cpu"], _env())
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        assert len(lines) == 1
        r = json.loads(lines[0])
        assert r["metric"] == "fleet_sim_requests_per_sec"
        assert r["value"] > 0
        assert r["simulated_requests"] == (2 * r["requests_main"]
                                           + 4 * r["requests_curve"])
        assert r["sim_wall_s"] < 60.0
        # every leg accounts for every request, and the chaos events
        # actually fired
        for leg in (r["autoscaler"], r["static"]):
            assert (leg["completed"] + leg["deadline_exceeded"]
                    + leg["lost"] == r["requests_main"])
            assert leg["correlated_kills_armed"] == 2
            assert 0 < leg["slo_attainment"] <= 1.0
        assert r["autoscaler"]["scale_outs"] >= 1
        # the acceptance bar: the SLO policy buys attainment with
        # capacity at the right moments — never worse per replica-second
        # than always-on peak provisioning
        assert r["autoscaler_vs_static"] >= 1.0
        curve = r["slo_vs_replicas"]
        assert set(curve) == {"2", "3", "4", "6"}
        for c in curve.values():
            assert 0 < c["slo_attainment"] <= 1.0
            assert c["ttft_p99_ms"] > 0
        assert (curve["6"]["slo_attainment"]
                >= curve["2"]["slo_attainment"])
        assert r["cost_model"]["provenance"] == "analytic"
        v = r["validation"]
        assert abs(v["tokens_per_sec_ratio"] - 1.0) <= 0.25
        assert abs(v["ttft_p50_ratio"] - 1.0) <= 0.25
        assert v["calibrated"]["decode_tick_s"] > 0
        assert r.get("retrace_warnings", 0) == 0
        # prefix-affinity ablation (docs/SERVING.md §Fleet affinity
        # policy): same fingerprinted Zipf trace both arms, affinity
        # wins on throughput AND hit rate
        abl = r["ablation"]
        assert abl["trace_fingerprint"] and abl["requests"] >= 2000
        assert r["affinity_vs_blind"] > 1.0
        assert (abl["affinity"]["fleet_prefix_hit_rate"]
                > abl["blind"]["fleet_prefix_hit_rate"])
        assert r["fleet_prefix_hit_rate"] \
            == abl["affinity"]["fleet_prefix_hit_rate"]
        for arm in abl["affinity"], abl["blind"]:
            assert 0 < arm["ttft_p50_ms"] <= arm["ttft_p95_ms"]
        # the real 2-replica CPU leg: affinity beats blind on actual
        # radix-cache hits, and the affinity placements really fired
        ra = r["real_affinity"]
        assert (ra["affinity"]["fleet_prefix_hit_rate"]
                > ra["blind"]["fleet_prefix_hit_rate"])
        assert ra["affinity"]["affinity_hits"] >= 1

    @pytest.mark.slow
    def test_fleet_sim_full_scale_acceptance(self):
        """The headline claim at FULL size (no smoke shrink): at least
        one million simulated requests through the real router in under
        60 s of CPU wall-clock, with the autoscaler no worse than
        static provisioning per replica-second."""
        env = _env()
        env.pop("DTTPU_BENCH_SMOKE", None)
        proc = _run(["--config=fleet_sim", "--device=cpu"], env)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
        r = json.loads(lines[-1])
        assert r["simulated_requests"] >= 1_000_000
        assert r["sim_wall_s"] < 60.0
        assert r["autoscaler_vs_static"] >= 1.0
        # the 10⁶-request prefix-affinity ablation at full size: the
        # headline affinity_vs_blind > 1.0 must hold off-smoke too
        assert r["ablation"]["requests"] >= 1_000_000
        assert r["affinity_vs_blind"] > 1.0
        assert (r["ablation"]["affinity"]["fleet_prefix_hit_rate"]
                > r["ablation"]["blind"]["fleet_prefix_hit_rate"])


class TestAnalytical:
    """The graph-tier static cost model riding the bench JSON
    (``analytical_flops``/``analytical_bytes``/``analytical_mfu``):
    every measured perf claim gets a same-program static roofline next
    to it (docs/ANALYSIS.md §graph tier)."""

    def test_attach_analytical_exact_on_a_matmul(self, monkeypatch):
        monkeypatch.setenv("DTTPU_PEAK_FLOPS", "1e12")
        monkeypatch.setenv("DTTPU_PEAK_BW", "1e10")
        import jax
        import jax.numpy as jnp
        step = jax.jit(lambda a, b: a @ b)
        args = (jax.ShapeDtypeStruct((4, 8), jnp.float32),
                jax.ShapeDtypeStruct((8, 16), jnp.float32))
        r = bench._attach_analytical({"metric": "m"}, step, args,
                                     tokens_per_step=4)
        assert r["analytical_flops"] == 2 * 4 * 8 * 16
        assert r["analytical_bytes"] == (4 * 8 + 8 * 16 + 4 * 16) * 4
        assert r["analytical_flops_per_token"] == pytest.approx(
            2 * 8 * 16)
        intensity = r["analytical_flops"] / r["analytical_bytes"]
        assert r["analytical_mfu"] == pytest.approx(
            min(1.0, 1e10 * intensity / 1e12), abs=1e-4)

    def test_attach_analytical_without_peak_omits_mfu(self, monkeypatch):
        # CPU mesh, no override: flops/bytes still land (they're
        # hardware-independent), the roofline field does not
        monkeypatch.delenv("DTTPU_PEAK_FLOPS", raising=False)
        monkeypatch.delenv("DTTPU_PEAK_BW", raising=False)
        import jax
        import jax.numpy as jnp
        step = jax.jit(lambda a: a + 1.0)
        r = bench._attach_analytical(
            {"metric": "m"}, step,
            (jax.ShapeDtypeStruct((8,), jnp.float32),))
        assert r["analytical_flops"] == 8
        assert "analytical_mfu" not in r

    def test_gpt_smoke_analytical_schema_and_roofline_bound(self):
        """--config=gpt carries the graph-tier fields, and the measured
        mfu sits below the static roofline ceiling — the sanity bound
        that makes a too-good-to-be-true number fail loudly."""
        proc = _run(["--config=gpt", "--device=cpu"],
                    _env(DTTPU_PEAK_FLOPS="1e15", DTTPU_PEAK_BW="1e13"))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        lines = [l for l in proc.stdout.decode().splitlines()
                 if l.strip()]
        r = json.loads(lines[-1])
        assert r["analytical_flops"] > 0
        assert r["analytical_bytes"] > 0
        assert r["analytical_flops_per_token"] > 0
        assert 0 < r["analytical_mfu"] <= 1.0
        # the cost model counts scan bodies times their trip count, so
        # the static figure must not fall below XLA's scan-undercounted
        # per-token number
        assert r["analytical_flops_per_token"] >= r["flops_per_example"]
        # measured <= static roofline ceiling
        assert r["mfu"] <= r["analytical_mfu"]
