"""Runtime sanitizer (analysis/sanitizer.py): retrace budgets, arg-diff
reporting, donated-buffer enforcement, and the pytest marker wiring.

The seeded-retrace tests are the contract from ISSUE 2: a retrace storm
that is invisible without the sanitizer (first test proves the storm runs
silently) must fail loudly under the guard (second test).
"""
import io
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.analysis.sanitizer import (
    RetraceBudgetExceeded, RetraceGuard, retrace_guard)


def _double(x):
    return x * 2


def _storm(jitted, n=4):
    """Seed a retrace per call: every iteration changes the arg shape."""
    for i in range(1, n + 1):
        jitted(jnp.ones((i,)))


# ------------------------------------------------------------- retraces

def test_seeded_retrace_storm_is_silent_without_sanitizer():
    # the hazard the sanitizer exists for: nothing raises, nothing warns
    _storm(jax.jit(_double))


def test_seeded_retrace_storm_fails_under_guard():
    with pytest.raises(RetraceBudgetExceeded) as ei:
        with RetraceGuard(budget=2):
            _storm(jax.jit(_double))
    msg = str(ei.value)
    assert "budget=2" in msg
    # the report carries an actionable arg-diff, not just a count
    assert "->" in msg and "float32[2]" in msg and "float32[3]" in msg


def test_stable_shapes_stay_within_budget():
    with RetraceGuard(budget=1) as guard:
        f = jax.jit(_double)
        for _ in range(5):
            f(jnp.ones((4,)))
    assert guard.violations == []
    assert guard.report() == "RetraceGuard: clean"


def test_warn_mode_records_and_continues():
    buf = io.StringIO()
    with RetraceGuard(budget=1, mode="warn", stream=buf) as guard:
        _storm(jax.jit(_double), n=3)
    assert len(guard.violations) == 2           # traces 2 and 3
    assert "arg-diff" in buf.getvalue()


def test_static_arg_cache_defeat_reports_value_change():
    def f(x, cfg):
        return x * cfg[0]

    with pytest.raises(RetraceBudgetExceeded) as ei:
        with RetraceGuard(budget=1):
            g = jax.jit(f, static_argnums=(1,))
            g(jnp.ones((2,)), (2,))
            g(jnp.ones((2,)), (3,))             # new static value: retrace
    assert "2 -> 3" in str(ei.value)            # leaf-level value diff


def test_guard_restores_jit_on_exit():
    orig = jax.jit
    with RetraceGuard(budget=1):
        assert jax.jit is not orig
    assert jax.jit is orig
    # and on the exception path
    try:
        with RetraceGuard(budget=1):
            raise ValueError("boom")
    except ValueError:
        pass
    assert jax.jit is orig


# ------------------------------------------------------------- donation

def _step(state, batch):
    return state + batch, {"loss": state.sum()}


def test_donated_read_raises_under_guard_even_when_backend_rejects():
    # a donation XLA cannot use (output aliases nothing): jax leaves the
    # buffer readable — the guard enforces the *declared* contract anyway
    def shrink(state, b):
        return (state[:2] + b[:2]).astype(jnp.bfloat16)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with RetraceGuard(budget=2):
            f = jax.jit(shrink, donate_argnums=0)
            state = jnp.ones((8,))
            out = f(state, jnp.ones((8,)))
            np.asarray(out)                     # result stays readable
            with pytest.raises(RuntimeError, match="deleted"):
                np.asarray(state)


def test_donated_read_passes_silently_without_guard():
    # the hole the guard closes: same rejected donation, no guard — the
    # read succeeds and a test would happily pass TPU-divergent code
    def shrink(state, b):
        return (state[:2] + b[:2]).astype(jnp.bfloat16)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        f = jax.jit(shrink, donate_argnums=0)
        state = jnp.ones((8,))
        f(state, jnp.ones((8,)))
        assert float(np.asarray(state)[0]) == 1.0


def test_donation_chain_with_rebinding_is_clean():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with RetraceGuard(budget=2) as guard:
            step = jax.jit(_step, donate_argnums=0)
            state = jnp.ones((8,))
            for _ in range(3):
                state, m = step(state, jnp.ones((8,)))
            assert float(np.asarray(m["loss"])) > 0
    assert guard.violations == []


def test_enforcer_delegates_jit_attributes():
    with RetraceGuard(budget=2):
        step = jax.jit(_step, donate_argnums=0)
        lowered = step.lower(jnp.ones((4,)), jnp.ones((4,)))
        assert lowered.compile() is not None


def test_enforce_donation_off_leaves_buffers_alone():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with RetraceGuard(budget=2, enforce_donation=False):
            def shrink(state, b):
                return (state[:2] + b[:2]).astype(jnp.bfloat16)
            f = jax.jit(shrink, donate_argnums=0)
            state = jnp.ones((8,))
            f(state, jnp.ones((8,)))
            assert float(np.asarray(state)[0]) == 1.0


# ------------------------------------------------------------- fixture

@pytest.mark.retrace_guard(budget=1)
def test_marker_wraps_test_in_guard():
    f = jax.jit(_double)
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))                           # cache hit: no retrace
    with pytest.raises(RetraceBudgetExceeded):
        f(jnp.ones((5,)))                       # second trace: over budget


def test_functional_alias():
    with retrace_guard(budget=3) as guard:
        assert isinstance(guard, RetraceGuard)
        assert guard.budget == 3
