"""Test configuration: force an 8-device virtual CPU mesh.

The reference repo's de-facto smoke test was its single-machine fallback path
(reference example.py:64-68,111-113): unset the cluster env vars and the same
code runs locally.  The JAX-native analogue is a virtual multi-device CPU
platform, so every multi-chip code path (shard_map, pjit on a Mesh, ring
collectives) runs for real at world-size 8 inside plain pytest.

This file must set the env vars BEFORE jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize force-selects the TPU platform via
# jax.config.update("jax_platforms", ...), which overrides the env var —
# override it back at the config level before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
