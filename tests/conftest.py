"""Test configuration: force an 8-device virtual CPU mesh.

The reference repo's de-facto smoke test was its single-machine fallback path
(reference example.py:64-68,111-113): unset the cluster env vars and the same
code runs locally.  The JAX-native analogue is a virtual multi-device CPU
platform, so every multi-chip code path (shard_map, pjit on a Mesh, ring
collectives) runs for real at world-size 8 inside plain pytest.

This file must set the env vars BEFORE jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize force-selects the TPU platform via
# jax.config.update("jax_platforms", ...), which overrides the env var —
# override it back at the config level before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Speed tiers.  `pytest -m "not slow"` is the default development loop;
# the full suite (including this list) is the CI/driver gate.  Entries are
# nodeid prefixes (after "tests/"); whole files for the subprocess-heavy
# tiers, individual tests elsewhere — from the measured round-4 full-run
# durations (docs/ROUND4.md), threshold ~14 s/test on the 8-device mesh.
_SLOW_FILES = {
    "test_example_gpt.py",   # full example-script smoke (900 s budget)
    "test_multihost.py",     # real 2-process jax.distributed bootstraps
    "test_cluster.py",       # subprocess cluster bootstrap tests
    "test_graft_entry.py",   # dryrun_multichip compile at n=1/2/8
}
_SLOW_TESTS = (
    # subprocess round-trips; the in-process classes in the same file
    # (TestSupervisorProbe, TestHelpers, TestProvenance) stay fast
    "test_bench.py::TestSupervisor::",
    "test_bench.py::TestGptLong",
    # round-5 re-tier: every >=12 s test from the measured durations run
    # (2026-07-31, 8-device CPU mesh) moves to the slow tier
    "test_resnet.py::test_resnet50_forward_shape",
    "test_resnet.py::test_resnet_partition_rules_on_mesh",
    "test_bert.py::test_partition_rules_cover_all_big_params",
    "test_bert.py::test_tensor_parallel_sharding_and_step",
    "test_bert.py::test_mlm_training_reduces_loss",
    "test_decoding.py::test_sampling_in_generate_paths",
    "test_convert.py::test_gpt2_generate_greedy_matches_torch",
    "test_convergence.py::test_mnist_mlp_learns_data_parallel",
    "test_gpt.py::test_lm_training_loss_decreases",
    # sequential-decode-loop parity variants (the base block-prefill
    # oracle stays fast)
    "test_gpt.py::test_decode_block_matches_sequential_prefill_rope_gqa",
    "test_gpt.py::test_decode_block_ragged_matches_sequential_prefill",
    # second re-tier pass (fast tier measured 10:57 on the 1-core host):
    # everything >= ~5.3 s from the same durations profile
    "test_sequential.py::test_zoo_stack_serializes_through_sequential",
    "test_gpt.py::test_gqa_tensor_parallel_rules_and_step",
    "test_bert.py::test_forward_shapes_and_dtypes",
    "test_convert.py::test_gpt2_converted_shards_and_trains_on_mesh",
    "test_bert.py::test_fused_layernorm_matches_plain",
    "test_seq2seq.py::test_beam_search_eos_early_exit_pads_with_eos",
    "test_vit.py::test_forward_shapes_and_dtype",
    "test_ring_flash.py::test_causal_matches_plain_ring",
    "test_bert.py::test_sequence_parallel_matches_dense_attention",
    "test_bert.py::test_flash_attention_matches_dense",
    "test_moe.py::test_ample_capacity_no_drops_and_combine_normalized",
    "test_resnet.py::test_fresh_instance_applies_restored_params",
    "test_vit.py::test_vit_bf16_compute",
    "test_ema.py::test_with_ema_rides_train_step_and_checkpoints",
    "test_ring_flash.py::test_gqa_kv_heads_unbroadcast",
    "test_gpt.py::test_tensor_parallel_training_step",
    "test_quant.py::test_quantized_gpt_generates",
    "test_gpt.py::test_remat_matches_no_remat",
    "test_gpt.py::test_tp_sharded_decode_matches_single_device",
    "test_gpt.py::test_chunked_prefill_matches_one_block",
    # only the bf16 parametrization is slow-tiered; [float32] stays fast
    "test_gpt.py::test_decode_block_matches_sequential_prefill[bfloat16",
    "test_gpt.py::test_int8_kv_cache_decode",
    "test_seq2seq.py::test_src_padding_masked_out",
    "test_convert.py::test_gpt2_converted_finetunes",
    # round-5 speculative additions: keep the fast exactness oracle
    # (self-draft); the variants and the window oracle are slow-tier
    "test_speculative.py::test_weak_draft_still_matches_target_greedy",
    "test_speculative.py::test_gamma_one_and_long_run",
    "test_speculative.py::test_decode_window_matches_sequential_steps",
    "test_speculative.py::test_sampled_spec_runs_and_is_plausible",
    "test_speculative.py::test_spec_composes_with_chunked_prefill_and_int8_kv",
    "test_speculative.py::test_spec_eos_early_stop_matches_generate",
    "test_speculative.py::test_sampled_spec_with_filters_stays_in_filtered_support",
    # third pass (measured 8:16): the >=10 s stragglers
    "test_resnet.py::test_head_key_independent_of_blocks",
    "test_seq2seq.py::test_partition_rules_compile_on_mesh",
    "test_convert.py::test_bert_sequence_and_pooled_match_torch",
    "test_pipeline.py::test_gpt_pipeline_loss_and_grads_match",
    "test_pipeline.py::test_gpt_1f1b_full_model_grads_match_gpipe",
    "test_pipeline.py::test_gpt_1f1b_loss_mask_matches_gpipe",
    "test_pipeline.py::test_gpt_pipeline_training_trajectory_matches",
    "test_pipeline.py::test_gpt_pipeline_forward_matches_sequential",
    "test_pipeline.py::test_gpt_1f1b_train_step_converges",
    "test_pipeline.py::test_1f1b_matches_gpipe_autodiff",
    "test_pipeline.py::test_pipeline_backward_matches_sequential",
    "test_pallas.py::TestFlashShapeFuzz",
    "test_pallas.py::TestFlashGQA",
    "test_pallas.py::TestFlashAttention::test_fused_backward",
    "test_pallas.py::TestFlashAttention::test_gradients_match_reference",
    "test_gpt.py::TestChunkedLoss",
    "test_gpt.py::test_remat_policies_match",
    "test_gpt.py::test_moe_gpt_trains_and_decodes",
    "test_gpt.py::test_gqa_trains_cache_shrinks_and_decode_matches_forward",
    "test_gpt.py::test_beam_search_ragged_prompts_match_solo",
    "test_gpt.py::test_rope_gpt_trains_and_decode_matches_forward",
    "test_gpt.py::test_kv_cache_decode_matches_full_forward",
    "test_gpt.py::test_beam_search_ragged_plus_eos_compose",
    "test_gpt.py::test_moe_gpt_expert_parallel_step",
    "test_gpt.py::test_gpt_beam_search_improves_logprob_and_eos_freezes",
    "test_gpt.py::test_ragged_prompt_left_padding_matches_solo_rows",
    "test_gpt.py::test_bf16_forward_and_training",
    "test_gpt.py::test_beam_search_eos_early_exit_pads_with_eos",
    "test_sharding.py::test_fsdp_shards_params_and_optimizer_moments",
    "test_seq2seq.py::test_beam_search_beats_or_matches_greedy",
    "test_seq2seq.py::test_learns_copy_task",
    "test_seq2seq.py::test_generate_eos_early_stop_and_padding",
    "test_data.py::test_synthetic_datasets_shapes_and_learnability",
    "test_ring.py::test_ring_gradients_flow",
    "test_ring_flash.py::test_gradients_match_dense",
    "test_ring_flash.py::test_padding_plus_causal_gradients",
    "test_ring_flash.py::test_bert_sp_flash_matches_dense",
    "test_ring_flash.py::test_gpt_sp_flash_matches_dense",
    "test_ring_flash.py::test_gpt_gqa_sp_flash_matches_dense",
    "test_ring_flash.py::test_ring_flash_composes_with_remat",
    "test_moe.py::test_single_expert_equals_dense_ffn",
    "test_moe.py::test_moe_gradients_flow_through_router_and_experts",
    "test_moe.py::test_tiny_capacity_drops_tokens_to_zero",
    "test_session.py::test_masked_loss_accumulation_exact",
    "test_convert.py::test_gpt2_logits_match_torch",
    "test_resnet.py::test_resnet50_canonical_param_count",
    "test_resnet.py::test_resnet_cifar_trains_and_updates_bn",
    "test_vit.py::test_vit_tensor_parallel_step",
    "test_vit.py::test_vit_trains",
    "test_convergence.py::test_xor_learns_low_level",
    "test_bert.py::test_bert_base_param_count",
    "test_bert.py::TestMlmGather",
    "test_llama.py::TestLlamaRecipe::test_trains",
    "test_quant.py::test_quantized_beam_search_with_ragged_prompts",
)


def pytest_collection_modifyitems(config, items):
    slow = pytest.mark.slow
    for item in items:
        nodeid = item.nodeid.split("tests/")[-1]
        if nodeid.split("::")[0] in _SLOW_FILES:
            item.add_marker(slow)
        elif any(nodeid.startswith(p) for p in _SLOW_TESTS):
            item.add_marker(slow)


# ---------------------------------------------------------------------------
# Opt-in runtime sanitizer (analysis/sanitizer.py, docs/ANALYSIS.md):
#
#   @pytest.mark.retrace_guard            # budget=1: "compiles once"
#   @pytest.mark.retrace_guard(budget=2, enforce_donation=False)
#
# wraps the test in a RetraceGuard, so jit functions built inside the test
# fail it on unexpected recompiles (with an arg-diff) and donated-buffer
# reads raise even when XLA rejects the donation (routine on this CPU
# mesh).  Opt-in by marker: the guard patches jax.jit for its extent,
# which must never leak into unmarked tests.

@pytest.fixture(autouse=True)
def _retrace_guard_marker(request):
    marker = request.node.get_closest_marker("retrace_guard")
    if marker is None:
        yield
        return
    from distributed_tensorflow_tpu.analysis.sanitizer import RetraceGuard
    with RetraceGuard(*marker.args, **marker.kwargs):
        yield


# ---------------------------------------------------------------------------
# Opt-in race harness (analysis/race_harness.py, docs/ANALYSIS.md):
#
#   @pytest.mark.race_harness(seed=7, scope=("serve/", "fleet/"))
#
# wraps the test in a RaceHarness: threads started inside it are forced
# to context-switch at attribute/call sites in the scoped modules under
# the seed, so host-concurrency races manifest deterministically instead
# of once a fortnight in CI.  Opt-in by marker — opcode tracing is a
# ~100x slowdown inside scope and must never leak into other tests.

@pytest.fixture(autouse=True)
def _race_harness_marker(request):
    marker = request.node.get_closest_marker("race_harness")
    if marker is None:
        yield
        return
    from distributed_tensorflow_tpu.analysis.race_harness import RaceHarness
    with RaceHarness(*marker.args, **marker.kwargs) as harness:
        request.node.race_harness = harness
        yield


# ---------------------------------------------------------------------------
# Opt-in resource ledger (analysis/leak_ledger.py, docs/ANALYSIS.md):
#
#   @pytest.mark.resource_ledger                      # all four surfaces
#   @pytest.mark.resource_ledger(track=("pages",))    # just page leases
#
# wraps the test in a ResourceLedger: PagePool lease, AdapterTable pin,
# goodput frame, and reqtrace span acquire/release traffic inside the
# test must balance exactly at teardown or the test fails with a
# per-resource imbalance table (LedgerImbalance).  This is the runtime
# sibling of the DT6xx lifecycle lint tier — chaos tests run under it
# to prove release-on-injected-fault paths.  Opt-in by marker: the
# ledger patches the serve/obs classes for its extent.

@pytest.fixture(autouse=True)
def _resource_ledger_marker(request):
    marker = request.node.get_closest_marker("resource_ledger")
    if marker is None:
        yield
        return
    from distributed_tensorflow_tpu.analysis.leak_ledger import ResourceLedger
    with ResourceLedger(*marker.args, **marker.kwargs) as ledger:
        request.node.resource_ledger = ledger
        yield


# ---------------------------------------------------------------------------
# Fault injection (resilience/faults.py, docs/RESILIENCE.md): chaos tests
# activate a deterministic FaultPlan for their extent via
#
#   plan = activate_faults({"kind": "kill_prefetch", "at": 3}, ...)
#
# The fixture guarantees deactivation even when the test dies mid-chaos —
# a leaked plan would inject faults into every later test's saves/batches.

@pytest.fixture
def activate_faults():
    from distributed_tensorflow_tpu.resilience import faults

    def _activate(*fault_dicts, seed=0, registry=None):
        plan = faults.FaultPlan(list(fault_dicts), seed=seed,
                                registry=registry)
        return faults.activate(plan)

    yield _activate
    faults.deactivate()
