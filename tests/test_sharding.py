"""Partition-rule machinery tests."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.sharding import (PartitionRules,
                                                          prune_spec,
                                                          shard_pytree,
                                                          tree_paths)


def test_tree_paths():
    tree = {"a": {"b": jnp.zeros(2), "c": jnp.zeros(3)}, "d": jnp.zeros(4)}
    assert tree_paths(tree) == ["a/b", "a/c", "d"]


def test_first_match_wins():
    rules = PartitionRules([
        (r"special/kernel", P("tensor")),
        (r"kernel", P("data")),
    ])
    assert rules.spec_for("layer/special/kernel") == P("tensor")
    assert rules.spec_for("layer/other/kernel") == P("data")
    assert rules.spec_for("layer/bias") == P()


def test_prune_spec_degrades_gracefully():
    mesh = make_mesh({"data": 8})
    assert prune_spec(P("tensor", None), mesh) == P(None, None)
    assert prune_spec(P("data", "tensor"), mesh) == P("data", None)
    assert prune_spec(P(("data", "fsdp"), None), mesh) == P(("data",), None)


def test_shard_pytree_places_leaves():
    mesh = make_mesh({"data": 4, "tensor": 2})
    params = {"dense": {"kernel": jnp.ones((8, 16)), "bias": jnp.ones((16,))}}
    rules = PartitionRules([(r"kernel", P(None, "tensor"))])
    out = shard_pytree(params, mesh, rules)
    assert "tensor" in str(out["dense"]["kernel"].sharding.spec)
    # bias replicated across all 8 devices
    assert len(out["dense"]["bias"].sharding.device_set) == 8
    shapes = {s.data.shape for s in out["dense"]["kernel"].addressable_shards}
    assert shapes == {(8, 8)}



def test_fsdp_shards_params_and_optimizer_moments():
    """ZeRO requirement: Adam m/v shard WITH their params over fsdp; the
    sharded run matches the replicated run numerically."""
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny
    from distributed_tensorflow_tpu.parallel import make_mesh

    mesh = make_mesh({"fsdp": 8})
    model = gpt_tiny(dropout_rate=0.0)
    params = model.init(jax.random.PRNGKey(0))
    opt = optim.adam(1e-3)
    rules = model.partition_rules(fsdp=True)

    state = train.TrainState.create(
        jax.tree.map(jnp.copy, params), opt.init(params))
    state = train.shard_train_state(state, mesh, rules)
    w_in = state.params["decoder"]["ffn"]["w_in"]["kernel"]
    assert "fsdp" in str(w_in.sharding.spec)
    m_in = state.opt_state.inner["m"]["decoder"]["ffn"]["w_in"]["kernel"]
    assert m_in.sharding == w_in.sharding  # moments shard with params

    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 512)
    state, m = step(state, {"input_ids": ids})
    assert np.isfinite(float(m["loss"]))
    # placements survive the step
    assert "fsdp" in str(
        state.params["decoder"]["ffn"]["w_in"]["kernel"].sharding.spec)
    assert state.opt_state.inner["m"]["decoder"]["ffn"]["w_in"][
        "kernel"].sharding == state.params["decoder"]["ffn"]["w_in"][
        "kernel"].sharding

    ref_state = train.TrainState.create(
        jax.tree.map(jnp.copy, params), opt.init(params))
    ref_state, ref_m = step(ref_state, {"input_ids": ids})
    np.testing.assert_allclose(float(m["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    # atol 5e-5: sharded reductions reorder float sums vs the replicated run
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=5e-5),
        jax.device_get(state.params), jax.device_get(ref_state.params))


def test_shard_train_state_momentum_and_sgd():
    """momentum's mu (params-shaped inner) shards WITH params; sgd's empty
    inner passes through; bare-array params don't crash."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.parallel import (PartitionRules,
                                                     make_mesh)

    mesh = make_mesh({"fsdp": 8})
    params = {"dense": {"kernel": jnp.ones((16, 8))}}
    rules = PartitionRules([(r"kernel", P("fsdp", None))])

    opt = optim.momentum(0.1)
    state = train.shard_train_state(
        train.TrainState.create(params, opt.init(params)), mesh, rules)
    k_sh = state.params["dense"]["kernel"].sharding
    assert "fsdp" in str(k_sh.spec)
    assert state.opt_state.inner["dense"]["kernel"].sharding == k_sh

    opt2 = optim.sgd(0.1)
    s2 = train.shard_train_state(
        train.TrainState.create(params, opt2.init(params)), mesh, rules)
    assert s2.opt_state.inner == ()
