"""Partition-rule machinery tests."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.sharding import (PartitionRules,
                                                          prune_spec,
                                                          shard_pytree,
                                                          tree_paths)


def test_tree_paths():
    tree = {"a": {"b": jnp.zeros(2), "c": jnp.zeros(3)}, "d": jnp.zeros(4)}
    assert tree_paths(tree) == ["a/b", "a/c", "d"]


def test_first_match_wins():
    rules = PartitionRules([
        (r"special/kernel", P("tensor")),
        (r"kernel", P("data")),
    ])
    assert rules.spec_for("layer/special/kernel") == P("tensor")
    assert rules.spec_for("layer/other/kernel") == P("data")
    assert rules.spec_for("layer/bias") == P()


def test_prune_spec_degrades_gracefully():
    mesh = make_mesh({"data": 8})
    assert prune_spec(P("tensor", None), mesh) == P(None, None)
    assert prune_spec(P("data", "tensor"), mesh) == P("data", None)
    assert prune_spec(P(("data", "fsdp"), None), mesh) == P(("data",), None)


def test_shard_pytree_places_leaves():
    mesh = make_mesh({"data": 4, "tensor": 2})
    params = {"dense": {"kernel": jnp.ones((8, 16)), "bias": jnp.ones((16,))}}
    rules = PartitionRules([(r"kernel", P(None, "tensor"))])
    out = shard_pytree(params, mesh, rules)
    assert "tensor" in str(out["dense"]["kernel"].sharding.spec)
    # bias replicated across all 8 devices
    assert len(out["dense"]["bias"].sharding.device_set) == 8
    shapes = {s.data.shape for s in out["dense"]["kernel"].addressable_shards}
    assert shapes == {(8, 8)}
