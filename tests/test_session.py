"""TrainSession + hooks tests (reference MTS loop, example.py:187-228)."""
import jax
import pytest

from distributed_tensorflow_tpu import data, ops, optim, train


def make_bits():
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt,
                                 metric_fns={"acc": "bitwise_accuracy"})
    (xt, yt), _ = data.xor_data(500, val_size=10, seed=0)
    ds = data.Dataset([xt, yt], 50, seed=0)
    return model, opt, state, step, ds


def run_session(sess, ds, max_batches=10_000):
    it = iter(ds.epochs(1000))
    n = 0
    while not sess.should_stop() and n < max_batches:
        sess.run_step(next(it))
        n += 1


def test_stop_at_step():
    _, _, state, step, ds = make_bits()
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=7)]) as sess:
        run_session(sess, ds)
    assert sess.step == 7


def test_checkpoint_and_resume(tmp_path):
    """MTS semantics: periodic save + auto-restore-latest on a fresh session
    (reference example.py:189-192)."""
    model, opt, state, step, ds = make_bits()
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=12),
                                   train.CheckpointHook(every_steps=5)]) as s1:
        run_session(s1, ds)
    assert train.checkpoint.latest_step(d) == 12  # final save at end

    fresh = train.init_train_state(model, opt, jax.random.PRNGKey(9), (64,))
    with train.TrainSession(fresh, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=15)]) as s2:
        assert s2.step == 12  # restored, the global_step resume cursor
        run_session(s2, ds)
    assert s2.step == 15


def test_num_steps_counts_from_restore(tmp_path):
    model, opt, state, step, ds = make_bits()
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=4)]) as s1:
        run_session(s1, ds)
    fresh = train.init_train_state(model, opt, jax.random.PRNGKey(9), (64,))
    with train.TrainSession(fresh, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(num_steps=3)]) as s2:
        run_session(s2, ds)
    assert s2.step == 7


def test_non_chief_never_writes(tmp_path):
    _, _, state, step, ds = make_bits()
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d, is_chief=False,
                            hooks=[train.StopAtStepHook(last_step=3),
                                   train.CheckpointHook(every_steps=1)]) as s:
        run_session(s, ds)
    assert train.checkpoint.latest_checkpoint(d) is None


def test_nan_hook():
    _, _, state, _, ds = make_bits()

    def bad_step(state, batch):
        return state._replace(step=state.step + 1), {
            "loss": jax.numpy.asarray(float("nan"))}

    with pytest.raises(FloatingPointError):
        with train.TrainSession(state, bad_step,
                                hooks=[train.NaNHook(every_steps=1)]) as s:
            run_session(s, ds)


def test_summary_hook(tmp_path):
    import glob
    from distributed_tensorflow_tpu.summary import SummaryWriter
    from tests.test_summary import parse_event, read_records

    _, _, state, step, ds = make_bits()
    writer = SummaryWriter(str(tmp_path))
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=4),
                                   train.SummaryHook(writer, every_steps=2)]) as s:
        run_session(s, ds)
    writer.close()
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)[1:]  # drop version record
    assert len(records) == 2  # steps 2 and 4
    tags = set()
    for rec in records:
        summary = parse_event(parse_event(rec)[5][0])
        for v in summary[1]:
            tags.add(parse_event(v)[1][0])
    assert tags == {b"loss", b"acc"}


def test_logging_hook(capsys):
    _, _, state, step, ds = make_bits()
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=4),
                                   train.LoggingHook(every_steps=2)]) as s:
        run_session(s, ds)
    out = capsys.readouterr().out
    assert "step 2:" in out and "step 4:" in out and "loss=" in out


def test_multi_train_step_matches_sequential_single_steps():
    """K scanned updates in one dispatch == K single-step dispatches."""
    import numpy as np
    from distributed_tensorflow_tpu import parallel
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    mesh = parallel.data_parallel_mesh()
    single = train.make_train_step(model, "mse", opt, mesh=mesh)
    multi = train.make_multi_train_step(model, "mse", opt, steps_per_call=4,
                                        mesh=mesh)
    (xt, yt), _ = data.xor_data(400, val_size=10, seed=0)
    xs = xt[:320].reshape(4, 80, 64)
    ys = yt[:320].reshape(4, 80, 32)

    s1 = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    for i in range(4):
        s1, m1 = single(s1, (xs[i], ys[i]))

    s2 = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    s2, metrics = multi(s2, (xs, ys))
    assert metrics["loss"].shape == (4,)
    assert int(s2.step) == int(s1.step) == 4
    np.testing.assert_allclose(float(metrics["loss"][-1]), float(m1["loss"]),
                               rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), s1.params, s2.params)
