"""TrainSession + hooks tests (reference MTS loop, example.py:187-228)."""
import jax
import pytest

from distributed_tensorflow_tpu import data, ops, optim, train


def make_bits():
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt,
                                 metric_fns={"acc": "bitwise_accuracy"})
    (xt, yt), _ = data.xor_data(500, val_size=10, seed=0)
    ds = data.Dataset([xt, yt], 50, seed=0)
    return model, opt, state, step, ds


def run_session(sess, ds, max_batches=10_000):
    it = iter(ds.epochs(1000))
    n = 0
    while not sess.should_stop() and n < max_batches:
        sess.run_step(next(it))
        n += 1


def test_stop_at_step():
    _, _, state, step, ds = make_bits()
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=7)]) as sess:
        run_session(sess, ds)
    assert sess.step == 7


def test_checkpoint_and_resume(tmp_path):
    """MTS semantics: periodic save + auto-restore-latest on a fresh session
    (reference example.py:189-192)."""
    model, opt, state, step, ds = make_bits()
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=12),
                                   train.CheckpointHook(every_steps=5)]) as s1:
        run_session(s1, ds)
    assert train.checkpoint.latest_step(d) == 12  # final save at end

    fresh = train.init_train_state(model, opt, jax.random.PRNGKey(9), (64,))
    with train.TrainSession(fresh, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=15)]) as s2:
        assert s2.step == 12  # restored, the global_step resume cursor
        run_session(s2, ds)
    assert s2.step == 15


def test_num_steps_counts_from_restore(tmp_path):
    model, opt, state, step, ds = make_bits()
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=4)]) as s1:
        run_session(s1, ds)
    fresh = train.init_train_state(model, opt, jax.random.PRNGKey(9), (64,))
    with train.TrainSession(fresh, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(num_steps=3)]) as s2:
        run_session(s2, ds)
    assert s2.step == 7


def test_non_chief_never_writes(tmp_path):
    _, _, state, step, ds = make_bits()
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d, is_chief=False,
                            hooks=[train.StopAtStepHook(last_step=3),
                                   train.CheckpointHook(every_steps=1)]) as s:
        run_session(s, ds)
    assert train.checkpoint.latest_checkpoint(d) is None


def test_nan_hook():
    _, _, state, _, ds = make_bits()

    def bad_step(state, batch):
        return state._replace(step=state.step + 1), {
            "loss": jax.numpy.asarray(float("nan"))}

    with pytest.raises(FloatingPointError):
        with train.TrainSession(state, bad_step,
                                hooks=[train.NaNHook(every_steps=1)]) as s:
            run_session(s, ds)


def test_profiler_hook_writes_trace(tmp_path):
    """ProfilerHook captures a jax.profiler trace for its step window and
    leaves a non-empty trace directory (works on the CPU backend too)."""
    import os
    _, _, state, step, ds = make_bits()
    d = str(tmp_path / "profile")
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=6),
                                   train.ProfilerHook(d, start_step=2,
                                                      num_steps=2)]) as s:
        run_session(s, ds)
        assert not s.hooks[1]._active  # trace stopped at stop_step
    files = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert files, "profiler trace directory is empty"


def test_profiler_hook_closes_open_trace(tmp_path):
    """A trace left running when the session exits (e.g. exception before
    stop_step) is closed by Hook.close, not leaked."""
    _, _, state, step, ds = make_bits()
    hook = train.ProfilerHook(str(tmp_path / "p2"), start_step=1,
                              num_steps=100)
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=3),
                                   hook]) as s:
        run_session(s, ds)
        assert hook._active  # still tracing when the loop ends
    assert not hook._active  # close() stopped it


class _FakeProfiler:
    """Monkeypatch stand-in for jax.profiler: tracks active state only."""

    def __init__(self):
        self.active = False
        self.starts = 0

    def start_trace(self, log_dir):
        assert not self.active, "start_trace while a trace is running"
        self.active = True
        self.starts += 1

    def stop_trace(self):
        assert self.active, "stop_trace with no trace running"
        self.active = False


def _traced_steps(monkeypatch, state, step, ds, hook, last_step):
    """Post-execution global-step values whose step ran under the trace."""
    import jax
    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)

    class Spy(train.Hook):
        # placed AFTER ProfilerHook: before_step sees the trace state the
        # upcoming execution runs under
        def __init__(self):
            self.traced = []
            self._pre_active = False

        def before_step(self, session):
            self._pre_active = fake.active

        def after_step(self, session, metrics):
            if self._pre_active:
                self.traced.append(session.step)

    spy = Spy()
    with train.TrainSession(state, step,
                            hooks=[hook, spy,
                                   train.StopAtStepHook(last_step)]) as s:
        run_session(s, ds)
    return spy.traced, fake


def test_profiler_hook_traces_exact_step_set(monkeypatch):
    """Regression for the seed off-by-one: the start check used the
    PRE-step counter (==) while the stop check used the POST-step counter
    (>=), so under the global-step numbering every other hook uses the
    traced window was {start+1, ..., start+num} — one step late.  Pin the
    contract: exactly num_steps steps, global steps
    {start_step, ..., start_step + num_steps - 1}."""
    _, _, state, step, ds = make_bits()
    hook = train.ProfilerHook("/tmp/unused", start_step=3, num_steps=2)
    traced, fake = _traced_steps(monkeypatch, state, step, ds, hook,
                                 last_step=8)
    assert traced == [3, 4]
    assert fake.starts == 1 and not fake.active


def test_profiler_hook_starts_after_restore_past_start(monkeypatch):
    """A session restored beyond start_step still captures num_steps steps
    (the seed's == start check silently skipped the trace forever)."""
    import jax.numpy as jnp
    _, _, state, step, ds = make_bits()
    state = state._replace(step=jnp.asarray(5, jnp.int32))  # "restored"
    hook = train.ProfilerHook("/tmp/unused", start_step=2, num_steps=3)
    traced, fake = _traced_steps(monkeypatch, state, step, ds, hook,
                                 last_step=12)
    assert traced == [6, 7, 8]
    assert fake.starts == 1 and not fake.active


def test_summary_hook(tmp_path):
    import glob
    from distributed_tensorflow_tpu.summary import SummaryWriter
    from tests.test_summary import parse_event, read_records

    _, _, state, step, ds = make_bits()
    writer = SummaryWriter(str(tmp_path))
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=4),
                                   train.SummaryHook(writer, every_steps=2)]) as s:
        run_session(s, ds)
    writer.close()
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)[1:]  # drop version record
    assert len(records) == 2  # steps 2 and 4
    tags = set()
    for rec in records:
        summary = parse_event(parse_event(rec)[5][0])
        for v in summary[1]:
            tags.add(parse_event(v)[1][0])
    assert tags == {b"loss", b"acc"}


def test_logging_hook(capsys):
    _, _, state, step, ds = make_bits()
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(last_step=4),
                                   train.LoggingHook(every_steps=2)]) as s:
        run_session(s, ds)
    out = capsys.readouterr().out
    assert "step 2:" in out and "step 4:" in out and "loss=" in out


def test_multi_train_step_matches_sequential_single_steps():
    """K scanned updates in one dispatch == K single-step dispatches."""
    import numpy as np
    from distributed_tensorflow_tpu import parallel
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    mesh = parallel.data_parallel_mesh()
    single = train.make_train_step(model, "mse", opt, mesh=mesh)
    multi = train.make_multi_train_step(model, "mse", opt, steps_per_call=4,
                                        mesh=mesh)
    (xt, yt), _ = data.xor_data(400, val_size=10, seed=0)
    xs = xt[:320].reshape(4, 80, 64)
    ys = yt[:320].reshape(4, 80, 32)

    s1 = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    for i in range(4):
        s1, m1 = single(s1, (xs[i], ys[i]))

    s2 = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    s2, metrics = multi(s2, (xs, ys))
    assert metrics["loss"].shape == (4,)
    assert int(s2.step) == int(s1.step) == 4
    np.testing.assert_allclose(float(metrics["loss"][-1]), float(m1["loss"]),
                               rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), s1.params, s2.params)


def test_gradient_accumulation_matches_full_batch():
    """accum_steps=4 on one batch == the full-batch gradient step (mean
    loss, no dropout)."""
    import numpy as np
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    (xt, yt), _ = data.xor_data(80, val_size=10, seed=0)
    batch = (xt[:80], yt[:80])

    s1 = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    full = train.make_train_step(model, "mse", opt)
    s1, m1 = full(s1, batch)

    s2 = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    accum = train.make_train_step(model, "mse", opt, accum_steps=4)
    s2, m2 = accum(s2, batch)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), s1.params, s2.params)


def test_async_checkpointer_roundtrip_and_errors(tmp_path):
    import numpy as np
    import pytest
    tree = {"a": jax.numpy.arange(6.0).reshape(2, 3), "b": {"c": jax.numpy.ones(4)}}
    ck = train.checkpoint.AsyncCheckpointer()
    ck.save(str(tmp_path), 7, tree)
    ck.wait()
    assert train.checkpoint.latest_step(str(tmp_path)) == 7
    target = jax.tree.map(lambda a: jax.numpy.zeros_like(a), tree)
    out = train.checkpoint.restore(
        target, train.checkpoint.latest_checkpoint(str(tmp_path)))
    np.testing.assert_array_equal(np.asarray(out["a"]), np.asarray(tree["a"]))
    # background failure surfaces on wait()
    ck.save("/proc/definitely/not/writable", 8, tree)
    with pytest.raises(Exception):
        ck.wait()
    ck.close()


def test_session_async_checkpoint_and_resume(tmp_path):
    _, _, state, step, ds = make_bits()
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=5)],
                            async_checkpoint=True) as sess:
        run_session(sess, ds)
    # exit drained the writer: the final save is durable
    assert train.checkpoint.latest_step(d) == 5
    _, _, state2, step2, _ = make_bits()
    with train.TrainSession(state2, step2, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=6)]) as s2:
        assert s2.step == 5


def test_masked_loss_accumulation_exact():
    """Unequal mask counts per microbatch: loss_weight-weighted accumulation
    reproduces the full-batch masked-mean gradient exactly."""
    import numpy as np
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    model = gpt_tiny(dropout_rate=0.0)
    params = model.init(jax.random.PRNGKey(0))
    opt = optim.adam(1e-3)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 512)
    # heavily skewed mask: microbatch 0 has 2 tokens, microbatch 3 has 40
    mask = np.zeros((8, 15), np.float32)
    mask[0, :1] = 1; mask[1, :1] = 1
    mask[2, :4] = 1; mask[3, :4] = 1
    mask[4, :9] = 1; mask[5, :9] = 1
    mask[6:, :] = 1
    batch = {"input_ids": ids, "loss_mask": jnp.asarray(mask)}

    # copy params per state: the jitted steps donate their inputs
    s1 = train.TrainState.create(jax.tree.map(jnp.copy, params),
                                 opt.init(params))
    s2 = train.TrainState.create(jax.tree.map(jnp.copy, params),
                                 opt.init(params))
    full = train.make_custom_train_step(model.lm_loss_fn(), opt)
    s1, m1 = full(s1, batch)
    accum = train.make_custom_train_step(model.lm_loss_fn(), opt,
                                         accum_steps=4)
    s2, m2 = accum(s2, batch)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=2e-5), s1.params, s2.params)


def test_accum_steps_divisibility_error():
    import pytest
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt, accum_steps=4)
    (xt, yt), _ = data.xor_data(30, val_size=10, seed=0)
    with pytest.raises(ValueError, match="not divisible"):
        step(state, (xt[:30], yt[:30]))


def test_eval_hook_runs_periodically_and_at_end():
    model, opt, state, step, ds = make_bits()
    eval_step = train.make_eval_step(model, "mse",
                                     metric_fns={"acc": "bitwise_accuracy"})
    (xv, yv) = data.xor_data(100, val_size=40, seed=1)[1]
    calls = []

    def eval_fn(s):
        m = eval_step(s, (xv, yv))
        calls.append(True)
        return m

    hook = train.EvalHook(eval_fn, every_steps=3)
    with train.TrainSession(state, step,
                            hooks=[hook,
                                   train.StopAtStepHook(last_step=7)]) as sess:
        run_session(sess, ds)
    # fired at steps 3, 6 and once more at end (step 7)
    assert len(calls) == 3
    assert hook.last_metrics is not None
    assert set(hook.last_metrics) == {"val_loss", "val_acc"}


def test_step_counter_hook(tmp_path):
    """StepCounterHook writes steps_per_sec/examples_per_sec scalars
    (tf.train.StepCounterHook parity)."""
    import jax
    from distributed_tensorflow_tpu import data, models, optim, summary, train

    model = models.xor_mlp()
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt)
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    writer = summary.SummaryWriter(str(tmp_path))
    with train.TrainSession(state, step,
                            hooks=[train.StopAtStepHook(6),
                                   train.StepCounterHook(
                                       every_steps=2, writer=writer,
                                       batch_size=50)]) as sess:
        while not sess.should_stop():
            sess.run_step((xt[:50], yt[:50]))
    writer.close()
    import glob
    from tests.test_summary import parse_event, read_records
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)
    tags = []
    for rec in records[1:]:
        ev = parse_event(rec)
        summ = parse_event(ev[5][0])
        for v in summ.get(1, []):
            tags.append(parse_event(v)[1][0])
    assert b"steps_per_sec" in tags and b"examples_per_sec" in tags
