"""Critical-path ledger tests: per-request latency decomposition and
head-of-line interference attribution (obs/critpath.py).

The contracts pinned here (docs/OBSERVABILITY.md §Critical path):
  * phases sum to e2e by construction — for every request, including a
    chaos run with kills, stalls, and migrations (no double-count, no
    loss across engines: the ledger observes each request exactly once),
  * the HOL charging rule: a long prompt landing mid-decode puts
    NONZERO ``prefill_interference`` on the co-scheduled decoders, and
    a decode-only trace (co-submitted equal prompts) measures EXACTLY
    zero,
  * the breakdown survives migration (export→import gap lands in the
    ``migration`` phase; phases carried, not reset),
  * the fleet simulator mirrors the same vocabulary on virtual time,
  * watchdog forensics carry the victim's breakdown, /statusz gains the
    top-K table, and the sentinel gates ``interference_share*`` drift
    (up is bad).
"""
import time

import numpy as np
import pytest

import jax

from distributed_tensorflow_tpu import fleet, serve
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import critpath as critpath_lib
from distributed_tensorflow_tpu.obs import http as http_lib
from distributed_tensorflow_tpu.obs import ledger as ledger_lib
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.obs import reqtrace
from distributed_tensorflow_tpu.obs import sentinel as sentinel_lib
from distributed_tensorflow_tpu.obs import trace as obs_trace
from distributed_tensorflow_tpu.fleet import sim as sim_lib
from distributed_tensorflow_tpu.resilience import faults


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _engine(model, params, reg=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("tick_steps", 2)
    return serve.Engine(model, params,
                        registry=reg or metrics_lib.Registry(), **kw)


def _assert_sums(cp, tol_rel=0.02):
    """The by-construction invariant: the seven exclusive phases sum to
    the measured e2e (``other`` is the clamped remainder, so the only
    slack is boundary clock granularity)."""
    total = sum(cp[p] for p in critpath_lib.PHASES)
    assert all(cp[p] >= 0.0 for p in critpath_lib.PHASES), cp
    assert total == pytest.approx(cp["e2e_s"], rel=tol_rel, abs=1e-6), cp


@pytest.fixture
def req_tracer():
    """Active host tracer + clean reqtrace state (trace ids only mint
    while a tracer is live), torn down either way."""
    reqtrace.reset()
    tracer = obs_trace.activate(obs_trace.Tracer(enabled=True))
    try:
        yield tracer
    finally:
        obs_trace.deactivate(tracer)
        reqtrace.reset()


# ---------------------------------------------------------------------------
# ledger unit surface


def test_finalize_sums_other_clamped_and_share():
    ph = critpath_lib.new_phases()
    assert ph is None                      # nothing active: disabled path
    with critpath_lib.activated(critpath_lib.CritpathLedger()):
        ph = critpath_lib.new_phases()
    assert ph == {p: 0.0 for p in critpath_lib.PHASES[:-1]}
    ph["queue_wait"] = 0.25
    ph["decode_compute"] = 0.5
    ph["prefill_interference"] = 0.25
    cp = critpath_lib.finalize(ph, 1.25)
    assert cp["other"] == pytest.approx(0.25)
    assert cp["interference_share"] == pytest.approx(0.2)
    _assert_sums(cp)
    # overshoot (boundary noise): other clamps at zero, never negative
    cp2 = critpath_lib.finalize(ph, 0.9)
    assert cp2["other"] == 0.0
    # finalize COPIES — the accrual dict is untouched
    assert "other" not in ph and "e2e_s" not in ph


def test_activated_restores_previous_ledger():
    a, b = critpath_lib.CritpathLedger(), critpath_lib.CritpathLedger()
    with critpath_lib.activated(a):
        assert critpath_lib.active() is a
        with critpath_lib.activated(b):
            assert critpath_lib.active() is b
        assert critpath_lib.active() is a
    assert critpath_lib.active() is None


def test_ledger_worst_k_reservoir_and_metrics():
    reg = metrics_lib.Registry()
    led = critpath_lib.CritpathLedger(registry=reg, worst_k=2,
                                      reservoir=4)
    for i in range(6):
        ph = {p: 0.0 for p in critpath_lib.PHASES[:-1]}
        ph["decode_compute"] = 0.1 * (i + 1)
        ph["prefill_interference"] = 0.01 * (i + 1)
        led.observe("t%d" % (i % 2), critpath_lib.finalize(
            ph, 0.2 * (i + 1)), trace_id="id%d" % i)
    worst = led.worst()                    # slowest first, capped at K
    assert [w["trace_id"] for w in worst] == ["id5", "id4"]
    # deterministic reservoir: 6 samples into 4 slots, i % cap overwrite
    assert len(led.interference_shares()) == 4
    rep = led.report()
    assert rep["requests"] == 6
    assert rep["interference_share_p95"] > 0
    assert set(rep["per_tenant"]) == {"t0", "t1"}
    _assert_sums({**rep["phase_seconds"],
                  "e2e_s": rep["e2e_seconds"]}, tol_rel=1e-9)
    # the two exported series, per docs/OBSERVABILITY.md §Critical path
    c = reg.get("dttpu_critpath_seconds_total",
                labels={"phase": "decode_compute", "tenant": "t0"})
    assert c is not None and c.value == pytest.approx(0.1 + 0.3 + 0.5)
    g = reg.get("dttpu_critpath_interference_ratio")
    assert g is not None and 0 < g.value < 1


def test_statusz_includes_critpath_section():
    led = critpath_lib.CritpathLedger()
    ph = {p: 0.0 for p in critpath_lib.PHASES[:-1]}
    ph["prefill_interference"] = 0.5
    led.observe("pro", critpath_lib.finalize(ph, 1.0), trace_id="tid0")
    with critpath_lib.activated(led):
        doc = http_lib.default_statusz()
    assert doc["critpath"]["requests"] == 1
    (row,) = doc["critpath"]["slowest"]
    assert row["trace_id"] == "tid0" and row["tenant"] == "pro"
    assert row["interference_share"] == pytest.approx(0.5)
    assert "critpath" not in http_lib.default_statusz()   # deactivated


def test_sentinel_gates_interference_share_drift():
    assert sentinel_lib.DEFAULT_INTERFERENCE_MAX_RATIO == 1.5
    assert sentinel_lib.classify_field("interference_share_p95") == \
        "lower"
    base = {"measured": {"interference_share_p95": 0.10}}
    sent = sentinel_lib.Sentinel()

    def verdict(v):
        row = {"config": "x",
               "measured": {"interference_share_p95": v}}
        (out,) = [x for x in sent.check(row, baseline=base)
                  if x.field == "interference_share_p95"]
        return out
    assert verdict(0.14).ok                 # 1.4x drift: inside 1.5x
    bad = verdict(0.16)                     # 1.6x drift: up is bad
    assert not bad.ok and "max_ratio 1.5" in bad.detail


def test_bench_row_lifts_interference_fields():
    """The gpt_serve bench row carries the shares at TOP level because
    row_from_bench only lifts top-level numerics into ``measured`` —
    the nested critpath document is detail, not a gated field."""
    row = ledger_lib.row_from_bench({
        "config": "gpt_serve", "interference_share_p95": 0.05,
        "sim_interference_share_p95": 0.06,
        "critpath": {"interference_ratio": 0.04}})
    assert row["measured"]["interference_share_p95"] == 0.05
    assert row["measured"]["sim_interference_share_p95"] == 0.06
    assert "critpath" not in row["measured"]


# ---------------------------------------------------------------------------
# serve engine: planted interference + the exactly-zero control


def test_handle_critpath_none_without_active_ledger():
    model, params = _model_params()
    eng = _engine(model, params)
    h = eng.submit(_prompt(3), 2)
    eng.drain()
    assert h.done and h.critpath is None    # disabled fast path


def test_cosubmitted_decode_only_interference_exactly_zero():
    """Two equal single-window prompts admitted in the SAME tick: both
    are exempt from that tick's prefill wall (they ARE the prefill),
    and no later tick mixes prefill with their decode — interference is
    exactly 0.0, not merely small."""
    model, params = _model_params()
    eng = _engine(model, params)
    with critpath_lib.activated(critpath_lib.CritpathLedger()):
        hs = [eng.submit(_prompt(3, seed=s), 6) for s in (11, 12)]
        eng.drain()
    for h in hs:
        assert h.status == "ok"
        cp = h.critpath
        assert cp["prefill_interference"] == 0.0
        _assert_sums(cp)


def test_planted_long_prompt_interferes_with_decoder():
    """The HOL plant: A is decoding when B's multi-window prompt lands —
    every tick that prefills B while A decodes charges A the window
    wall.  A's interference is nonzero; B (whose own admission tick is
    exempt, and whose decode never shares a tick with a prefill) stays
    at exactly zero."""
    model, params = _model_params()
    eng = _engine(model, params)
    led = critpath_lib.CritpathLedger()
    with critpath_lib.activated(led):
        a = eng.submit(_prompt(3, seed=21), 12)
        while not a.tokens:                 # A through prefill, decoding
            eng.step()
        b = eng.submit(_prompt(10, seed=22), 2)   # 3 windows, mid-decode
        eng.drain()
    assert a.status == "ok" and b.status == "ok"
    cp_a, cp_b = a.critpath, b.critpath
    assert cp_a["prefill_interference"] > 0.0, cp_a
    assert cp_b["prefill_interference"] == 0.0, cp_b
    assert cp_a["interference_share"] > 0.0
    for cp in (cp_a, cp_b):
        _assert_sums(cp)
    # both retirements reached the active ledger exactly once
    rep = led.report()
    assert rep["requests"] == 2
    assert rep["interference_ratio"] > 0.0


def test_migration_carries_phases_and_charges_the_gap():
    """Export mid-decode, import elsewhere: accrued phases ride the
    snapshot, the export→import wall lands in ``migration``, and the
    ledger sees ONE retirement (the source's ``migrated`` status is not
    a retirement)."""
    model, params = _model_params()
    src, dst = _engine(model, params), _engine(model, params)
    led = critpath_lib.CritpathLedger()
    with critpath_lib.activated(led):
        h = src.submit(_prompt(5, seed=31), 10)
        while len(h.tokens) < 4:
            src.step()
        snap = src.export_request(h)
        assert snap.critpath is not None
        carried = snap.critpath["phases"]
        assert carried["decode_compute"] > 0.0
        time.sleep(0.02)                    # a measurable transit gap
        h2 = dst.import_request(snap)
        dst.drain()
    assert h.status == "migrated" and h2.status == "ok"
    cp = h2.critpath
    assert cp["migration"] >= 0.02
    # source-side accrual carried, then grew on the destination
    assert cp["decode_compute"] >= carried["decode_compute"]
    assert cp["e2e_s"] >= snap.critpath["elapsed_s"] + cp["migration"]
    _assert_sums(cp)
    assert led.report()["requests"] == 1    # exactly once, final hop


@pytest.mark.chaos
def test_chaos_sum_invariant_no_double_count_across_engines():
    """THE property test: kill one replica and stall another mid-run —
    every request still retires with a breakdown whose phases sum to
    its e2e, every phase nonnegative, the ledger observes each request
    EXACTLY once despite exports/imports, and at least one migrated
    request shows a positive ``migration`` phase."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    engines = [_engine(model, params, reg=reg) for _ in range(3)]
    router = fleet.Router(engines, registry=reg)
    # warm every executable BEFORE activating the ledger: compile ticks
    # are legitimately slow and the warmup requests must not be counted
    ws = [eng.submit(_prompt(6, seed=50 + j), 3)
          for j, eng in enumerate(engines)]
    for _ in range(8):
        for eng in engines:
            eng.step()
    assert all(w.done for w in ws)
    wd = fleet.Watchdog(router, tick_deadline_s=0.25,
                        export_timeout_s=0.1, registry=reg)
    plan = faults.FaultPlan(
        [{"kind": "kill_replica", "at": 5, "replica": 1},
         {"kind": "stall_tick", "at": 6, "replica": 2, "seconds": 0.6}],
        registry=metrics_lib.Registry())
    led = critpath_lib.CritpathLedger(worst_k=16)
    with critpath_lib.activated(led), faults.activated(plan):
        hs = [router.submit(_prompt(3 + i % 3, seed=i), 8,
                            deadline_s=120.0) for i in range(8)]
        deadline = time.perf_counter() + 120
        while router.busy:
            assert time.perf_counter() < deadline, "chaos run hung"
            router.step()
            wd.check()
    assert {e["kind"] for e in plan.log} == {"kill_replica",
                                             "stall_tick"}
    migrated = 0
    for i, h in enumerate(hs):
        assert h.status == "ok", (i, h.status)
        cp = h.critpath
        assert cp is not None, i
        _assert_sums(cp)
        if cp["migration"] > 0.0:
            migrated += 1
    assert reg.get("dttpu_migrations_total").value >= 1
    assert migrated >= 1                    # the gap was charged
    # exactly once per request: migrated hops retired on ONE engine
    assert led.report()["requests"] == len(hs)


def test_watchdog_forensics_include_victim_breakdown(req_tracer):
    """A quarantine's forensic dumps carry each victim's critpath
    accrual so far, captured BEFORE the export moved it away."""
    model, params = _model_params()
    engines = [_engine(model, params) for _ in range(2)]
    router = fleet.Router(engines, registry=metrics_lib.Registry())
    led = critpath_lib.CritpathLedger()
    with critpath_lib.activated(led):
        hs = [router.submit(_prompt(5, seed=70 + i), 8)
              for i in range(3)]
        while not any(len(h.tokens) >= 2 for h in hs):
            router.step()
        wd = fleet.Watchdog(router, tick_deadline_s=5.0,
                            registry=metrics_lib.Registry())
        calls = []

        def forced(stats, now=None):
            calls.append(1)
            return "stalled: forced by test" if len(calls) == 1 else None

        wd.verdict = forced
        hits = wd.check()
        assert hits and hits[0][0] == 0
        dumps = reqtrace.forensics_log()
        assert dumps
        for d in dumps:
            cp = d["context"]["critpath"]
            assert set(critpath_lib.PHASES) <= set(cp)
            assert cp["e2e_s"] > 0.0
        while any(not h.done for h in hs):
            router.step()
    assert all(h.status == "ok" for h in hs)


# ---------------------------------------------------------------------------
# fleet simulator mirror (virtual time)


def _sim_engine(**kw):
    cm = sim_lib.CostModel(prefill_window_s=0.01, decode_tick_s=0.002,
                           overhead_s=0.0)
    kw.setdefault("num_slots", 4)
    kw.setdefault("prefill_chunk", 32)
    kw.setdefault("tick_steps", 4)
    return sim_lib.SimEngine(cm, **kw)


def test_sim_cosubmitted_zero_staggered_nonzero():
    # co-submitted: both prefill in the same tick — exempt, exactly 0.0
    eng = _sim_engine()
    r1, r2 = eng.submit(16, 5), eng.submit(16, 5)
    assert eng.drain()
    assert r1.cp_interf == 0.0 and r2.cp_interf == 0.0
    assert r1.cp_prefill == pytest.approx(0.01)
    # staggered: r1 decoding when r2's two windows run — r1 is charged
    # exactly two window walls; r2's own decode shares no prefill tick
    eng = _sim_engine()
    r1 = eng.submit(16, 20)
    eng.step()                              # r1 admitted + first token
    assert r1.emitted == 1
    r2 = eng.submit(64, 4)                  # 2 windows, lands mid-decode
    assert eng.drain()
    assert r1.cp_interf == pytest.approx(2 * 0.01)
    assert r2.cp_interf == 0.0
    assert r2.cp_prefill == pytest.approx(2 * 0.01)
    # the handle surface the router's FleetHandle reads
    assert set(critpath_lib.PHASES[:-1]) == set(r1.critpath)


def test_sim_export_import_carries_and_charges_virtual_gap():
    clock = sim_lib.SimClock(0.0)
    a = _sim_engine(clock=clock)
    b = _sim_engine(clock=clock)
    r = a.submit(16, 12)
    a._tick_once()
    a._tick_once()                          # decoding
    assert r.emitted > 1
    pre = dict(r.critpath)
    snap = a.export_request(r)
    assert snap.critpath["exported_at"] == 0.0
    clock.now = 0.5                         # half a virtual second away
    r2 = b.import_request(snap)
    assert b.drain()
    assert r2.status == "ok"
    assert r2.cp_migr == pytest.approx(0.5)
    assert r2.cp_decode >= pre["decode_compute"]
    assert r2.cp_prefill >= pre["prefill_compute"]  # re-prefill is real


def test_fleet_sim_reports_deterministic_interference():
    from distributed_tensorflow_tpu.fleet import workload

    def run():
        cm = sim_lib.CostModel.analytic(
            n_params=1e8, prefill_chunk=64, num_slots=8, tick_steps=16)
        tr = workload.synthesize(1500, seed=7, horizon_s=20.0)
        return sim_lib.FleetSim(
            tr, cm, replicas=2,
            engine={"num_slots": 8, "prefill_chunk": 64,
                    "tick_steps": 16}).run()

    r1, r2 = run(), run()
    assert r1["interference_share_p95"] > 0.0
    assert r1["interference_share_p50"] == r2["interference_share_p50"]
    assert r1["interference_share_p95"] == r2["interference_share_p95"]
