"""ViT model family tests (forward shapes, training, remat parity, TP)."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import optim, train
from distributed_tensorflow_tpu.models.vit import vit_tiny


def _data(n=32, size=32, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, size, size, 3).astype("float32")
    y = rng.randint(0, classes, size=(n,)).astype("int32")
    return jnp.asarray(x), jnp.asarray(y)


def test_forward_shapes_and_dtype():
    m = vit_tiny()
    params = m.init(jax.random.PRNGKey(0))
    x, _ = _data(4)
    logits = m.apply(params, x)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32
    # 32/8 = 4 -> 16 patches + CLS
    assert params["pos_embed"].shape == (1, 17, 64)


def test_vit_trains():
    m = vit_tiny()
    params = m.init(jax.random.PRNGKey(0))
    opt = optim.adam(1e-3)
    state = train.TrainState.create(params, opt.init(params), {})
    step = train.make_custom_train_step(m.loss_fn(), opt)
    x, y = _data(32)
    losses = []
    for _ in range(20):
        state, metrics = step(state, (x, y))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7
    assert np.isfinite(losses[-1])


def test_remat_forward_parity():
    x, _ = _data(4)
    a = vit_tiny(remat=False)
    b = vit_tiny(remat=True)
    params = a.init(jax.random.PRNGKey(0))
    la = a.apply(params, x)
    lb = b.apply(params, x)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=1e-5)


def test_vit_bf16_compute():
    m = vit_tiny(dtype=jnp.bfloat16)
    params = m.init(jax.random.PRNGKey(0))
    x, y = _data(8)
    logits = m.apply(params, x)
    assert logits.dtype == jnp.float32  # widened at the head
    assert np.isfinite(np.asarray(logits)).all()


def test_vit_tensor_parallel_step():
    """TP+DP sharded ViT gradient step on the 8-device mesh."""
    from distributed_tensorflow_tpu.parallel import make_mesh
    mesh = make_mesh({"data": 4, "tensor": 2})
    m = vit_tiny(num_heads=2)
    params = m.init(jax.random.PRNGKey(0))
    rules = m.partition_rules()
    opt = optim.adam(1e-3)
    state = train.TrainState.create(params, opt.init(params), {})
    state = train.shard_train_state(state, mesh, rules)
    assert "tensor" in str(
        state.params["encoder"]["ffn"]["w_in"]["kernel"].sharding.spec)
    step = train.make_custom_train_step(m.loss_fn(), opt)
    from jax.sharding import NamedSharding, PartitionSpec as P
    x, y = _data(8)
    batch = (jax.device_put(x, NamedSharding(mesh, P("data"))),
             jax.device_put(y, NamedSharding(mesh, P("data"))))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_bad_patch_size_rejected():
    import pytest
    m = vit_tiny(patch_size=7)
    with pytest.raises(ValueError, match="divisible"):
        m.init(jax.random.PRNGKey(0))
