"""Pipeline parallelism (GPipe schedule) tests on the 8-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.pipeline import (
    pipeline_apply, pipeline_rules_spec, stack_pipeline_params)

HID = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(n, key=0):
    keys = jax.random.split(jax.random.PRNGKey(key), n)
    return [{"w": jax.random.normal(k, (HID, HID)) * 0.5,
             "b": jnp.zeros((HID,))} for k in keys]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_pipeline_matches_sequential():
    mesh = make_mesh({"pipe": 8})
    stages = _stages(8)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (24, HID))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=4)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_single_microbatch():
    mesh = make_mesh({"pipe": 8})
    stages = _stages(8)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(2), (6, HID))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=1)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)), atol=1e-5)


def test_pipeline_microbatches_exceed_stages():
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (32, HID))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=8)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)), atol=1e-5)


def test_pipeline_backward_matches_sequential():
    """jax.grad through the scan+ppermute program IS the backward pipeline."""
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(4), (8, HID))

    def pipe_loss(stacked, x):
        return (pipeline_apply(_stage_fn, stacked, x, mesh,
                               num_microbatches=2) ** 2).mean()

    def ref_loss(stages, x):
        return (_sequential(stages, x) ** 2).mean()

    g = jax.grad(pipe_loss)(stacked, x)
    g_ref_list = jax.grad(ref_loss)(stages, x)
    g_ref = stack_pipeline_params(g_ref_list)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), g, g_ref)


def test_pipeline_sharded_params_inside_jit():
    """Stacked params placed P('pipe') on a pipe×data mesh, under jit."""
    mesh = make_mesh({"pipe": 4, "data": 2})
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    specs = pipeline_rules_spec(stacked)
    stacked = jax.device_put(
        stacked, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                              is_leaf=lambda v: isinstance(v, P)))
    x = jax.random.normal(jax.random.PRNGKey(5), (16, HID))
    x = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def f(stacked, x):
        return pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=4)

    out = f(stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)), atol=1e-5)


def test_pipeline_mixed_precision_carry():
    """bf16 batch through f32 stage params: carry dtype resolves, no trace
    error, result matches the sequential reference in f32."""
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(6), (8, HID)).astype(jnp.bfloat16)
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=2)
    ref = _sequential(stages, x.astype(jnp.float32))
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


def test_pipeline_stage_count_mismatch_raises():
    import pytest
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stacked = stack_pipeline_params(_stages(8))
    x = jnp.zeros((8, HID))
    with pytest.raises(ValueError, match="drop stages"):
        pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=2)
