"""Pipeline parallelism (GPipe schedule) tests on the 8-device mesh."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.pipeline import (
    pipeline_apply, pipeline_rules_spec, stack_pipeline_params)

HID = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(n, key=0):
    keys = jax.random.split(jax.random.PRNGKey(key), n)
    return [{"w": jax.random.normal(k, (HID, HID)) * 0.5,
             "b": jnp.zeros((HID,))} for k in keys]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_pipeline_matches_sequential():
    mesh = make_mesh({"pipe": 8})
    stages = _stages(8)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (24, HID))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=4)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_single_microbatch():
    mesh = make_mesh({"pipe": 8})
    stages = _stages(8)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(2), (6, HID))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=1)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)), atol=1e-5)


def test_pipeline_microbatches_exceed_stages():
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (32, HID))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=8)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)), atol=1e-5)


def test_pipeline_backward_matches_sequential():
    """jax.grad through the scan+ppermute program IS the backward pipeline."""
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(4), (8, HID))

    def pipe_loss(stacked, x):
        return (pipeline_apply(_stage_fn, stacked, x, mesh,
                               num_microbatches=2) ** 2).mean()

    def ref_loss(stages, x):
        return (_sequential(stages, x) ** 2).mean()

    g = jax.grad(pipe_loss)(stacked, x)
    g_ref_list = jax.grad(ref_loss)(stages, x)
    g_ref = stack_pipeline_params(g_ref_list)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), g, g_ref)


def test_pipeline_sharded_params_inside_jit():
    """Stacked params placed P('pipe') on a pipe×data mesh, under jit."""
    mesh = make_mesh({"pipe": 4, "data": 2})
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    specs = pipeline_rules_spec(stacked)
    stacked = jax.device_put(
        stacked, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                              is_leaf=lambda v: isinstance(v, P)))
    x = jax.random.normal(jax.random.PRNGKey(5), (16, HID))
    x = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def f(stacked, x):
        return pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=4)

    out = f(stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_sequential(stages, x)), atol=1e-5)


def test_pipeline_mixed_precision_carry():
    """bf16 batch through f32 stage params: carry dtype resolves, no trace
    error, result matches the sequential reference in f32."""
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stages = _stages(4)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(6), (8, HID)).astype(jnp.bfloat16)
    out = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=2)
    ref = _sequential(stages, x.astype(jnp.float32))
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


def test_pipeline_stage_count_mismatch_raises():
    import pytest
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stacked = stack_pipeline_params(_stages(8))
    x = jnp.zeros((8, HID))
    with pytest.raises(ValueError, match="drop stages"):
        pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=2)


def test_1f1b_matches_gpipe_autodiff():
    """pipeline_value_and_grad (hand-scheduled 1F1B) returns the same loss
    and gradients as jax.value_and_grad through the GPipe program."""
    from distributed_tensorflow_tpu.parallel.pipeline import (
        pipeline_value_and_grad)
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stages = _stages(4, key=7)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(5), (24, HID))
    y = jax.random.normal(jax.random.PRNGKey(6), (24, HID))

    def loss_fn(out, y_mb):
        return ((out - y_mb) ** 2).mean()

    loss, grads = pipeline_value_and_grad(
        _stage_fn, loss_fn, stacked, x, y, mesh, num_microbatches=6)

    def gpipe_loss(stacked, x):
        out = pipeline_apply(_stage_fn, stacked, x, mesh,
                             num_microbatches=6)
        return ((out - y) ** 2).mean()

    ref_loss, ref_grads = jax.value_and_grad(gpipe_loss)(stacked, x)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-4), grads, ref_grads)


def test_1f1b_few_microbatches_and_jit():
    """M < S still schedules correctly; the whole pass jits."""
    from distributed_tensorflow_tpu.parallel.pipeline import (
        pipeline_value_and_grad)
    mesh = make_mesh({"pipe": 8})
    stages = _stages(8, key=9)
    stacked = stack_pipeline_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(10), (8, HID))
    y = jax.random.normal(jax.random.PRNGKey(11), (8, HID))

    def loss_fn(out, y_mb):
        return ((out - y_mb) ** 2).mean()

    fn = jax.jit(lambda p, x, y: pipeline_value_and_grad(
        _stage_fn, loss_fn, p, x, y, mesh, num_microbatches=2))
    loss, grads = fn(stacked, x, y)

    def ref(stages_list, x):
        return ((_sequential(stages_list, x) - y) ** 2).mean()

    ref_loss, ref_list = jax.value_and_grad(ref)(stages, x)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    ref_grads = stack_pipeline_params(ref_list)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-4), grads, ref_grads)


def test_1f1b_sgd_training_converges():
    """A few 1F1B steps reduce the loss (grads point the right way)."""
    from distributed_tensorflow_tpu.parallel.pipeline import (
        pipeline_value_and_grad)
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stacked = stack_pipeline_params(_stages(4, key=3))
    x = jax.random.normal(jax.random.PRNGKey(12), (16, HID))
    y = jnp.tanh(jax.random.normal(jax.random.PRNGKey(13), (16, HID)))

    def loss_fn(out, y_mb):
        return ((out - y_mb) ** 2).mean()

    step = jax.jit(lambda p, x, y: pipeline_value_and_grad(
        _stage_fn, loss_fn, p, x, y, mesh, num_microbatches=4))
    losses = []
    for _ in range(40):
        loss, grads = step(stacked, x, y)
        losses.append(float(loss))
        stacked = jax.tree.map(lambda p, g: p - 0.1 * g, stacked, grads)
    assert losses[-1] < losses[0] * 0.7


def _gpt_pair(mesh, stages=4, **overrides):
    """(reference model, pipelined model) sharing one GPTConfig base."""
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    base = dict(vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
                intermediate_size=64, max_position=16, dropout_rate=0.0)
    base.update(overrides)
    ref = GPT(GPTConfig(**base))
    pp = GPT(GPTConfig(**base, pipeline_stages=stages), mesh=mesh)
    return ref, pp


def test_gpt_pipeline_forward_matches_sequential():
    """GPT with pipeline_stages=4: hidden states match the plain scanned
    stack bit-for-tolerance — the model-zoo wiring of parallel.pipeline."""
    import numpy as np
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    ref, pp = _gpt_pair(mesh)
    params = ref.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    h_ref = ref.apply(params, ids)
    h_pp = pp.apply(params, ids)
    np.testing.assert_allclose(np.asarray(h_pp), np.asarray(h_ref),
                               atol=1e-5)


def test_gpt_pipeline_loss_and_grads_match():
    """jax.grad through the pipelined lm_loss_fn == the non-pp gradients
    (the backward pipeline is the autodiff transpose)."""
    import numpy as np
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    ref, pp = _gpt_pair(mesh)
    params = ref.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(2), (8, 17), 0, 64)
    batch = {"input_ids": ids}
    rng = jax.random.PRNGKey(3)

    def loss_of(model):
        return lambda p: model.lm_loss_fn()(p, None, batch, rng, True)[0]

    l_ref, g_ref = jax.value_and_grad(loss_of(ref))(params)
    l_pp, g_pp = jax.value_and_grad(loss_of(pp))(params)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=2e-4), g_pp, g_ref)


def test_gpt_pipeline_training_trajectory_matches():
    """Three pipelined train steps on a dp2 x pipe4 mesh track the non-pp
    loss trajectory, with the decoder's layer dim sharded over pipe
    (partition_rules) — pp as a usable training strategy, not a primitive."""
    import numpy as np
    from jax.sharding import NamedSharding
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.parallel.sharding import shard_pytree

    mesh = make_mesh({"data": 2, "pipe": 4})
    ref, pp = _gpt_pair(mesh)
    params = ref.init(jax.random.PRNGKey(0))
    specs = pp.partition_rules().tree_specs(params)
    assert "pipe" in str(specs["decoder"]["ffn"]["w_in"]["kernel"])
    # a separate tree for the pp path: the train step donates its state, so
    # the two paths must not alias buffers
    pp_params = shard_pytree(ref.init(jax.random.PRNGKey(0)), mesh,
                             pp.partition_rules())
    optimizer = optim.sgd(0.1)
    step_ref = train.make_custom_train_step(ref.lm_loss_fn(), optimizer)
    step_pp = train.make_custom_train_step(pp.lm_loss_fn(), optimizer)
    state_ref = train.TrainState.create(params, optimizer.init(params))
    state_pp = train.TrainState.create(pp_params, optimizer.init(pp_params))
    ids = jax.random.randint(jax.random.PRNGKey(4), (8, 17), 0, 64)
    batch = {"input_ids": jax.device_put(
        ids, NamedSharding(mesh, jax.sharding.PartitionSpec("data")))}
    for _ in range(3):
        state_ref, m_ref = step_ref(state_ref, batch)
        state_pp, m_pp = step_pp(state_pp, batch)
        np.testing.assert_allclose(float(m_pp["loss"]),
                                   float(m_ref["loss"]), rtol=1e-4)


def test_gpt_1f1b_full_model_grads_match_gpipe():
    """lm_1f1b_value_and_grad (hand-scheduled 1F1B, O(stages) memory)
    returns the same loss AND the same full-model gradient tree —
    embeddings (tied: lookup + head paths), decoder stages, final LN — as
    jax.value_and_grad through the GPipe lm_loss_fn.  Dropout ON: both
    schedules draw identical per-layer/per-microbatch masks."""
    import numpy as np
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    _, pp = _gpt_pair(mesh, dropout_rate=0.1)
    params = pp.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(5), (8, 17), 0, 64)
    batch = {"input_ids": ids}
    rng = jax.random.PRNGKey(6)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: pp.lm_loss_fn()(p, None, batch, rng, True)[0])(params)
    loss, grads = pp.lm_1f1b_value_and_grad(params, batch, rng, True)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert jax.tree.structure(grads) == jax.tree.structure(ref_grads)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=3e-4), grads, ref_grads)


def test_gpt_1f1b_loss_mask_matches_gpipe():
    """With a ragged loss_mask the 1F1B path must reproduce the GPipe
    GLOBAL masked mean (per-microbatch masked means are reweighted by
    each microbatch's mask share) — loss and grads."""
    import numpy as np
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    _, pp = _gpt_pair(mesh)
    params = pp.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(8), (8, 17), 0, 64)
    # uneven mask: microbatches carry different token counts
    mask = (jax.random.uniform(jax.random.PRNGKey(9), (8, 16)) < 0.6
            ).astype(jnp.float32)
    batch = {"input_ids": ids, "loss_mask": mask}
    rng = jax.random.PRNGKey(10)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: pp.lm_loss_fn()(p, None, batch, rng, True)[0])(params)
    loss, grads = pp.lm_1f1b_value_and_grad(params, batch, rng, True)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=3e-4), grads, ref_grads)


def test_gpt_1f1b_train_step_converges():
    """make_1f1b_train_step drives real updates: loss drops over a few
    steps on a repeated batch (full 1F1B path under jit, donated state)."""
    from distributed_tensorflow_tpu import optim, train
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    _, pp = _gpt_pair(mesh)
    params = pp.init(jax.random.PRNGKey(0))
    optimizer = optim.adam(1e-2)
    step = train.make_1f1b_train_step(pp, optimizer, grad_clip_norm=1.0)
    state = train.TrainState.create(params, optimizer.init(params))
    ids = jax.random.randint(jax.random.PRNGKey(7), (8, 17), 0, 64)
    losses = []
    for _ in range(8):
        state, m = step(state, {"input_ids": ids})
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses


def test_gpt_pipeline_config_validation():
    import pytest
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    with pytest.raises(ValueError, match="not divisible"):
        GPTConfig(num_layers=5, pipeline_stages=4)
    with pytest.raises(ValueError, match="MoE"):
        GPTConfig(num_layers=4, pipeline_stages=4, moe_experts=2)
    with pytest.raises(ValueError, match="seq_axis"):
        GPTConfig(num_layers=4, pipeline_stages=4, seq_axis="seq")
    mesh_less = GPT(GPTConfig(num_layers=4, hidden_size=32, num_heads=2,
                              vocab_size=64, intermediate_size=64,
                              max_position=16, pipeline_stages=4))
    with pytest.raises(ValueError, match="mesh"):
        mesh_less.apply(mesh_less.init(jax.random.PRNGKey(0)),
                        jnp.zeros((4, 8), jnp.int32))


def test_1f1b_mixed_precision_stage():
    """bf16-compute stages on f32 carries: the backward's recomputed output
    must cast to the carry dtype or the cotangent is rejected."""
    from distributed_tensorflow_tpu.parallel.pipeline import (
        pipeline_value_and_grad)
    mesh = make_mesh({"pipe": 4}, jax.devices()[:4])
    stacked = stack_pipeline_params(_stages(4, key=21))

    def bf16_stage(params, x):
        return jnp.tanh(x.astype(jnp.bfloat16)
                        @ params["w"].astype(jnp.bfloat16)
                        + params["b"].astype(jnp.bfloat16))

    x = jax.random.normal(jax.random.PRNGKey(22), (8, HID))
    y = jax.random.normal(jax.random.PRNGKey(23), (8, HID))
    loss, grads = pipeline_value_and_grad(
        bf16_stage, lambda o, yy: ((o.astype(jnp.float32) - yy) ** 2).mean(),
        stacked, x, y, mesh, num_microbatches=2)
    assert np.isfinite(float(loss))
    assert all(bool(jnp.isfinite(g).all())
               for g in jax.tree_util.tree_leaves(grads))
