"""High-level Sequential API tests (reference example2.py:148-200 capability)."""
import glob

import numpy as np

from distributed_tensorflow_tpu import data, models, ops


def xor_model():
    model = models.Sequential()
    model.add(ops.Dense(64, "relu"))
    model.add(ops.Dense(32, "sigmoid"))
    model.compile(loss="mean_squared_error", optimizer="adam",
                  metrics=["bitwise_accuracy"])
    return model


def test_fit_evaluate_predict():
    (xt, yt), (xv, yv) = data.xor_data(600, val_size=64, seed=0)
    model = xor_model()
    hist = model.fit(xt, yt, epochs=2, batch_size=50,
                     validation_data=(xv, yv), verbose=0)
    assert set(hist.history) >= {"loss", "bitwise_accuracy", "val_loss",
                                 "val_bitwise_accuracy"}
    assert len(hist.history["loss"]) == 2
    out = model.evaluate(xv, yv, verbose=0)
    assert "loss" in out and "bitwise_accuracy" in out
    preds = model.predict(xv)
    assert preds.shape == (64, 32)
    assert 0.0 <= preds.min() and preds.max() <= 1.0


def test_tensorboard_callback(tmp_path):
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    model.fit(xt, yt, epochs=2, batch_size=50, verbose=0,
              callbacks=[models.TensorBoard(str(tmp_path))])
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    from tests.test_summary import read_records
    assert len(read_records(files[0])) == 3  # version + 2 epochs


def test_early_stopping():
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    stopper = models.EarlyStopping(monitor="loss", patience=1,
                                   min_delta=10.0)  # impossible improvement
    hist = model.fit(xt, yt, epochs=10, batch_size=50, verbose=0,
                     callbacks=[stopper])
    assert len(hist.history["loss"]) < 10


def test_mesh_compile_fit():
    """High-level API runs data-parallel over the 8-device mesh."""
    from distributed_tensorflow_tpu import parallel
    (xt, yt), (xv, yv) = data.xor_data(512, val_size=64, seed=0)
    model = models.Sequential([ops.Dense(64, "relu"),
                               ops.Dense(32, "sigmoid")])
    model.compile(loss="mse", optimizer="adam", metrics=["bitwise_accuracy"],
                  mesh=parallel.data_parallel_mesh())
    hist = model.fit(xt, yt, epochs=2, batch_size=64,
                     validation_data=(xv, yv), verbose=0)
    assert len(hist.history["loss"]) == 2


def test_summary(capsys):
    model = xor_model()
    model.build((64,))
    text = model.summary()
    assert "Total params" in text


def test_save_load_weights_and_model_checkpoint(tmp_path):
    import numpy as np
    from distributed_tensorflow_tpu import models, ops
    from distributed_tensorflow_tpu.models.callbacks import ModelCheckpoint

    rng = np.random.default_rng(0)
    x = rng.random((128, 8), np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int32)

    m = models.Sequential([ops.Dense(16, activation="relu"), ops.Dense(2)])
    m.compile("sparse_categorical_crossentropy", metrics=["accuracy"])
    ckdir = str(tmp_path / "cb")
    m.fit(x, y, epochs=2, batch_size=32, verbose=0,
          validation_data=(x, y),
          callbacks=[ModelCheckpoint(ckdir, save_best_only=True)])
    import os
    assert any(p.startswith("ckpt-") for p in os.listdir(ckdir))

    wdir = str(tmp_path / "w")
    m.save_weights(wdir)
    preds = m.predict(x[:8])

    m2 = models.Sequential([ops.Dense(16, activation="relu"), ops.Dense(2)])
    m2.compile("sparse_categorical_crossentropy")
    m2.build((8,), seed=123)          # different init
    m2.load_weights(wdir)
    np.testing.assert_allclose(np.asarray(m2.predict(x[:8])),
                               np.asarray(preds), rtol=1e-5)


def test_model_checkpoint_loadable_mode_auto_and_nan_guard(tmp_path):
    import math
    import numpy as np
    import pytest
    from distributed_tensorflow_tpu import models, ops
    from distributed_tensorflow_tpu.models.callbacks import (ModelCheckpoint,
                                                             _monitor_sign)

    assert _monitor_sign("auto", "val_loss") == 1.0
    assert _monitor_sign("auto", "val_accuracy") == -1.0
    with pytest.raises(ValueError, match="mode"):
        _monitor_sign("bogus", "val_loss")

    rng = np.random.default_rng(0)
    x = rng.random((64, 8), np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int32)
    m = models.Sequential([ops.Dense(8, activation="relu"), ops.Dense(2)])
    m.compile("sparse_categorical_crossentropy")
    ckdir = str(tmp_path)
    cb = ModelCheckpoint(ckdir, save_best_only=True)
    m.fit(x, y, epochs=1, batch_size=32, verbose=0, validation_data=(x, y),
          callbacks=[cb])
    # these checkpoints load back through the Sequential weights API
    preds = m.predict(x[:4])
    m2 = models.Sequential([ops.Dense(8, activation="relu"), ops.Dense(2)])
    m2.compile("sparse_categorical_crossentropy")
    m2.build((8,), seed=99)
    m2.load_weights(ckdir)
    np.testing.assert_allclose(np.asarray(m2.predict(x[:4])),
                               np.asarray(preds), rtol=1e-5)
    # NaN epochs never become "best"
    best = cb.best
    cb.on_epoch_end(m, 5, {"val_loss": float("nan")})
    assert cb.best == best and math.isfinite(best)
