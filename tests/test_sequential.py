"""High-level Sequential API tests (reference example2.py:148-200 capability)."""
import glob

import numpy as np

from distributed_tensorflow_tpu import data, models, ops


def xor_model():
    model = models.Sequential()
    model.add(ops.Dense(64, "relu"))
    model.add(ops.Dense(32, "sigmoid"))
    model.compile(loss="mean_squared_error", optimizer="adam",
                  metrics=["bitwise_accuracy"])
    return model


def test_fit_evaluate_predict():
    (xt, yt), (xv, yv) = data.xor_data(600, val_size=64, seed=0)
    model = xor_model()
    hist = model.fit(xt, yt, epochs=2, batch_size=50,
                     validation_data=(xv, yv), verbose=0)
    assert set(hist.history) >= {"loss", "bitwise_accuracy", "val_loss",
                                 "val_bitwise_accuracy"}
    assert len(hist.history["loss"]) == 2
    out = model.evaluate(xv, yv, verbose=0)
    assert "loss" in out and "bitwise_accuracy" in out
    preds = model.predict(xv)
    assert preds.shape == (64, 32)
    assert 0.0 <= preds.min() and preds.max() <= 1.0


def test_tensorboard_callback(tmp_path):
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    model.fit(xt, yt, epochs=2, batch_size=50, verbose=0,
              callbacks=[models.TensorBoard(str(tmp_path))])
    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    from tests.test_summary import parse_event, read_records
    records = read_records(files[0])
    assert len(records) == 4  # version + graph + 2 epochs
    # exactly one graph event (Event.graph_def, field 4): the Sequential
    # model.layers path (advisor round 2 — previously silently swallowed)
    assert sum(1 for r in records if 4 in parse_event(r)) == 1


def test_early_stopping():
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    stopper = models.EarlyStopping(monitor="loss", patience=1,
                                   min_delta=10.0)  # impossible improvement
    hist = model.fit(xt, yt, epochs=10, batch_size=50, verbose=0,
                     callbacks=[stopper])
    assert len(hist.history["loss"]) < 10


def test_mesh_compile_fit():
    """High-level API runs data-parallel over the 8-device mesh."""
    from distributed_tensorflow_tpu import parallel
    (xt, yt), (xv, yv) = data.xor_data(512, val_size=64, seed=0)
    model = models.Sequential([ops.Dense(64, "relu"),
                               ops.Dense(32, "sigmoid")])
    model.compile(loss="mse", optimizer="adam", metrics=["bitwise_accuracy"],
                  mesh=parallel.data_parallel_mesh())
    hist = model.fit(xt, yt, epochs=2, batch_size=64,
                     validation_data=(xv, yv), verbose=0)
    assert len(hist.history["loss"]) == 2


def test_summary(capsys):
    model = xor_model()
    model.build((64,))
    text = model.summary()
    assert "Total params" in text


def test_save_load_weights_and_model_checkpoint(tmp_path):
    import numpy as np
    from distributed_tensorflow_tpu import models, ops
    from distributed_tensorflow_tpu.models.callbacks import ModelCheckpoint

    rng = np.random.default_rng(0)
    x = rng.random((128, 8), np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int32)

    m = models.Sequential([ops.Dense(16, activation="relu"), ops.Dense(2)])
    m.compile("sparse_categorical_crossentropy", metrics=["accuracy"])
    ckdir = str(tmp_path / "cb")
    m.fit(x, y, epochs=2, batch_size=32, verbose=0,
          validation_data=(x, y),
          callbacks=[ModelCheckpoint(ckdir, save_best_only=True)])
    import os
    assert any(p.startswith("ckpt-") for p in os.listdir(ckdir))

    wdir = str(tmp_path / "w")
    m.save_weights(wdir)
    preds = m.predict(x[:8])

    m2 = models.Sequential([ops.Dense(16, activation="relu"), ops.Dense(2)])
    m2.compile("sparse_categorical_crossentropy")
    m2.build((8,), seed=123)          # different init
    m2.load_weights(wdir)
    np.testing.assert_allclose(np.asarray(m2.predict(x[:8])),
                               np.asarray(preds), rtol=1e-5)


def test_model_checkpoint_loadable_mode_auto_and_nan_guard(tmp_path):
    import math
    import numpy as np
    import pytest
    from distributed_tensorflow_tpu import models, ops
    from distributed_tensorflow_tpu.models.callbacks import (ModelCheckpoint,
                                                             _monitor_sign)

    assert _monitor_sign("auto", "val_loss") == 1.0
    assert _monitor_sign("auto", "val_accuracy") == -1.0
    with pytest.raises(ValueError, match="mode"):
        _monitor_sign("bogus", "val_loss")

    rng = np.random.default_rng(0)
    x = rng.random((64, 8), np.float32)
    y = (x.sum(axis=1) > 4).astype(np.int32)
    m = models.Sequential([ops.Dense(8, activation="relu"), ops.Dense(2)])
    m.compile("sparse_categorical_crossentropy")
    ckdir = str(tmp_path)
    cb = ModelCheckpoint(ckdir, save_best_only=True)
    m.fit(x, y, epochs=1, batch_size=32, verbose=0, validation_data=(x, y),
          callbacks=[cb])
    # these checkpoints load back through the Sequential weights API
    preds = m.predict(x[:4])
    m2 = models.Sequential([ops.Dense(8, activation="relu"), ops.Dense(2)])
    m2.compile("sparse_categorical_crossentropy")
    m2.build((8,), seed=99)
    m2.load_weights(ckdir)
    np.testing.assert_allclose(np.asarray(m2.predict(x[:4])),
                               np.asarray(preds), rtol=1e-5)
    # NaN epochs never become "best"
    best = cb.best
    cb.on_epoch_end(m, 5, {"val_loss": float("nan")})
    assert cb.best == best and math.isfinite(best)


def test_with_lr_scale_wrapper_halves_updates():
    import jax
    import jax.numpy as jnp
    from distributed_tensorflow_tpu import optim

    base = optim.sgd(0.1)
    wrapped = optim.with_lr_scale(base)
    params = {"w": jnp.asarray(1.0)}
    s = wrapped.init(params)
    assert optim.get_lr_scale(s) == 1.0
    g = {"w": jnp.asarray(1.0)}
    u1, _ = wrapped.update(g, s, params)
    s_half = optim.set_lr_scale(s, 0.5)
    u2, s2 = wrapped.update(g, s_half, params)
    np.testing.assert_allclose(float(u2["w"]), float(u1["w"]) * 0.5,
                               rtol=1e-6)
    # the scale survives the update
    assert optim.get_lr_scale(s2) == 0.5
    # non-wrapped state is rejected, not silently misread
    import pytest
    with pytest.raises(ValueError, match="with_lr_scale"):
        optim.get_lr_scale(base.init(params))


def test_lr_scale_zero_freezes_training():
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
    import jax
    # snapshot to host: the jitted step donates the state buffers
    before = jax.tree.map(np.asarray, model.state.params)
    model.lr_scale = 0.0
    model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
    deltas = jax.tree.map(lambda a, b: float(abs(np.asarray(a) -
                                                 np.asarray(b)).max()),
                          before, model.state.params)
    assert max(jax.tree_util.tree_leaves(deltas)) == 0.0


def test_learning_rate_scheduler_callback():
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    seen = []
    sched = models.LearningRateScheduler(
        lambda epoch: [1.0, 0.25][epoch])
    probe = models.LambdaCallback(
        on_epoch_begin=lambda m, e: seen.append(m.lr_scale))
    model.fit(xt, yt, epochs=2, batch_size=50, verbose=0,
              callbacks=[sched, probe])
    assert seen == [1.0, 0.25]


def test_reduce_lr_on_plateau():
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    plateau = models.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                       patience=1, min_delta=10.0)
    model.fit(xt, yt, epochs=4, batch_size=50, verbose=0,
              callbacks=[plateau])
    # impossible min_delta: every epoch after the first is a plateau;
    # patience=1 -> reductions at epochs 1, 2, 3 -> 0.5^3
    np.testing.assert_allclose(model.lr_scale, 0.125, rtol=1e-6)


def test_csv_logger(tmp_path):
    (xt, yt), (xv, yv) = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    path = str(tmp_path / "log.csv")
    model.fit(xt, yt, epochs=3, batch_size=50, verbose=0,
              validation_data=(xv, yv),
              callbacks=[models.CSVLogger(path)])
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 4  # header + 3 epochs
    header = lines[0].split(",")
    assert header[0] == "epoch" and "loss" in header and \
        "val_loss" in header
    assert lines[1].split(",")[0] == "0"


def test_terminate_on_nan_stops():
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    # poison the loss via a callback that injects NaN params after epoch 0
    import jax
    def poison(m, e, logs):
        if e == 0:
            m.state = m.state._replace(
                params=jax.tree.map(lambda p: p * np.nan, m.state.params))
    hist = model.fit(xt, yt, epochs=10, batch_size=50, verbose=0,
                     callbacks=[models.LambdaCallback(on_epoch_end=poison),
                                models.TerminateOnNaN()])
    assert len(hist.history["loss"]) < 10


def test_model_save_load_roundtrip(tmp_path):
    """model.save -> load_model: same architecture, same predictions,
    compile config restored (Keras model.save/load_model parity)."""
    (xt, yt), (xv, yv) = data.xor_data(300, val_size=32, seed=0)
    model = models.Sequential([
        ops.Dense(64, "relu"),
        ops.Dropout(0.3),
        ops.Dense(32, "sigmoid"),
    ])
    model.compile(loss="mse", optimizer="adam", metrics=["bitwise_accuracy"])
    model.fit(xt, yt, epochs=2, batch_size=50, verbose=0)
    before = model.predict(xv)
    path = str(tmp_path / "saved")
    model.save(path)

    loaded = models.load_model(path)
    after = loaded.predict(xv)
    np.testing.assert_allclose(np.asarray(after), np.asarray(before),
                               atol=1e-6)
    # the restored model is trainable immediately (compile config kept)
    loaded.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
    # and evaluation still reports the compiled metric set
    out = loaded.evaluate(xv, yv, verbose=0)
    assert "bitwise_accuracy" in out


def test_model_to_json_from_json():
    model = models.Sequential([
        ops.Conv2D(8, 3, activation="relu"),
        ops.MaxPool2D(2),
        ops.Flatten(),
        ops.Dense(10),
    ], name="tiny_cnn")
    model.compile(loss="sparse_categorical_crossentropy", optimizer="sgd")
    text = model.to_json()
    rebuilt = models.Sequential.from_json(text)
    assert rebuilt.name == "tiny_cnn"
    assert [type(l).__name__ for l in rebuilt._layers] == \
        ["Conv2D", "MaxPool2D", "Flatten", "Dense"]
    # same param structure when built with the same seed/shape
    import jax
    rebuilt.build((8, 8, 1), seed=0)
    model.build((8, 8, 1), seed=0)
    assert jax.tree_util.tree_structure(model.state.params) == \
        jax.tree_util.tree_structure(rebuilt.state.params)
    leaves_a = jax.tree_util.tree_leaves(model.state.params)
    leaves_b = jax.tree_util.tree_leaves(rebuilt.state.params)
    for a, b in zip(leaves_a, leaves_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_callable_activation_refuses_serialization():
    import pytest
    import jax
    model = models.Sequential([ops.Dense(4, activation=jax.nn.relu)])
    model.compile(loss="mse", optimizer="sgd")
    model.build((8,))
    with pytest.raises(ValueError, match="registry name"):
        model.to_json()


def test_batchnorm_layernorm_embedding_serialize(tmp_path):
    """State-carrying layers (BatchNorm running stats) round-trip through
    save_model; Embedding/LayerNorm configs rebuild."""
    x = np.random.RandomState(0).randn(64, 16).astype("float32")
    y = np.random.RandomState(1).randint(0, 2, size=(64, 1)).astype("float32")
    model = models.Sequential([
        ops.Dense(16, "relu"),
        ops.BatchNorm(momentum=0.8),
        ops.LayerNorm(epsilon=1e-5),
        ops.Dense(1, "sigmoid"),
    ])
    model.compile(loss="binary_crossentropy", optimizer="adam")
    model.fit(x, y, epochs=2, batch_size=16, verbose=0)
    path = str(tmp_path / "bn_model")
    model.save(path)
    loaded = models.load_model(path)
    # BatchNorm inference stats must match (they live in model_state)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(model.state.model_state),
                    jax.tree_util.tree_leaves(loaded.state.model_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(loaded.predict(x), model.predict(x),
                               atol=1e-6)
    cfg = ops.Embedding(100, 8).get_config()
    assert cfg == {"vocab_size": 100, "dim": 8, "name": "embedding"}


def test_load_model_compile_false_still_restores_weights(tmp_path):
    (xt, yt), (xv, yv) = data.xor_data(200, val_size=16, seed=0)
    model = xor_model()
    model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
    before = model.predict(xv)
    path = str(tmp_path / "m")
    model.save(path)
    loaded = models.load_model(path, compile=False)
    assert loaded._compiled is None          # uncompiled, as asked
    assert loaded.state is not None          # but the weights DID load
    # user's own compile keeps the weights (Keras recompile semantics)
    loaded.compile(loss="mse", optimizer="sgd")
    np.testing.assert_allclose(np.asarray(loaded.predict(xv)),
                               np.asarray(before), atol=1e-6)


def test_recompile_keeps_weights_resets_opt_state():
    (xt, yt), (xv, yv) = data.xor_data(200, val_size=16, seed=0)
    model = xor_model()
    model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
    import jax
    before = jax.tree.map(np.asarray, model.state.params)
    step_before = int(model.state.step)
    model.compile(loss="mse", optimizer="momentum")
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(model.state.params)):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert int(model.state.step) == step_before
    assert int(model.state.opt_state.count) == 0   # fresh optimizer
    model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)  # trains fine


def test_csv_logger_rewrites_header_on_reuse(tmp_path):
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    model = xor_model()
    path = str(tmp_path / "log.csv")
    cb = models.CSVLogger(path)
    model.fit(xt, yt, epochs=2, batch_size=50, verbose=0, callbacks=[cb])
    model.fit(xt, yt, epochs=1, batch_size=50, verbose=0, callbacks=[cb])
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2                  # truncated: header + 1 epoch
    assert lines[0].startswith("epoch,")    # header present after reuse


def test_csv_logger_append_no_duplicate_header(tmp_path):
    """append=True onto an existing CSV (e.g. a resumed run in a fresh
    process) must not write a second header row mid-file."""
    (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
    path = str(tmp_path / "log.csv")
    model = xor_model()
    model.fit(xt, yt, epochs=2, batch_size=50, verbose=0,
              callbacks=[models.CSVLogger(path)])
    model2 = xor_model()  # fresh callback object = fresh process analogue
    model2.fit(xt, yt, epochs=1, batch_size=50, verbose=0,
               callbacks=[models.CSVLogger(path, append=True)])
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 4                       # 1 header + 3 epoch rows
    assert sum(1 for l in lines if l.startswith("epoch,")) == 1


def test_csv_logger_append_foreign_header_refused(tmp_path):
    """append=True onto a file whose header isn't this logger's format
    must refuse instead of interleaving two incompatible tables."""
    import pytest
    path = str(tmp_path / "log.csv")
    with open(path, "w") as f:
        f.write("step,lr,grad_norm\n0,0.1,2.3\n")
    cb = models.CSVLogger(path, append=True)
    with pytest.raises(ValueError, match="incompatible header"):
        cb.on_train_begin(model=None)


def test_class_weighted_binary_soft_targets():
    """Label-smoothed binary targets (0.9/0.1) take the weight of the
    NEAREST class — a bare int cast floored 0.9 to class 0's weight."""
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.ops import losses
    wl = losses.class_weighted("binary_crossentropy", {0: 1.0, 1: 100.0})
    p = jnp.asarray([[0.8], [0.8]])
    t = jnp.asarray([[0.9], [0.0]])   # soft positive + hard negative
    weighted = float(wl(p, t))
    unweighted = float(losses.get("binary_crossentropy")(p, t))
    # The soft positive (low bce here) must carry class 1's 100x weight and
    # dominate the mean; the broken int cast gave both rows weight 1.0,
    # collapsing the weighted mean onto the unweighted one.
    assert weighted < 0.9 * unweighted


def test_sample_weight_keras_rule():
    """fit(sample_weight=...) applies Keras 2.0.8's exact normalization:
    sum(loss_i * w_i) / count_nonzero(w) (reference example2.py:200's fit
    surface).  Checked numerically against the initial parameters."""
    import numpy as np
    from distributed_tensorflow_tpu import ops

    rng = np.random.default_rng(0)
    x = rng.random((6, 3)).astype(np.float32)
    y = rng.random((6, 2)).astype(np.float32)
    w = np.asarray([2.0, 1.0, 0.0, 1.0, 0.0, 3.0], np.float32)
    model = models.Sequential([ops.Dense(4, activation="relu"),
                               ops.Dense(2)])
    model.compile(loss="mse", optimizer="sgd")
    model.build((3,))
    per = ((model.predict(x) - y) ** 2).mean(axis=1)
    expected = float((per * w).sum() / 4)    # 4 nonzero weights
    hist = model.fit(x, y, epochs=1, batch_size=6, shuffle=False,
                     verbose=0, sample_weight=w)
    assert abs(hist.history["loss"][0] - expected) < 1e-5


def test_sample_weight_zero_excludes_samples():
    """Zero-weighted samples must not influence training: poisoned labels
    at weight 0 leave convergence on the real task intact.  Uses a config
    that demonstrably learns 64-bit XOR (128-128 MLP, 4000 samples) so the
    oracle actually discriminates — a smaller model fails even unweighted."""
    import numpy as np
    (xt, yt), (xv, yv) = data.xor_data(4000, val_size=128, seed=0)
    # append 1000 label-poisoned rows with weight 0
    xp = xt[:1000]
    yp = 1.0 - yt[:1000]
    x = np.concatenate([xt, xp])
    y = np.concatenate([yt, yp])
    w = np.concatenate([np.ones(len(xt)), np.zeros(1000)]).astype(np.float32)
    model = models.Sequential()
    model.add(ops.Dense(128, "relu"))
    model.add(ops.Dense(128, "relu"))
    model.add(ops.Dense(32, "sigmoid"))
    model.compile(loss="mean_squared_error", optimizer="adam",
                  metrics=["bitwise_accuracy"])
    model.fit(x, y, epochs=30, batch_size=100, verbose=0, sample_weight=w)
    acc = model.evaluate(xv, yv, verbose=0)["bitwise_accuracy"]
    assert acc > 0.9


def test_sample_weight_validation():
    import numpy as np
    import pytest
    (xt, yt), _ = data.xor_data(100, val_size=8, seed=0)
    model = xor_model()
    with pytest.raises(ValueError, match="not both"):
        model.fit(xt, yt, epochs=1, verbose=0,
                  sample_weight=np.ones(len(xt)), class_weight={0: 2.0})
    with pytest.raises(ValueError, match="one float per sample"):
        model.fit(xt, yt, epochs=1, verbose=0,
                  sample_weight=np.ones(len(xt) - 1))


def test_sample_weight_on_mesh():
    """The weighted step's 3-tuple batch shards over the data axis."""
    import numpy as np
    from distributed_tensorflow_tpu import ops, parallel

    mesh = parallel.data_parallel_mesh()
    (xt, yt), _ = data.xor_data(400, val_size=8, seed=0)
    w = np.ones(len(xt), np.float32)
    model = models.Sequential([ops.Dense(16, activation="relu"),
                               ops.Dense(32, activation="sigmoid")])
    model.compile(loss="mse", optimizer="adam", mesh=mesh)
    hist = model.fit(xt, yt, epochs=1, batch_size=64, verbose=0,
                     sample_weight=w)
    assert np.isfinite(hist.history["loss"][0])


def test_validation_split():
    (xt, yt), _ = data.xor_data(300, val_size=8, seed=0)
    model = xor_model()
    hist = model.fit(xt, yt, epochs=2, batch_size=50, verbose=0,
                     validation_split=0.2)
    assert "val_loss" in hist.history and len(hist.history["val_loss"]) == 2
    import pytest
    with pytest.raises(ValueError, match="validation_split"):
        xor_model().fit(xt, yt, epochs=1, verbose=0, validation_split=1.5)


def test_on_batch_apis():
    (xt, yt), _ = data.xor_data(128, val_size=8, seed=0)
    model = xor_model()
    m1 = model.train_on_batch(xt[:32], yt[:32])
    assert "loss" in m1 and np.isfinite(m1["loss"])
    step_after = int(model.state.step)
    assert step_after == 1
    m2 = model.test_on_batch(xt[32:64], yt[32:64])
    assert "loss" in m2 and int(model.state.step) == 1  # no state change
    preds = model.predict_on_batch(xt[:16])
    assert preds.shape == (16, 32)


def test_on_batch_with_mesh():
    from distributed_tensorflow_tpu import parallel
    import pytest
    (xt, yt), _ = data.xor_data(128, val_size=8, seed=0)
    model = models.Sequential([ops.Dense(32, "relu"),
                               ops.Dense(32, "sigmoid")])
    model.compile(loss="mse", optimizer="adam",
                  mesh=parallel.data_parallel_mesh())
    m = model.train_on_batch(xt[:64], yt[:64])      # divisible by 8
    assert np.isfinite(m["loss"])
    with pytest.raises(ValueError, match="divisible"):
        model.train_on_batch(xt[:12], yt[:12])
    # eval accepts a non-divisible remainder batch (sharding propagates)
    m = model.test_on_batch(xt[:12], yt[:12])
    assert np.isfinite(m["loss"])


def test_zoo_stack_serializes_through_sequential(tmp_path):
    """zoo models are Stacks; Sequential([stack]) round-trips through
    model.save via nested Stack specs."""
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8, 8, 3).astype("float32")
    y = rng.randint(0, 10, 32).astype("int32")
    inner = models.Sequential([models.cifar_cnn(num_classes=10)])
    inner.compile(loss="sparse_categorical_crossentropy", optimizer="adam")
    inner.fit(x, y, epochs=1, batch_size=16, verbose=0)
    path = str(tmp_path / "zoo")
    inner.save(path)
    loaded = models.load_model(path)
    np.testing.assert_allclose(np.asarray(loaded.predict(x[:4])),
                               np.asarray(inner.predict(x[:4])), atol=1e-6)
    import json
    spec = json.load(open(path + "/model.json"))
    assert spec["layers"][0]["class_name"] == "Stack"
    nested = spec["layers"][0]["config"]["layers"]
    assert nested[0]["class_name"] == "Conv2D"


def test_class_weight_shifts_decision_boundary():
    """Upweighting one class reduces its error rate relative to the
    unweighted run (Keras fit(class_weight=...) semantics), and the
    weighted step is cached per weighting."""
    rng = np.random.RandomState(0)
    # imbalanced: 90% class 0, 10% class 1, overlapping features
    n = 512
    y = (rng.rand(n) < 0.1).astype("int32")
    x = (rng.randn(n, 8) + y[:, None] * 1.0).astype("float32")

    def build():
        m = models.Sequential([ops.Dense(16, "relu"), ops.Dense(2)])
        m.compile(loss="sparse_categorical_crossentropy", optimizer="adam")
        return m

    plain = build()
    plain.fit(x, y, epochs=20, batch_size=64, verbose=0)
    weighted = build()
    weighted.fit(x, y, epochs=20, batch_size=64, verbose=0,
                 class_weight={0: 1.0, 1: 8.0})

    import jax
    def recall_minority(m):
        preds = np.argmax(m.predict(x), -1)
        mask = y == 1
        return float((preds[mask] == 1).mean())

    assert recall_minority(weighted) > recall_minority(plain)
    # cached: a second fit with the same weighting reuses the step
    c = weighted._compiled
    assert len(c["weighted_steps"]) == 1
    weighted.fit(x, y, epochs=1, batch_size=64, verbose=0,
                 class_weight={0: 1.0, 1: 8.0})
    assert len(c["weighted_steps"]) == 1


def test_class_weight_validation():
    import pytest
    from distributed_tensorflow_tpu.ops import losses
    (xt, yt), _ = data.xor_data(100, val_size=8, seed=0)
    m = models.Sequential([ops.Dense(8), ops.Dense(32, "sigmoid")])
    m.compile(loss=losses.mean_squared_error, optimizer="sgd")  # callable
    with pytest.raises(ValueError, match="loss NAME"):
        m.fit(xt, yt, epochs=1, verbose=0, class_weight={0: 2.0})
    with pytest.raises(ValueError, match="class_weight supports"):
        losses.class_weighted("mse", {0: 2.0})
    # weighted loss equals unweighted when all weights are 1
    import jax.numpy as jnp
    wl = losses.class_weighted("sparse_categorical_crossentropy",
                               {0: 1.0, 1: 1.0})
    logits = jnp.asarray([[2.0, 0.0], [0.0, 1.0]])
    labels = jnp.asarray([0, 1])
    np.testing.assert_allclose(
        float(wl(logits, labels)),
        float(losses.softmax_cross_entropy_with_integer_labels(
            logits, labels)), rtol=1e-6)


def test_class_weight_out_of_range_classes_weigh_one():
    """The Keras idiom of specifying only the minority class must not
    skew higher class ids onto the largest specified weight."""
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.ops import losses
    wl = losses.class_weighted("sparse_categorical_crossentropy", {1: 10.0})
    base = losses.softmax_cross_entropy_with_integer_labels
    logits = jnp.asarray([[1.0, 0.0, -1.0]] * 3)
    # all labels are class 2 (absent from the dict): weighted == unweighted
    labels2 = jnp.asarray([2, 2, 2])
    np.testing.assert_allclose(float(wl(logits, labels2)),
                               float(base(logits, labels2)), rtol=1e-6)
    # degenerate single-entry dict is NOT a uniform no-op
    wl0 = losses.class_weighted("sparse_categorical_crossentropy", {0: 2.0})
    labels = jnp.asarray([0, 1, 1])
    w = np.asarray([2.0, 1.0, 1.0])
    logp = np.asarray(jnp.log(jnp.exp(logits) /
                              jnp.exp(logits).sum(-1, keepdims=True)))
    nll = -logp[np.arange(3), np.asarray(labels)]
    np.testing.assert_allclose(float(wl0(logits, labels)),
                               float((nll * w).sum() / w.sum()), rtol=1e-5)


def test_get_set_weights_roundtrip():
    (xt, yt), (xv, yv) = data.xor_data(200, val_size=16, seed=0)
    a = xor_model()
    a.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
    weights = a.get_weights()
    assert all(isinstance(w, np.ndarray) for w in weights)
    b = xor_model()
    b.compile(loss="mse", optimizer="adam")
    b.build((64,), seed=99)                # different init
    b.set_weights(weights)
    np.testing.assert_allclose(np.asarray(b.predict(xv)),
                               np.asarray(a.predict(xv)), atol=1e-6)
    import pytest
    with pytest.raises(ValueError, match="expected"):
        b.set_weights(weights[:-1])
    with pytest.raises(ValueError, match="shape mismatch"):
        b.set_weights([w.T for w in weights])


def test_class_weight_edge_cases():
    """Empty dict = unweighted no-op; negative class ids rejected; small
    weight sums divide exactly (no 1.0 denominator floor)."""
    import jax.numpy as jnp
    import pytest
    from distributed_tensorflow_tpu.ops import losses
    base = losses.softmax_cross_entropy_with_integer_labels
    assert losses.class_weighted("sparse_categorical_crossentropy", {}) \
        is losses.get("sparse_categorical_crossentropy")
    with pytest.raises(ValueError, match=">= 0"):
        losses.class_weighted("sparse_categorical_crossentropy",
                              {-1: 0.0, 1: 2.0})
    # uniform small weights must equal the unweighted loss exactly
    wl = losses.class_weighted("sparse_categorical_crossentropy",
                               {0: 0.1, 1: 0.1})
    logits = jnp.asarray([[2.0, 0.0], [0.0, 1.0], [1.0, 1.0]])
    labels = jnp.asarray([0, 1, 0])
    np.testing.assert_allclose(float(wl(logits, labels)),
                               float(base(logits, labels)), rtol=1e-6)


def test_get_weights_layer_order_beyond_ten_layers():
    """11+ same-type layers: flat order is LAYER order, not lexicographic
    dict order (where 'dense_10' would precede 'dense_2')."""
    m = models.Sequential([ops.Dense(4) for _ in range(12)])
    m.compile(loss="mse", optimizer="sgd")
    m.build((4,))
    ws = m.get_weights()
    assert len(ws) == 24                   # kernel+bias per layer
    # poison layer index 2's kernel (per-layer leaf order is sorted:
    # [bias, kernel], so the kernel sits at slot 2*L + 1) and check it
    # lands on 'dense_2', not 'dense_10'
    ws = [w.copy() for w in ws]
    ws[2 * 2 + 1] = np.full_like(ws[2 * 2 + 1], 7.0)
    m.set_weights(ws)
    assert float(np.asarray(
        m.state.params["dense_2"]["kernel"]).max()) == 7.0
    assert float(np.asarray(
        m.state.params["dense_10"]["kernel"]).max()) < 7.0


class TestStepsPerExecution:
    """compile(steps_per_execution=K): K updates per dispatch via the
    multi-step scan; update semantics must equal K single steps."""

    def _fit(self, spe, n=600, epochs=2):
        import jax
        (xt, yt), (xv, yv) = data.xor_data(n, val_size=64, seed=0)
        model = models.Sequential([ops.Dense(64, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="adam",
                      metrics=["bitwise_accuracy"],
                      steps_per_execution=spe)
        hist = model.fit(xt, yt, epochs=epochs, batch_size=50,
                         validation_data=(xv, yv), verbose=0,
                         shuffle=True, seed=3)
        return jax.device_get(model.state.params), hist

    def test_parity_with_single_step(self):
        """Same data order, same seeds -> the K=4 run must land on the
        same weights as K=1 (the scan body IS the single-step fn).
        600/50 = 12 batches: K=4 divides one epoch exactly."""
        p1, h1 = self._fit(1)
        p4, h4 = self._fit(4)
        flat1 = np.concatenate([np.ravel(l) for l in
                                __import__("jax").tree.leaves(p1)])
        flat4 = np.concatenate([np.ravel(l) for l in
                                __import__("jax").tree.leaves(p4)])
        np.testing.assert_allclose(flat1, flat4, rtol=0, atol=1e-6)
        assert set(h4.history) == set(h1.history)

    def test_count_tail_falls_back_to_single(self):
        """550 train samples / 50 = 11 equal batches (fit's Dataset drops
        sample remainders); K=4 groups 8 and leaves 3 as single-step
        dispatches — the run must complete and train."""
        p, h = self._fit(4, n=550 + 64)
        assert np.isfinite(h.history["loss"][-1])

    def test_weighted_fit_ignores_spe(self):
        (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
        model = models.Sequential([ops.Dense(16, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="sgd",
                      steps_per_execution=8)
        w = np.ones(len(xt), np.float32)
        hist = model.fit(xt, yt, epochs=1, batch_size=50, verbose=0,
                         sample_weight=w)
        assert np.isfinite(hist.history["loss"][0])

    def test_invalid_spe_raises(self):
        import pytest
        model = models.Sequential([ops.Dense(4)])
        with pytest.raises(ValueError, match="steps_per_execution"):
            model.compile(loss="mse", optimizer="sgd",
                          steps_per_execution=0)

    def test_spe_on_mesh(self):
        """K-groups shard P(None, 'data') over the 8-device mesh; the run
        must train to a finite loss with the tail handled."""
        from distributed_tensorflow_tpu import parallel
        (xt, yt), _ = data.xor_data(560 + 64, val_size=64, seed=0)
        model = models.Sequential([ops.Dense(32, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="adam",
                      mesh=parallel.data_parallel_mesh(),
                      steps_per_execution=3)
        hist = model.fit(xt, yt, epochs=2, batch_size=56, verbose=0)
        assert np.isfinite(hist.history["loss"][-1])


class TestGroupBatches:
    """_group_batches: the K-stacker feeding the multi-step path must
    tolerate ragged batches (drop_remainder=False tails) instead of
    raising from np.stack on the producer thread."""

    def test_ragged_tail_flushes_as_singles(self):
        from distributed_tensorflow_tpu.models.sequential import \
            _group_batches
        full = [(np.zeros((4, 3)), np.zeros((4,))) for _ in range(5)]
        ragged = (np.zeros((2, 3)), np.zeros((2,)))
        out = list(_group_batches(iter(full + [ragged]), spe=2,
                                  active=True))
        # two stacked pairs, then the odd full batch flushed single when
        # the ragged batch arrives, then the ragged batch itself
        assert [o[0].shape for o in out] == [
            (2, 4, 3), (2, 4, 3), (4, 3), (2, 3)]

    def test_ragged_midstream_then_regroups(self):
        from distributed_tensorflow_tpu.models.sequential import \
            _group_batches
        a = (np.zeros((4, 3)), np.zeros((4,)))
        b = (np.zeros((2, 3)), np.zeros((2,)))
        out = list(_group_batches(iter([a, b, a, a]), spe=2, active=True))
        assert [o[0].shape for o in out] == [(4, 3), (2, 3), (2, 4, 3)]


def test_masked_eval_step_excludes_padding():
    """make_masked_eval_step on a padded (x, y, w) batch reproduces the
    plain eval_step on the unpadded batch exactly — the core of the
    multi-process ragged-tail path (real 2-process equality is proven in
    tests/test_multihost.py)."""
    model = models.Sequential([ops.Dense(8, "relu"),
                               ops.Dense(32, "sigmoid")])
    model.compile(loss="mean_squared_error", optimizer="sgd",
                  metrics=["binary_accuracy"])
    model.build((3,), seed=1)
    rng = np.random.default_rng(0)
    x = rng.random((5, 3)).astype(np.float32)
    y = (rng.random((5, 32)) > 0.5).astype(np.float32)
    plain = model._require_compiled()["eval_step"](
        model.state, (x, y))
    masked_step = model._masked_eval_step(model._require_compiled())
    # pad with garbage rows that MUST not influence the means
    xp = np.concatenate([x, np.full((3, 3), 7.0, np.float32)])
    yp = np.concatenate([y, np.zeros((3, 32), np.float32)])
    w = np.asarray([1, 1, 1, 1, 1, 0, 0, 0], np.float32)
    masked = masked_step(model.state, (xp, yp, w))
    assert set(masked) == set(plain)
    for k in plain:
        np.testing.assert_allclose(float(masked[k]), float(plain[k]),
                                   rtol=1e-6, atol=1e-7)


class TestGradAccum:
    """compile(grad_accum_steps=A): microbatched gradients, one update."""

    def _fit(self, accum, spe=1):
        import jax
        (xt, yt), _ = data.xor_data(600, val_size=64, seed=0)
        model = models.Sequential([ops.Dense(64, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="adam",
                      grad_accum_steps=accum, steps_per_execution=spe)
        hist = model.fit(xt, yt, epochs=2, batch_size=50, verbose=0,
                         shuffle=True, seed=3)
        return jax.device_get(model.state.params), hist

    def test_accum_matches_full_batch(self):
        """Mean-loss microbatch averaging reproduces the full-batch
        gradient; weights must match the accum=1 run to float tolerance."""
        import jax
        p1, _ = self._fit(1)
        p2, _ = self._fit(2)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_accum_composes_with_steps_per_execution(self):
        import jax
        p1, _ = self._fit(1)
        p, h = self._fit(2, spe=4)
        assert np.isfinite(h.history["loss"][-1])
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p)):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_weighted_fit_refused(self):
        import pytest
        (xt, yt), _ = data.xor_data(100, val_size=8, seed=0)
        model = models.Sequential([ops.Dense(8, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="sgd",
                      grad_accum_steps=2)
        with pytest.raises(ValueError, match="unweighted"):
            model.fit(xt, yt, epochs=1, batch_size=50, verbose=0,
                      sample_weight=np.ones(len(xt), np.float32))

    def test_indivisible_batch_refused(self):
        import pytest
        (xt, yt), _ = data.xor_data(100, val_size=8, seed=0)
        model = models.Sequential([ops.Dense(8, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="sgd",
                      grad_accum_steps=3)
        with pytest.raises(ValueError, match="divisible"):
            model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)

    def test_compile_config_roundtrips_through_save(self, tmp_path):
        """steps_per_execution/grad_accum_steps survive model.save ->
        load_model (compile_config is re-applied verbatim)."""
        (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
        model = models.Sequential([ops.Dense(16, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="adam",
                      steps_per_execution=4, grad_accum_steps=2)
        model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
        path = str(tmp_path / "m")
        model.save(path)
        loaded = models.load_model(path)
        cc = loaded._compile_config
        assert cc["steps_per_execution"] == 4
        assert cc["grad_accum_steps"] == 2
        assert loaded._compiled["steps_per_execution"] == 4
        assert loaded._compiled["multi_train_step"] is not None
        hist = loaded.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
        assert np.isfinite(hist.history["loss"][0])

    def test_mesh_rounded_batch_divisibility_checked(self):
        """The accum divisibility check runs on the MESH-ROUNDED batch
        size: 51 % 3 == 0 would pass naively, but rounding to the 8-way
        mesh gives 56, which must be refused up front."""
        import pytest
        from distributed_tensorflow_tpu import parallel
        (xt, yt), _ = data.xor_data(200, val_size=8, seed=0)
        model = models.Sequential([ops.Dense(8, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="sgd",
                      mesh=parallel.data_parallel_mesh(),
                      grad_accum_steps=3)
        with pytest.raises(ValueError, match="divisible"):
            model.fit(xt, yt, epochs=1, batch_size=51, verbose=0)


class TestFitStream:
    """fit_stream: the fit_generator-shaped entry over streamed batches
    (data.tfrecord_batches -> Sequential)."""

    def _records(self, tmp_path, n=400):
        import io
        (xt, yt), _ = data.xor_data(n, val_size=8, seed=0)
        path = str(tmp_path / "xor.tfrecord")

        def ser(i):
            buf = io.BytesIO()
            np.save(buf, xt[i]); np.save(buf, yt[i])
            return buf.getvalue()

        data.write_tfrecord(path, (ser(i) for i in range(len(xt))))

        def parse(rec):
            buf = io.BytesIO(rec)
            return np.load(buf), np.load(buf)

        return path, parse

    def _model(self, spe=1):
        model = models.Sequential([ops.Dense(32, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="adam",
                      steps_per_execution=spe)
        return model

    def test_trains_from_tfrecords(self, tmp_path):
        path, parse = self._records(tmp_path)
        model = self._model()
        hist = model.fit_stream(
            lambda epoch: data.tfrecord_batches(path, parse, batch_size=50,
                                                shuffle_buffer=128,
                                                epoch=epoch),
            steps_per_epoch=8, epochs=2, verbose=0)
        assert len(hist.history["loss"]) == 2
        assert np.isfinite(hist.history["loss"][-1])

    def test_steps_per_execution_grouping(self, tmp_path):
        path, parse = self._records(tmp_path)
        model = self._model(spe=3)
        hist = model.fit_stream(
            lambda epoch: data.tfrecord_batches(path, parse, batch_size=50,
                                                epoch=epoch),
            steps_per_epoch=8, epochs=2, verbose=0)
        assert len(hist.history["loss"]) == 2
        assert np.isfinite(hist.history["loss"][-1])

    def test_exhausted_stream_ends_training(self, tmp_path):
        path, parse = self._records(tmp_path, n=110)  # 2 batches of 50
        model = self._model()
        hist = model.fit_stream(
            data.tfrecord_batches(path, parse, batch_size=50),
            steps_per_epoch=10, epochs=5, verbose=0)
        # one short epoch, then the (now empty) iterator ends training
        assert len(hist.history["loss"]) == 1

    def test_no_ghost_epoch_on_exact_boundary(self, tmp_path):
        """A stream with exactly steps_per_epoch batches must log ONE
        epoch — no zero-step epoch with misaligned val-only history."""
        path, parse = self._records(tmp_path, n=110)  # yields 2 batches
        (xv, yv) = data.xor_data(64, val_size=32, seed=1)[1]
        model = self._model()
        hist = model.fit_stream(
            data.tfrecord_batches(path, parse, batch_size=50),
            steps_per_epoch=2, epochs=5, verbose=0,
            validation_data=(xv, yv))
        assert len(hist.history["loss"]) == 1
        assert len(hist.history["val_loss"]) == 1

    def test_stream_batch_validations(self, tmp_path):
        import pytest
        path, parse = self._records(tmp_path)
        model = models.Sequential([ops.Dense(8, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="sgd",
                      grad_accum_steps=3)
        with pytest.raises(ValueError, match="grad_accum_steps"):
            model.fit_stream(
                data.tfrecord_batches(path, parse, batch_size=50),
                steps_per_epoch=2, verbose=0)

    def test_evaluate_stream(self, tmp_path):
        """Streamed evaluation: weighted means over the drawn batches
        match in-memory evaluate on the same examples."""
        path, parse = self._records(tmp_path, n=208)  # 4 batches of 50
        model = self._model()
        (xt, yt), _ = data.xor_data(208, val_size=8, seed=0)
        model.fit(xt, yt, epochs=1, batch_size=50, verbose=0)
        streamed = model.evaluate_stream(
            data.tfrecord_batches(path, parse, batch_size=50), verbose=0)
        in_mem = model.evaluate(xt[:200], yt[:200], batch_size=50, verbose=0)
        assert abs(streamed["loss"] - in_mem["loss"]) < 1e-5
        limited = model.evaluate_stream(
            data.tfrecord_batches(path, parse, batch_size=50), steps=1,
            verbose=0)
        assert set(limited) == set(in_mem)

    def test_fit_stream_on_mesh(self, tmp_path):
        """Streamed fit over the 8-device mesh with multi-step grouping:
        sharded uploads for both group and tail dispatches."""
        from distributed_tensorflow_tpu import parallel
        path, parse = self._records(tmp_path, n=408)  # 8 batches of 48
        model = models.Sequential([ops.Dense(16, "relu"),
                                   ops.Dense(32, "sigmoid")])
        model.compile(loss="mean_squared_error", optimizer="adam",
                      mesh=parallel.data_parallel_mesh(),
                      steps_per_execution=3)
        hist = model.fit_stream(
            lambda epoch: data.tfrecord_batches(path, parse, batch_size=48,
                                                epoch=epoch),
            steps_per_epoch=8, epochs=2, verbose=0)
        assert len(hist.history["loss"]) == 2
        assert np.isfinite(hist.history["loss"][-1])
