"""Mixed-precision policy and loss-scaling tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu import models, optim, train
from distributed_tensorflow_tpu.train import precision as prec


def test_policy_parsing():
    p = prec.policy("mixed_bfloat16")
    assert p.param_dtype == jnp.float32
    assert p.compute_dtype == jnp.bfloat16
    assert p.output_dtype == jnp.float32
    p = prec.policy("bf16")
    assert p.param_dtype == p.compute_dtype == jnp.bfloat16
    p = prec.policy("params=f32,compute=bf16,output=f32")
    assert p.compute_dtype == jnp.bfloat16
    p = prec.policy("p=f16,c=f16,o=f32")
    assert p.param_dtype == jnp.float16 and p.output_dtype == jnp.float32
    assert prec.policy(None) == prec.Policy()
    with pytest.raises(ValueError, match="unparseable"):
        prec.policy("compute=int8")


def test_policy_casts_only_floats():
    p = prec.policy("mixed_bfloat16")
    tree = {"w": jnp.ones(3, jnp.float32), "ids": jnp.ones(3, jnp.int32)}
    out = p.cast_to_compute(tree)
    assert out["w"].dtype == jnp.bfloat16
    assert out["ids"].dtype == jnp.int32


def test_all_finite():
    assert bool(prec.all_finite({"a": jnp.ones(3)}))
    assert not bool(prec.all_finite({"a": jnp.array([1.0, jnp.inf])}))
    assert bool(prec.all_finite({"ids": jnp.ones(3, jnp.int32)}))  # no floats


def test_dynamic_loss_scale_adjust():
    ls = prec.DynamicLossScale.create(1024.0, growth_interval=2)
    ls = ls.adjust(jnp.asarray(False))           # overflow: halve
    assert float(ls.value) == 512.0 and int(ls.streak) == 0
    ls = ls.adjust(jnp.asarray(True))            # finite 1/2
    assert float(ls.value) == 512.0 and int(ls.streak) == 1
    ls = ls.adjust(jnp.asarray(True))            # finite 2/2: double
    assert float(ls.value) == 1024.0 and int(ls.streak) == 0
    tiny = prec.DynamicLossScale.create(1.0)
    assert float(tiny.adjust(jnp.asarray(False)).value) == 1.0  # min clamp


def test_mixed_bf16_step_keeps_f32_master_params():
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, policy="mixed_bfloat16")
    x = jnp.ones((8, 784))
    y = jnp.zeros((8,), jnp.int32)
    state2, metrics = step(state, (x, y))
    assert np.isfinite(float(metrics["loss"]))
    for leaf in jax.tree.leaves(state2.params):
        assert leaf.dtype == jnp.float32  # master copy untouched by casts


def test_loss_scale_skips_nonfinite_update():
    """A poisoned batch (inf input) must leave params/opt state untouched
    and halve the scale; a clean batch then updates normally."""
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    ls = prec.DynamicLossScale.create(1024.0, growth_interval=1000)
    state = train.attach_loss_scale(state, ls)
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, loss_scale=True)
    y = jnp.zeros((8,), jnp.int32)
    bad_x = jnp.full((8, 784), jnp.inf)
    before = [np.asarray(l) for l in jax.tree.leaves(state.params)]
    state2, m = step(state, (bad_x, y))   # donates state
    assert not bool(m["grads_finite"])
    assert float(m["loss_scale"]) == 512.0
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(state2.params)[0]), before[0])
    assert int(state2.opt_state.count) == 0   # optimizer saw no step
    assert int(state2.step) == 1              # cursor still advances

    good_x = jnp.ones((8, 784))
    p2 = [np.asarray(l) for l in jax.tree.leaves(state2.params)]
    state3, m = step(state2, (good_x, y))  # donates state2
    assert bool(m["grads_finite"])
    assert int(state3.opt_state.count) == 1
    changed = any(
        not np.array_equal(np.asarray(a), b)
        for a, b in zip(jax.tree.leaves(state3.params), p2))
    assert changed


def test_loss_scale_gradients_match_unscaled():
    """Static scale: the applied update equals the unscaled update."""
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.sgd(0.1)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (8, 784))
    y = jnp.zeros((8,), jnp.int32)

    s_plain = train.init_train_state(model, optimizer, key, (784,))
    plain = train.make_train_step(model, "sparse_categorical_crossentropy",
                                  optimizer)
    out_plain, _ = plain(s_plain, (x, y))

    s_scaled = train.init_train_state(model, optimizer, key, (784,))
    s_scaled = train.attach_loss_scale(s_scaled,
                                       prec.StaticLossScale.create(4096.0))
    scaled = train.make_train_step(model, "sparse_categorical_crossentropy",
                                   optimizer, loss_scale=True)
    out_scaled, _ = scaled(s_scaled, (x, y))
    for a, b in zip(jax.tree.leaves(out_plain.params),
                    jax.tree.leaves(out_scaled.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_loss_scale_state_checkpoints(tmp_path):
    """The LossScaled wrapper (incl. scale value) round-trips checkpoints."""
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = train.attach_loss_scale(
        state, prec.DynamicLossScale.create(2048.0))
    from distributed_tensorflow_tpu.train import checkpoint as ck
    d = str(tmp_path)
    ck.save(d, 0, state)
    target = train.init_train_state(model, optimizer, jax.random.PRNGKey(1),
                                    (784,))
    target = train.attach_loss_scale(
        target, prec.DynamicLossScale.create(1.0))
    out = ck.restore(target, ck.latest_checkpoint(d))
    assert float(out.model_state.loss_scale.value) == 2048.0


def test_accum_with_loss_scale_and_policy():
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = train.attach_loss_scale(state,
                                    prec.StaticLossScale.create(256.0))
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, accum_steps=4,
                                 policy="mixed_bfloat16", loss_scale=True)
    x = jnp.ones((16, 784))
    y = jnp.zeros((16,), jnp.int32)
    state2, m = step(state, (x, y))
    assert np.isfinite(float(m["loss"]))
    assert bool(m["grads_finite"])


def test_eval_step_sees_through_loss_scaled_state():
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = train.attach_loss_scale(state,
                                    prec.DynamicLossScale.create(1024.0))
    eval_step = train.make_eval_step(
        model, "sparse_categorical_crossentropy",
        metric_fns={"accuracy": "accuracy"})
    m = eval_step(state, (jnp.ones((8, 784)), jnp.zeros((8,), jnp.int32)))
    assert np.isfinite(float(m["loss"]))


def test_skipped_step_grad_norm_is_finite():
    """Overflow steps must not leak inf into the grad_norm metric (a
    NaNHook watching it would kill the very run loss scaling protects)."""
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = train.attach_loss_scale(state,
                                    prec.DynamicLossScale.create(1024.0))
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer, loss_scale=True,
                                 grad_clip_norm=1.0)
    bad_x = jnp.full((8, 784), jnp.inf)
    _, m = step(state, (bad_x, jnp.zeros((8,), jnp.int32)))
    assert not bool(m["grads_finite"])
    assert np.isfinite(float(m["grad_norm"]))


def test_skipped_step_sanitizes_loss_and_keeps_model_state():
    """Overflow steps: metrics['loss'] must be finite (NaNHook safety) and
    model_state (running stats) must keep its pre-step values."""
    from distributed_tensorflow_tpu import ops

    model = ops.Stack([ops.Dense(8), ops.BatchNorm(), ops.Dense(4)]) \
        if hasattr(ops, "BatchNorm") else None
    if model is None:
        pytest.skip("no BatchNorm layer")
    optimizer = optim.adam()
    params, mstate = model.init(jax.random.PRNGKey(0), (16,))
    state = train.TrainState.create(params, optimizer.init(params), mstate)
    state = train.attach_loss_scale(state,
                                    prec.DynamicLossScale.create(1024.0))
    step = train.make_custom_train_step(
        lambda p, ms, b, rng, t: (
            lambda preds_ms: (jnp.mean((preds_ms[0] - b[1]) ** 2),
                              ({}, preds_ms[1]))
        )(model.apply(p, ms, b[0], train=t, rng=rng)),
        optimizer, loss_scale=True)
    ms_before = jax.tree.map(np.asarray, state.model_state.model_state)
    bad = (jnp.full((8, 16), jnp.inf), jnp.zeros((8, 4)))
    state2, m = step(state, bad)
    assert not bool(m["grads_finite"])
    assert np.isfinite(float(m["loss"]))
    for a, b in zip(jax.tree.leaves(state2.model_state.model_state),
                    jax.tree.leaves(ms_before)):
        np.testing.assert_array_equal(np.asarray(a), b)


def test_sequential_compile_with_policy():
    from distributed_tensorflow_tpu.models import Sequential
    from distributed_tensorflow_tpu import ops

    m = Sequential([ops.Dense(16, activation="relu"), ops.Dense(4)])
    m.compile("sparse_categorical_crossentropy", metrics=["accuracy"],
              policy="mixed_bfloat16")
    x = np.random.default_rng(0).random((64, 8), np.float32)
    y = np.zeros((64,), np.int32)
    h = m.fit(x, y, epochs=1, batch_size=16, verbose=0)
    assert np.isfinite(h.history["loss"][-1])
    out = m.evaluate(x, y, batch_size=32, verbose=0)
    assert np.isfinite(out["loss"])
