"""Host-concurrency runtime tests: the DT3xx tier's runtime sibling.

What is pinned here (docs/ANALYSIS.md §RaceHarness):

* ``RaceHarness`` makes a planted lost-update race manifest on EVERY
  run under a fixed seed, and the lock-fixed twin passes the same
  forced schedule — the harness turns "flaky once a fortnight" into a
  regression test.
* ``RetraceGuard``'s global ``jax.jit`` patch is refcounted: concurrent
  guards (one per engine thread, the multi-replica fleet shape) and
  nested guards share one installed patch and the LAST exit restores
  the pristine ``jax.jit``.
* The PR's concrete fixes, each under its own test: the obs.metrics
  torn-exposition read, engine submit/cancel racing the scheduler pump,
  the router stress acceptance (4 submitter threads over 2 engines,
  terminal tokens bit-identical to solo ``generate``, no handle lost),
  adapter-table hot-swap under concurrent registration, the
  ``resilience.faults`` env-plan double-build, and prefetch producer
  shutdown under forced preemption.
"""
import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import fleet, serve
from distributed_tensorflow_tpu.analysis.race_harness import RaceHarness
from distributed_tensorflow_tpu.analysis.sanitizer import RetraceGuard
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import metrics as metrics_lib

_THIS_FILE = os.path.basename(__file__)


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _generate_tokens(model, params, prompt, new, max_len, **kw):
    out = model.generate(params, jnp.asarray(prompt[None]),
                         max_new_tokens=new, max_len=max_len, **kw)
    return np.asarray(out)[0, prompt.size:].tolist()


# ---------------------------------------------------------------------------
# RaceHarness: planted race reproduces, fixed twin passes


class _RacyCounter:
    """Deliberate lost-update window: load, compute, store — three
    separate lines so the harness can preempt between them."""

    def __init__(self):
        self.n = 0

    def bump(self):
        cur = self.n
        cur = cur + 1
        self.n = cur


class _LockedCounter:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()

    def bump(self):
        with self._lock:
            cur = self.n
            cur = cur + 1
            self.n = cur


def _hammer(counter, threads=2, per_thread=60, seed=7):
    barrier = threading.Barrier(threads)

    def work():
        barrier.wait()
        for _ in range(per_thread):
            counter.bump()

    with RaceHarness(seed=seed, scope=(_THIS_FILE,)) as harness:
        ts = [threading.Thread(target=work, name=f"dttpu-race-{i}",
                               daemon=True) for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
    assert all(not t.is_alive() for t in ts)
    return harness


def test_race_harness_reproduces_planted_race_deterministically():
    # the same seed forces yields at the same sites on every run: the
    # unlocked read-modify-write LOSES updates, run after run — not
    # once a fortnight in CI
    counter = _RacyCounter()
    harness = _hammer(counter)
    assert harness.preemptions > 0
    assert harness.threads_seen >= 2
    assert counter.n < 120, (counter.n, harness.report())


def test_race_harness_fixed_twin_passes_same_schedule():
    counter = _LockedCounter()
    harness = _hammer(counter)
    assert harness.preemptions > 0
    assert counter.n == 120, (counter.n, harness.report())


def test_race_harness_restores_tracing_state():
    old_interval = __import__("sys").getswitchinterval()
    with RaceHarness(seed=0, scope=(_THIS_FILE,)):
        pass
    import sys
    assert sys.gettrace() is None
    assert sys.getswitchinterval() == pytest.approx(old_interval)


@pytest.mark.race_harness(seed=3, scope=(_THIS_FILE,))
def test_race_harness_pytest_marker_is_wired(request):
    harness = getattr(request.node, "race_harness", None)
    assert isinstance(harness, RaceHarness)
    counter = _RacyCounter()
    ts = [threading.Thread(target=lambda: [counter.bump()
                                           for _ in range(40)],
                           name=f"dttpu-mk-{i}", daemon=True)
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert harness.preemptions > 0


# ---------------------------------------------------------------------------
# RetraceGuard: refcounted global patch (satellite regression)


def test_retrace_guard_concurrent_guards_do_not_corrupt_patch():
    """Two guards entered from concurrent threads (the multi-replica
    fleet shape): no lost original, no double patch — after both exit,
    jax.jit is pristine; a retrace inside the window is still caught."""
    orig_jit = jax.jit
    barrier = threading.Barrier(2)
    done = threading.Barrier(2)
    errors = []
    guards = {}

    def engine_thread(name, retrace):
        try:
            with RetraceGuard(budget=1, mode="warn",
                              enforce_donation=False) as g:
                guards[name] = g
                barrier.wait(timeout=30)   # both guards active at once
                f = jax.jit(lambda x: x + 1)
                f(jnp.zeros((2,)))
                if retrace:
                    f(jnp.zeros((3,)))     # second shape: retrace
                done.wait(timeout=30)      # neither exits early
        except Exception as e:             # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=engine_thread, args=("a", True),
                           name="dttpu-g-a", daemon=True),
          threading.Thread(target=engine_thread, args=("b", False),
                           name="dttpu-g-b", daemon=True)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errors
    assert all(not t.is_alive() for t in ts)
    assert jax.jit is orig_jit             # last guard out restored it
    # the retracing thread's guard saw its violation (warn mode records)
    assert any("retrace budget exceeded" in v
               for v in guards["a"].violations)


def test_retrace_guard_nested_guards_share_one_patch():
    orig_jit = jax.jit
    with RetraceGuard(budget=5, mode="warn",
                      enforce_donation=False) as outer:
        with RetraceGuard(budget=1, mode="warn",
                          enforce_donation=False) as inner:
            f = jax.jit(lambda x: x * 2)
            f(jnp.zeros((2,)))
            f(jnp.zeros((3,)))             # inner violates, outer absorbs
        assert jax.jit is not orig_jit     # outer still active
        g = jax.jit(lambda x: x - 1)       # constructed after inner exit
        g(jnp.zeros((2,)))
    assert jax.jit is orig_jit
    assert inner.violations and not outer.violations


def test_retrace_guard_same_object_reentry_rejected():
    guard = RetraceGuard(budget=1)
    with guard:
        with pytest.raises(RuntimeError, match="not re-entrant"):
            guard.__enter__()
    assert jax.jit.__module__.startswith("jax")


# ---------------------------------------------------------------------------
# obs.metrics: torn exposition regression (DT301 fix)


def test_metrics_exposition_never_torn_under_preemption():
    """A /metrics scrape racing observe(): the histogram's +Inf bucket
    must equal its _count in EVERY exposition (the unlocked samples()
    rendered mid-observe snapshots where they disagree)."""
    reg = metrics_lib.Registry()
    hist = reg.histogram("t_seconds", "t", buckets=(0.1, 1.0))
    ctr = reg.counter("t_total", "t")
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            hist.observe(0.05 * (i % 40))
            ctr.inc()
            i += 1

    with RaceHarness(seed=11, scope=("obs/metrics.py",)) as harness:
        t = threading.Thread(target=writer, name="dttpu-obs-w",
                             daemon=True)
        t.start()
        try:
            for _ in range(25):
                doc = metrics_lib.parse_exposition(reg.expose())
                fam = doc["t_seconds"]["samples"]
                inf = fam[("t_seconds_bucket", (("le", "+Inf"),))]
                cnt = fam[("t_seconds_count", ())]
                assert inf == cnt, (inf, cnt)
        finally:
            stop.set()
            t.join(timeout=30)
    assert harness.preemptions > 0
    assert hist.count == hist.samples()[-1][2]   # locked reads agree


# ---------------------------------------------------------------------------
# engine/scheduler: concurrent submit + cancel vs the pump (DT301 fix)


def test_engine_concurrent_submitters_no_loss_and_exact():
    """4 submitter threads race the pumping main thread: every handle
    completes ok, every stream is bit-identical to solo generate, and
    the tenant accounting drains to zero."""
    model, params = _model_params()
    prompts = {i: _prompt(4 + (i % 3), seed=20 + i) for i in range(8)}
    want = {i: _generate_tokens(model, params, prompts[i], 6, 32)
            for i in range(8)}
    eng = serve.Engine(model, params, num_slots=3, max_len=32,
                       prefill_chunk=4, tick_steps=2,
                       registry=metrics_lib.Registry())
    handles = {}
    hlock = threading.Lock()
    barrier = threading.Barrier(4)

    def submitter(ids):
        barrier.wait(timeout=30)
        for i in ids:
            h = eng.submit(prompts[i], 6, tenant=f"t{i % 2}")
            with hlock:
                handles[i] = h

    ts = [threading.Thread(target=submitter, args=([k, k + 4],),
                           name=f"dttpu-sub-{k}", daemon=True)
          for k in range(4)]
    for t in ts:
        t.start()
    deadline = time.time() + 120
    while True:
        with hlock:
            got = dict(handles)
        if len(got) == 8 and all(h.done for h in got.values()):
            break
        eng.step()
        assert time.time() < deadline, "fleet did not drain"
    for t in ts:
        t.join(timeout=30)
    for i, h in handles.items():
        assert h.status == "ok", (i, h.status, h.error)
        assert h.tokens == want[i], i
    st = eng.stats()
    assert st.inflight == 0
    assert st.inflight_per_tenant == {}
    assert st.tokens_inflight_per_tenant == {}


def test_engine_cancel_from_other_thread_then_slot_reuse_exact():
    """Cross-thread cancel against a live pump: the cancelled handle
    terminates exactly once, and the freed slot's next occupant decodes
    bit-identically (stale-row freeze + orphaned-cache pooling)."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=1, max_len=64,
                       prefill_chunk=4, tick_steps=1,
                       registry=metrics_lib.Registry())
    want = _generate_tokens(model, params, _prompt(4, seed=2), 6, 64)
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            if not eng.step():
                time.sleep(0.001)

    pt = threading.Thread(target=pump, name="dttpu-pump", daemon=True)
    pt.start()
    try:
        h1 = eng.submit(_prompt(4, seed=1), 40)
        deadline = time.time() + 60
        while not h1.tokens:
            assert time.time() < deadline
            time.sleep(0.001)
        assert eng.cancel(h1) is True      # from THIS thread, pump live
        assert h1.done and h1.status == "cancelled"
        assert eng.cancel(h1) is False
        h2 = eng.submit(_prompt(4, seed=2), 6)
        deadline = time.time() + 60
        while not h2.done:
            assert time.time() < deadline
            time.sleep(0.001)
        assert h2.status == "ok" and h2.tokens == want
    finally:
        stop.set()
        pt.join(timeout=30)
    assert not pt.is_alive()


def test_engine_queue_depth_is_atomic_across_submitters():
    """max_queue_depth under 4 racing submitters: exactly depth requests
    are accepted (check-then-enqueue used to live outside the lock and
    could overshoot)."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=1, max_len=32,
                       prefill_chunk=4, tick_steps=1,
                       max_queue_depth=2,
                       registry=metrics_lib.Registry())
    accepted, rejected = [], []
    lock = threading.Lock()
    barrier = threading.Barrier(4)

    def submitter(i):
        barrier.wait(timeout=30)
        try:
            h = eng.submit(_prompt(4, seed=i), 4)
            with lock:
                accepted.append(h)
        except serve.QueueFullError:
            with lock:
                rejected.append(i)

    ts = [threading.Thread(target=submitter, args=(i,),
                           name=f"dttpu-q-{i}", daemon=True)
          for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert len(accepted) == 2 and len(rejected) == 2
    eng.drain()
    assert all(h.status == "ok" for h in accepted)


# ---------------------------------------------------------------------------
# the router stress acceptance (satellite): 4 submitters, 2 engines


@pytest.mark.race_harness(
    seed=17, scope=("distributed_tensorflow_tpu/serve/",
                    "distributed_tensorflow_tpu/fleet/"))
def test_router_stress_tokens_exact_and_no_handle_lost(request):
    """THE stress test: one Router over 2 engines driven by 4 submitter
    threads under a seeded preemption schedule.  Every handle reaches a
    terminal status (none lost in a torn in-flight list), and every
    terminal token stream is bit-identical to solo ``generate`` — the
    forced context switches land inside the scheduler/router critical
    sections, exactly where the pre-lock code tore."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    engines = [serve.Engine(model, params, num_slots=2, max_len=32,
                            prefill_chunk=4, tick_steps=2, registry=reg)
               for _ in range(2)]
    router = fleet.Router(engines, registry=reg)
    prompts = {i: _prompt(4 + (i % 3), seed=40 + i) for i in range(8)}
    want = {i: _generate_tokens(model, params, prompts[i], 6, 32)
            for i in range(8)}
    handles = {}
    hlock = threading.Lock()
    barrier = threading.Barrier(4)

    def submitter(ids):
        barrier.wait(timeout=60)
        for i in ids:
            h = router.submit(prompts[i], 6)
            with hlock:
                handles[i] = h

    ts = [threading.Thread(target=submitter, args=([k, k + 4],),
                           name=f"dttpu-fleet-{k}", daemon=True)
          for k in range(4)]
    for t in ts:
        t.start()
    deadline = time.time() + 300
    while True:
        with hlock:
            got = dict(handles)
        if len(got) == 8 and all(h.done for h in got.values()):
            break
        router.step()
        assert time.time() < deadline, "router did not drain"
    for t in ts:
        t.join(timeout=60)

    harness = request.node.race_harness
    assert harness.preemptions > 0, "harness never fired"
    assert len(handles) == 8                      # no handle lost
    for i, h in handles.items():
        assert h.status == "ok", (i, h.status, h.error)
        assert h.tokens == want[i], i             # bit-identical streams
    assert len(router.placements) >= 8
    for st in router.stats().values():
        assert st.inflight == 0


# ---------------------------------------------------------------------------
# adapter table: hot-swap registration racing acquire/release


def test_adapter_table_register_races_acquire_release():
    model, params = _model_params()
    table = serve.AdapterTable(model, capacity=2, rank=4,
                               registry=metrics_lib.Registry())
    ad = model.init_lora(jax.random.PRNGKey(1), rank=4)
    table.register("hot", ad)
    errors = []
    stop = threading.Event()

    def swapper():
        while not stop.is_set():
            try:
                table.register("hot", ad)      # hot-update re-splice
            except Exception as e:             # pragma: no cover
                errors.append(e)

    with RaceHarness(seed=5, scope=("serve/adapters.py",)) as harness:
        t = threading.Thread(target=swapper, name="dttpu-swap",
                             daemon=True)
        t.start()
        try:
            for _ in range(60):
                row = table.acquire("hot")
                assert row == 1                # stable resident row
                table.release("hot")
        finally:
            stop.set()
            t.join(timeout=30)
    assert not errors
    assert harness.preemptions > 0
    assert table.resident_ids == ("hot",)
    assert table._refs == {}                   # every pin released


# ---------------------------------------------------------------------------
# resilience.faults: env-plan cache builds exactly one instance


def test_env_fault_plan_single_instance_across_threads(monkeypatch):
    from distributed_tensorflow_tpu.resilience import faults

    monkeypatch.setenv("DTTPU_FAULTS",
                       '[{"kind": "poison_batch", "at": 999999}]')
    faults._ENV_CACHE = (None, None)          # force a fresh rebuild
    plans = []
    plock = threading.Lock()
    barrier = threading.Barrier(4)

    def reader():
        barrier.wait(timeout=30)
        for _ in range(10):
            p = faults.active()
            with plock:
                plans.append(p)

    with RaceHarness(seed=9, scope=("resilience/faults.py",)):
        ts = [threading.Thread(target=reader, name=f"dttpu-f-{i}",
                               daemon=True) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
    faults._ENV_CACHE = (None, None)
    assert len(plans) == 40
    # one spec value -> ONE plan instance: racing rebuilds used to split
    # the per-site at-most-`times` counters across two plans
    assert len({id(p) for p in plans}) == 1


# ---------------------------------------------------------------------------
# data.pipeline: producer shutdown under forced preemption


def test_prefetch_abandonment_joins_producer_under_preemption():
    """The PR 4 leak fix, re-pinned under the harness: breaking out of
    an epoch mid-stream (then closing) must unblock and join the
    dttpu-prefetch producer even when the scheduler interleaves the
    producer and consumer at every attribute/call site."""
    from distributed_tensorflow_tpu.data import pipeline

    batches = [np.full((2,), i, np.int32) for i in range(64)]
    with RaceHarness(seed=13, scope=("data/pipeline.py",)) as harness:
        it = pipeline.prefetch_to_device(iter(batches), size=2)
        first = next(it)
        assert int(np.asarray(first)[0]) == 0
        it.close()                             # abandon mid-epoch
    assert harness.preemptions > 0
    leftover = [t for t in threading.enumerate()
                if t.name == "dttpu-prefetch" and t.is_alive()]
    assert leftover == []


# ---------------------------------------------------------------------------
# obs.federate: federation mutates sources while exposing


@pytest.mark.race_harness(
    seed=11, scope=("distributed_tensorflow_tpu/obs/federate.py",))
def test_federated_metrics_expose_races_ingest_and_add(request):
    """FederatedMetrics under the forced schedule: two ingest threads
    stream SLO evidence and a third keeps adding registries while the
    main thread scrapes ``expose()`` in a loop.  Every exposition must
    parse cleanly (no torn merge), the per-tenant attainment gauge must
    equal the pooled verdict ratio at the end, and late-added registries
    must eventually surface under their replica label."""
    from distributed_tensorflow_tpu.obs.federate import FederatedMetrics

    fed = FederatedMetrics()
    base = metrics_lib.Registry()
    base.counter("dttpu_test_base_total", "seed series").inc(7)
    fed.add_registry(base, replica="0")
    errors = []
    stop = threading.Event()

    def ingester(tenant, ok_every):
        i = 0
        while not stop.is_set():
            i += 1
            try:
                fed.ingest(tenant, ttft_s=0.01 * (i % 10 + 1),
                           tpot_s=0.001, ttft_ok=i % ok_every != 0,
                           itl_ok=True)
            except Exception as e:              # pragma: no cover
                errors.append(e)

    def adder():
        for k in range(1, 9):
            if stop.is_set():
                break
            reg = metrics_lib.Registry()
            reg.gauge("dttpu_test_added", "late source").set(float(k))
            try:
                fed.add_registry(reg, replica=str(k))
            except Exception as e:              # pragma: no cover
                errors.append(e)

    ts = [threading.Thread(target=ingester, args=("a", 5),
                           name="dttpu-fed-a", daemon=True),
          threading.Thread(target=ingester, args=("b", 3),
                           name="dttpu-fed-b", daemon=True),
          threading.Thread(target=adder, name="dttpu-fed-add",
                           daemon=True)]
    for t in ts:
        t.start()
    try:
        for _ in range(40):
            text = fed.expose()
            fams = metrics_lib.parse_exposition(text)   # parses whole
            fam = fams.get("dttpu_test_base_total")
            assert fam is not None
            (key,) = [k for k in fam["samples"] if k[0].endswith("_total")]
            assert dict(key[1])["replica"] == "0"
            assert fam["samples"][key] == 7.0
    finally:
        stop.set()
        for t in ts:
            t.join(timeout=60)
    assert not errors
    harness = request.node.race_harness
    assert harness.preemptions > 0, "harness never fired"
    # all 8 late registries landed and expose under distinct replicas
    assert fed.source_count() == 1 + 1 + 8
    fams = metrics_lib.parse_exposition(fed.expose())
    added = fams["dttpu_test_added"]["samples"]
    assert {dict(lbls)["replica"] for _, lbls in added} == {
        str(k) for k in range(1, 9)}
    for tenant in ("a", "b"):
        key = ("dttpu_slo_attainment", (("tenant", tenant),))
        att = fams["dttpu_slo_attainment"]["samples"][key]
        assert 0.0 < att <= 1.0
