"""Augmentation pipeline tests."""
import numpy as np

from distributed_tensorflow_tpu import data
from distributed_tensorflow_tpu.data import augment


def _images(b=16, h=8, w=8, c=3, seed=0):
    return np.random.default_rng(seed).random((b, h, w, c)).astype(np.float32)


def test_flip_preserves_content():
    rng = np.random.default_rng(0)
    x = _images()
    (out,) = augment.random_flip_lr(prob=1.0)(rng, (x,))
    np.testing.assert_array_equal(out, x[:, :, ::-1])


def test_crop_shape_and_content_domain():
    rng = np.random.default_rng(0)
    x = _images()
    (out,) = augment.random_crop(padding=2)(rng, (x,))
    assert out.shape == x.shape
    # reflect-padding means every output pixel exists in the input's value set
    assert np.isin(np.round(out, 6), np.round(x, 6)).all()


def test_crop_zero_offset_possible_and_varies():
    rng = np.random.default_rng(3)
    x = _images(b=64)
    (out,) = augment.random_crop(padding=2)(rng, (x,))
    same = [np.array_equal(out[i], x[i]) for i in range(64)]
    assert any(same) and not all(same)  # center crop happens; offsets vary


def test_normalize():
    rng = np.random.default_rng(0)
    x = np.ones((4, 2, 2, 3), np.float32)
    (out,) = augment.normalize([1.0, 1.0, 1.0], [2.0, 2.0, 2.0])(rng, (x,))
    np.testing.assert_allclose(out, 0.0)


def test_cutout_zeroes_a_patch():
    rng = np.random.default_rng(0)
    x = _images() + 1.0  # strictly positive
    (out,) = augment.cutout(size=4)(rng, (x,))
    assert (out == 0).any()
    assert out.shape == x.shape


def test_compose_and_labels_untouched():
    rng = np.random.default_rng(0)
    x = _images()
    y = np.arange(16)
    t = augment.compose(augment.random_flip_lr(0.5),
                        augment.normalize([0.5] * 3, [0.5] * 3))
    ox, oy = t(rng, (x, y))
    np.testing.assert_array_equal(oy, y)
    assert ox.dtype == np.float32


def test_dataset_transform_applied_and_deterministic():
    x = _images(b=32)
    y = np.arange(32)
    t = augment.compose(augment.random_crop(2), augment.random_flip_lr())
    ds1 = data.Dataset([x, y], 8, seed=7, transform=t)
    ds2 = data.Dataset([x, y], 8, seed=7, transform=t)
    b1 = [b for b in ds1]
    b2 = [b for b in ds2]
    for (x1, y1), (x2, y2) in zip(b1, b2):
        np.testing.assert_array_equal(x1, x2)   # same seed -> same batches
        np.testing.assert_array_equal(y1, y2)
    ds3 = data.Dataset([x, y], 8, seed=7)       # no transform differs
    raw = next(iter(ds3))[0]
    assert not np.array_equal(b1[0][0], raw)


def test_cutout_full_size_patch_odd_size():
    rng = np.random.default_rng(0)
    x = np.ones((8, 16, 16, 3), np.float32)
    (out,) = augment.cutout(size=5, prob=1.0)(rng, (x,))
    for i in range(8):
        zeros = int((out[i] == 0).sum())
        assert zeros == 5 * 5 * 3  # exact square even for odd sizes
