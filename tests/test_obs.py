"""obs/ telemetry layer tests: Chrome-trace validity, Prometheus text
round-trip, the /metrics + /healthz endpoint, in-graph device health,
and the end-to-end TrainSession acceptance path (TraceHook +
MetricsExportHook + RetraceGuard retrace instants + a live scrape).
"""
import json
import math
import os
import sys
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu import data, obs, ops, optim, train
from distributed_tensorflow_tpu.obs import device as obs_device
from distributed_tensorflow_tpu.obs import reqtrace
from distributed_tensorflow_tpu.obs import trace as obs_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


# ------------------------------------------------------------- tracing

class TestTrace:
    def test_chrome_trace_json_valid(self, tmp_path):
        t = obs.Tracer(enabled=True, pid=3, host="hostX")
        with t.span("dispatch", step=1):
            pass
        t.add_span("data_load", 10.0, 20.0, step=2)
        t.instant("retrace", fn="step", arg_diff="~ x: f32[2] -> f32[3]")
        path = t.save(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        by_name = {e["name"]: e for e in events}
        # metadata record carries the host label for multi-host merging
        assert by_name["process_name"]["ph"] == "M"
        assert "hostX" in by_name["process_name"]["args"]["name"]
        assert by_name["dispatch"]["ph"] == "X"
        assert by_name["dispatch"]["dur"] >= 0
        assert by_name["data_load"]["dur"] == pytest.approx(10.0)
        assert by_name["retrace"]["ph"] == "i"
        assert all(e["pid"] == 3 for e in events)
        # every non-metadata event is timestamped (merge-sortable)
        assert all("ts" in e for e in events if e["ph"] != "M")

    def test_disabled_tracer_records_nothing(self):
        t = obs.Tracer(enabled=False)
        with t.span("dispatch"):
            pass
        t.instant("retrace")
        assert [e for e in t.events() if e["ph"] != "M"] == []

    def test_active_tracer_module_sink(self):
        t = obs.Tracer(enabled=True)
        obs_trace.instant("orphan")          # no active tracer: no-op
        with obs_trace.activated(t):
            obs_trace.instant("mark", k=1)
            with obs_trace.span("s"):
                pass
        obs_trace.instant("after")           # deactivated again
        names = [e["name"] for e in t.events() if e["ph"] != "M"]
        assert names == ["mark", "s"]
        assert t.instant_counts == {"mark": 1}


# ------------------------------------------------------------- metrics

class TestMetrics:
    def test_exposition_roundtrips_prometheus_text(self):
        reg = obs.Registry()
        reg.counter("requests_total", "Requests.",
                    labels={"path": "a"}).inc(3)
        reg.counter("requests_total", "Requests.",
                    labels={"path": "b"}).inc()
        reg.gauge("temp_celsius", "Temp.").set(-1.5)
        h = reg.histogram("lat_seconds", "Latency.", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.5, 5.0):
            h.observe(v)
        text = reg.expose()
        parsed = obs.parse_exposition(text)
        assert parsed["requests_total"]["type"] == "counter"
        assert parsed["requests_total"]["samples"][
            ("requests_total", (("path", "a"),))] == 3.0
        assert parsed["requests_total"]["samples"][
            ("requests_total", (("path", "b"),))] == 1.0
        assert parsed["temp_celsius"]["samples"][
            ("temp_celsius", ())] == -1.5
        hs = parsed["lat_seconds"]["samples"]
        # cumulative buckets + +Inf + sum/count — the full histogram law
        assert hs[("lat_seconds_bucket", (("le", "0.1"),))] == 1.0
        assert hs[("lat_seconds_bucket", (("le", "1"),))] == 3.0
        assert hs[("lat_seconds_bucket", (("le", "+Inf"),))] == 4.0
        assert hs[("lat_seconds_count", ())] == 4.0
        assert hs[("lat_seconds_sum", ())] == pytest.approx(6.05)

    def test_get_or_create_shares_series_and_checks_types(self):
        reg = obs.Registry()
        a = reg.counter("steps_total", "Steps.")
        b = reg.counter("steps_total")
        assert a is b
        a.inc(2)
        assert b.value == 2
        with pytest.raises(ValueError):
            reg.gauge("steps_total")
        with pytest.raises(ValueError):
            reg.counter("bad name!")
        with pytest.raises(ValueError):
            a.inc(-1)

    def test_histogram_quantile_estimate(self):
        h = obs.Histogram("h", "", (), buckets=(0.01, 0.1, 1.0))
        assert math.isnan(h.quantile(0.5))
        for _ in range(9):
            h.observe(0.05)
        h.observe(5.0)
        assert h.quantile(0.5) == 0.1
        assert h.quantile(0.99) == float("inf")


# ---------------------------------------------------------------- http

class TestHttp:
    def test_metrics_and_healthz_endpoints(self):
        reg = obs.Registry()
        reg.counter("ticks_total", "Ticks.").inc(7)
        server = obs.MetricsServer(reg, port=0,
                                   health_fn=lambda: {"status": "ok",
                                                      "replica": 2})
        server.start()
        try:
            assert server.port != 0   # ephemeral port resolved
            status, text = _get(server.url + "/metrics")
            assert status == 200
            parsed = obs.parse_exposition(text)
            assert parsed["ticks_total"]["samples"][
                ("ticks_total", ())] == 7.0
            status, body = _get(server.url + "/healthz")
            assert status == 200
            assert json.loads(body)["replica"] == 2
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + "/nope")
            assert e.value.code == 404
        finally:
            server.stop()

    def test_statusz_debug_snapshot(self):
        """/statusz merges the live obs sinks (goodput split, tracer
        occupancy, reqtrace ring) with whatever statusz_fn contributes —
        the curl-a-wedged-process endpoint (docs/OBSERVABILITY.md)."""
        from distributed_tensorflow_tpu.obs import goodput as goodput_lib
        acct = goodput_lib.GoodputAccountant()
        tracer = obs_trace.Tracer(enabled=True)
        tracer.instant("retrace", fn="step")
        server = obs.MetricsServer(
            obs.Registry(), port=0,
            statusz_fn=lambda: {"engine": {"running": 3,
                                           "waiting": 1}}).start()
        try:
            with obs_trace.activated(tracer), \
                    goodput_lib.activated(acct):
                with goodput_lib.account("step"):
                    pass
                status, body = _get(server.url + "/statusz")
            assert status == 200
            doc = json.loads(body)
            gp = doc["goodput"]
            assert set(gp["buckets_s"]) == set(goodput_lib.BUCKETS)
            assert gp["wall_s"] >= gp["buckets_s"]["step"] >= 0.0
            assert doc["trace"]["events"] >= 1
            assert doc["trace"]["instant_counts"]["retrace"] == 1
            # a tracer is active inside the with-block, so reqtrace
            # minting reports enabled; the ring is untouched
            assert doc["reqtrace"]["enabled"] is True
            assert doc["reqtrace"]["live"] == 0
            # the statusz_fn extras (Engine.stats() in serving) merge in
            assert doc["engine"] == {"running": 3, "waiting": 1}

            # with every sink inactive, the endpoint still answers
            status, body = _get(server.url + "/statusz")
            doc = json.loads(body)
            assert status == 200 and "goodput" not in doc
        finally:
            server.stop()

    def test_statusz_fn_failure_is_500_not_a_crash(self):
        def broken():
            raise RuntimeError("stats wedged")

        server = obs.MetricsServer(obs.Registry(), port=0,
                                   statusz_fn=broken).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + "/statusz")
            assert e.value.code == 500
            assert "wedged" in e.value.read().decode()
        finally:
            server.stop()

    def test_healthz_failure_is_503_not_a_crash(self):
        def sick():
            raise RuntimeError("replica wedged")

        server = obs.MetricsServer(obs.Registry(), port=0,
                                   health_fn=sick).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + "/healthz")
            assert e.value.code == 503
            assert "wedged" in e.value.read().decode()
        finally:
            server.stop()


# --------------------------------------------- exposition round-trip

class TestExpositionRoundTrip:
    """parse_exposition/render_exposition must be exact duals —
    including +Inf histogram buckets and escaped label values, the two
    spots where a lossy pass would silently corrupt a federated proxy.
    No hypothesis in the image, so "property test" = seeded random
    adversarial cases + the parse∘render fixpoint law on each."""

    ALPHABET = ['a', 'Z', '0', ' ', '"', "\\", "\n", "n",
                "\\n", "\\\\", 'x"y', "µ", "{", "}", "=", ","]

    def _random_families(self, rng):
        fams = {}
        for fi in range(rng.randrange(1, 4)):
            name = f"dttpu_prop_{fi}_total"
            samples = {}
            for si in range(rng.randrange(1, 4)):
                labels = tuple(sorted(
                    (f"l{li}", "".join(rng.choice(self.ALPHABET)
                                       for _ in range(rng.randrange(0, 6))))
                    for li in range(rng.randrange(0, 3))))
                value = rng.choice(
                    [0.0, -1.5, 3e18, float("inf"), float("-inf"),
                     rng.random()])
                samples[(name, labels)] = value
            # help is "rest of line": trailing SPACES can't survive a
            # line-stripping parser (escaped \n and \\ do) — rstrip
            # them; label VALUES stay fully adversarial, they're quoted
            help_text = "".join(rng.choice(self.ALPHABET)
                                for _ in range(5)).rstrip(" ")
            fams[name] = {"type": rng.choice(["counter", "gauge"]),
                          "help": help_text,
                          "samples": samples}
        return fams

    def test_random_families_survive_parse_render_parse(self):
        import random
        rng = random.Random(0xD77)
        for _ in range(50):
            fams = self._random_families(rng)
            text = obs.render_exposition(fams)
            parsed = obs.parse_exposition(text)
            for fam, entry in fams.items():
                assert parsed[fam]["samples"] == entry["samples"], text
                assert parsed[fam]["help"] == entry["help"], text
            # the fixpoint law: one more render/parse round changes
            # nothing (what lets the federation re-proxy a proxy)
            again = obs.parse_exposition(obs.render_exposition(parsed))
            assert again == parsed

    def test_inf_buckets_and_escapes_roundtrip_through_registry(self):
        reg = obs.Registry()
        h = reg.histogram("dttpu_prop_lat_seconds", "Latency.",
                          buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        reg.counter("dttpu_prop_req_total", 'Say "hi"\nback\\slash.',
                    labels={"path": 'a\\n"b"\nc'}).inc()
        text = reg.expose()
        parsed = obs.parse_exposition(text)
        hs = parsed["dttpu_prop_lat_seconds"]["samples"]
        assert hs[("dttpu_prop_lat_seconds_bucket",
                   (("le", "+Inf"),))] == 3.0
        assert parsed["dttpu_prop_req_total"]["samples"][
            ("dttpu_prop_req_total",
             (("path", 'a\\n"b"\nc'),))] == 1.0
        # literal-backslash-then-n must NOT decode as newline, and the
        # second round trip must agree with the first exactly
        assert obs.parse_exposition(
            obs.render_exposition(parsed)) == parsed

    def test_adjacent_escape_sequences_decode_single_pass(self):
        # ``\\n`` (escaped backslash, then literal n) was the v3 bug:
        # a sequential .replace() chain ate the backslash it decoded
        reg = obs.Registry()
        reg.gauge("dttpu_prop_g", "G.", labels={"v": "\\n"}).set(1)
        parsed = obs.parse_exposition(reg.expose())
        assert parsed["dttpu_prop_g"]["samples"][
            ("dttpu_prop_g", (("v", "\\n"),))] == 1.0

    def test_extra_labels_stamp_and_override(self):
        reg = obs.Registry()
        reg.counter("dttpu_prop_c", "C.", labels={"replica": "9",
                                                  "path": "a"}).inc(2)
        text = obs.render_exposition(obs.parse_exposition(reg.expose()),
                                     extra_labels={"replica": "0"})
        parsed = obs.parse_exposition(text)
        assert parsed["dttpu_prop_c"]["samples"][
            ("dttpu_prop_c", (("path", "a"), ("replica", "0")))] == 2.0
        with pytest.raises(ValueError):
            obs.render_exposition({}, extra_labels={"bad name!": "x"})


# -------------------------------------------------------- device health

class TestDeviceHealth:
    def test_grad_health_in_graph_counts_nonfinite(self):
        grads = {"a": jnp.asarray([3.0, 4.0]),
                 "b": jnp.asarray([[float("nan"), float("inf")],
                                   [0.0, 0.0]])}

        @jax.jit
        def health(g):
            return obs_device.grad_health(g)

        out = health(grads)
        assert float(out[obs_device.NONFINITE_KEY]) == 2.0
        assert not math.isfinite(float(out[obs_device.GRAD_NORM_KEY]))
        clean = health({"a": jnp.asarray([3.0, 4.0])})
        assert float(clean[obs_device.GRAD_NORM_KEY]) == pytest.approx(5.0)
        assert float(clean[obs_device.NONFINITE_KEY]) == 0.0

    def test_train_step_device_health_rides_metrics_dict(self):
        model = ops.serial(ops.Dense(8, "relu"), ops.Dense(32, "sigmoid"))
        opt = optim.adam()
        state = train.init_train_state(model, opt, jax.random.PRNGKey(0),
                                       (64,))
        step = train.make_train_step(model, "mse", opt, device_health=True)
        (xt, yt), _ = data.xor_data(100, val_size=10, seed=0)
        state, m = step(state, (xt[:50], yt[:50]))
        assert float(m[obs_device.GRAD_NORM_KEY]) > 0
        assert float(m[obs_device.NONFINITE_KEY]) == 0.0

    def test_live_arrays_bytes_counts_new_buffer(self):
        before = obs_device.live_arrays_bytes()
        keep = jnp.ones((256, 256), jnp.float32)
        keep.block_until_ready()
        after = obs_device.live_arrays_bytes()
        assert after - before >= 256 * 256 * 4
        del keep


# ------------------------------------------------- end-to-end acceptance

def test_session_telemetry_end_to_end(tmp_path):
    """ISSUE 3 acceptance: a short TrainSession run with TraceHook +
    MetricsExportHook yields (a) valid Chrome trace JSON containing
    dispatch and retrace events and (b) a live /metrics scrape showing
    the step counter and the step-time histogram."""
    from distributed_tensorflow_tpu.analysis.sanitizer import RetraceGuard

    tele = obs.Telemetry(trace_dir=str(tmp_path), metrics_port=0)
    (xt, yt), _ = data.xor_data(200, val_size=10, seed=0)
    with RetraceGuard(budget=1, mode="warn",
                      stream=open("/dev/null", "w")) as guard:
        model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
        opt = optim.adam()
        state = train.init_train_state(model, opt, jax.random.PRNGKey(0),
                                       (64,))
        # built INSIDE the guard: traces are counted and mirrored onto
        # the active tracer as jit_compile/retrace instants
        step = train.make_train_step(model, "mse", opt, device_health=True)
        with train.TrainSession(
                state, step, telemetry=tele,
                hooks=[train.TraceHook(tele),
                       train.MetricsExportHook(tele, every_steps=1,
                                               examples_per_step=50),
                       train.StopAtStepHook(4)]) as sess:
            n = 0
            while not sess.should_stop():
                # last batch changes shape: a real retrace, on purpose
                b = (xt[:30], yt[:30]) if n == 3 else (xt[:50], yt[:50])
                sess.run_step(b)
                n += 1
        status, text = _get(tele.metrics_url())
    tele.close()
    assert guard.violations, "the shape change must have retraced"

    # (a) the trace file is valid Chrome trace JSON with the span/instant
    # vocabulary docs/OBSERVABILITY.md documents
    doc = json.load(open(tele.trace_path))
    events = doc["traceEvents"]
    names = {}
    for e in events:
        names[e["name"]] = names.get(e["name"], 0) + 1
    assert names["dispatch"] == 4        # one per run_step, from session
    assert names["step"] == 4            # TraceHook host-step spans
    assert names["data_load"] == 4       # inter-step host gap spans
    assert names["jit_compile"] >= 1     # first trace instant
    assert names["retrace"] == 1         # the shape-change recompile
    retrace = next(e for e in events if e["name"] == "retrace")
    assert "arg_diff" in retrace["args"]         # actionable, not forensic
    assert "[30,64]" in retrace["args"]["arg_diff"]
    steps_args = sorted(e["args"]["step"] for e in events
                        if e["name"] == "step")
    assert steps_args == [1, 2, 3, 4]

    # (b) the live scrape carried the step counter + step-time histogram
    assert status == 200
    parsed = obs.parse_exposition(text)
    assert parsed["dttpu_steps_total"]["type"] == "counter"
    assert parsed["dttpu_steps_total"]["samples"][
        ("dttpu_steps_total", ())] == 4.0
    hist = parsed["dttpu_step_time_seconds"]
    assert hist["type"] == "histogram"
    assert hist["samples"][("dttpu_step_time_seconds_count", ())] == 4.0
    assert hist["samples"][("dttpu_step_time_seconds_sum", ())] > 0
    # throughput, retrace count, device health, memory gauge all exported
    assert parsed["dttpu_examples_per_second"]["samples"][
        ("dttpu_examples_per_second", ())] > 0
    assert parsed["dttpu_retraces_total"]["samples"][
        ("dttpu_retraces_total", ())] == 1.0
    assert parsed["dttpu_live_arrays_bytes"]["samples"][
        ("dttpu_live_arrays_bytes", ())] > 0
    assert ("dttpu_grad_norm", ()) in parsed["dttpu_grad_norm"]["samples"]


def test_telemetry_checkpoint_span_and_duration(tmp_path):
    """session.save() under telemetry: a 'checkpoint' span lands on the
    timeline and the save-duration histogram observes it."""
    model = ops.serial(ops.Dense(8, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt)
    (xt, yt), _ = data.xor_data(100, val_size=10, seed=0)
    tele = obs.Telemetry(trace_dir=str(tmp_path))
    with train.TrainSession(state, step, checkpoint_dir=str(tmp_path / "ck"),
                            telemetry=tele,
                            hooks=[train.StopAtStepHook(2)]) as sess:
        while not sess.should_stop():
            sess.run_step((xt[:50], yt[:50]))
    tele.close()
    doc = json.load(open(tele.trace_path))
    assert any(e["name"] == "checkpoint" for e in doc["traceEvents"])
    h = tele.registry.get("dttpu_checkpoint_save_seconds")
    assert h is not None and h.count >= 1


def test_telemetry_off_is_inert(tmp_path):
    """No trace_dir, no metrics_port: spans are no-ops, nothing is
    written, and the session hot path takes the telemetry-off branch."""
    tele = obs.Telemetry()
    assert tele.trace_path is None and tele.metrics_url() is None
    with tele.tracer.span("dispatch"):
        pass
    assert tele.save_trace() is None
    assert [e for e in tele.tracer.events() if e["ph"] != "M"] == []
    tele.close()


# ------------------------------------------------------ request tracing

class TestReqtrace:
    """obs.reqtrace unit tier: minting gates, lane lifecycle, migration
    stitching, forensics.  The integration tier (real scheduler through
    a double migration) lives in tests/test_migration.py."""

    @pytest.fixture(autouse=True)
    def _clean(self):
        reqtrace.reset()
        yield
        reqtrace.reset()

    def test_mint_gates_on_tracer_and_configure(self):
        assert not reqtrace.enabled()
        assert reqtrace.mint() is None          # no active tracer
        t = obs_trace.activate(obs.Tracer(enabled=True))
        try:
            assert reqtrace.enabled()
            tid = reqtrace.mint()
            assert tid is not None and tid.startswith("req-")
            assert reqtrace.mint() != tid       # sequence advances
            reqtrace.configure(enabled=False)
            assert reqtrace.mint() is None      # the bench's off arm
            reqtrace.configure(enabled=True)
            assert reqtrace.mint("sim").startswith("sim-")
        finally:
            obs_trace.deactivate(t)

    def test_lifecycle_lane_rings_and_trees(self):
        tid = "req-t-000001"
        reqtrace.submitted(tid, ts_us=0.0, rid=1, plen=7)
        reqtrace.stage(tid, "prefill", ts_us=10.0)
        reqtrace.mark(tid, "first_token", ts_us=15.0, ttft_s=1.5e-5)
        reqtrace.stage(tid, "decode", ts_us=15.0)
        assert reqtrace.live_ids() == [tid]
        reqtrace.retired(tid, "ok", ts_us=40.0, tokens=3)
        assert reqtrace.live_ids() == []
        (rec,) = reqtrace.completed()
        assert rec["status"] == "ok" and rec["hops"] == 0
        # every async event shares the one (cat, id) pair — the track key
        assert {(e["cat"], e["id"]) for e in rec["events"]} == {
            ("request", tid)}
        t = reqtrace.tree(tid)
        (root,) = t["spans"]
        assert root["name"] == "request"
        assert root["start_us"] == 0.0 and root["end_us"] == 40.0
        assert [c["name"] for c in root["children"]] == [
            "queued", "prefill", "decode"]
        assert [m["name"] for m in root["children"][1]["marks"]] == [
            "first_token"]
        assert root["args"]["status"] == "ok"

    def test_migrated_lane_is_one_contiguous_tree(self):
        tid = "req-t-000002"
        reqtrace.submitted(tid, ts_us=0.0)
        reqtrace.stage(tid, "prefill", ts_us=5.0)
        reqtrace.exported(tid, ts_us=9.0, generated=2)
        reqtrace.retired(tid, "migrated", ts_us=9.0)   # no-op: lane open
        assert reqtrace.live_ids() == [tid]
        reqtrace.imported(tid, ts_us=11.0, resumed=2)
        reqtrace.stage(tid, "decode", ts_us=14.0)
        reqtrace.retired(tid, "ok", ts_us=20.0)
        rec = reqtrace.lookup(tid)
        assert rec["hops"] == 1 and rec["status"] == "ok"
        # exactly one flow arrow: s (export, binding-point e) then f
        flow = [(e["ph"], e.get("bp")) for e in rec["events"]
                if e["cat"] == "migration"]
        assert flow == [("s", "e"), ("f", None)]
        t = reqtrace.tree(tid)
        (root,) = t["spans"]                  # ONE root: one lane
        assert [c["name"] for c in root["children"]] == [
            "queued", "prefill", "queued", "decode"]
        assert all(c["end_us"] is not None for c in root["children"])
        assert [m["name"] for m in root["marks"]] == [
            "exported", "imported"]

    def test_events_forward_to_active_tracer(self):
        t = obs_trace.activate(obs.Tracer(enabled=True))
        try:
            tid = reqtrace.mint()
            reqtrace.submitted(tid)
            reqtrace.retired(tid, "ok")
        finally:
            obs_trace.deactivate(t)
        evs = [e for e in t.events() if e.get("cat") == "request"]
        assert [e["ph"] for e in evs] == ["b", "b", "e", "e"]
        assert {e["id"] for e in evs} == {tid}

    def test_forensic_dump_snapshots_live_victim(self):
        tid = "req-t-000003"
        reqtrace.submitted(tid, ts_us=0.0)
        reqtrace.stage(tid, "prefill", ts_us=3.0)
        entry = reqtrace.forensic_dump(tid, "watchdog_quarantine",
                                       replica=4)
        assert entry["reason"] == "watchdog_quarantine"
        assert entry["context"] == {"replica": 4}
        (root,) = entry["spans"]
        assert root["end_us"] is None          # still live when dumped
        assert root["children"][-1]["name"] == "prefill"
        assert reqtrace.forensics_log()[-1]["trace_id"] == tid
        assert reqtrace.forensic_dump("req-unknown", "x") is None

    def test_ring_is_bounded(self):
        reqtrace.configure(ring=4)
        for i in range(9):
            tid = f"req-t-{i:06x}"
            reqtrace.submitted(tid, ts_us=0.0)
            reqtrace.retired(tid, "ok", ts_us=1.0)
        ids = [r["trace_id"] for r in reqtrace.completed()]
        assert len(ids) == 4 and ids[-1] == "req-t-000008"


# --------------------------------------------------------- merge_traces

class TestMergeTraces:
    def _merge_mod(self):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import merge_traces
        finally:
            sys.path.pop(0)
        return merge_traces

    def _host_doc(self, pid, tid):
        meta = {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"host{pid}"}}
        return {"displayTimeUnit": "ms", "traceEvents": [
            meta, dict(meta),                 # per-file duplicate
            {"name": "request", "ph": "b", "cat": "request", "id": tid,
             "ts": 1.0 + pid, "pid": pid, "tid": 0}]}

    def test_merge_concatenates_and_dedupes_metadata(self):
        mod = self._merge_mod()
        tid = "req-abc-000001"
        merged = mod.merge([self._host_doc(0, tid),
                            self._host_doc(1, tid)])
        assert merged["displayTimeUnit"] == "ms"
        evs = merged["traceEvents"]
        metas = [e for e in evs if e["ph"] == "M"]
        # one per (pid, name, args): in-file + cross-file dupes dropped
        assert [m["pid"] for m in metas] == [0, 1]
        lanes = [e for e in evs if e.get("cat") == "request"]
        # both hosts' async events survive with the SAME (cat, id) —
        # the stitching invariant the merge exists to preserve
        assert len(lanes) == 2
        assert {(e["cat"], e["id"]) for e in lanes} == {
            ("request", tid)}
        assert {e["pid"] for e in lanes} == {0, 1}

    def test_cli_merges_files(self, tmp_path):
        mod = self._merge_mod()
        a, b = tmp_path / "trace-host0.json", tmp_path / "trace-host1.json"
        a.write_text(json.dumps(self._host_doc(0, "req-1")))
        b.write_text(json.dumps(self._host_doc(1, "req-1")))
        out = tmp_path / "trace-fleet.json"
        assert mod.main([str(a), str(b), "-o", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert len(doc["traceEvents"]) == 4   # 2 metas + 2 lane events


# ------------------------------------------------------------ federation

class TestFederatedMetrics:
    def test_registries_merge_under_distinct_replica_labels(self):
        fed = obs.FederatedMetrics()
        for i in range(2):
            reg = obs.Registry()
            reg.counter("dttpu_serve_tokens_total", "Tokens.").inc(
                10 * (i + 1))
            fed.add_registry(reg, replica=str(i))
        parsed = obs.parse_exposition(fed.expose())
        s = parsed["dttpu_serve_tokens_total"]["samples"]
        assert s[("dttpu_serve_tokens_total",
                  (("replica", "0"),))] == 10.0
        assert s[("dttpu_serve_tokens_total",
                  (("replica", "1"),))] == 20.0
        assert parsed["dttpu_federation_sources"]["samples"][
            ("dttpu_federation_sources", ())] == 3.0  # 2 regs + own

    def test_scraped_peer_and_dead_peer(self):
        peer = obs.Registry()
        peer.gauge("dttpu_serve_queue_depth", "Depth.").set(5)
        server = obs.MetricsServer(peer, port=0).start()
        fed = obs.FederatedMetrics()
        fed.add_scrape(server.url + "/metrics", host="peer0")
        try:
            parsed = obs.parse_exposition(fed.expose())
            assert parsed["dttpu_serve_queue_depth"]["samples"][
                ("dttpu_serve_queue_depth", (("host", "peer0"),))] == 5.0
        finally:
            server.stop()
        # dead peer: skipped + counted, never raises
        parsed = obs.parse_exposition(fed.expose())
        assert "dttpu_serve_queue_depth" not in parsed
        assert parsed["dttpu_federation_scrape_errors_total"]["samples"][
            ("dttpu_federation_scrape_errors_total", ())] >= 1.0

    def test_slo_gauges_from_streamed_evidence(self):
        fed = obs.FederatedMetrics()
        for i in range(100):
            fed.ingest("pro", ttft_s=0.01 * (i + 1),
                       tpot_s=0.001, ttft_ok=i < 90, itl_ok=True)
        parsed = obs.parse_exposition(fed.expose())
        pro = (("tenant", "pro"),)
        sam = lambda n: parsed[n]["samples"][(n, pro)]
        # nearest-rank percentiles over the sorted reservoir
        assert sam("dttpu_slo_ttft_p50_seconds") == pytest.approx(0.50)
        assert sam("dttpu_slo_ttft_p99_seconds") == pytest.approx(0.99)
        assert sam("dttpu_slo_tpot_p50_seconds") == pytest.approx(0.001)
        assert sam("dttpu_slo_tpot_p99_seconds") == pytest.approx(0.001)
        # verdicts pool TTFT and inter-token: (90 + 100) / 200
        assert sam("dttpu_slo_attainment") == pytest.approx(0.95)

    def test_federation_behind_metrics_server(self):
        reg = obs.Registry()
        reg.counter("dttpu_steps_total", "Steps.").inc(3)
        fed = obs.FederatedMetrics().add_registry(reg, replica="0")
        server = obs.MetricsServer(fed, port=0).start()
        try:
            status, text = _get(server.url + "/metrics")
            assert status == 200
            parsed = obs.parse_exposition(text)
            assert parsed["dttpu_steps_total"]["samples"][
                ("dttpu_steps_total", (("replica", "0"),))] == 3.0
        finally:
            server.stop()
