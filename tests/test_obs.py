"""obs/ telemetry layer tests: Chrome-trace validity, Prometheus text
round-trip, the /metrics + /healthz endpoint, in-graph device health,
and the end-to-end TrainSession acceptance path (TraceHook +
MetricsExportHook + RetraceGuard retrace instants + a live scrape).
"""
import json
import math
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu import data, obs, ops, optim, train
from distributed_tensorflow_tpu.obs import device as obs_device
from distributed_tensorflow_tpu.obs import trace as obs_trace


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


# ------------------------------------------------------------- tracing

class TestTrace:
    def test_chrome_trace_json_valid(self, tmp_path):
        t = obs.Tracer(enabled=True, pid=3, host="hostX")
        with t.span("dispatch", step=1):
            pass
        t.add_span("data_load", 10.0, 20.0, step=2)
        t.instant("retrace", fn="step", arg_diff="~ x: f32[2] -> f32[3]")
        path = t.save(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        by_name = {e["name"]: e for e in events}
        # metadata record carries the host label for multi-host merging
        assert by_name["process_name"]["ph"] == "M"
        assert "hostX" in by_name["process_name"]["args"]["name"]
        assert by_name["dispatch"]["ph"] == "X"
        assert by_name["dispatch"]["dur"] >= 0
        assert by_name["data_load"]["dur"] == pytest.approx(10.0)
        assert by_name["retrace"]["ph"] == "i"
        assert all(e["pid"] == 3 for e in events)
        # every non-metadata event is timestamped (merge-sortable)
        assert all("ts" in e for e in events if e["ph"] != "M")

    def test_disabled_tracer_records_nothing(self):
        t = obs.Tracer(enabled=False)
        with t.span("dispatch"):
            pass
        t.instant("retrace")
        assert [e for e in t.events() if e["ph"] != "M"] == []

    def test_active_tracer_module_sink(self):
        t = obs.Tracer(enabled=True)
        obs_trace.instant("orphan")          # no active tracer: no-op
        with obs_trace.activated(t):
            obs_trace.instant("mark", k=1)
            with obs_trace.span("s"):
                pass
        obs_trace.instant("after")           # deactivated again
        names = [e["name"] for e in t.events() if e["ph"] != "M"]
        assert names == ["mark", "s"]
        assert t.instant_counts == {"mark": 1}


# ------------------------------------------------------------- metrics

class TestMetrics:
    def test_exposition_roundtrips_prometheus_text(self):
        reg = obs.Registry()
        reg.counter("requests_total", "Requests.",
                    labels={"path": "a"}).inc(3)
        reg.counter("requests_total", "Requests.",
                    labels={"path": "b"}).inc()
        reg.gauge("temp_celsius", "Temp.").set(-1.5)
        h = reg.histogram("lat_seconds", "Latency.", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 0.5, 5.0):
            h.observe(v)
        text = reg.expose()
        parsed = obs.parse_exposition(text)
        assert parsed["requests_total"]["type"] == "counter"
        assert parsed["requests_total"]["samples"][
            ("requests_total", (("path", "a"),))] == 3.0
        assert parsed["requests_total"]["samples"][
            ("requests_total", (("path", "b"),))] == 1.0
        assert parsed["temp_celsius"]["samples"][
            ("temp_celsius", ())] == -1.5
        hs = parsed["lat_seconds"]["samples"]
        # cumulative buckets + +Inf + sum/count — the full histogram law
        assert hs[("lat_seconds_bucket", (("le", "0.1"),))] == 1.0
        assert hs[("lat_seconds_bucket", (("le", "1"),))] == 3.0
        assert hs[("lat_seconds_bucket", (("le", "+Inf"),))] == 4.0
        assert hs[("lat_seconds_count", ())] == 4.0
        assert hs[("lat_seconds_sum", ())] == pytest.approx(6.05)

    def test_get_or_create_shares_series_and_checks_types(self):
        reg = obs.Registry()
        a = reg.counter("steps_total", "Steps.")
        b = reg.counter("steps_total")
        assert a is b
        a.inc(2)
        assert b.value == 2
        with pytest.raises(ValueError):
            reg.gauge("steps_total")
        with pytest.raises(ValueError):
            reg.counter("bad name!")
        with pytest.raises(ValueError):
            a.inc(-1)

    def test_histogram_quantile_estimate(self):
        h = obs.Histogram("h", "", (), buckets=(0.01, 0.1, 1.0))
        assert math.isnan(h.quantile(0.5))
        for _ in range(9):
            h.observe(0.05)
        h.observe(5.0)
        assert h.quantile(0.5) == 0.1
        assert h.quantile(0.99) == float("inf")


# ---------------------------------------------------------------- http

class TestHttp:
    def test_metrics_and_healthz_endpoints(self):
        reg = obs.Registry()
        reg.counter("ticks_total", "Ticks.").inc(7)
        server = obs.MetricsServer(reg, port=0,
                                   health_fn=lambda: {"status": "ok",
                                                      "replica": 2})
        server.start()
        try:
            assert server.port != 0   # ephemeral port resolved
            status, text = _get(server.url + "/metrics")
            assert status == 200
            parsed = obs.parse_exposition(text)
            assert parsed["ticks_total"]["samples"][
                ("ticks_total", ())] == 7.0
            status, body = _get(server.url + "/healthz")
            assert status == 200
            assert json.loads(body)["replica"] == 2
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + "/nope")
            assert e.value.code == 404
        finally:
            server.stop()

    def test_healthz_failure_is_503_not_a_crash(self):
        def sick():
            raise RuntimeError("replica wedged")

        server = obs.MetricsServer(obs.Registry(), port=0,
                                   health_fn=sick).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(server.url + "/healthz")
            assert e.value.code == 503
            assert "wedged" in e.value.read().decode()
        finally:
            server.stop()


# -------------------------------------------------------- device health

class TestDeviceHealth:
    def test_grad_health_in_graph_counts_nonfinite(self):
        grads = {"a": jnp.asarray([3.0, 4.0]),
                 "b": jnp.asarray([[float("nan"), float("inf")],
                                   [0.0, 0.0]])}

        @jax.jit
        def health(g):
            return obs_device.grad_health(g)

        out = health(grads)
        assert float(out[obs_device.NONFINITE_KEY]) == 2.0
        assert not math.isfinite(float(out[obs_device.GRAD_NORM_KEY]))
        clean = health({"a": jnp.asarray([3.0, 4.0])})
        assert float(clean[obs_device.GRAD_NORM_KEY]) == pytest.approx(5.0)
        assert float(clean[obs_device.NONFINITE_KEY]) == 0.0

    def test_train_step_device_health_rides_metrics_dict(self):
        model = ops.serial(ops.Dense(8, "relu"), ops.Dense(32, "sigmoid"))
        opt = optim.adam()
        state = train.init_train_state(model, opt, jax.random.PRNGKey(0),
                                       (64,))
        step = train.make_train_step(model, "mse", opt, device_health=True)
        (xt, yt), _ = data.xor_data(100, val_size=10, seed=0)
        state, m = step(state, (xt[:50], yt[:50]))
        assert float(m[obs_device.GRAD_NORM_KEY]) > 0
        assert float(m[obs_device.NONFINITE_KEY]) == 0.0

    def test_live_arrays_bytes_counts_new_buffer(self):
        before = obs_device.live_arrays_bytes()
        keep = jnp.ones((256, 256), jnp.float32)
        keep.block_until_ready()
        after = obs_device.live_arrays_bytes()
        assert after - before >= 256 * 256 * 4
        del keep


# ------------------------------------------------- end-to-end acceptance

def test_session_telemetry_end_to_end(tmp_path):
    """ISSUE 3 acceptance: a short TrainSession run with TraceHook +
    MetricsExportHook yields (a) valid Chrome trace JSON containing
    dispatch and retrace events and (b) a live /metrics scrape showing
    the step counter and the step-time histogram."""
    from distributed_tensorflow_tpu.analysis.sanitizer import RetraceGuard

    tele = obs.Telemetry(trace_dir=str(tmp_path), metrics_port=0)
    (xt, yt), _ = data.xor_data(200, val_size=10, seed=0)
    with RetraceGuard(budget=1, mode="warn",
                      stream=open("/dev/null", "w")) as guard:
        model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
        opt = optim.adam()
        state = train.init_train_state(model, opt, jax.random.PRNGKey(0),
                                       (64,))
        # built INSIDE the guard: traces are counted and mirrored onto
        # the active tracer as jit_compile/retrace instants
        step = train.make_train_step(model, "mse", opt, device_health=True)
        with train.TrainSession(
                state, step, telemetry=tele,
                hooks=[train.TraceHook(tele),
                       train.MetricsExportHook(tele, every_steps=1,
                                               examples_per_step=50),
                       train.StopAtStepHook(4)]) as sess:
            n = 0
            while not sess.should_stop():
                # last batch changes shape: a real retrace, on purpose
                b = (xt[:30], yt[:30]) if n == 3 else (xt[:50], yt[:50])
                sess.run_step(b)
                n += 1
        status, text = _get(tele.metrics_url())
    tele.close()
    assert guard.violations, "the shape change must have retraced"

    # (a) the trace file is valid Chrome trace JSON with the span/instant
    # vocabulary docs/OBSERVABILITY.md documents
    doc = json.load(open(tele.trace_path))
    events = doc["traceEvents"]
    names = {}
    for e in events:
        names[e["name"]] = names.get(e["name"], 0) + 1
    assert names["dispatch"] == 4        # one per run_step, from session
    assert names["step"] == 4            # TraceHook host-step spans
    assert names["data_load"] == 4       # inter-step host gap spans
    assert names["jit_compile"] >= 1     # first trace instant
    assert names["retrace"] == 1         # the shape-change recompile
    retrace = next(e for e in events if e["name"] == "retrace")
    assert "arg_diff" in retrace["args"]         # actionable, not forensic
    assert "[30,64]" in retrace["args"]["arg_diff"]
    steps_args = sorted(e["args"]["step"] for e in events
                        if e["name"] == "step")
    assert steps_args == [1, 2, 3, 4]

    # (b) the live scrape carried the step counter + step-time histogram
    assert status == 200
    parsed = obs.parse_exposition(text)
    assert parsed["dttpu_steps_total"]["type"] == "counter"
    assert parsed["dttpu_steps_total"]["samples"][
        ("dttpu_steps_total", ())] == 4.0
    hist = parsed["dttpu_step_time_seconds"]
    assert hist["type"] == "histogram"
    assert hist["samples"][("dttpu_step_time_seconds_count", ())] == 4.0
    assert hist["samples"][("dttpu_step_time_seconds_sum", ())] > 0
    # throughput, retrace count, device health, memory gauge all exported
    assert parsed["dttpu_examples_per_second"]["samples"][
        ("dttpu_examples_per_second", ())] > 0
    assert parsed["dttpu_retraces_total"]["samples"][
        ("dttpu_retraces_total", ())] == 1.0
    assert parsed["dttpu_live_arrays_bytes"]["samples"][
        ("dttpu_live_arrays_bytes", ())] > 0
    assert ("dttpu_grad_norm", ()) in parsed["dttpu_grad_norm"]["samples"]


def test_telemetry_checkpoint_span_and_duration(tmp_path):
    """session.save() under telemetry: a 'checkpoint' span lands on the
    timeline and the save-duration histogram observes it."""
    model = ops.serial(ops.Dense(8, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt)
    (xt, yt), _ = data.xor_data(100, val_size=10, seed=0)
    tele = obs.Telemetry(trace_dir=str(tmp_path))
    with train.TrainSession(state, step, checkpoint_dir=str(tmp_path / "ck"),
                            telemetry=tele,
                            hooks=[train.StopAtStepHook(2)]) as sess:
        while not sess.should_stop():
            sess.run_step((xt[:50], yt[:50]))
    tele.close()
    doc = json.load(open(tele.trace_path))
    assert any(e["name"] == "checkpoint" for e in doc["traceEvents"])
    h = tele.registry.get("dttpu_checkpoint_save_seconds")
    assert h is not None and h.count >= 1


def test_telemetry_off_is_inert(tmp_path):
    """No trace_dir, no metrics_port: spans are no-ops, nothing is
    written, and the session hot path takes the telemetry-off branch."""
    tele = obs.Telemetry()
    assert tele.trace_path is None and tele.metrics_url() is None
    with tele.tracer.span("dispatch"):
        pass
    assert tele.save_trace() is None
    assert [e for e in tele.tracer.events() if e["ph"] != "M"] == []
    tele.close()
