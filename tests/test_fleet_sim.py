"""Fleet simulator + autoscaler tests (fleet/sim.py, fleet/workload.py,
fleet/autoscaler.py).

The contracts pinned here (docs/FLEET_SIM.md):
  * determinism — the same (trace seed, sim seed) pair reproduces the
    run BIT-IDENTICALLY: report dict, event log, and router placements;
    a different trace seed produces a different arrival schedule
    (fingerprint), so seeds are real knobs rather than decoration,
  * ``EngineProtocol`` — ``SimEngine`` and the real ``serve.Engine``
    both satisfy the runtime-checkable protocol, and
    ``Router.add_replica`` rejects anything that doesn't (the sim's
    core claim — the SAME router code runs in both worlds — is a type
    statement, so it is enforced as one),
  * ``correlated_kill`` — a scheduled multi-replica kill mid-trace is
    healed by the autoscaler floor and every request is accounted for
    (completed + expired + lost == submitted),
  * wedge -> quarantine on VIRTUAL time — the real ``Watchdog`` reads
    the simulated heartbeat through ``check(now=vt)``,
  * ``CostModel.calibrate`` rejects ill-conditioned two-point fits
    (implied negative host overhead) instead of clamping,
  * the real-fleet acceptance: the SAME ``Autoscaler`` drives a real
    CPU ``serve.Engine`` fleet through one backlog-triggered scale-out
    and one migrate-based scale-in, with every request — including the
    migrated one — token-identical to solo ``generate``.
"""
import dataclasses

import numpy as np
import pytest

from distributed_tensorflow_tpu import fleet
from distributed_tensorflow_tpu.analysis import graph as graph_lib
from distributed_tensorflow_tpu.fleet import sim as sim_lib
from distributed_tensorflow_tpu.fleet import workload
from distributed_tensorflow_tpu.obs import metrics as metrics_lib

from test_fleet import (_engine, _generate_tokens, _model_params,
                        _prompt)


def _cost_model(**kw):
    kw.setdefault("n_params", 1.0e8)
    kw.setdefault("prefill_chunk", 16)
    kw.setdefault("num_slots", 4)
    kw.setdefault("tick_steps", 4)
    return sim_lib.CostModel.analytic(hw=sim_lib.HardwarePoint(), **kw)


def _sim(trace, **kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("engine", dict(num_slots=4, prefill_chunk=16,
                                 tick_steps=4))
    kw.setdefault("slo", fleet.SLO(ttft_s=2.0, itl_s=0.05))
    return sim_lib.FleetSim(trace, _cost_model(), **kw)


# ---------------------------------------------------------------------------
# determinism


def test_same_seeds_reproduce_run_bit_identically():
    """Same (trace, sim seed) twice -> identical report, event log,
    and placement sequence.  This is the property that makes the
    simulator usable for regression bisection: a policy diff is a real
    diff, never noise."""
    def run():
        trace = workload.synthesize(3000, seed=7, horizon_s=90.0,
                                    bursts=2, burst_magnitude=4.0,
                                    failures=1, failure_k=1)
        fs = _sim(trace,
                  autoscaler=dict(min_replicas=2, max_replicas=4,
                                  eval_interval_s=5.0, cooldown_s=10.0),
                  watchdog=dict(tick_deadline_s=2.0), seed=3)
        rep = fs.run()
        return rep, list(fs.event_log), list(fs.router.placements)

    rep_a, log_a, place_a = run()
    rep_b, log_b, place_b = run()
    assert rep_a == rep_b
    assert log_a == log_b
    assert place_a == place_b
    assert rep_a["completed"] > 0


def test_different_trace_seed_changes_arrivals():
    a = workload.synthesize(500, seed=0, horizon_s=30.0)
    b = workload.synthesize(500, seed=1, horizon_s=30.0)
    c = workload.synthesize(500, seed=0, horizon_s=30.0)
    assert a.fingerprint() != b.fingerprint()
    assert a.fingerprint() == c.fingerprint()
    assert not np.array_equal(a.arrival_s, b.arrival_s)


# ---------------------------------------------------------------------------
# EngineProtocol: one router, two worlds


def test_sim_engine_satisfies_engine_protocol():
    eng = sim_lib.SimEngine(_cost_model(), num_slots=2)
    assert isinstance(eng, fleet.EngineProtocol)


def test_real_engine_satisfies_engine_protocol():
    model, params = _model_params()
    assert isinstance(_engine(model, params), fleet.EngineProtocol)


def test_router_rejects_non_engine():
    class Bogus:
        def submit(self, *a, **k):
            pass

    router = fleet.Router(registry=metrics_lib.Registry())
    with pytest.raises(TypeError, match="EngineProtocol"):
        router.add_replica(Bogus())


# ---------------------------------------------------------------------------
# chaos on virtual time


def test_correlated_kill_healed_by_autoscaler_floor():
    """A scheduled correlated_kill takes out the whole 2-replica fleet
    mid-trace; the autoscaler's heal path restores the floor and the
    run accounts for every request."""
    trace = workload.synthesize(1500, seed=5, horizon_s=60.0,
                                bursts=0, failures=1, failure_k=2)
    assert any(e.kind == "correlated_kill" for e in trace.events)
    fs = _sim(trace,
              autoscaler=dict(min_replicas=2, max_replicas=3,
                              eval_interval_s=2.0, cooldown_s=5.0),
              seed=1)
    rep = fs.run()
    assert rep["correlated_kills_armed"] == 1
    assert rep["replicas_final"] >= 2
    assert rep["scale_outs"] >= 1
    assert (rep["completed"] + rep["deadline_exceeded"] + rep["lost"]
            == rep["simulated_requests"] == len(trace))
    # the kill actually fired: its victims' requests moved or died,
    # either way the router logged the arming
    assert any(e[0] == "correlated_kill" for e in fs.event_log)


def test_wedged_replica_quarantined_on_virtual_time():
    """A wedge_replica event stalls one SimEngine's heartbeat; the REAL
    Watchdog, fed virtual now, quarantines it and the router migrates
    its requests to the survivor."""
    base = workload.synthesize(600, seed=2, horizon_s=40.0, bursts=0,
                               failures=0)
    trace = dataclasses.replace(
        base, events=(workload.FleetEvent(
            at_s=5.0, kind="wedge_replica", seconds=30.0),))
    fs = _sim(trace, watchdog=dict(tick_deadline_s=1.0), seed=4)
    rep = fs.run()
    assert rep["quarantines"] >= 1
    assert any(e[0] == "wedge" for e in fs.event_log)
    assert any(e[0] == "quarantine" for e in fs.event_log)
    assert rep["completed"] + rep["deadline_exceeded"] == len(trace)
    assert rep["migrations"] >= 1


# ---------------------------------------------------------------------------
# sampled request tracing on virtual time (obs/reqtrace.py)


def test_sim_emits_sampled_request_lanes_on_virtual_time():
    """SimEngines mint nothing themselves — router-minted ids arrive at
    submit and are kept 1-in-``trace_sample``.  Kept lanes carry the
    full lifecycle vocabulary with VIRTUAL timestamps (ts == virtual
    seconds * 1e6), and a sim-migrated lane keeps its id across the
    hop (the import side never re-samples)."""
    from distributed_tensorflow_tpu.obs import reqtrace
    from distributed_tensorflow_tpu.obs import trace as obs_trace
    reqtrace.reset()
    tracer = obs_trace.activate(obs_trace.Tracer(enabled=True))
    try:
        trace = workload.synthesize(400, seed=3, horizon_s=30.0,
                                    bursts=0, failures=0)
        fs = _sim(trace, engine=dict(num_slots=4, prefill_chunk=16,
                                     tick_steps=4, trace_sample=8),
                  seed=1)
        rep = fs.run()
        assert rep["completed"] == len(trace)
        lanes = reqtrace.completed()
        # 1-in-8 of 400 over 2 replicas: sampled, not all, not none
        assert 20 <= len(lanes) <= 80
        for rec in lanes[:10]:
            names = [e["name"] for e in rec["events"]]
            assert names[0] == "request" and "prefill" in names
            # virtual clocks: the whole run spans ~30 virtual seconds,
            # so every ts sits far below any wall-clock microsecond
            # stamp (perf-counter epochs are >> 1e9)
            assert all(0 <= e["ts"] < 300e6 for e in rec["events"])
            t = reqtrace.tree(rec["trace_id"])
            (root,) = t["spans"]
            assert root["args"]["status"] == "ok"
    finally:
        obs_trace.deactivate(tracer)
        reqtrace.reset()


def test_sim_migrated_lane_survives_hop_without_resampling():
    """A wedge-driven sim migration: every victim lane that was sampled
    on the source replica continues on the survivor under the SAME id
    (hops >= 1), never re-rolled by the destination's sampler."""
    from distributed_tensorflow_tpu.obs import reqtrace
    from distributed_tensorflow_tpu.obs import trace as obs_trace
    reqtrace.reset()
    tracer = obs_trace.activate(obs_trace.Tracer(enabled=True))
    try:
        base = workload.synthesize(600, seed=2, horizon_s=40.0,
                                   bursts=0, failures=0)
        trace = dataclasses.replace(
            base, events=(workload.FleetEvent(
                at_s=5.0, kind="wedge_replica", seconds=30.0),))
        fs = _sim(trace, engine=dict(num_slots=4, prefill_chunk=16,
                                     tick_steps=4, trace_sample=4),
                  watchdog=dict(tick_deadline_s=1.0), seed=4)
        rep = fs.run()
        assert rep["migrations"] >= 1
        migrated = [r for r in reqtrace.completed() if r["hops"] >= 1]
        assert migrated, "no sampled lane crossed the hop"
        for rec in migrated:
            flow = [e["ph"] for e in rec["events"]
                    if e["cat"] == reqtrace.FLOW_CAT]
            assert flow == ["s", "f"] * rec["hops"]
            assert rec["status"] in ("ok", "deadline_exceeded")
    finally:
        obs_trace.deactivate(tracer)
        reqtrace.reset()


# ---------------------------------------------------------------------------
# cost model calibration


def test_calibrate_good_fit_reproduces_measured_points():
    window = graph_lib.Cost(flops=1.0e9, bytes=0.0, peak_bytes=0.0)
    tick = graph_lib.Cost(flops=4.0e9, bytes=0.0, peak_bytes=0.0)
    cm = sim_lib.CostModel.calibrate(window, tick,
                                     measured_window_s=0.002,
                                     measured_tick_s=0.005)
    assert cm.provenance == "calibrated"
    assert cm.prefill_window_s == pytest.approx(0.002)
    assert cm.decode_tick_s == pytest.approx(0.005)


def test_calibrate_rejects_ill_conditioned_fit():
    """Times 3x apart but flops nearly equal -> the implied host
    overhead is negative (the separation is dispatch, not compute);
    the fit must fall back to the measured times, not clamp."""
    window = graph_lib.Cost(flops=1.00e9, bytes=0.0, peak_bytes=0.0)
    tick = graph_lib.Cost(flops=1.01e9, bytes=0.0, peak_bytes=0.0)
    cm = sim_lib.CostModel.calibrate(window, tick,
                                     measured_window_s=0.001,
                                     measured_tick_s=0.003)
    assert cm.provenance == "measured"
    assert cm.prefill_window_s == pytest.approx(0.001)
    assert cm.decode_tick_s == pytest.approx(0.003)


# ---------------------------------------------------------------------------
# the real-fleet acceptance: same policy object, real engines


def test_autoscaler_real_fleet_scale_out_in_token_identical():
    """The Autoscaler drives a REAL CPU serve.Engine fleet: a backlog
    burst trips one scale-out (1 -> 2 replicas), the lull trips one
    migrate-based scale-in (2 -> 1) while work is still decoding, and
    every request — including the migrated one — matches solo
    ``generate`` token-for-token."""
    model, params = _model_params()
    reg = metrics_lib.Registry()

    def factory():
        return _engine(model, params, reg=reg, num_slots=4)

    router = fleet.Router([factory()], registry=reg)
    auto = fleet.Autoscaler(
        router, factory, fleet.SLO(ttft_s=2.0, itl_s=1.0),
        min_replicas=1, max_replicas=2, backlog_high=0.5,
        util_low=0.8, eval_interval_s=1.0, cooldown_s=30.0,
        drain_timeout_s=60.0, registry=reg)

    # burst: 5 queued on 4 slots > backlog_high * slots -> scale out
    prompts = [_prompt(3 + i % 4, seed=i) for i in range(5)]
    hs = [router.submit(p, 6) for p in prompts]
    assert auto.evaluate(now=0.0) == ("scale_out", 1)
    assert len(router.replica_ids) == 2
    router.drain()
    assert all(h.status == "ok" for h in hs)

    # lull: two live decodes spread across both replicas, then the
    # policy retires the newest replica — its in-flight request rides
    # a migration snapshot, it does NOT restart
    tail = [_prompt(4, seed=10), _prompt(5, seed=11)]
    ht = [router.submit(p, 8) for p in tail]
    router.step()
    assert {h.replica_id for h in ht} == {0, 1}
    assert auto.evaluate(now=60.0) == ("scale_in", 1)
    assert router.replica_ids == (0,)
    assert reg.get("dttpu_migrations_total").value >= 1
    router.drain()

    assert auto.scale_outs == 1 and auto.scale_ins == 1
    for p, h in zip(prompts + tail, hs + ht):
        assert h.status == "ok"
        assert h.tokens == _generate_tokens(model, params, p, len(h.tokens), 32)
    assert [len(h.tokens) for h in hs + ht] == [6] * 5 + [8] * 2
