"""Ring-flash attention (SP ring x fused Pallas kernel) parity tests.

Same contracts as tests/test_ring.py, plus parity against the plain XLA
ring — the composition must be numerically interchangeable with both the
dense reference and the existing ring path (kernels run in interpret
mode on the CPU mesh, so this covers the identical kernel code)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops.attention import (causal_mask,
                                                      dot_product_attention,
                                                      padding_mask)
from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.ring import ring_attention_sharded
from distributed_tensorflow_tpu.parallel.ring_flash import (
    ring_flash_attention_sharded)


def _qkv(b=2, s=64, h=4, d=16):
    k = jax.random.PRNGKey(0)
    return [jax.random.normal(x, (b, s, h, d)) for x in jax.random.split(k, 3)]


def test_matches_full_attention():
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v)
    mesh = make_mesh({"seq": 8})
    out = ring_flash_attention_sharded(q, k, v, mesh, "seq")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_causal_matches_masked_attention():
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, mask=causal_mask(64))
    mesh = make_mesh({"seq": 8})
    out = ring_flash_attention_sharded(q, k, v, mesh, "seq", causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_causal_matches_plain_ring():
    q, k, v = _qkv(b=1, s=32, h=2, d=8)
    mesh = make_mesh({"seq": 8})
    ring = ring_attention_sharded(q, k, v, mesh, "seq", causal=True)
    flash = ring_flash_attention_sharded(q, k, v, mesh, "seq", causal=True)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(ring),
                               atol=2e-5)


def test_partial_manual_inside_jit():
    """seq manual, data auto — the nesting used by the models under pjit."""
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v)
    mesh = make_mesh({"data": 2, "seq": 4})
    sh = NamedSharding(mesh, P("data", "seq"))

    @jax.jit
    def f(q, k, v):
        return ring_flash_attention_sharded(q, k, v, mesh, "seq")

    out = f(*[jax.device_put(t, sh) for t in (q, k, v)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gradients_match_dense():
    q, k, v = _qkv(b=1, s=16, h=2, d=8)
    mesh = make_mesh({"seq": 8})

    def loss(q, k, v):
        return ring_flash_attention_sharded(q, k, v, mesh, "seq",
                                            causal=True).sum()

    def ref_loss(q, k, v):
        return dot_product_attention(q, k, v,
                                     mask=causal_mask(16)).sum()

    g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_padding_mask_matches_masked_attention():
    q, k, v = _qkv()
    valid = jnp.ones((2, 64), jnp.int32).at[:, 48:].set(0)
    ref = dot_product_attention(q, k, v, mask=padding_mask(valid))
    mesh = make_mesh({"seq": 8})
    out = ring_flash_attention_sharded(q, k, v, mesh, "seq",
                                       kv_valid=valid)
    np.testing.assert_allclose(np.asarray(out[:, :48]),
                               np.asarray(ref[:, :48]), atol=2e-5)


def test_padding_plus_causal_gradients():
    """Both masks at once, through the custom backward."""
    q, k, v = _qkv(b=1, s=16, h=2, d=8)
    valid = jnp.ones((1, 16), jnp.int32).at[:, 12:].set(0)
    mesh = make_mesh({"seq": 8})

    def loss(q, k, v):
        out = ring_flash_attention_sharded(q, k, v, mesh, "seq",
                                           causal=True, kv_valid=valid)
        return (out[:, :12] ** 2).sum()

    def ref_loss(q, k, v):
        m = padding_mask(valid) + causal_mask(16)
        out = dot_product_attention(q, k, v, mask=m)
        return (out[:, :12] ** 2).sum()

    g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_bert_sp_flash_matches_dense():
    """BERT with seq_axis + use_flash=True routes through ring-flash and
    must match the dense single-device forward."""
    from distributed_tensorflow_tpu.models.bert import Bert, bert_tiny
    mesh = make_mesh({"seq": 8})
    dense = bert_tiny(dropout_rate=0.0, use_flash=False)
    spf = Bert(dense.config.__class__(**{**dense.config.__dict__,
                                         "seq_axis": "seq",
                                         "use_flash": True}), mesh=mesh)
    params = dense.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 1000)
    np.testing.assert_allclose(np.asarray(dense.apply(params, ids)),
                               np.asarray(spf.apply(params, ids)),
                               atol=2e-4)


def test_gpt_sp_flash_matches_dense():
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    mesh = make_mesh({"seq": 8})
    kw = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=2,
              intermediate_size=512, max_position=128, dropout_rate=0.0)
    dense = GPT(GPTConfig(use_flash=False, **kw))
    spf = GPT(GPTConfig(seq_axis="seq", use_flash=True, **kw), mesh=mesh)
    params = dense.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 512)
    np.testing.assert_allclose(np.asarray(dense.apply(params, ids)),
                               np.asarray(spf.apply(params, ids)),
                               atol=2e-4)


def test_gqa_kv_heads_unbroadcast():
    """GQA: the ring rotates the SMALL kv-head blocks (hk < h) and the
    kernel maps query groups by index — parity vs broadcasting kv."""
    k0 = jax.random.PRNGKey(7)
    b, s, h, hk, d = 1, 32, 4, 2, 8
    q = jax.random.normal(jax.random.fold_in(k0, 0), (b, s, h, d))
    k = jax.random.normal(jax.random.fold_in(k0, 1), (b, s, hk, d))
    v = jax.random.normal(jax.random.fold_in(k0, 2), (b, s, hk, d))
    kb = jnp.repeat(k, h // hk, axis=2)
    vb = jnp.repeat(v, h // hk, axis=2)
    ref = dot_product_attention(q, kb, vb, mask=causal_mask(s))
    mesh = make_mesh({"seq": 8})
    out = ring_flash_attention_sharded(q, k, v, mesh, "seq", causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gpt_gqa_sp_flash_matches_dense():
    """GQA GPT under SP+flash: the supports_gqa route end-to-end."""
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    mesh = make_mesh({"seq": 8})
    kw = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
              num_kv_heads=2, intermediate_size=512, max_position=128,
              dropout_rate=0.0)
    dense = GPT(GPTConfig(use_flash=False, **kw))
    spf = GPT(GPTConfig(seq_axis="seq", use_flash=True, **kw), mesh=mesh)
    params = dense.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 512)
    np.testing.assert_allclose(np.asarray(dense.apply(params, ids)),
                               np.asarray(spf.apply(params, ids)),
                               atol=2e-4)


def test_ring_flash_composes_with_remat():
    """seq_axis + use_flash + remat: the custom-vjp ring inside a
    jax.checkpoint'd scanned layer — gradients must match the dense
    no-remat model."""
    from distributed_tensorflow_tpu.models.bert import Bert, bert_tiny
    mesh = make_mesh({"seq": 8})
    dense = bert_tiny(dropout_rate=0.0, use_flash=False)
    spf = Bert(dense.config.__class__(**{**dense.config.__dict__,
                                         "seq_axis": "seq",
                                         "use_flash": True,
                                         "remat": True}), mesh=mesh)
    params = dense.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 1000)

    def loss(model):
        return lambda p: (model.apply(p, ids).astype(jnp.float32) ** 2).sum()

    # jit is required: remat (closed_call) inside shard_map has no eager
    # path — and the train steps that use this are always jitted
    g0 = jax.jit(jax.grad(loss(dense)))(params)
    g1 = jax.jit(jax.grad(loss(spf)))(params)
    f0 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g0)])
    f1 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g1)])
    np.testing.assert_allclose(f0, f1, atol=5e-3)
