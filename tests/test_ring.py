"""Ring attention (sequence parallelism) tests on the 8-device mesh."""
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops.attention import (causal_mask,
                                                      dot_product_attention)
from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.ring import ring_attention_sharded


def _qkv(b=2, s=64, h=4, d=16):
    k = jax.random.PRNGKey(0)
    return [jax.random.normal(x, (b, s, h, d)) for x in jax.random.split(k, 3)]


def test_ring_matches_full_attention():
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v)
    mesh = make_mesh({"seq": 8})
    out = ring_attention_sharded(q, k, v, mesh, "seq")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_causal_matches_masked_attention():
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, mask=causal_mask(64))
    mesh = make_mesh({"seq": 8})
    out = ring_attention_sharded(q, k, v, mesh, "seq", causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_partial_manual_inside_jit():
    """seq manual, data auto — the nesting used by BERT under pjit."""
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v)
    mesh = make_mesh({"data": 2, "seq": 4})
    sh = NamedSharding(mesh, P("data", "seq"))

    @jax.jit
    def f(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, "seq")

    out = f(*[jax.device_put(t, sh) for t in (q, k, v)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_flow():
    q, k, v = _qkv(b=1, s=16, h=2, d=8)
    mesh = make_mesh({"seq": 8})

    def loss(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, "seq").sum()

    def ref_loss(q, k, v):
        return dot_product_attention(q, k, v).sum()

    g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=2e-4)


def test_ring_padding_mask_matches_masked_attention():
    from distributed_tensorflow_tpu.ops.attention import padding_mask
    import jax.numpy as jnp
    q, k, v = _qkv()
    valid = jnp.ones((2, 64), jnp.int32).at[:, 48:].set(0)
    ref = dot_product_attention(q, k, v, mask=padding_mask(valid))
    mesh = make_mesh({"seq": 8})
    out = ring_attention_sharded(q, k, v, mesh, "seq", kv_valid=valid)
    # only compare valid query rows (padded queries are garbage either way)
    np.testing.assert_allclose(np.asarray(out[:, :48]),
                               np.asarray(ref[:, :48]), atol=2e-5)
