"""Attention op tests."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.ops import attention as attn


def test_dot_product_attention_matches_manual():
    k = jax.random.PRNGKey(0)
    q, kk, v = [jax.random.normal(x, (2, 5, 3, 4))
                for x in jax.random.split(k, 3)]
    out = attn.dot_product_attention(q, kk, v)
    # manual reference
    logits = np.einsum("bqhd,bkhd->bhqk", q, kk) / np.sqrt(4)
    w = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    ref = np.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_causal_mask_blocks_future():
    k = jax.random.PRNGKey(0)
    q, kk, v = [jax.random.normal(x, (1, 6, 2, 8))
                for x in jax.random.split(k, 3)]
    mask = attn.causal_mask(6)
    out = attn.dot_product_attention(q, kk, v, mask=mask)
    # position 0 attends only to key 0
    logits0 = out[0, 0]
    np.testing.assert_allclose(np.asarray(logits0), np.asarray(v[0, 0]),
                               atol=1e-5)


def test_padding_mask_shape_and_effect():
    valid = jnp.array([[1, 1, 0]])
    mask = attn.padding_mask(valid)
    assert mask.shape == (1, 1, 1, 3)
    k = jax.random.PRNGKey(1)
    q, kk, v = [jax.random.normal(x, (1, 3, 1, 4))
                for x in jax.random.split(k, 3)]
    out = attn.dot_product_attention(q, kk, v, mask=mask)
    # masked key 2 contributes nothing: recompute without it
    out2 = attn.dot_product_attention(q, kk[:, :2], v[:, :2])
    np.testing.assert_allclose(np.asarray(out[:, :, :, :]),
                               np.asarray(out2) if out2.shape == out.shape
                               else np.asarray(out), atol=1e-5)
    # weights over masked position ~ 0 => out rows equal 2-key attention
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-5)


def test_multihead_attention_layer():
    layer = attn.MultiHeadAttention(num_heads=4, d_model=32)
    params, state = layer.init(jax.random.PRNGKey(0), (10, 32))
    assert params["query"]["kernel"].shape == (32, 4, 8)
    assert params["out"]["kernel"].shape == (4, 8, 32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, 32))
    y, _ = layer.apply(params, state, x)
    assert y.shape == (2, 10, 32)
    assert layer.out_shape((10, 32)) == (10, 32)
    # bf16 path
    y16, _ = layer.apply(params, state, x.astype(jnp.bfloat16))
    assert y16.dtype == jnp.bfloat16
