"""Smoke tests for the example scripts (full-stack, real subprocesses)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=900):
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable] + args, env=env,
                          capture_output=True, text=True, timeout=timeout,
                          cwd=REPO)


def test_train_gpt_example_smoke(tmp_path):
    proc = _run(["examples/train_gpt.py", "--device=cpu",
                 "--steps=8", "--batch_size=16", f"--log_dir={tmp_path}"])
    # rc 1 is the script's defined "ran fine but didn't beat the uniform
    # baseline" outcome (train_gpt.py prints the WARNING and returns 1) —
    # possible at an 8-step budget.  Anything else nonzero is a crash.
    ok = proc.returncode == 0 or (
        proc.returncode == 1
        and "did not beat the uniform baseline" in proc.stderr)
    assert ok, f"rc={proc.returncode}\n{proc.stderr[-2000:]}"
    assert "eval loss:" in proc.stdout
    assert any(p.startswith("ckpt-") for p in os.listdir(tmp_path))


def test_train_gpt_levers_smoke(tmp_path):
    """The round-4 MFU levers through the full script path (not just
    bench configs): chunked LM loss + remat with the dots policy."""
    proc = _run(["examples/train_gpt.py", "--device=cpu",
                 "--steps=4", "--batch_size=16", "--loss_seq_chunk=16",
                 "--remat", "--remat_policy=dots",
                 f"--log_dir={tmp_path}"])
    ok = proc.returncode == 0 or (
        proc.returncode == 1
        and "did not beat the uniform baseline" in proc.stderr)
    assert ok, f"rc={proc.returncode}\n{proc.stderr[-2000:]}"
    assert "eval loss:" in proc.stdout


def test_serve_gpt_demo_smoke():
    """The serving demo drives every decode path (greedy, sampled,
    ragged, beam, int8, speculative) end to end; int8 agreement and the
    spec greedy-match honesty numbers must come out ~1."""
    proc = _run(["examples/serve_gpt.py", "--device=cpu",
                 "--new_tokens=12", "--batch=2"])
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\n{proc.stderr[-2000:]}"
    for label in ("greedy generate", "beam search", "int8 weights",
                  "speculative"):
        assert label in proc.stdout, proc.stdout
    # "full-int8 ..." contains the weight-only substring; exclude it so
    # each assertion targets exactly one printed line
    agree = [l for l in proc.stdout.splitlines()
             if "int8 greedy agreement" in l and "full-int8" not in l]
    assert agree and float(agree[0].split()[-1]) > 0.9
    full8 = [l for l in proc.stdout.splitlines()
             if "full-int8 greedy agreement" in l]
    assert full8 and float(full8[0].split()[-1]) > 0.9
    match = [l for l in proc.stdout.splitlines() if "greedy match" in l]
    assert match and float(match[0].split()[-1]) > 0.9


def test_serve_gpt_shared_prefix_demo_smoke():
    """--shared_prefix adds the paged-KV radix-cache demo: the
    cold-vs-hit TTFT delta line and the prefix-hit accounting line
    must print, with at least one hit and at least one skipped prefill
    window (the mechanism, not just the headline)."""
    proc = _run(["examples/serve_gpt.py", "--device=cpu",
                 "--new_tokens=8", "--batch=2", "--shared_prefix"])
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\n{proc.stderr[-2000:]}"
    assert "shared-prefix (paged KV)" in proc.stdout, proc.stdout
    ttft = [l for l in proc.stdout.splitlines()
            if "ttft cold" in l][0]
    assert "-> hit" in ttft and "x faster" in ttft
    hits = [l for l in proc.stdout.splitlines()
            if "prefix hits" in l][0]
    n_hits = int(hits.split("prefix hits ")[1].split("/")[0])
    n_skipped = int(hits.split(", ")[1].split()[0])
    assert n_hits >= 1 and n_skipped >= 1, hits


def test_serve_gpt_fleet_demo_smoke():
    """--engine --replicas=2 adds the fleet demo: two engine replicas
    behind the Router, tenant fair-share, a hot-swapped LoRA adapter on
    the "pro" tenant — the fleet line must print with base-model rows
    token-identical to lock-step greedy and placements spread over both
    replicas."""
    proc = _run(["examples/serve_gpt.py", "--device=cpu",
                 "--new_tokens=8", "--batch=2", "--engine",
                 "--replicas=2"])
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\n{proc.stderr[-2000:]}"
    assert "fleet (2 replicas)" in proc.stdout, proc.stdout
    eng = [l for l in proc.stdout.splitlines()
           if "engine==lock-step greedy" in l]
    assert eng and float(eng[0].split()[-1]) == 1.0
    fl = [l for l in proc.stdout.splitlines()
          if "fleet==lock-step greedy" in l]
    assert fl and float(fl[0].split()[2]) == 1.0
    # placement spread printed as {0: n, 1: m}: both replicas used
    assert "placements {0:" in fl[0] and "1:" in fl[0]


def test_finetune_bert_mlm_gather_smoke():
    """MLM warm-up with the masked-position gather + fused-LN/remat flags
    through examples/finetune_bert.py (the fit-level lever surface)."""
    proc = _run(["examples/finetune_bert.py", "--device=cpu",
                 "--steps=6", "--mlm_steps=4",
                 "--mlm_predictions_per_seq=8",
                 "--remat", "--remat_policy=dots"])
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\n{proc.stderr[-2000:]}"
    assert "mlm step 4:" in proc.stdout
    assert "eval accuracy:" in proc.stdout
