"""Smoke test for the distributed GPT example (full-stack script)."""
import os
import subprocess
import sys


def test_train_gpt_example_smoke(tmp_path):
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "examples/train_gpt.py", "--device=cpu",
         "--steps=8", "--batch_size=16", f"--log_dir={tmp_path}"],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # rc 1 is the script's defined "ran fine but didn't beat the uniform
    # baseline" outcome (train_gpt.py prints the WARNING and returns 1) —
    # possible at an 8-step budget.  Anything else nonzero is a crash.
    ok = proc.returncode == 0 or (
        proc.returncode == 1
        and "did not beat the uniform baseline" in proc.stderr)
    assert ok, f"rc={proc.returncode}\n{proc.stderr[-2000:]}"
    assert "eval loss:" in proc.stdout
    assert any(p.startswith("ckpt-") for p in os.listdir(tmp_path))
