"""Continuous-batching engine tests: slot admission exactness, stale-KV
safety, retrace-free scheduling, metrics.

The contracts pinned here (docs/SERVING.md):
  * single request through the engine == greedy ``GPT.generate``
    token-for-token (chunked prefill included),
  * admitting a request mid-decode leaves other slots' logits
    BIT-identical (same executable, row-independent math),
  * int8 ``kv_cache_dtype`` slot splices round-trip values AND scales,
  * a reused slot never reads the previous occupant's K/V (left-padded
    ragged splices included),
  * admission/retirement never recompile anything (RetraceGuard).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import serve
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.ops import decoding as dec


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _generate_tokens(model, params, prompt, new, max_len, **kw):
    out = model.generate(params, jnp.asarray(prompt[None]),
                         max_new_tokens=new, max_len=max_len, **kw)
    return np.asarray(out)[0, prompt.size:].tolist()


# ---------------------------------------------------------------------------
# exactness: engine vs generate


def test_single_request_matches_generate():
    """One request in flight: streamed tokens == generate() greedy,
    token-for-token — with a single-window AND a chunked (multi-window)
    prefill."""
    model, params = _model_params()
    prompt = _prompt(7)
    want = _generate_tokens(model, params, prompt, 9, 32)
    for chunk in (8, 3):           # one window; 3 windows (ragged last)
        eng = serve.Engine(model, params, num_slots=3, max_len=32,
                           prefill_chunk=chunk, tick_steps=2)
        h = eng.submit(prompt, max_new_tokens=9)
        eng.drain()
        assert h.done and h.tokens == want, (chunk, h.tokens, want)
        assert h.ttft_s is not None and h.ttft_s > 0


def test_single_request_eos_matches_generate():
    """EOS retirement: the engine stops at the token where generate()
    starts padding, and delivers the EOS itself."""
    model, params = _model_params()
    prompt = _prompt(6, seed=3)
    plain = _generate_tokens(model, params, prompt, 10, 32)
    eos = plain[2]                  # force an early stop on a real token
    want = plain[:plain.index(eos) + 1]
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=8, tick_steps=3, eos_id=eos)
    h = eng.submit(prompt, max_new_tokens=10)
    eng.drain()
    assert h.tokens == want
    gen = _generate_tokens(model, params, prompt, 10, 32, eos_id=eos)
    assert gen[:len(want)] == want          # same prefix, then pad
    assert all(t == eos for t in gen[len(want):])


def test_concurrent_unequal_requests_match_solo():
    """Unequal-length requests decoding CONCURRENTLY in slots each equal
    their own solo generate — ragged batching without any padding."""
    model, params = _model_params()
    prompts = [_prompt(7, seed=1), _prompt(5, seed=2), _prompt(3, seed=4)]
    budgets = [9, 12, 6]
    wants = [_generate_tokens(model, params, p, n, 32)
             for p, n in zip(prompts, budgets)]
    eng = serve.Engine(model, params, num_slots=3, max_len=32,
                       prefill_chunk=4, tick_steps=3)
    handles = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
    eng.drain()
    for h, want in zip(handles, wants):
        assert h.tokens == want


def test_rope_gqa_engine_matches_generate():
    """The slot step's per-row positions drive RoPE too (Llama-shaped
    recipe: rotary positions + grouped-query cache)."""
    model, params = _model_params(position_embedding="rope", num_heads=4,
                                  hidden_size=128, num_kv_heads=2)
    prompt = _prompt(6, seed=5)
    want = _generate_tokens(model, params, prompt, 8, 32)
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2)
    h = eng.submit(prompt, 8)
    eng.drain()
    assert h.tokens == want


def test_decode_slots_step_matches_decode_step_logits():
    """Numeric oracle below the engine: one slot holding a prefilled
    request produces decode_step's logits (same cache contents, per-row
    state vs scalar pos)."""
    model, params = _model_params()
    ids = np.asarray(_prompt(6, seed=7))[None, :]
    ref_cache = model.init_cache(1, 16)
    _, ref_cache = model.decode_block(params, ref_cache,
                                      jnp.asarray(ids))
    cache = serve.init_slot_cache(model, num_slots=3, max_len=16)
    cache = serve.insert_slot(cache, 0, serve.strip_pos(ref_cache), 6)
    tok = jnp.asarray([ids[0, -1], 0, 0], jnp.int32)
    live = jnp.asarray([True, False, False])
    # feed the same token through both paths (the value fed does not
    # matter for the comparison as long as both sides see it)
    ref_logits, _ = model.decode_step(params, ref_cache, tok[:1])
    slot_logits, cache = serve.decode_slots_step(model, params, cache,
                                                 tok, live)
    np.testing.assert_allclose(np.asarray(slot_logits[0]),
                               np.asarray(ref_logits[0]), atol=2e-4)
    assert int(cache["write_col"][0]) == 7      # live row advanced
    assert int(cache["write_col"][1]) == 0      # dead rows frozen


# ---------------------------------------------------------------------------
# isolation: admission / stale KV


def test_mid_decode_insertion_keeps_other_slots_bit_identical():
    """Splicing a request into slot 1 mid-decode must not change slot
    0's logits by even one bit: same executable, row-independent math."""
    model, params = _model_params()
    p0, p1 = _prompt(6, seed=1), _prompt(4, seed=2)
    pf0 = model.init_cache(1, 16)
    _, pf0 = model.decode_block(params, pf0, jnp.asarray(p0[None]))
    pf1 = model.init_cache(1, 16)
    _, pf1 = model.decode_block(params, pf1, jnp.asarray(p1[None]))
    feed = np.asarray(_prompt(6, seed=9))       # fixed row-0 token feed

    def run(insert_at):
        cache = serve.init_slot_cache(model, 2, 16)
        cache = serve.insert_slot(cache, 0, serve.strip_pos(pf0), 6)
        live = jnp.asarray([True, False])
        out = []
        for t in range(6):
            if t == insert_at:
                cache = serve.insert_slot(cache, 1,
                                          serve.strip_pos(pf1), 4)
                live = jnp.asarray([True, True])
            tokens = jnp.asarray([feed[t], 0], jnp.int32)
            logits, cache = serve.decode_slots_step(model, params,
                                                    cache, tokens, live)
            out.append(np.asarray(logits[0]))
        return out

    alone = run(insert_at=None)
    with_insert = run(insert_at=3)
    for a, b in zip(alone, with_insert):
        np.testing.assert_array_equal(a, b)


def test_retire_then_reuse_never_reads_stale_kv():
    """Three requests through ONE slot: each newcomer's tokens equal its
    solo generate even though the slot's cache still holds the previous
    occupant's K/V beyond the new validity window — including a reuse
    where the new request is SHORTER than the leftovers."""
    model, params = _model_params()
    long_p, short_p = _prompt(12, seed=11), _prompt(3, seed=12)
    eng = serve.Engine(model, params, num_slots=1, max_len=40,
                       prefill_chunk=4, tick_steps=4)
    h1 = eng.submit(long_p, 20)     # fills columns 0..31
    h2 = eng.submit(short_p, 5)     # reuse: much shorter
    h3 = eng.submit(long_p, 20)     # reuse again with the long one
    eng.drain()
    assert h1.tokens == _generate_tokens(model, params, long_p, 20, 40)
    assert h2.tokens == _generate_tokens(model, params, short_p, 5, 40)
    assert h3.tokens == h1.tokens


def test_left_padded_ragged_splice_matches_solo():
    """insert_slot(pad_len=...) accepts a LEFT-padded ragged prefill row
    (decode_block kv_valid/positions) and the slot then decodes exactly
    the solo ragged generate — pads masked, positions shifted."""
    model, params = _model_params()
    plen, pad = 6, 2
    real = _prompt(plen - pad, seed=13)
    padded = np.zeros((plen,), np.int32)
    padded[pad:] = real
    valid = np.zeros((plen,), np.int32)
    valid[pad:] = 1
    max_len = 24
    pad_len, kv_valid = dec.ragged_prompt_masks(
        jnp.asarray(valid[None]), (1, plen), max_len)
    pf = model.init_cache(1, max_len)
    logits, pf = model.decode_block(
        params, pf, jnp.asarray(padded[None]),
        kv_valid=kv_valid[:, :plen],
        positions=jnp.maximum(jnp.arange(plen)[None, :]
                              - pad_len[:, None], 0))
    want = _generate_tokens(model, params, real, 7, max_len)

    cache = serve.init_slot_cache(model, 2, max_len)
    cache = serve.insert_slot(cache, 0, serve.strip_pos(pf),
                              plen - pad, pad_len=pad)
    kvv = np.asarray(serve.slot_kv_valid(cache))
    assert not kvv[0, :pad].any() and kvv[0, pad:plen].all() \
        and not kvv[0, plen:].any()
    tok = int(jnp.argmax(logits[0]))
    got = [tok]
    live = jnp.asarray([True, False])
    for _ in range(6):
        logits, cache = serve.decode_slots_step(
            model, params, cache, jnp.asarray([tok, 0], jnp.int32), live)
        tok = int(jnp.argmax(logits[0]))
        got.append(tok)
    assert got == want


def test_int8_slot_splice_roundtrips_scales():
    """kv_cache_dtype='int8': the slot splice carries int8 planes AND
    f32 scales bit-for-bit, and the engine's greedy output equals the
    int8 generate()'s."""
    model, params = _model_params(kv_cache_dtype="int8")
    prompt = _prompt(6, seed=1)
    pf = model.init_cache(1, 16)
    _, pf = model.decode_block(params, pf, jnp.asarray(prompt[None]))
    cache = serve.init_slot_cache(model, 3, 16)
    assert cache["kv"]["k"].dtype == jnp.int8
    assert cache["kv"]["k_scale"].dtype == jnp.float32
    cache = serve.insert_slot(cache, 1, serve.strip_pos(pf), 6)
    for name in ("k", "v", "k_scale", "v_scale"):
        np.testing.assert_array_equal(
            np.asarray(cache["kv"][name][:, 1]),
            np.asarray(pf[name][:, 0]))

    want = _generate_tokens(model, params, prompt, 8, 32)
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=8, tick_steps=3)
    h = eng.submit(prompt, 8)
    eng.drain()
    assert h.tokens == want


# ---------------------------------------------------------------------------
# scheduling behavior


@pytest.mark.retrace_guard(budget=1, enforce_donation=True)
def test_admission_and_retirement_never_recompile():
    """Every engine executable traces ONCE across a mixed workload of
    admissions, chunked prefills, EOS/budget retirements, and slot
    reuse (budget=1: the second trace of anything fails the test).
    Donation enforcement doubles as a use-after-donate check on the
    scheduler's buffer management."""
    model, params = _model_params()
    rng = np.random.default_rng(0)
    eng = serve.Engine(model, params, num_slots=2, max_len=64,
                       prefill_chunk=4, tick_steps=3, eos_id=7)
    handles = []
    for i in range(7):
        plen = int(rng.integers(2, 11))
        prompt = rng.integers(0, 512, plen).astype(np.int32)
        handles.append(eng.submit(prompt, int(rng.integers(1, 12))))
        eng.step()
    eng.drain()
    assert all(h.done for h in handles)
    assert all(len(h.tokens) >= 1 for h in handles)


def test_streaming_callbacks_deliver_everything_in_order():
    model, params = _model_params()
    prompt = _prompt(5, seed=2)
    got = []
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=8, tick_steps=2)
    h = eng.submit(prompt, 9, on_token=got.extend)
    eng.drain()
    assert got == h.tokens
    assert h.result() == h.tokens        # result() on a done handle


def test_sampled_mode_runs_and_stays_in_vocab():
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=8, tick_steps=2, temperature=0.9,
                       top_p=0.95, rng=jax.random.PRNGKey(5))
    h1 = eng.submit(_prompt(4, seed=1), 8)
    h2 = eng.submit(_prompt(6, seed=2), 8)
    eng.drain()
    for h in (h1, h2):
        assert len(h.tokens) == 8
        assert all(0 <= t < 512 for t in h.tokens)


def test_submit_validation():
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=16,
                       prefill_chunk=4)
    with pytest.raises(ValueError, match="empty"):
        eng.submit(np.zeros((0,), np.int32), 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(_prompt(4), 0)
    with pytest.raises(ValueError, match="exceeds max_len"):
        eng.submit(_prompt(4), 13)           # 4 + 13 > 16
    eng.submit(_prompt(15), 1)               # chunk-padded 16 fits
    with pytest.raises(ValueError, match="exceeds max_len"):
        eng.submit(_prompt(17), 1)           # chunk-padded 20 > 16
    with pytest.raises(ValueError, match="num_slots"):
        serve.Engine(model, params, num_slots=0, max_len=16)


def test_engine_metrics_land_in_registry():
    """The obs wiring: queue/active gauges move, TTFT and per-request
    histograms observe once per request, token/request counters add up —
    all scrapable through the standard exposition path."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=8, tick_steps=2, registry=reg)
    n_tok = [6, 4, 9]
    handles = [eng.submit(_prompt(4 + i, seed=i), n)
               for i, n in enumerate(n_tok)]
    eng.drain()
    assert all(h.done for h in handles)
    assert reg.get("dttpu_serve_requests_total").value == 3
    assert reg.get("dttpu_serve_tokens_total").value == sum(n_tok)
    assert reg.get("dttpu_serve_ttft_seconds").count == 3
    assert reg.get("dttpu_serve_request_decode_seconds").count == 3
    assert reg.get("dttpu_serve_queue_depth").value == 0
    assert reg.get("dttpu_serve_active_slots").value == 0
    doc = metrics_lib.parse_exposition(reg.expose())
    assert doc["dttpu_serve_ttft_seconds"]["type"] == "histogram"
    assert doc["dttpu_serve_tokens_total"]["type"] == "counter"


def test_generate_batch_convenience_and_queueing():
    """More requests than slots: the queue drains through slot reuse and
    every output matches its solo generate."""
    model, params = _model_params()
    prompts = [_prompt(3 + i, seed=20 + i) for i in range(6)]
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=3,
                       default_max_new_tokens=5)
    outs = eng.generate_batch(prompts)
    for p, got in zip(prompts, outs):
        assert got == _generate_tokens(model, params, p, 5, 32)


# ---------------------------------------------------------------------------
# graceful degradation: backpressure, deadlines, failure isolation
# (docs/RESILIENCE.md)


def test_queue_full_rejects_with_metric():
    """Admission control: the queue holds max_queue_depth requests, the
    next submit is rejected loudly (and counted), and a later submit is
    accepted again once the queue drains."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    eng = serve.Engine(model, params, num_slots=1, max_len=32,
                       prefill_chunk=8, tick_steps=2, registry=reg,
                       max_queue_depth=2)
    handles = [eng.submit(_prompt(4, seed=i), 4) for i in range(2)]
    with pytest.raises(serve.QueueFullError):
        eng.submit(_prompt(4, seed=9), 4)
    assert reg.get("dttpu_serve_rejected_total").value == 1
    assert reg.get("dttpu_serve_requests_total").value == 2
    eng.drain()
    assert all(h.status == "ok" for h in handles)
    h = eng.submit(_prompt(4, seed=9), 4)      # accepted after drain
    eng.drain()
    assert h.status == "ok"


def test_deadline_expires_queued_and_active_requests():
    """A queued request past its deadline never prefills; an ACTIVE one
    is retired mid-decode with partial tokens — both carry status
    deadline_exceeded + the metric, and neither decodes forever."""
    import time as time_mod
    model, params = _model_params()
    reg = metrics_lib.Registry()
    eng = serve.Engine(model, params, num_slots=1, max_len=64,
                       prefill_chunk=4, tick_steps=1, registry=reg)
    # queued expiry: one slot is busy, the second request's deadline
    # passes while it waits
    h_busy = eng.submit(_prompt(4, seed=1), 8)
    h_q = eng.submit(_prompt(4, seed=2), 8, deadline_s=0.0)
    time_mod.sleep(0.005)
    eng.drain()
    assert h_busy.status == "ok" and len(h_busy.tokens) == 8
    assert h_q.status == "deadline_exceeded" and h_q.tokens == []
    # active expiry: admit, decode a few ticks, then let the deadline hit
    h_a = eng.submit(_prompt(4, seed=3), 60, deadline_s=0.05)
    while not h_a.tokens:
        eng.step()
    deadline = time_mod.perf_counter() + 2.0
    while not h_a.done and time_mod.perf_counter() < deadline:
        eng.step()
        time_mod.sleep(0.005)
    assert h_a.status == "deadline_exceeded"
    assert 0 < len(h_a.tokens) < 60
    assert reg.get("dttpu_serve_deadline_expired_total").value == 2
    assert not eng.busy


def test_deadline_expiry_dumps_victim_span_tree():
    """Tail-latency forensics at the scheduler: a traced request that
    blows its deadline lands in reqtrace.forensics_log() with reason
    ``deadline_expired`` and its span tree intact — queued-only for a
    never-admitted victim, so the dump itself shows WHERE the budget
    went."""
    import time as time_mod
    from distributed_tensorflow_tpu.obs import reqtrace
    from distributed_tensorflow_tpu.obs import trace as obs_trace
    model, params = _model_params()
    reqtrace.reset()
    tracer = obs_trace.activate(obs_trace.Tracer(enabled=True))
    try:
        eng = serve.Engine(model, params, num_slots=1, max_len=64,
                           prefill_chunk=4, tick_steps=1,
                           registry=metrics_lib.Registry())
        h_busy = eng.submit(_prompt(4, seed=1), 8)
        h_q = eng.submit(_prompt(4, seed=2), 8, deadline_s=0.0)
        time_mod.sleep(0.005)
        eng.drain()
        assert h_busy.status == "ok"
        assert h_q.status == "deadline_exceeded"
        victims = [d for d in reqtrace.forensics_log()
                   if d["reason"] == "deadline_expired"]
        assert len(victims) == 1
        (root,) = victims[0]["spans"]
        assert root["name"] == "request"
        # the victim never left the queue — the dump says so
        assert [c["name"] for c in root["children"]] == ["queued"]
        # and the lane itself retired with the honest status
        assert reqtrace.lookup(
            victims[0]["trace_id"])["status"] == "deadline_exceeded"
    finally:
        obs_trace.deactivate(tracer)
        reqtrace.reset()


def test_poisoned_request_fails_alone_survivors_bit_exact():
    """THE serve acceptance contract: one request whose callback raises
    mid-decode fails ONLY its own handle; the scheduler keeps ticking
    and every surviving request's greedy output stays token-identical
    to generate()."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    eng = serve.Engine(model, params, num_slots=3, max_len=32,
                       prefill_chunk=4, tick_steps=2, registry=reg)
    prompts = [_prompt(5, seed=1), _prompt(4, seed=2), _prompt(6, seed=3)]
    wants = [_generate_tokens(model, params, p, 8, 32) for p in prompts]

    poison_after = [3]

    def bad_callback(toks):
        poison_after[0] -= len(toks)
        if poison_after[0] <= 0:
            raise RuntimeError("poisoned request payload")

    h0 = eng.submit(prompts[0], 8)
    h1 = eng.submit(prompts[1], 8, on_token=bad_callback)
    h2 = eng.submit(prompts[2], 8)
    eng.drain()
    assert h1.status == "failed"
    assert isinstance(h1.error, RuntimeError)
    assert h0.status == "ok" and h0.tokens == wants[0]
    assert h2.status == "ok" and h2.tokens == wants[2]
    assert reg.get("dttpu_serve_failed_total").value == 1
    # the freed slot is reusable and still exact
    h3 = eng.submit(prompts[1], 8)
    eng.drain()
    assert h3.tokens == wants[1]


def test_injected_decode_fault_fails_exact_request():
    """resilience.faults fail_decode: rid-targeted injection fails that
    handle with InjectedFault; everyone else matches generate()."""
    from distributed_tensorflow_tpu.resilience import InjectedFault, faults
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2,
                       registry=metrics_lib.Registry())
    prompts = [_prompt(5, seed=1), _prompt(4, seed=2)]
    wants = [_generate_tokens(model, params, p, 6, 32) for p in prompts]
    plan = faults.FaultPlan([{"kind": "fail_decode", "at": 1}],
                            registry=metrics_lib.Registry())
    with faults.activated(plan):
        h0 = eng.submit(prompts[0], 6)
        h1 = eng.submit(prompts[1], 6)
        eng.drain()
    assert h0.status == "ok" and h0.tokens == wants[0]
    assert h1.status == "failed" and isinstance(h1.error, InjectedFault)
    assert plan.log == [{"kind": "fail_decode", "at": 1, "rid": 1}]


def test_generate_batch_failed_submit_cancels_earlier_handles():
    """Satellite regression: a mid-list submit failure must not leave
    the already-submitted handles permanently pending — they are
    cancelled before the error propagates."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=16,
                       prefill_chunk=4, tick_steps=2,
                       registry=metrics_lib.Registry())
    prompts = [_prompt(4, seed=1), _prompt(4, seed=2),
               _prompt(17, seed=3)]          # third fails validation
    with pytest.raises(ValueError, match="exceeds max_len"):
        eng.generate_batch(prompts, max_new_tokens=4)
    # nothing left in flight, nothing pending forever
    assert not eng.busy
    assert eng.scheduler.queued == 0
    # the engine still works afterwards
    outs = eng.generate_batch(prompts[:2], max_new_tokens=4)
    assert outs == [_generate_tokens(model, params, p, 4, 16)
                    for p in prompts[:2]]


def test_drain_timeout_exports_stragglers_lossless():
    """The old ``drain(timeout_s=) -> False`` left requests stranded in
    limbo; now a timed-out drain EXPORTS the stragglers (DrainResult is
    falsy, carries their snapshots, the engine ends idle) and importing
    a snapshot resumes bit-identically to an unmigrated run."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=1, max_len=64,
                       prefill_chunk=4, tick_steps=1,
                       registry=metrics_lib.Registry())
    want = _generate_tokens(model, params, _prompt(4, seed=1), 40, 64)
    h = eng.submit(_prompt(4, seed=1), 40)
    res = eng.drain(timeout_s=0.0)              # budget hit immediately
    assert not res                              # falsy: not completed
    assert len(res.exported) == 1
    assert h.status == "migrated" and h.done
    assert not eng.busy                         # nothing left in limbo
    h2 = eng.import_request(res.exported[0])    # resume in place
    assert eng.drain()                          # truthy: fully drained
    assert h2.status == "ok" and h2.tokens == want


def test_cancel_frees_slot_and_marks_status():
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=1, max_len=64,
                       prefill_chunk=4, tick_steps=1,
                       registry=metrics_lib.Registry())
    want = _generate_tokens(model, params, _prompt(4, seed=2), 6, 64)
    h = eng.submit(_prompt(4, seed=1), 40)
    while not h.tokens:
        eng.step()
    assert eng.cancel(h) is True
    assert h.status == "cancelled" and h.done
    assert eng.cancel(h) is False               # already finished
    h2 = eng.submit(_prompt(4, seed=2), 6)      # slot reuse stays exact
    eng.drain()
    assert h2.tokens == want
