"""End-to-end convergence oracles (SURVEY.md §4: the XOR task as the
integration-level correctness signal, reference example.py:222-226)."""
import jax

from distributed_tensorflow_tpu import data, models, ops, optim, parallel, train


def test_xor_learns_low_level():
    """Low-level tier (reference example.py shape): should reach >0.95 val
    bitwise accuracy quickly on a reduced-size run."""
    model = ops.serial(ops.Dense(128, "relu"), ops.Dropout(0.3),
                       ops.Dense(128, "relu"), ops.Dropout(0.3),
                       ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    (xt, yt), (xv, yv) = data.xor_data(8000, val_size=500, seed=0)
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt)
    for batch in data.Dataset([xt, yt], 50, seed=0).epochs(60):
        state, _ = step(state, batch)
    evaluate = train.make_eval_step(model, "mse",
                                    metric_fns={"acc": "bitwise_accuracy"})
    acc = float(evaluate(state, (xv, yv))["acc"])
    assert acc > 0.95, f"XOR val accuracy {acc} below threshold"


def test_mnist_mlp_learns_data_parallel():
    """Synthetic-MNIST MLP over the 8-device mesh (BASELINE config #1/#2)."""
    (xt, yt), (xv, yv) = data.mnist(flatten=True)
    xt, yt = xt[:8192], yt[:8192]
    model = models.Sequential([ops.Dense(128, "relu"), ops.Dense(10)])
    model.compile(loss="sparse_categorical_crossentropy", optimizer="adam",
                  metrics=["accuracy"], mesh=parallel.data_parallel_mesh())
    model.fit(xt, yt, epochs=2, batch_size=256, verbose=0)
    out = model.evaluate(xv[:2048], yv[:2048], batch_size=256, verbose=0)
    assert out["accuracy"] > 0.9, out
