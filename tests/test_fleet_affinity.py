"""Prefix-affinity fleet routing tests (docs/SERVING.md §Fleet
affinity policy).

The contracts pinned here:
  * ``EngineStats.prefix_hit_rate`` never divides by zero — a cold
    engine (and a bare stats dataclass) reports 0.0,
  * ``Trace.fingerprint`` folds the per-prefix popularity histogram in
    and stays a determinism pin (same seed -> equal, different seed ->
    different),
  * the pool publishes EVERY depth of a registered chain in its
    bounded fingerprint, and the router's ``expected_pages_reused``
    scores a real ``EngineStats`` and a sim ``_SimStats`` identically
    for identical coverage (sim/real scorer parity),
  * placement prefers the fingerprint holder over the id-tie winner
    (real engines AND SimEngines behind the same Router), and an
    identical replayed trace reproduces ``router.placements`` exactly,
  * with no fingerprints anywhere (contiguous engines) placement
    degrades EXACTLY to the original least-loaded (inflight, id)
    order — the blind fleet replays unchanged,
  * migrate-based scale-in spares the sole holder of a hot chain
    (the old newest-first tie-break victim survives when its chains
    are replicated nowhere else),
  * migration/failover re-placement runs through the SAME scorer: a
    removed replica's in-flight request lands on the survivor holding
    its prefix, not the lowest id,
  * race_harness: concurrent prefix-sharing submits never tear the
    fingerprint — it stays bounded, page-aligned, and scoreable.
"""
import threading
import time

import numpy as np
import pytest

import jax

from distributed_tensorflow_tpu import fleet, serve
from distributed_tensorflow_tpu.fleet import router as router_lib
from distributed_tensorflow_tpu.fleet import sim as sim_lib
from distributed_tensorflow_tpu.fleet import workload
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.serve import pages as pages_lib
from distributed_tensorflow_tpu.serve.scheduler import EngineStats


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _generate_tokens(model, params, prompt, new, max_len, **kw):
    import jax.numpy as jnp
    out = model.generate(params, jnp.asarray(prompt[None]),
                         max_new_tokens=new, max_len=max_len, **kw)
    return np.asarray(out)[0, prompt.size:].tolist()


def _engine(model, params, reg=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("page_size", 8)
    return serve.Engine(model, params, tick_steps=2,
                        registry=reg or metrics_lib.Registry(), **kw)


def _cost_model(**kw):
    kw.setdefault("n_params", 1.0e8)
    kw.setdefault("prefill_chunk", 16)
    kw.setdefault("num_slots", 4)
    kw.setdefault("tick_steps", 4)
    return sim_lib.CostModel.analytic(hw=sim_lib.HardwarePoint(), **kw)


# ---------------------------------------------------------------------------
# EngineStats.prefix_hit_rate: the zero-division guard


def test_prefix_hit_rate_zero_lookups_is_zero():
    """A stats snapshot with zero prefix lookups reports hit rate 0.0
    instead of dividing by zero — both the bare dataclass and a cold
    paged engine that has never admitted a request."""
    cold = EngineStats(queued=0, prefilling=0, active=0, num_slots=2,
                       inflight_per_tenant={},
                       tokens_inflight_per_tenant={})
    assert cold.prefix_lookups_total == 0
    assert cold.prefix_hit_rate == 0.0
    model, params = _model_params()
    eng = _engine(model, params)
    st = eng.stats()
    assert st.prefix_lookups_total == 0
    assert st.prefix_hit_rate == 0.0


# ---------------------------------------------------------------------------
# Trace fingerprint: popularity histogram + determinism pin


def test_trace_fingerprint_and_prefix_popularity():
    a = workload.synthesize(200, seed=5, prefix_populations=8,
                            prefix_fraction=0.6)
    b = workload.synthesize(200, seed=5, prefix_populations=8,
                            prefix_fraction=0.6)
    c = workload.synthesize(200, seed=6, prefix_populations=8,
                            prefix_fraction=0.6)
    # same seed -> identical fingerprint AND histogram; other seed
    # differs (the determinism pin the ablation arms rely on)
    assert a.fingerprint() == b.fingerprint()
    assert a.prefix_popularity() == b.prefix_popularity()
    assert a.fingerprint() != c.fingerprint()
    # the histogram covers exactly the prefix-carrying requests,
    # sorted by id, every id positive
    pop = a.prefix_popularity()
    assert sum(n for _, n in pop) == int((a.prefix_id > 0).sum())
    ids = [i for i, _ in pop]
    assert ids == sorted(ids) and all(i > 0 for i in ids)
    assert all(n > 0 for _, n in pop)


# ---------------------------------------------------------------------------
# fingerprint publication + sim/real scorer parity


def test_pool_publishes_every_chain_depth():
    """One 16-token prompt through a page_size=8 pool lands BOTH chain
    depths (8 and 16 cached tokens) in the published fingerprint, keyed
    exactly by ``prompt_chain_keys`` — a follower sharing only the
    first page still scores."""
    model, params = _model_params()
    eng = _engine(model, params)
    p = _prompt(16, seed=3)
    eng.submit(p, 4)
    eng.drain()
    st = eng.stats()
    assert st.page_size == 8
    keys = pages_lib.prompt_chain_keys(p, 8)
    assert [tok for _, tok in keys] == [8, 16]
    for key, tokens in keys:
        assert st.prefix_fingerprint.get(key) == tokens


def test_expected_pages_reused_sim_real_parity():
    """The scorer returns the SAME page count for the same coverage on
    both sides of the sim/real boundary: a real engine holding a
    16-token chain (page_size 8) and a SimEngine holding a 32-token
    prefix (chunk 16) both score 2 pages for a follower."""
    model, params = _model_params()
    eng = _engine(model, params)
    sys_prompt = _prompt(16, seed=3)
    eng.submit(sys_prompt, 4)
    eng.drain()
    follower = np.concatenate([sys_prompt, _prompt(3, seed=4)])
    real_score = router_lib.expected_pages_reused(follower, eng.stats())

    sim = sim_lib.SimEngine(_cost_model(), num_slots=4,
                            prefill_chunk=16)
    sim.submit((32, 7, 32, 0.0), 4)
    while sim.step():
        pass
    st = sim.stats()
    assert st.page_size == 16
    assert st.prefix_fingerprint == {7: 32}
    sim_score = router_lib.expected_pages_reused((40, 7, 32, 0.0), st)
    assert real_score == sim_score == 2
    # no-prefix requests score zero on both sides: prefix-free sim
    # tuple, and a real prompt sharing no leading chain
    assert router_lib.expected_pages_reused((40, 0, 0, 0.0), st) == 0
    assert router_lib.expected_pages_reused(
        np.concatenate([_prompt(8, seed=9), sys_prompt[:8]]),
        eng.stats()) == 0


# ---------------------------------------------------------------------------
# router placement: affinity beats the id tie, replays exactly


def test_affinity_placement_prefers_holder_and_replays():
    """The seeded replica (id 1 — NOT the id-tie winner) attracts every
    follower sharing its prefix while loads are equal, and an identical
    replayed trace reproduces ``placements`` exactly."""
    model, params = _model_params()

    def run():
        reg = metrics_lib.Registry()
        router = fleet.Router(
            [_engine(model, params, reg=reg) for _ in range(2)],
            registry=reg)
        sys_prompt = _prompt(16, seed=3)
        # park junk on replica 0 so the seed lands on replica 1
        junk = router.submit(_prompt(8, seed=99), 4)
        seed = router.submit(sys_prompt, 4)
        assert router.placements == [(junk.rid, 0), (seed.rid, 1)]
        router.drain()
        hs = []
        for i in range(4):
            h = router.submit(
                np.concatenate([sys_prompt, _prompt(3, seed=10 + i)]), 4)
            hs.append(h)
            router.drain()
        # all idle at each submit: the blind tie-break picks id 0, the
        # fingerprint holder (id 1) wins only through affinity
        assert [rid for _, rid in router.placements[2:]] == [1] * 4
        assert all(h.status == "ok" for h in hs)
        assert reg.get("dttpu_router_affinity_hits_total").value == 4
        assert reg.get("dttpu_router_affinity_score").value == 2
        return router.placements

    assert run() == run()               # deterministic replay


def test_hot_prefix_convergence_sim_fleet():
    """SimEngines behind the SAME Router converge hot-prefix traffic
    onto the holding replica under equal load; a blind router
    (affinity_weight=0) sends the identical trace to the id-tie
    winner instead."""
    def run(weight):
        reg = metrics_lib.Registry()
        router = fleet.Router(
            [sim_lib.SimEngine(_cost_model(), num_slots=4)
             for _ in range(2)],
            registry=reg, affinity_weight=weight)
        junk = router.submit((64, 0, 0, 0.0), 4)
        seed = router.submit((32, 7, 32, 0.0), 4)
        assert router.placements == [(junk.rid, 0), (seed.rid, 1)]
        router.drain()
        for _ in range(6):
            router.submit((40, 7, 32, 0.0), 4)
            router.drain()
        return [rid for _, rid in router.placements[2:]]

    assert run(1.0) == [1] * 6          # converges on the holder
    assert run(0.0) == [0] * 6          # blind: id tie every time


def test_blind_fallback_contiguous_engines_keep_original_order():
    """Contiguous engines publish NO fingerprint, so the affinity
    router's placement order degrades exactly to the original
    least-loaded (inflight, id) order — bit-identical to an
    affinity_weight=0 fleet on the same trace."""
    model, params = _model_params()

    def run(weight):
        reg = metrics_lib.Registry()
        router = fleet.Router(
            [_engine(model, params, reg=reg, paged=False, page_size=None)
             for _ in range(2)],
            registry=reg, affinity_weight=weight)
        for i in range(6):
            router.submit(_prompt(4 + i % 3, seed=i), 5)
            if i % 2:
                router.step()
        router.drain()
        assert reg.get("dttpu_router_affinity_hits_total").value == 0
        return router.placements

    affinity, blind = run(1.0), run(0.0)
    assert affinity[:2] == [(0, 0), (1, 1)]     # idle tie -> id order
    assert affinity == blind


# ---------------------------------------------------------------------------
# scale-in: spare the sole holder


def test_scale_in_spares_sole_holder_of_hot_chain():
    """Replicas 0 and 1 share a hot chain; replica 2 is the ONLY
    holder of another.  The old rule (least inflight, ties newest
    first) would retire replica 2; the affinity-aware rule retires a
    replicated holder (replica 1) and keeps the sole copy alive."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    engines = [_engine(model, params, reg=reg) for _ in range(3)]
    shared, unique = _prompt(16, seed=3), _prompt(16, seed=4)
    for eng, p in zip(engines, [shared, shared, unique]):
        eng.submit(p, 4)
        eng.drain()
    router = fleet.Router(engines, registry=reg)
    scaler = fleet.Autoscaler(
        router, lambda: _engine(model, params, reg=reg),
        fleet.SLO(ttft_s=2.0, itl_s=0.1), registry=reg)
    victim = scaler._scale_in_victim(router.stats())
    assert victim == 1                  # replicated holder, newest-first
    assert 2 in router.stats()          # sole holder survives
    assert scaler.scale_ins == 1


# ---------------------------------------------------------------------------
# migration/failover re-placement goes through the scorer


def test_migration_replacement_lands_on_fingerprint_holder():
    """An in-flight request whose replica is removed re-places through
    the affinity scorer: it lands on the survivor holding its prefix
    chains (replica 2), not the id-tie survivor (replica 1), and
    finishes token-exact."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    router = fleet.Router(
        [_engine(model, params, reg=reg) for _ in range(3)],
        registry=reg)
    sys_prompt = _prompt(16, seed=3)
    # seed the prefix on replica 2 (park junk on 0 and 1 first)
    router.submit(_prompt(8, seed=98), 4)
    router.submit(_prompt(8, seed=99), 4)
    seed = router.submit(sys_prompt, 4)
    assert router.placements[-1] == (seed.rid, 2)
    router.drain()
    # keep the follower OFF the holder: mark 2 draining for one submit
    assert router.drain_replica(2, timeout_s=5.0)
    follower = np.concatenate([sys_prompt, _prompt(3, seed=7)])
    fh = router.submit(follower, 6)
    assert fh.replica_id == 0
    router.resume_replica(2)
    # removing replica 0 exports the request; re-placement scores the
    # survivors and picks the fingerprint holder over the lower id
    router.remove_replica(0)
    assert router.placements[-1] == (fh.rid, 2)
    assert fh.migrations == 1
    router.drain()
    assert fh.status == "ok"
    assert fh.tokens == _generate_tokens(model, params, follower, 6, 32)


# ---------------------------------------------------------------------------
# race harness: fingerprint coherence under concurrent submits


@pytest.mark.race_harness(
    seed=23, scope=("distributed_tensorflow_tpu/serve/",))
def test_fingerprint_coherent_under_concurrent_submits(request):
    """3 submitter threads sharing one system prompt against a pumping
    engine under seeded preemption: every request finishes exact, and
    the published fingerprint stays coherent — bounded by
    ``fingerprint_k``, every entry a positive multiple of the page
    size, and the hot chain still scores through the router's
    ``expected_pages_reused``."""
    model, params = _model_params()
    eng = _engine(model, params, num_slots=3)
    sys_prompt = _prompt(8, seed=91)
    reqs = {i: np.concatenate([sys_prompt,
                               _prompt(2 + (i % 3), seed=100 + i)])
            for i in range(6)}
    wants = {i: _generate_tokens(model, params, reqs[i], 5, 32)
             for i in reqs}
    handles = {}
    hlock = threading.Lock()
    barrier = threading.Barrier(3)

    def submitter(ids):
        barrier.wait(timeout=60)
        for i in ids:
            h = eng.submit(reqs[i], 5)
            with hlock:
                handles[i] = h

    ts = [threading.Thread(target=submitter, args=([k, k + 3],),
                           name=f"dttpu-affinity-{k}", daemon=True)
          for k in range(3)]
    for t in ts:
        t.start()
    deadline = time.time() + 300
    while True:
        with hlock:
            got = dict(handles)
        if len(got) == 6 and all(h.done for h in got.values()):
            break
        eng.step()
        # mid-flight snapshots must already be coherent
        st = eng.stats()
        assert len(st.prefix_fingerprint) <= pages_lib.FINGERPRINT_K
        assert all(tok > 0 and tok % 8 == 0
                   for tok in st.prefix_fingerprint.values())
        assert time.time() < deadline, "engine did not drain"
    for t in ts:
        t.join(timeout=60)

    harness = request.node.race_harness
    assert harness.preemptions > 0, "harness never fired"
    for i, h in handles.items():
        assert h.status == "ok" and h.tokens == wants[i], i
    pool = eng.scheduler.pages
    st = eng.stats()
    assert len(st.prefix_fingerprint) <= pool.fingerprint_k
    assert all(tok > 0 and tok % pool.page_size == 0
               for tok in st.prefix_fingerprint.values())
    # the shared chain survived the churn and still scores
    assert router_lib.expected_pages_reused(
        np.concatenate([sys_prompt, _prompt(2, seed=200)]), st) >= 1
