"""GPT decoder family tests: causality, training, KV-cache decode, TP."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu import optim, train
from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig, gpt_tiny
from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.sharding import shard_pytree


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _ids(b=2, s=16, vocab=512, seed=1):
    return jax.random.randint(jax.random.PRNGKey(seed), (b, s), 0, vocab)


def test_forward_shapes_and_dtype():
    model, params = _model_params()
    ids = _ids()
    h = model.apply(params, ids)
    assert h.shape == (2, 16, 128)
    logits = model.logits(params, h)
    assert logits.shape == (2, 16, 512) and logits.dtype == jnp.float32


def test_causality():
    """Changing a future token must not change logits at earlier positions."""
    model, params = _model_params()
    ids = _ids()
    base = model.logits(params, model.apply(params, ids))
    ids2 = ids.at[:, 10].set((ids[:, 10] + 7) % 512)
    pert = model.logits(params, model.apply(params, ids2))
    np.testing.assert_allclose(np.asarray(base[:, :10]),
                               np.asarray(pert[:, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(base[:, 10:]), np.asarray(pert[:, 10:]))


def test_lm_training_loss_decreases():
    model, params = _model_params()
    opt = optim.adam(1e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    batch = {"input_ids": _ids(b=4, s=32)}
    losses = []
    for _ in range(30):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_kv_cache_decode_matches_full_forward():
    """decode_step through the cache == slicing the full-sequence logits."""
    model, params = _model_params()
    ids = _ids(b=2, s=12)
    full = model.logits(params, model.apply(params, ids))
    cache = model.init_cache(2, max_len=12)
    for t in range(12):
        step_logits, cache = model.decode_step(params, cache, ids[:, t])
        np.testing.assert_allclose(np.asarray(step_logits),
                                   np.asarray(full[:, t]), atol=2e-4)


@pytest.mark.parametrize("dtype,atol", [(jnp.float32, 2e-4),
                                        (jnp.bfloat16, 5e-2)],
                         ids=["float32", "bfloat16"])
def test_decode_block_matches_sequential_prefill(dtype, atol):
    """decode_block (one batched prompt forward) must produce exactly the
    cache contents and last-position logits of plen sequential
    decode_step calls — the prefill fast path behind generate/beam.
    bf16 is the bench decode configs' dtype (looser tolerance; ~3
    decimal digits)."""
    model, params = _model_params(dtype=dtype)
    ids = _ids(b=2, s=6)
    seq_cache = model.init_cache(2, max_len=12)
    for t in range(6):
        seq_logits, seq_cache = model.decode_step(params, seq_cache,
                                                  ids[:, t])
    blk_cache = model.init_cache(2, max_len=12)
    blk_logits, blk_cache = model.decode_block(params, blk_cache, ids)
    assert int(blk_cache["pos"]) == int(seq_cache["pos"]) == 6
    np.testing.assert_allclose(np.asarray(blk_logits, np.float32),
                               np.asarray(seq_logits, np.float32),
                               atol=atol, rtol=atol)
    for key in ("k", "v"):
        np.testing.assert_allclose(np.asarray(blk_cache[key], np.float32),
                                   np.asarray(seq_cache[key], np.float32),
                                   atol=atol)


def test_decode_block_matches_sequential_prefill_rope_gqa():
    """Same block-vs-sequential oracle on the Llama-shaped recipe (RoPE
    positions + grouped-query cache)."""
    model, params = _model_params(position_embedding="rope", num_heads=4,
                                  hidden_size=128, num_kv_heads=2)
    ids = _ids(b=2, s=5)
    seq_cache = model.init_cache(2, max_len=10)
    for t in range(5):
        seq_logits, seq_cache = model.decode_step(params, seq_cache,
                                                  ids[:, t])
    blk_cache = model.init_cache(2, max_len=10)
    blk_logits, blk_cache = model.decode_block(params, blk_cache, ids)
    np.testing.assert_allclose(np.asarray(blk_logits),
                               np.asarray(seq_logits), atol=2e-4)
    for key in ("k", "v"):
        np.testing.assert_allclose(np.asarray(blk_cache[key]),
                                   np.asarray(seq_cache[key]), atol=2e-4)


def test_decode_block_ragged_matches_sequential_prefill():
    """Block prefill with LEFT-padded ragged prompts: per-row positions
    and pad masking must reproduce the sequential decode_step prefill
    (cache equality on valid columns + last logits)."""
    from distributed_tensorflow_tpu.ops import decoding as dec
    model, params = _model_params()
    b, plen = 2, 5
    ids = np.asarray(_ids(b=b, s=plen))
    valid = np.asarray([[1, 1, 1, 1, 1], [0, 0, 1, 1, 1]], np.int32)
    ids = np.where(valid, ids, 7).astype(np.int32)
    pad_len, kv_valid = dec.ragged_prompt_masks(
        jnp.asarray(valid), (b, plen), 10)
    seq_cache = model.init_cache(b, max_len=10)
    for t in range(plen):
        seq_logits, seq_cache = model.decode_step(
            params, seq_cache, jnp.asarray(ids[:, t]),
            kv_valid=kv_valid,
            positions=jnp.maximum(t - pad_len, 0))
    blk_cache = model.init_cache(b, max_len=10)
    blk_logits, blk_cache = model.decode_block(
        params, blk_cache, jnp.asarray(ids),
        kv_valid=kv_valid[:, :plen],
        positions=jnp.maximum(jnp.arange(plen)[None, :]
                              - pad_len[:, None], 0))
    np.testing.assert_allclose(np.asarray(blk_logits),
                               np.asarray(seq_logits), atol=2e-4)
    # pad columns hold garbage in both paths (masked from attention);
    # compare the valid region only
    mask = np.asarray(kv_valid[:, :plen])[None, :, :, None, None]
    for key in ("k", "v"):
        got = np.asarray(blk_cache[key])[:, :, :plen] * mask
        want = np.asarray(seq_cache[key])[:, :, :plen] * mask
        np.testing.assert_allclose(got, want, atol=2e-4)


def test_generate_greedy_is_deterministic_and_consistent():
    model, params = _model_params()
    prompt = _ids(b=2, s=4)
    out1 = model.generate(params, prompt, max_new_tokens=6)
    out2 = model.generate(params, prompt, max_new_tokens=6)
    assert out1.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :4]), np.asarray(prompt))
    # greedy continuation must equal argmax of the teacher-forced forward
    full = model.logits(params, model.apply(params, out1[:, :-1]))
    np.testing.assert_array_equal(np.asarray(out1[:, 4:]),
                                  np.asarray(jnp.argmax(full, -1)[:, 3:]))


def test_generate_sampling_runs():
    model, params = _model_params()
    prompt = _ids(b=1, s=2)
    out = model.generate(params, prompt, max_new_tokens=5, temperature=1.0,
                         rng=jax.random.PRNGKey(3))
    assert out.shape == (1, 7)
    assert int(out.max()) < 512 and int(out.min()) >= 0


def test_tensor_parallel_training_step():
    mesh = make_mesh({"data": 2, "tensor": 2}, jax.devices()[:4])
    model, params = _model_params()
    params = shard_pytree(params, mesh, model.partition_rules())
    # vocab dim of the (tied) word embedding really sharded over tensor
    assert "tensor" in str(params["embeddings"]["word"].sharding.spec)
    opt = optim.adamw(1e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    ids = jax.device_put(_ids(b=4, s=16), NamedSharding(mesh, P("data")))
    state, m = step(state, {"input_ids": ids})
    assert np.isfinite(float(m["loss"]))
    spec = state.params["decoder"]["ffn"]["w_in"]["kernel"].sharding.spec
    assert "tensor" in str(spec)


def test_int8_kv_cache_decode():
    """kv_cache_dtype='int8': cache stores int8 + per-(token, head)
    scales, decode logits stay within quantization tolerance of the fp
    cache, greedy decode agrees at these seeds, and beam search's cache
    fold/reorder carries the scale arrays."""
    fp = gpt_tiny(dropout_rate=0.0)
    q8 = gpt_tiny(dropout_rate=0.0, kv_cache_dtype="int8")
    params = fp.init(jax.random.PRNGKey(0))
    ids = _ids(b=2, s=8)
    cq = q8.init_cache(2, 16)
    assert cq["k"].dtype == jnp.int8 and cq["k_scale"].dtype == jnp.float32
    assert cq["k_scale"].shape == cq["k"].shape[:-1] + (1,)

    cf = fp.init_cache(2, 16)
    lf, cf = fp.decode_block(params, cf, ids)
    lq, cq = q8.decode_block(params, cq, ids)
    # prefill logits attend the block's own fp K/V — identical by design
    np.testing.assert_allclose(np.asarray(lq), np.asarray(lf), atol=1e-5)
    sf, cf = fp.decode_step(params, cf, ids[:, -1])
    sq, cq = q8.decode_step(params, cq, ids[:, -1])
    # cache reads dequantize: per-(token, head) int8 keeps logits close
    np.testing.assert_allclose(np.asarray(sq), np.asarray(sf), atol=5e-2)

    of = fp.generate(params, ids, max_new_tokens=6, max_len=16)
    oq = q8.generate(params, ids, max_new_tokens=6, max_len=16)
    np.testing.assert_array_equal(np.asarray(oq), np.asarray(of))
    ob = q8.beam_search(params, ids, max_new_tokens=4, beam_size=3,
                        max_len=16)
    assert ob.shape == (2, 12) and int(np.asarray(ob).max()) < 512


def test_chunked_prefill_matches_one_block():
    """prefill_cache(chunk=W) — the bounded-memory long-prompt path —
    must reproduce the one-block prefill exactly: same last logits, same
    cache contents, including a ragged final window (7 = 3+3+1)."""
    model, params = _model_params()
    ids = _ids(b=2, s=7)
    blk_cache = model.init_cache(2, max_len=12)
    blk_logits, blk_cache = model.prefill_cache(params, blk_cache, ids)
    for chunk in (3, 2):
        ch_cache = model.init_cache(2, max_len=12)
        ch_logits, ch_cache = model.prefill_cache(params, ch_cache, ids,
                                                  chunk=chunk)
        assert int(ch_cache["pos"]) == 7
        np.testing.assert_allclose(np.asarray(ch_logits),
                                   np.asarray(blk_logits), atol=2e-4)
        for key in ("k", "v"):
            np.testing.assert_allclose(np.asarray(ch_cache[key]),
                                       np.asarray(blk_cache[key]),
                                       atol=2e-4)


def test_generate_with_chunked_prefill_matches_default():
    """generate(prefill_chunk=W) and beam_search(prefill_chunk=W) emit
    the same outputs as the default one-block prefill; composing with
    prompt_valid raises in both."""
    model, params = _model_params()
    prompt = _ids(b=2, s=6)
    want = model.generate(params, prompt, max_new_tokens=5, max_len=12)
    got = model.generate(params, prompt, max_new_tokens=5, max_len=12,
                         prefill_chunk=2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    beam_want = model.beam_search(params, prompt, max_new_tokens=4,
                                  beam_size=3, max_len=12)
    beam_got = model.beam_search(params, prompt, max_new_tokens=4,
                                 beam_size=3, max_len=12,
                                 prefill_chunk=2)
    np.testing.assert_array_equal(np.asarray(beam_got),
                                  np.asarray(beam_want))
    valid = jnp.ones((2, 6), jnp.int32)
    with pytest.raises(ValueError, match="prefill_chunk"):
        model.generate(params, prompt, max_new_tokens=2, max_len=12,
                       prefill_chunk=2, prompt_valid=valid)
    with pytest.raises(ValueError, match="prefill_chunk"):
        model.beam_search(params, prompt, max_new_tokens=2, max_len=12,
                          prefill_chunk=2, prompt_valid=valid)


def test_tp_sharded_decode_matches_single_device():
    """Multi-chip SERVING: with params sharded over a tensor mesh, the
    KV-cache decode path (prefill block + per-token steps) must produce
    the single-device logits — XLA inserts the TP collectives inside the
    compiled decode steps, and the numbers agree to reduction-order
    tolerance.  A full sharded generate() then runs and emits in-vocab
    tokens."""
    mesh = make_mesh({"tensor": 2}, jax.devices()[:2])
    model, params = _model_params()
    ids = _ids(b=2, s=8)

    plain_cache = model.init_cache(2, max_len=12)
    plain_logits, plain_cache = model.decode_block(params, plain_cache,
                                                   ids[:, :6])
    step_logits, plain_cache = model.decode_step(params, plain_cache,
                                                 ids[:, 6])

    sp = shard_pytree(params, mesh, model.partition_rules())
    assert "tensor" in str(sp["embeddings"]["word"].sharding.spec)
    tp_cache = model.init_cache(2, max_len=12)
    tp_logits, tp_cache = jax.jit(model.decode_block)(sp, tp_cache,
                                                      ids[:, :6])
    tp_step_logits, tp_cache = jax.jit(model.decode_step)(sp, tp_cache,
                                                          ids[:, 6])
    np.testing.assert_allclose(np.asarray(tp_logits),
                               np.asarray(plain_logits), atol=2e-3)
    np.testing.assert_allclose(np.asarray(tp_step_logits),
                               np.asarray(step_logits), atol=2e-3)

    out = jax.jit(lambda p, i: model.generate(
        p, i, max_new_tokens=4, max_len=12))(sp, ids)
    assert out.shape == (2, 12)
    assert int(np.asarray(out).max()) < 512
    np.testing.assert_array_equal(np.asarray(out)[:, :8], np.asarray(ids))


def test_ring_attention_path_matches_dense():
    """seq_axis path (ring attention over the mesh) == dense causal path."""
    mesh = make_mesh({"seq": 8})
    dense_model, params = _model_params()
    ring_model = GPT(GPTConfig(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=2,
        intermediate_size=512, max_position=128, dropout_rate=0.0,
        seq_axis="seq"), mesh=mesh)
    ids = _ids(b=2, s=32)
    ref = dense_model.apply(params, ids)
    out = ring_model.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_generate_refuses_overlong_and_small_max_len():
    import pytest
    model, params = _model_params()
    prompt = _ids(b=1, s=4)
    with pytest.raises(ValueError, match="exceeds max_len"):
        model.generate(params, prompt, max_new_tokens=100, max_len=16)
    with pytest.raises(ValueError, match="max_position"):
        model.generate(params, prompt, max_new_tokens=300)


def test_moe_gpt_trains_and_decodes():
    """Sparse-FFN GPT: loss decreases (incl. router aux), KV-cache decode
    matches full forward when capacity drops nothing."""
    model, params = _model_params(moe_experts=4, moe_capacity_factor=4.0)

    # decode parity first: the jitted train step donates params.
    ids = _ids(b=2, s=10)
    full = model.logits(params, model.apply(params, ids))
    cache = model.init_cache(2, max_len=10)
    for t in range(10):
        lg, cache = model.decode_step(params, cache, ids[:, t])
        np.testing.assert_allclose(np.asarray(lg), np.asarray(full[:, t]),
                                   atol=2e-4)

    opt = optim.adam(1e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    batch = {"input_ids": _ids(b=4, s=32)}
    first = None
    for i in range(25):
        state, m = step(state, batch)
        if i == 0:
            first = float(m["loss"])
    assert float(m["loss"]) < first
    assert np.isfinite(float(m["moe_aux"])) and float(m["moe_aux"]) > 0


def test_moe_gpt_expert_parallel_step():
    mesh = make_mesh({"data": 2, "expert": 4})
    model, params = _model_params(moe_experts=4, moe_capacity_factor=2.0)
    params = shard_pytree(params, mesh, model.partition_rules())
    spec = params["decoder"]["moe"]["experts"]["w_in"].sharding.spec
    assert "expert" in str(spec)
    opt = optim.adamw(1e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    ids = jax.device_put(_ids(b=4, s=16), NamedSharding(mesh, P("data")))
    state, m = step(state, {"input_ids": ids})
    assert np.isfinite(float(m["loss"]))


def test_remat_matches_no_remat():
    """jax.checkpoint through the scanned stack: identical outputs, HBM
    traded for recompute (the long-context lever)."""
    base, params = _model_params()
    remat_model, _ = _model_params(remat=True)
    ids = _ids()
    ref = base.apply(params, ids)
    out = remat_model.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
    # gradients flow through the checkpointed scan
    def loss(p):
        return (remat_model.apply(p, ids) ** 2).mean()
    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["decoder"]["ffn"]["w_in"]["kernel"]).sum()) > 0


def test_bf16_forward_and_training():
    model = gpt_tiny(dropout_rate=0.0, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    ids = _ids()
    h = model.apply(params, ids)
    assert h.dtype == jnp.bfloat16              # activations on the MXU path
    assert model.logits(params, h).dtype == jnp.float32  # f32 logits
    opt = optim.adam(1e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    first = None
    for i in range(10):
        state, m = step(state, {"input_ids": ids})
        if i == 0:
            first = float(m["loss"])
    assert np.isfinite(float(m["loss"])) and float(m["loss"]) < first
    # master params stay f32
    assert state.params["decoder"]["ffn"]["w_in"]["kernel"].dtype == jnp.float32


def test_rope_relative_invariance():
    """RoPE logits depend only on relative distance: rotating at positions
    p and p+delta gives the same q.k as 0 and delta."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu.ops.attention import rotary_embedding

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (1, 1, 2, 8))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 2, 8))

    def dot_at(pq, pk):
        qr = rotary_embedding(q, jnp.asarray([pq]))
        kr = rotary_embedding(k, jnp.asarray([pk]))
        return float(jnp.sum(qr * kr))

    np.testing.assert_allclose(dot_at(7, 3), dot_at(14, 10), rtol=1e-5)
    np.testing.assert_allclose(dot_at(5, 5), dot_at(0, 0), rtol=1e-5)
    assert abs(dot_at(7, 3) - dot_at(7, 5)) > 1e-6  # distance matters


def test_rope_gpt_trains_and_decode_matches_forward():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    m = gpt_tiny(position_embedding="rope", dropout_rate=0.0)
    params = m.init(jax.random.PRNGKey(0))
    assert "position" not in params["embeddings"]  # no table with RoPE

    # KV-cache decode must match the full-sequence forward exactly
    ids = jnp.asarray([[5, 9, 2, 7, 1, 3]], jnp.int32)
    full = m.logits(params, m.apply(params, ids))
    cache = m.init_cache(1, max_len=8)
    outs = []
    for t in range(ids.shape[1]):
        logits, cache = m.decode_step(params, cache, ids[:, t])
        outs.append(logits)
    stepped = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(stepped),
                               rtol=2e-4, atol=2e-4)

    # and it trains
    opt = optim.adam(3e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(m.lm_loss_fn(), opt)
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, 512, (16, 12)).astype(np.int32))}
    l0 = None
    for i in range(25):
        state, metrics = step(state, batch)
        l0 = l0 or float(metrics["loss"])
    assert float(metrics["loss"]) < l0


def test_rope_generates_past_max_position():
    """RoPE has no position table — generation may exceed max_position."""
    import jax
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    m = gpt_tiny(position_embedding="rope", max_position=16,
                 dropout_rate=0.0)
    params = m.init(jax.random.PRNGKey(0))
    out = m.generate(params, jnp.ones((1, 4), jnp.int32),
                     max_new_tokens=20, max_len=24)  # 24 > 16
    assert out.shape == (1, 24)

    # the learned table still refuses
    m2 = gpt_tiny(max_position=16, dropout_rate=0.0)
    params2 = m2.init(jax.random.PRNGKey(0))
    import pytest
    with pytest.raises(ValueError, match="max_position"):
        m2.generate(params2, jnp.ones((1, 4), jnp.int32),
                    max_new_tokens=20, max_len=24)


def test_rope_odd_head_dim_rejected():
    import jax.numpy as jnp
    import pytest
    from distributed_tensorflow_tpu.ops.attention import rope_tables
    with pytest.raises(ValueError, match="even head_dim"):
        rope_tables(jnp.arange(4), head_dim=7)


def test_rope_with_ring_attention_matches_dense():
    """RoPE composes with the sharded ring-attention (SP) path."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P  # noqa: F401
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
    from distributed_tensorflow_tpu.parallel import make_mesh

    kw = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=2,
              intermediate_size=512, max_position=128, dropout_rate=0.0,
              position_embedding="rope")
    dense = GPT(GPTConfig(**kw))
    params = dense.init(jax.random.PRNGKey(0))
    mesh = make_mesh({"seq": 8})
    ring = GPT(GPTConfig(**kw, seq_axis="seq"), mesh=mesh)
    ids = _ids(b=2, s=32)
    np.testing.assert_allclose(np.asarray(ring.apply(params, ids)),
                               np.asarray(dense.apply(params, ids)),
                               atol=2e-4)


def test_gpt_beam_search_beam1_matches_greedy_generate():
    import jax
    import jax.numpy as jnp
    import numpy as np
    model, params = _model_params()
    prompt = _ids(b=3, s=5)
    greedy = model.generate(params, prompt, max_new_tokens=6)
    beam1 = model.beam_search(params, prompt, max_new_tokens=6, beam_size=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(beam1))


def test_gpt_beam_search_improves_logprob_and_eos_freezes():
    import jax
    import jax.numpy as jnp
    import numpy as np
    model, params = _model_params()
    prompt = _ids(b=2, s=4)
    T = 6
    greedy = model.generate(params, prompt, max_new_tokens=T)
    beam4 = model.beam_search(params, prompt, max_new_tokens=T, beam_size=4)
    assert beam4.shape == greedy.shape
    # prompt preserved
    np.testing.assert_array_equal(np.asarray(beam4[:, :4]),
                                  np.asarray(prompt))

    # determinism (beam output dominating greedy is NOT an invariant of
    # beam search — per-step top-k can prune the greedy path)
    again = model.beam_search(params, prompt, max_new_tokens=T, beam_size=4)
    np.testing.assert_array_equal(np.asarray(beam4), np.asarray(again))

    # EOS freeze: after the first eos in the generated part, all eos
    out = np.asarray(jax.jit(
        lambda p, s: model.beam_search(p, s, max_new_tokens=8, beam_size=3,
                                       eos_id=11))(params, prompt))
    for row in out:
        gen = row[4:]
        hits = np.flatnonzero(gen == 11)
        if hits.size:
            assert (gen[hits[0]:] == 11).all(), gen


def test_gqa_trains_cache_shrinks_and_decode_matches_forward():
    """Grouped-query attention: kv cache is kv_heads-sized, decode parity
    holds, and the model trains; MQA (kv=1) included."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig, gpt_tiny

    for kv in (1, 2):
        m = gpt_tiny(num_heads=4, hidden_size=128, num_kv_heads=kv,
                     dropout_rate=0.0, position_embedding="rope")
        params = m.init(jax.random.PRNGKey(0))
        k_shape = params["decoder"]["attention"]["key"]["kernel"].shape
        assert k_shape == (2, 128, kv, 32)          # [L, d, kv, hd]
        cache = m.init_cache(1, max_len=8)
        assert cache["k"].shape[3] == kv

        ids = jnp.asarray([[5, 9, 2, 7, 1, 3]], jnp.int32)
        full = m.logits(params, m.apply(params, ids))
        outs = []
        for t in range(ids.shape[1]):
            logits, cache = m.decode_step(params, cache, ids[:, t])
            outs.append(logits)
        np.testing.assert_allclose(np.asarray(full),
                                   np.asarray(jnp.stack(outs, 1)),
                                   rtol=2e-4, atol=2e-4)

    m = gpt_tiny(num_heads=4, hidden_size=128, num_kv_heads=2,
                 dropout_rate=0.0)
    params = m.init(jax.random.PRNGKey(0))
    opt = optim.adam(3e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(m.lm_loss_fn(), opt)
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, 512, (16, 12)).astype(np.int32))}
    l0 = None
    for _ in range(20):
        state, metrics = step(state, batch)
        l0 = l0 or float(metrics["loss"])
    assert float(metrics["loss"]) < l0

    import pytest
    with pytest.raises(ValueError, match="divisor"):
        gpt_tiny(num_heads=4, num_kv_heads=3).init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="divisor"):
        gpt_tiny(num_heads=4, num_kv_heads=0).init(jax.random.PRNGKey(0))


def test_gqa_tensor_parallel_rules_and_step():
    """MQA + TP: query shards over heads, kv replicates; a sharded train
    step runs."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny
    from distributed_tensorflow_tpu.parallel import make_mesh
    from distributed_tensorflow_tpu.parallel.sharding import shard_pytree

    mesh = make_mesh({"data": 4, "tensor": 2})
    m = gpt_tiny(num_heads=4, hidden_size=128, num_kv_heads=1,
                 dropout_rate=0.0)
    params = m.init(jax.random.PRNGKey(0))
    params = shard_pytree(params, mesh, m.partition_rules())  # must not raise
    q_spec = params["decoder"]["attention"]["query"]["kernel"].sharding.spec
    k_spec = params["decoder"]["attention"]["key"]["kernel"].sharding.spec
    assert "tensor" in str(q_spec)
    assert "tensor" not in str(k_spec)

    opt = optim.adam()
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(m.lm_loss_fn(), opt)
    ids = jax.device_put(
        jnp.ones((8, 12), jnp.int32), NamedSharding(mesh, P("data")))
    state, metrics = step(state, {"input_ids": ids})
    assert np.isfinite(float(metrics["loss"]))


def test_gqa_shard_kv_override():
    import jax
    from jax.sharding import PartitionSpec as P
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny
    from distributed_tensorflow_tpu.parallel import make_mesh
    from distributed_tensorflow_tpu.parallel.sharding import shard_pytree

    mesh = make_mesh({"data": 4, "tensor": 2})
    m = gpt_tiny(num_heads=4, hidden_size=128, num_kv_heads=2,
                 dropout_rate=0.0)
    params = m.init(jax.random.PRNGKey(0))
    sharded = shard_pytree(params, mesh,
                           m.partition_rules(shard_kv=True))
    spec = sharded["decoder"]["attention"]["key"]["kernel"].sharding.spec
    assert "tensor" in str(spec)  # 2 kv heads shard over tensor=2


def test_generate_eos_early_stop():
    """eos_id: finished rows pad; the while_loop path matches the scan
    path token-for-token up to each row's EOS."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    prompt = jnp.ones((2, 3), jnp.int32)
    # greedy: scan path and eos path must agree before any EOS is hit
    base = g.generate(params, prompt, max_new_tokens=6)
    # use an id that greedy decoding never emits in `base`
    emitted = set(np.asarray(base[:, 3:]).ravel().tolist())
    eos_free = next(i for i in range(g.config.vocab_size)
                    if i not in emitted)
    out = g.generate(params, prompt, max_new_tokens=6, eos_id=eos_free)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))

    # now force an immediate EOS: the very first sampled token
    first = int(base[0, 3])
    out2 = g.generate(params, prompt, max_new_tokens=6, eos_id=first,
                      pad_id=0)
    row = np.asarray(out2[0, 3:])
    assert row[0] == first            # the EOS token itself is kept
    assert (row[1:] == 0).all()       # everything after is pad


def test_generate_eos_jits():
    import jax
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    fn = jax.jit(lambda p, ids: g.generate(p, ids, max_new_tokens=4,
                                           eos_id=5, pad_id=0))
    out = fn(params, jnp.ones((2, 3), jnp.int32))
    assert out.shape == (2, 7)


def test_ragged_prompt_left_padding_matches_solo_rows():
    """A left-padded batch of unequal prompts generates, row for row, what
    each prompt generates alone (greedy) — pad slots masked from
    attention, positions shifted per row.  Checked for BOTH position
    embeddings (RoPE is shift-invariant; learned needs the explicit
    per-row positions)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    for pe in ("learned", "rope"):
        g = gpt_tiny(dropout_rate=0.0, position_embedding=pe)
        params = g.init(jax.random.PRNGKey(0))
        short = jnp.asarray([[7, 8]], jnp.int32)            # len 2
        long = jnp.asarray([[3, 4, 5, 6]], jnp.int32)       # len 4
        solo_short = g.generate(params, short, max_new_tokens=4)
        solo_long = g.generate(params, long, max_new_tokens=4)

        # batch them left-padded to len 4 (pad id value is arbitrary:
        # masked out of attention)
        batch = jnp.asarray([[0, 0, 7, 8], [3, 4, 5, 6]], jnp.int32)
        valid = jnp.asarray([[0, 0, 1, 1], [1, 1, 1, 1]], jnp.int32)
        out = g.generate(params, batch, max_new_tokens=4,
                         prompt_valid=valid)
        np.testing.assert_array_equal(np.asarray(out[0, 4:]),
                                      np.asarray(solo_short[0, 2:]),
                                      err_msg=f"pe={pe} short row")
        np.testing.assert_array_equal(np.asarray(out[1, 4:]),
                                      np.asarray(solo_long[0, 4:]),
                                      err_msg=f"pe={pe} long row")


def test_ragged_prompt_validation():
    import jax
    import jax.numpy as jnp
    import pytest
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    prompt = jnp.ones((2, 3), jnp.int32)
    with pytest.raises(ValueError, match="shape"):
        g.generate(params, prompt, max_new_tokens=2,
                   prompt_valid=jnp.ones((2, 5), jnp.int32))
    with pytest.raises(ValueError, match="LEFT-padded"):
        g.generate(params, prompt, max_new_tokens=2,
                   prompt_valid=jnp.asarray([[1, 1, 0], [1, 1, 1]],
                                            jnp.int32))


def test_ragged_prompt_jits():
    import jax
    import jax.numpy as jnp
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny

    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    fn = jax.jit(lambda p, ids, v: g.generate(p, ids, max_new_tokens=3,
                                              prompt_valid=v))
    out = fn(params, jnp.ones((2, 4), jnp.int32),
             jnp.ones((2, 4), jnp.int32))
    assert out.shape == (2, 7)


def test_beam_search_eos_early_exit_pads_with_eos():
    """The early-exit beam loop produces the same output as the full run:
    once every beam finished, trailing positions read EOS (what frozen
    beams would have kept emitting)."""
    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    prompt = jnp.ones((2, 3), jnp.int32)
    # no-eos baseline: pure scan path
    base = g.beam_search(params, prompt, max_new_tokens=5, beam_size=2)
    assert base.shape == (2, 8)
    # pick the first generated token of row 0's best beam as EOS: that row
    # finishes immediately; the loop still runs until row 1 finishes or
    # steps run out, and the output stays [b, total] with EOS-padded tails
    eos = int(base[0, 3])
    out = g.beam_search(params, prompt, max_new_tokens=5, beam_size=2,
                        eos_id=eos)
    assert out.shape == (2, 8)
    row = np.asarray(out[0])
    first_eos = int(np.argmax(row[3:] == eos)) + 3
    assert (row[first_eos:] == eos).all()
    # and the whole thing jits
    fn = jax.jit(lambda p, ids: g.beam_search(p, ids, max_new_tokens=4,
                                              beam_size=2, eos_id=eos))
    assert fn(params, prompt).shape == (2, 7)


def test_beam_search_ragged_prompts_match_solo():
    """Left-padded beam search equals per-row solo beam search (both
    position embeddings)."""
    for pe in ("learned", "rope"):
        g = gpt_tiny(dropout_rate=0.0, position_embedding=pe)
        params = g.init(jax.random.PRNGKey(0))
        short = jnp.asarray([[7, 8]], jnp.int32)
        long = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
        solo_short = g.beam_search(params, short, max_new_tokens=4,
                                   beam_size=2)
        solo_long = g.beam_search(params, long, max_new_tokens=4,
                                  beam_size=2)
        batch = jnp.asarray([[0, 0, 7, 8], [3, 4, 5, 6]], jnp.int32)
        valid = jnp.asarray([[0, 0, 1, 1], [1, 1, 1, 1]], jnp.int32)
        out = g.beam_search(params, batch, max_new_tokens=4, beam_size=2,
                            prompt_valid=valid)
        np.testing.assert_array_equal(np.asarray(out[0, 4:]),
                                      np.asarray(solo_short[0, 2:]),
                                      err_msg=f"pe={pe} short")
        np.testing.assert_array_equal(np.asarray(out[1, 4:]),
                                      np.asarray(solo_long[0, 4:]),
                                      err_msg=f"pe={pe} long")


def test_beam_search_ragged_plus_eos_compose():
    """prompt_valid + eos_id together: folded kv_valid/positions inside
    the early-exit while_loop still match the solo runs."""
    g = gpt_tiny(dropout_rate=0.0)
    params = g.init(jax.random.PRNGKey(0))
    short = jnp.asarray([[7, 8]], jnp.int32)
    long = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    # choose an EOS id that greedy beams don't emit so the outputs align
    base_s = g.beam_search(params, short, max_new_tokens=4, beam_size=2)
    base_l = g.beam_search(params, long, max_new_tokens=4, beam_size=2)
    emitted = set(np.asarray(base_s[:, 2:]).ravel().tolist()) | \
        set(np.asarray(base_l[:, 4:]).ravel().tolist())
    eos = next(i for i in range(g.config.vocab_size) if i not in emitted)
    solo_short = g.beam_search(params, short, max_new_tokens=4,
                               beam_size=2, eos_id=eos)
    solo_long = g.beam_search(params, long, max_new_tokens=4,
                              beam_size=2, eos_id=eos)
    batch = jnp.asarray([[0, 0, 7, 8], [3, 4, 5, 6]], jnp.int32)
    valid = jnp.asarray([[0, 0, 1, 1], [1, 1, 1, 1]], jnp.int32)
    out = g.beam_search(params, batch, max_new_tokens=4, beam_size=2,
                        eos_id=eos, prompt_valid=valid)
    np.testing.assert_array_equal(np.asarray(out[0, 4:]),
                                  np.asarray(solo_short[0, 2:]))
    np.testing.assert_array_equal(np.asarray(out[1, 4:]),
                                  np.asarray(solo_long[0, 4:]))


class TestChunkedLoss:
    """loss_seq_chunk: the chunked head-projection loss must be exactly
    interchangeable with the full-logits path — same loss, same metrics,
    same gradients (it is the same math, reduced chunk-at-a-time under
    jax.checkpoint)."""

    def _losses(self, chunk, mask=None, b=2, s=17):
        model, params = _model_params(loss_seq_chunk=chunk)
        batch = {"input_ids": _ids(b=b, s=s)}
        if mask is not None:
            batch["loss_mask"] = mask
        loss_fn = model.lm_loss_fn()

        def scalar(p):
            loss, (metrics, _) = loss_fn(p, {}, batch, None, False)
            return loss, metrics

        return scalar(params), jax.grad(lambda p: scalar(p)[0])(params)

    def test_loss_metrics_and_grads_match_unchunked(self):
        # chunk 8 divides the 32 tokens; chunk 7 exercises padding;
        # chunk 1000 > token count exercises the clamp (no pad-up)
        (l0, m0), g0 = self._losses(0)
        for chunk in (8, 7, 1000):
            (l1, m1), g1 = self._losses(chunk)
            np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
            np.testing.assert_allclose(float(m0["token_accuracy"]),
                                       float(m1["token_accuracy"]),
                                       rtol=1e-6)
            f0 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g0)])
            f1 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g1)])
            np.testing.assert_allclose(f0, f1, atol=2e-5)

    def test_masked_parity(self):
        mask = np.zeros((2, 16), np.float32)
        mask[:, 3:9] = 1.0
        (l0, m0), g0 = self._losses(0, mask=mask)
        (l1, m1), g1 = self._losses(5, mask=mask)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        np.testing.assert_allclose(float(m0["token_accuracy"]),
                                   float(m1["token_accuracy"]), rtol=1e-6)
        np.testing.assert_allclose(float(m0["loss_weight"]),
                                   float(m1["loss_weight"]), rtol=0)
        f0 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g0)])
        f1 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g1)])
        np.testing.assert_allclose(f0, f1, atol=2e-5)

    def test_trains(self):
        model, params = _model_params(loss_seq_chunk=8)
        opt = optim.adamw(1e-3)
        step = train.make_custom_train_step(model.lm_loss_fn(), opt)
        state = train.TrainState.create(params, opt.init(params))
        ids = np.asarray(_ids(b=4, s=33))
        first = None
        for _ in range(10):
            state, m = step(state, {"input_ids": ids})
            first = float(m["loss"]) if first is None else first
        assert float(m["loss"]) < first


def test_remat_policies_match():
    """remat policy choices change memory/recompute, never values: dots /
    dots_no_batch / full all match the no-remat forward and gradients."""
    ids = _ids(b=2, s=16)
    base_model, params = _model_params()

    def loss_of(model):
        fn = model.lm_loss_fn()
        return lambda p: fn(p, {}, {"input_ids": ids}, None, False)[0]

    l0 = float(loss_of(base_model)(params))
    g0 = jax.grad(loss_of(base_model))(params)
    for policy in ("full", "dots", "dots_no_batch"):
        m = gpt_tiny(dropout_rate=0.0, remat=True, remat_policy=policy)
        l1 = float(loss_of(m)(params))
        g1 = jax.grad(loss_of(m))(params)
        np.testing.assert_allclose(l0, l1, rtol=1e-6)
        f0 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g0)])
        f1 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g1)])
        np.testing.assert_allclose(f0, f1, atol=2e-5)


def test_remat_policy_invalid_raises():
    m = gpt_tiny(remat=True, remat_policy="bogus")
    import pytest
    with pytest.raises(ValueError, match="remat_policy"):
        m.apply(m.init(jax.random.PRNGKey(0)), _ids())
