"""Tokenizer tests (data/text.py)."""
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.text import BPETokenizer, ByteTokenizer


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    s = "héllo wörld 123"
    ids = t.encode(s, bos=True, eos=True)
    assert int(ids[0]) == t.bos_id and int(ids[-1]) == t.eos_id
    assert t.decode(ids) == s
    assert t.vocab_size == 259


def test_bpe_learns_frequent_pairs_and_roundtrips():
    corpus = ["the cat sat on the mat"] * 50 + ["the dog"] * 20
    t = BPETokenizer.train(corpus, vocab_size=300)
    assert t.vocab_size > 259  # learned some merges
    for s in ["the cat", "a dog on the mat", "unseen zebra!"]:
        assert t.decode(t.encode(s)) == s
    # "the" (with following space) should compress well
    ids_the = t.encode("the the the the")
    ids_xyz = t.encode("xq zj vk pw")     # no trained pairs
    assert len(ids_the) < len(ids_xyz)


def test_bpe_deterministic_and_serializable(tmp_path):
    corpus = ["abab abab", "ababab"] * 10
    t1 = BPETokenizer.train(corpus, vocab_size=270)
    t2 = BPETokenizer.train(corpus, vocab_size=270)
    assert t1.merges == t2.merges
    s = "ababab and more"
    np.testing.assert_array_equal(t1.encode(s), t2.encode(s))
    p = str(tmp_path / "bpe.json")
    t1.save(p)
    t3 = BPETokenizer.load(p)
    assert t3.merges == t1.merges
    np.testing.assert_array_equal(t3.encode(s), t1.encode(s))
    assert t3.decode(t3.encode(s, bos=True, eos=True)) == s


def test_bpe_vocab_size_validation():
    with pytest.raises(ValueError, match="vocab_size"):
        BPETokenizer.train(["x"], vocab_size=10)


def test_bpe_feeds_lm_pipeline():
    from distributed_tensorflow_tpu.data.datasets import lm_sequences
    t = BPETokenizer.train(["hello world " * 40], vocab_size=280)
    ids = t.encode("hello world " * 40)
    rows = lm_sequences(ids, seq_len=8)
    assert rows.dtype == np.int32 and rows.shape[1] == 9


def test_encode_backend_validation():
    import pytest
    from distributed_tensorflow_tpu.data.text import BPETokenizer
    tok = BPETokenizer.train(["ab ab ab ab"], vocab_size=262)
    with pytest.raises(ValueError, match="unknown backend"):
        tok.encode("ab", backend="Auto")
    # backend="native" either runs the C++ encoder or raises loudly
    from distributed_tensorflow_tpu.utils import native
    if native.native_available():
        import numpy as np
        np.testing.assert_array_equal(
            tok.encode("ab ab", backend="native"),
            tok.encode("ab ab", backend="python"))
    else:
        with pytest.raises(RuntimeError, match="native"):
            tok.encode("ab", backend="native")
