"""Failure-detection / preemption tests (SURVEY.md §5 failure row)."""
import os
import signal
import time

import jax

from distributed_tensorflow_tpu import data, ops, optim, train


def make_bits():
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt)
    (xt, yt), _ = data.xor_data(500, val_size=10, seed=0)
    ds = data.Dataset([xt, yt], 50, seed=0)
    return state, step, ds


def test_preemption_saves_and_stops(tmp_path):
    """SIGTERM mid-loop: finish the step, write a checkpoint at the exact
    preemption step, stop cleanly, auto-restore on the next session."""
    state, step, ds = make_bits()
    d = str(tmp_path)

    class KillAtStep(train.Hook):
        def after_step(self, session, metrics):
            if session.step == 3:
                os.kill(os.getpid(), signal.SIGTERM)

    prev_handler = signal.getsignal(signal.SIGTERM)
    hooks = [KillAtStep(), train.PreemptionHook(),
             train.StopAtStepHook(last_step=1000)]
    with train.TrainSession(state, step, checkpoint_dir=d,
                            hooks=hooks) as sess:
        it = iter(ds.epochs(100))
        n = 0
        while not sess.should_stop() and n < 100:
            sess.run_step(next(it))
            n += 1
    # KillAtStep fires inside step 3's hook phase; PreemptionHook (later in
    # the list) sees the flag in the same step's after_step.
    assert sess.step == 3
    assert train.checkpoint.latest_step(d) == 3
    # the exact pre-session handler was restored on exit
    assert signal.getsignal(signal.SIGTERM) == prev_handler

    state2, step2, _ = make_bits()
    with train.TrainSession(state2, step2, checkpoint_dir=d,
                            hooks=[train.StopAtStepHook(last_step=5)]) as s2:
        assert s2.step == 3  # auto-restored from the preemption save


def test_preemption_without_save(tmp_path):
    state, step, ds = make_bits()

    class KillNow(train.Hook):
        def after_step(self, session, metrics):
            if session.step == 1:
                os.kill(os.getpid(), signal.SIGTERM)

    hooks = [KillNow(), train.PreemptionHook(save=False)]
    with train.TrainSession(state, step, hooks=hooks) as sess:
        it = iter(ds.epochs(100))
        while not sess.should_stop():
            sess.run_step(next(it))
    assert sess.step == 1
    assert train.checkpoint.latest_checkpoint(str(tmp_path)) is None


def test_watchdog_fires_on_stall_and_not_on_progress():
    state, step, ds = make_bits()
    # Warm the jit cache so in-session steps are fast relative to the
    # watchdog budget (first-compile would legitimately count as a stall).
    state, _ = step(state, next(iter(ds)))
    fired = []

    wd = train.WatchdogHook(timeout_secs=0.3, poll_secs=0.05,
                            on_stall=lambda s, e: fired.append(e))
    hooks = [wd, train.StopAtStepHook(last_step=4)]
    with train.TrainSession(state, step, hooks=hooks) as sess:
        it = iter(ds.epochs(100))
        while not sess.should_stop():
            sess.run_step(next(it))
        assert fired == []          # steady progress: no stall
        time.sleep(0.6)             # simulated hang (no steps completing)
        assert wd.stall_count == 1 and len(fired) == 1
        time.sleep(0.4)             # same stall: fires only once
        assert wd.stall_count == 1
    # watchdog thread stopped at session exit
    assert not wd._thread.is_alive()


def test_cleanup_hooks_run_on_exception():
    """close() must run even when the loop raises: the SIGTERM handler is
    restored and the watchdog thread stops (regression: end() was skipped on
    exception, leaving a dead session's handler installed forever)."""
    import pytest
    state, step, ds = make_bits()
    prev_handler = signal.getsignal(signal.SIGTERM)
    pre = train.PreemptionHook()
    wd = train.WatchdogHook(timeout_secs=60.0)

    with pytest.raises(RuntimeError, match="boom"):
        with train.TrainSession(state, step, hooks=[pre, wd]) as sess:
            sess.run_step(next(iter(ds)))
            raise RuntimeError("boom")
    assert signal.getsignal(signal.SIGTERM) == prev_handler
    wd._thread.join(timeout=5)
    assert not wd._thread.is_alive()


def test_preemption_save_not_duplicated_by_checkpoint_hook(tmp_path, monkeypatch):
    """SIGTERM at step N with a CheckpointHook installed: exactly one save
    at N (PreemptionHook's), not a second identical one at exit."""
    state, step, ds = make_bits()
    d = str(tmp_path)
    saves = []
    orig = train.checkpoint.save

    def counting_save(directory, step_, state_, **kw):
        saves.append(step_)
        return orig(directory, step_, state_, **kw)

    monkeypatch.setattr(train.checkpoint, "save", counting_save)

    class KillNow(train.Hook):
        def after_step(self, session, metrics):
            if session.step == 2:
                os.kill(os.getpid(), signal.SIGTERM)

    hooks = [KillNow(), train.PreemptionHook(),
             train.CheckpointHook(every_secs=9999.0)]
    with train.TrainSession(state, step, checkpoint_dir=d,
                            hooks=hooks) as sess:
        it = iter(ds.epochs(100))
        while not sess.should_stop():
            sess.run_step(next(it))
    assert saves == [2]
