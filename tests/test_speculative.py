"""Speculative decoding: greedy output must EQUAL the target's own greedy
decode — the draft only amortizes target dispatches, never changes the
answer (models/speculative.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.models.speculative import \
    generate_speculative


def _prompt(s=4, vocab=512, seed=1):
    return jax.random.randint(jax.random.PRNGKey(seed), (1, s), 0, vocab)


def test_self_draft_matches_generate_with_high_acceptance():
    """Draft == target: the output equals generate()'s greedy
    continuation and acceptance is high.  (Not asserted == 1.0: the
    draft proposes through decode_step and the verifier scores through
    decode_window — different XLA reductions — so a random-init model's
    near-uniform logits flip argmax near-ties in the acceptance test.
    The output equality below holds at these fixed seeds on the CPU
    backend; a tie at an EMITTED position could in principle flip a
    token between the window and step paths — see the module
    docstring's numerical caveat.)"""
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    prompt = _prompt()
    want = model.generate(params, prompt, max_new_tokens=12)
    got, acc = generate_speculative(model, params, model, params,
                                    prompt, max_new_tokens=12, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert float(acc) >= 0.5


def test_decode_window_matches_sequential_steps():
    """The verification primitive: decode_window over tokens 4..9 of a
    cache prefilled to position 4 must reproduce the per-step
    decode_step logits and cache columns exactly."""
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 10), 0, 512)
    seq_cache = model.init_cache(1, max_len=16)
    seq_logits = []
    for t in range(10):
        lg, seq_cache = model.decode_step(params, seq_cache, ids[:, t])
        seq_logits.append(np.asarray(lg))
    win_cache = model.init_cache(1, max_len=16)
    for t in range(4):
        _, win_cache = model.decode_step(params, win_cache, ids[:, t])
    win_logits, win_cache = model.decode_window(params, win_cache,
                                               ids[:, 4:10])
    assert int(win_cache["pos"]) == 10
    np.testing.assert_allclose(np.asarray(win_logits)[0],
                               np.stack([l[0] for l in seq_logits[4:]]),
                               atol=2e-4)
    for key in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(win_cache[key])[:, :, :10],
            np.asarray(seq_cache[key])[:, :, :10], atol=2e-4)


def test_weak_draft_still_matches_target_greedy():
    """A DIFFERENT (differently-initialized) draft: proposals are mostly
    rejected, but the emitted sequence is still bit-identical to the
    target's greedy decode — the exactness guarantee."""
    target = gpt_tiny(dropout_rate=0.0, max_position=64)
    t_params = target.init(jax.random.PRNGKey(0))
    draft = gpt_tiny(dropout_rate=0.0, max_position=64, num_layers=1)
    d_params = draft.init(jax.random.PRNGKey(7))
    prompt = _prompt()
    want = target.generate(t_params, prompt, max_new_tokens=10)
    got, acc = generate_speculative(target, t_params, draft, d_params,
                                    prompt, max_new_tokens=10, gamma=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert 0.0 <= float(acc) <= 1.0


def test_gamma_one_and_long_run():
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    prompt = _prompt(s=2)
    want = model.generate(params, prompt, max_new_tokens=20)
    got, _ = generate_speculative(model, params, model, params,
                                  prompt, max_new_tokens=20, gamma=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_speculative_accept_preserves_target_distribution():
    """The rejection-sampling kernel's emitted-token marginal must equal
    the TARGET distribution p exactly (Leviathan Thm 1) — checked by
    Monte Carlo at gamma=1, vocab 8: first-emitted-token frequencies vs
    p[0], and bonus-token frequencies vs p[1] on all-accept trials."""
    from distributed_tensorflow_tpu.models.speculative import \
        speculative_accept
    vocab, trials = 8, 200_000
    p0 = np.asarray([.30, .20, .15, .10, .10, .08, .05, .02], np.float32)
    q0 = np.asarray([.10, .30, .05, .20, .05, .10, .05, .15], np.float32)
    p1 = np.asarray([.05, .05, .40, .10, .10, .10, .10, .10], np.float32)
    p = jnp.stack([jnp.asarray(p0), jnp.asarray(p1)])
    q = jnp.asarray(q0)[None, :]

    def trial(key):
        k1, k2 = jax.random.split(key)
        d = jax.random.choice(k1, vocab, p=jnp.asarray(q0))
        n, emit = speculative_accept(k2, p, q,
                                     d[None].astype(jnp.int32))
        return emit[0], emit[1], n

    first, bonus, n = jax.jit(jax.vmap(trial))(
        jax.random.split(jax.random.PRNGKey(0), trials))
    freq = np.bincount(np.asarray(first), minlength=vocab) / trials
    np.testing.assert_allclose(freq, p0, atol=5e-3)
    # bonus tokens (only defined when the draft was accepted) ~ p[1]
    acc = np.asarray(n) == 1
    freq_b = (np.bincount(np.asarray(bonus)[acc], minlength=vocab)
              / max(acc.sum(), 1))
    np.testing.assert_allclose(freq_b, p1, atol=8e-3)


def test_sampled_spec_runs_and_is_plausible():
    """temperature>0 end to end: right shapes, tokens in-vocab, prompt
    preserved, acceptance in [0,1], and a different rng gives a
    different continuation (it is actually sampling)."""
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    draft = gpt_tiny(dropout_rate=0.0, max_position=64, num_layers=1)
    d_params = draft.init(jax.random.PRNGKey(7))
    prompt = _prompt()
    out1, acc = generate_speculative(model, params, draft, d_params,
                                     prompt, max_new_tokens=16, gamma=3,
                                     temperature=1.0,
                                     rng=jax.random.PRNGKey(1))
    out2, _ = generate_speculative(model, params, draft, d_params,
                                   prompt, max_new_tokens=16, gamma=3,
                                   temperature=1.0,
                                   rng=jax.random.PRNGKey(2))
    assert out1.shape == (1, 20)
    np.testing.assert_array_equal(np.asarray(out1[:, :4]),
                                  np.asarray(prompt))
    assert 0.0 <= float(acc) <= 1.0
    assert np.asarray(out1).max() < 512 and np.asarray(out1).min() >= 0
    assert not np.array_equal(np.asarray(out1), np.asarray(out2))


def test_spec_composes_with_chunked_prefill_and_int8_kv():
    """Composition: speculative decoding with (a) chunked prompt
    prefill and (b) an int8 KV cache on BOTH models still reproduces
    the same-config generate() exactly at these seeds."""
    model = gpt_tiny(dropout_rate=0.0, max_position=64,
                     kv_cache_dtype="int8")
    params = model.init(jax.random.PRNGKey(0))
    prompt = _prompt(s=6)
    # baseline with the SAME chunked prefill so both sides build the
    # identical int8 cache (one-block vs chunked prefill differ by a
    # quantization rounding step under int8 — gpt.py prefill_cache doc)
    want = model.generate(params, prompt, max_new_tokens=10,
                          prefill_chunk=2)
    got, acc = generate_speculative(model, params, model, params,
                                    prompt, max_new_tokens=10, gamma=3,
                                    prefill_chunk=2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert 0.0 <= float(acc) <= 1.0


def test_sampled_spec_with_filters_stays_in_filtered_support():
    """top_k on the sampled path: both sides filter identically, so no
    emitted token may fall outside the target's per-step top_k set.
    Verified by re-scoring the emitted continuation against the target's
    teacher-forced logits: every emitted token must rank < k."""
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    draft = gpt_tiny(dropout_rate=0.0, max_position=64, num_layers=1)
    d_params = draft.init(jax.random.PRNGKey(7))
    prompt = _prompt()
    k = 5
    out, acc = generate_speculative(model, params, draft, d_params,
                                    prompt, max_new_tokens=12, gamma=3,
                                    temperature=1.0, top_k=k,
                                    rng=jax.random.PRNGKey(3))
    assert 0.0 <= float(acc) <= 1.0
    full = model.logits(params, model.apply(params, out[:, :-1]))
    toks = np.asarray(out)[0, 4:]
    lg = np.asarray(full)[0, 3:]                 # row t scores token t+1
    for t, tok in enumerate(toks):
        # margin absorbs the ~1e-4 decode-window-vs-teacher-forced
        # reduction difference so a k-th-rank near-tie can't flip the
        # re-scored rank across backends
        rank = int((lg[t] > lg[t, tok] + 1e-3).sum())
        assert rank < k, (t, tok, rank)


def test_spec_eos_early_stop_matches_generate():
    """eos_id: speculative stops at the first emitted EOS and pads the
    rest — identical output to generate(eos_id=...) at these seeds."""
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    prompt = _prompt(s=4)
    free = model.generate(params, prompt, max_new_tokens=12)
    # pick a token the unstopped greedy continuation actually emits
    eos = int(np.asarray(free)[0, 7])
    want = model.generate(params, prompt, max_new_tokens=12, eos_id=eos)
    got, _ = generate_speculative(model, params, model, params, prompt,
                                  max_new_tokens=12, gamma=3, eos_id=eos)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # the stop actually truncated: pad fills after the first eos
    row = np.asarray(got)[0]
    eos_idx = int(np.argmax(row[4:] == eos)) + 4
    assert (row[eos_idx + 1:] == eos).all()   # pad defaults to eos_id


def test_rejects_bad_args():
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="batch-1"):
        generate_speculative(model, params, model, params,
                             jnp.zeros((2, 4), jnp.int32), 8)
    with pytest.raises(ValueError, match="gamma"):
        generate_speculative(model, params, model, params,
                             _prompt(), 8, gamma=0)
    with pytest.raises(ValueError, match="position table"):
        # learned table 64 < plen + new + gamma - 1 = 4 + 60 + 4 - 1 = 67
        generate_speculative(model, params, model, params,
                             _prompt(), max_new_tokens=60, gamma=4)
