"""Speculative decoding: greedy output must EQUAL the target's own greedy
decode — the draft only amortizes target dispatches, never changes the
answer (models/speculative.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.models.speculative import \
    generate_speculative


def _prompt(s=4, vocab=512, seed=1):
    return jax.random.randint(jax.random.PRNGKey(seed), (1, s), 0, vocab)


def test_self_draft_matches_generate_with_high_acceptance():
    """Draft == target: the output equals generate()'s greedy
    continuation and acceptance is high.  (Not asserted == 1.0: the
    draft proposes through decode_step and the verifier scores through
    decode_window — different XLA reductions — so a random-init model's
    near-uniform logits flip argmax near-ties in the acceptance test.
    The output equality below holds at these fixed seeds on the CPU
    backend; a tie at an EMITTED position could in principle flip a
    token between the window and step paths — see the module
    docstring's numerical caveat.)"""
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    prompt = _prompt()
    want = model.generate(params, prompt, max_new_tokens=12)
    got, acc = generate_speculative(model, params, model, params,
                                    prompt, max_new_tokens=12, gamma=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert float(acc) >= 0.5


def test_decode_window_matches_sequential_steps():
    """The verification primitive: decode_window over tokens 4..9 of a
    cache prefilled to position 4 must reproduce the per-step
    decode_step logits and cache columns exactly."""
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 10), 0, 512)
    seq_cache = model.init_cache(1, max_len=16)
    seq_logits = []
    for t in range(10):
        lg, seq_cache = model.decode_step(params, seq_cache, ids[:, t])
        seq_logits.append(np.asarray(lg))
    win_cache = model.init_cache(1, max_len=16)
    for t in range(4):
        _, win_cache = model.decode_step(params, win_cache, ids[:, t])
    win_logits, win_cache = model.decode_window(params, win_cache,
                                               ids[:, 4:10])
    assert int(win_cache["pos"]) == 10
    np.testing.assert_allclose(np.asarray(win_logits)[0],
                               np.stack([l[0] for l in seq_logits[4:]]),
                               atol=2e-4)
    for key in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(win_cache[key])[:, :, :10],
            np.asarray(seq_cache[key])[:, :, :10], atol=2e-4)


def test_weak_draft_still_matches_target_greedy():
    """A DIFFERENT (differently-initialized) draft: proposals are mostly
    rejected, but the emitted sequence is still bit-identical to the
    target's greedy decode — the exactness guarantee."""
    target = gpt_tiny(dropout_rate=0.0, max_position=64)
    t_params = target.init(jax.random.PRNGKey(0))
    draft = gpt_tiny(dropout_rate=0.0, max_position=64, num_layers=1)
    d_params = draft.init(jax.random.PRNGKey(7))
    prompt = _prompt()
    want = target.generate(t_params, prompt, max_new_tokens=10)
    got, acc = generate_speculative(target, t_params, draft, d_params,
                                    prompt, max_new_tokens=10, gamma=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert 0.0 <= float(acc) <= 1.0


def test_gamma_one_and_long_run():
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    prompt = _prompt(s=2)
    want = model.generate(params, prompt, max_new_tokens=20)
    got, _ = generate_speculative(model, params, model, params,
                                  prompt, max_new_tokens=20, gamma=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rejects_bad_args():
    model = gpt_tiny(dropout_rate=0.0, max_position=64)
    params = model.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="batch-1"):
        generate_speculative(model, params, model, params,
                             jnp.zeros((2, 4), jnp.int32), 8)
    with pytest.raises(ValueError, match="gamma"):
        generate_speculative(model, params, model, params,
                             _prompt(), 8, gamma=0)
    with pytest.raises(ValueError, match="position table"):
        # learned table 64 < plen + new + gamma - 1 = 4 + 60 + 4 - 1 = 67
        generate_speculative(model, params, model, params,
                             _prompt(), max_new_tokens=60, gamma=4)
