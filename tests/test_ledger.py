"""Perf ledger + regression sentinel tests (obs/ledger.py,
obs/sentinel.py, scripts/perf_gate.py; docs/OBSERVABILITY.md
§Ledger/Sentinel).

Durability is the headline: concurrent appenders under the race harness
must never produce a torn line, a corrupt trailing line must be skipped
loudly on load (a crash mid-append), and schema-version skew must skip,
not crash.  The sentinel half pins both gate directions: green on an
unchanged row, red — naming the field and delta — on a ~2x slowdown and
on measured MFU collapsing away from the analytical ceiling.
"""
import json
import os
import sys
import threading

import pytest

from distributed_tensorflow_tpu.obs import ledger as ledger_lib
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.obs import sentinel as sentinel_lib
from distributed_tensorflow_tpu.obs.ledger import (LedgerSchemaError,
                                                   PerfLedger,
                                                   row_from_bench)
from distributed_tensorflow_tpu.obs.sentinel import (Sentinel, Tolerance,
                                                     classify_field)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _row(config="mnist_mlp", run_id="r1", value=1000.0, p50=2.0,
         mfu=None, analytical_mfu=None, ts=1000.0, backend="cpu"):
    row = {
        "schema_version": ledger_lib.SCHEMA_VERSION,
        "run_id": run_id, "git_sha": "abc123def456", "config": config,
        "timestamp": ts,
        "fingerprint": {"backend": backend, "device_count": 8,
                        "device_kind": "cpu", "process_count": 1},
        "measured": {"value": value, "step_time_p50_ms": p50},
        "analytical": {},
    }
    if mfu is not None:
        row["measured"]["mfu"] = mfu
    if analytical_mfu is not None:
        row["analytical"]["analytical_mfu"] = analytical_mfu
    return row


# ---------------------------------------------------------------------------
# append / load mechanics


class TestLedgerBasics:
    def test_append_rows_round_trip(self, tmp_path):
        led = PerfLedger(str(tmp_path / "perf.jsonl"))
        written = led.append(_row(run_id="a"))
        led.append(_row(run_id="b", ts=2000.0))
        assert written["schema_version"] == ledger_lib.SCHEMA_VERSION
        rows = led.rows()
        assert [r["run_id"] for r in rows] == ["a", "b"]
        assert led.skipped_lines == 0 and led.skipped_versions == 0

    def test_append_stamps_version_and_timestamp(self, tmp_path):
        led = PerfLedger(str(tmp_path / "perf.jsonl"))
        row = _row()
        del row["schema_version"]
        row.pop("timestamp")
        out = led.append(row)
        assert out["schema_version"] == ledger_lib.SCHEMA_VERSION
        assert out["timestamp"] > 0

    def test_schema_violations_raise(self, tmp_path):
        led = PerfLedger(str(tmp_path / "perf.jsonl"))
        bad = _row()
        del bad["run_id"]
        with pytest.raises(LedgerSchemaError, match="run_id"):
            led.append(bad)
        bad = _row()
        bad["measured"]["value"] = "fast"
        with pytest.raises(LedgerSchemaError, match="number"):
            led.append(bad)
        with pytest.raises(LedgerSchemaError):
            led.append(["not", "a", "dict"])
        assert led.rows() == []        # nothing invalid reached disk

    def test_missing_file_is_empty_not_an_error(self, tmp_path):
        assert PerfLedger(str(tmp_path / "nope.jsonl")).rows() == []

    def test_corrupt_trailing_line_skipped_loudly(self, tmp_path, caplog):
        path = str(tmp_path / "perf.jsonl")
        led = PerfLedger(path)
        led.append(_row(run_id="good"))
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"schema_version": 1, "run_id": "torn", "mea')
        with caplog.at_level("WARNING"):
            rows = led.rows()
        assert [r["run_id"] for r in rows] == ["good"]
        assert led.skipped_lines == 1
        assert any("corrupt" in r.message for r in caplog.records)

    def test_schema_version_skew_skipped_loudly(self, tmp_path, caplog):
        path = str(tmp_path / "perf.jsonl")
        led = PerfLedger(path)
        led.append(_row(run_id="current"))
        future = _row(run_id="future")
        future["schema_version"] = ledger_lib.SCHEMA_VERSION + 7
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(future) + "\n")
        with caplog.at_level("WARNING"):
            rows = led.rows()
        assert [r["run_id"] for r in rows] == ["current"]
        assert led.skipped_versions == 1
        assert any("schema_version" in r.message for r in caplog.records)


@pytest.mark.race_harness(seed=11, scope=("obs/",))
def test_concurrent_appenders_never_tear_a_line(tmp_path):
    """Eight threads hammering one ledger file under forced preemption:
    every byte run between newlines must parse as one whole row — the
    O_APPEND single-write contract."""
    path = str(tmp_path / "perf.jsonl")
    THREADS, EACH = 8, 12
    errors = []

    def appender(tid):
        led = PerfLedger(path)       # one handle per thread, like CI jobs
        try:
            for i in range(EACH):
                led.append(_row(run_id=f"t{tid}-{i}",
                                ts=float(tid * 1000 + i)))
        except Exception as e:       # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=appender, args=(t,),
                           name=f"dttpu-ledger-{t}", daemon=True)
          for t in range(THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errors
    with open(path, "r", encoding="utf-8") as f:
        lines = [ln for ln in f.read().splitlines() if ln]
    assert len(lines) == THREADS * EACH
    ids = set()
    for line in lines:
        row = json.loads(line)       # a torn line dies right here
        ids.add(row["run_id"])
    assert len(ids) == THREADS * EACH
    led = PerfLedger(path)
    assert len(led.rows()) == THREADS * EACH
    assert led.skipped_lines == 0


# ---------------------------------------------------------------------------
# queries


class TestLedgerQueries:
    def test_latest_filters_config_and_backend(self, tmp_path):
        led = PerfLedger(str(tmp_path / "perf.jsonl"))
        led.append(_row(config="a", run_id="old", ts=1.0))
        led.append(_row(config="a", run_id="new", ts=2.0))
        led.append(_row(config="a", run_id="tpu", ts=3.0, backend="tpu"))
        led.append(_row(config="b", run_id="other", ts=9.0))
        assert led.latest("a", backend="cpu")["run_id"] == "new"
        assert led.latest("a", backend="tpu")["run_id"] == "tpu"
        assert led.latest("a")["run_id"] == "tpu"    # newest overall
        assert led.latest("zzz") is None

    def test_series_is_time_ordered(self, tmp_path):
        led = PerfLedger(str(tmp_path / "perf.jsonl"))
        led.append(_row(run_id="r2", ts=2.0, value=20.0))
        led.append(_row(run_id="r1", ts=1.0, value=10.0))
        assert led.series("value", config="mnist_mlp") == [
            (1.0, 10.0), (2.0, 20.0)]
        assert led.series("not_measured") == []

    def test_delta_ratios(self):
        new = _row(value=500.0, p50=4.0)
        base = _row(value=1000.0, p50=2.0)
        d = PerfLedger.delta(new, base)
        assert d["value"]["ratio"] == pytest.approx(0.5)
        assert d["step_time_p50_ms"]["ratio"] == pytest.approx(2.0)

    def test_row_field_reaches_goodput_buckets(self):
        row = _row()
        row["goodput"] = {"goodput_pct": 61.5,
                          "buckets_s": {"step": 1.25, "other": 0.5}}
        assert ledger_lib.row_field(row, "goodput_pct") == 61.5
        assert ledger_lib.row_field(row, "goodput_step_s") == 1.25
        assert ledger_lib.row_field(row, "value") == 1000.0
        assert ledger_lib.row_field(row, "nope") is None


# ---------------------------------------------------------------------------
# bench row -> ledger row


class TestRowFromBench:
    def test_splits_measured_and_analytical(self):
        result = {
            "metric": "mnist_mlp_train_examples_per_sec_per_chip",
            "value": 178683.1, "unit": "examples/sec/chip",
            "eval_accuracy": 1.0, "data": "synthetic",
            "analytical_flops": 1.0e9, "analytical_mfu": 0.42,
            "schema_version": ledger_lib.SCHEMA_VERSION,
            "run_id": "deadbeef", "git_sha": "cafe", "config": "mnist_mlp",
            "timestamp": 123.0,
            "fingerprint": {"backend": "cpu", "device_count": 8},
            "goodput": {"goodput_pct": 50.0},
        }
        row = row_from_bench(result, knobs={"DTTPU_BENCH_SMOKE": "1"})
        ledger_lib.validate_row(row)
        assert row["run_id"] == "deadbeef"
        assert row["measured"]["value"] == 178683.1
        assert "analytical_flops" not in row["measured"]
        assert row["analytical"]["analytical_mfu"] == 0.42
        assert row["goodput"]["goodput_pct"] == 50.0
        assert row["knobs"] == {"DTTPU_BENCH_SMOKE": "1"}
        # identity/bookkeeping fields never masquerade as measurements
        assert "timestamp" not in row["measured"]
        assert "schema_version" not in row["measured"]


# ---------------------------------------------------------------------------
# sentinel


class TestSentinel:
    def test_classify_field_directions(self):
        assert classify_field("value") == "higher"
        assert classify_field("tokens_per_sec") == "higher"
        assert classify_field("mfu") == "higher"
        assert classify_field("step_time_p50_ms") == "lower"
        assert classify_field("ttft_ms") == "lower"
        assert classify_field("watchdog_stall_s") == "lower"
        # NOT misread as a duration by the "_s" suffix rule
        assert classify_field("single_step_value") == "higher"
        assert classify_field("data") is None
        assert classify_field("dispatch_mode") is None

    def test_green_on_identical_row(self):
        sent = Sentinel()
        verdicts = sent.check(_row(), baseline=_row())
        assert verdicts and all(v.ok for v in verdicts)

    def test_red_on_2x_slowdown_names_field_and_delta(self):
        sent = Sentinel()
        slow = _row(value=380.0, p50=5.2)       # ~2.6x worse both ways
        verdicts = sent.check(slow, baseline=_row(value=1000.0, p50=2.0))
        bad = {v.field: v for v in verdicts if not v.ok}
        assert "value" in bad and "step_time_p50_ms" in bad
        assert bad["value"].ratio == pytest.approx(0.38)
        report = Sentinel.report(verdicts, row=slow)
        assert "REGRESSED" in report
        assert "step_time_p50_ms" in report and "+160.0%" in report

    def test_jitter_within_tolerance_is_green(self):
        sent = Sentinel()
        wobbly = _row(value=700.0, p50=2.6)     # 30% wobble: CI jitter
        assert all(v.ok for v in
                   sent.check(wobbly, baseline=_row()))

    def test_roofline_drift_flags_without_history(self):
        sent = Sentinel(roofline_floor=0.25)
        good = sent.check(_row(mfu=0.30, analytical_mfu=0.9))
        assert [v.kind for v in good] == ["roofline"]
        assert good[0].ok
        bad = sent.check(_row(mfu=0.01, analytical_mfu=0.9))
        assert not bad[0].ok
        assert "roofline" in bad[0].kind
        assert "analytical ceiling" in bad[0].detail

    def test_per_field_tolerance_override(self):
        sent = Sentinel(tolerances={"value": Tolerance(min_ratio=0.95)})
        verdicts = sent.check(_row(value=900.0),
                              baseline=_row(value=1000.0))
        assert not [v for v in verdicts if v.field == "value"][0].ok

    def test_metrics_export(self):
        reg = metrics_lib.Registry()
        sent = Sentinel(registry=reg)
        sent.check(_row(value=100.0), baseline=_row(value=1000.0))
        assert reg.get("dttpu_sentinel_checks_total").value > 0
        assert reg.get("dttpu_sentinel_regressions_total").value >= 1
        g = reg.get("dttpu_sentinel_verdict",
                    labels={"config": "mnist_mlp"})
        assert g is not None and g.value == 0.0

    def test_parse_tolerance_overrides(self):
        tol = sentinel_lib.parse_tolerance_overrides(
            ["value=0.9:", "p50_ms=:1.5"])
        assert tol["value"].min_ratio == 0.9
        assert tol["value"].max_ratio == sentinel_lib.DEFAULT_MAX_RATIO
        assert tol["p50_ms"].max_ratio == 1.5
        with pytest.raises(ValueError, match="tolerance"):
            sentinel_lib.parse_tolerance_overrides(["nonsense"])


# ---------------------------------------------------------------------------
# perf_gate CLI (in-process: the module is import-light by design)


@pytest.fixture()
def perf_gate():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import perf_gate
        yield perf_gate
    finally:
        sys.path.pop(0)


class TestPerfGate:
    def _baseline(self, tmp_path) -> str:
        path = str(tmp_path / "baseline.jsonl")
        PerfLedger(path).append(_row(run_id="base"))
        return path

    def test_green_on_unchanged_row(self, tmp_path, perf_gate, capsys):
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(_row(run_id="fresh")))
        rc = perf_gate.main(["--row", str(fresh),
                             "--baseline", self._baseline(tmp_path)])
        assert rc == 0
        assert "verdict: pass" in capsys.readouterr().out

    def test_red_on_synthetic_2x_slowdown(self, tmp_path, perf_gate,
                                          capsys):
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(
            _row(run_id="slow", value=400.0, p50=5.0)))
        rc = perf_gate.main(["--row", str(fresh),
                             "--baseline", self._baseline(tmp_path)])
        assert rc == 1
        out = capsys.readouterr().out
        assert "FAIL" in out and "step_time_p50_ms" in out
        assert "+150.0%" in out            # the delta is named

    def test_accepts_raw_bench_line_and_appends(self, tmp_path,
                                                perf_gate):
        # a raw stamped bench line (no "measured" section yet) with log
        # noise above it, exactly what CI pipes in
        fresh = tmp_path / "bench.out"
        fresh.write_text(
            "bench: backend up: 8 device(s)\n" + json.dumps({
                "metric": "mnist_mlp_train_examples_per_sec_per_chip",
                "value": 1000.0, "unit": "examples/sec/chip",
                "step_time_p50_ms": 2.0, "config": "mnist_mlp",
                "run_id": "raw", "git_sha": "cafe",
                "schema_version": ledger_lib.SCHEMA_VERSION,
                "timestamp": 5.0,
                "fingerprint": {"backend": "cpu", "device_count": 8},
            }) + "\n")
        out_ledger = str(tmp_path / "out.jsonl")
        rc = perf_gate.main(["--row", str(fresh),
                             "--baseline", self._baseline(tmp_path),
                             "--append-to", out_ledger])
        assert rc == 0
        appended = PerfLedger(out_ledger).rows()
        assert len(appended) == 1 and appended[0]["run_id"] == "raw"

    def test_missing_baseline_row_modes(self, tmp_path, perf_gate,
                                        capsys):
        empty = str(tmp_path / "empty.jsonl")
        PerfLedger(empty).append(_row(config="unrelated"))
        fresh = tmp_path / "fresh.json"
        fresh.write_text(json.dumps(_row(run_id="fresh")))
        # default: falls back to roofline-only gating (here: no statics,
        # so zero checks) and passes
        rc = perf_gate.main(["--row", str(fresh), "--baseline", empty])
        assert rc == 0
        assert "roofline drift only" in capsys.readouterr().err
        # strict mode: usage error, not a silent pass
        rc = perf_gate.main(["--row", str(fresh), "--baseline", empty,
                             "--require-baseline"])
        assert rc == 2
