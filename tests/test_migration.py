"""Live request migration, lossless drain, and the fleet watchdog.

The contracts pinned here (docs/RESILIENCE.md §migration,
docs/SERVING.md §exactly-once):

  * ``Engine.export_request`` / ``import_request``: a request migrated
    mid-decode resumes on the destination with terminal tokens
    BIT-IDENTICAL to an unmigrated greedy run, and the concatenated
    callback stream across both engines has zero duplicated and zero
    lost tokens (exactly-once delivery at the snapshot's
    ``stream_offset``);
  * migration admits through the SAME three hot executables — importing
    never recompiles (retrace_guard budget=1);
  * ``Engine.drain(timeout_s=)`` is lossless: a timed-out drain exports
    the stragglers instead of stranding them pending forever;
  * the export's lease handoff publishes final pages into the radix
    tree, so a re-import skips the handed-off prefill windows;
  * chaos acceptance: ``kill_replica`` and ``stall_tick`` mid-decode
    under a shared-prefix trace -> every non-expired request completes
    on a survivor, bit-identical, zero duplicated stream tokens;
  * the ``Watchdog`` tick-deadline policy catches both failure shapes —
    a stalled tick (post-hoc, single-threaded) and a WEDGED pump (in
    progress, seen from another thread) — and quarantine migrates.
"""
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import fleet, obs, serve
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.obs import reqtrace
from distributed_tensorflow_tpu.obs import trace as obs_trace
from distributed_tensorflow_tpu.resilience import faults


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _generate_tokens(model, params, prompt, new, max_len, **kw):
    out = model.generate(params, jnp.asarray(prompt[None]),
                         max_new_tokens=new, max_len=max_len, **kw)
    return np.asarray(out)[0, prompt.size:].tolist()


def _engine(model, params, reg=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("tick_steps", 2)
    return serve.Engine(model, params,
                        registry=reg or metrics_lib.Registry(), **kw)


def _warm(engines, steps=8):
    """Compile every executable on every engine BEFORE arming tick-
    indexed faults or a watchdog deadline (a first-compile tick is
    legitimately slow, and the fault counters must start at a known
    index)."""
    hs = [eng.submit(_prompt(6, seed=50 + j), 3)
          for j, eng in enumerate(engines)]
    for _ in range(steps):
        for eng in engines:
            eng.step()
    assert all(h.done for h in hs)


def _streamer(streams, i):
    streams[i] = []
    return lambda toks: streams[i].extend(toks)


# ---------------------------------------------------------------------------
# engine-level export / import


def test_export_mid_decode_import_bit_identical_exactly_once():
    """THE migration exactness contract: export mid-decode, import on a
    second engine — terminal tokens equal the unmigrated greedy run
    token-for-token, and the stream concatenated across both engines
    has no duplicated and no missing tokens."""
    model, params = _model_params()
    src, dst_reg = _engine(model, params), metrics_lib.Registry()
    dst = _engine(model, params, reg=dst_reg)
    p = _prompt(5, seed=1)
    want = _generate_tokens(model, params, p, 10, 64)
    stream = []
    h = src.submit(p, 10, on_token=stream.extend)
    while len(h.tokens) < 4:
        src.step()
    snap = src.export_request(h)
    assert h.status == "migrated" and h.done
    assert not src.busy                      # nothing left behind
    assert snap.clean and snap.stream_offset == len(snap.generated)
    assert snap.generated == want[:len(snap.generated)]
    h2 = dst.import_request(snap, on_token=stream.extend)
    dst.drain()
    assert h2.status == "ok"
    assert h2.tokens == want                 # full sequence, pre-seeded
    assert stream == want                    # exactly-once across hops
    # the resume offset landed on the destination's histogram
    hist = dst_reg.get("dttpu_serve_stream_resume_offset")
    assert hist.count == 1


def test_export_before_first_token_restarts_cleanly():
    """Queued and mid-prefill requests export with no generated tokens
    (prefill progress is re-derived on the destination) and still
    finish bit-identical."""
    model, params = _model_params()
    src = _engine(model, params, num_slots=1)
    dst = _engine(model, params)
    p_queued, p_prefill = _prompt(4, seed=2), _prompt(10, seed=3)
    wants = [_generate_tokens(model, params, p, 6, 64)
             for p in (p_queued, p_prefill)]
    h_pf = src.submit(p_prefill, 6)          # 3 windows: stays prefilling
    h_q = src.submit(p_queued, 6)            # one slot: stays queued
    src.step()                               # h_pf mid-prefill
    snaps = src.export_inflight()
    assert not src.busy and len(snaps) == 2
    assert all(s.generated == [] for s in snaps)
    assert h_pf.status == "migrated" and h_q.status == "migrated"
    hs = [dst.import_request(s) for s in
          sorted(snaps, key=lambda s: s.rid)]
    dst.drain()
    assert hs[0].tokens == wants[1]          # rid order: prefill first
    assert hs[1].tokens == wants[0]


def test_export_terminal_and_unknown_rid_raise():
    model, params = _model_params()
    eng = _engine(model, params)
    h = eng.submit(_prompt(4, seed=1), 4)
    eng.drain()
    with pytest.raises(RuntimeError, match="already terminal"):
        eng.export_request(h)
    with pytest.raises(KeyError, match="no in-flight request"):
        eng.export_request(12345)


def test_import_rejects_incompatible_or_spent_snapshots():
    """A snapshot must fail loudly where resuming would lie: sampling
    config drift, exhausted budget, context past max_len."""
    model, params = _model_params()
    src = _engine(model, params)
    h = src.submit(_prompt(4, seed=1), 6)
    while not h.tokens:
        src.step()
    snap = src.export_request(h)
    sampled = _engine(model, params, temperature=0.7)
    with pytest.raises(ValueError, match="sampling config mismatch"):
        sampled.import_request(snap)
    spent = serve.RequestSnapshot(
        rid=0, prompt=_prompt(4, seed=1), generated=[1, 2, 3],
        max_new_tokens=3, stream_offset=3)
    with pytest.raises(ValueError, match="no remaining budget"):
        _engine(model, params).import_request(spent)
    tiny = _engine(model, params, max_len=16)
    long_snap = serve.RequestSnapshot(
        rid=0, prompt=_prompt(10, seed=2), generated=[1] * 4,
        max_new_tokens=8, stream_offset=4)
    with pytest.raises(ValueError, match="exceeds max_len"):
        tiny.import_request(long_snap)


def test_import_admission_respects_queue_depth_and_quota():
    """Imports go through the same admission door as submits: a full
    queue rejects with QueueFullError (the router's probe signal), and
    tenancy charges only the REMAINING budget."""
    model, params = _model_params()
    src = _engine(model, params)
    h = src.submit(_prompt(4, seed=1), 8, tenant="a")
    while len(h.tokens) < 3:
        src.step()
    snap = src.export_request(h)
    dst = _engine(model, params, max_queue_depth=1)
    dst.submit(_prompt(4, seed=9), 4)        # queue now full
    with pytest.raises(serve.QueueFullError):
        dst.import_request(snap)
    policy = fleet.TenantPolicy(
        {"a": fleet.TenantQuota(max_tokens_inflight=6)})
    quota_dst = _engine(model, params, tenancy=policy)
    # remaining budget is 8 - 3 = 5 <= 6: admitted even though the
    # ORIGINAL budget (8) would have blown the quota
    h2 = quota_dst.import_request(snap)
    quota_dst.drain()
    assert h2.status == "ok"


def test_export_handoff_seeds_radix_for_reimport():
    """The export's lease handoff publishes the request's final pages —
    including a chunk completed by GENERATED tokens, which admission
    registration alone could never have cached: a re-import radix-hits
    the handed-off chain and skips those prefill windows (the
    warm-handoff half of the migration cost story)."""
    model, params = _model_params()
    eng = _engine(model, params, max_len=64, prefill_chunk=4)
    page = eng.scheduler.page_size           # 16 for max_len=64
    # prompt stops 2 short of the second chunk boundary: admission can
    # register only 1 chunk; the 2nd chunk completes mid-DECODE
    p = _prompt(2 * page - 2, seed=4)
    want = _generate_tokens(model, params, p, 10, 64)
    h = eng.submit(p, 10)
    while len(h.tokens) < 5:                 # written = plen + 4 >= 2*page
        eng.step()
    before = eng.stats()
    snap = eng.export_request(h)
    h2 = eng.import_request(snap)
    eng.drain()
    after = eng.stats()
    assert h2.tokens == want
    assert after.prefix_hits_total > before.prefix_hits_total
    # the re-import reused BOTH chunks (2*page tokens) — the second one
    # exists only because the export handed it off
    assert (after.prefix_tokens_reused_total
            - before.prefix_tokens_reused_total) >= 2 * page
    assert after.prefill_windows_skipped_total \
        > before.prefill_windows_skipped_total


def test_drain_timeout_exports_then_migrates_elsewhere():
    """drain(timeout_s=) is lossless: stragglers export, the engine is
    idle, and the snapshots finish bit-identical on another engine."""
    model, params = _model_params()
    a, b = _engine(model, params, num_slots=1), _engine(model, params)
    prompts = [_prompt(4, seed=i) for i in range(3)]
    wants = [_generate_tokens(model, params, p, 12, 64) for p in prompts]
    hs = [a.submit(p, 12) for p in prompts]
    for _ in range(3):
        a.step()
    res = a.drain(timeout_s=0.0)
    assert not res and not a.busy
    assert len(res.exported) == 3
    assert all(h.status == "migrated" for h in hs)
    out = [b.import_request(s) for s in res.exported]
    assert b.drain()
    for h2, want in zip(out, wants):
        assert h2.status == "ok" and h2.tokens == want


@pytest.mark.retrace_guard(budget=1, enforce_donation=True)
def test_migration_admits_within_retrace_budget():
    """Import goes through the SAME three hot executables: exporting
    and re-importing (same engine — radix hit and cold paths both)
    never retraces anything (budget=1: a second trace of any
    executable fails the test)."""
    model, params = _model_params()
    eng = _engine(model, params)
    p = _prompt(9, seed=5)
    want = _generate_tokens(model, params, p, 10, 64)
    h = eng.submit(p, 10)
    h_other = eng.submit(_prompt(5, seed=6), 6)    # shares the ticks
    while len(h.tokens) < 3:
        eng.step()
    snap = eng.export_request(h)
    h2 = eng.import_request(snap)
    while len(h2.tokens) < 6:
        eng.step()
    snap2 = eng.export_request(h2)               # migrate TWICE
    h3 = eng.import_request(snap2)
    eng.drain()
    assert h3.tokens == want
    assert h_other.status == "ok"


# ---------------------------------------------------------------------------
# request-scoped tracing across migration (obs/reqtrace.py)


@pytest.fixture
def req_tracer():
    """Active host tracer + clean reqtrace state, torn down either way
    (a leaked live record would bleed span events into later tests)."""
    reqtrace.reset()
    tracer = obs_trace.activate(obs_trace.Tracer(enabled=True))
    try:
        yield tracer
    finally:
        obs_trace.deactivate(tracer)
        reqtrace.reset()


def test_double_migration_one_trace_tree_and_federated_metrics(
        req_tracer):
    """ISSUE 13 acceptance: a request migrated TWICE across three
    engines is ONE trace tree — a single async lane (every event on one
    (cat, id)), contiguous stage spans, a flow arrow per hop — with the
    token stream exactly-once, and one federated /metrics scrape shows
    all three replicas under distinct ``replica`` labels."""
    model, params = _model_params()
    regs = [metrics_lib.Registry() for _ in range(3)]
    engines = [_engine(model, params, reg=r) for r in regs]
    p = _prompt(5, seed=2)
    want = _generate_tokens(model, params, p, 12, 64)
    stream = []
    h = engines[0].submit(p, 12, on_token=stream.extend)
    (tid,) = reqtrace.live_ids()             # minted at the front door
    while len(h.tokens) < 3:
        engines[0].step()
    snap = engines[0].export_request(h)
    assert snap.trace_id == tid              # the lane rides the snapshot
    h2 = engines[1].import_request(snap, on_token=stream.extend)
    assert reqtrace.live_ids() == [tid]      # same lane, not a new one
    while len(h2.tokens) < 7:
        engines[1].step()
    snap2 = engines[1].export_request(h2)
    assert snap2.trace_id == tid
    h3 = engines[2].import_request(snap2, on_token=stream.extend)
    engines[2].drain()

    # exactly-once token stream across the two hops
    assert h3.status == "ok" and h3.tokens == want
    assert stream == want

    rec = reqtrace.lookup(tid)
    assert rec["status"] == "ok" and rec["hops"] == 2
    lane = [e for e in rec["events"] if e["cat"] == reqtrace.CAT]
    assert {(e["cat"], e["id"]) for e in lane} == {("request", tid)}
    # two flow arrows: s (binding at enclosing slice) then f, per hop
    flow = [(e["ph"], e.get("bp")) for e in rec["events"]
            if e["cat"] == reqtrace.FLOW_CAT]
    assert flow == [("s", "e"), ("f", None)] * 2

    t = reqtrace.tree(tid)
    (root,) = t["spans"]                     # ONE root: one lane
    assert root["name"] == "request"
    assert root["end_us"] is not None        # lane closed at retire
    assert [m["name"] for m in root["marks"]].count("exported") == 2
    assert [m["name"] for m in root["marks"]].count("imported") == 2
    kids = [c["name"] for c in root["children"]]
    # each hop replays the full stage progression (the re-prefill is
    # real work); every stage span is closed — the lane is contiguous
    assert kids == ["queued", "prefill", "decode"] * 3
    assert all(c["end_us"] is not None for c in root["children"])
    # every lane event also reached the host tracer (the Perfetto file)
    assert len([e for e in req_tracer.events()
                if e.get("id") == tid]) == len(rec["events"])

    # one federated scrape, three replicas, distinct labels, and the
    # delivered-token counters sum to exactly the request's tokens
    fed = obs.FederatedMetrics()
    for i, r in enumerate(regs):
        fed.add_registry(r, replica=str(i))
    parsed = obs.parse_exposition(fed.expose())
    samples = parsed["dttpu_serve_tokens_total"]["samples"]
    by_replica = {dict(lbls)["replica"]: v
                  for (_, lbls), v in samples.items()}
    assert set(by_replica) == {"0", "1", "2"}
    assert all(v > 0 for v in by_replica.values())
    assert sum(by_replica.values()) == len(want)   # exactly-once


@pytest.mark.retrace_guard
def test_traced_double_migration_compiles_once(req_tracer):
    """Span emission must cost ZERO recompiles: the full traced
    lifecycle — submit, chunked prefill, decode, export, re-import,
    export again — under RetraceGuard budget=1 (a second trace of any
    executable built here fails the test)."""
    model, params = _model_params()
    eng = _engine(model, params)
    p = _prompt(9, seed=5)
    want = _generate_tokens(model, params, p, 10, 64)
    h = eng.submit(p, 10)
    (tid,) = reqtrace.live_ids()
    while len(h.tokens) < 3:
        eng.step()
    h2 = eng.import_request(eng.export_request(h))
    while len(h2.tokens) < 6:
        eng.step()
    h3 = eng.import_request(eng.export_request(h2))
    eng.drain()
    assert h3.tokens == want
    assert reqtrace.lookup(tid)["hops"] == 2
    # zero retrace instants on the host timeline (the guard would have
    # raised first; the trace file is the visible proof)
    assert [e for e in req_tracer.events()
            if e.get("name") == "retrace"] == []


def test_watchdog_quarantine_dumps_victim_span_trees(req_tracer):
    """The watchdog snapshots every victim's span tree AT the
    quarantine verdict — the forensics land in reqtrace.forensics_log()
    with the replica and reason, while the requests themselves migrate
    and finish cleanly.  Verdict policy is forced (single-threaded)
    so the test pins the forensics contract, not stall timing."""
    model, params = _model_params()
    engines = [_engine(model, params) for _ in range(2)]
    router = fleet.Router(engines, registry=metrics_lib.Registry())
    _warm(engines)
    reqtrace.reset()            # drop the warmup lanes
    wd = fleet.Watchdog(router, tick_deadline_s=5.0,
                        registry=metrics_lib.Registry())
    hs = [router.submit(_prompt(5, seed=70 + i), 8) for i in range(3)]
    while not any(len(h.tokens) >= 2 for h in hs):
        router.step()
    victims = set(engines[0].inflight_trace_ids())
    # force ONLY the first replica unhealthy: check() sweeps stats in
    # rid order, so the first verdict call is replica 0
    calls = []

    def forced(stats, now=None):
        calls.append(1)
        return "stalled: forced by test" if len(calls) == 1 else None

    wd.verdict = forced
    hits = wd.check()
    assert hits and hits[0][0] == 0
    dumps = reqtrace.forensics_log()
    assert {d["trace_id"] for d in dumps} == victims
    for d in dumps:
        assert d["reason"] == "watchdog_quarantine"
        assert d["context"]["replica"] == 0
        (root,) = d["spans"]
        assert root["end_us"] is None        # dumped while live
    while any(not h.done for h in hs):
        router.step()
    assert all(h.status == "ok" for h in hs)


# ---------------------------------------------------------------------------
# fleet-level migration


def test_drain_replica_migrates_then_resume_replica():
    """drain_replica moves in-flight work to the survivor with progress
    intact (no wait-out), the drained replica ends idle, and
    resume_replica puts it back in rotation."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    router = fleet.Router(
        [_engine(model, params, reg=reg) for _ in range(2)],
        registry=reg)
    prompts = [_prompt(4 + i % 3, seed=i) for i in range(4)]
    wants = [_generate_tokens(model, params, p, 10, 64) for p in prompts]
    streams = {}
    hs = [router.submit(p, 10, on_token=_streamer(streams, i))
          for i, p in enumerate(prompts)]
    router.step()
    router.step()                               # decode in flight
    assert router.drain_replica(0, timeout_s=60) is True
    assert not router.replica(0).busy           # emptied by migration
    router.drain()
    for i, (h, want) in enumerate(zip(hs, wants)):
        assert h.status == "ok" and h.tokens == want
        assert streams[i] == want, f"stream {i} dup/loss"
    assert reg.get("dttpu_migrations_total").value >= 1
    # preserved decode work is visible on the handles
    assert sum(h.tokens_preserved for h in hs) >= 0
    router.resume_replica(0)
    h2 = router.submit(_prompt(4, seed=9), 4)
    router.drain()
    assert h2.status == "ok"
    with pytest.raises(KeyError):
        router.resume_replica(99)


def test_remove_replica_migrates_progress():
    model, params = _model_params()
    reg = metrics_lib.Registry()
    router = fleet.Router(
        [_engine(model, params, reg=reg) for _ in range(2)],
        registry=reg)
    prompts = [_prompt(4, seed=i) for i in range(4)]
    hs = [router.submit(p, 10) for p in prompts]
    for _ in range(4):
        router.step()                           # tokens on both replicas
    removed = router.remove_replica(1)
    router.drain()
    for p, h in zip(prompts, hs):
        assert h.status == "ok"
        assert h.tokens == _generate_tokens(model, params, p, 10, 64)
    moved = [h for h in hs if h.migrations]
    assert moved and sum(h.tokens_preserved for h in moved) > 0
    router.add_replica(removed)                 # rolling restart


# ---------------------------------------------------------------------------
# chaos acceptance


@pytest.mark.chaos
def test_kill_and_stall_mid_decode_shared_prefix_exactly_once():
    """THE migration chaos acceptance: a shared-prefix trace loses one
    replica to ``kill_replica`` mid-decode and has the other STALL
    (watchdog quarantine) — every non-expired request still completes
    on a survivor with terminal tokens bit-identical to solo
    ``generate`` and ZERO duplicated stream tokens."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    engines = [_engine(model, params, reg=reg) for _ in range(3)]
    router = fleet.Router(engines, registry=reg)
    _warm(engines)
    wd = fleet.Watchdog(router, tick_deadline_s=0.25,
                        export_timeout_s=0.1, registry=reg)
    page = engines[0].scheduler.page_size
    sys_prefix = _prompt(page, seed=99)          # one shared radix chunk
    prompts = [np.concatenate([sys_prefix, _prompt(3 + i % 3, seed=i)])
               for i in range(8)]
    wants = [_generate_tokens(model, params, p, 8, 64) for p in prompts]
    plan = faults.FaultPlan(
        [{"kind": "kill_replica", "at": 5, "replica": 1},
         {"kind": "stall_tick", "at": 6, "replica": 2, "seconds": 0.6}],
        registry=metrics_lib.Registry())
    streams = {}
    with faults.activated(plan):
        hs = [router.submit(p, 8, deadline_s=120.0,
                            on_token=_streamer(streams, i))
              for i, p in enumerate(prompts)]
        quarantined = []
        deadline = time.perf_counter() + 120
        while router.busy:
            assert time.perf_counter() < deadline, "chaos run hung"
            router.step()
            quarantined.extend(wd.check())
    kinds = {e["kind"] for e in plan.log}
    assert kinds == {"kill_replica", "stall_tick"}, plan.log
    assert [rid for rid, _ in quarantined] == [2]
    assert router.replica_ids == (0,)
    assert 2 in router.quarantined
    for i, (h, want) in enumerate(zip(hs, wants)):
        assert h.status == "ok", (i, h.status, h.error)
        assert h.tokens == want, f"request {i} terminal tokens diverged"
        assert streams[i] == want, f"request {i} stream dup/loss"
    assert reg.get("dttpu_migrations_total").value >= 1
    assert reg.get("dttpu_watchdog_unhealthy_total").value == 1


@pytest.mark.chaos
def test_wedge_replica_watchdog_forced_export_migrates():
    """A WEDGED pump (blocked mid-tick, mutex held) is invisible to
    everything but the in-progress heartbeat: the watchdog detects it
    from another thread, the quarantine's bounded-wait export goes
    around the held mutex, and the requests finish on the survivor.
    The released wedge's late tick delivers nothing (terminal-status
    check drops it)."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    engines = [_engine(model, params, reg=reg) for _ in range(2)]
    router = fleet.Router(engines, registry=reg)
    _warm(engines)
    wd = fleet.Watchdog(router, tick_deadline_s=0.2,
                        export_timeout_s=0.1, registry=reg)
    prompts = [_prompt(5, seed=90 + i) for i in range(4)]
    wants = [_generate_tokens(model, params, p, 8, 64) for p in prompts]
    plan = faults.FaultPlan(
        [{"kind": "wedge_replica", "at": 3, "replica": 0,
          "seconds": 30.0}],
        registry=metrics_lib.Registry())
    stop = threading.Event()

    def pump_fleet():
        while not stop.is_set() and router.busy:
            router.step()

    pump = threading.Thread(target=pump_fleet,
                            name="dttpu-migration-pump", daemon=True)
    try:
        with faults.activated(plan):
            streams = {}
            hs = [router.submit(p, 8, on_token=_streamer(streams, i))
                  for i, p in enumerate(prompts)]
            pump.start()
            hits = []
            deadline = time.perf_counter() + 60
            while not hits:
                assert time.perf_counter() < deadline, "never detected"
                time.sleep(0.02)
                hits.extend(wd.check())
            assert hits[0][0] == 0 and "wedged" in hits[0][1]
            # the survivor finishes the migrated work while the wedged
            # pump thread is still parked inside replica 0's tick
            deadline = time.perf_counter() + 120
            while any(not h.done for h in hs):
                assert time.perf_counter() < deadline, "migration hung"
                router.step()
    finally:
        plan.release_wedges()
        stop.set()
        pump.join(timeout=30)
    assert not pump.is_alive()
    for i, (h, want) in enumerate(zip(hs, wants)):
        assert h.status == "ok", (i, h.status, h.error)
        assert h.tokens == want
        assert streams[i] == want, f"stream {i} dup/loss"
    assert 0 in router.quarantined
    assert "wedged" in router.quarantined[0][1]


@pytest.mark.chaos
def test_stall_and_wedge_faults_fire_at_most_times_and_log():
    """The new fault kinds obey the standard plan contract: seeded,
    index-targeted, at-most-``times`` fires, each injection logged."""
    model, params = _model_params()
    eng = _engine(model, params)
    _warm([eng])                # compile: ticks timed below must be hot
    plan = faults.FaultPlan(
        [{"kind": "stall_tick", "at": 1, "seconds": 0.15},
         {"kind": "wedge_replica", "at": 3, "seconds": 0.15}],
        registry=metrics_lib.Registry())
    with faults.activated(plan):
        h = eng.submit(_prompt(4, seed=1), 8)
        durations = []
        while eng.busy:
            t0 = time.perf_counter()
            eng.step()
            durations.append(time.perf_counter() - t0)
    assert h.status == "ok"
    assert plan.log == [
        {"kind": "stall_tick", "at": 1, "replica": 0, "tick": 1,
         "seconds": 0.15},
        {"kind": "wedge_replica", "at": 3, "replica": 0, "tick": 3},
    ]
    # exactly the targeted ticks ran long (the unreleased wedge
    # self-freed at its seconds cap), and only once each
    slow = [i for i, d in enumerate(durations) if d >= 0.1]
    assert slow == [1, 3], durations


# ---------------------------------------------------------------------------
# watchdog policy unit + concurrency


def test_watchdog_verdict_policy_unit():
    """The tick-deadline policy on synthetic heartbeats: healthy, idle,
    wedged (in progress too long), stalled (completed too slow)."""
    model, params = _model_params()
    router = fleet.Router(registry=metrics_lib.Registry())
    wd = fleet.Watchdog(router, tick_deadline_s=1.0,
                        registry=metrics_lib.Registry())

    def stats(**kw):
        return serve.EngineStats(queued=0, prefilling=0, active=1,
                                 num_slots=2, inflight_per_tenant={},
                                 tokens_inflight_per_tenant={}, **kw)

    now = 100.0
    assert wd.verdict(stats(), now) is None                  # never ticked
    healthy = stats(ticks_started=5, ticks_completed=5,
                    last_tick_start_s=99.0, last_tick_end_s=99.1,
                    last_tick_duration_s=0.1)
    assert wd.verdict(healthy, now) is None
    wedged = stats(ticks_started=6, ticks_completed=5,
                   last_tick_start_s=98.0)
    assert "wedged" in wd.verdict(wedged, now)
    in_progress_fresh = stats(ticks_started=6, ticks_completed=5,
                              last_tick_start_s=99.9)
    assert wd.verdict(in_progress_fresh, now) is None
    stalled = stats(ticks_started=5, ticks_completed=5,
                    last_tick_duration_s=2.5)
    assert "stalled" in wd.verdict(stalled, now)
    with pytest.raises(ValueError, match="tick_deadline_s"):
        fleet.Watchdog(router, tick_deadline_s=0.0)


@pytest.mark.race_harness(
    seed=11, scope=("distributed_tensorflow_tpu/serve/",
                    "distributed_tensorflow_tpu/fleet/"))
def test_concurrent_export_vs_pump_tick(request):
    """Export racing a live pump under seeded preemption: the export
    serializes against the tick (pump mutex), so however the schedule
    interleaves, the snapshot and the stream agree — the resumed run
    is bit-identical with zero duplicated/lost stream tokens."""
    model, params = _model_params()
    src, dst = _engine(model, params), _engine(model, params)
    p = _prompt(5, seed=7)
    want = _generate_tokens(model, params, p, 12, 64)
    stream = []
    h = src.submit(p, 12, on_token=stream.extend)
    stop = threading.Event()

    def pump():
        while not stop.is_set() and src.busy:
            src.step()

    t = threading.Thread(target=pump, name="dttpu-export-pump",
                         daemon=True)
    t.start()
    try:
        deadline = time.time() + 120
        while not h.tokens:
            assert time.time() < deadline
            time.sleep(0.001)
        snap = src.export_request(h)         # races the running tick
    finally:
        stop.set()
        t.join(timeout=60)
    assert not t.is_alive()
    assert h.status == "migrated"
    h2 = dst.import_request(snap, on_token=stream.extend)
    dst.drain()
    assert h2.tokens == want
    assert stream == want, "stream dup/loss across the export race"
