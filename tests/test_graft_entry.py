"""Driver-contract tests: dryrun_multichip must compile+run the full sharded
train step at several world sizes on the virtual CPU mesh."""
import pytest

import __graft_entry__ as graft


@pytest.mark.parametrize("n", [1, 2, 8])
def test_dryrun_multichip(n, capsys):
    graft.dryrun_multichip(n)
    out = capsys.readouterr().out
    assert "OK" in out


def test_mesh_axes_factoring():
    assert graft._mesh_axes_for(1) == {"data": 1}
    assert graft._mesh_axes_for(2) == {"tensor": 2}
    assert graft._mesh_axes_for(4) == {"tensor": 2, "seq": 2}
    assert graft._mesh_axes_for(8) == {"tensor": 2, "seq": 2, "data": 2}
    assert graft._mesh_axes_for(6) == {"tensor": 2, "data": 3}


def test_entry_returns_jittable():
    import jax
    fn, args = graft.entry()
    # Abstract trace (no full compile in the unit suite — the driver does
    # the real single-chip compile check).
    jax.eval_shape(fn, *args)
