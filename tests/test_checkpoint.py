"""Checkpoint subsystem tests (reference MTS checkpoint_dir capability,
example.py:189-192)."""
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.train import checkpoint as ck


def tree(value):
    return {"params": {"dense": {"kernel": jnp.full((3, 2), value),
                                 "bias": jnp.zeros((2,))}},
            "step": jnp.asarray(int(value), jnp.int32)}


def test_roundtrip(tmp_path):
    d = str(tmp_path)
    ck.save(d, 10, tree(1.5))
    restored = ck.restore(tree(0.0), ck.latest_checkpoint(d))
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["dense"]["kernel"]),
        np.full((3, 2), 1.5, np.float32))
    assert int(restored["step"]) == 1


def test_latest_and_max_to_keep(tmp_path):
    d = str(tmp_path)
    for step in [5, 10, 15, 20]:
        ck.save(d, step, tree(step), max_to_keep=2)
    assert ck.latest_step(d) == 20
    assert len(ck.all_checkpoints(d)) == 2
    with open(tmp_path / "checkpoint") as f:
        assert f.read().strip() == "ckpt-0000000020"


def test_restore_structure_mismatch(tmp_path):
    d = str(tmp_path)
    ck.save(d, 1, tree(1.0))
    bad = {"params": {"dense": {"kernel": jnp.zeros((4, 2)),
                                "bias": jnp.zeros((2,))}},
           "step": jnp.asarray(0)}
    with pytest.raises(ValueError, match="shape"):
        ck.restore(bad, ck.latest_checkpoint(d))


def test_empty_dir(tmp_path):
    assert ck.latest_checkpoint(str(tmp_path)) is None
    assert ck.latest_step(str(tmp_path)) is None


def test_bfloat16_roundtrip(tmp_path):
    """bf16 leaves survive npz save/restore (stored uint16-encoded)."""
    d = str(tmp_path)
    t = {"w": jnp.arange(8, dtype=jnp.bfloat16)}
    ck.save(d, 1, t)
    out = ck.restore({"w": jnp.zeros(8, jnp.bfloat16)},
                     ck.latest_checkpoint(d))
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(out["w"], np.float32),
                                  np.arange(8, dtype=np.float32))
