"""Resilience layer tests: fault injection, verified-checkpoint fallback,
auto-resume supervisor, non-finite guard (docs/RESILIENCE.md).

Every recovery path here is exercised UNDER an injected fault (the
``FaultPlan`` harness), not just asserted from the happy path — the
chaos acceptance test at the bottom drives corrupt-checkpoint fallback,
a NaN-poisoned step, and a killed prefetch producer through one
supervised training run.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import data, ops, optim, train
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.obs import trace as trace_lib
from distributed_tensorflow_tpu.resilience import (Fault, FaultPlan,
                                                   InjectedFault,
                                                   NonfiniteGuardHook,
                                                   Supervisor, faults)
from distributed_tensorflow_tpu.train import checkpoint as ckpt_lib
from distributed_tensorflow_tpu.train import sharded_checkpoint as sh_lib


def make_bits(device_health=False, skip_nonfinite=False):
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adam()
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt,
                                 device_health=device_health,
                                 skip_nonfinite=skip_nonfinite)
    (xt, yt), _ = data.xor_data(500, val_size=10, seed=0)
    ds = data.Dataset([xt, yt], 50, seed=0)
    return state, step, ds


def tree() -> dict:
    return {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.zeros((4,)),
            "step": np.int32(0)}


# ---------------------------------------------------------------------------
# FaultPlan mechanics


class TestFaultPlan:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultPlan([{"kind": "set_cpu_on_fire", "at": 0}])

    def test_env_spec_parses_both_shapes(self):
        p = faults.plan_from_env('[{"kind": "nan_grads", "at": 3}]')
        assert p.faults[0].kind == "nan_grads" and p.faults[0].at == 3
        p = faults.plan_from_env(
            '{"seed": 7, "faults": [{"kind": "kill_prefetch", "at": 1}]}')
        assert p.seed == 7 and p.faults[0].kind == "kill_prefetch"

    def test_env_activation_and_counter_persistence(self, monkeypatch):
        monkeypatch.setenv("DTTPU_FAULTS",
                           '[{"kind": "save_oserror", "at": 1}]')
        plan = faults.active()
        assert plan is not None
        # same env value -> same cached plan (counters must persist)
        assert faults.active() is plan
        monkeypatch.delenv("DTTPU_FAULTS")
        assert faults.active() is None

    def test_fires_once_by_default_and_times_n(self, activate_faults):
        reg = metrics_lib.Registry()
        plan = activate_faults({"kind": "fail_decode", "at": 5},
                               {"kind": "fail_decode", "at": 6, "times": 2},
                               registry=reg)
        with pytest.raises(InjectedFault):
            plan.on_decode(5)
        plan.on_decode(5)                       # exhausted: no raise
        for _ in range(2):
            with pytest.raises(InjectedFault):
                plan.on_decode(6)
        plan.on_decode(6)
        assert reg.get("dttpu_faults_injected_total").value == 3
        assert [e["kind"] for e in plan.log] == ["fail_decode"] * 3

    def test_injections_emit_trace_instants(self, activate_faults):
        tracer = trace_lib.Tracer(enabled=True)
        trace_lib.activate(tracer)
        try:
            plan = activate_faults({"kind": "nan_grads", "at": 0},
                                   registry=metrics_lib.Registry())
            plan.on_step(0, (np.ones((2, 2), np.float32),))
            assert tracer.instant_counts.get("fault") == 1
        finally:
            trace_lib.deactivate(tracer)

    def test_poison_hits_float_leaves_only(self, activate_faults):
        plan = activate_faults({"kind": "poison_batch", "at": 0},
                               registry=metrics_lib.Registry())
        x = np.ones((3,), np.float32)
        ids = np.ones((3,), np.int32)
        px, pids = plan.on_batch((x, ids))
        assert np.isnan(px).all() and (pids == 1).all()

    def test_flip_corruption_is_seeded_deterministic(self, tmp_path):
        files = []
        for seed in (3, 3):
            d = tmp_path / f"s{seed}-{len(files)}"
            d.mkdir()
            f = d / "arrays.npz"
            f.write_bytes(bytes(range(256)) * 4)
            plan = FaultPlan([{"kind": "corrupt_checkpoint", "at": 0,
                               "mode": "flip"}], seed=seed,
                             registry=metrics_lib.Registry())
            plan.on_saved(str(d), plan.on_save())
            files.append(f.read_bytes())
        assert files[0] == files[1] != bytes(range(256)) * 4


# ---------------------------------------------------------------------------
# Verified checkpoints: CRC manifest, quarantine, newest-good fallback


class TestVerifiedCheckpoint:
    def test_manifest_records_crc_and_verify_passes(self, tmp_path):
        d = str(tmp_path)
        p = ckpt_lib.save(d, 1, tree())
        with open(os.path.join(p, "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["checksum"] == ckpt_lib.CHECKSUM_FORMAT
        assert all(isinstance(m["crc32c"], int) for m in manifest["leaves"])
        ok, reason = ckpt_lib.verify(p, target=tree())
        assert ok, reason

    def test_truncated_npz_quarantined_and_falls_back(self, tmp_path):
        d = str(tmp_path)
        t = tree()
        ckpt_lib.save(d, 1, t)
        good = ckpt_lib.save(d, 2, jax.tree.map(
            lambda a: np.asarray(a) * 0 + 7, t))
        bad = ckpt_lib.save(d, 3, t)
        npz = os.path.join(bad, "arrays.npz")
        with open(npz, "r+b") as f:
            f.truncate(os.path.getsize(npz) // 2)
        ok, reason = ckpt_lib.verify(bad)
        assert not ok and "arrays.npz" in reason
        restored, path = ckpt_lib.restore_latest_good(t, d)
        assert path == good and restored is not None
        assert float(np.asarray(restored["b"])[0]) == 7.0
        # the bad dir moved out of the restore namespace, with a reason
        q = os.path.join(d, "corrupt-ckpt-0000000003")
        assert os.path.isdir(q)
        with open(os.path.join(q, "QUARANTINE_REASON")) as f:
            assert "arrays.npz" in f.read()
        assert bad not in ckpt_lib.all_checkpoints(d)

    def test_content_swap_caught_by_leaf_crc(self, tmp_path):
        """A structurally VALID npz whose array content no longer matches
        the manifest (silent bitrot 'repair', a leaf swapped between
        checkpoints): the zip layer's own CRC passes — only the
        manifest's per-leaf CRC can catch it."""
        d = str(tmp_path)
        p = ckpt_lib.save(d, 1, tree())
        npz = os.path.join(p, "arrays.npz")
        with np.load(npz) as z:
            arrs = {k: z[k].copy() for k in z.files}
        arrs["leaf_0"][0] += 1.0               # same shape/dtype, new value
        np.savez(npz, **arrs)
        ok, reason = ckpt_lib.verify(p)
        assert not ok and "CRC mismatch" in reason

    def test_leaf_count_mismatch_quarantined(self, tmp_path):
        d = str(tmp_path)
        t = tree()
        good = ckpt_lib.save(d, 1, t)
        bad = ckpt_lib.save(d, 2, t)
        mpath = os.path.join(bad, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        manifest["leaves"] = manifest["leaves"][:-1]
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        ok, reason = ckpt_lib.verify(bad)
        assert not ok and "mismatch" in reason
        restored, path = ckpt_lib.restore_latest_good(t, d)
        assert path == good
        assert ckpt_lib.all_checkpoints(d) == [good]

    def test_all_bad_returns_none(self, tmp_path):
        d = str(tmp_path)
        t = tree()
        for step in (1, 2):
            p = ckpt_lib.save(d, step, t)
            with open(os.path.join(p, "arrays.npz"), "r+b") as f:
                f.truncate(10)
        restored, path = ckpt_lib.restore_latest_good(t, d)
        assert restored is None and path is None
        assert ckpt_lib.all_checkpoints(d) == []

    def test_quarantine_names_uniquify(self, tmp_path):
        d = str(tmp_path)
        t = tree()
        for _ in range(2):
            p = ckpt_lib.save(d, 5, t)
            ckpt_lib.quarantine(p, "test reason")
        names = sorted(os.listdir(d))
        assert "corrupt-ckpt-0000000005" in names
        assert "corrupt-ckpt-0000000005.1" in names

    def test_session_restores_through_fallback(self, tmp_path):
        """TrainSession(restore=True) lands on the previous good step when
        the newest checkpoint is corrupt — the MTS auto-restore contract
        surviving corruption."""
        d = str(tmp_path)
        state, step, ds = make_bits()
        with train.TrainSession(state, step, checkpoint_dir=d,
                                hooks=[train.CheckpointHook(
                                    every_steps=2, every_secs=None),
                                    train.StopAtStepHook(last_step=5)]
                                ) as sess:
            for batch in ds.epochs(10):
                if sess.should_stop():
                    break
                sess.run_step(batch)
        newest = ckpt_lib.latest_checkpoint(d)
        assert newest.endswith("ckpt-0000000005")
        with open(os.path.join(newest, "arrays.npz"), "r+b") as f:
            f.truncate(20)
        state2, step2, _ = make_bits()
        with train.TrainSession(state2, step2, checkpoint_dir=d,
                                hooks=[train.StopAtStepHook(last_step=9)]
                                ) as s2:
            assert s2.step == 4           # fell back one save interval
        assert os.path.isdir(os.path.join(d, "corrupt-ckpt-0000000005"))

    def test_save_oserror_fault_is_transient_shaped(self, tmp_path,
                                                    activate_faults):
        activate_faults({"kind": "save_oserror", "at": 0},
                        registry=metrics_lib.Registry())
        with pytest.raises(OSError, match="injected fault"):
            ckpt_lib.save(str(tmp_path), 1, tree())
        # next save (index 1) succeeds and verifies
        p = ckpt_lib.save(str(tmp_path), 2, tree())
        assert ckpt_lib.verify(p)[0]


class TestCheckpointIndex:
    def test_index_written_atomically_and_preferred(self, tmp_path):
        d = str(tmp_path)
        ckpt_lib.save(d, 1, tree())
        p2 = ckpt_lib.save(d, 2, tree())
        with open(os.path.join(d, "checkpoint")) as f:
            assert f.read().strip() == "ckpt-0000000002"
        assert ckpt_lib.latest_checkpoint(d) == p2
        # no stray tmp files from the tmp+replace dance
        assert not [n for n in os.listdir(d) if n.startswith(".checkpoint")]

    def test_torn_index_falls_back_to_scan(self, tmp_path):
        d = str(tmp_path)
        p = ckpt_lib.save(d, 3, tree())
        with open(os.path.join(d, "checkpoint"), "w") as f:
            f.write("ckpt-00000")          # torn mid-write
        assert ckpt_lib.latest_checkpoint(d) == p
        with open(os.path.join(d, "checkpoint"), "w") as f:
            f.write("not-a-checkpoint\n")
        assert ckpt_lib.latest_checkpoint(d) == p
        assert ckpt_lib.latest_step(d) == 3

    def test_index_pointing_at_quarantined_dir_falls_back(self, tmp_path):
        d = str(tmp_path)
        p3 = ckpt_lib.save(d, 3, tree())
        p5 = ckpt_lib.save(d, 5, tree())
        ckpt_lib.quarantine(p5, "poof")     # index still names ckpt-5
        assert ckpt_lib.latest_checkpoint(d) == p3

    def test_missing_index_still_scans(self, tmp_path):
        d = str(tmp_path)
        p = ckpt_lib.save(d, 1, tree())
        os.unlink(os.path.join(d, "checkpoint"))
        assert ckpt_lib.latest_checkpoint(d) == p


# ---------------------------------------------------------------------------
# Sharded checkpoints: chunk CRCs, coverage, quarantine walk


def sharded_tree():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(4), "data")
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("data"))
    return {"w": jax.device_put(jnp.arange(16.0).reshape(8, 2), sh),
            "step": np.int32(7)}


class TestShardedVerify:
    def test_chunk_rows_carry_crc_and_verify_passes(self, tmp_path):
        d = str(tmp_path)
        p = sh_lib.save_sharded(d, 1, sharded_tree())
        with open(os.path.join(p, "chunks-00000.json")) as f:
            rows = json.load(f)
        assert rows and all(isinstance(r["crc32c"], int) for r in rows)
        ok, reason = sh_lib.verify_sharded(p)
        assert ok, reason

    def test_missing_chunk_file_quarantined_with_fallback(self, tmp_path):
        """Manifest present but a shard npz gone: structurally incomplete
        → quarantined by the restore walk, restore falls back."""
        d = str(tmp_path)
        t = sharded_tree()
        good = sh_lib.save_sharded(d, 1, t)
        bad = sh_lib.save_sharded(d, 2, t)
        os.unlink(os.path.join(bad, "shards-00000.npz"))
        assert os.path.exists(os.path.join(bad, "manifest.json"))
        ok, reason = sh_lib.verify_sharded(bad)
        assert not ok and "incomplete" in reason
        assert sh_lib.all_sharded_checkpoints(d) == [good]
        restored, path = sh_lib.restore_latest_good_sharded(t, d)
        assert path == good and restored is not None
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.asarray(t["w"]))
        assert os.path.isdir(os.path.join(d, "corrupt-ckpt-0000000002"))
        assert sh_lib.all_sharded_checkpoints(d) == [good]

    def test_chunk_content_swap_fails_chunk_crc(self, tmp_path):
        """Valid shard npz, wrong chunk content: only the chunk-index
        CRC catches it (the zip layer re-checksums the new bytes)."""
        d = str(tmp_path)
        t = sharded_tree()
        p = sh_lib.save_sharded(d, 1, t)
        npz = os.path.join(p, "shards-00000.npz")
        with np.load(npz) as z:
            arrs = {k: z[k].copy() for k in z.files}
        key = next(k for k in arrs if arrs[k].size > 0
                   and arrs[k].dtype == np.float32)
        arrs[key] = arrs[key] + 1.0
        np.savez(npz, **arrs)
        ok, reason = sh_lib.verify_sharded(p)
        assert not ok and "CRC mismatch" in reason

    def test_dropped_chunk_row_fails_coverage(self, tmp_path):
        d = str(tmp_path)
        p = sh_lib.save_sharded(d, 1, sharded_tree())
        cpath = os.path.join(p, "chunks-00000.json")
        with open(cpath) as f:
            rows = json.load(f)
        with open(cpath, "w") as f:
            json.dump(rows[1:], f)
        ok, reason = sh_lib.verify_sharded(p)
        assert not ok and "cover" in reason

    def test_session_sharded_restore_falls_back(self, tmp_path):
        d = str(tmp_path)
        state, step, ds = make_bits()
        with train.TrainSession(state, step, checkpoint_dir=d,
                                sharded_checkpoint=True,
                                hooks=[train.StopAtStepHook(last_step=3)]
                                ) as sess:
            for batch in ds.epochs(10):
                if sess.should_stop():
                    break
                sess.run_step(batch)
        # corrupt the (only) checkpoint -> next session starts fresh at 0
        newest = sh_lib.all_sharded_checkpoints(d)[-1]
        os.unlink(os.path.join(newest, "shards-00000.npz"))
        state2, step2, _ = make_bits()
        sess2 = train.TrainSession(state2, step2, checkpoint_dir=d,
                                   sharded_checkpoint=True)
        assert sess2.step == 0
        assert any(n.startswith("corrupt-") for n in os.listdir(d))


# ---------------------------------------------------------------------------
# skip_nonfinite step option + NonfiniteGuardHook


class TestSkipNonfinite:
    def test_bad_step_rolls_back_in_graph(self):
        state, step, ds = make_bits(device_health=True, skip_nonfinite=True)
        batch = next(iter(ds))
        state, m = step(state, batch)
        params_before = jax.tree.map(np.asarray, state.params)
        opt_before = jax.tree.map(np.asarray, state.opt_state)
        poisoned = tuple(np.full_like(a, np.nan) for a in batch)
        state, m = step(state, poisoned)
        assert not bool(m["grads_finite"])
        assert float(m["nonfinite_grads"]) > 0
        for a, b in zip(jax.tree.leaves(params_before),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(a, np.asarray(b))
        for a, b in zip(jax.tree.leaves(opt_before),
                        jax.tree.leaves(state.opt_state)):
            np.testing.assert_array_equal(a, np.asarray(b))
        assert int(state.step) == 2            # cursor still advances
        # and a following clean step updates params again
        state2, m2 = step(state, batch)
        assert bool(m2["grads_finite"])
        assert any(not np.array_equal(a, np.asarray(b))
                   for a, b in zip(jax.tree.leaves(params_before),
                                   jax.tree.leaves(state2.params)))

    def test_rejected_with_loss_scale(self):
        model = ops.serial(ops.Dense(4))
        with pytest.raises(ValueError, match="loss_scale"):
            train.make_train_step(model, "mse", optim.sgd(0.1),
                                  loss_scale=True, skip_nonfinite=True)


class TestNonfiniteGuard:
    def test_aborts_after_k_consecutive(self):
        state, step, ds = make_bits(device_health=True, skip_nonfinite=True)
        batch = next(iter(ds))
        poisoned = tuple(np.full_like(a, np.nan) for a in batch)
        guard = NonfiniteGuardHook(max_consecutive=3)
        with pytest.raises(FloatingPointError, match="3 consecutive"):
            with train.TrainSession(state, step, hooks=[guard]) as sess:
                for _ in range(5):
                    sess.run_step(poisoned)
        assert guard.total_nonfinite == 3

    def test_isolated_bad_steps_survive(self):
        state, step, ds = make_bits(device_health=True, skip_nonfinite=True)
        it = iter(ds.epochs(10))
        guard = NonfiniteGuardHook(max_consecutive=2)
        with train.TrainSession(state, step, hooks=[guard]) as sess:
            for i in range(6):
                batch = next(it)
                if i % 2 == 0:     # never two bad in a row
                    batch = tuple(np.full_like(a, np.nan) for a in batch)
                sess.run_step(batch)
        assert guard.total_nonfinite == 3 and guard.consecutive <= 1

    def test_no_health_metrics_is_a_noop(self):
        state, step, ds = make_bits()      # no device_health
        guard = NonfiniteGuardHook(max_consecutive=1)
        with train.TrainSession(state, step, hooks=[guard]) as sess:
            sess.run_step(next(iter(ds)))
        assert guard.consecutive == 0


# ---------------------------------------------------------------------------
# Supervisor


class TestSupervisor:
    def _sup(self, **kw):
        sleeps = []
        reg = metrics_lib.Registry()
        sup = Supervisor(registry=reg, sleep=sleeps.append,
                         backoff_base=0.5, jitter=0.0, **kw)
        return sup, sleeps, reg

    def test_transient_retries_with_exponential_backoff(self):
        sup, sleeps, reg = self._sup(max_restarts=3)
        calls = []

        class Sess:
            def __enter__(self):
                return self

            def __exit__(self, *e):
                return False

        def build():
            calls.append("build")
            return Sess()

        def train_fn(sess):
            if len(calls) < 3:
                raise OSError("flaky storage")
            return "done"

        assert sup.run(build, train_fn) == "done"
        assert calls == ["build"] * 3
        assert sleeps == [0.5, 1.0]
        assert reg.get("dttpu_restarts_total").value == 2
        assert reg.get("dttpu_recovery_seconds").count == 2
        assert len(sup.restart_log) == 2

    def test_fatal_raises_immediately(self):
        sup, sleeps, reg = self._sup(max_restarts=5)

        def build():
            raise ValueError("shape mismatch: a code bug")

        with pytest.raises(ValueError):
            sup.run(build, lambda s: None)
        assert sleeps == []
        assert reg.get("dttpu_restarts_total").value == 0

    def test_budget_exhaustion_reraises_last_transient(self):
        sup, sleeps, reg = self._sup(max_restarts=2)

        def build():
            raise OSError("down hard")

        with pytest.raises(OSError, match="down hard"):
            sup.run(build, lambda s: None)
        assert len(sleeps) == 2
        assert reg.get("dttpu_restarts_total").value == 2

    def test_classify_override(self):
        sup, sleeps, _ = self._sup(
            max_restarts=3,
            classify=lambda e: "transient"
            if isinstance(e, KeyError) else "fatal")
        n = []

        class Sess:
            def __enter__(self):
                return self

            def __exit__(self, *e):
                return False

        def train_fn(sess):
            n.append(1)
            if len(n) == 1:
                raise KeyError("custom-transient")
            return len(n)

        assert sup.run(Sess, train_fn) == 2
        # and the default-transient OSError is now fatal under override
        with pytest.raises(OSError):
            sup.run(Sess, lambda s: (_ for _ in ()).throw(OSError("x")))

    def test_backoff_caps_and_jitters(self):
        sup, _, _ = self._sup(max_restarts=1)
        sup.backoff_max = 2.0
        sup.jitter = 0.5
        delays = {sup._delay(10) for _ in range(8)}
        assert all(2.0 <= d <= 3.0 for d in delays)
        assert len(delays) > 1                  # jitter actually jitters


# ---------------------------------------------------------------------------
# Chaos acceptance: the whole layer under one FaultPlan


@pytest.mark.chaos
def test_chaos_training_run_survives_three_faults(tmp_path,
                                                  activate_faults):
    """THE acceptance scenario (ISSUE 5): corrupt the newest checkpoint,
    NaN-poison one step, kill the prefetch producer — the supervised run
    still reaches the target step via quarantine-fallback + restart,
    with >= 1 restart recorded and finite final params."""
    reg = metrics_lib.Registry()
    d = str(tmp_path)
    TARGET = 12
    activate_faults({"kind": "corrupt_checkpoint", "at": 1},
                    {"kind": "nan_grads", "at": 4},
                    {"kind": "kill_prefetch", "at": 8},
                    registry=reg)

    def build_session():
        state, step, ds = make_bits(device_health=True, skip_nonfinite=True)
        sess = train.TrainSession(
            state, step, checkpoint_dir=d,
            hooks=[train.CheckpointHook(every_steps=3, every_secs=None),
                   NonfiniteGuardHook(max_consecutive=3),
                   train.StopAtStepHook(last_step=TARGET)])
        sess._chaos_ds = ds
        return sess

    def train_fn(sess):
        it = data.prefetch_to_device(iter(sess._chaos_ds.epochs(100)),
                                     size=2)
        for batch in it:
            if sess.should_stop():
                break
            sess.run_step(batch)
        return sess.state

    sup = Supervisor(max_restarts=3, backoff_base=0.01, registry=reg)
    final_state = sup.run(build_session, train_fn)

    assert int(final_state.step) == TARGET
    assert reg.get("dttpu_restarts_total").value >= 1
    assert reg.get("dttpu_faults_injected_total").value == 3
    plan = faults.active()
    assert {e["kind"] for e in plan.log} == {
        "corrupt_checkpoint", "nan_grads", "kill_prefetch"}
    # final params finite despite the poisoned step
    assert all(bool(jnp.all(jnp.isfinite(leaf)))
               for leaf in jax.tree.leaves(final_state.params))
    # the corrupted newest checkpoint (step 6, save #1) was quarantined
    # with its reason, and fallback resumed from step 3
    assert os.path.isdir(os.path.join(d, "corrupt-ckpt-0000000006"))
    # training then re-saved past the quarantined step
    assert train.checkpoint.latest_step(d) == TARGET


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_storm_long_run(tmp_path, activate_faults):
    """Storm tier: several faults of every train-side kind across a
    longer supervised run — the restart budget absorbs all of them."""
    reg = metrics_lib.Registry()
    d = str(tmp_path)
    TARGET = 40
    activate_faults({"kind": "save_oserror", "at": 2},
                    {"kind": "corrupt_checkpoint", "at": 4},
                    {"kind": "nan_grads", "at": 11},
                    {"kind": "poison_batch", "at": 17},
                    {"kind": "kill_prefetch", "at": 7},
                    {"kind": "kill_prefetch", "at": 26},
                    registry=reg)

    def build_session():
        state, step, ds = make_bits(device_health=True, skip_nonfinite=True)
        sess = train.TrainSession(
            state, step, checkpoint_dir=d,
            hooks=[train.CheckpointHook(every_steps=4, every_secs=None),
                   NonfiniteGuardHook(max_consecutive=3),
                   train.StopAtStepHook(last_step=TARGET)])
        sess._chaos_ds = ds
        return sess

    def train_fn(sess):
        it = data.prefetch_to_device(iter(sess._chaos_ds.epochs(1000)),
                                     size=2)
        for batch in it:
            if sess.should_stop():
                break
            sess.run_step(batch)
        return sess.state

    sup = Supervisor(max_restarts=6, backoff_base=0.01, registry=reg)
    final_state = sup.run(build_session, train_fn)
    assert int(final_state.step) == TARGET
    assert reg.get("dttpu_restarts_total").value >= 2   # 2 kills + OSError
    assert all(bool(jnp.all(jnp.isfinite(leaf)))
               for leaf in jax.tree.leaves(final_state.params))
