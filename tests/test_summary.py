"""Event-writer tests: the files must parse as valid TFRecord/Event streams
(reference tf.summary capability, example.py:160,164,172-174,219)."""
import glob
import struct

from distributed_tensorflow_tpu.summary import (SummaryWriter, crc32c,
                                                masked_crc32c)


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA


def read_records(path):
    with open(path, "rb") as f:
        blob = f.read()
    out = []
    off = 0
    while off < len(blob):
        (length,) = struct.unpack("<Q", blob[off:off + 8])
        (hc,) = struct.unpack("<I", blob[off + 8:off + 12])
        assert hc == masked_crc32c(blob[off:off + 8])
        payload = blob[off + 12:off + 12 + length]
        (pc,) = struct.unpack("<I", blob[off + 12 + length:off + 16 + length])
        assert pc == masked_crc32c(payload)
        out.append(payload)
        off += 16 + length
    return out


def parse_event(payload):
    """Minimal proto reader for the Event subset we emit."""
    fields = {}
    off = 0
    while off < len(payload):
        tag = payload[off]
        num, wire = tag >> 3, tag & 7
        off += 1
        if wire == 1:
            fields.setdefault(num, []).append(payload[off:off + 8])
            off += 8
        elif wire == 5:
            fields.setdefault(num, []).append(payload[off:off + 4])
            off += 4
        elif wire == 0:
            val = 0
            shift = 0
            while True:
                b = payload[off]
                off += 1
                val |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            fields.setdefault(num, []).append(val)
        elif wire == 2:
            ln = 0
            shift = 0
            while True:  # varint length (can exceed one byte)
                b = payload[off]
                off += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            fields.setdefault(num, []).append(payload[off:off + ln])
            off += ln
    return fields


def test_event_file_structure(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("loss", 0.5, 1)
    w.add_scalars({"accuracy": 0.9, "loss": 0.25}, 2)
    w.add_scalar("loss", 0.1, 2.5)  # fractional step -> floor
    w.close()

    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)
    assert len(records) == 4

    first = parse_event(records[0])
    assert first[3][0] == b"brain.Event:2"

    ev = parse_event(records[1])
    assert ev[2][0] == 1  # step
    summary = parse_event(ev[5][0])
    value = parse_event(summary[1][0])
    assert value[1][0] == b"loss"
    assert abs(struct.unpack("<f", value[2][0])[0] - 0.5) < 1e-7

    ev2 = parse_event(records[2])
    summary2 = parse_event(ev2[5][0])
    assert len(summary2[1]) == 2  # two scalar values in one event

    ev3 = parse_event(records[3])
    assert ev3[2][0] == 2  # fractional 2.5 floored


def test_negative_step_does_not_hang(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("loss", 1.0, -1)  # must terminate (two's-complement varint)
    w.close()
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(read_records(path)) == 2


def test_histograms_parse(tmp_path):
    import numpy as np
    w = SummaryWriter(str(tmp_path))
    vals = np.concatenate([np.zeros(10), np.ones(30), np.full(60, 2.0)])
    w.add_histogram("weights/w1", vals, step=3)
    w.add_histogram("constant", np.full(7, 5.0), step=3)  # degenerate range
    w.close()
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)[1:]
    assert len(records) == 2
    ev = parse_event(records[0])
    value = parse_event(parse_event(ev[5][0])[1][0])
    assert value[1][0] == b"weights/w1"
    assert 4 not in value  # image slot must stay empty
    histo = parse_event(value[5][0])  # Summary.Value.histo = field 5
    (mn,) = struct.unpack("<d", histo[1][0])
    (mx,) = struct.unpack("<d", histo[2][0])
    (num,) = struct.unpack("<d", histo[3][0])
    (total,) = struct.unpack("<d", histo[4][0])
    assert (mn, mx, num) == (0.0, 2.0, 100.0)
    assert total == vals.sum()
    # packed bucket arrays decode to matching lengths and full coverage
    limits = struct.unpack(f"<{len(histo[6][0])//8}d", histo[6][0])
    counts = struct.unpack(f"<{len(histo[7][0])//8}d", histo[7][0])
    assert len(limits) == len(counts) and sum(counts) == 100.0
    # degenerate histogram also parses
    ev2 = parse_event(records[1])
    v2 = parse_event(parse_event(ev2[5][0])[1][0])
    h2 = parse_event(v2[5][0])
    (n2,) = struct.unpack("<d", h2[3][0])
    assert n2 == 7.0
    lim2 = struct.unpack("<2d", h2[6][0])
    assert lim2[1] > lim2[0]  # strictly increasing even at huge magnitudes


def test_histogram_nonfinite_and_large_constant(tmp_path):
    import numpy as np
    w = SummaryWriter(str(tmp_path))
    w.add_histogram("has_nan", np.array([1.0, np.nan, 2.0, np.inf]), 1)
    w.add_histogram("big_const", np.full(7, 1e5), 1)
    w.close()
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)[1:]
    h = parse_event(parse_event(parse_event(records[0])[5][0])[1][0])
    histo = parse_event(h[5][0])
    (num,) = struct.unpack("<d", histo[3][0])
    assert num == 2.0  # only the finite values counted
    h2 = parse_event(parse_event(parse_event(records[1])[5][0])[1][0])
    histo2 = parse_event(h2[5][0])
    lims = struct.unpack("<2d", histo2[6][0])
    assert lims[1] > lims[0]


def test_image_summary_roundtrip(tmp_path):
    """Image events frame correctly and the embedded PNG decodes back to
    the original pixels (pure-zlib decode, no image library)."""
    import struct
    import zlib

    import numpy as np
    from distributed_tensorflow_tpu.data.tfrecord import read_tfrecord
    from distributed_tensorflow_tpu.summary.event_writer import (
        EventFileWriter, _png_encode)

    rgb = np.random.default_rng(0).integers(0, 256, (5, 7, 3), np.uint8)

    # PNG: decode our own encoding and compare pixels
    png = _png_encode(rgb)
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
    w, h = struct.unpack(">II", png[16:24])
    assert (w, h) == (7, 5)
    idat = png.index(b"IDAT")
    length = struct.unpack(">I", png[idat - 4:idat])[0]
    raw = zlib.decompress(png[idat + 4:idat + 4 + length])
    rows = [raw[i * (1 + 7 * 3) + 1:(i + 1) * (1 + 7 * 3)] for i in range(5)]
    decoded = np.frombuffer(b"".join(rows), np.uint8).reshape(5, 7, 3)
    np.testing.assert_array_equal(decoded, rgb)

    # float convention: [0,1] -> uint8
    png_f = _png_encode(rgb.astype(np.float32) / 255.0)
    assert png_f[:8] == b"\x89PNG\r\n\x1a\n"

    # the event record embeds the PNG and frames as a valid TFRecord stream
    d = str(tmp_path)
    with EventFileWriter(d) as w_:
        w_.add_image("samples/input", rgb, step=3)
        path = w_.path
    records = list(read_tfrecord(path))
    assert len(records) == 2  # version event + image event
    assert png in records[1]
    assert b"samples/input" in records[1]


def test_image_summary_integer_dtypes():
    import numpy as np
    from distributed_tensorflow_tpu.summary.event_writer import _png_encode
    a64 = np.full((4, 4, 3), 128, np.int64)
    b = _png_encode(a64)
    import struct, zlib
    idat = b.index(b"IDAT")
    length = struct.unpack(">I", b[idat - 4:idat])[0]
    raw = zlib.decompress(b[idat + 4:idat + 4 + length])
    # rows: filter byte + 12 pixel bytes; every pixel must be 128, not 255
    assert set(raw[1:13]) == {128}


def test_text_summary_roundtrip(tmp_path):
    """add_text emits a DT_STRING TensorProto routed to the text plugin."""
    from distributed_tensorflow_tpu.summary import EventFileWriter

    md = "## run config\n- lr: 1e-3\n- batch: 64"
    with EventFileWriter(str(tmp_path)) as w:
        w.add_text("notes", md, step=7)
    import glob
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)
    assert len(records) == 2  # version + text event
    event = parse_event(records[1])
    assert event[2] == [7]                       # step
    summary = parse_event(event[5][0])
    value = parse_event(summary[1][0])
    assert value[1] == [b"notes"]                # tag
    tensor = parse_event(value[8][0])
    assert tensor[1] == [7]                      # DT_STRING
    assert tensor[8] == [md.encode("utf-8")]     # string_val
    metadata = parse_event(value[9][0])
    plugin = parse_event(metadata[1][0])
    assert plugin[1] == [b"text"]                # plugin_name


def test_audio_summary_roundtrip(tmp_path):
    """add_audio emits a WAV-encoded Audio proto in Summary.Value field 6."""
    import numpy as np
    from distributed_tensorflow_tpu.summary import EventFileWriter

    t = np.linspace(0, 1, 16000, endpoint=False)
    tone = (0.5 * np.sin(2 * np.pi * 440 * t)).astype("float32")
    with EventFileWriter(str(tmp_path)) as w:
        w.add_audio("tone", tone, sample_rate=16000, step=3)
    import glob
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)
    event = parse_event(records[1])
    assert event[2] == [3]
    summary = parse_event(event[5][0])
    value = parse_event(summary[1][0])
    assert value[1] == [b"tone"]
    audio = parse_event(value[6][0])
    assert audio[2] == [1] and audio[3] == [16000]   # channels, frames
    assert audio[5] == [b"audio/wav"]
    wav = audio[4][0]
    assert wav[:4] == b"RIFF" and wav[8:12] == b"WAVE"
    # PCM data round-trips to ~the original samples
    pcm = np.frombuffer(wav[44:], dtype="<i2").astype(np.float64) / 32767.0
    np.testing.assert_allclose(pcm, tone, atol=1e-3)


def test_graph_event_roundtrip(tmp_path):
    """add_graph writes Event.graph_def (field 4) — the reference's
    writer.add_graph(sess.graph) channel (reference example.py:195) — as a
    GraphDef whose NodeDefs chain input -> layers in model order."""
    import glob

    from distributed_tensorflow_tpu import ops
    from distributed_tensorflow_tpu.summary import EventFileWriter

    model = ops.serial(ops.Dense(8, activation="relu"),
                       ops.Dropout(0.3),
                       ops.Dense(4))
    with EventFileWriter(str(tmp_path)) as w:
        w.add_graph(model)
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)
    event = parse_event(records[1])
    graph = parse_event(event[4][0])             # Event.graph_def
    nodes = [parse_event(n) for n in graph[1]]   # GraphDef.node
    names = [n[1][0].decode() for n in nodes]
    ops_ = [n[2][0].decode() for n in nodes]
    assert ops_[0] == "Placeholder"
    assert ops_[1:] == ["Dense", "Dropout", "Dense"]
    # the chain: every layer node's single input is the previous node
    for prev, node in zip(names, nodes[1:]):
        assert node[3] == [prev.encode()]
    # duplicate layer names are disambiguated
    assert len(set(names)) == len(names)
    # versions field present (TB graph plugin requirement)
    assert 4 in graph


def test_graph_event_sequential(tmp_path):
    """The TensorBoard callback's primary consumer is Sequential: fit with
    the callback must write a graph event reflecting model.layers (advisor
    round 2: Sequential previously lacked .layers and the event was
    silently swallowed)."""
    import glob

    import numpy as np

    from distributed_tensorflow_tpu import models, ops

    model = models.Sequential([ops.Dense(8, activation="relu"),
                               ops.Dense(2)])
    assert model.layers == model._layers   # Keras-parity property
    model.compile(loss="mse", optimizer="sgd")
    x = np.random.default_rng(0).random((16, 3)).astype(np.float32)
    y = np.random.default_rng(1).random((16, 2)).astype(np.float32)
    model.fit(x, y, epochs=1, batch_size=16, verbose=0,
              callbacks=[models.TensorBoard(str(tmp_path))])
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    records = read_records(path)
    graphs = [parse_event(r) for r in records if 4 in parse_event(r)]
    assert len(graphs) == 1
    graph = parse_event(graphs[0][4][0])
    nodes = [parse_event(n) for n in graph[1]]
    ops_ = [n[2][0].decode() for n in nodes]
    assert ops_ == ["Placeholder", "Dense", "Dense"]


def test_graph_event_explicit_nodes(tmp_path):
    """add_graph also takes explicit (name, op, inputs) tuples — the escape
    hatch for non-Sequential topologies (BERT/GPT blocks)."""
    import glob

    from distributed_tensorflow_tpu.summary import EventFileWriter

    nodes = [("tokens", "Placeholder", ()),
             ("embed", "Embedding", ("tokens",)),
             ("block0", "TransformerBlock", ("embed",)),
             ("head", "Dense", ("block0",))]
    with EventFileWriter(str(tmp_path)) as w:
        w.add_graph(nodes)
    (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    event = parse_event(read_records(path)[1])
    graph = parse_event(event[4][0])
    parsed = [parse_event(n) for n in graph[1]]
    assert [p[1][0] for p in parsed] == [b"tokens", b"embed", b"block0",
                                         b"head"]
    assert parsed[3][3] == [b"block0"]
