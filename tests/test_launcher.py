"""Supervised multi-host launcher (fleet/launcher.py).

The contracts pinned here (docs/RESILIENCE.md §launcher):

  * exit-code classification — 0 is done, ``LEGACY_PS_EXIT_CODE`` (64)
    is fatal WITH the misconfiguration named in the report (the old
    silent ps no-op ran fleets one host short), signal deaths and
    listed codes are transient, anything else is fatal;
  * transient exits restart with bounded seeded backoff: a host in
    backoff is NOT respawned before its due time, the budget exhausts
    into fatal, and the audit trail (``restart_log``, exit histories,
    ``dttpu_launcher_*``) records every decision;
  * chief re-election — the chief is the lowest-id live host; host 0's
    death moves the title and counts the election;
  * heartbeat liveness — a child whose heartbeat file goes stale past
    the timeout is killed (alive-but-stuck) and the kill classifies as
    a transient restart;
  * ``kill_host`` chaos (host site) SIGKILLs a supervised child at a
    deterministic poll index and the launcher restarts it;
  * the real-subprocess smoke: ``local_topology`` assembles the
    env-var topology ``parallel/cluster.py`` resolves, and a 2-host
    python child tree runs to clean completion under real
    ``subprocess.Popen``.
"""
import os
import sys
import time

import pytest

from distributed_tensorflow_tpu import fleet
from distributed_tensorflow_tpu.fleet import launcher as launcher_lib
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.parallel import cluster
from distributed_tensorflow_tpu.resilience import faults


class _FakeProc:
    """One fake child: returns None for ``polls_alive`` polls, then its
    exit code.  ``kill()`` forces a signal death immediately."""

    def __init__(self, rc=0, polls_alive=0):
        self._rc = rc
        self._alive = polls_alive
        self.killed = False

    def poll(self):
        if self.killed:
            return self._rc
        if self._alive > 0:
            self._alive -= 1
            return None
        return self._rc

    def kill(self):
        self.killed = True
        self._rc = -9

    def wait(self, timeout=None):
        return self.poll()


_FOREVER = 10 ** 9


class _Backend:
    """Injectable popen: per-host list of fake procs, consumed one per
    spawn (a missing entry runs forever)."""

    def __init__(self, script):
        self.script = {hid: list(procs) for hid, procs in script.items()}
        self.spawns = {hid: 0 for hid in script}

    def __call__(self, spec):
        hid = spec.host_id
        self.spawns[hid] = self.spawns.get(hid, 0) + 1
        seq = self.script.get(hid, [])
        if self.spawns[hid] <= len(seq):
            return seq[self.spawns[hid] - 1]
        return _FakeProc(rc=0, polls_alive=_FOREVER)


class _FakeTime:
    def __init__(self):
        self.now = 0.0

    def clock(self):
        return self.now

    def sleep(self, s):
        self.now += s


def _specs(n=2, env=None):
    return [fleet.HostSpec(host_id=i, argv=("true",), env=dict(env or {}))
            for i in range(n)]


def _launcher(backend, hosts=None, reg=None, **kw):
    ft = _FakeTime()
    kw.setdefault("jitter", 0.0)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("poll_interval_s", 0.01)
    lc = fleet.Launcher(hosts if hosts is not None else _specs(),
                        registry=reg or metrics_lib.Registry(),
                        popen=backend, sleep=ft.sleep, clock=ft.clock,
                        **kw)
    return lc, ft


# ---------------------------------------------------------------------------
# classification


def test_clean_completion():
    reg = metrics_lib.Registry()
    lc, _ = _launcher(_Backend({0: [_FakeProc(0)], 1: [_FakeProc(0)]}),
                      reg=reg)
    lc.start()
    assert lc.chief_id == 0
    assert lc.wait(timeout_s=10.0) is True
    assert lc.succeeded
    rep = lc.report()
    assert rep[0]["status"] == "done" and rep[1]["status"] == "done"
    assert rep[0]["reason"] == "completed"
    assert lc.elections == []    # draining to done is not an election
    assert reg.get("dttpu_launcher_restarts_total").value == 0
    assert reg.get("dttpu_launcher_fatal_total").value == 0


def test_legacy_ps_exit_is_fatal_with_reason():
    """Satellite: a legacy JOB_NAME=ps child exits 64 under the
    launcher (parallel/cluster.py) and the report NAMES the
    misconfiguration instead of counting a silent no-op as success."""
    reg = metrics_lib.Registry()
    lc, _ = _launcher(_Backend({
        0: [_FakeProc(0)],
        1: [_FakeProc(cluster.LEGACY_PS_EXIT_CODE)],
    }), reg=reg)
    lc.start()
    assert lc.wait(timeout_s=10.0) is True
    assert not lc.succeeded
    rep = lc.report()
    assert rep[1]["status"] == "fatal"
    assert "JOB_NAME=ps" in rep[1]["reason"]
    assert rep[1]["exit_history"] == [cluster.LEGACY_PS_EXIT_CODE]
    assert rep[1]["restarts"] == 0           # no restart-looping a
    #                                          role that cannot exist
    assert reg.get("dttpu_launcher_fatal_total").value == 1


def test_unknown_exit_code_is_fatal():
    lc, _ = _launcher(_Backend({0: [_FakeProc(3)], 1: [_FakeProc(0)]}))
    lc.start()
    assert lc.wait(timeout_s=10.0) is True
    rep = lc.report()
    assert rep[0]["status"] == "fatal"
    assert "exit code 3" in rep[0]["reason"]


def test_listed_transient_code_restarts():
    lc, _ = _launcher(
        _Backend({0: [_FakeProc(75), _FakeProc(0)], 1: [_FakeProc(0)]}),
        transient_exit_codes=(75,))
    lc.start()
    assert lc.wait(timeout_s=10.0) is True
    assert lc.succeeded
    assert lc.report()[0]["exit_history"] == [75, 0]


# ---------------------------------------------------------------------------
# restart discipline


def test_signal_death_restarts_with_backoff():
    """Two signal deaths, then success: each restart waits out its
    backoff (no respawn before due time), the audit trail records
    both, and the exit history is complete."""
    reg = metrics_lib.Registry()
    backend = _Backend({
        0: [_FakeProc(-9), _FakeProc(-15), _FakeProc(0)],
        1: [_FakeProc(0)],
    })
    lc, ft = _launcher(backend, reg=reg, backoff_base_s=1.0,
                       backoff_factor=2.0)
    lc.start()
    lc.poll()                                # classify the -9 death
    rep = lc.report()
    assert rep[0]["status"] == "backoff"
    assert rep[0]["restarts"] == 1
    assert backend.spawns[0] == 1            # in backoff, NOT respawned
    lc.poll()
    assert backend.spawns[0] == 1            # still before due time
    ft.now += 1.0                            # backoff_base elapses
    lc.poll()
    assert backend.spawns[0] == 2            # respawned on schedule
    assert lc.wait(timeout_s=60.0) is True
    assert lc.succeeded
    rep = lc.report()
    assert rep[0]["exit_history"] == [-9, -15, 0]
    assert rep[0]["restarts"] == 2
    assert [(h, a) for h, a, _ in rep[-1]["restart_log"]] == \
        [(0, 1), (0, 2)]
    assert reg.get("dttpu_launcher_restarts_total").value == 2


def test_restart_budget_exhausts_into_fatal():
    reg = metrics_lib.Registry()
    lc, _ = _launcher(
        _Backend({0: [_FakeProc(-9), _FakeProc(-9)], 1: [_FakeProc(0)]}),
        reg=reg, max_restarts=1)
    lc.start()
    assert lc.wait(timeout_s=10.0) is True
    rep = lc.report()
    assert rep[0]["status"] == "fatal"
    assert "restart budget exhausted" in rep[0]["reason"]
    assert rep[0]["restarts"] == 1
    assert reg.get("dttpu_launcher_fatal_total").value == 1


# ---------------------------------------------------------------------------
# chief election


def test_chief_reelection_on_host0_loss():
    reg = metrics_lib.Registry()
    lc, _ = _launcher(_Backend({
        0: [_FakeProc(1)],                   # fatal: chief dies
        1: [_FakeProc(0, polls_alive=_FOREVER)],
    }), reg=reg)
    lc.start()
    assert lc.chief_id == 0
    lc.poll()
    assert lc.chief_id == 1
    assert lc.elections == [(0, 1)]
    assert reg.get("dttpu_launcher_chief_elections_total").value == 1
    assert lc.report()[-1]["chief"] == 1
    lc.stop()


def test_restarting_chief_keeps_title():
    """A chief in backoff is still the fleet's host 0 (the topology
    env pins PROCESS_ID): its transient death is NOT an election."""
    lc, _ = _launcher(_Backend({
        0: [_FakeProc(-9), _FakeProc(0, polls_alive=_FOREVER)],
        1: [_FakeProc(0, polls_alive=_FOREVER)],
    }))
    lc.start()
    lc.poll()                                # host 0 into backoff
    assert lc.chief_id == 0 and lc.elections == []
    lc.stop()


# ---------------------------------------------------------------------------
# heartbeat liveness


def test_stale_heartbeat_kills_and_restarts(tmp_path):
    hb = tmp_path / "host0.hb"
    hb.write_text("")
    stale = time.time() - 100.0
    os.utime(hb, (stale, stale))
    reg = metrics_lib.Registry()
    hosts = [fleet.HostSpec(host_id=0, argv=("true",),
                            env={"DTTPU_HEARTBEAT_FILE": str(hb)})]
    backend = _Backend({0: [_FakeProc(0, polls_alive=_FOREVER),
                            _FakeProc(0, polls_alive=_FOREVER)]})
    lc, _ = _launcher(backend, hosts=hosts, reg=reg,
                      heartbeat_timeout_s=5.0)
    lc.start()
    lc.poll()                                # stale -> kill -> backoff
    assert reg.get("dttpu_launcher_heartbeat_missed_total").value == 1
    rep = lc.report()
    assert rep[0]["restarts"] == 1 and rep[0]["exit_history"] == [-9]
    os.utime(hb, None)                       # child ticks again
    lc._hosts[0].due_at = 0.0                # backoff due immediately
    lc.poll()                                # respawn
    assert backend.spawns[0] == 2
    lc.poll()                                # fresh heartbeat: healthy
    assert reg.get("dttpu_launcher_heartbeat_missed_total").value == 1
    lc.stop()


def test_missing_heartbeat_file_gets_grace(tmp_path):
    """No file yet (slow-starting child): the spawn-anchored grace
    window applies before the kill."""
    hb = tmp_path / "never.hb"
    hosts = [fleet.HostSpec(host_id=0, argv=("true",),
                            env={"DTTPU_HEARTBEAT_FILE": str(hb)})]
    lc, ft = _launcher(_Backend({0: [_FakeProc(0,
                                               polls_alive=_FOREVER)]}),
                       hosts=hosts, heartbeat_timeout_s=1.0,
                       heartbeat_grace_s=5.0)
    lc.start()
    lc.poll()
    assert lc.report()[0]["restarts"] == 0   # inside the grace window
    ft.now += 10.0                           # grace + timeout blown
    lc.poll()
    assert lc.report()[0]["restarts"] == 1
    lc.stop()


# ---------------------------------------------------------------------------
# chaos: kill_host at the launcher site


@pytest.mark.chaos
def test_kill_host_chaos_restarts_supervised_child():
    reg = metrics_lib.Registry()
    backend = _Backend({
        0: [_FakeProc(0, polls_alive=_FOREVER),
            _FakeProc(0, polls_alive=_FOREVER)],
        1: [_FakeProc(0, polls_alive=_FOREVER)],
    })
    lc, ft = _launcher(backend, reg=reg)
    plan = faults.FaultPlan(
        [{"kind": "kill_host", "at": 2, "replica": 0}],
        registry=metrics_lib.Registry())
    with faults.activated(plan):
        lc.start()
        for _ in range(3):                   # host:0 polls 0,1,2
            lc.poll()
        assert plan.log == [{"kind": "kill_host", "at": 2, "host": 0,
                             "poll": 2}]
        rep = lc.report()
        assert rep[0]["restarts"] == 1
        assert rep[0]["exit_history"] == [-9]
        assert rep[1]["restarts"] == 0       # only the targeted host
        ft.now += 1.0
        lc.poll()                            # backoff due: respawn
        assert backend.spawns[0] == 2
    assert reg.get("dttpu_launcher_restarts_total").value == 1
    lc.stop()                                # teardown reads as done
    assert all(d["status"] == "done"
               for h, d in lc.report().items() if h >= 0)


# ---------------------------------------------------------------------------
# topology + validation


def test_local_topology_env_assembly(tmp_path):
    specs = launcher_lib.local_topology(
        2, [sys.executable, "-c", "pass"], 12345,
        extra_env={"JAX_PLATFORMS": "cpu"},
        heartbeat_dir=str(tmp_path))
    assert [s.host_id for s in specs] == [0, 1]
    for hid, s in enumerate(specs):
        assert s.env["COORDINATOR_ADDRESS"] == "localhost:12345"
        assert s.env["NUM_PROCESSES"] == "2"
        assert s.env["PROCESS_ID"] == str(hid)
        assert s.env["DTTPU_LAUNCHER"] == "1"
        assert s.env["JAX_PLATFORMS"] == "cpu"
        assert s.env["DTTPU_HEARTBEAT_FILE"].endswith(f"host{hid}.hb")
    # the assembled env resolves to the topology cluster_from_env reads
    cfg = cluster.cluster_from_env(environ=specs[1].env)
    assert cfg.distributed and cfg.process_id == 1
    assert cfg.num_processes == 2


def test_empty_and_duplicate_hosts_raise():
    with pytest.raises(ValueError, match="at least one"):
        fleet.Launcher([])
    with pytest.raises(ValueError, match="duplicate host ids"):
        fleet.Launcher([fleet.HostSpec(0, ("true",)),
                        fleet.HostSpec(0, ("true",))])


def test_heartbeat_helper_touches_file(tmp_path):
    hb = tmp_path / "h.hb"
    launcher_lib.heartbeat(environ={})       # unset: no-op, no file
    assert not hb.exists()
    launcher_lib.heartbeat(environ={"DTTPU_HEARTBEAT_FILE": str(hb)})
    assert hb.exists()
    old = time.time() - 50.0
    os.utime(hb, (old, old))
    launcher_lib.heartbeat(environ={"DTTPU_HEARTBEAT_FILE": str(hb)})
    assert time.time() - os.path.getmtime(hb) < 10.0


# ---------------------------------------------------------------------------
# real subprocesses


def test_real_two_host_tree_completes():
    """Real ``subprocess.Popen`` smoke: two python children read the
    launcher-assembled topology env, heartbeat once, and exit clean —
    the supervised bring-up the CI smoke job scales up."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = (
        "import os, sys; sys.path.insert(0, os.environ['DTTPU_REPO']); "
        "from distributed_tensorflow_tpu.fleet import launcher; "
        "assert os.environ['DTTPU_LAUNCHER'] == '1'; "
        "launcher.heartbeat(); "
        "sys.exit(int(os.environ['PROCESS_ID']) * 0)")
    specs = launcher_lib.local_topology(
        2, [sys.executable, "-c", child], 23456,
        extra_env={"DTTPU_REPO": repo, "JAX_PLATFORMS": "cpu"})
    lc = fleet.Launcher(specs, registry=metrics_lib.Registry(),
                        poll_interval_s=0.02)
    lc.start()
    try:
        assert lc.wait(timeout_s=60.0) is True
    finally:
        lc.stop()
    assert lc.succeeded, lc.report()


def test_real_child_killed_by_signal_restarts():
    """A child that SIGKILLs itself is a transient death under real
    Popen; the respawned incarnation completes."""
    marker_env = "DTTPU_TEST_MARKER_DIR"
    child = (
        "import os, signal; "
        "d = os.environ['%s']; "
        "p = os.path.join(d, 'spawned' + os.environ['PROCESS_ID']); "
        "n = int(open(p).read()) if os.path.exists(p) else 0; "
        "open(p, 'w').write(str(n + 1)); "
        "os.kill(os.getpid(), signal.SIGKILL) if n == 0 else None"
        % marker_env)
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        specs = launcher_lib.local_topology(
            1, [sys.executable, "-c", child], 34567,
            extra_env={marker_env: d})
        lc = fleet.Launcher(specs, registry=metrics_lib.Registry(),
                            backoff_base_s=0.02, poll_interval_s=0.02)
        lc.start()
        try:
            assert lc.wait(timeout_s=60.0) is True
        finally:
            lc.stop()
        assert lc.succeeded, lc.report()
        assert lc.report()[0]["restarts"] == 1
        assert open(os.path.join(d, "spawned0")).read() == "2"
