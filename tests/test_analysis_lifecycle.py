"""dtlint lifecycle tier (DT6xx) + the runtime leak ledger.

Static half: one planted / fixed-twin / suppression triple per rule
DT601-DT605, the ownership-transfer exemptions (stored on self,
returned, handed off, passed to a releasing callee), the typestate
shapes the engine had to learn from the real scheduler (guarded
``acquire()`` results, timeout acquires, acquire-raise edges, except
handlers), the ``--rules`` selector, the tier cache key, and the
zero-findings self-check over the real package.

Runtime half: ``ResourceLedger`` balance semantics (idempotent second
release is not a release, a release finding no pin is an over-release,
handoff counts through its internal release), the
``@pytest.mark.resource_ledger`` fixture, the satellite regression for
the ``_begin_prefill`` unwind, and the chaos acceptances — a fault
storm through a paged+LoRA engine and a kill_replica migration, both
required to finish with lease/pin traffic exactly balanced.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax

from distributed_tensorflow_tpu import analysis, fleet, serve
from distributed_tensorflow_tpu.analysis import cli as cli_mod
from distributed_tensorflow_tpu.analysis.callgraph import Project
from distributed_tensorflow_tpu.analysis.leak_ledger import (
    LedgerImbalance, ResourceLedger)
from distributed_tensorflow_tpu.analysis.lifecycle import PROTOCOLS
from distributed_tensorflow_tpu.analysis.lifecycle_rules import (
    LIFECYCLE_RULES, run_lifecycle_rules)
from distributed_tensorflow_tpu.analysis.report import Severity
from distributed_tensorflow_tpu.analysis.walker import Source
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.serve import pages as pages_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint(code, mod="m"):
    src = Source(mod.replace(".", "/") + ".py", textwrap.dedent(code))
    return run_lifecycle_rules(Project.from_sources({mod: src}))


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# DT601: leak on an exception/early-return path


def test_dt601_exception_path_leaks_lease():
    fs = lint("""
        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)
            decode(req)          # may raise -> lease leaked
            pool.release(lease)
    """)
    assert rules_of(fs) == ["DT601"]
    (f,) = fs
    # anchored at the acquire, where the fix (try/finally) goes
    assert f.line == 3 and f.severity is Severity.ERROR
    assert "page lease" in f.message and "leaked" in f.message


def test_dt601_fixed_twin_try_finally():
    assert lint("""
        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)
            try:
                decode(req)
            finally:
                pool.release(lease)
    """) == []


def test_dt601_fixed_twin_handler_releases_and_reraises():
    assert lint("""
        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)
            try:
                decode(req)
            except Exception:
                pool.release(lease)
                raise
            pool.release(lease)
    """) == []


def test_dt601_early_return_leaks():
    assert rules_of(lint("""
        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)
            if req.bad:
                return None
            pool.release(lease)
    """)) == ["DT601"]


def test_dt601_transfer_stored_on_self_is_silent():
    assert lint("""
        def admit(self, pool, req):
            lease = pool.begin(req.rid, need=4)
            self.lease = lease
    """) == []


def test_dt601_transfer_returned_is_silent():
    assert lint("""
        def admit(pool, req):
            lease = pool.begin(req.rid, need=4)
            return lease
    """) == []


def test_dt601_handoff_transfers_but_earlier_call_edge_still_leaks():
    # handoff alone is a clean transfer; a raising call BETWEEN begin
    # and handoff still strands the lease on that edge
    assert lint("""
        def publish(pool, req, toks):
            lease = pool.begin(req.rid, need=4)
            pool.handoff(lease, toks)
    """) == []
    assert rules_of(lint("""
        def publish(pool, req, toks):
            lease = pool.begin(req.rid, need=4)
            decode(req)
            pool.handoff(lease, toks)
    """)) == ["DT601"]


def test_dt601_releasing_callee_summary_is_silent():
    assert lint("""
        def cleanup(pool, lease):
            pool.release(lease)

        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)
            cleanup(pool, lease)
    """) == []


def test_dt601_second_acquire_raising_leaks_the_first():
    # the acquire call itself is an exception edge: if the second
    # begin() raises (pool exhausted), the first lease is stranded
    assert rules_of(lint("""
        def admit_pair(pool, a, b):
            la = pool.begin(a.rid, need=4)
            lb = pool.begin(b.rid, need=4)
            pool.release(la)
            pool.release(lb)
    """)) == ["DT601"]


def test_dt601_suppression():
    assert lint("""
        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)  # dtlint: disable=DT601 -- transferred via side table
            decode(req)
            pool.release(lease)
    """) == []


# ---------------------------------------------------------------------------
# DT602: use-after-release / double release of a non-idempotent protocol


def test_dt602_double_release_non_idempotent_pin():
    fs = lint("""
        def drop(adapters, aid):
            adapters.acquire(aid)
            adapters.release(aid)
            adapters.release(aid)
    """)
    assert rules_of(fs) == ["DT602"]
    assert fs[0].line == 5          # anchored at the offending release


def test_dt602_idempotent_double_release_is_silent():
    # PagePool.release is declared idempotent in the protocol registry
    assert lint("""
        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)
            pool.release(lease)
            pool.release(lease)
    """) == []


def test_dt602_fires_inside_except_handler():
    # handler entry includes the post-release state of the try body
    assert rules_of(lint("""
        def drop(adapters, aid):
            adapters.acquire(aid)
            adapters.release(aid)
            try:
                flush()
            except Exception:
                adapters.release(aid)
                raise
    """)) == ["DT602"]


def test_dt602_suppression():
    assert lint("""
        def drop(adapters, aid):
            adapters.acquire(aid)
            adapters.release(aid)
            adapters.release(aid)  # dtlint: disable=DT602 -- table tolerates it
    """) == []


# ---------------------------------------------------------------------------
# DT603: bare lock acquire without release on every path


def test_dt603_bare_lock_early_return():
    fs = lint("""
        def pump(self):
            self._lock.acquire()
            if self.closed:
                return
            self._lock.release()
    """)
    assert rules_of(fs) == ["DT603"]
    assert fs[0].severity is Severity.WARNING


def test_dt603_fixed_twin_try_finally():
    assert lint("""
        def pump(self):
            self._lock.acquire()
            try:
                if self.closed:
                    return
            finally:
                self._lock.release()
    """) == []


def test_dt603_with_lock_is_silent():
    assert lint("""
        def pump(self):
            with self._lock:
                step(self)
    """) == []


def test_dt603_split_acquire_release_api_is_silent():
    # no matching release anywhere in the function (an __enter__ half
    # of a split API): the consistency gate keeps the tier quiet
    assert lint("""
        def __enter__(self):
            self._lock.acquire()
            return self
    """) == []


def test_dt603_guarded_acquire_result_shape():
    # the scheduler's export shape: the acquire RESULT is a guard, not
    # an alias of the lock; if-gated release on the guard is clean
    assert lint("""
        def export(self, rid):
            clean = self._lock.acquire()
            try:
                return self._do_export(rid, clean)
            finally:
                if clean:
                    self._lock.release()
    """) == []


def test_dt603_timeout_guard_acquire_shape():
    # export_all: acquire(timeout=...) may fail; only the guard-true
    # branch holds, so releasing under the guard covers every path
    assert lint("""
        def export_all(self, timeout_s):
            clean = self._lock.acquire(timeout=timeout_s)
            try:
                return [self._do_export(r, clean) for r in self._live()]
            finally:
                if clean:
                    self._lock.release()
    """) == []


def test_dt603_suppression():
    assert lint("""
        def pump(self):
            self._lock.acquire()  # dtlint: disable=DT603 -- released by the watchdog
            if self.closed:
                return
            self._lock.release()
    """) == []


# ---------------------------------------------------------------------------
# DT604: resource held across a yield / into an un-shimmed callback


def test_dt604_lease_held_across_yield():
    fs = lint("""
        def stream(pool, req):
            lease = pool.begin(req.rid, need=4)
            try:
                for tok in decode(req):
                    yield tok
            finally:
                pool.release(lease)
    """)
    assert rules_of(fs) == ["DT604"]
    assert fs[0].severity is Severity.WARNING


def test_dt604_contextmanager_exempt():
    assert lint("""
        import contextlib

        @contextlib.contextmanager
        def leased(pool, req):
            lease = pool.begin(req.rid, need=4)
            try:
                yield lease
            finally:
                pool.release(lease)
    """) == []


def test_dt604_shimmed_callback_is_silent():
    # callback inside a try with handlers: a raise is caught and the
    # lease released — that is the shim the rule asks for
    assert lint("""
        def serve(self, pool, req):
            lease = pool.begin(req.rid, need=4)
            try:
                self.on_token(req)
                pool.release(lease)
            except Exception:
                pool.release(lease)
                raise
    """) == []


def test_dt604_unshimmed_callback_in_finally():
    # the callback runs un-shimmed while the lease is held (DT604) and
    # its raise strands the lease before the release line (DT601)
    assert rules_of(lint("""
        def serve(self, pool, req):
            lease = pool.begin(req.rid, need=4)
            try:
                step(req)
            finally:
                self.on_token(req)
                pool.release(lease)
    """)) == ["DT601", "DT604"]


def test_dt604_suppression():
    assert lint("""
        def stream(pool, req):
            lease = pool.begin(req.rid, need=4)
            try:
                for tok in decode(req):
                    yield tok  # dtlint: disable=DT604 -- consumer owns the generator
            finally:
                pool.release(lease)
    """) == []


# ---------------------------------------------------------------------------
# DT605: protocol-order violations


def test_dt605_register_after_release():
    fs = lint("""
        def publish(pool, req, toks):
            lease = pool.begin(req.rid, need=4)
            pool.release(lease)
            pool.register(lease, toks)
    """)
    assert rules_of(fs) == ["DT605"]
    # anchored at the offending op, not the acquire
    assert fs[0].line == 5 and fs[0].severity is Severity.ERROR


def test_dt605_terminal_recancel():
    assert rules_of(lint("""
        def abort(engine, rid):
            handle = engine.submit(rid)
            handle.cancel()
            handle.cancel()
    """)) == ["DT605"]


def test_dt605_suppression():
    assert lint("""
        def publish(pool, req, toks):
            lease = pool.begin(req.rid, need=4)
            pool.release(lease)
            pool.register(lease, toks)  # dtlint: disable=DT605 -- registry replays idempotently
    """) == []


# ---------------------------------------------------------------------------
# shared shapes


def test_with_lease_auto_releases():
    assert lint("""
        def serve(pool, req):
            with pool.begin(req.rid, need=4) as lease:
                decode(req)
    """) == []


def test_loop_release_then_reacquire_no_false_storm():
    assert lint("""
        def serve(pool, reqs):
            for req in reqs:
                lease = pool.begin(req.rid, need=4)
                try:
                    decode(req)
                finally:
                    pool.release(lease)
    """) == []


def test_begin_prefill_unwind_shape_is_clean():
    # the fixed scheduler admission shape: pin stored on the request
    # (ownership transferred to the retire path), broad unwind releases
    # the lease and the pin on ANY failure, then re-raises
    assert lint("""
        def begin_prefill(self, req):
            req.adapter_row = self.adapters.acquire(req.adapter_id)
            try:
                lease = self.pages.begin(req.ctx, req.total)
                req.lease = lease
                return [req, lease]
            except BaseException:
                if req.lease is not None:
                    self.pages.release(req.lease)
                self.adapters.release(req.adapter_id)
                raise
    """) == []


def test_lifecycle_rule_catalog_ids_and_severities():
    assert [r for r, _, _ in LIFECYCLE_RULES] == [
        "DT601", "DT602", "DT603", "DT604", "DT605"]
    ids = [rid for rid, _, _ in analysis.full_rule_catalog()]
    assert ids[-5:] == ["DT601", "DT602", "DT603", "DT604", "DT605"]


def test_protocol_registry_names():
    assert {p.name for p in PROTOCOLS} == {
        "page lease", "adapter pin", "lock", "request handle"}


# ---------------------------------------------------------------------------
# --rules selection


def test_expand_rules_exact_wildcard_case_and_unknown():
    expand = cli_mod._expand_rules
    assert expand(None) is None and expand("") is None
    assert expand("DT601") == {"DT601"}
    assert expand("dt601, dt303") == {"DT601", "DT303"}
    assert expand("DT6xx") == {"DT601", "DT602", "DT603", "DT604",
                               "DT605"}
    assert expand("dt6XX,DT101") == {"DT601", "DT602", "DT603",
                                     "DT604", "DT605", "DT101"}
    for tier in ("DT1xx", "DT2xx", "DT3xx", "DT4xx", "DT5xx"):
        assert expand(tier), tier
    with pytest.raises(ValueError, match="unknown rule"):
        expand("DT999")
    with pytest.raises(ValueError, match="unknown tier"):
        expand("DT9xx")


MIXED_TIER_SRC = """
import threading

def fire(work):
    t = threading.Thread(target=work, name="w", daemon=True)
    t.start()

def serve(pool, req):
    lease = pool.begin(req.rid, need=4)
    decode(req)
    pool.release(lease)
"""


def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "distributed_tensorflow_tpu.analysis",
         *argv], capture_output=True, text=True, cwd=REPO)


def test_cli_rules_filter_narrows_across_tiers(tmp_path):
    f = tmp_path / "mixed.py"
    f.write_text(MIXED_TIER_SRC)
    base = (str(f), "--no-cache", "--format", "json")

    proc = _run_cli(*base)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    both = {x["rule"] for x in json.loads(proc.stdout)["findings"]}
    assert both == {"DT305", "DT601"}

    proc = _run_cli(*base, "--rules", "DT601")
    assert {x["rule"] for x in json.loads(proc.stdout)["findings"]} \
        == {"DT601"}

    proc = _run_cli(*base, "--rules", "dt3xx")       # case-insensitive
    assert {x["rule"] for x in json.loads(proc.stdout)["findings"]} \
        == {"DT305"}


def test_cli_rules_unknown_id_exits_2(tmp_path):
    f = tmp_path / "x.py"
    f.write_text("x = 1\n")
    proc = _run_cli(str(f), "--no-cache", "--rules", "DT777")
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr + proc.stdout


def test_cli_no_lifecycle_flag_drops_tier(tmp_path):
    f = tmp_path / "leak.py"
    f.write_text(textwrap.dedent("""
        def serve(pool, req):
            lease = pool.begin(req.rid, need=4)
            decode(req)
            pool.release(lease)
    """))
    proc = _run_cli(str(f), "--no-cache", "--format", "json")
    assert proc.returncode == 1
    assert [x["rule"] for x in json.loads(proc.stdout)["findings"]] \
        == ["DT601"]
    proc = _run_cli(str(f), "--no-cache", "--format", "json",
                    "--no-lifecycle")
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["count"] == 0


def test_cli_timings_include_lifecycle_tier(tmp_path):
    f = tmp_path / "x.py"
    f.write_text("x = 1\n")
    proc = _run_cli(str(f), "--no-cache", "--timings")
    assert proc.returncode == 0
    assert "lifecycle (DT6xx)" in proc.stderr


# ---------------------------------------------------------------------------
# tier cache


class TestLifecycleTierCache:
    """Cold run computes, warm run hits, an edited file re-runs the
    tier (full-tree key: the typestate walk is interprocedural)."""

    def _setup(self, tmp_path, monkeypatch):
        d = tmp_path / "pkg"
        d.mkdir()
        (d / "clean.py").write_text("x = 1\n")
        monkeypatch.setenv("DTLINT_CACHE_DIR", str(tmp_path / "cache"))
        calls = {"life": 0}
        real = cli_mod.run_lifecycle_rules

        def counted(*a, **kw):
            calls["life"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(cli_mod, "run_lifecycle_rules", counted)
        return d, calls

    def test_cold_warm_and_file_edit_invalidation(self, tmp_path,
                                                  monkeypatch):
        d, calls = self._setup(tmp_path, monkeypatch)
        cat = analysis.full_rule_catalog()

        assert analysis.analyze_paths(
            [str(d)], cache=analysis.ResultCache(catalog=cat)) == []
        assert calls["life"] == 1

        assert analysis.analyze_paths(
            [str(d)], cache=analysis.ResultCache(catalog=cat)) == []
        assert calls["life"] == 1          # warm: tier cache hit

        (d / "clean.py").write_text("x = 2\n")
        analysis.analyze_paths(
            [str(d)], cache=analysis.ResultCache(catalog=cat))
        assert calls["life"] == 2          # tree changed: recompute

    def test_no_lifecycle_pass_skips_tier(self, tmp_path, monkeypatch):
        d, calls = self._setup(tmp_path, monkeypatch)
        cat = analysis.full_rule_catalog()
        analysis.analyze_paths(
            [str(d)], lifecycle_pass=False,
            cache=analysis.ResultCache(catalog=cat))
        assert calls["life"] == 0


# ---------------------------------------------------------------------------
# self-check: the real package is clean, with no unjustified escapes


def test_dt6xx_clean_on_real_package():
    """The tier's findings on the repo itself were triaged to zero: the
    scheduler/pages/adapters release discipline is the proof surface.
    A regression here is a real leak (or an engine false positive) —
    either way it blocks."""
    files = analysis.collect_files(
        [os.path.join(REPO, "distributed_tensorflow_tpu")])
    project = analysis.Project.from_sources({
        analysis.module_name_for(os.path.relpath(p, REPO)):
            analysis.Source(p, open(p, encoding="utf-8").read())
        for p in files})
    findings = run_lifecycle_rules(project)
    assert findings == [], [(f.rule, f.path, f.line, f.message)
                            for f in findings]


def test_no_dt6xx_suppressions_in_package():
    out = subprocess.run(
        ["grep", "-rn", r"dtlint: disable=DT60[1-5]",
         os.path.join(REPO, "distributed_tensorflow_tpu")],
        capture_output=True, text=True)
    assert out.stdout == "", \
        f"unexpected DT6xx suppressions:\n{out.stdout}"


def test_lifecycle_model_sees_serve_protocol_traffic():
    """The typestate walk must actually visit the serve tier's
    acquire/release sites — if the prescan gate ever skips them, the
    clean self-check above means nothing."""
    from distributed_tensorflow_tpu.analysis.lifecycle import (
        LifecycleModel)
    serve_dir = os.path.join(REPO, "distributed_tensorflow_tpu",
                             "serve")
    files = analysis.collect_files([serve_dir])
    project = analysis.Project.from_sources({
        analysis.module_name_for(os.path.relpath(p, REPO)):
            analysis.Source(p, open(p, encoding="utf-8").read())
        for p in files})
    model = LifecycleModel(project, PROTOCOLS)
    walked = {q.rsplit(".", 1)[-1] for (_, q) in model.walked}
    for expect in ("_begin_prefill", "_retire_accounting", "export",
                   "export_all"):
        assert expect in walked, sorted(walked)


# ---------------------------------------------------------------------------
# ResourceLedger unit semantics


def _pool(**kw):
    kw.setdefault("num_pages", 8)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 4)
    return pages_lib.PagePool(**kw)


def _ctx(n=6, seed=0):
    return np.arange(seed, seed + n, dtype=np.int32)


def test_ledger_balanced_pages_extent():
    with ResourceLedger(track=("pages",)) as led:
        pool = _pool()
        lease = pool.begin(_ctx(), 8)
        pool.release(lease)
        pool.release(lease)            # idempotent: NOT a second credit
    assert led.counts() == {"pages.begin": 1, "pages.release": 1}


def test_ledger_detects_leaked_lease_and_gauge_drift():
    with pytest.raises(LedgerImbalance) as err:
        with ResourceLedger(track=("pages",)):
            pool = _pool()
            pool.begin(_ctx(), 8)      # never released
    msg = str(err.value)
    assert "page leases: 1 acquired vs 0 released" in msg
    assert "_lease_count 0 -> 1" in msg
    assert "traffic:" in msg


def test_ledger_handoff_counts_as_release():
    ctx = _ctx(8)
    with ResourceLedger(track=("pages",)) as led:
        pool = _pool()
        lease = pool.begin(ctx, 8)
        pool.handoff(lease, ctx)       # register + release internally
    c = led.counts()
    assert c["pages.handoff"] == 1
    assert c["pages.begin"] == c["pages.release"] == 1


@pytest.fixture(scope="module")
def adapter_table():
    from distributed_tensorflow_tpu.serve.adapters import AdapterTable
    model = gpt_tiny(dropout_rate=0.0)
    table = AdapterTable(model, capacity=2, rank=2)
    table.register("tuned", model.init_lora(jax.random.PRNGKey(0),
                                            rank=2))
    return table


def test_ledger_books_adapter_over_release(adapter_table):
    with pytest.raises(LedgerImbalance) as err:
        with ResourceLedger(track=("adapters",)) as led:
            adapter_table.acquire("tuned")
            adapter_table.release("tuned")
            adapter_table.release("tuned")   # finds no pin
    assert "release(s) found no pin" in str(err.value)
    assert led.counts()["adapters.over_release"] == 1


def test_ledger_adapter_none_id_is_not_traffic(adapter_table):
    with ResourceLedger(track=("adapters",)) as led:
        assert adapter_table.acquire(None) == 0
        adapter_table.release(None)
    assert led.counts() == {}


def test_ledger_extents_cannot_nest():
    with ResourceLedger(track=("pages",)):
        with pytest.raises(RuntimeError, match="nest"):
            with ResourceLedger(track=("pages",)):
                pass


def test_ledger_stays_silent_when_body_raises():
    # the imbalance report must never mask the test's own failure
    with pytest.raises(RuntimeError, match="real failure"):
        with ResourceLedger(track=("pages",)):
            pool = _pool()
            pool.begin(_ctx(), 8)      # leaked, but the raise wins
            raise RuntimeError("real failure")


def test_ledger_restores_class_methods_on_exit():
    orig = (pages_lib.PagePool.begin, pages_lib.PagePool.release,
            pages_lib.PagePool.handoff)
    with ResourceLedger(track=("pages",)):
        assert pages_lib.PagePool.begin is not orig[0]
    assert (pages_lib.PagePool.begin, pages_lib.PagePool.release,
            pages_lib.PagePool.handoff) == orig


def test_ledger_rejects_unknown_surface():
    with pytest.raises(ValueError, match="unknown ledger surface"):
        ResourceLedger(track=("pages", "filehandles"))


def test_ledger_untracked_surface_is_ignored():
    with ResourceLedger(track=("goodput",)):
        pool = _pool()
        pool.begin(_ctx(), 8)          # pages surface not instrumented


@pytest.mark.resource_ledger(track=("pages",))
def test_resource_ledger_marker_wraps_test_body(request):
    ledger = request.node.resource_ledger
    assert isinstance(ledger, ResourceLedger)
    assert ledger.track == ("pages",)
    pool = _pool()
    lease = pool.begin(_ctx(), 8)
    pool.release(lease)
    assert ledger.counts()["pages.begin"] == 1
    # teardown re-checks balance; this extent is balanced


# ---------------------------------------------------------------------------
# satellite regression: _begin_prefill unwinds on ANY admission failure


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _adapter(model, seed, rank=4, scale=0.3):
    ad = model.init_lora(jax.random.PRNGKey(seed), rank=rank)
    for t in model._LORA_TARGETS:
        ad[t]["b"] = scale * jax.random.normal(
            jax.random.PRNGKey(seed + 1), ad[t]["b"].shape)
    return ad


def test_begin_prefill_unwinds_pin_when_page_begin_fails_hard():
    """A non-transient begin() failure (ValueError, not exhaustion)
    used to strand the adapter pin: the old unwind only covered
    PagePoolExhausted.  The broad unwind must release it and leave no
    lease born."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       adapter_capacity=1, adapter_rank=4,
                       registry=metrics_lib.Registry())
    eng.load_adapter("tuned", _adapter(model, seed=3))

    def boom(prompt, total_cols):
        raise ValueError("synthetic admission failure after the pin")

    eng.scheduler.pages.begin = boom
    eng.submit(_prompt(5), 4, adapter_id="tuned")
    with pytest.raises(ValueError, match="synthetic"):
        eng.step()
    assert eng.adapters._refs == {}                 # pin unwound
    assert eng.scheduler.pages._lease_count == 0    # nothing leaked


def test_begin_prefill_unwinds_pin_when_cache_init_fails(monkeypatch):
    """Contiguous-mode twin: a failure AFTER the pin in the kv-cache
    init path (first admission, empty prefill pool) must unwind the
    pin before propagating."""
    from distributed_tensorflow_tpu.serve import scheduler as sched_mod
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2, paged=False,
                       adapter_capacity=1, adapter_rank=4,
                       registry=metrics_lib.Registry())
    eng.load_adapter("tuned", _adapter(model, seed=3))

    def boom(kv):
        raise RuntimeError("synthetic cache-init failure")

    eng.scheduler._pf_pool.clear()      # force the init_cache path
    monkeypatch.setattr(sched_mod.slots_lib, "strip_pos", boom)
    eng.submit(_prompt(5), 4, adapter_id="tuned")
    with pytest.raises(RuntimeError, match="cache-init"):
        eng.step()
    assert eng.adapters._refs == {}


# ---------------------------------------------------------------------------
# chaos acceptance: fault storms under the ledger must balance exactly


@pytest.mark.chaos
@pytest.mark.resource_ledger
def test_chaos_storm_lease_and_pin_traffic_balances(request,
                                                    activate_faults):
    """THE DT6xx runtime acceptance: a paged+LoRA engine under a fault
    storm (two targeted decode failures + a stalled tick) retires every
    request — ok or failed — with lease/pin traffic exactly balanced.
    The marker fixture re-asserts balance (and pool/table gauge return)
    at teardown; an imbalance fails the test with the per-resource
    table."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       adapter_capacity=2, adapter_rank=4,
                       registry=metrics_lib.Registry())
    eng.load_adapter("a", _adapter(model, seed=3))
    eng.load_adapter("b", _adapter(model, seed=7))
    activate_faults({"kind": "fail_decode", "at": 1},
                    {"kind": "fail_decode", "at": 3},
                    {"kind": "stall_tick", "at": 2, "seconds": 0.02})
    hs = [eng.submit(_prompt(4 + i % 3, seed=i), 5,
                     adapter_id=("a", "b", None)[i % 3])
          for i in range(6)]
    eng.drain()
    assert sorted(h.status for h in hs) == ["failed"] * 2 + ["ok"] * 4

    c = request.node.resource_ledger.counts()
    assert c["pages.begin"] >= 6               # every admission leased
    assert c["pages.begin"] == c["pages.release"]
    assert c["adapters.acquire"] == c["adapters.release"]
    assert "adapters.over_release" not in c


@pytest.mark.chaos
@pytest.mark.resource_ledger
def test_kill_replica_migration_balances_lease_traffic(request,
                                                       activate_faults):
    """Killing a replica mid-traffic exports its in-flight work
    (handoff: publish-then-release) and re-admits it on the survivor
    (fresh leases) — the whole migration must net to zero held pages
    and every handle still completes."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    router = fleet.Router(
        [serve.Engine(model, params, num_slots=2, max_len=64,
                      prefill_chunk=4, tick_steps=2, page_size=8,
                      registry=reg) for _ in range(2)],
        registry=reg)
    activate_faults({"kind": "kill_replica", "at": 2, "replica": 1})
    hs = [router.submit(_prompt(3 + i % 3, seed=i), 6,
                        deadline_s=120.0) for i in range(6)]
    router.step()
    assert router.drain(timeout_s=120)
    for h in hs:
        assert h.status == "ok", (h.status, h.error)

    c = request.node.resource_ledger.counts()
    assert c["pages.begin"] == c["pages.release"]
    assert c["pages.begin"] > 6     # migration re-admissions leased anew
