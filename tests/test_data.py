"""Data subsystem tests (reference example.py:24-48 capability + pipeline)."""
import numpy as np

from distributed_tensorflow_tpu import data


def test_xor_labels_correct():
    (x, y), (xv, yv) = data.xor_data(100, val_size=10, seed=3)
    assert x.shape == (100, 64) and y.shape == (100, 32)
    assert xv.shape == (10, 64) and yv.shape == (10, 32)
    np.testing.assert_array_equal(
        y, np.bitwise_xor(x[:, :32].astype(int), x[:, 32:].astype(int)))
    assert set(np.unique(x)) <= {0.0, 1.0}


def test_xor_deterministic():
    a = data.xor_data(50, seed=7)
    b = data.xor_data(50, seed=7)
    np.testing.assert_array_equal(a[0][0], b[0][0])
    c = data.xor_data(50, seed=8)
    assert not np.array_equal(a[0][0], c[0][0])


def test_dataset_batching_and_shuffle():
    x = np.arange(100).reshape(100, 1).astype(np.float32)
    y = np.arange(100).astype(np.float32)
    ds = data.Dataset([x, y], batch_size=32, seed=0)
    batches = list(ds)
    assert len(batches) == 3  # drop_remainder
    assert all(b[0].shape == (32, 1) for b in batches)
    # shuffling changes across epochs (unlike the reference, which never
    # reshuffles — contiguous slices at example.py:209-211)
    epoch2 = list(ds)
    assert not np.array_equal(batches[0][1], epoch2[0][1])
    # all elements covered each epoch before dropping
    seen = np.concatenate([b[1] for b in batches])
    assert len(np.unique(seen)) == 96


def test_dataset_process_sharding():
    x = np.arange(100).reshape(100, 1).astype(np.float32)
    d0 = data.Dataset([x], 10, shuffle=False, process_index=0, process_count=2)
    d1 = data.Dataset([x], 10, shuffle=False, process_index=1, process_count=2)
    assert d0.n == d1.n == 50
    assert float(next(iter(d0))[0][0, 0]) == 0.0
    assert float(next(iter(d1))[0][0, 0]) == 50.0


def test_prefetch_to_device():
    x = np.arange(40).reshape(10, 4).astype(np.float32)
    ds = data.Dataset([x], 2, shuffle=False)
    out = list(data.prefetch_to_device(iter(ds), size=2))
    assert len(out) == 5
    np.testing.assert_array_equal(np.asarray(out[0][0]), x[:2])


def _prefetch_threads():
    import threading
    return [t for t in threading.enumerate() if t.name == "dttpu-prefetch"]


def _wait_for_no_prefetch_threads(timeout=5.0):
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _prefetch_threads():
            return True
        time.sleep(0.01)
    return False


def test_prefetch_consumer_abandonment_terminates_producer():
    """A caller that drops the generator early (break out of an epoch)
    must not leave the producer thread parked on the capacity semaphore
    forever, pinning ``size`` device batches — the seed's leak."""
    x = np.arange(400).reshape(100, 4).astype(np.float32)
    ds = data.Dataset([x], 2, shuffle=False)
    gen = data.prefetch_to_device(iter(ds), size=2)
    next(gen)
    next(gen)          # producer now parked on the capacity semaphore
    gen.close()        # GeneratorExit -> unblock + join the producer
    assert _wait_for_no_prefetch_threads(), "producer thread leaked"


def test_prefetch_break_out_of_loop_terminates_producer():
    """The natural spelling of the leak: ``break`` inside a for-loop
    then dropping the generator (refcount close via gc)."""
    x = np.arange(400).reshape(100, 4).astype(np.float32)
    ds = data.Dataset([x], 2, shuffle=False)
    for i, _batch in enumerate(data.prefetch_to_device(iter(ds), size=3)):
        if i == 1:
            break      # the for-loop's generator is closed on gc
    import gc
    gc.collect()
    assert _wait_for_no_prefetch_threads(), "producer thread leaked"


def test_prefetch_producer_error_still_raises_and_joins():
    def bad_iter():
        yield (np.zeros((2, 2), np.float32),)
        raise RuntimeError("upstream boom")

    gen = data.prefetch_to_device(bad_iter(), size=2)
    next(gen)
    with np.testing.assert_raises_regex(RuntimeError, "upstream boom"):
        for _ in gen:
            pass
    assert _wait_for_no_prefetch_threads()


def test_prefetch_caps_resident_batches():
    """The capacity contract survives the rewrite: at most ``size``
    batches are uploaded ahead of the consumer (the ticket is taken
    BEFORE device_put)."""
    import time
    uploaded = []

    def tracking_iter():
        for i in range(10):
            uploaded.append(i)
            yield (np.full((2, 2), i, np.float32),)

    gen = data.prefetch_to_device(tracking_iter(), size=2)
    first = next(gen)
    time.sleep(0.3)    # give the producer every chance to overrun
    # consumed 1 + at most `size` in flight ahead of it
    assert len(uploaded) <= 3, uploaded
    np.testing.assert_array_equal(np.asarray(first[0]),
                                  np.zeros((2, 2)))
    rest = list(gen)
    assert len(rest) == 9
    assert _wait_for_no_prefetch_threads()


def test_synthetic_datasets_shapes_and_learnability():
    (xt, yt), (xe, ye) = data.mnist()
    assert xt.shape == (60000, 28, 28, 1) and xt.dtype == np.float32
    assert yt.shape == (60000,) and yt.dtype == np.int32
    assert 0.0 <= xt.min() and xt.max() <= 1.0
    (xt, yt), _ = data.cifar10()
    assert xt.shape == (50000, 32, 32, 3)
    # class-conditional structure: per-class mean images differ
    m0 = xt[yt == 0].mean(axis=0)
    m1 = xt[yt == 1].mean(axis=0)
    assert np.abs(m0 - m1).mean() > 0.01


def test_mnist_flatten():
    (xt, _), _ = data.mnist(flatten=True)
    assert xt.shape == (60000, 784)


def test_mnist_partial_idx_falls_back(tmp_path):
    import warnings
    (tmp_path / "train-images-idx3-ubyte").write_bytes(b"\x00\x00\x08\x01" +
                                                       b"\x00\x00\x00\x01A")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        (xt, yt), _ = data.mnist(str(tmp_path))
    assert xt.shape == (60000, 28, 28, 1)  # synthetic fallback
    assert any("missing" in str(w.message) for w in caught)


def test_synthetic_lm_corpus_and_sequences():
    from distributed_tensorflow_tpu.data.datasets import (lm_sequences,
                                                          synthetic_lm_corpus)

    c1 = synthetic_lm_corpus(vocab_size=64, length=5000, seed=3, order=1)
    c2 = synthetic_lm_corpus(vocab_size=64, length=5000, seed=3, order=1)
    np.testing.assert_array_equal(c1, c2)          # deterministic
    assert c1.dtype == np.int32
    assert c1.min() >= 0 and c1.max() < 64
    # order-1 structure: the modal continuation of a frequent token
    # dominates (80% deterministic chain)
    tok = np.bincount(c1).argmax()
    nxt = c1[1:][c1[:-1] == tok]
    assert (np.bincount(nxt).max() / len(nxt)) > 0.5

    rows = lm_sequences(c1, seq_len=16)
    assert rows.shape == ((5000 - 1) // 16, 17)
    np.testing.assert_array_equal(rows[0], c1[:17])
    np.testing.assert_array_equal(rows[1], c1[16:33])


def test_lm_sequences_short_corpus_and_big_vocab_bounded():
    from distributed_tensorflow_tpu.data.datasets import (lm_sequences,
                                                          synthetic_lm_corpus)

    assert lm_sequences(np.arange(10), seq_len=16).shape == (0, 17)
    # 50k-vocab corpus must not allocate a vocab^2 table
    c = synthetic_lm_corpus(vocab_size=50_000, length=2000, seed=0)
    assert c.max() < 50_000 and len(c) == 2000
