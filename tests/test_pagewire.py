"""Fault-tolerant cross-host KV-page wire (fleet/pagewire.py).

The contracts pinned here (docs/RESILIENCE.md §page wire):

  * frame/parse roundtrip — bytes AND int chain keys, multi-leaf
    payloads with exact dtype/shape (int8 scale planes ride as
    ordinary leaves); corruption and truncation are CRC-detected and
    NAKed (``WireFrameError``), never spliced;
  * ``PageWire.ship`` — bounded per-chunk retry with seeded backoff,
    idempotent re-send (the receiver dedups by chain key), splice of
    the contiguous chunk prefix only, graceful degradation on a
    refusing destination, every ``dttpu_wire_*`` series advancing;
  * the serve-tier pre-warm — shipped pages are adopted into the
    destination pool BEFORE ``import_request`` admits, so the resumed
    request's prefill radix-matches the shipped chain and SKIPS those
    windows, with terminal tokens bit-identical to a solo run;
  * the chaos matrix — {drop_chunk, corrupt_chunk, stall_wire,
    kill_host} x {pre-transfer, mid-transfer} all end with the
    migrated request completed token-identical with zero duplicated
    stream tokens (``kill_host`` degrades to re-prefill migration —
    it never loses or duplicates a token);
  * the fleet-sim mirror ships fingerprint entries over the SAME wire
    (int chain keys, payload-free records), and the federation
    recovers a scoreable ``RemoteAffinity`` from the serve tier's
    chain gauges — cross-host prefix-affinity routing from one
    /metrics scrape.
"""
import struct

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import fleet, obs, serve
from distributed_tensorflow_tpu.fleet import pagewire
from distributed_tensorflow_tpu.fleet import sim as sim_lib
from distributed_tensorflow_tpu.fleet.router import expected_pages_reused
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.summary.crc32c import crc32c


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _generate_tokens(model, params, prompt, new, max_len, **kw):
    out = model.generate(params, jnp.asarray(prompt[None]),
                         max_new_tokens=new, max_len=max_len, **kw)
    return np.asarray(out)[0, prompt.size:].tolist()


def _engine(model, params, reg=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("tick_steps", 2)
    return serve.Engine(model, params,
                        registry=reg or metrics_lib.Registry(), **kw)


def _warm(engines, steps=8):
    hs = [eng.submit(_prompt(6, seed=50 + j), 3)
          for j, eng in enumerate(engines)]
    for _ in range(steps):
        for eng in engines:
            eng.step()
    assert all(h.done for h in hs)


def _records(chains):
    return [(i, c, {}) for i, c in enumerate(chains)]


class _Snap:
    """Minimal shipped-pages manifest carrier for wire unit tests."""

    def __init__(self, shipped, page_size):
        self.shipped_pages = tuple(shipped)
        self.page_size = page_size


class _FakeDest:
    """Destination double: adopts everything (or refuses loudly)."""

    def __init__(self, fail=False):
        self.calls = []
        self.fail = fail

    def import_wire_pages(self, snap, records, timeout_s=None):
        if self.fail:
            raise RuntimeError("injected: destination pool exhausted")
        self.calls.append(list(records))
        return len(records)


def _wire(reg=None, **kw):
    kw.setdefault("chunk_pages", 1)
    kw.setdefault("backoff_base_s", 1e-4)
    kw.setdefault("backoff_max_s", 1e-3)
    kw.setdefault("sleep", lambda s: None)
    return fleet.PageWire(registry=reg or metrics_lib.Registry(), **kw)


# ---------------------------------------------------------------------------
# frame format


def test_frame_roundtrip_bytes_and_int_keys():
    """Both chain-key worlds (serve blake2b bytes, sim int prefix ids)
    and a multi-leaf payload — int8 data plus its float32 scale plane,
    the int8-pool layout — survive frame/parse with exact dtype,
    shape, and bytes."""
    import ml_dtypes
    payload = {
        "k": np.arange(24, dtype=np.float32).reshape(2, 3, 2, 2),
        "k_scale": np.ones((2, 3, 1), np.float32) * 0.5,
        "v": (np.arange(12, dtype=np.int8) - 6).reshape(2, 3, 2),
        # extension dtype: .str is an opaque void ("<V2"), so the wire
        # must carry the NAME or the receiver's dtype check refuses
        # every bf16 pool (the serving default on real hardware)
        "v_bf16": np.arange(8).reshape(2, 4).astype(ml_dtypes.bfloat16),
    }
    recs = [
        pagewire.PageRecord(index=0, chain=b"\x01\x02\xff" * 2,
                            tokens=16, payload=payload),
        pagewire.PageRecord(index=1, chain=-12345, tokens=32,
                            payload={}),
    ]
    seq, out = pagewire.parse_frame(pagewire.frame_chunk(7, recs))
    assert seq == 7
    assert [(r.index, r.chain, r.tokens) for r in out] == \
        [(0, b"\x01\x02\xff" * 2, 16), (1, -12345, 32)]
    assert set(out[0].payload) == set(payload)
    for name, leaf in payload.items():
        got = out[0].payload[name]
        assert got.dtype == leaf.dtype and got.shape == leaf.shape
        assert np.array_equal(got, leaf)
    assert out[1].payload == {}


def test_frame_corruption_truncation_and_magic_nak():
    """Every malformed-frame shape NAKs (WireFrameError) instead of
    delivering records: a flipped byte (CRC), a truncated tail, a
    frame too short to hold the header, and a bad magic that passes
    the CRC (the trailer covers the magic, so this needs a re-signed
    body to even reach the magic check)."""
    frame = pagewire.frame_chunk(0, [pagewire.PageRecord(
        index=0, chain=b"abc12345", tokens=16,
        payload={"k": np.ones((2, 4), np.float32)})])
    bad = bytearray(frame)
    bad[len(bad) // 2] ^= 0xFF
    with pytest.raises(pagewire.WireFrameError, match="CRC32C"):
        pagewire.parse_frame(bytes(bad))
    with pytest.raises(pagewire.WireFrameError, match="short frame"):
        pagewire.parse_frame(frame[:8])
    with pytest.raises(pagewire.WireFrameError):
        pagewire.parse_frame(frame[:-10])       # truncated, CRC gone
    body = bytearray(frame[:-4])
    body[0] ^= 0xFF                             # break DTPW, re-sign
    resigned = bytes(body) + struct.pack(">I", crc32c(bytes(body)))
    with pytest.raises(pagewire.WireFrameError, match="bad magic"):
        pagewire.parse_frame(resigned)
    # WireFrameError IS a WireError: the NAK rides the same degrade
    # ladder as every other wire failure
    assert issubclass(pagewire.WireFrameError, fleet.WireError)


# ---------------------------------------------------------------------------
# PageWire.ship unit (fake destination)


def test_ship_adopts_and_counts():
    reg = metrics_lib.Registry()
    wire = _wire(reg, chunk_pages=2)
    dest = _FakeDest()
    snap = _Snap([(b"a", 4), (b"b", 8), (b"c", 12)], 4)
    n = wire.ship(_records([b"a", b"b", b"c"]), dest, snap)
    assert n == 3
    (call,) = dest.calls
    assert [(r.index, r.chain, r.tokens) for r in call] == \
        [(0, b"a", 4), (1, b"b", 8), (2, b"c", 12)]
    assert reg.get("dttpu_wire_transfers_total").value == 1
    assert reg.get("dttpu_wire_pages_shipped_total").value == 3
    assert reg.get("dttpu_wire_chunks_total").value == 2    # ceil(3/2)
    assert reg.get("dttpu_wire_bytes_total").value > 0
    assert reg.get("dttpu_wire_transfer_seconds").count == 1
    assert reg.get("dttpu_wire_chunk_retries_total").value == 0


def test_ship_degrades_without_shipping():
    """The no-transfer shapes: nothing to ship, a destination without
    the wire surface (contiguous engine), a refusing destination, and
    a non-contiguous accepted set (chunk 0 missing) — all return 0
    adopted, and only the refusal counts as a wire failure."""
    reg = metrics_lib.Registry()
    wire = _wire(reg)
    snap = _Snap([(b"a", 4), (b"b", 8)], 4)
    assert wire.ship([], _FakeDest(), snap) == 0
    assert wire.ship(_records([b"a"]), object(), snap) == 0
    # records starting at chunk 1: no contiguous prefix from 0
    assert wire.ship([(1, b"b", {})], _FakeDest(), snap) == 0
    assert reg.get("dttpu_wire_failures_total").value == 0
    refused = _FakeDest(fail=True)
    assert wire.ship(_records([b"a", b"b"]), refused, snap) == 0
    assert reg.get("dttpu_wire_failures_total").value == 1
    assert reg.get("dttpu_wire_transfers_total").value == 0


@pytest.mark.chaos
@pytest.mark.parametrize("kind", ["drop_chunk", "corrupt_chunk",
                                  "stall_wire"])
def test_ship_retries_recoverable_faults(kind):
    """A dropped, corrupted, or stalled chunk frame costs a bounded
    retry, never the transfer: the re-send is deduped by chain key on
    the receiver, so the destination adopts each page exactly once."""
    reg = metrics_lib.Registry()
    wire = _wire(reg, timeout_s=0.01)        # stalled == late == lost
    dest = _FakeDest()
    snap = _Snap([(b"a", 4), (b"b", 8)], 4)
    plan = faults.FaultPlan(
        [{"kind": kind, "at": 0, "replica": 0, "seconds": 0.05}],
        registry=metrics_lib.Registry())
    with faults.activated(plan):
        n = wire.ship(_records([b"a", b"b"]), dest, snap)
    assert n == 2
    assert plan.log and plan.log[0]["kind"] == kind
    (call,) = dest.calls
    assert [r.chain for r in call] == [b"a", b"b"]   # deduped, ordered
    assert reg.get("dttpu_wire_chunk_retries_total").value >= 1
    assert reg.get("dttpu_wire_failures_total").value == 0


@pytest.mark.chaos
def test_ship_kill_host_raises_wireerror():
    """A dead host mid-transfer is unrecoverable: WireError, the
    failure counted, NOTHING spliced — the caller re-prefills."""
    reg = metrics_lib.Registry()
    wire = _wire(reg)
    dest = _FakeDest()
    snap = _Snap([(b"a", 4), (b"b", 8)], 4)
    plan = faults.FaultPlan(
        [{"kind": "kill_host", "at": 1, "replica": 0}],
        registry=metrics_lib.Registry())
    with faults.activated(plan), \
            pytest.raises(fleet.WireError, match="link down"):
        wire.ship(_records([b"a", b"b"]), dest, snap)
    assert plan.log[0]["kind"] == "kill_host"
    assert dest.calls == []
    assert reg.get("dttpu_wire_failures_total").value == 1


@pytest.mark.chaos
def test_ship_retries_exhausted_is_wireerror():
    """A frame that NEVER arrives (drop armed past the retry budget)
    exhausts the bounded retries and degrades, not loops."""
    reg = metrics_lib.Registry()
    wire = _wire(reg, max_retries=2)
    snap = _Snap([(b"a", 4)], 4)
    plan = faults.FaultPlan(
        [{"kind": "drop_chunk", "at": i, "replica": 0}
         for i in range(3)],
        registry=metrics_lib.Registry())
    with faults.activated(plan), \
            pytest.raises(fleet.WireError, match="retries exhausted"):
        wire.ship(_records([b"a"]), _FakeDest(), snap)
    assert reg.get("dttpu_wire_chunk_retries_total").value == 2
    assert reg.get("dttpu_wire_failures_total").value == 1


# ---------------------------------------------------------------------------
# serve-tier pre-warm: real engines, device pages over the wire


def test_wire_ship_prewarms_destination_and_skips_windows():
    """THE tentpole contract end to end at the engine level: export a
    mid-decode request, read its handed-off radix pages off the
    source device, ship them, splice into the destination pool — the
    re-import radix-matches the shipped chain, SKIPS those prefill
    windows, and finishes bit-identical to the solo run.  Re-shipping
    the same records is idempotent (radix dedup)."""
    model, params = _model_params()
    src = _engine(model, params)
    dst = _engine(model, params)
    page = src.scheduler.page_size
    p = _prompt(2 * page - 2, seed=4)
    want = _generate_tokens(model, params, p, 10, 64)
    h = src.submit(p, 10)
    while len(h.tokens) < 5:                 # written >= 2 full pages
        src.step()
    snap = src.export_request(h)
    assert snap.page_size == page
    assert snap.shipped_pages is not None
    assert [t for _, t in snap.shipped_pages] == [page, 2 * page]
    records = src.export_wire_pages(snap)
    assert [i for i, _, _ in records] == [0, 1]
    for _, chain, payload in records:
        assert isinstance(chain, bytes) and payload
        for leaf in payload.values():
            assert leaf.shape[1] == page     # [L, page_size, ...]
    wreg = metrics_lib.Registry()
    wire = fleet.PageWire(registry=wreg)
    before = dst.stats()
    assert wire.ship(records, dst, snap) == 2
    assert wire.ship(records, dst, snap) == 2     # idempotent re-send
    h2 = dst.import_request(snap)
    dst.drain()
    after = dst.stats()
    assert h2.status == "ok" and h2.tokens == want
    assert after.prefill_windows_skipped_total \
        > before.prefill_windows_skipped_total
    assert (after.prefix_tokens_reused_total
            - before.prefix_tokens_reused_total) >= 2 * page
    assert wreg.get("dttpu_wire_transfers_total").value == 2
    assert wreg.get("dttpu_wire_pages_shipped_total").value == 4


def test_wire_import_refuses_alien_page_size_and_chains():
    """The splice validates before it touches the pool: a snapshot
    chunked under a different page size adopts nothing, and records
    whose chain hashes don't match the context's radix keys adopt
    nothing — re-prefill is always the fallback, never a bad splice."""
    model, params = _model_params()
    src = _engine(model, params)
    dst = _engine(model, params)
    page = src.scheduler.page_size
    p = _prompt(2 * page - 2, seed=6)
    h = src.submit(p, 10)
    while len(h.tokens) < 5:
        src.step()
    snap = src.export_request(h)
    records = src.export_wire_pages(snap)
    good_page_size = snap.page_size
    snap.page_size = good_page_size // 2
    assert dst.import_wire_pages(snap, [
        pagewire.PageRecord(index=i, chain=c, tokens=(i + 1) * page,
                            payload=dict(pl))
        for i, c, pl in records]) == 0
    snap.page_size = good_page_size
    forged = [pagewire.PageRecord(index=i, chain=b"\x00" * 8,
                                  tokens=(i + 1) * page,
                                  payload=dict(pl))
              for i, c, pl in records]
    assert dst.import_wire_pages(snap, forged) == 0
    # the real records still splice fine afterwards
    real = [pagewire.PageRecord(index=i, chain=c,
                                tokens=(i + 1) * page,
                                payload=dict(pl))
            for i, c, pl in records]
    assert dst.import_wire_pages(snap, real) == 2


# ---------------------------------------------------------------------------
# fleet-level wire migration


def test_router_wire_migration_end_to_end():
    """drain_replica with a page wire: the victim's pages ship to the
    survivor, the import skips the shipped prefill windows, terminal
    tokens and the stream are exactly the solo run's."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    engines = [_engine(model, params, reg=reg) for _ in range(2)]
    router = fleet.Router(engines, registry=reg,
                          page_wire=fleet.PageWire(registry=reg))
    _warm(engines)
    page = engines[0].scheduler.page_size
    p = _prompt(2 * page - 2, seed=11)
    want = _generate_tokens(model, params, p, 10, 64)
    stream = []
    h = router.submit(p, 10, on_token=stream.extend)
    while len(h.tokens) < 5:
        router.step()
    victim = h.replica_id
    survivor = engines[1 - victim]
    before = survivor.stats()
    assert router.drain_replica(victim, timeout_s=60) is True
    router.drain()
    after = survivor.stats()
    assert h.status == "ok" and h.tokens == want
    assert stream == want, "stream dup/loss across the wire migration"
    assert reg.get("dttpu_router_wire_migrations_total").value == 1
    assert reg.get("dttpu_router_wire_degraded_total").value == 0
    assert reg.get("dttpu_wire_transfers_total").value == 1
    assert reg.get("dttpu_migrations_total").value >= 1
    assert after.prefill_windows_skipped_total \
        > before.prefill_windows_skipped_total


# ---------------------------------------------------------------------------
# chaos matrix: every wire fault x {pre-transfer, mid-transfer}


@pytest.fixture(scope="module")
def wire_fleet():
    """One compiled two-engine fleet shared by the whole chaos matrix
    (each case migrates a FRESH prompt, so radix state carried between
    cases cannot fake token identity)."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    engines = [_engine(model, params, reg=reg) for _ in range(2)]
    wire = fleet.PageWire(chunk_pages=1, timeout_s=0.05,
                          backoff_base_s=1e-4, backoff_max_s=1e-3,
                          registry=reg)
    router = fleet.Router(engines, registry=reg, page_wire=wire)
    _warm(engines)
    return model, params, engines, router, reg


_WIRE_KINDS = ["drop_chunk", "corrupt_chunk", "stall_wire", "kill_host"]


@pytest.mark.chaos
@pytest.mark.parametrize("at", [0, 1], ids=["pre_transfer",
                                            "mid_transfer"])
@pytest.mark.parametrize("kind", _WIRE_KINDS)
def test_wire_chaos_matrix_token_identical(wire_fleet, kind, at):
    """ISSUE 20 acceptance matrix: every wire fault kind, armed at the
    first chunk (pre-transfer) and the second (mid-transfer), ends
    with the migrated request completed token-identical to the
    unmigrated run and zero duplicated stream tokens.  Recoverable
    faults still ship (retry); kill_host degrades to re-prefill."""
    model, params, engines, router, reg = wire_fleet
    page = engines[0].scheduler.page_size
    seed = 200 + 10 * at + _WIRE_KINDS.index(kind)
    p = _prompt(2 * page - 2, seed=seed)
    want = _generate_tokens(model, params, p, 8, 64)
    shipped0 = reg.get("dttpu_router_wire_migrations_total").value
    degraded0 = reg.get("dttpu_router_wire_degraded_total").value
    retries0 = reg.get("dttpu_wire_chunk_retries_total").value
    plan = faults.FaultPlan(
        [{"kind": kind, "at": at, "replica": 0, "seconds": 0.2}],
        registry=metrics_lib.Registry())
    stream = []
    with faults.activated(plan):
        h = router.submit(p, 8, on_token=stream.extend)
        while len(h.tokens) < 5:
            router.step()
        victim = h.replica_id
        assert router.drain_replica(victim, timeout_s=60) is True
        while not h.done:
            router.step()
    router.resume_replica(victim)
    assert plan.log and plan.log[0]["kind"] == kind, plan.log
    assert h.status == "ok", (h.status, h.error)
    assert h.tokens == want, "terminal tokens diverged under chaos"
    assert stream == want, "stream dup/loss under chaos"
    if kind == "kill_host":
        assert reg.get("dttpu_router_wire_degraded_total").value \
            == degraded0 + 1
        assert reg.get("dttpu_router_wire_migrations_total").value \
            == shipped0
    else:
        assert reg.get("dttpu_router_wire_migrations_total").value \
            == shipped0 + 1
        assert reg.get("dttpu_wire_chunk_retries_total").value \
            > retries0


@pytest.mark.chaos
def test_kill_host_mid_transfer_launcher_restarts_request_survives(
        wire_fleet, tmp_path):
    """The combined kill_host story, ONE fault plan driving both
    sites: the wire cut (``wire:0``) degrades the transfer — the
    in-flight request completes on the survivor token-identical with
    zero duplicate stream tokens — while the launcher's liveness poll
    (``host:0``) SIGKILLs and RESTARTS the dead host process."""
    model, params, engines, router, reg = wire_fleet
    page = engines[0].scheduler.page_size
    p = _prompt(2 * page - 2, seed=321)
    want = _generate_tokens(model, params, p, 8, 64)
    # fake process tree: host 0's first incarnation runs until killed,
    # later incarnations run forever (the restart is the assertion)
    class _Proc:
        def __init__(self):
            self.rc = None

        def poll(self):
            return self.rc

        def kill(self):
            self.rc = -9

        def wait(self, timeout=None):
            return self.rc

    t = {"now": 0.0}
    launcher = fleet.Launcher(
        fleet.launcher.local_topology(1, ["true"], 9999),
        registry=reg, jitter=0.0, backoff_base_s=0.01,
        popen=lambda spec: _Proc(),
        sleep=lambda s: t.__setitem__("now", t["now"] + s),
        clock=lambda: t["now"])
    # wire dies at its chunk #1 (mid-transfer); the host poll fault is
    # armed at an index only the launcher site reaches (the two sites
    # keep separate counters but share the fault pool, so the indices
    # must not collide)
    plan = faults.FaultPlan(
        [{"kind": "kill_host", "at": 1, "replica": 0},
         {"kind": "kill_host", "at": 5, "replica": 0}],
        registry=metrics_lib.Registry())
    stream = []
    with faults.activated(plan):
        launcher.start()
        h = router.submit(p, 8, on_token=stream.extend)
        while len(h.tokens) < 5:
            router.step()
        victim = h.replica_id
        assert router.drain_replica(victim, timeout_s=60) is True
        while not h.done:
            router.step()
        for _ in range(8):                   # host:0 poll #5 kills
            launcher.poll()
            t["now"] += 0.05
    router.resume_replica(victim)
    launcher.stop()
    assert {(e["kind"], "wire" in e) for e in plan.log} == \
        {("kill_host", True), ("kill_host", False)}, plan.log
    assert h.status == "ok" and h.tokens == want
    assert stream == want
    rep = launcher.report()
    assert rep[0]["restarts"] == 1           # killed host came back
    assert rep[0]["exit_history"][0] == -9


# ---------------------------------------------------------------------------
# fleet-sim mirror


def test_sim_engine_wire_mirror_roundtrip():
    """The sim ships fingerprint entries over the REAL wire (int chain
    keys, payload-free records): the destination marks the prefix
    cached, and the re-admitted request radix-hits instead of paying
    the full prefill."""
    cost = sim_lib.CostModel(prefill_window_s=1e-3, decode_tick_s=1e-3)
    src = sim_lib.SimEngine(cost, num_slots=2, prefill_chunk=4)
    dst = sim_lib.SimEngine(cost, num_slots=2, prefill_chunk=4)
    warm = src.submit((12, 7, 8, 0.0), 4)    # teaches src prefix 7
    while src.busy:
        src.step()
    assert warm.status == "ok"
    h = src.submit((12, 7, 8, 0.1), 4)
    snap = src.export_request(h)
    assert snap.shipped_pages == ((7, 8),)
    assert snap.page_size == 4
    records = src.export_wire_pages(snap)
    assert records == [(0, 7, {})]
    reg = metrics_lib.Registry()
    wire = fleet.PageWire(registry=reg)
    assert wire.ship(records, dst, snap) == 2        # 8 tokens / 4
    assert dst.stats().prefix_fingerprint.get(7) == 8
    h2 = dst.import_request(snap)
    while dst.busy:
        dst.step()
    assert h2.status == "ok"
    assert dst.stats().prefix_hits_total == 1        # pre-warmed
    assert dst.stats().prefix_tokens_reused_total >= 8
    assert reg.get("dttpu_wire_transfers_total").value == 1


def test_sim_import_rejects_alien_chunking():
    cost = sim_lib.CostModel(prefill_window_s=1e-3, decode_tick_s=1e-3)
    dst = sim_lib.SimEngine(cost, prefill_chunk=4)
    snap = _Snap([(7, 8)], 8)                # chunked by 8, not 4
    rec = pagewire.PageRecord(index=0, chain=7, tokens=8, payload={})
    assert dst.import_wire_pages(snap, [rec]) == 0
    assert dst.stats().prefix_fingerprint == {}


# ---------------------------------------------------------------------------
# federation: cross-host prefix affinity from one scrape


def test_federated_fingerprints_score_prefix_affinity():
    """Satellite: the serve tier renders its pool fingerprint as
    ``dttpu_serve_prefix_chain_tokens{chain=..}`` gauges (plus the
    page size), the federation recovers a ``RemoteAffinity`` per
    source, and ``expected_pages_reused`` scores it EXACTLY like the
    local ``EngineStats`` — prefix-affinity routing works from the
    scrape plane."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    eng = _engine(model, params, reg=reg)
    page = eng.scheduler.page_size
    p = _prompt(2 * page, seed=21)
    h = eng.submit(p, 4)
    eng.drain()
    assert h.status == "ok"
    stats = eng.stats()
    assert stats.prefix_fingerprint            # pool registered chains
    fed = obs.FederatedMetrics()
    fed.add_registry(reg, replica="7")
    fps = fed.fleet_fingerprints()
    (src,) = list(fps)
    assert ("replica", "7") in src
    aff = fps[src]
    assert isinstance(aff, obs.RemoteAffinity)
    assert aff.page_size == page
    assert aff.prefix_fingerprint == stats.prefix_fingerprint
    assert (expected_pages_reused(p, aff)
            == expected_pages_reused(p, stats) >= 2)
    # a prompt sharing only the first chunk scores exactly one page
    mixed = np.concatenate([p[:page], _prompt(page, seed=77)])
    assert expected_pages_reused(mixed, aff) == 1
