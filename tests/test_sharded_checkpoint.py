"""Sharded checkpoint tests: per-shard save, reshard-on-restore.

Runs on the 8-device virtual CPU mesh (conftest) — the same chunk-indexed
format a multi-process pod writes, with one process owning all chunks.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.train import (restore_sharded, save_sharded,
                                              sharded_checkpoint as sck)


def make_state(mesh, spec_kernel):
    k = jnp.arange(64 * 16, dtype=jnp.float32).reshape(64, 16)
    b = jnp.arange(16, dtype=jnp.float32)
    tree = {"params": {"kernel": jax.device_put(
                           k, NamedSharding(mesh, spec_kernel)),
                       "bias": jax.device_put(b, NamedSharding(mesh, P()))},
            "step": np.int64(7)}
    return tree


def zeros_like_on(mesh, spec_kernel):
    return {"params": {"kernel": jax.device_put(
                           jnp.zeros((64, 16)),
                           NamedSharding(mesh, spec_kernel)),
                       "bias": jax.device_put(
                           jnp.zeros((16,)), NamedSharding(mesh, P()))},
            "step": np.int64(0)}


def test_roundtrip_same_sharding(tmp_path):
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    path = save_sharded(str(tmp_path), 7, state)
    assert sck.is_sharded_checkpoint(path)
    out = restore_sharded(zeros_like_on(mesh, P("data", None)), path)
    np.testing.assert_array_equal(np.asarray(out["params"]["kernel"]),
                                  np.asarray(state["params"]["kernel"]))
    np.testing.assert_array_equal(np.asarray(out["params"]["bias"]),
                                  np.arange(16, dtype=np.float32))
    assert int(out["step"]) == 7
    # restored leaf keeps the target's sharding
    assert out["params"]["kernel"].sharding.spec == P("data", None)


def test_restore_onto_different_mesh_layout(tmp_path):
    mesh_save = make_mesh({"data": 8})
    state = make_state(mesh_save, P("data", None))
    path = save_sharded(str(tmp_path), 1, state)

    # Restore onto a 4x2 mesh sharded over BOTH axes — every chunk boundary
    # moves; values must still reassemble exactly.
    mesh_new = make_mesh({"data": 4, "tensor": 2})
    target = zeros_like_on(mesh_new, P("data", "tensor"))
    out = restore_sharded(target, path)
    np.testing.assert_array_equal(np.asarray(out["params"]["kernel"]),
                                  np.arange(64 * 16,
                                            dtype=np.float32).reshape(64, 16))
    assert out["params"]["kernel"].sharding.spec == P("data", "tensor")


def test_explicit_shardings_tree(tmp_path):
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    path = save_sharded(str(tmp_path), 2, state)
    shardings = {"params": {"kernel": NamedSharding(mesh, P(None, "data")),
                            "bias": NamedSharding(mesh, P())},
                 "step": None}
    out = restore_sharded(zeros_like_on(mesh, P("data", None)), path,
                          shardings=shardings)
    assert out["params"]["kernel"].sharding.spec == P(None, "data")
    np.testing.assert_array_equal(np.asarray(out["params"]["kernel"]),
                                  np.asarray(state["params"]["kernel"]))


def test_replicated_leaves_written_once(tmp_path):
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P())  # kernel fully replicated on 8 devices
    path = save_sharded(str(tmp_path), 3, state)
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    with open(os.path.join(path, "chunks-00000.json")) as f:
        chunk_rows = json.load(f)
    kernel_chunks = [c for c in chunk_rows if c["leaf"] ==
                     [m["path"] for m in manifest["leaves"]].index(
                         "['params']['kernel']")]
    assert len(kernel_chunks) == 1  # not 8 copies


def test_incomplete_checkpoint_not_listed(tmp_path):
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    path = save_sharded(str(tmp_path), 5, state)
    os.unlink(os.path.join(path, "manifest.json"))  # simulate chief crash
    assert sck.all_sharded_checkpoints(str(tmp_path)) == []
    assert not sck.is_sharded_checkpoint(path)


def test_missing_chunk_detected(tmp_path):
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    path = save_sharded(str(tmp_path), 4, state)
    # Corrupt the per-process chunk index: drop the bias chunk entries.
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    bias_leaf = [m["path"] for m in manifest["leaves"]].index(
        "['params']['bias']")
    cpath = os.path.join(path, "chunks-00000.json")
    with open(cpath) as f:
        chunk_rows = json.load(f)
    with open(cpath, "w") as f:
        json.dump([c for c in chunk_rows if c["leaf"] != bias_leaf], f)
    with pytest.raises(ValueError, match="cover"):
        restore_sharded(zeros_like_on(mesh, P("data", None)), path)


def test_structural_completeness_gates_listing(tmp_path):
    """A checkpoint is complete only when EVERY process's shard + chunk
    files exist alongside the manifest — the barrier-free contract that
    makes async sharded saves safe."""
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    # simulate process 0 of 2: pid 1's files haven't landed yet
    path = save_sharded(str(tmp_path), 9, state, process_index=0,
                        process_count=2)
    assert sck.is_sharded_checkpoint(path)          # format recognized
    assert not sck.is_complete_sharded_checkpoint(path)
    assert sck.all_sharded_checkpoints(str(tmp_path)) == []
    # pid 1 lands (same tree here; ownership dedupe is separately tested)
    save_sharded(str(tmp_path), 9, state, process_index=1, process_count=2)
    assert sck.is_complete_sharded_checkpoint(path)
    assert sck.all_sharded_checkpoints(str(tmp_path)) == [path]


def test_restore_incomplete_raises_clearly(tmp_path):
    """restore_sharded on a structurally-incomplete checkpoint must raise
    a diagnosable error, not FileNotFoundError on an internal filename."""
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    path = save_sharded(str(tmp_path), 13, state, process_index=0,
                        process_count=2)    # pid 1 never lands
    with pytest.raises(ValueError, match="INCOMPLETE"):
        restore_sharded(zeros_like_on(mesh, P("data", None)), path)


def test_prune_removes_old_incomplete_dirs(tmp_path):
    """Incomplete checkpoint dirs older than the retained window are
    garbage-collected (a crashed process's torn save must not leak shard
    files forever); newer ones — possibly still in flight — survive."""
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    # torn save at step 1 (pid 1 of 2 never lands)
    torn_old = save_sharded(str(tmp_path), 1, state, process_index=0,
                            process_count=2, max_to_keep=2)
    for s in (2, 3, 4):
        save_sharded(str(tmp_path), s, state, max_to_keep=2)
    # in-flight save newer than every complete one
    torn_new = save_sharded(str(tmp_path), 5, state, process_index=0,
                            process_count=2, max_to_keep=2)
    kept = sck.all_sharded_checkpoints(str(tmp_path))
    assert [os.path.basename(p) for p in kept] == ["ckpt-0000000003",
                                                   "ckpt-0000000004"]
    assert not os.path.exists(torn_old)      # GC'd with step 2
    assert os.path.exists(torn_new)          # never touched


def test_legacy_embedded_chunk_manifest_restores(tmp_path):
    """Pre-round-3 checkpoints embedded the chunk index in the manifest
    ("chunks" key, barrier-ordered manifest-last) — they must keep
    restoring and count as complete."""
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    path = save_sharded(str(tmp_path), 11, state)
    mpath = os.path.join(path, "manifest.json")
    with open(mpath) as f:
        manifest = json.load(f)
    with open(os.path.join(path, "chunks-00000.json")) as f:
        manifest["chunks"] = json.load(f)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    os.unlink(os.path.join(path, "chunks-00000.json"))   # legacy layout
    assert sck.is_complete_sharded_checkpoint(path)
    out = restore_sharded(zeros_like_on(mesh, P("data", None)), path)
    np.testing.assert_array_equal(np.asarray(out["params"]["kernel"]),
                                  np.asarray(state["params"]["kernel"]))


def test_async_sharded_session_roundtrip(tmp_path):
    """sharded_checkpoint=True + async_checkpoint=True: background chunk
    writes drain on session exit and the next session auto-restores."""
    from distributed_tensorflow_tpu import ops, optim, train
    model = ops.serial(ops.Dense(8, activation="relu"), ops.Dense(2))
    opt = optim.sgd(0.01)
    mesh = make_mesh({"data": 8})
    step = train.make_train_step(model, "mse", opt, mesh=mesh)
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (4,))
    rng = np.random.default_rng(0)
    x = rng.random((16, 4)).astype(np.float32)
    y = rng.random((16, 2)).astype(np.float32)
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d,
                            sharded_checkpoint=True,
                            async_checkpoint=True,
                            hooks=[train.CheckpointHook(every_steps=2)]
                            ) as sess:
        for _ in range(5):
            sess.run_step((x, y))
    ckpts = sck.all_sharded_checkpoints(d)
    assert ckpts, os.listdir(d)
    state2 = train.init_train_state(model, opt, jax.random.PRNGKey(1), (4,))
    with train.TrainSession(state2, step, checkpoint_dir=d,
                            sharded_checkpoint=True) as s2:
        assert s2.step == 5


def test_structure_and_shape_mismatch(tmp_path):
    mesh = make_mesh({"data": 8})
    state = make_state(mesh, P("data", None))
    path = save_sharded(str(tmp_path), 6, state)
    bad = dict(zeros_like_on(mesh, P("data", None)))
    bad["params"] = {"kernel": jnp.zeros((32, 16)), "bias": jnp.zeros((16,))}
    with pytest.raises(ValueError, match="shape"):
        restore_sharded(bad, path)


def test_train_state_roundtrip_with_zero_placement(tmp_path):
    """End-to-end: a real sharded TrainState (ZeRO placement) survives a
    save/restore cycle and keeps training."""
    from distributed_tensorflow_tpu import models, optim, train

    mesh = make_mesh({"data": 4, "fsdp": 2})
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    from distributed_tensorflow_tpu.parallel.sharding import PartitionRules
    rules = PartitionRules([(r"kernel", P(None, "fsdp"))])
    state = train.shard_train_state(state, mesh, rules)
    path = save_sharded(str(tmp_path), 0, state)

    target = train.init_train_state(model, optimizer, jax.random.PRNGKey(1),
                                    (784,))
    target = train.shard_train_state(target, mesh, rules)
    out = restore_sharded(target, path)
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer)
    x = jnp.ones((8, 784))
    y = jnp.zeros((8,), jnp.int32)
    out2, metrics = step(out, (x, y))
    assert np.isfinite(float(metrics["loss"]))


def test_session_sharded_mode_roundtrip(tmp_path):
    """TrainSession(sharded_checkpoint=True): final save on exit, sharded
    auto-restore on re-entry, and cursor-correct resume."""
    from distributed_tensorflow_tpu import models, optim, train
    from distributed_tensorflow_tpu.parallel.sharding import PartitionRules

    mesh = make_mesh({"data": 4, "fsdp": 2})
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.adam()
    rules = PartitionRules([(r"kernel", P(None, "fsdp"))])
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer)
    x = jnp.ones((8, 784))
    y = jnp.zeros((8,), jnp.int32)

    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    state = train.shard_train_state(state, mesh, rules)
    d = str(tmp_path)
    with train.TrainSession(state, step, checkpoint_dir=d,
                            sharded_checkpoint=True) as sess:
        sess.run_step((x, y))
        sess.run_step((x, y))
        final = sess.state
    assert sck.all_sharded_checkpoints(d)  # final save happened

    state2 = train.init_train_state(model, optimizer, jax.random.PRNGKey(9),
                                    (784,))
    state2 = train.shard_train_state(state2, mesh, rules)
    with train.TrainSession(state2, step, checkpoint_dir=d,
                            sharded_checkpoint=True) as sess2:
        assert sess2.step == 2  # resumed at the saved cursor
        for a, b in zip(jax.tree_util.tree_leaves(sess2.state.params),
                        jax.tree_util.tree_leaves(final.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        sess2.run_step((x, y))
        assert sess2.step == 3


def test_bfloat16_roundtrip_and_reshard(tmp_path):
    """Extension dtypes (bf16) survive the npz format uint-encoded."""
    mesh = make_mesh({"data": 8})
    x = jax.device_put(jnp.arange(64, dtype=jnp.bfloat16).reshape(8, 8),
                       NamedSharding(mesh, P("data", None)))
    path = save_sharded(str(tmp_path), 0, {"w": x})
    target = {"w": jax.device_put(jnp.zeros((8, 8), jnp.bfloat16),
                                  NamedSharding(mesh, P(None, "data")))}
    out = restore_sharded(target, path)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["w"], np.float32),
        np.arange(64, dtype=np.float32).reshape(8, 8))
