"""HF-checkpoint interop parity (models/convert.py).

Hermetic under zero egress: the tests build RANDOM-initialized tiny
transformers models in-process (no hub fetch) — the weight-layout mapping
they verify is exactly what a real downloaded checkpoint exercises.
"""
import jax
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf(seed=0):
    torch.manual_seed(seed)
    cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    return transformers.GPT2LMHeadModel(cfg).eval()


def test_gpt2_logits_match_torch():
    """Converted params reproduce the torch forward's logits."""
    from distributed_tensorflow_tpu.models.convert import gpt2_from_hf
    hf = _tiny_hf()
    model, params = gpt2_from_hf(hf)
    ids = np.random.default_rng(0).integers(0, 96, (2, 17)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(model.logits(params, model.apply(
        params, ids.astype(np.int32))))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_gpt2_generate_greedy_matches_torch():
    """Greedy decode through OUR KV cache == transformers' greedy output."""
    from distributed_tensorflow_tpu.models.convert import gpt2_from_hf
    hf = _tiny_hf(seed=1)
    model, params = gpt2_from_hf(hf)
    prompt = np.asarray([[5, 9, 2, 41]], np.int64)
    with torch.no_grad():
        want = hf.generate(torch.from_numpy(prompt), max_new_tokens=8,
                           do_sample=False,
                           pad_token_id=0).numpy()
    got = np.asarray(model.generate(params,
                                    prompt.astype(np.int32),
                                    max_new_tokens=8, temperature=0.0))
    np.testing.assert_array_equal(got, want)


def test_gpt2_converted_finetunes():
    """Converted weights are trainable: lm_loss_fn drops over a few steps."""
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.models.convert import gpt2_from_hf
    hf = _tiny_hf(seed=2)
    model, params = gpt2_from_hf(hf)
    opt = optim.adam(1e-3)
    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    state = train.TrainState.create(params, opt.init(params))
    ids = np.random.default_rng(1).integers(0, 96, (4, 17)).astype(np.int32)
    losses = []
    for _ in range(6):
        state, m = step(state, {"input_ids": ids})
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses


def test_gpt2_converted_shards_and_trains_on_mesh():
    """Interop composes with parallelism: converted HF weights shard over
    a tensor x data mesh via partition_rules and train under pjit."""
    from distributed_tensorflow_tpu import optim, parallel, train
    from distributed_tensorflow_tpu.models.convert import gpt2_from_hf
    from distributed_tensorflow_tpu.parallel.sharding import shard_pytree
    mesh = parallel.make_mesh({"data": 4, "tensor": 2})
    hf = _tiny_hf(seed=4)
    model, params = gpt2_from_hf(hf, mesh=mesh)
    params = shard_pytree(params, mesh, model.partition_rules())
    assert "tensor" in str(
        params["decoder"]["ffn"]["w_in"]["kernel"].sharding.spec)
    opt = optim.adam(1e-3)
    step = train.make_custom_train_step(model.lm_loss_fn(), opt)
    state = train.TrainState.create(params, opt.init(params))
    ids = np.random.default_rng(2).integers(0, 96, (8, 17)).astype(np.int32)
    from jax.sharding import NamedSharding, PartitionSpec as P
    batch = {"input_ids": jax.device_put(
        ids, NamedSharding(mesh, P("data")))}
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_gpt2_bpe_tokenizer_matches_transformers(tmp_path):
    """GPT2BPETokenizer replays a checkpoint's vocab.json + merges.txt with
    the EXACT ids transformers.GPT2Tokenizer produces — the other half of
    GPT-2 checkpoint reuse (weights convert via gpt2_from_hf, text
    round-trips through the same id space)."""
    import json

    from distributed_tensorflow_tpu.data import GPT2BPETokenizer
    from distributed_tensorflow_tpu.data.text import _gpt2_bytes_to_unicode

    # synthetic checkpoint files: the full byte alphabet + a few merges
    b2u = _gpt2_bytes_to_unicode()
    alphabet = [b2u[b] for b in sorted(b2u)]
    vocab = {u: i for i, u in enumerate(alphabet)}
    # ('#', '#') pins the loader bug class: real GPT-2 merges.txt contains
    # rules starting with '#', only the first '#version' line is a header
    merges = [("t", "h"), ("th", "e"), ("Ġ", "the"), ("e", "s"),
              ("i", "n"), ("Ġthe", "s"), ("1", "2"), ("#", "#")]
    for a, b in merges:
        vocab[a + b] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)   # added token: must stay ONE id
    vf, mf = tmp_path / "vocab.json", tmp_path / "merges.txt"
    vf.write_text(json.dumps(vocab), encoding="utf-8")
    mf.write_text("#version: 0.2\n" +
                  "\n".join(f"{a} {b}" for a, b in merges) + "\n",
                  encoding="utf-8")

    ours = GPT2BPETokenizer.load(str(vf), str(mf))
    hf = transformers.GPT2Tokenizer(str(vf), str(mf))
    texts = [
        "the thesis in the theses",
        "  leading spaces, punctuation! and 123 numbers",
        "unicode: café — 中文 \U0001f600",
        "line\nbreaks\n\n and trailing ",
        "it's the'd they'll we've I'm",
        "## markdown header and #include <stdio.h>",
        "doc one<|endoftext|>doc two<|endoftext|>",
    ]
    for text in texts:
        want = hf.encode(text)
        got = ours.encode(text).tolist()
        assert got == want, (text, got, want)
        assert ours.decode(got) == text


def test_gpt2_unsupported_configs_refused():
    from distributed_tensorflow_tpu.models.convert import gpt2_config_from_hf
    cfg = transformers.GPT2Config(activation_function="relu")
    with pytest.raises(ValueError, match="activation"):
        gpt2_config_from_hf(cfg)


def _tiny_hf_bert(seed=0, mlm=False):
    torch.manual_seed(seed)
    cfg = transformers.BertConfig(
        vocab_size=120, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    cls = (transformers.BertForMaskedLM if mlm else transformers.BertModel)
    return cls(cfg).eval()


def test_bert_sequence_and_pooled_match_torch():
    """Converted BERT reproduces HF's last_hidden_state and pooler output
    (exact-gelu activation threaded through hidden_act)."""
    from distributed_tensorflow_tpu.models.convert import bert_from_hf
    hf = _tiny_hf_bert()
    model, params = bert_from_hf(hf)
    assert model.config.hidden_act == "gelu"
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 120, (2, 19)).astype(np.int64)
    mask = np.ones((2, 19), np.int64)
    mask[1, 12:] = 0
    with torch.no_grad():
        out = hf(torch.from_numpy(ids),
                 attention_mask=torch.from_numpy(mask))
    seq = np.asarray(model.apply(params, ids.astype(np.int32),
                                 attention_mask=mask.astype(np.int32)))
    np.testing.assert_allclose(seq, out.last_hidden_state.numpy(),
                               atol=2e-4, rtol=2e-4)
    pooled = np.asarray(model.pooled(params, seq))
    np.testing.assert_allclose(pooled, out.pooler_output.numpy(),
                               atol=2e-4, rtol=2e-4)


def _tiny_hf_vit(seed=0, classify=False):
    torch.manual_seed(seed)
    cfg = transformers.ViTConfig(
        hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
        intermediate_size=64, image_size=16, patch_size=8,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        num_labels=5)
    cls = (transformers.ViTForImageClassification if classify
           else transformers.ViTModel)
    return cls(cfg).eval()


def test_vit_features_match_torch():
    from distributed_tensorflow_tpu.models.convert import vit_from_hf
    hf = _tiny_hf_vit()
    model, params = vit_from_hf(hf)
    imgs = np.random.default_rng(0).random((2, 16, 16, 3)).astype(np.float32)
    with torch.no_grad():
        want = hf(torch.from_numpy(imgs.transpose(0, 3, 1, 2))
                  ).last_hidden_state.numpy()
    got = np.asarray(model.apply(params, imgs, return_features=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_vit_classifier_logits_match_torch():
    from distributed_tensorflow_tpu.models.convert import vit_from_hf
    hf = _tiny_hf_vit(seed=5, classify=True)
    model, params = vit_from_hf(hf)
    assert model.config.num_classes == 5
    imgs = np.random.default_rng(1).random((2, 16, 16, 3)).astype(np.float32)
    with torch.no_grad():
        want = hf(torch.from_numpy(imgs.transpose(0, 3, 1, 2))
                  ).logits.numpy()
    got = np.asarray(model.apply(params, imgs))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_bert_mlm_logits_match_torch():
    from distributed_tensorflow_tpu.models.convert import bert_from_hf
    hf = _tiny_hf_bert(seed=3, mlm=True)
    model, params = bert_from_hf(hf)
    assert "mlm" in params
    ids = np.random.default_rng(1).integers(0, 120, (2, 11)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    seq = model.apply(params, ids.astype(np.int32))
    got = np.asarray(model.mlm_logits(params, seq))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)
