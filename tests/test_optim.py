"""Optimizer tests, including TF-1.4 Adam parity (reference example.py:168)."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import optim
from distributed_tensorflow_tpu.optim import schedules


def _run(opt, grads_seq, p0=1.0):
    params = {"w": jnp.asarray(p0, jnp.float32)}
    state = opt.init(params)
    for g in grads_seq:
        updates, state = opt.update({"w": jnp.asarray(g, jnp.float32)},
                                    state, params)
        params = optim.apply_updates(params, updates)
    return float(params["w"]), state


def test_adam_matches_tf14_formula():
    """Replicate TF 1.4 AdamOptimizer by hand: lr_t = lr*sqrt(1-b2^t)/(1-b1^t);
    p -= lr_t * m / (sqrt(v) + eps)."""
    lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8
    grads = [0.5, -0.3, 0.8, 0.1]
    p, m, v = 1.0, 0.0, 0.0
    for t, g in enumerate(grads, start=1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        p -= lr_t * m / (np.sqrt(v) + eps)
    got, state = _run(optim.adam(lr), grads)
    np.testing.assert_allclose(got, p, rtol=1e-6)
    assert int(state.count) == 4


def test_sgd_and_momentum():
    got, _ = _run(optim.sgd(0.1), [1.0, 1.0])
    np.testing.assert_allclose(got, 0.8, rtol=1e-6)
    got, _ = _run(optim.momentum(0.1, beta=0.9), [1.0, 1.0])
    # mu1=1, p=0.9; mu2=1.9, p=0.9-0.19=0.71
    np.testing.assert_allclose(got, 0.71, rtol=1e-6)


def test_adamw_decays_matrices_not_vectors():
    opt = optim.adamw(1e-2, weight_decay=0.5)
    params = {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))}
    state = opt.init(params)
    zero_grads = jax.tree.map(jnp.zeros_like, params)
    updates, state = opt.update(zero_grads, state, params)
    assert float(jnp.max(jnp.abs(updates["bias"]))) == 0.0
    assert float(jnp.max(jnp.abs(updates["kernel"]))) > 0.0


def test_global_norm_clip():
    tree = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
    clipped, norm = optim.clip_by_global_norm(tree, 1.0)
    np.testing.assert_allclose(float(norm), 5.0, rtol=1e-6)
    np.testing.assert_allclose(float(optim.global_norm(clipped)), 1.0,
                               rtol=1e-5)


def test_optimizer_state_jits():
    opt = optim.adam()
    params = {"w": jnp.ones((4, 4))}
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        g = jax.tree.map(jnp.ones_like, params)
        updates, state = opt.update(g, state, params)
        return optim.apply_updates(params, updates), state

    params, state = step(params, state)
    assert int(state.count) == 1


def test_schedules():
    c = schedules.constant(0.1)(jnp.asarray(100))
    np.testing.assert_allclose(float(c), 0.1, rtol=1e-6)
    cos = schedules.cosine_decay(1.0, 100)
    np.testing.assert_allclose(float(cos(jnp.asarray(0))), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(cos(jnp.asarray(100))), 0.0, atol=1e-6)
    warm = schedules.warmup_linear_decay(1.0, 10, 110)
    np.testing.assert_allclose(float(warm(jnp.asarray(5))), 0.5, atol=1e-6)
    np.testing.assert_allclose(float(warm(jnp.asarray(110))), 0.0, atol=1e-6)
    pw = schedules.piecewise_constant([10, 20], [1.0, 0.1, 0.01])
    assert float(pw(jnp.asarray(5))) == 1.0
    np.testing.assert_allclose(float(pw(jnp.asarray(15))), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(pw(jnp.asarray(25))), 0.01, rtol=1e-6)


def test_schedule_in_adam():
    sched = schedules.exponential_decay(1e-3, 10, 0.5)
    got, _ = _run(optim.adam(sched), [0.5] * 3)
    assert got < 1.0


def test_fused_adam_matches_reference_adam():
    """optim.adam(fused=True) — the Pallas kernel path (interpret mode on
    CPU) — produces the same updates as the XLA-op path."""
    import numpy as np
    key = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(key, (37, 13)),
              "b": jnp.zeros((13,))}
    # eps large enough that the epsilon-placement variant (hat-form vs
    # TF-1.4 form) would diverge visibly if the fused path used the wrong one
    ref = optim.adam(2e-3, eps=1e-3)
    fus = optim.adam(2e-3, eps=1e-3, fused=True)
    s_ref, s_fus = ref.init(params), fus.init(params)
    p_ref = p_fus = params
    for i in range(3):
        g = jax.tree.map(
            lambda p: jax.random.normal(jax.random.PRNGKey(i), p.shape),
            params)
        u_ref, s_ref = ref.update(g, s_ref, p_ref)
        p_ref = optim.apply_updates(p_ref, u_ref)
        u_fus, s_fus = fus.update(g, s_fus, p_fus)
        p_fus = optim.apply_updates(p_fus, u_fus)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6), p_ref, p_fus)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6),
        s_ref.inner, s_fus.inner)


def test_fused_adamw_trains_under_jit():
    import numpy as np
    from distributed_tensorflow_tpu import data, ops, train
    model = ops.serial(ops.Dense(16, "relu"), ops.Dense(32, "sigmoid"))
    opt = optim.adamw(1e-3, fused=True)
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (64,))
    step = train.make_train_step(model, "mse", opt)
    (xt, yt), _ = data.xor_data(200, val_size=10, seed=0)
    first = None
    for i in range(10):
        state, m = step(state, (xt[:100], yt[:100]))
        if i == 0:
            first = float(m["loss"])
    assert float(m["loss"]) < first


def test_fused_adam_requires_params():
    import pytest
    opt = optim.adam(fused=True)
    s = opt.init({"w": jnp.zeros((4,))})
    with pytest.raises(ValueError, match="needs params"):
        opt.update({"w": jnp.ones((4,))}, s, None)


def test_lamb_trains_and_trust_ratio_behaves():
    """LAMB: converges on a toy problem; biases skip the trust ratio."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu import models, optim, train

    model = models.mnist_mlp(num_classes=4)
    opt = optim.lamb(1e-2)
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (784,))
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 opt)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 784))
    y = (jax.random.uniform(jax.random.PRNGKey(2), (64,)) * 4).astype(
        jnp.int32)
    losses = []
    for _ in range(30):
        state, m = step(state, (x, y))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.5
    assert np.isfinite(losses[-1])


def test_lamb_registry_and_zero_param_safety():
    import jax.numpy as jnp
    from distributed_tensorflow_tpu import optim

    opt = optim.get("lamb")
    params = {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}
    s = opt.init(params)
    g = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    updates, s = opt.update(g, s, params)
    # zero-norm params: trust ratio must fall back to 1, not 0/inf
    assert bool(jnp.isfinite(updates["w"]).all())
    assert float(jnp.abs(updates["w"]).max()) > 0


def test_adafactor_memory_layout_and_convergence():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_tensorflow_tpu import models, optim, train

    model = models.mnist_mlp(num_classes=4)
    opt = optim.adafactor()           # relative-step mode
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (784,))
    # factored: [784,128] kernel keeps [784]+[128] vectors, no full moment
    vr = state.opt_state.inner["vr"]["dense"]["kernel"]
    vc = state.opt_state.inner["vc"]["dense"]["kernel"]
    v = state.opt_state.inner["v"]["dense"]["kernel"]
    assert vr.shape == (784,) and vc.shape == (128,) and v.shape == (0,)
    # biases keep a full moment
    assert state.opt_state.inner["v"]["dense"]["bias"].shape == (128,)

    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 opt)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 784))
    y = (jax.random.uniform(jax.random.PRNGKey(2), (64,)) * 4).astype(
        jnp.int32)
    losses = []
    for _ in range(40):
        state, m = step(state, (x, y))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7 and np.isfinite(losses[-1])


def test_adafactor_explicit_lr_and_zero_placement():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from distributed_tensorflow_tpu import models, optim, train
    from distributed_tensorflow_tpu.parallel import make_mesh
    from distributed_tensorflow_tpu.parallel.sharding import PartitionRules

    mesh = make_mesh({"fsdp": 8})
    model = models.mnist_mlp(num_classes=4)
    opt = optim.adafactor(1e-3)
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0), (784,))
    rules = PartitionRules([(r"kernel", P("fsdp", None))])
    state = train.shard_train_state(state, mesh, rules)  # must not crash
    # params sharded; factored vectors replicated
    assert "fsdp" in str(state.params["dense"]["kernel"].sharding.spec)
    assert state.opt_state.inner["vr"]["dense"]["kernel"].sharding.spec \
        == P()
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 opt)
    x = jnp.ones((8, 784))
    y = jnp.zeros((8,), jnp.int32)
    state, m = step(state, (x, y))
    assert np.isfinite(float(m["loss"]))


def test_rmsprop_matches_tf_formula():
    """tf.train.RMSPropOptimizer rule: ms = d*ms+(1-d)*g^2;
    mom = mu*mom + lr*g/sqrt(ms+eps); p -= mom."""
    lr, d, mu, eps = 0.1, 0.9, 0.5, 1e-10
    grads = [1.0, 0.5, -0.25]
    p, ms, mom = 1.0, 0.0, 0.0
    for g in grads:
        ms = d * ms + (1 - d) * g * g
        mom = mu * mom + lr * g / np.sqrt(ms + eps)
        p -= mom
    got, state = _run(optim.rmsprop(lr, decay=d, momentum=mu, eps=eps), grads)
    np.testing.assert_allclose(got, p, rtol=1e-5)
    assert int(state.count) == 3


def test_rmsprop_centered_finite_and_trains():
    got, _ = _run(optim.rmsprop(0.1, centered=True), [1.0] * 5)
    assert np.isfinite(got) and got < 1.0


def test_adagrad_matches_tf_formula():
    """tf.train.AdagradOptimizer: acc starts at 0.1; p -= lr*g/sqrt(acc)."""
    lr, iav = 0.1, 0.1
    grads = [1.0, 1.0, -2.0]
    p, acc = 1.0, iav
    for g in grads:
        acc += g * g
        p -= lr * g / np.sqrt(acc)
    got, _ = _run(optim.adagrad(lr, initial_accumulator_value=iav), grads)
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_adadelta_matches_formula():
    lr, rho, eps = 1.0, 0.95, 1e-6
    grads = [1.0, -0.5, 2.0]
    p, ag, ad = 1.0, 0.0, 0.0
    for g in grads:
        ag = rho * ag + (1 - rho) * g * g
        delta = np.sqrt(ad + eps) / np.sqrt(ag + eps) * g
        ad = rho * ad + (1 - rho) * delta * delta
        p -= lr * delta
    got, _ = _run(optim.adadelta(lr, rho=rho, eps=eps), grads)
    np.testing.assert_allclose(got, p, rtol=1e-5)


def test_ftrl_l1_produces_exact_zeros():
    """FTRL-Proximal closed form: small gradients with l1 > 0 pin the
    weight at exactly 0 (the sparsity property Ftrl exists for)."""
    opt = optim.ftrl(0.1, l1_regularization_strength=10.0)
    got, _ = _run(opt, [0.01, -0.02, 0.01], p0=0.0)
    assert got == 0.0
    # and with no regularization it moves like a (per-coord) adaptive step
    got, _ = _run(optim.ftrl(0.1), [1.0, 1.0])
    assert 0.0 < got < 1.0


def test_ftrl_requires_params():
    import pytest
    opt = optim.ftrl()
    s = opt.init({"w": jnp.zeros((4,))})
    with pytest.raises(ValueError, match="needs params"):
        opt.update({"w": jnp.ones((4,))}, s, None)


def test_new_optimizers_in_registry_and_jit():
    for name in ("rmsprop", "adagrad", "adadelta", "ftrl"):
        opt = optim.get(name)
        params = {"w": jnp.ones((4, 4))}
        state = opt.init(params)

        @jax.jit
        def step(params, state, opt=opt):
            g = jax.tree.map(jnp.ones_like, params)
            updates, state = opt.update(g, state, params)
            return optim.apply_updates(params, updates), state

        params, state = step(params, state)
        assert int(state.count) == 1
        assert bool(jnp.isfinite(params["w"]).all())


def test_polynomial_decay_schedule():
    s = schedules.polynomial_decay(1.0, 100, end_value=0.1, power=2.0)
    np.testing.assert_allclose(float(s(jnp.asarray(0))), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(s(jnp.asarray(50))),
                               0.9 * 0.25 + 0.1, atol=1e-6)
    np.testing.assert_allclose(float(s(jnp.asarray(100))), 0.1, atol=1e-6)
    # clamp past the horizon
    np.testing.assert_allclose(float(s(jnp.asarray(500))), 0.1, atol=1e-6)
    # cycle=True restarts the horizon instead of clamping
    c = schedules.polynomial_decay(1.0, 100, end_value=0.1, cycle=True)
    assert float(c(jnp.asarray(150))) > 0.1


def test_no_aliased_buffers_in_fresh_state():
    """Every optimizer (and the lr-scale/EMA wrapper compositions) must
    initialize a TrainState whose leaves all own distinct buffers: one
    buffer appearing in two pytree slots breaks donation at the first
    dispatch ("Attempt to donate the same buffer twice") — the bug
    with_lr_scale had when it mirrored inner.count."""
    import jax
    import jax.numpy as jnp
    from distributed_tensorflow_tpu import optim, train
    from distributed_tensorflow_tpu.optim import optimizers as opt_mod

    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    builders = {name: (lambda n=name: optim.get(n))
                for name in sorted(opt_mod._REGISTRY)}
    builders["lr_scale(adam)"] = lambda: opt_mod.with_lr_scale(optim.adam())
    builders["ema(adam)"] = lambda: optim.with_ema(optim.adam())
    builders["lr_scale(ema(adam))"] = (
        lambda: opt_mod.with_lr_scale(optim.with_ema(optim.adam())))
    for label, build in builders.items():
        opt = build()
        state = train.TrainState.create(params, opt.init(params))
        seen = {}
        for path, leaf in jax.tree_util.tree_leaves_with_path(state):
            try:
                ptr = leaf.unsafe_buffer_pointer()
            except Exception:
                continue
            assert ptr not in seen, (
                f"{label}: {jax.tree_util.keystr(path)} shares a buffer "
                f"with {seen[ptr]}")
            seen[ptr] = jax.tree_util.keystr(path)


def test_no_aliased_buffers_after_update():
    """The update path must not reintroduce the aliased count either:
    returning inner.count in the wrapper slot puts one jaxpr value in two
    output leaves, which a deduping backend can alias to one buffer."""
    import jax
    import jax.numpy as jnp
    from distributed_tensorflow_tpu import optim
    from distributed_tensorflow_tpu.optim import optimizers as opt_mod

    opt = opt_mod.with_lr_scale(optim.adam())
    params = {"w": jnp.ones((4, 4))}
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        grads = {"w": jnp.ones((4, 4))}
        updates, new_state = opt.update(grads, state, params)
        return opt_mod.apply_updates(params, updates), new_state

    _, state = step(params, state)
    assert int(state.count) == int(state.inner["inner"].count) == 1
    seen = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(state):
        try:
            ptr = leaf.unsafe_buffer_pointer()
        except Exception:
            continue
        assert ptr not in seen, (
            f"{jax.tree_util.keystr(path)} shares a buffer with {seen[ptr]}")
        seen[ptr] = jax.tree_util.keystr(path)
