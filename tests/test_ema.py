"""EMA parameter-averaging tests (tf.train.ExponentialMovingAverage
capability, rebuilt functional)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu import models, optim, train


def test_standalone_ema_tracks_constant():
    e = optim.ema(0.9)
    params = {"w": jnp.full((3,), 5.0)}
    s = e.init(params)
    for _ in range(200):
        s = e.update(s, params)
    np.testing.assert_allclose(np.asarray(e.value(s)["w"]),
                               np.full(3, 5.0), rtol=1e-5)


def test_debias_exact_after_first_update():
    e = optim.ema(0.9, debias=True)
    params = {"w": jnp.asarray([2.0, -4.0])}
    s = e.update(e.init(params), params)
    # shadow = 0.1*p; debias scale = 1/(1-0.9) = 10 -> exactly p
    np.testing.assert_allclose(np.asarray(e.value(s)["w"]),
                               [2.0, -4.0], rtol=1e-6)


def test_with_ema_rides_train_step_and_checkpoints(tmp_path):
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.with_ema(optim.adam(), decay=0.5)
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 optimizer)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 784))
    y = jnp.zeros((8,), jnp.int32)
    for _ in range(3):
        state, m = step(state, (x, y))
    assert int(state.opt_state.count) == 3
    avg = optim.ema_params(state.opt_state)
    # EMA stays within the convex hull of visited params: same structure,
    # finite, and distinct from the live params.
    live = state.params
    assert jax.tree_util.tree_structure(avg) == \
        jax.tree_util.tree_structure(live)
    diffs = [float(jnp.max(jnp.abs(a - b)))
             for a, b in zip(jax.tree.leaves(avg), jax.tree.leaves(live))]
    assert all(np.isfinite(d) for d in diffs) and any(d > 0 for d in diffs)

    # Rides the checkpoint subsystem unchanged.
    from distributed_tensorflow_tpu.train import checkpoint as ck
    d = str(tmp_path)
    ck.save(d, 3, state)
    target = train.init_train_state(model, optimizer, jax.random.PRNGKey(2),
                                    (784,))
    out = ck.restore(target, ck.latest_checkpoint(d))
    for a, b in zip(jax.tree.leaves(optim.ema_params(out.opt_state)),
                    jax.tree.leaves(avg)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ema_params_requires_wrapper():
    opt = optim.adam()
    s = opt.init({"w": jnp.ones(2)})
    with pytest.raises(ValueError, match="with_ema"):
        optim.ema_params(s)


def test_with_ema_matches_manual_average():
    """Wrapper shadow equals hand-rolled decay recursion on post-update
    params (sgd, so updates are deterministic)."""
    d = 0.8
    optimizer = optim.with_ema(optim.sgd(0.1), decay=d, debias=False)
    params = {"w": jnp.asarray([1.0, 2.0])}
    opt_state = optimizer.init(params)
    shadow = np.zeros(2)
    p = np.asarray([1.0, 2.0])
    for i in range(4):
        grads = {"w": jnp.asarray([0.5, -0.5]) * (i + 1)}
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optim.apply_updates(params, updates)
        p = p - 0.1 * np.asarray([0.5, -0.5]) * (i + 1)
        shadow = d * shadow + (1 - d) * p
    np.testing.assert_allclose(
        np.asarray(optim.ema_params(opt_state)["w"]), shadow, rtol=1e-5)


def test_shard_train_state_shards_ema_shadow_and_moments():
    """ZeRO placement must reach through with_ema: Adam m/v AND the shadow
    shard like the params instead of silently replicating."""
    from jax.sharding import PartitionSpec as P
    from distributed_tensorflow_tpu.parallel import make_mesh
    from distributed_tensorflow_tpu.parallel.sharding import PartitionRules

    mesh = make_mesh({"fsdp": 8})
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.with_ema(optim.adam(), decay=0.9)
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    rules = PartitionRules([(r"kernel", P("fsdp", None))])
    state = train.shard_train_state(state, mesh, rules)

    def kernel_specs(tree):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        return [leaf.sharding.spec for path, leaf in flat
                if "kernel" in jax.tree_util.keystr(path)]

    for tree in (state.params,
                 state.opt_state.inner["opt"].inner,   # adam m/v
                 state.opt_state.inner["ema"].shadow):
        specs = kernel_specs(tree)
        assert specs and all(s == P("fsdp", None) for s in specs), tree


def test_ema_shadow_dtype_stable_under_scan():
    """bf16 shadow must keep its dtype across updates (lax.scan carry and
    buffer donation demand a step-invariant state type)."""
    model = models.mnist_mlp(num_classes=4)
    optimizer = optim.with_ema(optim.adam(), decay=0.9)
    state = train.init_train_state(model, optimizer, jax.random.PRNGKey(0),
                                   (784,))
    # force a bf16 shadow (as a bf16-params run would produce)
    ema0 = state.opt_state.inner["ema"]
    state = state._replace(opt_state=state.opt_state._replace(inner={
        "opt": state.opt_state.inner["opt"],
        "ema": ema0._replace(shadow=jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), ema0.shadow))}))
    multi = train.make_multi_train_step(
        model, "sparse_categorical_crossentropy", optimizer, steps_per_call=3)
    xs = jnp.ones((3, 8, 784))
    ys = jnp.zeros((3, 8), jnp.int32)
    state2, m = multi(state, (xs, ys))  # traces: carry types must match
    for leaf in jax.tree.leaves(state2.opt_state.inner["ema"].shadow):
        assert leaf.dtype == jnp.bfloat16
