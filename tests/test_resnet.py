"""ResNet family tests (BASELINE config #4 capability)."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import optim, train
from distributed_tensorflow_tpu.models.resnet import (ResNet, resnet50,
                                                      resnet_cifar)
from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.sharding import shard_pytree


def test_resnet50_canonical_param_count():
    model = resnet50()
    params, state = model.init(jax.random.PRNGKey(0), (224, 224, 3))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    assert n == 25_557_032  # torchvision/keras ResNet-50 count


def test_resnet50_forward_shape():
    model = resnet50(num_classes=1000)
    params, state = model.init(jax.random.PRNGKey(0), (224, 224, 3))
    logits, _ = model.apply(params, state, jnp.ones((1, 224, 224, 3)))
    assert logits.shape == (1, 1000)


def test_resnet_cifar_trains_and_updates_bn():
    model = resnet_cifar()
    opt = optim.momentum(0.01, 0.9)
    state = train.init_train_state(model, opt, jax.random.PRNGKey(0),
                                   (32, 32, 3))
    step = train.make_train_step(model, "sparse_categorical_crossentropy",
                                 opt, metric_fns={"acc": "accuracy"})
    x = np.random.default_rng(0).random((16, 32, 32, 3), np.float32)
    y = np.random.default_rng(1).integers(0, 10, 16).astype(np.int32)
    bn_before = np.asarray(state.model_state["stem_bn"]["mean"]).copy()
    state, m = step(state, (x, y))
    assert np.isfinite(float(m["loss"]))
    bn_after = np.asarray(state.model_state["stem_bn"]["mean"])
    assert not np.array_equal(bn_before, bn_after)
    # eval path: running stats, no state mutation
    ev = train.make_eval_step(model, "sparse_categorical_crossentropy",
                              metric_fns={"acc": "accuracy"})
    out = ev(state, (x, y))
    assert np.isfinite(float(out["loss"]))


def test_resnet_partition_rules_on_mesh():
    mesh = make_mesh({"data": 4, "tensor": 2})
    model = resnet_cifar()
    params, _ = model.init(jax.random.PRNGKey(0), (32, 32, 3))
    sharded = shard_pytree(params, mesh, ResNet.partition_rules())
    stem = sharded["stem"]["kernel"]
    assert "tensor" in str(stem.sharding.spec)


def test_fresh_instance_applies_restored_params():
    """Model structure must not depend on init() side effects (regression):
    a fresh instance applies params produced by another instance."""
    m1 = resnet_cifar()
    params, state = m1.init(jax.random.PRNGKey(0), (32, 32, 3))
    m2 = resnet_cifar()  # never init()ed
    logits, _ = m2.apply(params, state, jnp.ones((2, 32, 32, 3)))
    assert logits.shape == (2, 10)


def test_head_key_independent_of_blocks():
    m = resnet_cifar()
    params, _ = m.init(jax.random.PRNGKey(0), (32, 32, 3))
    last_block = sorted(k for k in params if k.startswith("stage"))[-1]
    head = np.asarray(params["head"]["kernel"]).ravel()
    blk = np.asarray(params[last_block]["conv1"]["kernel"]).ravel()
    n = min(len(head), len(blk))
    corr = np.corrcoef(head[:n], blk[:n])[0, 1]
    assert abs(corr) < 0.2
