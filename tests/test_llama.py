"""Llama family (models/llama.py): block recipe + HF interop parity.

Hermetic under zero egress, like tests/test_convert.py: the interop tests
build a RANDOM-initialized tiny ``transformers.LlamaForCausalLM``
in-process — the layout mapping they verify is what a real checkpoint
exercises (rotate-half RoPE, GQA head folding, swiglu gate/up/down,
RMSNorm, untied head).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu import optim, train
from distributed_tensorflow_tpu.models.llama import llama_config, llama_tiny


class TestLlamaRecipe:
    def test_config_recipe(self):
        c = llama_config(vocab_size=64, hidden_size=32, num_layers=1,
                         num_heads=2, intermediate_size=48, max_position=16)
        assert c.norm == "rmsnorm"
        assert c.ffn_activation == "swiglu"
        assert c.position_embedding == "rope"
        assert not c.use_bias and not c.tied_head

    def test_param_tree_shape(self):
        m = llama_tiny()
        p = m.init(jax.random.PRNGKey(0))
        layer0 = jax.tree.map(lambda x: x[0], p["decoder"])
        # no biases anywhere, rmsnorm has no beta, swiglu has a gate
        assert "bias" not in layer0["attention"]["query"]
        assert "bias" not in layer0["ffn"]["w_in"]
        assert "beta" not in layer0["ln_1"] and "beta" not in p["ln_f"]
        assert layer0["ffn"]["w_gate"]["kernel"].shape == \
            layer0["ffn"]["w_in"]["kernel"].shape
        # untied head, GQA kv projections
        assert p["lm_head"].shape == (512, 128)
        assert layer0["attention"]["key"]["kernel"].shape == (128, 2, 32)

    def test_forward_and_loss(self):
        m = llama_tiny()
        p = m.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)
        h = m.apply(p, ids)
        assert h.shape == (2, 16, 128)
        loss, _ = m.lm_loss_fn()(p, {}, {"input_ids": ids},
                                 jax.random.PRNGKey(2), False)
        assert np.isfinite(float(loss))

    def test_generate_matches_decode_semantics(self):
        """Full-sequence forward and the GQA KV-cache decode agree: greedy
        generate teacher-forces the prompt, so the first sampled token must
        equal the argmax of the full forward's last-position logits."""
        m = llama_tiny()
        p = m.init(jax.random.PRNGKey(0))
        prompt = jnp.asarray([[3, 7, 11, 2]], jnp.int32)
        out = m.generate(p, prompt, max_new_tokens=3, temperature=0.0)
        h = m.apply(p, prompt)
        want_first = int(jnp.argmax(m.logits(p, h)[0, -1]))
        assert int(out[0, 4]) == want_first

    def test_trains(self):
        m = llama_tiny()
        p = m.init(jax.random.PRNGKey(0))
        opt = optim.adam(1e-3)
        step = train.make_custom_train_step(m.lm_loss_fn(), opt)
        state = train.TrainState.create(p, opt.init(p))
        ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 512)
        losses = []
        for _ in range(5):
            state, metrics = step(state, {"input_ids": ids})
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_partition_rules_cover_tree(self):
        from jax.sharding import PartitionSpec as P
        m = llama_tiny()
        p = m.init(jax.random.PRNGKey(0))
        specs = m.partition_rules().tree_specs(p)
        flat = dict(
            zip(["/".join(str(k.key) for k in path)
                 for path, _ in jax.tree_util.tree_flatten_with_path(p)[0]],
                jax.tree_util.tree_leaves(specs, is_leaf=lambda s:
                                          isinstance(s, P))))
        assert flat["lm_head"] == P("tensor", None)
        assert flat["decoder/ffn/w_gate/kernel"] == \
            flat["decoder/ffn/w_in/kernel"]


transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_hf_llama(seed=0, kv_heads=2, tie=False):
    torch.manual_seed(seed)
    cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kv_heads, max_position_embeddings=64,
        rms_norm_eps=1e-5, rope_theta=10000.0, attention_dropout=0.0,
        tie_word_embeddings=tie)
    return transformers.LlamaForCausalLM(cfg).eval()


class TestLlamaHFInterop:
    def test_logits_match_torch_gqa(self):
        from distributed_tensorflow_tpu.models.convert import llama_from_hf
        hf = _tiny_hf_llama()
        model, params = llama_from_hf(hf)
        c = model.config
        assert c.norm == "rmsnorm" and c.kv_heads == 2 and not c.tied_head
        ids = np.random.default_rng(0).integers(0, 96, (2, 13)
                                                ).astype(np.int64)
        with torch.no_grad():
            want = hf(torch.from_numpy(ids)).logits.numpy()
        got = np.asarray(model.logits(params, model.apply(
            params, ids.astype(np.int32))))
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)

    def test_logits_match_torch_mha_tied(self):
        from distributed_tensorflow_tpu.models.convert import llama_from_hf
        hf = _tiny_hf_llama(seed=1, kv_heads=4, tie=True)
        model, params = llama_from_hf(hf)
        assert model.config.tied_head and "lm_head" not in params
        ids = np.random.default_rng(1).integers(0, 96, (1, 9)
                                                ).astype(np.int64)
        with torch.no_grad():
            want = hf(torch.from_numpy(ids)).logits.numpy()
        got = np.asarray(model.logits(params, model.apply(
            params, ids.astype(np.int32))))
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)

    def test_generate_greedy_matches_torch(self):
        """Greedy decode through OUR GQA KV cache == transformers'."""
        from distributed_tensorflow_tpu.models.convert import llama_from_hf
        hf = _tiny_hf_llama(seed=2)
        model, params = llama_from_hf(hf)
        prompt = np.asarray([[5, 9, 2, 41]], np.int64)
        with torch.no_grad():
            want = hf.generate(torch.from_numpy(prompt), max_new_tokens=8,
                               do_sample=False, pad_token_id=0).numpy()
        got = np.asarray(model.generate(params, prompt.astype(np.int32),
                                        max_new_tokens=8, temperature=0.0))
        np.testing.assert_array_equal(got, want)

    def test_rejects_rope_scaling(self):
        from distributed_tensorflow_tpu.models.convert import (
            llama_config_from_hf)
        cfg = _tiny_hf_llama().config
        cfg.rope_scaling = {"rope_type": "linear", "factor": 2.0}
        with pytest.raises(ValueError, match="rope_scaling"):
            llama_config_from_hf(cfg)
