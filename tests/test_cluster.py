"""Cluster bootstrap tests (reference example.py:59-68,108-143 capability)."""
import pytest

from distributed_tensorflow_tpu.parallel import cluster


def test_single_machine_fallback():
    """No env vars => local config (reference example.py:111-113)."""
    cfg = cluster.cluster_from_env(environ={})
    assert not cfg.distributed
    assert cfg.process_id == 0
    assert cfg.coordinator_address is None


def test_new_style_env():
    cfg = cluster.cluster_from_env(environ={
        "COORDINATOR_ADDRESS": "host0:1234",
        "NUM_PROCESSES": "4",
        "PROCESS_ID": "2",
    })
    assert cfg.distributed
    assert cfg.num_processes == 4
    assert cfg.process_id == 2
    assert cfg.coordinator_address == "host0:1234"


def test_legacy_env_mapping():
    """Reference-style WORKER_HOSTS/TASK_INDEX map onto the new runtime."""
    cfg = cluster.cluster_from_env(environ={
        "JOB_NAME": "worker",
        "TASK_INDEX": "1",
        "PS_HOSTS": "ps0:2222",
        "WORKER_HOSTS": "w0:2222,w1:2222",
    })
    assert cfg.num_processes == 2
    assert cfg.process_id == 1  # parsed as int, unlike the reference bug
    assert cfg.coordinator_address == "w0:2222"
    assert not cfg.is_legacy_ps


def test_legacy_ps_refused():
    cfg = cluster.cluster_from_env(environ={
        "JOB_NAME": "ps",
        "TASK_INDEX": "0",
        "WORKER_HOSTS": "w0:2222",
    })
    assert cfg.is_legacy_ps
    out = cluster.initialize(cfg)  # must not try to start anything
    assert out is cfg


def test_legacy_ps_under_launcher_exits_loud(monkeypatch):
    """Under the fleet launcher the ps refusal must NOT read as a clean
    exit 0 (the launcher would count the fleet one host short as
    success): it exits LEGACY_PS_EXIT_CODE, which the launcher
    classifies fatal-with-reason (fleet/launcher.py)."""
    monkeypatch.setenv("DTTPU_LAUNCHER", "1")
    cfg = cluster.cluster_from_env(environ={
        "JOB_NAME": "ps",
        "TASK_INDEX": "0",
        "WORKER_HOSTS": "w0:2222",
    })
    with pytest.raises(SystemExit) as ei:
        cluster.initialize(cfg)
    assert ei.value.code == cluster.LEGACY_PS_EXIT_CODE == 64


def test_bad_int_env_falls_back():
    cfg = cluster.cluster_from_env(environ={
        "WORKER_HOSTS": "w0:2222,w1:2222",
        "TASK_INDEX": "zero",
    })
    assert cfg.process_id == 0


def test_is_chief_local():
    assert cluster.is_chief()


def test_two_process_bootstrap_cross_process_psum(tmp_path):
    """END-TO-END multi-host validation: two REAL processes bootstrap via
    the framework's env convention (COORDINATOR_ADDRESS/NUM_PROCESSES/
    PROCESS_ID -> jax.distributed.initialize), form one 4-device global
    CPU mesh (2 local devices each), and agree on a cross-process reduce.

    This is the TPU-native analogue of the reference's multi-process
    ClusterSpec/Server smoke path (reference example.py:124-141) — except
    there is no PS: the reduction is an XLA collective.
    """
    import os
    import socket
    import subprocess
    import sys
    import textwrap


    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {repo!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from distributed_tensorflow_tpu import parallel
        parallel.initialize()
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        assert jax.process_count() == 2
        mesh = parallel.make_mesh({{"data": len(jax.devices())}})
        n = len(jax.devices())
        x = jax.make_array_from_callback(
            (n,), NamedSharding(mesh, P("data")),
            lambda idx: np.asarray([idx[0].start], np.float32) + 1.0)
        total = jax.jit(lambda a: jnp.sum(a),
                        out_shardings=NamedSharding(mesh, P()))(x)
        print(f"RESULT proc={{jax.process_index()}} "
              f"chief={{parallel.is_chief()}} sum={{float(total)}}")
    """))

    def launch(pid, port):
        env = dict(os.environ,
                   XLA_FLAGS="--xla_force_host_platform_device_count=2",
                   JAX_PLATFORMS="cpu",
                   COORDINATOR_ADDRESS=f"localhost:{port}",
                   NUM_PROCESSES="2", PROCESS_ID=str(pid))
        return subprocess.Popen([sys.executable, str(script)], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    # bind-then-close port picking races against other processes; retry on
    # a fresh port rather than flake.  A stolen port can also HANG the
    # non-coordinator worker, so a timeout is a retryable symptom too (and
    # both children must be killed, not leaked).
    for _ in range(3):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [launch(0, port), launch(1, port)]
        outs = []
        try:
            for p in procs:
                try:
                    outs.append(p.communicate(timeout=180)[0])
                except subprocess.TimeoutExpired:
                    p.kill()
                    outs.append(p.communicate()[0] + "\n<TIMED OUT>")
        finally:
            # exception-safe: no child survives this attempt, whatever
            # interrupted it (pytest-timeout, KeyboardInterrupt, ...)
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        if all(p.returncode == 0 for p in procs):
            break
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    # 4 global devices hold [1, 2, 3, 4] -> sum 10 on every process
    assert "chief=True sum=10.0" in outs[0], outs[0]
    assert "chief=False sum=10.0" in outs[1], outs[1]
