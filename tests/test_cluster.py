"""Cluster bootstrap tests (reference example.py:59-68,108-143 capability)."""
from distributed_tensorflow_tpu.parallel import cluster


def test_single_machine_fallback():
    """No env vars => local config (reference example.py:111-113)."""
    cfg = cluster.cluster_from_env(environ={})
    assert not cfg.distributed
    assert cfg.process_id == 0
    assert cfg.coordinator_address is None


def test_new_style_env():
    cfg = cluster.cluster_from_env(environ={
        "COORDINATOR_ADDRESS": "host0:1234",
        "NUM_PROCESSES": "4",
        "PROCESS_ID": "2",
    })
    assert cfg.distributed
    assert cfg.num_processes == 4
    assert cfg.process_id == 2
    assert cfg.coordinator_address == "host0:1234"


def test_legacy_env_mapping():
    """Reference-style WORKER_HOSTS/TASK_INDEX map onto the new runtime."""
    cfg = cluster.cluster_from_env(environ={
        "JOB_NAME": "worker",
        "TASK_INDEX": "1",
        "PS_HOSTS": "ps0:2222",
        "WORKER_HOSTS": "w0:2222,w1:2222",
    })
    assert cfg.num_processes == 2
    assert cfg.process_id == 1  # parsed as int, unlike the reference bug
    assert cfg.coordinator_address == "w0:2222"
    assert not cfg.is_legacy_ps


def test_legacy_ps_refused():
    cfg = cluster.cluster_from_env(environ={
        "JOB_NAME": "ps",
        "TASK_INDEX": "0",
        "WORKER_HOSTS": "w0:2222",
    })
    assert cfg.is_legacy_ps
    out = cluster.initialize(cfg)  # must not try to start anything
    assert out is cfg


def test_bad_int_env_falls_back():
    cfg = cluster.cluster_from_env(environ={
        "WORKER_HOSTS": "w0:2222,w1:2222",
        "TASK_INDEX": "zero",
    })
    assert cfg.process_id == 0


def test_is_chief_local():
    assert cluster.is_chief()
