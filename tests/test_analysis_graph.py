"""dtlint graph tier (DT4xx): synthetic-injection fixtures per rule,
the DT405 executable census, the static cost model's unit semantics,
and the incremental result cache.

Every rule gets a planted bug (caught), a fixed twin (silent), and —
where the mechanism differs from the AST tiers — a suppression fixture
(the ``# dtlint: disable=`` comment on the REGISTRATION line, where
graph findings anchor).  Traces are abstract (ShapeDtypeStruct inputs,
CPU): nothing compiles, nothing runs.
"""
import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import analysis
from distributed_tensorflow_tpu.analysis import graph as graph_lib
from distributed_tensorflow_tpu.analysis import graph_rules

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

f32, bf16, i32 = jnp.float32, jnp.bfloat16, jnp.int32


def sds(*shape, dtype=f32):
    return jax.ShapeDtypeStruct(shape, dtype)


def run_registry(reg):
    traced = graph_lib.trace_registry(reg)
    return traced, graph_rules.run_graph_rules(traced, reg)


def rules_of(findings):
    return [f.rule for f in findings]


@pytest.fixture(scope="module")
def real_registry():
    from distributed_tensorflow_tpu.analysis import entries
    return entries.load_registry()


# ------------------------------------------------------------- DT400


def test_dt400_broken_builder_is_a_finding_not_a_crash():
    reg = graph_lib.Registry()

    @reg.trace_entry("boom")
    def build():
        raise RuntimeError("fixture builder exploded")

    traced, findings = run_registry(reg)
    assert rules_of(findings) == ["DT400"]
    assert "fixture builder exploded" in findings[0].message
    assert traced[0].error is not None


def test_dt400_broken_trace_is_a_finding_not_a_crash():
    reg = graph_lib.Registry()

    @reg.trace_entry("bad_shapes", specs=(sds(4, 8), sds(4, 8)))
    def entry(a, b):
        return a @ b          # contracting 8 against 4: trace error

    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT400"]


# ------------------------------------------------------------- DT401


def test_dt401_planted_constant_capture():
    reg = graph_lib.Registry()
    weights = np.ones((1024, 512), np.float32)      # 2 MiB closed over

    @reg.trace_entry("planted", specs=(sds(4, 1024),))
    def entry(x):
        return x @ weights

    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT401"]
    assert "2.0 MiB" in findings[0].message
    assert "planted" in findings[0].message


def test_dt401_fixed_twin_params_as_argument_silent():
    reg = graph_lib.Registry()

    @reg.trace_entry("fixed", specs=(sds(4, 1024), sds(1024, 512)))
    def entry(x, w):
        return x @ w

    _, findings = run_registry(reg)
    assert findings == []


def test_dt401_small_constants_under_threshold_silent():
    reg = graph_lib.Registry()
    table = np.arange(64, dtype=np.float32)          # 256 B: config, not weights

    @reg.trace_entry("small", specs=(sds(64,),))
    def entry(x):
        return x + table

    _, findings = run_registry(reg)
    assert findings == []


def test_dt401_suppression_on_registration_line():
    reg = graph_lib.Registry()
    weights = np.ones((1024, 512), np.float32)
    spec = (sds(4, 1024),)

    @reg.trace_entry("sup", specs=spec)  # dtlint: disable=DT401
    def entry(x):
        return x @ weights

    _, findings = run_registry(reg)
    assert findings == []


# ------------------------------------------------------------- DT402


def test_dt402_planted_f32_upcast_of_bf16_matmul():
    reg = graph_lib.Registry()

    @reg.trace_entry("planted", specs=(sds(4, 8, dtype=bf16),
                                       sds(8, 8, dtype=bf16)))
    def entry(x, w):
        return x.astype(jnp.float32) @ w.astype(jnp.float32)

    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT402"]
    assert findings[0].severity == "warning"
    assert "bfloat16" in findings[0].message


def test_dt402_fixed_twin_bf16_matmul_silent():
    reg = graph_lib.Registry()

    @reg.trace_entry("fixed", specs=(sds(4, 8, dtype=bf16),
                                     sds(8, 8, dtype=bf16)))
    def entry(x, w):
        return x @ w

    _, findings = run_registry(reg)
    assert findings == []


def test_dt402_preferred_element_type_accumulation_silent():
    # bf16 operands accumulated in f32 via preferred_element_type is
    # the GOOD mixed-precision pattern (MXU accumulate): never flagged
    reg = graph_lib.Registry()

    @reg.trace_entry("good", specs=(sds(4, 8, dtype=bf16),
                                    sds(8, 8, dtype=bf16)))
    def entry(x, w):
        return jax.lax.dot(x, w, preferred_element_type=jnp.float32)

    _, findings = run_registry(reg)
    assert findings == []


def test_dt402_x64_leakage_is_an_error():
    from jax.experimental import enable_x64
    reg = graph_lib.Registry()

    @reg.trace_entry("leak", specs=(sds(8),))
    def entry(x):
        return x * jnp.arange(8, dtype=jnp.float64).sum()

    with enable_x64():
        traced = graph_lib.trace_registry(reg)
    findings = graph_rules.run_graph_rules(traced, reg)
    assert "DT402" in rules_of(findings)
    assert any(f.severity == "error" and "64-bit" in f.message
               for f in findings)


# ------------------------------------------------------------- DT403


def test_dt403_planted_dead_donation():
    reg = graph_lib.Registry()

    @reg.trace_entry("planted")
    def build():
        # [8,8] donated, but the only output is [8]: nothing to alias
        step = jax.jit(lambda s: jnp.sum(s, axis=0), donate_argnums=(0,))
        return graph_lib.Target("", step, (sds(8, 8),))

    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT403"]
    assert "float32[8,8]" in findings[0].message


def test_dt403_fixed_twin_aliasable_donation_silent():
    reg = graph_lib.Registry()

    @reg.trace_entry("fixed")
    def build():
        step = jax.jit(lambda s: s + 1.0, donate_argnums=(0,))
        return graph_lib.Target("", step, (sds(8, 8),))

    _, findings = run_registry(reg)
    assert findings == []


def test_dt403_passthrough_donation_silent():
    # an input returned unchanged is pruned from the traced call's
    # outputs, but the caller gets the same buffer back — identity
    # aliasing, not a rejected donation
    reg = graph_lib.Registry()

    @reg.trace_entry("passthrough")
    def build():
        step = jax.jit(lambda d: dict(d, k=d["k"] + 1),
                       donate_argnums=(0,))
        return graph_lib.Target("", step,
                                ({"k": sds(8), "meta": sds(2, dtype=i32)},))

    _, findings = run_registry(reg)
    assert findings == []


# ------------------------------------------------------------- DT404


def test_dt404_planted_budget_blowout():
    reg = graph_lib.Registry()

    @reg.trace_entry("planted", specs=(sds(256, 256),), hbm_budget=1000)
    def entry(x):
        return (x @ x).sum()

    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT404"]
    assert "exceeds its declared HBM budget" in findings[0].message


def test_dt404_fixed_twin_inside_budget_silent():
    reg = graph_lib.Registry()

    @reg.trace_entry("fixed", specs=(sds(256, 256),),
                     hbm_budget=16 << 20)
    def entry(x):
        return (x @ x).sum()

    _, findings = run_registry(reg)
    assert findings == []


def test_dt404_no_budget_declared_never_fires():
    reg = graph_lib.Registry()

    @reg.trace_entry("unbudgeted", specs=(sds(512, 512),))
    def entry(x):
        return x @ x

    _, findings = run_registry(reg)
    assert findings == []


# ------------------------------------------------------------- DT405


def _register_n_distinct(reg, n, group="g"):
    # n structurally distinct programs (different shapes => different
    # signatures), registered one entry each
    for k in range(n):
        shape = (4 + k, 4 + k)

        @reg.trace_entry(f"e{k}", group=group,
                         specs=(jax.ShapeDtypeStruct(shape, f32),))
        def entry(x):
            return x * 2.0


def test_dt405_census_exact_count_silent():
    reg = graph_lib.Registry()
    reg.expect_census("g", 3)
    _register_n_distinct(reg, 3)
    _, findings = run_registry(reg)
    assert findings == []


def test_dt405_extra_executable_caught():
    reg = graph_lib.Registry()
    reg.expect_census("g", 3)
    _register_n_distinct(reg, 4)
    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT405"]
    assert "4 distinct" in findings[0].message


def test_dt405_missing_executable_caught():
    reg = graph_lib.Registry()
    reg.expect_census("g", 3)
    _register_n_distinct(reg, 2)
    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT405"]
    assert "2 distinct" in findings[0].message


def test_dt405_counts_signatures_not_entries():
    # two registrations tracing the IDENTICAL program are ONE executable
    reg = graph_lib.Registry()
    reg.expect_census("g", 2)
    for name in ("a", "b"):
        @reg.trace_entry(name, group="g", specs=(sds(4, 4),))
        def entry(x):
            return x * 2.0
    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT405"]
    assert "1 distinct" in findings[0].message


def test_dt405_failed_member_makes_census_unverifiable():
    reg = graph_lib.Registry()
    reg.expect_census("g", 1)

    @reg.trace_entry("broken", group="g")
    def build():
        raise RuntimeError("gone")

    _, findings = run_registry(reg)
    assert set(rules_of(findings)) == {"DT400", "DT405"}
    assert any("unverifiable" in f.message for f in findings
               if f.rule == "DT405")


# ------------------------------------------- the real serve census


def test_serve_census_pins_exactly_three_hot_executables(real_registry):
    """THE serving invariant, statically: the scheduler's registered
    entries trace to exactly 3 distinct executables and the whole real
    registry lints clean."""
    traced, findings = run_registry(real_registry)
    assert findings == [], [f.message for f in findings]
    serve = [t for t in traced if t.group == "serve-hot"]
    assert len(serve) == 3
    assert len({t.signature for t in serve}) == 3
    assert {t.name for t in serve} == {
        "serve.prefill_window", "serve.admit", "serve.decode_tick"}


def test_serve_census_fourth_executable_fails_lint(real_registry):
    """Adding a fourth jitted program to the hot set (what an
    untraced-arg branch or a new per-admission compile would do) turns
    into a DT405 lint failure, not a runtime retrace warning."""
    reg = real_registry.clone()

    @reg.trace_entry("rogue", group="serve-hot", specs=(sds(2, 2),))
    def entry(x):
        return x * 2.0

    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT405"]
    assert "4 distinct" in findings[0].message


def test_serve_census_deleting_an_executable_fails_lint(real_registry):
    """Deleting one of the three shared executables (e.g. folding the
    admit program into the tick) breaks the census the other way."""
    reg = real_registry.clone()
    serve = [e for e in reg.entries if e.name == "serve"][0]
    crippled = dataclasses.replace(
        serve, build=lambda: serve.build()[:2])
    reg.entries = [e for e in reg.entries if e.name != "serve"]
    reg.entries.append(crippled)
    _, findings = run_registry(reg)
    assert rules_of(findings) == ["DT405"]
    assert "2 distinct" in findings[0].message


# ------------------------------------------------------ cost model


def test_cost_model_matmul_flops_and_bytes_exact():
    cost = analysis.entry_cost(lambda a, b: a @ b, sds(4, 8), sds(8, 16))
    assert cost.flops == 2 * 4 * 8 * 16
    assert cost.bytes == (4 * 8 + 8 * 16 + 4 * 16) * 4
    assert cost.peak_bytes >= (4 * 8 + 8 * 16 + 4 * 16) * 4


def test_cost_model_scan_counts_trip_count():
    # THE divergence from XLA's cost_analysis (which counts a scan body
    # once): 5 trips of a 4x8x8 matmul body must cost 5x one trip
    w = np.eye(8, dtype=np.float32)

    def f(c):
        return jax.lax.scan(lambda c, _: (c @ w, None), c, None,
                            length=5)[0]

    cost = analysis.entry_cost(f, sds(4, 8))
    assert cost.flops == 5 * 2 * 4 * 8 * 8


def test_cost_model_donation_lowers_liveness_peak():
    # a donated 2-step elementwise chain can reuse the input buffer;
    # a non-donated one must keep input + both intermediates
    def chain(s):
        return (s + 1.0) * 2.0

    spec = sds(1024, 1024)
    plain = analysis.entry_cost(jax.jit(chain), spec)
    donated = analysis.entry_cost(jax.jit(chain, donate_argnums=(0,)),
                                  spec)
    assert donated.peak_bytes < plain.peak_bytes


def test_cost_model_signature_is_shape_sensitive():
    s1 = graph_lib.program_signature(
        jax.make_jaxpr(lambda x: x * 2.0)(sds(4, 4)))
    s2 = graph_lib.program_signature(
        jax.make_jaxpr(lambda x: x * 2.0)(sds(8, 8)))
    s3 = graph_lib.program_signature(
        jax.make_jaxpr(lambda x: x * 2.0)(sds(4, 4)))
    assert s1 != s2
    assert s1 == s3


def test_render_costs_table_is_deterministic(real_registry):
    traced = graph_lib.trace_registry(real_registry)
    t1 = graph_lib.render_costs(traced)
    t2 = graph_lib.render_costs(graph_lib.trace_registry(real_registry))
    assert t1 == t2
    for name in ("serve.decode_tick", "train.make_multi_train_step",
                 "bench.gpt_step"):
        assert name in t1


# ------------------------------------------------------------ CLI


def test_cli_report_costs_smoke():
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tensorflow_tpu.analysis",
         "--report", "costs"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "serve.decode_tick" in proc.stdout
    assert "gflops" in proc.stdout


def test_graph_tier_skipped_outside_the_package(tmp_path):
    # fixture trees never trigger the registry trace (no jax work):
    # the graph tier is package-scoped by construction
    (tmp_path / "m.py").write_text("x = 1\n")
    timings = {}
    findings = analysis.analyze_paths([str(tmp_path)], timings=timings)
    assert findings == []
    assert timings["graph_s"] < 0.05


def test_cli_no_graph_flag(tmp_path):
    # --select DT405 + --no-graph: the only selected rule lives in the
    # skipped tier, so the package lints clean without tracing
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tensorflow_tpu.analysis",
         "distributed_tensorflow_tpu", "--select", "DT405",
         "--no-graph", "--no-cache", "--format", "json"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert json.loads(proc.stdout)["count"] == 0


# ---------------------------------------------------- result cache


class TestResultCache:
    def _fixture_tree(self, tmp_path):
        d = tmp_path / "pkg"
        d.mkdir()
        (d / "clean.py").write_text("x = 1\n")
        (d / "bad.py").write_text(
            "import jax\n\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (2,))\n"
            "    b = jax.random.normal(key, (2,))\n"
            "    return a, b\n")
        return d

    def _counting(self, monkeypatch):
        from distributed_tensorflow_tpu.analysis import cli as cli_mod
        calls = {"file": 0, "project": 0, "concurrency": 0}
        real_rules = cli_mod.run_rules
        real_proj = cli_mod.run_project_rules
        real_conc = cli_mod.run_concurrency_rules

        def count(key, real):
            def wrapper(*a, **kw):
                calls[key] += 1
                return real(*a, **kw)
            return wrapper

        monkeypatch.setattr(cli_mod, "run_rules",
                            count("file", real_rules))
        monkeypatch.setattr(cli_mod, "run_project_rules",
                            count("project", real_proj))
        monkeypatch.setattr(cli_mod, "run_concurrency_rules",
                            count("concurrency", real_conc))
        return calls

    def test_warm_run_skips_every_tier_and_matches(self, tmp_path,
                                                   monkeypatch):
        d = self._fixture_tree(tmp_path)
        monkeypatch.setenv("DTLINT_CACHE_DIR", str(tmp_path / "cache"))
        calls = self._counting(monkeypatch)
        cat = analysis.full_rule_catalog()

        cold = analysis.analyze_paths(
            [str(d)], cache=analysis.ResultCache(catalog=cat))
        assert calls == {"file": 2, "project": 1, "concurrency": 1}
        assert rules_of(cold) == ["DT102"]

        warm = analysis.analyze_paths(
            [str(d)], cache=analysis.ResultCache(catalog=cat))
        assert calls == {"file": 2, "project": 1, "concurrency": 1}
        assert [f.to_dict() for f in warm] == [f.to_dict() for f in cold]

    def test_edit_invalidates_only_that_file_and_the_tiers(
            self, tmp_path, monkeypatch):
        d = self._fixture_tree(tmp_path)
        monkeypatch.setenv("DTLINT_CACHE_DIR", str(tmp_path / "cache"))
        calls = self._counting(monkeypatch)
        cat = analysis.full_rule_catalog()
        analysis.analyze_paths([str(d)],
                               cache=analysis.ResultCache(catalog=cat))
        (d / "bad.py").write_text("y = 2\n")   # fix the planted bug
        fixed = analysis.analyze_paths(
            [str(d)], cache=analysis.ResultCache(catalog=cat))
        # one per-file re-run (the edited file), tiers re-run once
        assert calls == {"file": 3, "project": 2, "concurrency": 2}
        assert fixed == []

    def test_catalog_change_invalidates_wholesale(self, tmp_path,
                                                  monkeypatch):
        d = self._fixture_tree(tmp_path)
        monkeypatch.setenv("DTLINT_CACHE_DIR", str(tmp_path / "cache"))
        calls = self._counting(monkeypatch)
        cat = analysis.full_rule_catalog()
        analysis.analyze_paths([str(d)],
                               cache=analysis.ResultCache(catalog=cat))
        stale = analysis.ResultCache(
            catalog=cat + [("DT999", "error", "new rule")])
        analysis.analyze_paths([str(d)], cache=stale)
        assert calls["file"] == 4   # both files re-ran

    def test_corrupt_cache_degrades_to_cold(self, tmp_path, monkeypatch):
        d = self._fixture_tree(tmp_path)
        cache_dir = tmp_path / "cache"
        cache_dir.mkdir()
        (cache_dir / "index.json").write_text("{ not json")
        monkeypatch.setenv("DTLINT_CACHE_DIR", str(cache_dir))
        cat = analysis.full_rule_catalog()
        findings = analysis.analyze_paths(
            [str(d)], cache=analysis.ResultCache(catalog=cat))
        assert rules_of(findings) == ["DT102"]


def test_lint_sh_warm_cache_measurably_faster(tmp_path):
    """The acceptance claim, asserted: a warm-cache scripts/lint.sh
    rerun of the unchanged tree beats the cold run by a wide margin
    (the whole 4-tier walk collapses to content hashing)."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "DTLINT_CACHE_DIR": str(tmp_path / "cache")}
    env.pop("DTLINT_LOG", None)

    def run():
        t0 = time.perf_counter()
        proc = subprocess.run(["bash", "scripts/lint.sh"], cwd=REPO,
                              env=env, capture_output=True, text=True)
        return time.perf_counter() - t0, proc

    cold_s, cold = run()
    assert cold.returncode == 0, cold.stdout + cold.stderr
    warm_s, warm = run()
    assert warm.returncode == 0, warm.stdout + warm.stderr
    assert "dtlint: clean" in cold.stdout
    assert "dtlint: clean" in warm.stdout
    # the cold run traces/parses ~110 files + the graph tier; warm is
    # hashing + one json read.  2x is a deliberately loose floor — the
    # real ratio is ~10x — so CI jitter can't flake this.
    assert warm_s < cold_s / 2, (cold_s, warm_s)
