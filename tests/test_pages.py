"""Paged KV cache + radix prefix reuse tests (serve/pages.py).

The contracts pinned here (docs/SERVING.md):
  * paged engine == contiguous engine == greedy ``GPT.generate``
    token-for-token (chunked prefill, RoPE + GQA, int8 scale planes),
  * a prefix-cache HIT request's tokens are bit-identical to the same
    request on a COLD cache, and the skipped prefill windows are
    measured, not assumed,
  * whole-chain prompts split their last page copy-on-write style
    (re-prefilled private copy) and stay exact,
  * eviction reclaims only refcount-0 chains — a pinned chain never
    loses a page while its holder is in flight; exhaustion requeues
    and always drains,
  * the fused page-walk kernel read path (``use_paged_kernel=True``,
    interpret mode on CPU) is token-identical to the gather path across
    config families and keeps the prefix-reuse contracts,
  * admission / page allocation / COW split / eviction never recompile
    (RetraceGuard budget=1) — on the kernel build too,
  * concurrent submitters sharing a prefix never tear the pool
    (race_harness: refcounts, free list, and radix stay consistent).
"""
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import serve
from distributed_tensorflow_tpu.models.gpt import gpt_tiny
from distributed_tensorflow_tpu.obs import metrics as metrics_lib
from distributed_tensorflow_tpu.serve import pages as pages_lib


def _model_params(seed=0, **kw):
    model = gpt_tiny(dropout_rate=0.0, **kw)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompt(plen, seed=1, vocab=512):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (plen,), 0, vocab), np.int32)


def _generate_tokens(model, params, prompt, new, max_len, **kw):
    out = model.generate(params, jnp.asarray(prompt[None]),
                         max_new_tokens=new, max_len=max_len, **kw)
    return np.asarray(out)[0, prompt.size:].tolist()


def _radix_pages(pool):
    """Pages currently held by the radix tree (and max refcount seen)."""
    n, max_ref = 0, 0
    stack = list(pool._root.children.values())
    while stack:
        node = stack.pop()
        n += 1
        max_ref = max(max_ref, node.refcount)
        stack.extend(node.children.values())
    return n, max_ref


# ---------------------------------------------------------------------------
# layout units


def test_auto_page_size_divides_max_len():
    assert pages_lib.auto_page_size(256) == 16
    assert pages_lib.auto_page_size(40) == 10
    assert pages_lib.auto_page_size(16) == 16
    assert pages_lib.auto_page_size(7) == 7
    assert pages_lib.auto_page_size(31) == 1     # prime: token pages
    for n in (16, 24, 40, 256, 31):
        assert n % pages_lib.auto_page_size(n) == 0


def test_init_paged_cache_shapes_and_int8_planes():
    model, _ = _model_params(kv_cache_dtype="int8")
    c = model.config
    cache = pages_lib.init_paged_cache(model, num_slots=3, num_pages=9,
                                       page_size=8)
    assert cache["kv"]["k"].shape == (c.num_layers, 9, 8, c.kv_heads,
                                      c.head_dim)
    assert cache["kv"]["k"].dtype == jnp.int8
    assert cache["kv"]["k_scale"].shape == (c.num_layers, 9, 8,
                                            c.kv_heads, 1)
    assert cache["kv"]["k_scale"].dtype == jnp.float32
    assert cache["write_col"].shape == (3,)


def test_pool_validation():
    with pytest.raises(ValueError, match="num_pages"):
        pages_lib.PagePool(num_pages=4, page_size=8, pages_per_slot=4)
    with pytest.raises(ValueError, match="page_size"):
        pages_lib.PagePool(num_pages=8, page_size=0, pages_per_slot=2)
    model, params = _model_params()
    with pytest.raises(ValueError, match="page_size"):
        serve.Engine(model, params, num_slots=2, max_len=32,
                     page_size=7)          # 7 does not divide 32


# ---------------------------------------------------------------------------
# host pool semantics (no device work)


def test_pool_match_register_release_refcounts():
    pool = pages_lib.PagePool(num_pages=17, page_size=4,
                              pages_per_slot=4)
    prompt = np.arange(10, dtype=np.int32)        # 2 full chunks + 2
    a = pool.begin(prompt, 12)
    assert a.skip == 0 and a.n_pages == 3 and len(a.private) == 3
    pool.register(a, prompt)                      # publish chunks 0, 1
    assert len(a.private) == 1 and len(a.shared) == 2
    cached, max_ref = _radix_pages(pool)
    assert cached == 2 and max_ref == 1           # pinned by a itself

    b = pool.begin(prompt, 12)                    # same prompt: a hit
    assert b.skip == 8 and len(b.shared) == 2 and len(b.private) == 1
    _, max_ref = _radix_pages(pool)
    assert max_ref == 2                           # both leases pin
    assert pool.stats()["prefix_hits_total"] == 1
    assert pool.stats()["prefix_tokens_reused_total"] == 8

    pool.release(a)
    pool.release(a)                               # idempotent
    _, max_ref = _radix_pages(pool)
    assert max_ref == 1                           # b still pins
    pool.release(b)
    cached, max_ref = _radix_pages(pool)
    assert cached == 2 and max_ref == 0           # cached, evictable
    st = pool.stats()
    assert st["pages_free"] + cached == st["pages_total"]


def test_pool_eviction_lru_and_pinning():
    pool = pages_lib.PagePool(num_pages=7, page_size=4,
                              pages_per_slot=4)   # 6 usable
    # two cached chains of one page each
    p1 = np.arange(4, dtype=np.int32)
    p2 = np.arange(4, 8, dtype=np.int32)
    for p in (p1, p2):
        lease = pool.begin(p, 5)                  # 2 pages
        pool.register(lease, p)
        pool.release(lease)
    assert pool.stats()["pages_free"] == 4
    # PIN p2's chain: a request extending p2 maps its page read-only
    held = pool.begin(np.concatenate([p2, np.arange(90, 94,
                                                    dtype=np.int32)]), 9)
    assert held.skip == 4 and len(held.shared) == 1
    # demand 3 pages with 2 free: must evict p1's chain (refcount 0)
    # but NEVER p2's pinned page
    big = pool.begin(np.arange(100, 112, dtype=np.int32), 12)
    assert pool.stats()["prefix_evictions_total"] == 1
    pool.release(big)
    probe = pool.begin(np.concatenate([p2, p2]), 9)
    assert probe.skip == 4                        # p2's page survived
    pool.release(probe)
    # p1's chain is gone: re-seeing it is a miss now
    miss = pool.begin(np.concatenate([p1, p1]), 9)
    assert miss.skip == 0
    pool.release(miss)
    pool.release(held)
    cached, max_ref = _radix_pages(pool)
    assert max_ref == 0
    assert pool.stats()["pages_free"] + cached == 6


def test_pool_exhausted_rolls_back_pins():
    pool = pages_lib.PagePool(num_pages=7, page_size=4,
                              pages_per_slot=4)   # 6 usable
    p = np.arange(8, dtype=np.int32)
    a = pool.begin(p, 9)                          # 3 pages
    pool.register(a, p)                           # 2 cached+pinned
    c = pool.begin(np.arange(50, 58, dtype=np.int32), 12)  # 3 private
    assert pool.stats()["pages_free"] == 0
    # shares a's prefix (pins +1 each during match) but cannot get its
    # 2 private pages: the pins must roll back on exhaustion
    with pytest.raises(pages_lib.PagePoolExhausted):
        pool.begin(np.concatenate([p, np.arange(60, 64, dtype=np.int32)]), 16)
    _, max_ref = _radix_pages(pool)
    assert max_ref == 1                           # only a's own pins
    assert pool.stats()["pages_free"] == 0        # nothing leaked
    pool.release(a)
    pool.release(c)
    cached, _ = _radix_pages(pool)
    assert pool.stats()["pages_free"] + cached == 6


# ---------------------------------------------------------------------------
# engine exactness: paged == contiguous == generate


@pytest.mark.parametrize("kw", [
    {},
    {"position_embedding": "rope", "num_heads": 4, "hidden_size": 128,
     "num_kv_heads": 2},
    {"kv_cache_dtype": "int8"},
], ids=["base", "rope_gqa", "int8"])
def test_paged_engine_matches_contiguous_and_generate(kw):
    """The tentpole exactness contract, per config family: a mixed
    workload through the paged engine equals the contiguous engine
    request-for-request, and both equal solo generate."""
    model, params = _model_params(**kw)
    prompts = [_prompt(7, seed=1), _prompt(5, seed=2), _prompt(9, seed=3),
               _prompt(3, seed=4)]
    budgets = [9, 6, 4, 8]
    wants = [_generate_tokens(model, params, p, n, 64)
             for p, n in zip(prompts, budgets)]
    outs = {}
    for paged in (True, False):
        eng = serve.Engine(model, params, num_slots=2, max_len=64,
                           prefill_chunk=4, tick_steps=3, paged=paged,
                           registry=metrics_lib.Registry())
        hs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
        eng.drain()
        outs[paged] = [h.tokens for h in hs]
    assert outs[True] == outs[False] == wants


def test_prefix_hit_bit_identical_to_cold_cache_and_skips_windows():
    """A request whose system prompt is radix-cached decodes tokens
    BIT-identical to the same request on a cold cache — and measurably
    skips its shared prefill windows."""
    model, params = _model_params()
    sys_prompt = _prompt(16, seed=7)              # 2 pages at page_size 8
    tails = [_prompt(5, seed=8), _prompt(3, seed=9)]
    reqs = [np.concatenate([sys_prompt, t]) for t in tails]

    def run(eng, req, new=7):
        h = eng.submit(req, new)
        eng.drain()
        assert h.status == "ok"
        return h.tokens

    warm = serve.Engine(model, params, num_slots=2, max_len=64,
                        prefill_chunk=4, tick_steps=2, page_size=8,
                        registry=metrics_lib.Registry())
    got_a = run(warm, reqs[0])                    # seeds the radix cache
    got_b = run(warm, reqs[1])                    # hits it
    st = warm.stats()
    assert st.prefix_hits_total == 1
    assert st.prefix_tokens_reused_total == 16
    assert st.prefill_windows_skipped_total == 4  # 16 skipped / W=4
    assert st.prefix_hit_rate == 0.5              # 1 of 2 lookups

    for req, got in zip(reqs, (got_a, got_b)):
        cold = serve.Engine(model, params, num_slots=2, max_len=64,
                            prefill_chunk=4, tick_steps=2, page_size=8,
                            registry=metrics_lib.Registry())
        assert run(cold, req) == got              # bit-identical tokens
        assert cold.stats().prefix_hits_total == 0


def test_concurrent_shared_prefix_requests_match_solo():
    """Requests sharing a prefix IN FLIGHT TOGETHER (the second maps
    pages the first published at admission) each equal their solo
    generate — read-only sharing never couples the streams."""
    model, params = _model_params()
    sys_prompt = _prompt(8, seed=11)
    tails = [_prompt(4, seed=20 + i) for i in range(4)]
    reqs = [np.concatenate([sys_prompt, t]) for t in tails]
    wants = [_generate_tokens(model, params, r, 8, 64) for r in reqs]
    eng = serve.Engine(model, params, num_slots=2, max_len=64,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       registry=metrics_lib.Registry())
    hs = [eng.submit(r, 8) for r in reqs]
    eng.drain()
    assert [h.tokens for h in hs] == wants
    st = eng.stats()
    assert st.prefix_hits_total >= 1              # later arrivals hit
    # all leases released: free + radix-cached == total, zero pins
    pool = eng.scheduler.pages
    cached, max_ref = _radix_pages(pool)
    assert pool.stats()["pages_free"] + cached == st.pages_total
    assert max_ref == 0


def test_cow_split_whole_chain_prompt_stays_exact():
    """A prompt EXACTLY equal to a cached chain must re-prefill its
    last page (the COW split — decode writes need a private copy) and
    still match solo generate token-for-token."""
    model, params = _model_params()
    prompt = _prompt(16, seed=13)                 # exactly 2 pages
    want = _generate_tokens(model, params, prompt, 6, 64)
    eng = serve.Engine(model, params, num_slots=2, max_len=64,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       registry=metrics_lib.Registry())
    h1 = eng.submit(prompt, 6)
    eng.drain()
    h2 = eng.submit(prompt, 6)                    # whole-chain re-submit
    eng.drain()
    assert h1.tokens == h2.tokens == want
    st = eng.stats()
    assert st.cow_splits_total == 1
    assert st.prefix_hits_total == 1              # page 0 still mapped
    assert st.prefix_tokens_reused_total == 8     # one page, not two


def test_exhaustion_requeues_pinned_chains_survive_and_drains():
    """More demand than pages: admission requeues on exhaustion (no
    deadlock — retirements free pages), an in-flight holder's chain is
    never evicted from under it, and every request finishes exact."""
    model, params = _model_params()
    # pool: 2 slots x 4 pages (page_size 8, max_len 32) + 1 spare + trash
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       num_pages=10, registry=metrics_lib.Registry())
    prompts = [_prompt(9 + (i % 3), seed=30 + i) for i in range(6)]
    wants = [_generate_tokens(model, params, p, 10, 32) for p in prompts]
    hs = [eng.submit(p, 10) for p in prompts]     # each needs 3 pages
    eng.drain()
    for h, want in zip(hs, wants):
        assert h.status == "ok" and h.tokens == want
    pool = eng.scheduler.pages
    cached, max_ref = _radix_pages(pool)
    assert max_ref == 0
    assert pool.stats()["pages_free"] + cached \
        == pool.stats()["pages_total"]


def test_eviction_under_pressure_then_reseeded_prefix_still_hits():
    """Distinct prompts fill the radix cache past the pool's capacity:
    LRU chains evict to keep admissions flowing, and a prefix evicted
    then re-seen simply re-prefills (a miss), while a recent one still
    hits."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=8, tick_steps=2, page_size=8,
                       num_pages=9, registry=metrics_lib.Registry())
    prompts = [_prompt(8, seed=50 + i) for i in range(8)]
    for p in prompts:                             # serially: each caches
        h = eng.submit(p, 3)                      # 2 pages in flight,
        eng.drain()                               # 1 cached after
        assert h.status == "ok"
    st = eng.stats()
    assert st.prefix_evictions_total >= 1         # pressure reclaimed LRU
    # the most recent prefix survived: resubmitting hits
    h = eng.submit(np.concatenate([prompts[-1], _prompt(2, seed=99)]), 3)
    eng.drain()
    assert h.status == "ok"
    assert eng.stats().prefix_hits_total >= 1


# ---------------------------------------------------------------------------
# retrace-free + concurrency


@pytest.mark.retrace_guard(budget=1, enforce_donation=True)
def test_paged_admission_alloc_cow_evict_never_recompile():
    """Every paged executable traces ONCE across a workload that
    exercises admission, page allocation, prefix hits, a COW split,
    eviction under pressure, and slot reuse (budget=1: the second
    trace of anything fails; donation enforcement doubles as a
    use-after-donate check on the pool buffer chain)."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       num_pages=9, eos_id=7,
                       registry=metrics_lib.Registry())
    sys_prompt = _prompt(8, seed=61)
    handles = []
    for i in range(2):                            # seed, then hit
        handles.append(eng.submit(
            np.concatenate([sys_prompt, _prompt(3, seed=70 + i)]), 5))
        eng.drain()
    handles.append(eng.submit(sys_prompt, 4))     # COW split
    eng.drain()
    for i in range(7):                            # distinct: evictions
        handles.append(eng.submit(_prompt(8, seed=80 + i), 4))
        eng.drain()
    assert all(h.done for h in handles)
    assert all(len(h.tokens) >= 1 for h in handles)
    st = eng.stats()
    assert st.prefix_hits_total >= 1
    assert st.cow_splits_total >= 1
    assert st.prefix_evictions_total >= 1


@pytest.mark.race_harness(
    seed=17, scope=("distributed_tensorflow_tpu/serve/",))
def test_concurrent_prefix_submits_never_tear_the_pool(request):
    """THE pool race test: 3 submitter threads sharing one system
    prompt against a pumping engine under seeded preemption.  Every
    request finishes exact (refcounts never dropped a live page), and
    the pool balances to free + radix-cached == total with zero
    refcounts — eviction/release under preemption never double-freed
    or leaked a page."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=3, max_len=32,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       registry=metrics_lib.Registry())
    sys_prompt = _prompt(8, seed=91)
    reqs = {i: np.concatenate([sys_prompt, _prompt(2 + (i % 3),
                                                   seed=100 + i)])
            for i in range(6)}
    wants = {i: _generate_tokens(model, params, reqs[i], 5, 32)
             for i in reqs}
    handles = {}
    hlock = threading.Lock()
    barrier = threading.Barrier(3)

    def submitter(ids):
        barrier.wait(timeout=60)
        for i in ids:
            h = eng.submit(reqs[i], 5)
            with hlock:
                handles[i] = h

    ts = [threading.Thread(target=submitter, args=([k, k + 3],),
                           name=f"dttpu-pages-{k}", daemon=True)
          for k in range(3)]
    for t in ts:
        t.start()
    deadline = time.time() + 300
    while True:
        with hlock:
            got = dict(handles)
        if len(got) == 6 and all(h.done for h in got.values()):
            break
        eng.step()
        assert time.time() < deadline, "engine did not drain"
    for t in ts:
        t.join(timeout=60)

    harness = request.node.race_harness
    assert harness.preemptions > 0, "harness never fired"
    for i, h in handles.items():
        assert h.status == "ok" and h.tokens == wants[i], i
    pool = eng.scheduler.pages
    cached, max_ref = _radix_pages(pool)
    st = pool.stats()
    assert max_ref == 0                           # no leaked pins
    assert st["pages_free"] + cached == st["pages_total"]
    assert eng.stats().prefix_hits_total >= 1


# ---------------------------------------------------------------------------
# metrics plumbing


def test_paged_metrics_land_in_registry():
    """The obs wiring for the new series: pages gauges move with the
    stats snapshot, prefix counters advance by delta, all scrapable
    through the standard exposition path."""
    model, params = _model_params()
    reg = metrics_lib.Registry()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       registry=reg)
    sys_prompt = _prompt(8, seed=5)
    for i in range(2):
        # serial: the hit needs the seeder's pages registered first
        eng.submit(np.concatenate([sys_prompt, _prompt(3, seed=i)]), 4)
        eng.drain()
    st = eng.stats()
    assert reg.get("dttpu_serve_pages_free").value == st.pages_free
    cached, _ = _radix_pages(eng.scheduler.pages)
    assert st.pages_free + cached == st.pages_total   # leases released
    assert reg.get("dttpu_serve_prefix_hits_total").value \
        == st.prefix_hits_total == 1
    assert reg.get("dttpu_serve_prefix_evictions_total").value == 0
    doc = metrics_lib.parse_exposition(reg.expose())
    assert doc["dttpu_serve_pages_free"]["type"] == "gauge"
    assert doc["dttpu_serve_pages_per_request"]["type"] == "gauge"
    assert doc["dttpu_serve_prefix_hits_total"]["type"] == "counter"


# ---------------------------------------------------------------------------
# fused page-walk kernel read path (ops/pallas/paged_attention.py)


def test_auto_page_size_multiple_of():
    """The kernel-tileability constraint: prefer a multiple-of-8
    divisor, fall back to the plain largest-divisor pick when max_len
    has none (the scheduler then logs and takes the gather path)."""
    assert pages_lib.auto_page_size(256, multiple_of=8) == 16
    assert pages_lib.auto_page_size(64, multiple_of=8) == 16
    assert pages_lib.auto_page_size(128, multiple_of=8) == 16
    assert pages_lib.auto_page_size(40, multiple_of=8) == 8
    # no lane-tileable divisor exists: unconstrained fallback
    assert pages_lib.auto_page_size(30, multiple_of=8) == 15
    assert pages_lib.auto_page_size(7, multiple_of=8) == 7


@pytest.mark.parametrize("kw", [
    {},
    {"position_embedding": "rope", "num_heads": 4, "hidden_size": 128,
     "num_kv_heads": 2},
    {"kv_cache_dtype": "int8"},
], ids=["base", "rope_gqa", "int8"])
def test_kernel_engine_matches_gather_contiguous_and_generate(kw):
    """The kernel exactness contract, per config family: the fused
    page-walk read path produces token streams bit-identical to the
    XLA gather path, the contiguous stripe engine, and solo greedy
    generate (the kernel runs in interpret mode on the CPU mesh, so
    this executes the real kernel body)."""
    model, params = _model_params(**kw)
    prompts = [_prompt(7, seed=1), _prompt(5, seed=2), _prompt(9, seed=3),
               _prompt(3, seed=4)]
    budgets = [9, 6, 4, 8]
    wants = [_generate_tokens(model, params, p, n, 64)
             for p, n in zip(prompts, budgets)]
    outs = {}
    for label, ekw in (("kernel", dict(use_paged_kernel=True,
                                       page_size=8)),
                       ("gather", dict(use_paged_kernel=False,
                                       page_size=8)),
                       ("contig", dict(paged=False))):
        eng = serve.Engine(model, params, num_slots=2, max_len=64,
                           prefill_chunk=4, tick_steps=3,
                           registry=metrics_lib.Registry(), **ekw)
        hs = [eng.submit(p, n) for p, n in zip(prompts, budgets)]
        eng.drain()
        outs[label] = [h.tokens for h in hs]
    assert outs["kernel"] == outs["gather"] == outs["contig"] == wants


def test_prefix_hit_and_cow_exact_through_kernel():
    """Radix reuse composes with the kernel read path: a prefix HIT
    and a whole-chain COW split through the kernel engine both stay
    token-identical to the gather engine on a cold cache."""
    model, params = _model_params()
    sys_prompt = _prompt(16, seed=7)
    tails = [_prompt(5, seed=8), _prompt(3, seed=9)]
    reqs = [np.concatenate([sys_prompt, t]) for t in tails]

    def run(eng, req, new=7):
        h = eng.submit(req, new)
        eng.drain()
        assert h.status == "ok"
        return h.tokens

    warm = serve.Engine(model, params, num_slots=2, max_len=64,
                        prefill_chunk=4, tick_steps=2, page_size=8,
                        use_paged_kernel=True,
                        registry=metrics_lib.Registry())
    assert warm.scheduler.use_paged_kernel is True
    got_a = run(warm, reqs[0])                    # seeds the radix cache
    got_b = run(warm, reqs[1])                    # hits it
    assert warm.stats().prefix_hits_total == 1
    got_cow = run(warm, sys_prompt)               # whole-chain COW split
    assert warm.stats().cow_splits_total == 1

    cold = serve.Engine(model, params, num_slots=2, max_len=64,
                        prefill_chunk=4, tick_steps=2, page_size=8,
                        use_paged_kernel=False,
                        registry=metrics_lib.Registry())
    assert run(cold, reqs[0]) == got_a
    assert run(serve.Engine(model, params, num_slots=2, max_len=64,
                            prefill_chunk=4, tick_steps=2, page_size=8,
                            use_paged_kernel=False,
                            registry=metrics_lib.Registry()),
               reqs[1]) == got_b
    assert got_cow == _generate_tokens(model, params, sys_prompt, 7, 64)


@pytest.mark.retrace_guard(budget=1, enforce_donation=True)
def test_kernel_engine_admission_retirement_never_recompile():
    """The kernel build must keep the retrace discipline: the fused
    read path REPLACES the gather read path inside the same three
    executables, so admission, prefix hits, a COW split, eviction
    pressure, and slot reuse still trace each program ONCE."""
    model, params = _model_params()
    eng = serve.Engine(model, params, num_slots=2, max_len=32,
                       prefill_chunk=4, tick_steps=2, page_size=8,
                       num_pages=9, eos_id=7, use_paged_kernel=True,
                       registry=metrics_lib.Registry())
    sys_prompt = _prompt(8, seed=61)
    handles = []
    for i in range(2):                            # seed, then hit
        handles.append(eng.submit(
            np.concatenate([sys_prompt, _prompt(3, seed=70 + i)]), 5))
        eng.drain()
    handles.append(eng.submit(sys_prompt, 4))     # COW split
    eng.drain()
    for i in range(7):                            # distinct: evictions
        handles.append(eng.submit(_prompt(8, seed=80 + i), 4))
        eng.drain()
    assert all(h.done for h in handles)
    assert all(len(h.tokens) >= 1 for h in handles)
    st = eng.stats()
    assert st.prefix_hits_total >= 1
    assert st.cow_splits_total >= 1
    assert st.prefix_evictions_total >= 1


def test_use_paged_kernel_page_size_validation(monkeypatch):
    """Both failure directions of the lane-tileability rule: explicit
    True + incompatible page_size is a construction-time ValueError;
    an "auto" that WOULD dispatch falls back to the gather path with a
    RuntimeWarning instead of a Mosaic error inside the kernel."""
    model, params = _model_params()
    with pytest.raises(ValueError, match="use_paged_kernel"):
        serve.Engine(model, params, num_slots=2, max_len=30,
                     page_size=10, use_paged_kernel=True,
                     registry=metrics_lib.Registry())
    # make the auto gate say yes (TPU backend, threshold met) while the
    # layout stays incompatible: warn + fall back, never raise
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("DTTPU_PAGED_KERNEL_MIN_VIEW", "16")
    with pytest.warns(RuntimeWarning, match="gather"):
        eng = serve.Engine(model, params, num_slots=2, max_len=30,
                           page_size=10, registry=metrics_lib.Registry())
    assert eng.scheduler.use_paged_kernel is False
