"""Native C++ runtime component tests (native/dttpu_native.cpp via ctypes).

The pure-Python implementations act as cross-check oracles; if the toolchain
cannot build the library these tests skip and every consumer falls back.
"""
import os

import numpy as np
import pytest

from distributed_tensorflow_tpu.summary.crc32c import (py_crc32c,
                                                       py_masked_crc32c)
from distributed_tensorflow_tpu.utils import native

pytestmark = pytest.mark.skipif(not native.native_available(),
                                reason="native library unavailable")


def test_crc32c_matches_python_oracle():
    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 8, 9, 63, 64, 1000, 4097):
        data = bytes(rng.integers(0, 256, n, dtype=np.uint8))
        assert native.crc32c(data) == py_crc32c(data)
        assert native.masked_crc32c(data) == py_masked_crc32c(data)


def test_crc32c_known_vector():
    # RFC 3720 test vector: crc32c of 32 zero bytes.
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA


def test_crc32c_incremental():
    data = b"hello, tpu world" * 10
    crc_all = native.crc32c(data)
    crc_inc = native.crc32c(data[7:], native.crc32c(data[:7]))
    assert crc_all == crc_inc == py_crc32c(data)


def test_xor_generate_labels_and_determinism():
    x, y = native.xor_generate(500, 32, seed=5)
    assert x.shape == (500, 64) and y.shape == (500, 32)
    assert set(np.unique(x)) <= {0.0, 1.0}
    np.testing.assert_array_equal(
        y, np.bitwise_xor(x[:, :32].astype(int), x[:, 32:].astype(int)))
    x2, _ = native.xor_generate(500, 32, seed=5)
    np.testing.assert_array_equal(x, x2)
    x3, _ = native.xor_generate(500, 32, seed=6)
    assert not np.array_equal(x, x3)
    # bits look fair
    assert 0.45 < x.mean() < 0.55


def test_loader_epoch_coverage_and_shapes():
    n, b = 103, 10
    x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    y = np.arange(n, dtype=np.int32)
    ld = native.NativeLoader(x, y, b, seed=1)
    assert ld.batches_per_epoch == 10
    seen = []
    for xb, yb in ld:
        assert xb.shape == (b, 3) and xb.dtype == np.float32
        assert yb.shape == (b,) and yb.dtype == np.int32
        np.testing.assert_array_equal(xb[:, 0], yb * 3)  # rows stay aligned
        seen.append(yb)
    seen = np.concatenate(seen)
    assert len(np.unique(seen)) == 100  # each row at most once per epoch
    ld.close()


def test_loader_epochs_reshuffle_and_streaming():
    n, b = 64, 8
    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    ld = native.NativeLoader(x, None, b, seed=3)
    e1 = np.concatenate([xb[0].ravel() for xb in ld])
    e2 = np.concatenate([xb[0].ravel() for xb in ld])
    assert not np.array_equal(e1, e2)  # per-epoch reshuffle
    assert len(np.unique(e1)) == len(e1)
    ld.close()


def test_loader_no_shuffle_preserves_order():
    n, b = 20, 5
    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    ld = native.NativeLoader(x, None, b, shuffle=False)
    batches = [xb[0].ravel() for xb in ld]
    np.testing.assert_array_equal(np.concatenate(batches), np.arange(n))
    ld.close()


def test_dataset_native_backend_coverage():
    from distributed_tensorflow_tpu import data
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    y = np.arange(100, dtype=np.int32)
    ds = data.Dataset([x, y], 32, seed=0, backend="native")
    b1 = list(ds)
    assert len(b1) == 3
    seen = np.concatenate([b[1] for b in b1])
    assert len(np.unique(seen)) == 96
    b2 = list(ds)  # next epoch reshuffles
    assert not np.array_equal(b1[0][1], b2[0][1])
    # partial consumption then restart stays well-formed
    it = iter(ds)
    next(it)
    del it
    assert len(list(ds)) == 3


def test_dataset_numpy_backend_unchanged_by_native_presence():
    from distributed_tensorflow_tpu import data
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    ds = data.Dataset([x], 2, shuffle=False, backend="numpy")
    np.testing.assert_array_equal(next(iter(ds))[0].ravel(), [0.0, 1.0])


def test_no_native_env_forces_fallback():
    import subprocess
    import sys
    code = (
        "import os; os.environ['DTTPU_NO_NATIVE']='1';"
        "from distributed_tensorflow_tpu.utils import native;"
        "assert not native.native_available();"
        "import importlib;"
        "c = importlib.import_module("
        "'distributed_tensorflow_tpu.summary.crc32c');"
        "assert c.crc32c(b'abc') == c.py_crc32c(b'abc')"
    )
    env = dict(os.environ, DTTPU_NO_NATIVE="1", JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr


def test_loader_stress_many_threads_and_epochs():
    """Regression for the slot claim-jumping deadlock: many workers, small
    ring, several epoch boundaries, coverage verified every epoch."""
    n, b = 48, 4
    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    ld = native.NativeLoader(x, None, b, seed=9, num_threads=4,
                             queue_depth=5)
    for _ in range(5):
        rows = np.concatenate([xb[0].ravel() for xb in ld])
        assert len(np.unique(rows)) == n
    ld.close()


def test_native_bpe_matches_python():
    """dt_bpe_encode produces the exact segmentation of the Python loop
    (rank-greedy, left-to-right non-overlapping) on trained merges."""
    import numpy as np
    import pytest
    from distributed_tensorflow_tpu.data.text import BPETokenizer
    from distributed_tensorflow_tpu.utils import native

    if not native.native_available():
        pytest.skip("native library unavailable")
    corpus = ["the quick brown fox jumps over the lazy dog " * 20,
              "pack my box with five dozen liquor jugs " * 20]
    tok = BPETokenizer.train(corpus, vocab_size=300)
    assert tok.merges   # learned something
    for text in corpus + ["the fox", "zzz unseen bytes éü",
                          "", "a"]:
        py = tok.encode(text, backend="python")
        nat = tok.encode(text, backend="auto")
        np.testing.assert_array_equal(np.asarray(nat), np.asarray(py))
        # and both decode back to the input
        assert tok.decode(nat) == text


def test_native_bpe_bos_eos_and_speed():
    import time
    import numpy as np
    import pytest
    from distributed_tensorflow_tpu.data.text import BPETokenizer
    from distributed_tensorflow_tpu.utils import native

    if not native.native_available():
        pytest.skip("native library unavailable")
    tok = BPETokenizer.train(["ababababab abab " * 50], vocab_size=270)
    out = tok.encode("abab", bos=True, eos=True)
    assert out[0] == tok.bos_id and out[-1] == tok.eos_id
    # the native path should not be slower on a long text
    text = "ababababab abab " * 2000
    t0 = time.perf_counter(); tok.encode(text, backend="python")
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter(); tok.encode(text, backend="auto")
    t_nat = time.perf_counter() - t0
    assert t_nat < t_py * 1.5   # loose: just prove it's wired + not broken
