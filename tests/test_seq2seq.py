"""Encoder-decoder transformer tests (models/seq2seq.py)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu import optim, train
from distributed_tensorflow_tpu.models.seq2seq import seq2seq_tiny
from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.sharding import shard_pytree


def _model():
    return seq2seq_tiny(dropout_rate=0.0)


def test_shapes_and_determinism():
    m = _model()
    params = m.init(jax.random.PRNGKey(0))
    src = jnp.ones((2, 12), jnp.int32)
    tgt = jnp.ones((2, 7), jnp.int32)
    mem = m.encode(params, src)
    assert mem.shape == (2, 12, m.config.hidden_size)
    h = m.decode(params, mem, tgt)
    assert h.shape == (2, 7, m.config.hidden_size)
    logits = m.logits(params, h)
    assert logits.shape == (2, 7, m.config.vocab_size)
    assert logits.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(m.encode(params, src)),
                                  np.asarray(mem))


def test_decoder_causality():
    """Changing a future target token must not change earlier positions."""
    m = _model()
    params = m.init(jax.random.PRNGKey(0))
    src = jnp.arange(10, dtype=jnp.int32)[None, :] % 32
    tgt = jnp.arange(6, dtype=jnp.int32)[None, :] % 32
    mem = m.encode(params, src)
    h1 = m.decode(params, mem, tgt)
    tgt2 = tgt.at[0, 4].set(99)
    h2 = m.decode(params, mem, tgt2)
    np.testing.assert_allclose(np.asarray(h1[:, :4]), np.asarray(h2[:, :4]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(h1[:, 4:]), np.asarray(h2[:, 4:]))


def test_src_padding_masked_out():
    """Padding positions in the source must not affect the output."""
    m = _model()
    params = m.init(jax.random.PRNGKey(0))
    src = jnp.asarray([[5, 6, 7, 0, 0]], jnp.int32)
    valid = jnp.asarray([[1, 1, 1, 0, 0]], jnp.int32)
    tgt = jnp.asarray([[1, 2, 3]], jnp.int32)
    mem = m.encode(params, src, valid)
    h1 = m.decode(params, mem, tgt, valid)
    src2 = jnp.asarray([[5, 6, 7, 50, 60]], jnp.int32)  # different padding
    mem2 = m.encode(params, src2, valid)
    h2 = m.decode(params, mem2, tgt, valid)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2), atol=1e-5)


def test_learns_copy_task():
    """Seq2seq sanity oracle: copy the source sequence."""
    m = _model()
    optimizer = optim.adam(3e-3)
    params = m.init(jax.random.PRNGKey(0))
    state = train.TrainState.create(params, optimizer.init(params))
    step = train.make_custom_train_step(m.seq2seq_loss_fn(), optimizer,
                                        grad_clip_norm=1.0)
    rng = np.random.default_rng(0)
    V, S = 16, 8
    # fixed pool: the oracle is copying THESE sequences (cross-attention
    # must route source tokens to target positions to get the loss down)
    pool_src = rng.integers(1, V, (128, S)).astype(np.int32)
    pool_tgt = np.concatenate([np.zeros((128, 1), np.int32), pool_src],
                              axis=1)

    def batch(i, n=64):
        lo = (i * n) % 128
        return {"src_ids": jnp.asarray(pool_src[lo:lo + n]),
                "tgt_ids": jnp.asarray(pool_tgt[lo:lo + n])}

    losses = []
    for i in range(260):
        state, metrics = step(state, batch(i))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 1.2, losses[-5:]  # well below uniform ln(15)=2.7

    out = m.generate(state.params, batch(0, 4)["src_ids"],
                     max_new_tokens=S)
    assert out.shape == (4, S)


def test_generate_greedy_matches_teacher_forcing():
    """With temperature 0, generate's argmax at position 0 equals the
    argmax of a teacher-forced decode of just BOS."""
    m = _model()
    params = m.init(jax.random.PRNGKey(1))
    src = jnp.arange(6, dtype=jnp.int32)[None, :] % 32
    out = m.generate(params, src, max_new_tokens=3, bos_id=0)
    mem = m.encode(params, src)
    h = m.decode(params, mem, jnp.zeros((1, 1), jnp.int32))
    first = int(jnp.argmax(m.logits(params, h)[:, 0, :], axis=-1)[0])
    assert int(out[0, 0]) == first


def test_partition_rules_compile_on_mesh():
    mesh = make_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    m = _model()
    params = m.init(jax.random.PRNGKey(0))
    params = shard_pytree(params, mesh, m.partition_rules(fsdp=True))
    spec = params["decoder"]["cross_attention"]["query"]["kernel"]\
        .sharding.spec
    assert "tensor" in str(spec)
    optimizer = optim.adam()
    state = train.TrainState.create(params, optimizer.init(params))
    step = train.make_custom_train_step(m.seq2seq_loss_fn(), optimizer)
    src = jnp.ones((4, 8), jnp.int32)
    tgt = jnp.ones((4, 5), jnp.int32)
    bsh = NamedSharding(mesh, P("data"))
    state, metrics = step(state, {
        "src_ids": jax.device_put(src, bsh),
        "tgt_ids": jax.device_put(tgt, bsh)})
    assert np.isfinite(float(metrics["loss"]))


def test_bf16_and_remat_forward():
    m = seq2seq_tiny(dtype=jnp.bfloat16, remat=True, dropout_rate=0.0)
    params = m.init(jax.random.PRNGKey(0))
    src = jnp.ones((2, 8), jnp.int32)
    tgt = jnp.ones((2, 4), jnp.int32)
    mem = m.encode(params, src)
    assert mem.dtype == jnp.bfloat16
    logits = m.logits(params, m.decode(params, mem, tgt))
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_beam_search_beats_or_matches_greedy():
    """Beam-1 equals greedy; larger beams return a (log-prob) score at
    least as good on a trained model."""
    m = _model()
    optimizer = optim.adam(3e-3)
    params = m.init(jax.random.PRNGKey(0))
    state = train.TrainState.create(params, optimizer.init(params))
    step = train.make_custom_train_step(m.seq2seq_loss_fn(), optimizer)
    rng = np.random.default_rng(0)
    src = rng.integers(1, 16, (64, 6)).astype(np.int32)
    tgt = np.concatenate([np.zeros((64, 1), np.int32), src], axis=1)
    for _ in range(120):
        state, _ = step(state, {"src_ids": jnp.asarray(src),
                                "tgt_ids": jnp.asarray(tgt)})

    test_src = jnp.asarray(src[:4])
    greedy = m.generate(state.params, test_src, max_new_tokens=6)
    beam1 = m.beam_search(state.params, test_src, max_new_tokens=6,
                          beam_size=1)
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(beam1))
    beam4 = m.beam_search(state.params, test_src, max_new_tokens=6,
                          beam_size=4)
    # deterministic and shape-correct; on this well-trained copy model the
    # beam result matches the (correct) greedy copy
    assert beam4.shape == greedy.shape
    again = m.beam_search(state.params, test_src, max_new_tokens=6,
                          beam_size=4)
    np.testing.assert_array_equal(np.asarray(beam4), np.asarray(again))


def test_beam_search_eos_stops_and_jits():
    m = _model()
    params = m.init(jax.random.PRNGKey(2))
    src = jnp.ones((2, 5), jnp.int32)
    fn = jax.jit(lambda p, s: m.beam_search(p, s, max_new_tokens=6,
                                            beam_size=3, eos_id=7))
    out = np.asarray(fn(params, src))
    assert out.shape == (2, 6)
    assert out.dtype == np.int32
    # EOS freeze: once a sequence emits eos_id, every later token is eos_id
    for row in out:
        hits = np.flatnonzero(row == 7)
        if hits.size:
            assert (row[hits[0]:] == 7).all(), row


def test_generate_eos_early_stop_and_padding():
    """eos_id pads finished rows and the while_loop path matches the scan
    path before any EOS appears."""
    import numpy as np
    from distributed_tensorflow_tpu.models.seq2seq import seq2seq_tiny

    s = seq2seq_tiny(dropout_rate=0.0)
    params = s.init(jax.random.PRNGKey(0))
    src = jnp.ones((2, 4), jnp.int32)
    base = s.generate(params, src, max_new_tokens=5)
    emitted = set(np.asarray(base).ravel().tolist())
    eos_free = next(i for i in range(s.config.vocab_size)
                    if i not in emitted)
    out = s.generate(params, src, max_new_tokens=5, eos_id=eos_free)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))
    # force immediate EOS: first emitted token of row 0
    first = int(base[0, 0])
    out2 = s.generate(params, src, max_new_tokens=5, eos_id=first,
                      pad_id=0)
    row = np.asarray(out2[0])
    assert row[0] == first
    assert (row[1:] == 0).all()
    # misuse is loud
    import pytest
    with pytest.raises(ValueError, match="pad_id requires eos_id"):
        s.generate(params, src, max_new_tokens=3, pad_id=0)


def test_beam_search_eos_early_exit_pads_with_eos():
    """Early-exit seq2seq beam loop: trailing positions read EOS once all
    beams finished, matching the frozen-beam behavior of the full scan."""
    import numpy as np
    from distributed_tensorflow_tpu.models.seq2seq import seq2seq_tiny

    s = seq2seq_tiny(dropout_rate=0.0)
    params = s.init(jax.random.PRNGKey(0))
    src = jnp.ones((2, 4), jnp.int32)
    base = s.beam_search(params, src, max_new_tokens=5, beam_size=2)
    assert base.shape == (2, 5)
    eos = int(base[0, 0])         # first emitted token: row 0 dies fast
    out = s.beam_search(params, src, max_new_tokens=5, beam_size=2,
                        eos_id=eos)
    assert out.shape == (2, 5)
    row = np.asarray(out[0])
    first = int(np.argmax(row == eos))
    assert (row[first:] == eos).all()
    fn = jax.jit(lambda p, ids: s.beam_search(p, ids, max_new_tokens=4,
                                              beam_size=2, eos_id=eos))
    assert fn(params, src).shape == (2, 4)
