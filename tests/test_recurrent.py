"""LSTM/GRU recurrent layers: shapes, correctness vs a numpy step loop,
training, serialization."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import models, ops


def test_lstm_matches_numpy_reference():
    """One scan == the textbook per-step recurrence (Keras gate order)."""
    u, f, t, b = 3, 4, 5, 2
    layer = ops.LSTM(u, return_sequences=True)
    params, _ = layer.init(jax.random.PRNGKey(0), (t, f))
    x = np.random.RandomState(0).randn(b, t, f).astype("float32")
    out, _ = layer.apply(params, {}, jnp.asarray(x))

    K = np.asarray(params["kernel"])
    R = np.asarray(params["recurrent_kernel"])
    bias = np.asarray(params["bias"])
    sig = lambda v: np.clip(0.2 * v + 0.5, 0.0, 1.0)   # Keras hard_sigmoid
    h = np.zeros((b, u)); c = np.zeros((b, u))
    for step in range(t):
        z = x[:, step] @ K + bias + h @ R
        i, fg, g, o = (sig(z[:, :u]), sig(z[:, u:2*u]),
                       np.tanh(z[:, 2*u:3*u]), sig(z[:, 3*u:]))
        c = fg * c + i * g
        h = o * np.tanh(c)
        np.testing.assert_allclose(np.asarray(out[:, step]), h, atol=1e-5)


def test_lstm_forget_bias_is_one():
    layer = ops.LSTM(4)
    params, _ = layer.init(jax.random.PRNGKey(0), (3, 2))
    bias = np.asarray(params["bias"])
    np.testing.assert_array_equal(bias[4:8], np.ones(4))   # forget slice
    np.testing.assert_array_equal(bias[:4], np.zeros(4))


def test_gru_matches_numpy_reference():
    u, f, t, b = 3, 4, 5, 2
    layer = ops.GRU(u, return_sequences=True)
    params, _ = layer.init(jax.random.PRNGKey(1), (t, f))
    x = np.random.RandomState(1).randn(b, t, f).astype("float32")
    out, _ = layer.apply(params, {}, jnp.asarray(x))

    K = np.asarray(params["kernel"])
    R = np.asarray(params["recurrent_kernel"])
    bias = np.asarray(params["bias"])
    sig = lambda v: np.clip(0.2 * v + 0.5, 0.0, 1.0)   # Keras hard_sigmoid
    h = np.zeros((b, u))
    for step in range(t):
        xin = x[:, step] @ K + bias
        rec = h @ R[:, :2*u]
        z = sig(xin[:, :u] + rec[:, :u])
        r = sig(xin[:, u:2*u] + rec[:, u:])
        hh = np.tanh(xin[:, 2*u:] + (r * h) @ R[:, 2*u:])
        h = z * h + (1 - z) * hh
        np.testing.assert_allclose(np.asarray(out[:, step]), h, atol=1e-5)


def test_recurrent_shapes_and_last_output():
    for layer in (ops.LSTM(8), ops.GRU(8)):
        params, _ = layer.init(jax.random.PRNGKey(0), (6, 4))
        assert layer.out_shape((6, 4)) == (8,)
        x = jnp.ones((2, 6, 4))
        out, _ = layer.apply(params, {}, x)
        assert out.shape == (2, 8)
    seq = ops.LSTM(8, return_sequences=True)
    assert seq.out_shape((6, 4)) == (6, 8)


def test_orthogonal_initializer():
    from distributed_tensorflow_tpu.ops import initializers
    q = initializers.orthogonal()(jax.random.PRNGKey(0), (16, 16))
    np.testing.assert_allclose(np.asarray(q.T @ q), np.eye(16), atol=1e-5)
    # wide/tall shapes keep orthonormal columns/rows
    w = initializers.orthogonal()(jax.random.PRNGKey(0), (8, 16))
    np.testing.assert_allclose(np.asarray(w @ w.T), np.eye(8), atol=1e-5)


def test_lstm_sequence_model_trains_and_serializes(tmp_path):
    """Sequential LSTM classifier learns a counting task; save/load
    round-trips (LSTM/GRU are registered serializable layers)."""
    rng = np.random.RandomState(0)
    x = rng.randint(0, 2, size=(256, 8, 1)).astype("float32")
    y = (x.sum(axis=(1, 2)) > 4).astype("int32")
    model = models.Sequential([
        ops.LSTM(24),
        ops.Dense(2),
    ])
    model.compile(loss="sparse_categorical_crossentropy", optimizer="adam",
                  metrics=["accuracy"])
    hist = model.fit(x, y, epochs=30, batch_size=64, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    path = str(tmp_path / "lstm")
    model.save(path)
    loaded = models.load_model(path)
    np.testing.assert_allclose(np.asarray(loaded.predict(x[:16])),
                               np.asarray(model.predict(x[:16])), atol=1e-5)


def test_gru_in_sequential_trains():
    rng = np.random.RandomState(1)
    x = rng.randn(128, 6, 4).astype("float32")
    y = (x.mean(axis=(1, 2)) > 0).astype("int32")
    model = models.Sequential([ops.GRU(16), ops.Dense(2)])
    model.compile(loss="sparse_categorical_crossentropy", optimizer="adam")
    hist = model.fit(x, y, epochs=15, batch_size=32, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_recurrent_activation_configurable():
    """recurrent_activation='sigmoid' switches the gates off the Keras-2
    hard_sigmoid default; the config round-trips."""
    layer = ops.LSTM(4, recurrent_activation="sigmoid")
    cfg = layer.get_config()
    assert cfg["recurrent_activation"] == "sigmoid"
    assert cfg["activation"] == "tanh"
    rebuilt = ops.LSTM(**cfg)
    params, _ = layer.init(jax.random.PRNGKey(0), (3, 2))
    x = jnp.ones((1, 3, 2))
    a, _ = layer.apply(params, {}, x)
    b_, _ = rebuilt.apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b_))
    # and differs from the hard_sigmoid default on the same weights
    default = ops.LSTM(4)
    d, _ = default.apply(params, {}, x)
    assert float(jnp.abs(a - d).max()) > 1e-6
