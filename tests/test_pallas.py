"""Pallas kernel parity tests (interpret mode on the CPU test mesh)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops.attention import (
    dot_product_attention, padding_mask, causal_mask)
from distributed_tensorflow_tpu.ops.pallas import (
    MIN_PAGE_SIZE, flash_attention, make_flash_attention_fn,
    fused_adam_update, fused_layernorm, fused_rmsnorm,
    page_size_kernel_ok, paged_decode_attention, paged_window_attention)


def _qkv(key, b=2, s=64, h=4, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, h, d), dtype)
    v = jax.random.normal(kv, (b, s, h, d), dtype)
    return q, k, v


class TestFlashAttention:
    def test_matches_reference_no_mask(self):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        got = flash_attention(q, k, v, block_q=32, block_k=32)
        want = dot_product_attention(q, k, v)
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_causal(self):
        q, k, v = _qkv(jax.random.PRNGKey(1))
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        want = dot_product_attention(q, k, v, mask=causal_mask(q.shape[1]))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_padding_mask(self):
        q, k, v = _qkv(jax.random.PRNGKey(2))
        valid = jnp.asarray(
            np.random.default_rng(0).random((2, 64)) < 0.7, jnp.int32)
        valid = valid.at[:, 0].set(1)      # no fully-masked rows
        got = flash_attention(q, k, v, kv_valid=valid, block_q=32, block_k=32)
        want = dot_product_attention(q, k, v, mask=padding_mask(valid))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_ragged_seq_not_multiple_of_block(self):
        q, k, v = _qkv(jax.random.PRNGKey(3), s=50)
        got = flash_attention(q, k, v, block_q=16, block_k=16)
        want = dot_product_attention(q, k, v)
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_causal_ragged(self):
        q, k, v = _qkv(jax.random.PRNGKey(4), s=40)
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        want = dot_product_attention(q, k, v, mask=causal_mask(40))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_bfloat16(self):
        q, k, v = _qkv(jax.random.PRNGKey(5), dtype=jnp.bfloat16)
        got = flash_attention(q, k, v, block_q=32, block_k=32)
        want = dot_product_attention(q, k, v)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(got.astype(np.float32),
                                   want.astype(np.float32),
                                   atol=3e-2, rtol=3e-2)

    def test_gradients_match_reference(self):
        q, k, v = _qkv(jax.random.PRNGKey(6), b=1, s=32, h=2, d=8)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           block_q=16, block_k=16) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(
                q, k, v, mask=causal_mask(q.shape[1])) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)

    def test_jit_compiles(self):
        q, k, v = _qkv(jax.random.PRNGKey(7), s=32)
        f = jax.jit(lambda q, k, v: flash_attention(q, k, v,
                                                    block_q=16, block_k=16))
        np.testing.assert_allclose(f(q, k, v),
                                   dot_product_attention(q, k, v),
                                   atol=1e-5, rtol=1e-5)

    def test_attention_fn_adapter(self):
        q, k, v = _qkv(jax.random.PRNGKey(8), s=32)
        valid = jnp.ones((2, 32), jnp.int32).at[:, 20:].set(0)
        fn = make_flash_attention_fn(block_q=16, block_k=16)
        got = fn(q, k, v, mask=padding_mask(valid))
        want = dot_product_attention(q, k, v, mask=padding_mask(valid))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_attention_fn_rejects_full_mask(self):
        q, k, v = _qkv(jax.random.PRNGKey(9), s=16)
        fn = make_flash_attention_fn()
        with pytest.raises(ValueError):
            fn(q, k, v, mask=causal_mask(16))

    # -- fused Pallas backward (dq/dk/dv kernels) parity ------------------
    def _grad_pair(self, q, k, v, flash_kwargs, ref_mask):
        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, **flash_kwargs)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        def loss_ref(q, k, v):
            out = dot_product_attention(q, k, v, mask=ref_mask)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        return g1, g2

    def test_fused_backward_no_mask(self):
        q, k, v = _qkv(jax.random.PRNGKey(10), b=2, s=64, h=2, d=16)
        g1, g2 = self._grad_pair(q, k, v,
                                 dict(block_q=32, block_k=32), None)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)

    def test_fused_backward_causal_multiblock(self):
        """Causal with several q/k blocks: exercises the diagonal-skip
        guards of both backward kernels."""
        q, k, v = _qkv(jax.random.PRNGKey(11), b=1, s=64, h=2, d=8)
        g1, g2 = self._grad_pair(
            q, k, v, dict(causal=True, block_q=16, block_k=16),
            causal_mask(64))
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)

    def test_fused_backward_padding_and_ragged(self):
        """Padding mask + seq not a block multiple: padded q rows and
        masked k columns must contribute exactly zero gradient."""
        q, k, v = _qkv(jax.random.PRNGKey(12), b=2, s=50, h=2, d=8)
        valid = jnp.ones((2, 50), jnp.int32).at[:, 40:].set(0)
        g1, g2 = self._grad_pair(
            q, k, v, dict(kv_valid=valid, block_q=16, block_k=16),
            padding_mask(valid))
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)
        # masked-out key positions get zero dk/dv
        assert float(jnp.abs(g1[1][:, 40:]).max()) < 1e-6
        assert float(jnp.abs(g1[2][:, 40:]).max()) < 1e-6

    def test_fused_backward_bf16(self):
        q, k, v = _qkv(jax.random.PRNGKey(13), b=1, s=32, h=2, d=8,
                       dtype=jnp.bfloat16)
        g1, g2 = self._grad_pair(
            q, k, v, dict(causal=True, block_q=16, block_k=16),
            causal_mask(32))
        for a, b in zip(g1, g2):
            assert a.dtype == b.dtype == jnp.bfloat16
            np.testing.assert_allclose(a.astype(np.float32),
                                       b.astype(np.float32),
                                       atol=6e-2, rtol=6e-2)

    def test_fused_backward_under_jit_value_and_grad(self):
        q, k, v = _qkv(jax.random.PRNGKey(14), b=1, s=32, h=2, d=8)

        @jax.jit
        def vg(q, k, v):
            return jax.value_and_grad(
                lambda q: jnp.sum(flash_attention(q, k, v, causal=True,
                                                  block_q=16,
                                                  block_k=16) ** 2))(q)

        val, grad = vg(q, k, v)
        ref = jnp.sum(dot_product_attention(
            q, k, v, mask=causal_mask(32)) ** 2)
        np.testing.assert_allclose(float(val), float(ref), rtol=1e-5)
        assert bool(jnp.isfinite(grad).all())


class TestFlashGQA:
    """GQA/MQA run natively in the kernels: kv blocks are selected by
    q_head // group in the BlockSpec index maps (forward + both backward
    kernels), and per-q-head dk/dv reduce over the group afterwards."""

    def test_kernel_rejects_nondivisible_heads(self):
        q, _, _ = _qkv(jax.random.PRNGKey(20), s=16, h=4)
        _, k, v = _qkv(jax.random.PRNGKey(21), s=16, h=3)
        with pytest.raises(ValueError, match="multiple of the kv head"):
            flash_attention(q, k, v)

    @pytest.mark.parametrize("kv_heads", [1, 2])   # MQA and GQA
    def test_gqa_forward_matches_grouped_dense(self, kv_heads):
        q, _, _ = _qkv(jax.random.PRNGKey(20), b=2, s=48, h=4, d=8)
        _, k, v = _qkv(jax.random.PRNGKey(21), b=2, s=48, h=kv_heads, d=8)
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        want = dot_product_attention(q, k, v, mask=causal_mask(48))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_gqa_backward_matches_grouped_dense(self):
        """dk/dv accumulate over the whole query group (per-q-head kernel
        outputs reduced in XLA) — grads must match the grouped einsum's."""
        q, _, _ = _qkv(jax.random.PRNGKey(22), b=2, s=48, h=4, d=8)
        _, k, v = _qkv(jax.random.PRNGKey(23), b=2, s=48, h=2, d=8)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=True, block_q=16,
                                  block_k=16)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        def loss_ref(q, k, v):
            out = dot_product_attention(q, k, v, mask=causal_mask(48))
            return jnp.sum(out.astype(jnp.float32) ** 2)

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        assert g1[1].shape == k.shape and g1[2].shape == v.shape
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)

    def test_gqa_padding_mask(self):
        q, _, _ = _qkv(jax.random.PRNGKey(24), b=2, s=40, h=4, d=8)
        _, k, v = _qkv(jax.random.PRNGKey(25), b=2, s=40, h=2, d=8)
        valid = jnp.ones((2, 40), jnp.int32).at[:, 30:].set(0)
        got = flash_attention(q, k, v, kv_valid=valid, block_q=16,
                              block_k=16)
        want = dot_product_attention(q, k, v, mask=padding_mask(valid))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_gpt_gqa_flash_matches_dense(self):
        """GQA + use_flash=True end-to-end through attention_core (which
        must NOT broadcast kv heads for a supports_gqa kernel): same
        hidden states as the dense grouped-einsum path."""
        import numpy as np
        from distributed_tensorflow_tpu.models.gpt import GPT, GPTConfig
        base = dict(vocab_size=32, hidden_size=32, num_layers=2,
                    num_heads=4, num_kv_heads=2, intermediate_size=32,
                    max_position=32, dropout_rate=0.0)
        flash = GPT(GPTConfig(**base, use_flash=True))
        dense = GPT(GPTConfig(**base, use_flash=False))
        params = flash.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, 32)
        h_flash = flash.apply(params, ids)
        h_dense = dense.apply(params, ids)
        np.testing.assert_allclose(np.asarray(h_flash),
                                   np.asarray(h_dense),
                                   atol=1e-5, rtol=1e-5)


class TestFlashAutoDispatch:
    def test_resolve_use_flash(self, monkeypatch):
        from distributed_tensorflow_tpu.ops import attention as attn_lib
        assert attn_lib.resolve_use_flash(True, 8) is True
        assert attn_lib.resolve_use_flash(False, 99999) is False
        # pin the backend so the assertions hold on TPU-attached hosts too
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert attn_lib.resolve_use_flash("auto", 99999) is False
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert attn_lib.resolve_use_flash("auto", 2048) is True
        assert attn_lib.resolve_use_flash("auto", 512) is False

    def test_flash_min_seq_env(self, monkeypatch):
        from distributed_tensorflow_tpu.ops import attention as attn_lib
        monkeypatch.setenv("DTTPU_FLASH_MIN_SEQ", "64")
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        # still gated on the TPU backend even past the threshold
        assert attn_lib.flash_wins(128) is False
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert attn_lib.flash_wins(128) is True
        assert attn_lib.flash_wins(32) is False


class TestFusedAdam:
    def _naive(self, p, g, m, v, t, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
               wd=0.0):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mh = m / (1 - b1 ** t)
        vh = v / (1 - b2 ** t)
        p = p - lr * (mh / (jnp.sqrt(vh) + eps) + wd * p)
        return p, m, v

    @pytest.mark.parametrize("shape", [(37,), (128, 130), (3, 5, 7)])
    def test_matches_naive(self, shape):
        key = jax.random.PRNGKey(0)
        kp, kg, km, kv = jax.random.split(key, 4)
        p = jax.random.normal(kp, shape)
        g = jax.random.normal(kg, shape)
        m = jax.random.normal(km, shape) * 0.1
        v = jax.random.uniform(kv, shape) * 0.01
        for t in (1, 10):
            got = fused_adam_update(p, g, m, v, jnp.asarray(t))
            want = self._naive(p, g, m, v, t)
            for a, b in zip(got, want):
                np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)

    def test_weight_decay(self):
        # Large wd + early steps: catches decay scaled by the bias-corrected
        # lr_t instead of plain lr (decoupled-AdamW semantics).
        p = jnp.ones((64,)) * 0.5
        g = jnp.ones((64,)) * 0.1
        m = jnp.zeros((64,))
        v = jnp.zeros((64,))
        for t in (1, 5):
            got = fused_adam_update(p, g, m, v, jnp.asarray(t),
                                    weight_decay=0.1)
            want = self._naive(p, g, m, v, t, wd=0.1)
            for a, b in zip(got, want):
                np.testing.assert_allclose(a, b, atol=1e-7, rtol=1e-6)

    def test_under_jit_with_traced_step(self):
        p = jnp.ones((100,))
        g = jnp.full((100,), 0.3)
        m = jnp.zeros((100,))
        v = jnp.zeros((100,))
        f = jax.jit(lambda p, g, m, v, t: fused_adam_update(p, g, m, v, t))
        got = f(p, g, m, v, jnp.asarray(3))
        want = self._naive(p, g, m, v, 3)
        for a, b in zip(got, want):
            np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)


class TestFusedLayerNorm:
    def _ref(self, x, gamma, beta, eps=1e-6):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) / jnp.sqrt(var + eps) * gamma + beta

    def test_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 7, 96))
        gamma = jax.random.normal(jax.random.PRNGKey(1), (96,)) + 1.0
        beta = jax.random.normal(jax.random.PRNGKey(2), (96,))
        got = fused_layernorm(x, gamma, beta)
        np.testing.assert_allclose(got, self._ref(x, gamma, beta),
                                   atol=1e-5, rtol=1e-5)

    def test_bfloat16(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (8, 64), jnp.bfloat16)
        gamma = jnp.ones((64,))
        beta = jnp.zeros((64,))
        got = fused_layernorm(x, gamma, beta)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            got.astype(np.float32),
            self._ref(x.astype(jnp.float32), gamma, beta),
            atol=3e-2, rtol=3e-2)

    def test_gradients(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (6, 32))
        gamma = jnp.ones((32,)) * 1.5
        beta = jnp.zeros((32,))

        g1 = jax.grad(lambda x, g, b: jnp.sum(fused_layernorm(x, g, b) ** 2),
                      argnums=(0, 1, 2))(x, gamma, beta)
        g2 = jax.grad(lambda x, g, b: jnp.sum(self._ref(x, g, b) ** 2),
                      argnums=(0, 1, 2))(x, gamma, beta)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


class TestFusedRmsNorm:
    def _ref(self, x, gamma, eps=1e-6):
        x32 = x.astype(jnp.float32)
        inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                            + eps)
        return (x32 * inv * gamma.astype(jnp.float32)).astype(x.dtype)

    def test_matches_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 7, 96))
        gamma = jax.random.normal(jax.random.PRNGKey(1), (96,)) + 1.0
        got = fused_rmsnorm(x, gamma)
        np.testing.assert_allclose(got, self._ref(x, gamma),
                                   atol=1e-5, rtol=1e-5)

    def test_bfloat16(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (8, 64), jnp.bfloat16)
        gamma = jnp.ones((64,)) * 1.5
        got = fused_rmsnorm(x, gamma)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            got.astype(np.float32),
            self._ref(x, gamma).astype(np.float32),
            atol=3e-2, rtol=3e-2)

    def test_gradients(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (6, 32))
        gamma = jnp.ones((32,)) * 1.5
        g1 = jax.grad(lambda x, g: jnp.sum(fused_rmsnorm(x, g) ** 2),
                      argnums=(0, 1))(x, gamma)
        g2 = jax.grad(lambda x, g: jnp.sum(self._ref(x, g) ** 2),
                      argnums=(0, 1))(x, gamma)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)

    def test_llama_model_parity(self):
        """fused_layernorm=True on a rmsnorm model must reproduce the
        unfused logits AND gradients — the whole _norm dispatch, not
        just the kernel in isolation."""
        from distributed_tensorflow_tpu.models.llama import llama_tiny
        ids = np.arange(24, dtype=np.int32).reshape(2, 12) % 512

        outs, grads = [], []
        for fused in (False, True):
            model = llama_tiny(fused_layernorm=fused)
            params = model.init(jax.random.PRNGKey(0))
            outs.append(model.apply(params, ids))
            loss = model.lm_loss_fn()
            g = jax.grad(lambda p: loss(
                p, {}, {"input_ids": ids}, jax.random.PRNGKey(1),
                False)[0])(params)
            grads.append(g)
        np.testing.assert_allclose(outs[0], outs[1], atol=2e-5, rtol=2e-5)
        for a, b in zip(jax.tree.leaves(grads[0]),
                        jax.tree.leaves(grads[1])):
            np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-3)


class TestFlashShapeFuzz:
    def test_random_shape_parity(self):
        """Seeded fuzz over odd seq lengths / head counts / GQA ratios /
        mask kinds: the padded-block kernel must match dense attention on
        shapes that don't divide the (512, 1024) default blocks."""
        import numpy as np
        from distributed_tensorflow_tpu.ops import attention as attn_lib
        from distributed_tensorflow_tpu.ops.pallas.flash_attention import (
            flash_attention)

        rng = np.random.default_rng(20260731)
        for trial in range(6):
            b = int(rng.integers(1, 3))
            s = int(rng.integers(3, 97))
            groups = int(rng.choice([1, 2, 4]))
            kvh = int(rng.choice([1, 2]))
            h = kvh * groups
            d = int(rng.choice([8, 16]))
            causal = bool(rng.integers(0, 2))
            use_pad = bool(rng.integers(0, 2))
            ks = jax.random.split(jax.random.PRNGKey(trial), 3)
            q = jax.random.normal(ks[0], (b, s, h, d))
            k = jax.random.normal(ks[1], (b, s, kvh, d))
            v = jax.random.normal(ks[2], (b, s, kvh, d))
            kv_valid = None
            mask = attn_lib.causal_mask(s) if causal else None
            if use_pad and not causal:
                keep = max(1, s - int(rng.integers(0, s)))
                kv_valid = jnp.asarray(
                    np.arange(s)[None, :] < keep, jnp.int32
                ).repeat(b, axis=0)
                mask = attn_lib.padding_mask(kv_valid)
            got = flash_attention(q, k, v, kv_valid=kv_valid, causal=causal)
            if kvh != h:   # dense path wants broadcast kv heads
                k2 = jnp.repeat(k, groups, axis=2)
                v2 = jnp.repeat(v, groups, axis=2)
            else:
                k2, v2 = k, v
            want = attn_lib.dot_product_attention(q, k2, v2, mask=mask)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=2e-5,
                err_msg=f"trial {trial}: b={b} s={s} h={h} kvh={kvh} "
                        f"d={d} causal={causal} pad={use_pad}")


class TestPagedAttention:
    """The fused page-walk kernel vs the gather reference: same pool,
    same table, same masks — the kernel must agree to float round-off
    (token-level bit-identity is pinned at engine level in
    tests/test_pages.py)."""
    L, NP, PG, HD = 2, 14, 8, 16

    def _pool(self, key, kvh, quantized=False):
        kk, kv_, ks, vs = jax.random.split(key, 4)
        shape = (self.L, self.NP, self.PG, kvh, self.HD)
        if quantized:
            pool = {
                "k": jax.random.randint(kk, shape, -127, 128, jnp.int8),
                "v": jax.random.randint(kv_, shape, -127, 128, jnp.int8),
                "k_scale": jax.random.uniform(
                    ks, shape[:-1] + (1,), jnp.float32, 0.01, 0.05),
                "v_scale": jax.random.uniform(
                    vs, shape[:-1] + (1,), jnp.float32, 0.01, 0.05),
            }
        else:
            pool = {"k": jax.random.normal(kk, shape),
                    "v": jax.random.normal(kv_, shape)}
        return pool

    def _dense_kv(self, pool, layer, tab):
        """The gather read path at test scale: pages -> contiguous."""
        view = tab.shape[-1] * self.PG
        def gather(leaf):
            g = leaf[layer][tab.reshape(-1)]
            return g.reshape(tab.shape[0], view, *leaf.shape[3:])
        k, v = gather(pool["k"]), gather(pool["v"])
        if "k_scale" in pool:
            k = k.astype(jnp.float32) * gather(pool["k_scale"])
            v = v.astype(jnp.float32) * gather(pool["v_scale"])
        return k, v

    @pytest.mark.parametrize("kvh,h,quantized", [
        (4, 4, False), (2, 4, False), (2, 4, True)],
        ids=["base", "gqa", "int8"])
    def test_decode_matches_gather(self, kvh, h, quantized):
        S, P = 3, 4
        key = jax.random.PRNGKey(7)
        pool = self._pool(key, kvh, quantized)
        rng = np.random.default_rng(11)
        tab = jnp.asarray(rng.choice(self.NP, size=(S, P), replace=False)
                          if S * P <= self.NP else
                          rng.integers(0, self.NP, (S, P)), jnp.int32)
        view = P * self.PG
        valid = jnp.asarray(rng.random((S, view)) < 0.6)
        valid = valid.at[:, 0].set(True)     # no fully-masked rows
        q = jax.random.normal(jax.random.PRNGKey(8), (S, 1, h, self.HD))
        for layer in range(self.L):
            got = paged_decode_attention(q, pool, layer, tab, valid)
            k, v = self._dense_kv(pool, layer, tab)
            want = dot_product_attention(
                q, k.astype(q.dtype), v.astype(q.dtype),
                mask=padding_mask(valid))
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=2e-6, rtol=2e-6)

    @pytest.mark.parametrize("pos", [0, 5, 17])
    def test_window_matches_reference(self, pos):
        kvh = h = 4
        P, s = 4, 8
        pool = self._pool(jax.random.PRNGKey(3), kvh)
        row = jnp.asarray([5, 2, 9, 0], jnp.int32)
        view = P * self.PG
        q = jax.random.normal(jax.random.PRNGKey(4), (1, s, h, self.HD))
        got = paged_window_attention(q, pool, 1, row, pos)
        k, v = self._dense_kv(pool, 1, row[None, :])
        cols = jnp.arange(view)[None, None, None, :]
        rows = jnp.arange(s)[None, None, :, None]
        mask = jnp.where(cols <= pos + rows, 0.0, -1e9)
        want = dot_product_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-6, rtol=2e-6)

    def test_gqa_window_matches_reference(self):
        kvh, h = 2, 4
        P, s, pos = 3, 6, 4
        pool = self._pool(jax.random.PRNGKey(5), kvh)
        row = jnp.asarray([1, 7, 3], jnp.int32)
        view = P * self.PG
        q = jax.random.normal(jax.random.PRNGKey(6), (1, s, h, self.HD))
        got = paged_window_attention(q, pool, 0, row, pos)
        k, v = self._dense_kv(pool, 0, row[None, :])
        cols = jnp.arange(view)[None, None, None, :]
        rows = jnp.arange(s)[None, None, :, None]
        mask = jnp.where(cols <= pos + rows, 0.0, -1e9)
        want = dot_product_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-6, rtol=2e-6)

    def test_trash_pages_bitwise_inert(self):
        """Pages the table never references (the retirement trash
        mapping) must not perturb a single output bit."""
        S, P, kvh, h = 2, 3, 2, 4
        pool = self._pool(jax.random.PRNGKey(9), kvh)
        tab = jnp.asarray([[0, 1, 2], [3, 4, 5]], jnp.int32)
        view = P * self.PG
        rng = np.random.default_rng(13)
        valid = jnp.asarray(rng.random((S, view)) < 0.7).at[:, 0].set(True)
        q = jax.random.normal(jax.random.PRNGKey(10), (S, 1, h, self.HD))
        base = np.asarray(paged_decode_attention(q, pool, 0, tab, valid))
        trash = np.setdiff1d(np.arange(self.NP), np.asarray(tab))
        scrambled = dict(pool)
        for leaf in ("k", "v"):
            scrambled[leaf] = pool[leaf].at[:, trash].set(
                jax.random.normal(jax.random.PRNGKey(99),
                                  (self.L, trash.size, self.PG, kvh,
                                   self.HD)))
        got = np.asarray(paged_decode_attention(q, scrambled, 0, tab,
                                                valid))
        assert np.array_equal(base, got)

    def test_under_jit_with_traced_layer(self):
        """The serve tier calls the kernel inside lax.scan with a traced
        layer index; pin that the scalar-prefetch operand tolerates it."""
        S, P, kvh, h = 2, 2, 2, 4
        pool = self._pool(jax.random.PRNGKey(12), kvh)
        tab = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
        valid = jnp.ones((S, P * self.PG), jnp.bool_)
        q = jax.random.normal(jax.random.PRNGKey(13), (S, 1, h, self.HD))

        @jax.jit
        def both_layers(q, pool, tab, valid):
            def body(_, i):
                return None, paged_decode_attention(q, pool, i, tab, valid)
            _, outs = jax.lax.scan(body, None, jnp.arange(self.L))
            return outs

        outs = both_layers(q, pool, tab, valid)
        for layer in range(self.L):
            direct = paged_decode_attention(q, pool, layer, tab, valid)
            np.testing.assert_allclose(np.asarray(outs[layer]),
                                       np.asarray(direct), atol=1e-6)

    def test_page_size_kernel_ok(self):
        assert page_size_kernel_ok(8) and page_size_kernel_ok(16)
        assert page_size_kernel_ok(MIN_PAGE_SIZE)
        assert not page_size_kernel_ok(4)
        assert not page_size_kernel_ok(10)
        assert not page_size_kernel_ok(0)


class TestPagedKernelDispatch:
    def test_resolve_use_paged_kernel(self, monkeypatch):
        from distributed_tensorflow_tpu.ops import attention as attn_lib
        assert attn_lib.resolve_use_paged_kernel(True, 8) is True
        assert attn_lib.resolve_use_paged_kernel(False, 99999) is False
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert attn_lib.resolve_use_paged_kernel("auto", 99999) is False
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert attn_lib.resolve_use_paged_kernel("auto", 2048) is True
        assert attn_lib.resolve_use_paged_kernel("auto", 128) is False

    def test_paged_kernel_min_view_env(self, monkeypatch):
        from distributed_tensorflow_tpu.ops import attention as attn_lib
        monkeypatch.setenv("DTTPU_PAGED_KERNEL_MIN_VIEW", "64")
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert attn_lib.paged_kernel_wins(128) is False
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert attn_lib.paged_kernel_wins(128) is True
        assert attn_lib.paged_kernel_wins(32) is False
