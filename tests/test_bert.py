"""BERT family tests (BASELINE config #5 capability)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu import optim, train
from distributed_tensorflow_tpu.models.bert import Bert, BertConfig, bert_tiny
from distributed_tensorflow_tpu.parallel import make_mesh
from distributed_tensorflow_tpu.parallel.sharding import (shard_pytree,
                                                          tree_paths)


def mlm_batch(vocab, b=4, s=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(0, vocab, (b, s)).astype(np.int32),
        "labels": rng.integers(0, vocab, (b, s)).astype(np.int32),
        "mlm_mask": (rng.random((b, s)) < 0.15).astype(np.float32),
        "attention_mask": np.ones((b, s), np.int32),
    }


def test_bert_base_param_count():
    """BERT-base (uncased) has the canonical ~110M params; with our heads:
    embeddings+encoder+mlm+pooler."""
    model = Bert(BertConfig())
    params = model.init(jax.random.PRNGKey(0))
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    # 109,514,298 (core 109,482,240 + mlm transform/ln/bias + pooler)
    assert 109e6 < n < 111e6, n


def test_forward_shapes_and_dtypes():
    model = bert_tiny(dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.ones((2, 16), jnp.int32)
    seq = model.apply(params, ids)
    assert seq.shape == (2, 16, 128)
    assert seq.dtype == jnp.bfloat16
    logits = model.mlm_logits(params, seq)
    assert logits.shape == (2, 16, 1000)
    assert logits.dtype == jnp.float32  # logits promoted for stable XE
    pooled = model.pooled(params, seq)
    assert pooled.shape == (2, 128)


def test_attention_mask_respected():
    model = bert_tiny(dropout_rate=0.0)
    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.ones((1, 8), jnp.int32)
    mask_full = jnp.ones((1, 8), jnp.int32)
    # Padding tokens beyond position 4 must not affect positions 0-3.
    ids_pad = ids.at[:, 4:].set(5)
    mask_half = mask_full.at[:, 4:].set(0)
    out_masked = model.apply(params, ids_pad, attention_mask=mask_half)
    ids_short = ids[:, :4]
    out_short = model.apply(params, ids_short,
                            attention_mask=jnp.ones((1, 4), jnp.int32))
    np.testing.assert_allclose(np.asarray(out_masked[:, :4]),
                               np.asarray(out_short), atol=1e-4)


def test_mlm_training_reduces_loss():
    model = bert_tiny()
    params = model.init(jax.random.PRNGKey(0))
    opt = optim.adamw(1e-3)
    state = train.TrainState.create(params, opt.init(params))
    step = train.make_custom_train_step(model.mlm_loss_fn(), opt,
                                        grad_clip_norm=1.0)
    batch = mlm_batch(1000)
    losses = []
    for _ in range(8):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert "mlm_accuracy" in m and "grad_norm" in m


def test_remat_matches_no_remat():
    ids = jnp.ones((2, 16), jnp.int32)
    m1 = bert_tiny(dropout_rate=0.0)
    m2 = bert_tiny(dropout_rate=0.0, remat=True)
    params = m1.init(jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(m1.apply(params, ids)),
        np.asarray(m2.apply(params, ids)), atol=1e-5)


def test_fused_layernorm_matches_plain():
    """fused_layernorm=True (Pallas kernel, interpret mode on CPU) must be
    numerically interchangeable with the plain XLA LayerNorm end-to-end —
    the wiring gate for enabling it in the bench configs."""
    ids = jnp.ones((2, 16), jnp.int32)
    m1 = bert_tiny(dropout_rate=0.0)
    m2 = bert_tiny(dropout_rate=0.0, fused_layernorm=True)
    params = m1.init(jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(m1.apply(params, ids)),
        np.asarray(m2.apply(params, ids)), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(m1.mlm_logits(params, m1.apply(params, ids))),
        np.asarray(m2.mlm_logits(params, m2.apply(params, ids))), atol=1e-4)


def test_tensor_parallel_sharding_and_step():
    mesh = make_mesh({"data": 4, "tensor": 2})
    model = bert_tiny()
    params = model.init(jax.random.PRNGKey(0))
    sharded = shard_pytree(params, mesh, model.partition_rules())
    w = sharded["encoder"]["ffn"]["w_in"]["kernel"]
    assert "tensor" in str(w.sharding.spec)
    opt = optim.adamw(1e-3)
    state = train.TrainState.create(sharded, opt.init(sharded))
    step = train.make_custom_train_step(model.mlm_loss_fn(), opt)
    batch = jax.device_put(mlm_batch(1000, b=8),
                           NamedSharding(mesh, P("data")))
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    # updated params keep their tensor sharding
    w2 = state.params["encoder"]["ffn"]["w_in"]["kernel"]
    assert "tensor" in str(w2.sharding.spec)


def test_sequence_parallel_matches_dense_attention():
    """SP (ring attention over 'seq') == full attention, same params."""
    mesh = make_mesh({"seq": 8})
    dense = bert_tiny(dropout_rate=0.0)
    sp = Bert(dense.config.__class__(**{**dense.config.__dict__,
                                        "dropout_rate": 0.0,
                                        "seq_axis": "seq"}), mesh=mesh)
    params = dense.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 1000)
    out_dense = dense.apply(params, ids)
    out_sp = sp.apply(params, ids)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(out_sp),
                               atol=2e-4)


def test_partition_rules_cover_all_big_params():
    model = Bert(BertConfig())
    params = model.init(jax.random.PRNGKey(1))
    rules = model.partition_rules(fsdp=True)
    specs = rules.tree_specs(params)
    flat_specs = jax.tree.leaves(specs, is_leaf=lambda v: isinstance(v, P))
    paths = tree_paths(params)
    leaves = jax.tree.leaves(params)
    for path, leaf, spec in zip(paths, leaves, flat_specs):
        if leaf.ndim >= 2 and int(np.prod(leaf.shape)) > 100_000:
            assert spec != P(), f"large param {path} unsharded"


def test_train_without_rng_raises():
    model = bert_tiny()
    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.ones((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="rng"):
        model.apply(params, ids, train=True)


def test_sp_respects_padding_mask():
    """SP path must honour attention_mask like the dense path (regression)."""
    mesh = make_mesh({"seq": 8})
    dense = bert_tiny(dropout_rate=0.0)
    sp = Bert(dense.config.__class__(**{**dense.config.__dict__,
                                        "seq_axis": "seq"}), mesh=mesh)
    params = dense.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 1000)
    mask = jnp.ones((2, 64), jnp.int32).at[:, 40:].set(0)
    out_dense = dense.apply(params, ids, attention_mask=mask)
    out_sp = sp.apply(params, ids, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(out_dense[:, :40]),
                               np.asarray(out_sp[:, :40]), atol=2e-4)


def test_flash_attention_matches_dense():
    dense = bert_tiny(dropout_rate=0.0)
    flash = bert_tiny(dropout_rate=0.0, use_flash=True)
    params = dense.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 1000)
    mask = jnp.ones((2, 32), jnp.int32).at[1, 20:].set(0)
    out_dense = dense.apply(params, ids, attention_mask=mask)
    out_flash = flash.apply(params, ids, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(out_flash),
                               atol=2e-4, rtol=2e-4)


class TestMlmGather:
    """mlm_predictions_per_seq: gathering masked positions before the MLM
    head must be exactly interchangeable with projecting every position
    whenever each row has <= N masked tokens."""

    def _run(self, n_pred, mask):
        model = bert_tiny(dropout_rate=0.0,
                          mlm_predictions_per_seq=n_pred)
        params = model.init(jax.random.PRNGKey(0))
        b, s = mask.shape
        ids = np.asarray(
            jax.random.randint(jax.random.PRNGKey(1), (b, s), 0, 1000))
        batch = {"input_ids": ids, "labels": ids,
                 "mlm_mask": mask.astype(np.float32)}
        loss_fn = model.mlm_loss_fn()

        def scalar(p):
            loss, (metrics, _) = loss_fn(p, {}, batch, None, False)
            return loss, metrics

        return scalar(params), jax.grad(lambda p: scalar(p)[0])(params)

    def test_exact_parity_under_cap(self):
        rng = np.random.default_rng(0)
        mask = (rng.random((2, 32)) < 0.15).astype(np.float32)
        assert mask.sum(1).max() <= 8
        (l0, m0), g0 = self._run(0, mask)
        (l1, m1), g1 = self._run(8, mask)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        np.testing.assert_allclose(float(m0["mlm_accuracy"]),
                                   float(m1["mlm_accuracy"]), rtol=1e-6)
        np.testing.assert_allclose(float(m0["loss_weight"]),
                                   float(m1["loss_weight"]), rtol=0)
        assert float(m1["mlm_overflow"]) == 0.0
        f0 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g0)])
        f1 = np.concatenate([np.ravel(x) for x in jax.tree.leaves(g1)])
        np.testing.assert_allclose(f0, f1, atol=2e-5)

    def test_overflow_drops_and_reports(self):
        mask = np.ones((1, 16), np.float32)   # 16 masked, cap 4
        (_, m1), _ = self._run(4, mask)
        assert float(m1["mlm_overflow"]) == 12.0
        assert float(m1["loss_weight"]) == 4.0
