"""Shared decoding machinery tests (ops/decoding.py)."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.ops import decoding as dec


def test_sample_greedy_and_temperature():
    logits = jnp.asarray([[1.0, 5.0, 2.0], [4.0, 0.0, -1.0]])
    out = dec.sample_logits(jax.random.PRNGKey(0), logits, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out), [1, 0])
    # high temperature still returns valid ids
    out = dec.sample_logits(jax.random.PRNGKey(0), logits, temperature=5.0)
    assert out.shape == (2,) and int(out.max()) < 3


def test_top_k_filters_tail():
    logits = jnp.asarray([[0.0, 10.0, 9.0, -5.0]])
    draws = [int(dec.sample_logits(jax.random.PRNGKey(s), logits,
                                   temperature=1.0, top_k=2)[0])
             for s in range(50)]
    assert set(draws) <= {1, 2}


def test_top_p_keeps_nucleus():
    # one dominant token (~0.99 prob): nucleus p=0.5 keeps only it
    logits = jnp.asarray([[0.0, 10.0, 1.0, 1.0]])
    draws = [int(dec.sample_logits(jax.random.PRNGKey(s), logits,
                                   temperature=1.0, top_p=0.5)[0])
             for s in range(30)]
    assert set(draws) == {1}
    # p=1.0 leaves the distribution untouched (any token possible)
    draws = [int(dec.sample_logits(jax.random.PRNGKey(s), logits,
                                   temperature=3.0, top_p=1.0)[0])
             for s in range(60)]
    assert len(set(draws)) > 1


def test_sampling_in_generate_paths():
    from distributed_tensorflow_tpu.models.gpt import gpt_tiny
    from distributed_tensorflow_tpu.models.seq2seq import seq2seq_tiny

    g = gpt_tiny(dropout_rate=0.0)
    gp = g.init(jax.random.PRNGKey(0))
    out = g.generate(gp, jnp.ones((2, 3), jnp.int32), max_new_tokens=4,
                     temperature=0.8, top_k=20, top_p=0.9)
    assert out.shape == (2, 7)

    s = seq2seq_tiny(dropout_rate=0.0)
    sp = s.init(jax.random.PRNGKey(0))
    out = s.generate(sp, jnp.ones((2, 4), jnp.int32), max_new_tokens=3,
                     temperature=0.8, top_p=0.9)
    assert out.shape == (2, 3)


def test_expand_beams_and_rank():
    scores = dec.init_beam_scores(1, 2)
    logp = jnp.log(jnp.asarray([[[0.6, 0.3, 0.1], [0.5, 0.4, 0.1]]]))
    new_scores, beam, tok = dec.expand_beams(scores, logp)
    # beam 1 starts at -inf: both winners come from beam 0
    np.testing.assert_array_equal(np.asarray(beam), [[0, 0]])
    np.testing.assert_array_equal(np.asarray(tok), [[0, 1]])
    # lengths via first EOS: beam0 ends at position 3 (len 4), beam1 at
    # position 0 (len 1).  With penalty 1.0 beam0 ranks -2/4 = -0.5 vs
    # beam1 -1.8/1; with penalty 0 raw scores decide and beam1 wins.
    scores = jnp.asarray([[-2.0, -1.8]])
    gen = jnp.asarray([[[3, 3, 3, 7], [7, 0, 0, 0]]])
    best = dec.rank_beams(scores, gen, eos_id=7, max_new_tokens=4,
                          length_penalty=1.0)
    assert int(best[0]) == 0
    best = dec.rank_beams(scores, gen, eos_id=7, max_new_tokens=4,
                          length_penalty=0.0)
    assert int(best[0]) == 1


def test_top_p_zero_degrades_to_greedy():
    logits = jnp.asarray([[0.0, 10.0, 1.0, 1.0]])
    out = dec.sample_logits(jax.random.PRNGKey(0), logits,
                            temperature=1.0, top_p=0.0)
    assert int(out[0]) == 1  # the argmax token, never id 0


def test_sample_logits_rank_agnostic_without_top_p():
    """top_k-only and plain-temperature paths accept leading dims beyond
    batch (e.g. [b, beams, V]); only nucleus needs the 2D form."""
    logits = jnp.zeros((2, 3, 8)).at[..., 1].set(5.0)
    out = dec.sample_logits(jax.random.PRNGKey(0), logits,
                            temperature=0.5, top_k=2)
    assert out.shape == (2, 3)
    out = dec.sample_logits(jax.random.PRNGKey(0), logits, temperature=0.5)
    assert out.shape == (2, 3)
